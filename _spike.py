"""GRR kernel spike: validate lowering + throughput with synthetic routes."""
import sys, time
import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from photon_ml_tpu.utils.timing import measure

def log(m): print(m, file=sys.stderr, flush=True)

CAP = 8
GROUP = 128 // CAP   # 16

def grr_contract_tpu(tableT, g1, g2, g3, vals, gw_of_st, ow_of_st, first_of_ow,
                     n_ow, interpret=False):
    n_st = vals.shape[0]

    def kernel(gw_ref, ow_ref, first_ref, wt_ref, g1_ref, g2_ref, g3_ref,
               v_ref, out_ref):
        st = pl.program_id(0)
        wt = wt_ref[0]
        x1 = jnp.take_along_axis(wt, g1_ref[0].astype(jnp.int32), axis=1)
        x2t = jnp.take_along_axis(x1.T, g2_ref[0].astype(jnp.int32), axis=1)
        x3 = jnp.take_along_axis(x2t.T, g3_ref[0].astype(jnp.int32), axis=1)
        c = x3 * v_ref[0]
        partial = c[0:GROUP, :]
        for q in range(1, CAP):
            partial = partial + c[q * GROUP:(q + 1) * GROUP, :]

        @pl.when(first_ref[st] == 1)
        def _init():
            out_ref[0] = partial

        @pl.when(first_ref[st] == 0)
        def _acc():
            out_ref[0] += partial

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n_st,),
        in_specs=[
            pl.BlockSpec((1, 128, 128), lambda i, gw, ow, first: (gw[i], 0, 0)),
            pl.BlockSpec((1, 128, 128), lambda i, gw, ow, first: (i, 0, 0)),
            pl.BlockSpec((1, 128, 128), lambda i, gw, ow, first: (i, 0, 0)),
            pl.BlockSpec((1, 128, 128), lambda i, gw, ow, first: (i, 0, 0)),
            pl.BlockSpec((1, 128, 128), lambda i, gw, ow, first: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, GROUP, 128),
                               lambda i, gw, ow, first: (ow[i], 0, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_ow, GROUP, 128), jnp.float32),
        interpret=interpret,
    )(gw_of_st, ow_of_st, first_of_ow, tableT, g1, g2, g3, vals)

def contract_jnp(tableT, g1, g2, g3, vals, gw_of_st, ow_of_st, n_ow):
    wt = tableT[gw_of_st]
    i32 = jnp.int32
    x1 = jnp.take_along_axis(wt, g1.astype(i32), axis=2)
    x2t = jnp.take_along_axis(x1.transpose(0, 2, 1), g2.astype(i32), axis=2)
    x3 = jnp.take_along_axis(x2t.transpose(0, 2, 1), g3.astype(i32), axis=2)
    c = x3 * vals
    n_st = vals.shape[0]
    partial = c.reshape(n_st, CAP, GROUP, 128).sum(1)
    return jax.ops.segment_sum(partial, ow_of_st, num_segments=n_ow)

# --- synthetic data: margins-shaped (n=1e6-ish) ------------------------------
n_st = 3424           # ~56M slots
n_gw = 7
n_ow = 489
rng = np.random.default_rng(0)
tableT = jnp.asarray(rng.normal(0, 1, (n_gw, 128, 128)).astype(np.float32))
g1 = jnp.asarray(rng.integers(0, 128, (n_st, 128, 128)).astype(np.int8))
g2 = jnp.asarray(rng.integers(0, 128, (n_st, 128, 128)).astype(np.int8))
g3 = jnp.asarray(rng.integers(0, 128, (n_st, 128, 128)).astype(np.int8))
vals = jnp.asarray(rng.normal(0, 1, (n_st, 128, 128)).astype(np.float32))
gw_of_st = jnp.asarray(np.sort(rng.integers(0, n_gw, n_st)).astype(np.int32))
ow_raw = np.sort(rng.integers(0, n_ow, n_st))
ow_raw[:n_ow] = np.arange(n_ow)         # every ow present
ow_raw = np.sort(ow_raw)
first = np.r_[1, (np.diff(ow_raw) != 0).astype(np.int32)].astype(np.int32)
ow_of_st = jnp.asarray(ow_raw.astype(np.int32))
first_of_ow = jnp.asarray(first)

# correctness vs jnp reference (small subset)
small = slice(0, 64)
ow_s = np.sort(rng.integers(0, 4, 64)); ow_s[:4] = np.arange(4); ow_s = np.sort(ow_s)
f_s = np.r_[1, (np.diff(ow_s) != 0).astype(np.int32)].astype(np.int32)
args_s = (tableT, g1[small], g2[small], g3[small], vals[small],
          gw_of_st[small], jnp.asarray(ow_s.astype(np.int32)), jnp.asarray(f_s))
out_k = grr_contract_tpu(*args_s, n_ow=4)
out_r = contract_jnp(tableT, g1[small], g2[small], g3[small], vals[small],
                     gw_of_st[small], jnp.asarray(ow_s.astype(np.int32)), 4)
err = float(jnp.max(jnp.abs(out_k - out_r)))
log(f"kernel vs jnp max err: {err:.2e}")
assert err < 1e-3

# throughput
f = jax.jit(lambda *a: grr_contract_tpu(*a, n_ow=n_ow))
t0 = time.time()
out = jax.block_until_ready(f(tableT, g1, g2, g3, vals, gw_of_st, ow_of_st, first_of_ow))
log(f"compile+run {time.time()-t0:.1f}s")
s = measure(f, tableT, g1, g2, g3, vals, gw_of_st, ow_of_st, first_of_ow, iters=20)
slots = n_st * 16384
stream_bytes = slots * 7  # vals f32 + 3x i8
log(f"GRR kernel: {s*1e3:.3f} ms  {slots/s/1e9:.1f} Gslot/s  stream {stream_bytes/s/1e9:.0f} GB/s")
