"""Unit tests for pointwise losses: derivatives via finite differences.

Mirrors the reference's unit-test strategy for the glm loss hierarchy
(finite-difference checks against closed forms, SURVEY.md §4 tier 1).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops import losses

pytestmark = pytest.mark.fast


ALL = [losses.LOGISTIC, losses.SQUARED, losses.POISSON, losses.SMOOTHED_HINGE]
LABELS = {
    "logistic": [0.0, 1.0],
    "squared": [-2.3, 0.0, 1.7],
    "poisson": [0.0, 1.0, 3.0],
    "smoothed_hinge": [0.0, 1.0],
}
# Margins avoiding the hinge's kink points {0, 1} where FD is invalid.
MARGINS = [-3.1, -0.52, 0.37, 1.44, 2.9]


@pytest.mark.parametrize("loss", ALL, ids=lambda l: l.name)
def test_d1_matches_finite_difference(loss):
    eps = 1e-4
    for y in LABELS[loss.name]:
        for z in MARGINS:
            z, y = jnp.float64(z), jnp.float64(y)
            fd = (loss.loss(z + eps, y) - loss.loss(z - eps, y)) / (2 * eps)
            np.testing.assert_allclose(loss.d1(z, y), fd, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("loss", ALL, ids=lambda l: l.name)
def test_d2_matches_finite_difference_of_d1(loss):
    eps = 1e-4
    for y in LABELS[loss.name]:
        for z in MARGINS:
            z, y = jnp.float64(z), jnp.float64(y)
            fd = (loss.d1(z + eps, y) - loss.d1(z - eps, y)) / (2 * eps)
            np.testing.assert_allclose(loss.d2(z, y), fd, rtol=1e-4, atol=1e-6)


def test_logistic_known_values():
    # loss(0, y) = log 2 for either label; d1(0, 1) = -0.5.
    np.testing.assert_allclose(
        losses.LOGISTIC.loss(jnp.float32(0.0), jnp.float32(1.0)),
        np.log(2.0), rtol=1e-6)
    np.testing.assert_allclose(
        losses.LOGISTIC.d1(jnp.float32(0.0), jnp.float32(1.0)), -0.5, rtol=1e-6)


def test_logistic_stable_at_extreme_margins():
    for z in [-80.0, 80.0]:
        v = losses.LOGISTIC.loss(jnp.float32(z), jnp.float32(1.0))
        assert np.isfinite(v)
        g = losses.LOGISTIC.d1(jnp.float32(z), jnp.float32(0.0))
        assert np.isfinite(g)


def test_squared_known_values():
    np.testing.assert_allclose(
        losses.SQUARED.loss(jnp.float32(3.0), jnp.float32(1.0)), 2.0)


def test_smoothed_hinge_piecewise_values():
    l, y1 = losses.SMOOTHED_HINGE, jnp.float32(1.0)
    np.testing.assert_allclose(l.loss(jnp.float32(2.0), y1), 0.0)       # t>=1
    np.testing.assert_allclose(l.loss(jnp.float32(-1.0), y1), 1.5)      # t<=0
    np.testing.assert_allclose(l.loss(jnp.float32(0.5), y1), 0.125)     # mid
    # label 0 mirrors: t = -z
    y0 = jnp.float32(0.0)
    np.testing.assert_allclose(l.loss(jnp.float32(-2.0), y0), 0.0)


def test_get_loss_aliases():
    assert losses.get_loss("LOGISTIC_REGRESSION") is losses.LOGISTIC
    assert losses.get_loss("poisson") is losses.POISSON
    with pytest.raises(ValueError):
        losses.get_loss("nope")


def test_losses_vmap_and_jit():
    z = jnp.linspace(-2, 2, 8)
    y = jnp.ones(8)
    for l in ALL:
        out = jax.jit(jax.vmap(l.loss))(z, y)
        assert out.shape == (8,)


def test_poisson_clamp_is_self_consistent():
    """Beyond z=30 the softened exp must keep loss/d1/d2 mutual derivatives."""
    eps = 1e-3
    y = jnp.float64(2.0)
    for z in [29.0, 29.999, 30.0, 30.001, 31.0, 45.0, 200.0]:
        z = jnp.float64(z)
        fd1 = (losses.POISSON.loss(z + eps, y) - losses.POISSON.loss(z - eps, y)) / (2 * eps)
        np.testing.assert_allclose(losses.POISSON.d1(z, y), fd1, rtol=1e-5)
        fd2 = (losses.POISSON.d1(z + eps, y) - losses.POISSON.d1(z - eps, y)) / (2 * eps)
        # rtol 1e-3: the FD stencil may straddle the z=30 switch point where
        # the third derivative jumps; the inconsistency this guards against
        # (plain clamp) is an order-1 error.
        np.testing.assert_allclose(losses.POISSON.d2(z, y), fd2, rtol=1e-3)
    # And it stays finite in float32 far beyond the clamp.
    big = losses.POISSON.loss(jnp.float32(500.0), jnp.float32(1.0))
    assert np.isfinite(np.asarray(big))


def test_sparse_batch_rejects_duplicate_col_ids():
    from photon_ml_tpu.data.batch import make_sparse_batch

    rows = [(np.array([0, 3, 3]), np.array([1.0, 2.0, 1.0]))]
    with pytest.raises(ValueError, match="duplicate column ids"):
        make_sparse_batch(rows, dim=5, labels=np.array([1.0]))
