"""Batched λ-sweep ≡ sequential per-point fits (ISSUE 2 tentpole).

The swept surfaces (``ops.objective`` lane sweep, ``optim.lbfgs
.lbfgs_solve_swept``, ``optim.streaming.streaming_lbfgs_solve_swept``,
the coordinate ``train_swept`` entries, and the GameEstimator grid /
tuned wiring) must reproduce the sequential one-λ-at-a-time fits to
float-reorder tolerance on BOTH the resident and chunked paths —
including an L1 (OWL-QN) lane — while paying a fraction of the data
passes (asserted through the chunk-sweep odometer).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.config import (
    CoordinateConfig,
    CoordinateKind,
    OptimizerSettings,
    TrainingConfig,
    TuningConfig,
)
from photon_ml_tpu.data.batch import make_sparse_batch
from photon_ml_tpu.data.chunked_batch import build_chunked_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.evaluation.evaluators import EvaluatorType
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.models.glm import TaskType
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import (
    RegularizationContext,
    RegularizationType,
    SweptRegularization,
)
from photon_ml_tpu.optim import (
    ChunkedGLMObjective,
    OptimizerConfig,
    lbfgs_solve,
    lbfgs_solve_swept,
    streaming_lbfgs_solve,
    streaming_lbfgs_solve_swept,
)

# Weakest lane kept ≥ 0.1: below that the logistic objective is flat
# enough that f32 solves stall-terminate at slightly different points
# (values equal to 1e-5, one-coordinate wander) — real float
# indeterminacy, not a sweep defect.
LAMS = [10.0, 1.0, 0.1]


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _sparse_problem(rng, n=1500, d=300, k=6):
    cols = np.stack([
        np.sort(rng.choice(d, k, replace=False)) for _ in range(n)
    ]).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    w_true = rng.normal(0, 0.8, d) * (rng.uniform(size=d) < 0.3)
    m = np.einsum("nk,nk->n", vals, w_true[cols])
    labels = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(
        np.float32)
    rows = SparseRows.from_flat(
        np.arange(n + 1, dtype=np.int64) * k,
        cols.reshape(-1).astype(np.int64), vals.reshape(-1))
    return rows, labels


def _objective(lam=1.0):
    return GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(lam),
        norm=NormalizationContext.identity(),
    )


# -- optimizer-level equivalence -------------------------------------------


@pytest.mark.parametrize("use_map", [False, True])
def test_lbfgs_solve_swept_matches_sequential(rng, use_map):
    """Each swept lane's solution ≡ the per-λ lbfgs_solve (vmap lane
    axis AND the lax.map lane-loop fallback for unbatchable kernels)."""
    rows, labels = _sparse_problem(rng)
    d = 300
    batch = make_sparse_batch(rows, d, labels)
    obj = _objective()
    cfg = OptimizerConfig(max_iters=200, tolerance=1e-7)

    def vg(w, l2):
        o = obj.replace(reg=obj.reg.replace(l2_weight=l2))
        return o.value_and_gradient(w, batch)

    W0 = jnp.zeros((len(LAMS), d), jnp.float32)
    res = lbfgs_solve_swept(vg, W0, jnp.asarray(LAMS, jnp.float32), cfg,
                            use_map=use_map)
    for i, lam in enumerate(LAMS):
        o = _objective(lam)
        r = lbfgs_solve(lambda w: o.value_and_gradient(w, batch),
                        jnp.zeros((d,), jnp.float32), cfg)
        np.testing.assert_allclose(np.asarray(res.w[i]), np.asarray(r.w),
                                   rtol=5e-3, atol=5e-3)
        np.testing.assert_allclose(float(res.value[i]), float(r.value),
                                   rtol=1e-5)
        assert bool(res.converged[i])


def test_owlqn_swept_matches_sequential(rng):
    """Elastic-net lanes: swept OWL-QN ≡ per-λ OWL-QN, with the lane
    sparsity pattern tracking λ."""
    rows, labels = _sparse_problem(rng)
    d = 300
    batch = make_sparse_batch(rows, d, labels)
    obj = _objective()
    cfg = OptimizerConfig(max_iters=80, tolerance=1e-7)
    lams = [1.0, 0.3, 0.03]
    reg = SweptRegularization.from_grid(
        RegularizationType.ELASTIC_NET, lams, elastic_net_alpha=0.5)
    assert reg.has_l1()

    def vg(w, l2):
        o = obj.replace(reg=obj.reg.replace(l2_weight=l2))
        return o.value_and_gradient(w, batch)

    W0 = jnp.zeros((len(lams), d), jnp.float32)
    res = lbfgs_solve_swept(vg, W0, reg.l2_weights, cfg,
                            l1_weights=reg.l1_vectors(d, None))
    zeros = []
    for i, lam in enumerate(lams):
        o = GLMObjective(
            loss=losses.LOGISTIC,
            reg=RegularizationContext.elastic_net(lam, 0.5),
            norm=NormalizationContext.identity(),
        )
        l1 = jnp.broadcast_to(o.reg.l1_weight, (d,))
        r = lbfgs_solve(lambda w: o.value_and_gradient(w, batch),
                        jnp.zeros((d,), jnp.float32), cfg, l1_weight=l1)
        np.testing.assert_allclose(np.asarray(res.w[i]), np.asarray(r.w),
                                   rtol=5e-3, atol=5e-3)
        zeros.append(int(np.sum(np.asarray(res.w[i]) == 0.0)))
    # Orthant-wise L1 must actually sparsify, more at larger λ.
    assert zeros[0] > zeros[-1]
    assert zeros[0] > 20


@pytest.mark.parametrize("layout", ["ell", "grr"])
def test_streaming_swept_matches_sequential_and_amortizes(rng, layout):
    """Chunked path: every batched lane ≡ its sequential streaming fit,
    and the batched grid pays well under half the data passes (the
    chunk-sweep odometer — passes per solver iteration L → ~1).  The
    GRR layout exercises the lane-loop (lax.map) per-chunk program."""
    rows, labels = _sparse_problem(rng)
    d = 300
    cb = build_chunked_batch(rows, d, labels, n_chunks=3, layout=layout)
    cfg = OptimizerConfig(max_iters=60, tolerance=1e-6)
    lams = [10.0, 3.0, 1.0, 0.3, 0.1]
    reg = SweptRegularization.from_grid(RegularizationType.L2, lams)
    cobj = ChunkedGLMObjective(_objective(), cb, max_resident=3)
    W0 = jnp.zeros((len(lams), d), jnp.float32)
    res = streaming_lbfgs_solve_swept(
        lambda W: cobj.value_and_gradient_swept(W, reg),
        lambda W: cobj.value_swept(W, reg),
        W0, cfg)
    batched_passes = cobj.sweeps

    seq_passes = 0
    for i, lam in enumerate(lams):
        co = ChunkedGLMObjective(_objective(lam), cb, max_resident=3)
        r = streaming_lbfgs_solve(co.value_and_gradient,
                                  jnp.zeros((d,), jnp.float32), cfg,
                                  value_fn=co.value)
        seq_passes += co.sweeps
        np.testing.assert_allclose(np.asarray(res.w[i]), np.asarray(r.w),
                                   rtol=5e-3, atol=5e-3)
    # ELL lanes mostly accept α=1 → ~0.3× the sequential passes; GRR's
    # reordered contractions backtrack more (each extra trial is one
    # shared value sweep), landing ~0.5× at L=5 — both well below L×,
    # and the ratio improves with lane count.
    bound = 0.5 if layout == "ell" else 0.6
    assert batched_passes <= seq_passes * bound, (
        f"batched {batched_passes} passes vs sequential {seq_passes}")


# -- estimator-level equivalence -------------------------------------------


def _glm_dataset(rng, n=1200, d=200, k=5, sparse=False):
    if sparse:
        rows, labels = _sparse_problem(rng, n=n, d=d, k=k)
        return GameDataset(labels=labels, features={"g": rows},
                           entity_ids={}, feature_dims={"g": d})
    x = rng.normal(0, 1, (n, d)).astype(np.float32)
    m = x @ (rng.normal(0, 1, d) * (rng.uniform(size=d) < 0.4))
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float32)
    return GameDataset(labels=y, features={"g": x}, entity_ids={})


def _glm_split(rng, n=1600, d=60):
    """One generative model, split train/validation (a held-out set
    from a DIFFERENT model would make AUC meaningless)."""
    x = rng.normal(0, 1, (n, d)).astype(np.float32)
    m = x @ (rng.normal(0, 1, d) * (rng.uniform(size=d) < 0.4))
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float32)
    cut = int(0.8 * n)
    return (GameDataset(labels=y[:cut], features={"g": x[:cut]},
                        entity_ids={}),
            GameDataset(labels=y[cut:], features={"g": x[cut:]},
                        entity_ids={}))


def _glm_config(**over):
    base = dict(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[CoordinateConfig(
            name="fixed", kind=CoordinateKind.FIXED_EFFECT,
            feature_shard="g",
            optimizer=OptimizerSettings(max_iters=200, tolerance=1e-7),
        )],
        update_sequence=["fixed"],
        evaluators=[EvaluatorType.AUC],
    )
    base.update(over)
    return TrainingConfig(**base)


def _assert_grid_matches_sequential(cfg, train, valid, grid,
                                    tol=2e-3):
    est = GameEstimator(cfg)
    results = est.fit(train, valid)
    assert len(results) == len(grid)
    est_seq = GameEstimator(cfg)
    prep = est_seq._prepare(train)
    for r, lam in zip(results, grid):
        assert r.reg_weights["fixed"] == lam
        seq = est_seq._fit_point(train, prep, {"fixed": lam}, valid,
                                 None)
        np.testing.assert_allclose(
            np.asarray(r.model.models["fixed"].coefficients.means),
            np.asarray(seq.model.models["fixed"].coefficients.means),
            rtol=tol, atol=tol)
        if valid is not None:
            assert (abs(r.evaluations[EvaluatorType.AUC]
                        - seq.evaluations[EvaluatorType.AUC]) < 5e-3)
    return results


def test_estimator_grid_swept_resident(rng, monkeypatch):
    """Eligible fixed-effect grids take the swept path (never
    _fit_point) and match sequential fits lane by lane — the resident
    batch, intercept reg-mask exercised."""
    train, valid = _glm_split(rng)
    grid = [0.1, 1.0, 10.0]
    cfg = _glm_config(reg_weight_grid={"fixed": grid}, intercept=True)

    calls = []
    orig = GameEstimator._fit_point

    def spy(self, *a, **kw):
        calls.append(1)
        return orig(self, *a, **kw)

    monkeypatch.setattr(GameEstimator, "_fit_point", spy)
    est = GameEstimator(cfg)
    results = est.fit(train, valid)
    assert calls == [], "eligible grid fell back to per-point fits"
    monkeypatch.undo()

    est_seq = GameEstimator(cfg)
    prep = est_seq._prepare(train)
    for r, lam in zip(results, grid):
        seq = est_seq._fit_point(train, prep, {"fixed": lam}, valid,
                                 None)
        np.testing.assert_allclose(
            np.asarray(r.model.models["fixed"].coefficients.means),
            np.asarray(seq.model.models["fixed"].coefficients.means),
            rtol=2e-3, atol=2e-3)
        # Per-iteration validation survives the swept path: one entry
        # per CD sweep, last entry == final evaluations (the
        # _fit_point contract).
        assert len(r.validation_history) == cfg.n_iterations
        assert r.validation_history[-1] == r.evaluations


def test_estimator_grid_swept_chunked(rng):
    """Chunked (streaming) estimator path: swept grid ≡ sequential
    per-point chunked fits."""
    train = _glm_dataset(rng, sparse=True)
    grid = [5.0, 1.0, 0.2]
    cfg = _glm_config(reg_weight_grid={"fixed": grid}, intercept=False,
                      chunk_rows=400, chunk_layout="ELL",
                      chunk_max_resident=8)
    _assert_grid_matches_sequential(cfg, train, None, grid, tol=5e-3)


def test_estimator_grid_swept_owlqn_lane(rng):
    """An elastic-net (OWL-QN) grid sweeps batched and matches the
    sequential fits — the L1 lane acceptance case."""
    train, valid = _glm_split(rng)
    grid = [8.0, 0.5]
    cfg = _glm_config(reg_weight_grid={"fixed": grid})
    cfg.coordinates[0].optimizer.regularization = (
        RegularizationType.ELASTIC_NET)
    cfg.coordinates[0].optimizer.elastic_net_alpha = 0.5
    results = _assert_grid_matches_sequential(cfg, train, valid, grid,
                                              tol=5e-3)
    w_strong = np.asarray(
        results[0].model.models["fixed"].coefficients.means)
    # OWL-QN at the strong-λ lane must sparsify (intercept excluded).
    assert int(np.sum(w_strong[:-1] == 0.0)) > 5


def test_estimator_grid_multi_coordinate_stays_sequential(rng,
                                                          monkeypatch):
    """A grid over a config with a random effect is NOT swept-eligible
    and keeps the per-point path."""
    from photon_ml_tpu.utils.synthetic import make_movielens_like

    data = make_movielens_like(n_users=40, n_items=1, n_obs=800, seed=3)
    train = GameDataset(
        labels=data["labels"],
        features={"g": data["x"],
                  "u": np.ones((len(data["labels"]), 1), np.float32)},
        entity_ids={"per_user": data["user_ids"]},
    )
    cfg = _glm_config(
        coordinates=[
            CoordinateConfig(
                name="fixed", kind=CoordinateKind.FIXED_EFFECT,
                feature_shard="g",
                optimizer=OptimizerSettings(max_iters=30)),
            CoordinateConfig(
                name="user", kind=CoordinateKind.RANDOM_EFFECT,
                feature_shard="u", entity_key="per_user",
                optimizer=OptimizerSettings(max_iters=20)),
        ],
        update_sequence=["fixed", "user"],
        reg_weight_grid={"fixed": [0.1, 1.0]},
        evaluators=[],
    )
    calls = []
    orig = GameEstimator._fit_point

    def spy(self, *a, **kw):
        calls.append(1)
        return orig(self, *a, **kw)

    monkeypatch.setattr(GameEstimator, "_fit_point", spy)
    results = GameEstimator(cfg).fit(train)
    assert len(results) == 2
    assert len(calls) == 2


def test_estimator_grid_swept_mesh(rng):
    """Mesh-sharded fixed effect: the swept grid lane-loops around the
    shard_mapped objective (8 virtual devices via conftest) and matches
    the sequential distributed fits."""
    train = _glm_dataset(rng, n=800, d=40)
    grid = [5.0, 0.5]
    cfg = _glm_config(reg_weight_grid={"fixed": grid}, n_devices=8,
                      intercept=False)
    cfg.coordinates[0].optimizer.max_iters = 60
    _assert_grid_matches_sequential(cfg, train, None, grid, tol=5e-3)


# -- batched tuning ---------------------------------------------------------


def test_fit_tuned_batched_trials(rng, monkeypatch):
    """Swept-eligible tuning evaluates whole proposal batches (no
    per-point _fit_point) and returns n_trials results, both modes."""
    train, valid = _glm_split(rng)
    monkeypatch.setattr(
        GameEstimator, "_fit_point",
        lambda self, *a, **kw: pytest.fail("tuned fell back"))
    for mode, n_trials in (("RANDOM", 5), ("BAYESIAN", 6)):
        cfg = _glm_config(tuning=TuningConfig(
            n_trials=n_trials, mode=mode, trial_batch=3,
            reg_weight_ranges={"fixed": {"low": 0.01, "high": 10.0}}))
        trials = GameEstimator(cfg).fit_tuned(train, valid)
        assert len(trials) == n_trials
        for t in trials:
            assert 0.01 <= t.reg_weights["fixed"] <= 10.0
            assert 0.5 <= t.evaluations[EvaluatorType.AUC] <= 1.0


def test_propose_batch_spreads(rng):
    """GP propose_batch: one fit, q distinct spread proposals; random
    propose_batch: q draws."""
    from photon_ml_tpu.hyperparameter import (
        GaussianProcessSearch,
        ParamRange,
        RandomSearch,
        SearchSpace,
    )

    space = SearchSpace([ParamRange("lam", 1e-3, 10.0)])
    rs = RandomSearch(space, seed=0)
    batch = rs.propose_batch([], 4)
    assert len(batch) == 4
    assert len({round(b["lam"], 9) for b in batch}) == 4

    gp = GaussianProcessSearch(space, seed=0, min_observations=3)
    history = [({"lam": lam}, -abs(np.log10(lam)))
               for lam in (0.01, 0.1, 1.0, 5.0)]
    batch = gp.propose_batch(history, 4)
    assert len(batch) == 4
    units = [space.to_unit(b)[0] for b in batch]
    # Spread: no two picks within the min-distance radius.
    for i in range(4):
        for j in range(i + 1, 4):
            assert abs(units[i] - units[j]) >= 0.05 - 1e-6


def test_tuner_run_batched_contract():
    """run_batched: respects n_trials across uneven batches and feeds
    whole config lists to the evaluator."""
    from photon_ml_tpu.hyperparameter import (
        HyperparameterTuner,
        ParamRange,
        SearchSpace,
        TunerMode,
    )

    space = SearchSpace([ParamRange("lam", 0.01, 10.0)])
    tuner = HyperparameterTuner(space, mode=TunerMode.RANDOM, seed=0)
    seen_batches = []

    def evaluate_batch(configs):
        seen_batches.append(len(configs))
        return [(float(c["lam"]), {"lam": c["lam"]}) for c in configs]

    trials = tuner.run_batched(evaluate_batch, 7, batch_size=3)
    assert len(trials) == 7
    assert seen_batches == [3, 3, 1]
    best = tuner.best(trials)
    assert best.metric == max(t.metric for t in trials)
