"""Pipeline telemetry (ISSUE 7): span tracer, metrics registry, trace
export, report CLI, and liveness (heartbeat / thread-death) contracts.

The pinned-metric tests are the acceptance check: telemetry's counters
must MATCH the subsystems' own ground truth (the chunk store's
hit/load odometers, the objective's ``sweeps`` odometer, the guards
compile listener) on a real streamed fit — a drifting counter is a
lying dashboard.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu import telemetry
from photon_ml_tpu.analysis.guards import count_compiles
from photon_ml_tpu.data.chunked_batch import build_chunked_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.base import OptimizerConfig
from photon_ml_tpu.optim.streaming import (
    ChunkedGLMObjective,
    ChunkPrefetcher,
    streaming_lbfgs_solve,
)
from photon_ml_tpu.utils.run_log import RunLogger, read_run_log

pytestmark = pytest.mark.fast

# Unique problem shape (compile-budget hygiene: the fresh-compile leg
# of other tests must not depend on what this module compiled).
D = 83
K = 4
CHUNK_ROWS = 200
N_CHUNKS = 6


@pytest.fixture(autouse=True)
def _no_leaked_session():
    """Every test must leave the module-global session closed."""
    assert telemetry.active() is None
    yield
    t = telemetry.active()
    if t is not None:        # a failing test leaked its session
        t.close()
        raise AssertionError("test leaked an active telemetry session")


def _spilled_objective(tmp_path, seed=7):
    rng = np.random.default_rng(seed)
    n = CHUNK_ROWS * N_CHUNKS
    cols = np.stack([np.sort(rng.choice(D, K, replace=False))
                     for _ in range(n)]).astype(np.int64)
    vals = rng.normal(size=(n, K)).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    rows = SparseRows.from_flat(np.arange(n + 1, dtype=np.int64) * K,
                                cols.reshape(-1), vals.reshape(-1))
    obj = GLMObjective(loss=losses.LOGISTIC,
                       reg=RegularizationContext.l2(1.0),
                       norm=NormalizationContext.identity())
    cb = build_chunked_batch(rows, D, labels, n_chunks=N_CHUNKS,
                             layout="ell",
                             spill_dir=str(tmp_path / "spill"),
                             host_max_resident=2)
    return ChunkedGLMObjective(obj, cb, max_resident=0, prefetch_depth=2)


def _fit(cobj, max_iters=4):
    return streaming_lbfgs_solve(
        cobj.value_and_gradient, jnp.zeros(D, jnp.float32),
        OptimizerConfig(max_iters=max_iters, tolerance=1e-9),
        value_fn=cobj.value)


# ---------------------------------------------------------------------------
# off path
# ---------------------------------------------------------------------------


def test_off_is_noop_and_emits_nothing(tmp_path):
    """The off contract: no session → the module helpers are no-ops,
    instrumented pipelines write ZERO telemetry events."""
    assert telemetry.active() is None
    with telemetry.span("anything", cat="x", k=1) as sp:
        assert sp.__class__.__name__ == "_NullSpan"
    telemetry.count("c", 5)
    telemetry.gauge("g", 1.0)
    telemetry.observe("h", 0.5)
    telemetry.heartbeat("stage")

    log = RunLogger(str(tmp_path / "log.jsonl"))
    cobj = _spilled_objective(tmp_path)
    _fit(cobj, max_iters=2)
    log.close()
    events = read_run_log(str(tmp_path / "log.jsonl"))
    # Only the RunLogger's own schema header — zero telemetry events
    # (no spans, counters, convergence or device records).
    assert [e["event"] for e in events] == ["run_header"]


def test_maybe_session_off_and_nested(tmp_path):
    with telemetry.maybe_session("off") as t:
        assert t is None
    with telemetry.maybe_session(None) as t:
        assert t is None
    with telemetry.maybe_session("metrics", str(tmp_path)) as outer:
        assert telemetry.active() is outer
        # A nested session request no-ops (driver-over-estimator rule).
        with telemetry.maybe_session("trace", str(tmp_path)) as inner:
            assert inner is outer
        assert telemetry.active() is outer
    assert telemetry.active() is None


def test_double_start_rejected(tmp_path):
    t = telemetry.start("metrics")
    try:
        with pytest.raises(RuntimeError, match="already active"):
            telemetry.start("metrics")
    finally:
        t.close()
    assert telemetry.active() is None


def test_config_validation():
    from photon_ml_tpu.config import ScoringConfig

    cfg = ScoringConfig(input_path="x", model_dir="m", telemetry="trace")
    cfg.validate()
    cfg.telemetry = "verbose"
    with pytest.raises(ValueError, match="telemetry"):
        cfg.validate()


# ---------------------------------------------------------------------------
# pinned metrics: telemetry counters == subsystem ground truth
# ---------------------------------------------------------------------------


def test_metrics_match_ground_truth_on_streamed_fit(tmp_path):
    """LRU hit count, sweeps odometer, and compile count all match the
    subsystems' own records on a small spilled streamed fit."""
    cobj = _spilled_objective(tmp_path)
    log = RunLogger(str(tmp_path / "run_log.jsonl"))
    t = telemetry.start("metrics", run_logger=log)
    try:
        with count_compiles() as cc:
            _fit(cobj)
        summary = t.summary()
    finally:
        t.close()
        log.close()
    c = summary["counters"]
    store = cobj.batch.store
    assert c["solver.sweeps"] == cobj.sweeps > 0
    assert c["store.hits"] == store.hits
    assert c["store.loads"] == store.loads > 0
    assert c["jax.compiles"] == cc.count
    assert c["prefetch.chunks_consumed"] == cobj.sweeps * N_CHUNKS
    assert c["prefetch.consumer_wait_s"] >= 0.0
    assert c["solver.iterations"] >= 1
    assert c["solver.ls_trials"] >= c["solver.iterations"]
    # Derived overlap: defined whenever sweeps streamed through the
    # prefetcher.
    d = summary["derived"]
    assert 0.0 <= d["overlap_efficiency"] <= 1.0
    assert 0.0 <= d["consumer_blocked_fraction"] <= 1.0
    # The summary event landed in the run log.
    events = read_run_log(str(tmp_path / "run_log.jsonl"))
    summ = [e for e in events if e["event"] == "telemetry_summary"]
    assert len(summ) == 1
    assert summ[0]["counters"]["solver.sweeps"] == cobj.sweeps
    # metrics mode: aggregated span stats only, no per-span events.
    assert summ[0]["spans"]["sweep"]["count"] == cobj.sweeps
    assert not [e for e in events if e["event"] == "span"]


# ---------------------------------------------------------------------------
# trace export
# ---------------------------------------------------------------------------


def _check_nesting(spans_by_tid):
    """Spans on one thread must be properly nested: a depth-d span lies
    inside the enclosing depth-(d-1) span's interval (small float
    slack)."""
    eps = 5e-3
    for tid, spans in spans_by_tid.items():
        spans = sorted(spans, key=lambda s: (s["ts"], -s["dur"]))
        stack = []
        for s in spans:
            while stack and stack[-1]["depth"] >= s["depth"]:
                stack.pop()
            if s["depth"] > 0:
                assert stack, f"depth-{s['depth']} span with no parent"
                parent = stack[-1]
                assert parent["depth"] == s["depth"] - 1
                assert s["ts"] >= parent["ts"] - eps
                assert (s["ts"] + s["dur"]
                        <= parent["ts"] + parent["dur"] + eps)
            stack.append(s)


def test_trace_export_valid_chrome_json_and_nesting(tmp_path):
    cobj = _spilled_objective(tmp_path)
    log = RunLogger(str(tmp_path / "run_log.jsonl"))
    t = telemetry.start("trace", telemetry_dir=str(tmp_path),
                        run_logger=log)
    try:
        with telemetry.span("fit", cat="phase"):
            _fit(cobj)
    finally:
        t.close()
        log.close()

    # trace.json: valid Chrome trace-event JSON.
    with open(tmp_path / "trace.json") as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    phs = {e["ph"] for e in events}
    assert "X" in phs and "M" in phs
    for e in events:
        assert {"ph", "name", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert e["dur"] >= 1 and e["ts"] >= 0
    # Thread-name metadata names the prefetch thread.
    names = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "photon-chunk-prefetch" in names
    assert any("MainThread" in n for n in names)

    # JSONL span events: nested correctly per thread, spans from BOTH
    # threads present.
    evs = read_run_log(str(tmp_path / "run_log.jsonl"))
    spans = [e for e in evs if e["event"] == "span"]
    assert spans
    by_tid: dict = {}
    for s in spans:
        by_tid.setdefault(s["tid"], []).append(s)
    assert len(by_tid) >= 2          # main + prefetch thread
    _check_nesting(by_tid)
    names = {s["name"] for s in spans}
    assert {"fit", "sweep", "chunk_compute", "prefetch_load",
            "prefetch_place"} <= names
    # The prefetch thread's loads/places carry the chunk index arg.
    loads = [s for s in spans if s["name"] == "prefetch_load"]
    assert all("args" in s and "chunk" in s["args"] for s in loads)


def test_report_cli_reconciles_and_reports_overlap(tmp_path, capsys):
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    cobj = _spilled_objective(tmp_path)
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("trace", telemetry_dir=str(tmp_path),
                        run_logger=log)
    try:
        with log.timed("fit"):
            _fit(cobj)
    finally:
        t.close()
        log.close()

    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    assert rc == 0
    tail = json.loads(out.strip().splitlines()[-1])
    # The fit phase span covers the solve: stage spans reconcile to
    # >= 90% of the measured wall clock (the ISSUE acceptance bar).
    assert tail["ok"] is True
    assert tail["reconciliation"] >= 0.9
    assert tail["overlap_efficiency"] is not None
    assert 0.0 <= tail["overlap_efficiency"] <= 1.0
    assert tail["phases"]["fit"] > 0
    assert "Reconciliation" in out and "overlap efficiency" in out


def test_report_tolerates_torn_tail(tmp_path, capsys):
    """The report's primary forensic case is a killed run — which can
    leave a partial final JSONL line.  Malformed lines are skipped and
    counted, never fatal (review finding)."""
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log, heartbeat_s=0.01)
    try:
        t.heartbeat("prefetch-producer", chunk=3)
    finally:
        t.close()
        log.close()
    with open(log_path, "a") as f:
        f.write('{"t": 1.0, "event": "hea')     # torn mid-write
    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "malformed line(s) skipped" in out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["heartbeats"]["prefetch-producer"] == 1


def test_report_cli_fails_below_threshold(tmp_path, capsys):
    """An uninstrumented gap (idle wall clock between depth-0 spans)
    fails the reconciliation check at rc 1."""
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("trace", run_logger=log)
    try:
        with telemetry.span("a", cat="x"):
            time.sleep(0.02)
        time.sleep(0.2)            # unattributed wall clock
        with telemetry.span("b", cat="x"):
            time.sleep(0.02)
    finally:
        t.close()
        log.close()
    rc = telemetry_main(["report", log_path])
    tail = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and tail["ok"] is False
    assert tail["reconciliation"] < 0.9


# ---------------------------------------------------------------------------
# liveness: heartbeats + thread death
# ---------------------------------------------------------------------------


def test_prefetcher_death_emits_exception_event(tmp_path):
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log, heartbeat_s=0.05)

    boom = RuntimeError("disk on fire")

    def load(i):
        if i >= 2:
            raise boom
        return np.zeros(4)

    pf = ChunkPrefetcher(load, lambda h: h, depth=2)
    pf.start(range(5))
    try:
        with pytest.raises(RuntimeError, match="disk on fire"):
            for i in range(5):
                pf.next(i)
    finally:
        pf.close()
        t.close()
        log.close()
    deaths = [e for e in read_run_log(log_path)
              if e["event"] == "thread_exception"]
    assert len(deaths) == 1
    assert deaths[0]["stage"] == "prefetch-producer"
    assert "disk on fire" in deaths[0]["error"]
    assert deaths[0]["thread"] == "photon-chunk-prefetch"


def test_starved_consumer_emits_heartbeats(tmp_path):
    """A hung producer (slow load) shows as waiting-but-alive consumer
    heartbeats — the which-stage-stopped forensic."""
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log, heartbeat_s=0.05)

    def slow_load(i):
        time.sleep(0.4 if i == 1 else 0.0)
        return np.zeros(4)

    pf = ChunkPrefetcher(slow_load, lambda h: h, depth=1)
    pf.start(range(3))
    try:
        for i in range(3):
            pf.next(i)
    finally:
        pf.close()
        t.close()
        log.close()
    beats = [e for e in read_run_log(log_path)
             if e["event"] == "heartbeat"]
    consumer = [e for e in beats if e["stage"] == "prefetch-consumer"]
    assert consumer, beats
    assert consumer[0]["state"] == "queue_empty"
    assert consumer[0]["waiting_s"] > 0


def test_sink_writer_death_emits_exception_event(tmp_path):
    from photon_ml_tpu.estimators.streaming_scorer import _SinkWriter

    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log, heartbeat_s=0.05)

    class BadSink:
        def write(self, *a, **kw):
            raise IOError("disk full")

    w = _SinkWriter([BadSink()])
    try:
        w.put(0, 4, np.zeros(4), np.zeros(4), np.zeros(4), {})
        with pytest.raises(IOError, match="disk full"):
            w.close()
            # A racing put may surface the error instead of close().
    finally:
        t.close()
        log.close()
    deaths = [e for e in read_run_log(log_path)
              if e["event"] == "thread_exception"]
    assert len(deaths) == 1
    assert deaths[0]["stage"] == "sink-writer"
    assert "disk full" in deaths[0]["error"]
    assert deaths[0]["thread"] == "photon-score-writer"


def test_idle_sink_writer_heartbeats(tmp_path):
    from photon_ml_tpu.estimators.streaming_scorer import _SinkWriter

    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log, heartbeat_s=0.05)

    class NullSink:
        def write(self, *a, **kw):
            pass

    w = _SinkWriter([NullSink()])
    try:
        time.sleep(0.25)     # starved writer: heartbeats while waiting
        w.close()
    finally:
        t.close()
        log.close()
    beats = [e for e in read_run_log(log_path)
             if e["event"] == "heartbeat"
             and e["stage"] == "sink-writer"]
    assert beats
    assert beats[0]["state"] == "queue_empty"


# ---------------------------------------------------------------------------
# estimator / config wiring
# ---------------------------------------------------------------------------


def test_estimator_fit_honors_telemetry_config(tmp_path):
    """A programmatic fit with telemetry='trace' in the config produces
    run_log.jsonl + trace.json in telemetry_dir with no driver."""
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
    )
    from photon_ml_tpu.estimators import GameEstimator
    from photon_ml_tpu.game.dataset import GameDataset
    from photon_ml_tpu.models.glm import TaskType

    rng = np.random.default_rng(11)
    n, d = 400, 13
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    train = GameDataset(labels=y, features={"global": x}, entity_ids={})
    cfg = TrainingConfig(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[CoordinateConfig(
            name="global", kind=CoordinateKind.FIXED_EFFECT,
            feature_shard="global",
            optimizer=OptimizerSettings(max_iters=10))],
        update_sequence=["global"],
        n_iterations=1,
        evaluators=[],
        telemetry="trace",
        telemetry_dir=str(tmp_path / "tel"),
        output_dir=str(tmp_path / "out"),
    )
    GameEstimator(cfg).fit(train)
    assert telemetry.active() is None     # session closed with fit
    tel_dir = tmp_path / "tel"
    assert (tel_dir / "trace.json").exists()
    events = read_run_log(str(tel_dir / "run_log.jsonl"))
    kinds = {e["event"] for e in events}
    assert {"telemetry_start", "telemetry_summary", "span",
            "trace_written"} <= kinds
    spans = [e for e in events if e["event"] == "span"]
    assert any(s["name"] == "estimator_fit" for s in spans)
    assert any(s["name"] == "cd_coordinate" for s in spans)


def test_e2e_streamed_swept_fit_trace_acceptance(tmp_path, capsys):
    """THE ISSUE-7 acceptance run, in miniature: an end-to-end streamed
    swept fit through the training driver with telemetry=trace yields
    run_log.jsonl + trace.json where the report CLI reconciles stage
    spans to >= 90% of measured wall clock and reports prefetcher
    overlap efficiency."""
    from photon_ml_tpu.cli import game_training_driver
    from photon_ml_tpu.io.libsvm import write_libsvm
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main
    from photon_ml_tpu.utils.synthetic import make_a1a_like

    rows, labels, _ = make_a1a_like(n=1200, seed=5)
    train_path = str(tmp_path / "a1a.libsvm")
    write_libsvm(train_path, rows, np.where(labels > 0, 1, -1))
    out_dir = str(tmp_path / "out")
    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [{
            "name": "global", "kind": "FIXED_EFFECT",
            "feature_shard": "features",
            "optimizer": {"optimizer": "LBFGS", "reg_weight": 1.0,
                          "max_iters": 12},
        }],
        "update_sequence": ["global"],
        "input_path": train_path,
        "validation_fraction": 0.2,
        "output_dir": out_dir,
        "evaluators": ["AUC"],
        "reg_weight_grid": {"global": [3.0, 1.0, 0.3]},
        "chunk_rows": 200,
        "spill_dir": str(tmp_path / "spill"),
        "host_max_resident": 2,
        "telemetry": "trace",
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    game_training_driver.main(["--config", cfg_path])
    assert telemetry.active() is None

    log_path = os.path.join(out_dir, "run_log.jsonl")
    assert os.path.exists(os.path.join(out_dir, "trace.json"))
    events = read_run_log(log_path)
    spans = [e for e in events if e["event"] == "span"]
    names = {s["name"] for s in spans}
    # Driver phases AND streaming-tier stages are on the timeline.
    assert {"fit", "sweep", "swept_train", "prefetch_load"} <= names

    rc = telemetry_main(["report", log_path])
    tail = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and tail["ok"] is True
    assert tail["reconciliation"] >= 0.9
    assert tail["overlap_efficiency"] is not None
    assert tail["counters"]["solver.sweeps"] > 0
    assert tail["counters"]["store.loads"] > 0


def test_runlogger_context_manager_and_thread_safety(tmp_path):
    path = str(tmp_path / "log.jsonl")
    with RunLogger(path) as log:
        log.event("hello", x=1)
        assert log._f is not None
    assert log._f is None                # context exit closed the file
    log.close()                          # idempotent (atexit fallback)
    events = read_run_log(path)
    # Schema header first (ISSUE 8 satellite), then the event.
    assert [e["event"] for e in events] == ["run_header", "hello"]
    assert events[0]["schema"] == 1
    assert events[0]["run_id"]
    assert isinstance(events[0]["argv"], list)
    # Cross-thread event writes keep lines whole (the lock contract:
    # heartbeats arrive from pipeline threads).
    with RunLogger(path) as log:
        threads = [threading.Thread(
            target=lambda j=j: [log.event("t", j=j, i=i)
                                for i in range(50)])
            for j in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    events = read_run_log(path)          # every line parses
    assert len(events) == 201            # header + 200 thread events


def test_runlogger_atexit_flush_fallback(tmp_path):
    """An abandoned logger (no close) still lands its events at
    interpreter exit — the file handle no longer leaks buffered
    lines."""
    import subprocess
    import sys

    path = str(tmp_path / "leak.jsonl")
    code = (
        "from photon_ml_tpu.utils.run_log import RunLogger\n"
        f"log = RunLogger({path!r})\n"
        "log.event('abandoned', x=1)\n"
        "# no close(): the atexit fallback must flush+close\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    events = read_run_log(path)
    assert [e["event"] for e in events] == ["run_header", "abandoned"]


# ---------------------------------------------------------------------------
# ISSUE 8: histogram percentiles (bounded-error contract)
# ---------------------------------------------------------------------------


def test_histogram_percentile_bounded_error():
    """The reservoir is a deterministic every-stride-th subsample; its
    quantiles must track the stream's within the documented rank-error
    bound once the stream far exceeds the cap (10000 obs vs cap 1024 →
    reservoir ≥ 512 entries)."""
    n = 10_000
    rng = np.random.default_rng(17)
    shuffled = rng.permutation(n).astype(float)
    t = telemetry.start("metrics")
    try:
        for v in shuffled:
            t.observe("test.shuffled", v)
        for v in range(n):                       # arrival-ordered
            t.observe("test.ordered", float(v))
        for q, truth in ((0.5, 0.5 * (n - 1)), (0.95, 0.95 * (n - 1)),
                         (0.99, 0.99 * (n - 1))):
            # Ordered arrivals: systematic sample → near-exact.
            assert abs(t.percentile("test.ordered", q) - truth) <= 0.01 * n
            # Shuffled arrivals: uniform-ish subsample of ≥512 → a few
            # percentile points of rank error.
            assert abs(t.percentile("test.shuffled", q) - truth) <= 0.05 * n
        assert t.percentile("no.such.metric", 0.5) is None
        with pytest.raises(ValueError, match="quantile"):
            t.percentile("test.ordered", 1.5)
        summ = t.summary()
        h = summ["histograms"]["test.ordered"]
        assert h["p50"] is not None and h["p95"] is not None
        assert h["p50"] <= h["p95"] <= h["p99"] <= h["max"]
    finally:
        t.close()


# ---------------------------------------------------------------------------
# ISSUE 8: device accounting
# ---------------------------------------------------------------------------


def test_device_cost_captured_on_streamed_fit(tmp_path):
    """A metrics-mode streamed fit captures the per-chunk programs' XLA
    cost analyses (FLOPs, bytes, roofline estimate) once per session,
    emits device_cost events, and samples the device-memory gauge at
    phase boundaries (live-buffer census on the CPU backend)."""
    cobj = _spilled_objective(tmp_path)
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log)
    try:
        with telemetry.span("fit", cat="phase"):
            _fit(cobj)
        summary = t.summary()
    finally:
        t.close()
        log.close()
    programs = summary["device"]["programs"]
    assert {"chunk_vg", "chunk_value"} <= set(programs)
    for name in ("chunk_vg", "chunk_value"):
        cost = programs[name]
        assert cost["flops"] > 0
        assert cost["bytes_accessed"] > 0
        assert cost["roofline_est_ms"] > 0
        assert cost["span"] == "chunk_compute"
    # Phase boundaries sampled the device-memory gauge (CPU → census).
    mem = summary["device"]["memory"]
    assert mem["source"] == "live_arrays"
    assert mem["samples"] >= 2                   # fit open + close
    assert summary["gauges"]["device.bytes_in_use"]["last"] >= 0
    events = read_run_log(log_path)
    costs = [e for e in events if e["event"] == "device_cost"]
    assert {e["program"] for e in costs} >= {"chunk_vg", "chunk_value"}
    # Each boundary sample lands as a TAGGED event, so a specific
    # boundary's footprint is recoverable from the log.
    mems = [e for e in events if e["event"] == "device_memory"]
    assert mems and all(e["tag"] == "fit" for e in mems)


def test_device_capture_compiles_nothing_new(tmp_path):
    """The capture relowers a warm program: the compile bridge (and the
    guard listener) must see ZERO new compile records — the
    compile-budget contract with telemetry on."""
    cobj = _spilled_objective(tmp_path)
    w0 = jnp.zeros(D, jnp.float32)
    _fit(cobj, max_iters=2)      # everything compiled, no session
    t = telemetry.start("metrics")
    try:
        with count_compiles() as cc:
            cobj.capture_device_cost(w0)
        summary = t.summary()
    finally:
        t.close()
    assert cc.count == 0, cc.programs
    assert summary["counters"].get("jax.compiles", 0) == 0
    assert summary["device"]["programs"]["chunk_vg"]["flops"] > 0


def test_report_shows_device_section(tmp_path, capsys):
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    cobj = _spilled_objective(tmp_path)
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("trace", telemetry_dir=str(tmp_path),
                        run_logger=log)
    try:
        with log.timed("fit"):
            _fit(cobj)
    finally:
        t.close()
        log.close()
    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Device programs (XLA cost analysis):" in out
    tail = json.loads(out.strip().splitlines()[-1])
    dev = tail["device"]["programs"]["chunk_vg"]
    assert dev["bytes_accessed"] > 0
    # The roofline estimate is joined against the measured span time.
    assert dev["measured_span_ms"] > 0
    assert dev["roofline_fraction"] is not None


# ---------------------------------------------------------------------------
# ISSUE 8: convergence traces + sweep-odometer reconciliation
# ---------------------------------------------------------------------------


def test_convergence_events_reconcile_with_odometer(tmp_path, capsys):
    """A metrics-mode streamed fit emits one convergence_iter event per
    solver iteration and one convergence_trace per solve; the report's
    sweep-odometer identity (sweeps == solves + ls trials + grad
    recoveries + aux) holds exactly."""
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    cobj = _spilled_objective(tmp_path)
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log)
    try:
        _fit(cobj)
        summary = t.summary()
    finally:
        t.close()
        log.close()
    c = summary["counters"]
    events = read_run_log(log_path)
    iters = [e for e in events if e["event"] == "convergence_iter"]
    traces = [e for e in events if e["event"] == "convergence_trace"]
    assert len(iters) == c["solver.iterations"] == c["conv.iterations"]
    assert len(traces) == 1
    tr = traces[0]
    assert tr["solver"] == "streaming_lbfgs"
    assert tr["iterations"] >= 1
    # Tracker planes ride the trace: slot 0 (initial) + one per iter.
    assert len(tr["values"]) == tr["iterations"] + 1
    assert len(tr["step_sizes"]) == tr["iterations"] + 1
    # Per-iteration events carry step size and trial count.
    assert all("step_size" in e and e["ls_trials"] >= 1 for e in iters)
    # The odometer identity, from the raw counters...
    assert c["solver.sweeps"] == (c["solver.streamed_solves"]
                                  + c["solver.ls_trials"]
                                  + c.get("solver.grad_recovery_sweeps", 0)
                                  + c.get("solver.aux_sweeps", 0))
    # ...and through the report (rc 0, convergence ok).
    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "sweep odometer" in out and "PASS" in out
    tail = json.loads(out.strip().splitlines()[-1])
    conv = tail["convergence"]
    assert conv["ok"] is True
    assert conv["unattributed_sweeps"] == 0
    assert conv["iterations"]["streaming_lbfgs"] == len(iters)


def test_tron_convergence_reconciles_with_hvp_odometer(tmp_path, capsys):
    """ISSUE 17: a streamed TRON fit closes the sweep-odometer identity
    through the new hvp_sweeps term exactly — sweeps == streamed_solves
    (the initial value+gradient) + ls_trials (one trial point per outer
    iteration) + aux_sweeps (the Jacobi diagonal) + hvp_sweeps (the CG
    passes) — and the report renders the trust-region trajectory (the
    per-iteration delta/rho the convergence events carry)."""
    from photon_ml_tpu.optim.streaming import streaming_tron_solve
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    cobj = _spilled_objective(tmp_path)
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log)
    try:
        streaming_tron_solve(
            cobj.value_and_gradient, cobj.hvp_pass,
            jnp.zeros(D, jnp.float32),
            OptimizerConfig(max_iters=4, tolerance=1e-9),
            hessian_diag=cobj.hessian_diagonal, label="t")
        summary = t.summary()
    finally:
        t.close()
        log.close()
    c = summary["counters"]
    assert c["solver.hvp_sweeps"] > 0
    assert c["solver.aux_sweeps"] >= 1       # the preconditioner pass
    assert c["solver.sweeps"] == (
        c["solver.streamed_solves"] + c["solver.ls_trials"]
        + c.get("solver.grad_recovery_sweeps", 0)
        + c["solver.aux_sweeps"]
        + c.get("solver.fused_cycle_sweeps", 0)
        + c["solver.hvp_sweeps"])
    events = read_run_log(log_path)
    iters = [e for e in events if e["event"] == "convergence_iter"]
    assert len(iters) == c["solver.iterations"]
    # Every TRON iteration event carries the radius and the ratio.
    assert all(e.get("delta", 0) > 0 for e in iters)
    assert all("rho" in e for e in iters)
    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    assert rc == 0
    tail = json.loads(out.strip().splitlines()[-1])
    conv = tail["convergence"]
    assert conv["ok"] is True
    assert conv["unattributed_sweeps"] == 0
    assert conv["hvp_sweeps"] == c["solver.hvp_sweeps"]
    assert conv["passes_per_solve"] == c["solver.sweeps"]
    tr = conv["trust_region"]["streaming_tron:t"]
    assert len(tr["delta"]) == len(iters)
    assert tr["delta"][0] > 0
    assert "trust region" in out
    assert "hvp" in out


def test_direct_evaluations_stay_informational(tmp_path, capsys):
    """A direct objective evaluation outside any solve (a final-loss
    log line, a notebook probe) is a legitimate pass no solve claims:
    it must show as POSITIVE unattributed sweeps and keep rc 0 — only
    impossible accounting (negative) fails the gate."""
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    cobj = _spilled_objective(tmp_path)
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log)
    try:
        res = _fit(cobj)
        cobj.value(res.w)                      # the unclaimed pass
    finally:
        t.close()
        log.close()
    rc = telemetry_main(["report", log_path])
    tail = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and tail["ok"] is True
    assert tail["convergence"]["ok"] is True
    assert tail["convergence"]["unattributed_sweeps"] == 1


def test_report_fails_on_odometer_drift(tmp_path, capsys):
    """A log whose counters claim more solver evaluations than data
    passes (the drift this check exists to catch) fails the report at
    rc 1 naming the convergence check."""
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    log_path = str(tmp_path / "drift.jsonl")
    events = [
        {"t": 0.0, "event": "run_header", "schema": 1, "run_id": "x"},
        {"t": 1.0, "event": "telemetry_summary", "mode": "metrics",
         "counters": {"solver.sweeps": 3, "solver.streamed_solves": 1,
                      "solver.ls_trials": 4, "solver.iterations": 4},
         "gauges": {}, "histograms": {}, "spans": {}, "derived": {}},
    ]
    with open(log_path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    assert rc == 1
    assert "CONVERGENCE FAIL" in out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["ok"] is False
    assert tail["convergence"]["ok"] is False
    # 3 sweeps recorded, 1 + 4 = 5 claimed evaluations → 2 passes
    # claimed by nobody's data.
    assert tail["convergence"]["unattributed_sweeps"] == -2


def test_e2e_swept_streamed_fit_metrics_convergence(tmp_path, capsys):
    """THE ISSUE-8 acceptance run: an e2e swept streamed fit through
    the training driver with telemetry=metrics emits convergence traces
    whose per-solver iteration totals reconcile with the solver.sweeps
    odometer in `telemetry report`."""
    from photon_ml_tpu.cli import game_training_driver
    from photon_ml_tpu.io.libsvm import write_libsvm
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main
    from photon_ml_tpu.utils.synthetic import make_a1a_like

    rows, labels, _ = make_a1a_like(n=1200, seed=5)
    train_path = str(tmp_path / "a1a.libsvm")
    write_libsvm(train_path, rows, np.where(labels > 0, 1, -1))
    out_dir = str(tmp_path / "out")
    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [{
            "name": "global", "kind": "FIXED_EFFECT",
            "feature_shard": "features",
            "optimizer": {"optimizer": "LBFGS", "reg_weight": 1.0,
                          "max_iters": 12},
        }],
        "update_sequence": ["global"],
        "input_path": train_path,
        "validation_fraction": 0.2,
        "output_dir": out_dir,
        "evaluators": ["AUC"],
        "reg_weight_grid": {"global": [3.0, 1.0, 0.3]},
        "chunk_rows": 200,
        "spill_dir": str(tmp_path / "spill"),
        "host_max_resident": 2,
        "telemetry": "metrics",
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    game_training_driver.main(["--config", cfg_path])
    assert telemetry.active() is None

    log_path = os.path.join(out_dir, "run_log.jsonl")
    events = read_run_log(log_path)
    # Header first (schema-versioned), convergence events present.
    assert events[0]["event"] == "run_header"
    assert events[0]["schema"] == 1
    assert events[0]["telemetry"] == "metrics"
    iters = [e for e in events if e["event"] == "convergence_iter"]
    assert iters and all(e["solver"] == "streaming_lbfgs_swept"
                         and e["label"] == "global" for e in iters)
    assert all(len(e["values"]) == 3 for e in iters)   # per-lane
    traces = [e for e in events if e["event"] == "convergence_trace"]
    assert len(traces) == 1 and traces[0]["lanes"] == 3

    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert rc == 0 and tail["ok"] is True
    conv = tail["convergence"]
    assert conv["ok"] is True
    assert conv["sweeps"] > 0
    assert conv["unattributed_sweeps"] == 0
    assert conv["iterations"]["streaming_lbfgs_swept:global"] == len(iters)
    assert tail["run_id"] == events[0]["run_id"]


def test_scoring_driver_trace_mode_report(tmp_path, capsys):
    """ISSUE 8 satellite: `telemetry report` over a trace-mode log
    produced by the SCORING driver e2e (only the training driver path
    was reconciliation-tested before)."""
    from photon_ml_tpu.cli import game_scoring_driver, game_training_driver
    from photon_ml_tpu.io.libsvm import write_libsvm
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main
    from photon_ml_tpu.utils.synthetic import make_a1a_like

    rows, labels, _ = make_a1a_like(n=1000, seed=7)
    train_path = str(tmp_path / "a1a.libsvm")
    write_libsvm(train_path, rows, np.where(labels > 0, 1, -1))
    out_dir = str(tmp_path / "out")
    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [{
            "name": "global", "kind": "FIXED_EFFECT",
            "feature_shard": "features",
            "optimizer": {"reg_weight": 1.0, "max_iters": 10},
        }],
        "update_sequence": ["global"],
        "input_path": train_path,
        "output_dir": out_dir,
        "evaluators": [],
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    game_training_driver.main(["--config", cfg_path])

    score_dir = tmp_path / "scored"
    sc = {"input_path": train_path,
          "model_dir": os.path.join(out_dir, "model"),
          "output_path": str(score_dir / "scores.npz"),
          "evaluators": ["AUC"],
          "score_chunk_rows": 128,
          "spill_dir": str(tmp_path / "spill_sc"),
          "host_max_resident": 2,
          "telemetry": "trace"}
    sc_path = str(tmp_path / "sc.json")
    with open(sc_path, "w") as f:
        json.dump(sc, f)
    game_scoring_driver.main(["--config", sc_path])
    assert telemetry.active() is None

    log_path = str(score_dir / "scoring_log.jsonl")
    assert os.path.exists(str(score_dir / "trace.json"))
    events = read_run_log(log_path)
    assert events[0]["event"] == "run_header"
    assert events[0]["driver"] == "game_scoring"
    assert events[0]["telemetry"] == "trace"
    spans = [e for e in events if e["event"] == "span"]
    names = {s["name"] for s in spans}
    assert {"transform_streamed", "score_pass", "chunk_compute"} <= names

    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert rc == 0 and tail["ok"] is True
    assert tail["reconciliation"] >= 0.9
    assert tail["phases"]["transform_streamed"] > 0
    assert tail["counters"]["score.passes"] == 1


def test_streamed_re_emits_convergence_dynamics(tmp_path):
    """The streamed random-effect coordinate emits one re_convergence
    event per sweep carrying the solved/converged/retired/woken entity
    dynamics (previously judged only by end-state parity)."""
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.game.coordinates import (
        build_streamed_random_effect_coordinate,
    )
    from photon_ml_tpu.game.dataset import GameDataset
    from photon_ml_tpu.optim import OptimizerConfig

    rng = np.random.default_rng(3)
    n, p, E = 600, 4, 24
    ids = rng.integers(0, E, n)
    x = rng.normal(size=(n, p)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    ds = GameDataset(labels=y, features={"re": x},
                     entity_ids={"u": ids})
    obj = GLMObjective(loss=losses.LOGISTIC,
                       reg=RegularizationContext.l2(1.0),
                       norm=NormalizationContext.identity())
    coord = build_streamed_random_effect_coordinate(
        "u", ds, "re", obj,
        config=OptimizerConfig(max_iters=30, tolerance=1e-4),
        spill_dir=str(tmp_path / "spill_re"), chunk_entities=8,
        host_max_resident=2, prefetch_depth=1, retirement=True)

    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log)
    try:
        off = jnp.zeros(n, jnp.float32)
        w, diag = coord.train(off, None)
        coord.retire_converged()               # sweep 1: no candidates yet
        w, diag = coord.train(off, w)
        coord.retire_converged()               # static offsets → retire
        w, diag = coord.train(off, w)
        summary = t.summary()
    finally:
        t.close()
        log.close()
    assert "entities_woken" in diag
    events = read_run_log(log_path)
    res = [e for e in events if e["event"] == "re_convergence"]
    assert len(res) == 3
    assert res[0]["coordinate"] == "u"
    assert res[0]["entities_solved"] == E
    assert res[2]["entities_retired"] > 0      # third sweep saw frozen
    assert summary["counters"]["conv.re_sweeps"] == 3
    # Device cost of the per-bucket chunk-train program was captured.
    programs = summary.get("device", {}).get("programs", {})
    assert any(k.startswith("re_chunk_train.b") for k in programs)


# ---------------------------------------------------------------------------
# ISSUE 8: bench-history trajectory gating
# ---------------------------------------------------------------------------


def _write_round(path, record, rc=0, wrapper=False):
    with open(path, "w") as f:
        if wrapper:
            json.dump({"n": 1, "cmd": "bench", "rc": rc,
                       "tail": "", "parsed": record}, f)
        else:
            json.dump({"schema": 1, "kind": "bench_record",
                       "argv": ["--section", "stream"], "rc": rc,
                       "record": record}, f)


def _stream_record(rows_per_sec, ratio=1.0):
    return {"stream": {"spilled": {"examples_per_sec": rows_per_sec},
                       "pass_time_ratio": ratio}}


def test_history_clean_then_regressed(tmp_path, capsys):
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    hist = tmp_path / "hist"
    hist.mkdir()
    _write_round(str(hist / "r01.json"), _stream_record(1000.0))
    _write_round(str(hist / "r02.json"), _stream_record(1040.0))
    rc = telemetry_main(["history", str(hist)])
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert rc == 0 and tail["ok"] is True
    assert tail["regressions"] == [] and tail["failed_rounds"] == []
    traj = tail["trajectory"]["stream:stream.spilled.examples_per_sec"]
    assert traj["values"] == [1000.0, 1040.0]

    # Injected 20% rows/s regression in a third round → rc 1 naming
    # the section/metric (the acceptance bar).
    _write_round(str(hist / "r03.json"), _stream_record(816.0))
    rc = telemetry_main(["history", str(hist)])
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert rc == 1 and tail["ok"] is False
    regs = tail["regressions"]
    assert len(regs) == 1
    assert regs[0]["round"] == "r03.json"
    assert regs[0]["metric"] == "stream:stream.spilled.examples_per_sec"
    assert "REGRESSION" in out


def test_history_flags_nonzero_rc_round(tmp_path, capsys):
    """A round whose wrapper recorded a nonzero rc (the repo's own
    BENCH_r05 shape: rc=124, parsed null) fails the gate by itself."""
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    hist = tmp_path / "hist"
    hist.mkdir()
    _write_round(str(hist / "r01.json"), _stream_record(1000.0),
                 wrapper=True)
    _write_round(str(hist / "r02.json"), None, rc=124, wrapper=True)
    # A torn wrapper that recorded "rc": null must flag, not crash.
    _write_round(str(hist / "r03.json"), None, rc=None, wrapper=True)
    rc = telemetry_main(["history", str(hist)])
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert rc == 1 and tail["ok"] is False
    assert {(f["round"], f["rc"]) for f in tail["failed_rounds"]} == {
        ("r02.json", 124), ("r03.json", None)}
    assert "FAILED ROUND" in out


def test_history_over_repo_bench_records(tmp_path, capsys):
    """THE acceptance check on the real artifacts: the repo's
    BENCH_r01..r04 trajectory is clean (rc 0); adding one synthetic
    regressed round — and the real rc-124 r05 — exits rc 1 naming the
    regressed section/metric."""
    import shutil

    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_rounds = [os.path.join(root, f"BENCH_r0{i}.json")
                   for i in range(1, 6)]
    assert all(os.path.exists(p) for p in repo_rounds)

    rc = telemetry_main(["history", *repo_rounds[:4]])
    tail = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and tail["ok"] is True

    # Repo rounds + a synthetic regressed round: the GRR throughput
    # collapses 40% → rc 1, regression named, r05's rc=124 flagged too.
    hist = tmp_path / "hist"
    hist.mkdir()
    for p in repo_rounds:
        shutil.copy(p, str(hist / os.path.basename(p)))
    _write_round(str(hist / "BENCH_r99.json"),
                 {"value": 206592425.1 * 0.6, "step_ms_grr": 4.84})
    rc = telemetry_main(["history", str(hist)])
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert rc == 1 and tail["ok"] is False
    assert any(r["metric"] == "overall:value"
               and r["round"] == "BENCH_r99.json"
               for r in tail["regressions"])
    assert any(fr["rc"] == 124 for fr in tail["failed_rounds"])


def test_history_tolerates_garbage_files(tmp_path, capsys):
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    hist = tmp_path / "hist"
    hist.mkdir()
    (hist / "bad.json").write_text("{not json")
    _write_round(str(hist / "ok.json"), _stream_record(1000.0))
    rc = telemetry_main(["history", str(hist)])
    tail = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1                       # unreadable round = failed round
    assert tail["failed_rounds"][0]["round"] == "bad.json"
    assert "error" in tail["failed_rounds"][0]
