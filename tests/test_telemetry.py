"""Pipeline telemetry (ISSUE 7): span tracer, metrics registry, trace
export, report CLI, and liveness (heartbeat / thread-death) contracts.

The pinned-metric tests are the acceptance check: telemetry's counters
must MATCH the subsystems' own ground truth (the chunk store's
hit/load odometers, the objective's ``sweeps`` odometer, the guards
compile listener) on a real streamed fit — a drifting counter is a
lying dashboard.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu import telemetry
from photon_ml_tpu.analysis.guards import count_compiles
from photon_ml_tpu.data.chunked_batch import build_chunked_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.base import OptimizerConfig
from photon_ml_tpu.optim.streaming import (
    ChunkedGLMObjective,
    ChunkPrefetcher,
    streaming_lbfgs_solve,
)
from photon_ml_tpu.utils.run_log import RunLogger, read_run_log

pytestmark = pytest.mark.fast

# Unique problem shape (compile-budget hygiene: the fresh-compile leg
# of other tests must not depend on what this module compiled).
D = 83
K = 4
CHUNK_ROWS = 200
N_CHUNKS = 6


@pytest.fixture(autouse=True)
def _no_leaked_session():
    """Every test must leave the module-global session closed."""
    assert telemetry.active() is None
    yield
    t = telemetry.active()
    if t is not None:        # a failing test leaked its session
        t.close()
        raise AssertionError("test leaked an active telemetry session")


def _spilled_objective(tmp_path, seed=7):
    rng = np.random.default_rng(seed)
    n = CHUNK_ROWS * N_CHUNKS
    cols = np.stack([np.sort(rng.choice(D, K, replace=False))
                     for _ in range(n)]).astype(np.int64)
    vals = rng.normal(size=(n, K)).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    rows = SparseRows.from_flat(np.arange(n + 1, dtype=np.int64) * K,
                                cols.reshape(-1), vals.reshape(-1))
    obj = GLMObjective(loss=losses.LOGISTIC,
                       reg=RegularizationContext.l2(1.0),
                       norm=NormalizationContext.identity())
    cb = build_chunked_batch(rows, D, labels, n_chunks=N_CHUNKS,
                             layout="ell",
                             spill_dir=str(tmp_path / "spill"),
                             host_max_resident=2)
    return ChunkedGLMObjective(obj, cb, max_resident=0, prefetch_depth=2)


def _fit(cobj, max_iters=4):
    return streaming_lbfgs_solve(
        cobj.value_and_gradient, jnp.zeros(D, jnp.float32),
        OptimizerConfig(max_iters=max_iters, tolerance=1e-9),
        value_fn=cobj.value)


# ---------------------------------------------------------------------------
# off path
# ---------------------------------------------------------------------------


def test_off_is_noop_and_emits_nothing(tmp_path):
    """The off contract: no session → the module helpers are no-ops,
    instrumented pipelines write ZERO telemetry events."""
    assert telemetry.active() is None
    with telemetry.span("anything", cat="x", k=1) as sp:
        assert sp.__class__.__name__ == "_NullSpan"
    telemetry.count("c", 5)
    telemetry.gauge("g", 1.0)
    telemetry.observe("h", 0.5)
    telemetry.heartbeat("stage")

    log = RunLogger(str(tmp_path / "log.jsonl"))
    cobj = _spilled_objective(tmp_path)
    _fit(cobj, max_iters=2)
    log.close()
    events = read_run_log(str(tmp_path / "log.jsonl"))
    assert events == []      # nothing touched the logger


def test_maybe_session_off_and_nested(tmp_path):
    with telemetry.maybe_session("off") as t:
        assert t is None
    with telemetry.maybe_session(None) as t:
        assert t is None
    with telemetry.maybe_session("metrics", str(tmp_path)) as outer:
        assert telemetry.active() is outer
        # A nested session request no-ops (driver-over-estimator rule).
        with telemetry.maybe_session("trace", str(tmp_path)) as inner:
            assert inner is outer
        assert telemetry.active() is outer
    assert telemetry.active() is None


def test_double_start_rejected(tmp_path):
    t = telemetry.start("metrics")
    try:
        with pytest.raises(RuntimeError, match="already active"):
            telemetry.start("metrics")
    finally:
        t.close()
    assert telemetry.active() is None


def test_config_validation():
    from photon_ml_tpu.config import ScoringConfig

    cfg = ScoringConfig(input_path="x", model_dir="m", telemetry="trace")
    cfg.validate()
    cfg.telemetry = "verbose"
    with pytest.raises(ValueError, match="telemetry"):
        cfg.validate()


# ---------------------------------------------------------------------------
# pinned metrics: telemetry counters == subsystem ground truth
# ---------------------------------------------------------------------------


def test_metrics_match_ground_truth_on_streamed_fit(tmp_path):
    """LRU hit count, sweeps odometer, and compile count all match the
    subsystems' own records on a small spilled streamed fit."""
    cobj = _spilled_objective(tmp_path)
    log = RunLogger(str(tmp_path / "run_log.jsonl"))
    t = telemetry.start("metrics", run_logger=log)
    try:
        with count_compiles() as cc:
            _fit(cobj)
        summary = t.summary()
    finally:
        t.close()
        log.close()
    c = summary["counters"]
    store = cobj.batch.store
    assert c["solver.sweeps"] == cobj.sweeps > 0
    assert c["store.hits"] == store.hits
    assert c["store.loads"] == store.loads > 0
    assert c["jax.compiles"] == cc.count
    assert c["prefetch.chunks_consumed"] == cobj.sweeps * N_CHUNKS
    assert c["prefetch.consumer_wait_s"] >= 0.0
    assert c["solver.iterations"] >= 1
    assert c["solver.ls_trials"] >= c["solver.iterations"]
    # Derived overlap: defined whenever sweeps streamed through the
    # prefetcher.
    d = summary["derived"]
    assert 0.0 <= d["overlap_efficiency"] <= 1.0
    assert 0.0 <= d["consumer_blocked_fraction"] <= 1.0
    # The summary event landed in the run log.
    events = read_run_log(str(tmp_path / "run_log.jsonl"))
    summ = [e for e in events if e["event"] == "telemetry_summary"]
    assert len(summ) == 1
    assert summ[0]["counters"]["solver.sweeps"] == cobj.sweeps
    # metrics mode: aggregated span stats only, no per-span events.
    assert summ[0]["spans"]["sweep"]["count"] == cobj.sweeps
    assert not [e for e in events if e["event"] == "span"]


# ---------------------------------------------------------------------------
# trace export
# ---------------------------------------------------------------------------


def _check_nesting(spans_by_tid):
    """Spans on one thread must be properly nested: a depth-d span lies
    inside the enclosing depth-(d-1) span's interval (small float
    slack)."""
    eps = 5e-3
    for tid, spans in spans_by_tid.items():
        spans = sorted(spans, key=lambda s: (s["ts"], -s["dur"]))
        stack = []
        for s in spans:
            while stack and stack[-1]["depth"] >= s["depth"]:
                stack.pop()
            if s["depth"] > 0:
                assert stack, f"depth-{s['depth']} span with no parent"
                parent = stack[-1]
                assert parent["depth"] == s["depth"] - 1
                assert s["ts"] >= parent["ts"] - eps
                assert (s["ts"] + s["dur"]
                        <= parent["ts"] + parent["dur"] + eps)
            stack.append(s)


def test_trace_export_valid_chrome_json_and_nesting(tmp_path):
    cobj = _spilled_objective(tmp_path)
    log = RunLogger(str(tmp_path / "run_log.jsonl"))
    t = telemetry.start("trace", telemetry_dir=str(tmp_path),
                        run_logger=log)
    try:
        with telemetry.span("fit", cat="phase"):
            _fit(cobj)
    finally:
        t.close()
        log.close()

    # trace.json: valid Chrome trace-event JSON.
    with open(tmp_path / "trace.json") as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    phs = {e["ph"] for e in events}
    assert "X" in phs and "M" in phs
    for e in events:
        assert {"ph", "name", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert e["dur"] >= 1 and e["ts"] >= 0
    # Thread-name metadata names the prefetch thread.
    names = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "photon-chunk-prefetch" in names
    assert any("MainThread" in n for n in names)

    # JSONL span events: nested correctly per thread, spans from BOTH
    # threads present.
    evs = read_run_log(str(tmp_path / "run_log.jsonl"))
    spans = [e for e in evs if e["event"] == "span"]
    assert spans
    by_tid: dict = {}
    for s in spans:
        by_tid.setdefault(s["tid"], []).append(s)
    assert len(by_tid) >= 2          # main + prefetch thread
    _check_nesting(by_tid)
    names = {s["name"] for s in spans}
    assert {"fit", "sweep", "chunk_compute", "prefetch_load",
            "prefetch_place"} <= names
    # The prefetch thread's loads/places carry the chunk index arg.
    loads = [s for s in spans if s["name"] == "prefetch_load"]
    assert all("args" in s and "chunk" in s["args"] for s in loads)


def test_report_cli_reconciles_and_reports_overlap(tmp_path, capsys):
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    cobj = _spilled_objective(tmp_path)
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("trace", telemetry_dir=str(tmp_path),
                        run_logger=log)
    try:
        with log.timed("fit"):
            _fit(cobj)
    finally:
        t.close()
        log.close()

    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    assert rc == 0
    tail = json.loads(out.strip().splitlines()[-1])
    # The fit phase span covers the solve: stage spans reconcile to
    # >= 90% of the measured wall clock (the ISSUE acceptance bar).
    assert tail["ok"] is True
    assert tail["reconciliation"] >= 0.9
    assert tail["overlap_efficiency"] is not None
    assert 0.0 <= tail["overlap_efficiency"] <= 1.0
    assert tail["phases"]["fit"] > 0
    assert "Reconciliation" in out and "overlap efficiency" in out


def test_report_tolerates_torn_tail(tmp_path, capsys):
    """The report's primary forensic case is a killed run — which can
    leave a partial final JSONL line.  Malformed lines are skipped and
    counted, never fatal (review finding)."""
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log, heartbeat_s=0.01)
    try:
        t.heartbeat("prefetch-producer", chunk=3)
    finally:
        t.close()
        log.close()
    with open(log_path, "a") as f:
        f.write('{"t": 1.0, "event": "hea')     # torn mid-write
    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "malformed line(s) skipped" in out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["heartbeats"]["prefetch-producer"] == 1


def test_report_cli_fails_below_threshold(tmp_path, capsys):
    """An uninstrumented gap (idle wall clock between depth-0 spans)
    fails the reconciliation check at rc 1."""
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main

    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("trace", run_logger=log)
    try:
        with telemetry.span("a", cat="x"):
            time.sleep(0.02)
        time.sleep(0.2)            # unattributed wall clock
        with telemetry.span("b", cat="x"):
            time.sleep(0.02)
    finally:
        t.close()
        log.close()
    rc = telemetry_main(["report", log_path])
    tail = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and tail["ok"] is False
    assert tail["reconciliation"] < 0.9


# ---------------------------------------------------------------------------
# liveness: heartbeats + thread death
# ---------------------------------------------------------------------------


def test_prefetcher_death_emits_exception_event(tmp_path):
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log, heartbeat_s=0.05)

    boom = RuntimeError("disk on fire")

    def load(i):
        if i >= 2:
            raise boom
        return np.zeros(4)

    pf = ChunkPrefetcher(load, lambda h: h, depth=2)
    pf.start(range(5))
    try:
        with pytest.raises(RuntimeError, match="disk on fire"):
            for i in range(5):
                pf.next(i)
    finally:
        pf.close()
        t.close()
        log.close()
    deaths = [e for e in read_run_log(log_path)
              if e["event"] == "thread_exception"]
    assert len(deaths) == 1
    assert deaths[0]["stage"] == "prefetch-producer"
    assert "disk on fire" in deaths[0]["error"]
    assert deaths[0]["thread"] == "photon-chunk-prefetch"


def test_starved_consumer_emits_heartbeats(tmp_path):
    """A hung producer (slow load) shows as waiting-but-alive consumer
    heartbeats — the which-stage-stopped forensic."""
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log, heartbeat_s=0.05)

    def slow_load(i):
        time.sleep(0.4 if i == 1 else 0.0)
        return np.zeros(4)

    pf = ChunkPrefetcher(slow_load, lambda h: h, depth=1)
    pf.start(range(3))
    try:
        for i in range(3):
            pf.next(i)
    finally:
        pf.close()
        t.close()
        log.close()
    beats = [e for e in read_run_log(log_path)
             if e["event"] == "heartbeat"]
    consumer = [e for e in beats if e["stage"] == "prefetch-consumer"]
    assert consumer, beats
    assert consumer[0]["state"] == "queue_empty"
    assert consumer[0]["waiting_s"] > 0


def test_sink_writer_death_emits_exception_event(tmp_path):
    from photon_ml_tpu.estimators.streaming_scorer import _SinkWriter

    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log, heartbeat_s=0.05)

    class BadSink:
        def write(self, *a, **kw):
            raise IOError("disk full")

    w = _SinkWriter([BadSink()])
    try:
        w.put(0, 4, np.zeros(4), np.zeros(4), np.zeros(4), {})
        with pytest.raises(IOError, match="disk full"):
            w.close()
            # A racing put may surface the error instead of close().
    finally:
        t.close()
        log.close()
    deaths = [e for e in read_run_log(log_path)
              if e["event"] == "thread_exception"]
    assert len(deaths) == 1
    assert deaths[0]["stage"] == "sink-writer"
    assert "disk full" in deaths[0]["error"]
    assert deaths[0]["thread"] == "photon-score-writer"


def test_idle_sink_writer_heartbeats(tmp_path):
    from photon_ml_tpu.estimators.streaming_scorer import _SinkWriter

    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log, heartbeat_s=0.05)

    class NullSink:
        def write(self, *a, **kw):
            pass

    w = _SinkWriter([NullSink()])
    try:
        time.sleep(0.25)     # starved writer: heartbeats while waiting
        w.close()
    finally:
        t.close()
        log.close()
    beats = [e for e in read_run_log(log_path)
             if e["event"] == "heartbeat"
             and e["stage"] == "sink-writer"]
    assert beats
    assert beats[0]["state"] == "queue_empty"


# ---------------------------------------------------------------------------
# estimator / config wiring
# ---------------------------------------------------------------------------


def test_estimator_fit_honors_telemetry_config(tmp_path):
    """A programmatic fit with telemetry='trace' in the config produces
    run_log.jsonl + trace.json in telemetry_dir with no driver."""
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
    )
    from photon_ml_tpu.estimators import GameEstimator
    from photon_ml_tpu.game.dataset import GameDataset
    from photon_ml_tpu.models.glm import TaskType

    rng = np.random.default_rng(11)
    n, d = 400, 13
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    train = GameDataset(labels=y, features={"global": x}, entity_ids={})
    cfg = TrainingConfig(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[CoordinateConfig(
            name="global", kind=CoordinateKind.FIXED_EFFECT,
            feature_shard="global",
            optimizer=OptimizerSettings(max_iters=10))],
        update_sequence=["global"],
        n_iterations=1,
        evaluators=[],
        telemetry="trace",
        telemetry_dir=str(tmp_path / "tel"),
        output_dir=str(tmp_path / "out"),
    )
    GameEstimator(cfg).fit(train)
    assert telemetry.active() is None     # session closed with fit
    tel_dir = tmp_path / "tel"
    assert (tel_dir / "trace.json").exists()
    events = read_run_log(str(tel_dir / "run_log.jsonl"))
    kinds = {e["event"] for e in events}
    assert {"telemetry_start", "telemetry_summary", "span",
            "trace_written"} <= kinds
    spans = [e for e in events if e["event"] == "span"]
    assert any(s["name"] == "estimator_fit" for s in spans)
    assert any(s["name"] == "cd_coordinate" for s in spans)


def test_e2e_streamed_swept_fit_trace_acceptance(tmp_path, capsys):
    """THE ISSUE-7 acceptance run, in miniature: an end-to-end streamed
    swept fit through the training driver with telemetry=trace yields
    run_log.jsonl + trace.json where the report CLI reconciles stage
    spans to >= 90% of measured wall clock and reports prefetcher
    overlap efficiency."""
    from photon_ml_tpu.cli import game_training_driver
    from photon_ml_tpu.io.libsvm import write_libsvm
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main
    from photon_ml_tpu.utils.synthetic import make_a1a_like

    rows, labels, _ = make_a1a_like(n=1200, seed=5)
    train_path = str(tmp_path / "a1a.libsvm")
    write_libsvm(train_path, rows, np.where(labels > 0, 1, -1))
    out_dir = str(tmp_path / "out")
    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [{
            "name": "global", "kind": "FIXED_EFFECT",
            "feature_shard": "features",
            "optimizer": {"optimizer": "LBFGS", "reg_weight": 1.0,
                          "max_iters": 12},
        }],
        "update_sequence": ["global"],
        "input_path": train_path,
        "validation_fraction": 0.2,
        "output_dir": out_dir,
        "evaluators": ["AUC"],
        "reg_weight_grid": {"global": [3.0, 1.0, 0.3]},
        "chunk_rows": 200,
        "spill_dir": str(tmp_path / "spill"),
        "host_max_resident": 2,
        "telemetry": "trace",
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    game_training_driver.main(["--config", cfg_path])
    assert telemetry.active() is None

    log_path = os.path.join(out_dir, "run_log.jsonl")
    assert os.path.exists(os.path.join(out_dir, "trace.json"))
    events = read_run_log(log_path)
    spans = [e for e in events if e["event"] == "span"]
    names = {s["name"] for s in spans}
    # Driver phases AND streaming-tier stages are on the timeline.
    assert {"fit", "sweep", "swept_train", "prefetch_load"} <= names

    rc = telemetry_main(["report", log_path])
    tail = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and tail["ok"] is True
    assert tail["reconciliation"] >= 0.9
    assert tail["overlap_efficiency"] is not None
    assert tail["counters"]["solver.sweeps"] > 0
    assert tail["counters"]["store.loads"] > 0


def test_runlogger_context_manager_and_thread_safety(tmp_path):
    path = str(tmp_path / "log.jsonl")
    with RunLogger(path) as log:
        log.event("hello", x=1)
        assert log._f is not None
    assert log._f is None                # context exit closed the file
    log.close()                          # idempotent (atexit fallback)
    events = read_run_log(path)
    assert events[0]["event"] == "hello"
    # Cross-thread event writes keep lines whole (the lock contract:
    # heartbeats arrive from pipeline threads).
    with RunLogger(path) as log:
        threads = [threading.Thread(
            target=lambda j=j: [log.event("t", j=j, i=i)
                                for i in range(50)])
            for j in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    events = read_run_log(path)          # every line parses
    assert len(events) == 200


def test_runlogger_atexit_flush_fallback(tmp_path):
    """An abandoned logger (no close) still lands its events at
    interpreter exit — the file handle no longer leaks buffered
    lines."""
    import subprocess
    import sys

    path = str(tmp_path / "leak.jsonl")
    code = (
        "from photon_ml_tpu.utils.run_log import RunLogger\n"
        f"log = RunLogger({path!r})\n"
        "log.event('abandoned', x=1)\n"
        "# no close(): the atexit fallback must flush+close\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    events = read_run_log(path)
    assert [e["event"] for e in events] == ["abandoned"]
