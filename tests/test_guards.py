"""Runtime guard budgets (ISSUE 6): the compile/transfer/leak contracts
on the streaming hot paths, enforced with ``photon_ml_tpu.analysis
.guards``.

The claims pinned here are the ones PR 2/3 established by construction
and nothing previously *checked*:

- a streaming swept L-BFGS fit compiles a FIXED program set -- the same
  whether the data is 4 resident chunks or 24 spilled chunks (chunk
  programs are shape-congruent, so chunk count and the disk tier add
  zero compiles), and bounded for any lane count;
- a warm re-fit (same shapes) compiles ZERO new programs;
- the fused streaming scorer's per-chunk program compiles once per
  model structure (asserted in test_scoring_stream.py);
- the per-chunk loop performs no implicit host transfers beyond the
  planned device_put/device_get (vacuous on the CPU backend -- host ==
  device -- but wired so accelerator runs inherit the contract);
- no tracer leaks out of a full streamed sweep.

Budget values are measured once and recorded in PERF.md (round 11);
the asserts leave headroom so routine jax-version drift in the eager
helper ops does not flake, while a per-iteration or per-chunk
recompile regression (tens to hundreds of events) still fails loudly.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.analysis.guards import (
    count_compiles,
    no_implicit_transfers,
    tracer_leak_guard,
)
from photon_ml_tpu.data.chunked_batch import build_chunked_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import (
    RegularizationContext,
    RegularizationType,
    SweptRegularization,
)
from photon_ml_tpu.optim.base import OptimizerConfig
from photon_ml_tpu.optim.streaming import (
    ChunkedGLMObjective,
    streaming_lbfgs_solve_swept,
)

# Measured 2026-08-03 (jax 0.4.37, CPU, cold process): a fresh-shape
# 3-lane swept streaming fit compiles 53 programs -- the 4 named solver
# programs (_jit_vg_swept, _jit_val_swept, _swept_direction,
# _swept_push) exactly ONCE each, plus 49 one-off eager helper ops
# (broadcast/multiply/convert/where/norm...) -- see PERF.md round 11.
# The budget is the contract: a per-iteration or per-chunk recompile
# would add O(iters)/O(chunks) events and blow straight through it
# (this fit runs 8 iterations x 3 trials x 4-24 chunks).
SWEEP_COMPILE_BUDGET = 60

# Unique shapes: the budget's ">= 1 fresh compile" leg must not depend
# on what earlier tests happened to compile.
D = 211
CHUNK_ROWS = 250
K = 6
LAMS = [3.0, 1.0, 0.3]


@pytest.fixture
def rng():
    return np.random.default_rng(31)


def _problem(rng, n):
    cols = np.stack([
        np.sort(rng.choice(D, K, replace=False)) for _ in range(n)
    ]).astype(np.int32)
    vals = rng.normal(0, 1, (n, K)).astype(np.float32)
    w_true = rng.normal(0, 0.8, D) * (rng.uniform(size=D) < 0.3)
    m = np.einsum("nk,nk->n", vals, w_true[cols])
    labels = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(
        np.float32)
    rows = SparseRows.from_flat(
        np.arange(n + 1, dtype=np.int64) * K,
        cols.reshape(-1).astype(np.int64), vals.reshape(-1))
    return rows, labels


def _objective():
    return GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(1.0),
        norm=NormalizationContext.identity(),
    )


def _chunked(rng, n_chunks, spill_dir=None):
    rows, labels = _problem(rng, CHUNK_ROWS * n_chunks)
    kw = {}
    if spill_dir is not None:
        kw = dict(spill_dir=spill_dir, host_max_resident=2)
    cb = build_chunked_batch(rows, D, labels, n_chunks=n_chunks,
                             layout="ell", **kw)
    return ChunkedGLMObjective(
        _objective(), cb,
        max_resident=0 if spill_dir is not None else n_chunks,
        prefetch_depth=2)


def _swept_fit(cobj, lams=LAMS, max_iters=8):
    reg = SweptRegularization.from_grid(RegularizationType.L2,
                                        list(lams))
    W0 = jnp.zeros((len(lams), D), jnp.float32)
    return streaming_lbfgs_solve_swept(
        lambda W: cobj.value_and_gradient_swept(W, reg),
        lambda W: cobj.value_swept(W, reg),
        W0, OptimizerConfig(max_iters=max_iters, tolerance=1e-8))


def test_count_compiles_counts_fresh_and_cached():
    """The primitive: a fresh shape compiles (named event), a cache hit
    compiles nothing."""
    f = jax.jit(lambda x: x * 2 + 1)
    x = jnp.ones(97)
    with count_compiles() as fresh:
        jax.block_until_ready(f(x))
    assert fresh.count >= 1
    assert any("lambda" in p or "<lambda>" in p for p in fresh.programs)
    with count_compiles() as warm:
        jax.block_until_ready(f(x))
    assert warm.count == 0, warm.programs


def test_sweep_compile_budget_and_chunk_count_invariance(rng, tmp_path):
    """THE acceptance budget: one swept streaming fit compiles <=
    SWEEP_COMPILE_BUDGET programs at a fresh shape; the same fit over
    6x the data (24 spilled chunks vs 4 resident -- different chunk
    count AND the disk/prefetch tier switched on) compiles ZERO new
    programs; a warm re-fit compiles ZERO new programs."""
    with count_compiles() as fresh:
        _swept_fit(_chunked(rng, n_chunks=4))
    assert 1 <= fresh.count <= SWEEP_COMPILE_BUDGET, fresh.programs

    with count_compiles() as more_chunks:
        _swept_fit(_chunked(rng, n_chunks=24,
                            spill_dir=str(tmp_path / "spill")))
    assert more_chunks.count == 0, more_chunks.programs

    with count_compiles() as warm:
        _swept_fit(_chunked(rng, n_chunks=4))
    assert warm.count == 0, warm.programs


def test_sweep_compile_budget_lane_count(rng):
    """A different lane count recompiles the [L, d]-shaped programs --
    but the total stays within the SAME fixed budget (no per-lane or
    per-iteration blowup)."""
    with count_compiles() as lanes:
        _swept_fit(_chunked(rng, n_chunks=4),
                   lams=[10.0, 3.0, 1.0, 0.3, 0.1])
    assert lanes.count <= SWEEP_COMPILE_BUDGET, lanes.programs


def test_chunk_loop_no_implicit_transfers(rng):
    """The per-chunk evaluation runs under jax.transfer_guard with only
    the planned explicit device_put/device_get transfers.  On the CPU
    backend the guard is structurally a no-op (host == device); on
    TPU/GPU this same scope turns any unplanned host sync in the
    dispatch path into a hard error."""
    cobj = _chunked(rng, n_chunks=4)
    w = jnp.zeros(D, jnp.float32)
    with no_implicit_transfers():
        f, g = cobj.value_and_gradient(w)
    assert np.isfinite(float(f))
    assert np.asarray(g).shape == (D,)


def test_streamed_sweep_leaks_no_tracers(rng):
    """jax.check_tracer_leaks over a full swept streamed fit: traced
    values escaping a chunk program (the classic closure leak) would
    raise here."""
    with tracer_leak_guard():
        res = _swept_fit(_chunked(rng, n_chunks=3), max_iters=3)
    assert np.all(np.isfinite(np.asarray(res.w)))


def test_tracer_leak_guard_catches_leak():
    leaked = []

    def f(x):
        leaked.append(x)
        return x * 2

    with pytest.raises(Exception):
        with tracer_leak_guard():
            jax.jit(f)(jnp.ones(13))


def test_telemetry_adds_no_compiles_on_or_off(rng, tmp_path):
    """ISSUE-7 budget: the telemetry tier must be free at the compile
    level.  A warm re-fit compiles ZERO new programs with telemetry
    OFF (the default everywhere above) AND with a live metrics session
    — the instrumentation is pure host bookkeeping, never a traced
    value or a new program."""
    from photon_ml_tpu import telemetry

    _swept_fit(_chunked(rng, n_chunks=4))        # warm the shapes
    with count_compiles() as off:
        _swept_fit(_chunked(rng, n_chunks=4))
    assert off.count == 0, off.programs

    t = telemetry.start("metrics")
    try:
        with count_compiles() as on:
            _swept_fit(_chunked(rng, n_chunks=4,
                                spill_dir=str(tmp_path / "spill")))
        summary = t.summary()
    finally:
        t.close()
    assert on.count == 0, on.programs
    # The session actually observed the fit (sweeps + prefetch).
    assert summary["counters"]["solver.sweeps"] > 0
    assert summary["counters"]["prefetch.chunks_consumed"] > 0
    assert summary["derived"]["overlap_efficiency"] is not None


def test_telemetry_off_keeps_prefetcher_blocking_path(rng):
    """With telemetry off the prefetcher consumer takes the plain
    blocking q.get() path — no polling wake-ups, no counters (the
    <=1% pass-time overhead contract's mechanism)."""
    from photon_ml_tpu import telemetry

    assert telemetry.active() is None
    cobj = _chunked(rng, n_chunks=4)
    w = jnp.zeros(D, jnp.float32)
    f, _ = cobj.value_and_gradient(w)
    assert np.isfinite(float(f))


def test_device_score_sparse_compiles_once(rng):
    """The ISSUE-6 true-positive fix pinned: _device_score_sparse used
    to construct ``jax.jit(gather_rowsum)`` per CALL (fresh executable
    cache -> recompile per scoring call, the photon-lint
    jit-in-function finding); the memoized module-level jit compiles
    once and every later call reuses it."""
    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.estimators.game_transformer import (
        _device_score_sparse,
    )

    n, k, d = 300, 4, 157
    cols = np.stack([np.sort(rng.choice(d, k, replace=False))
                     for _ in range(n)]).astype(np.int64)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    rows = SparseRows.from_flat(np.arange(n + 1, dtype=np.int64) * k,
                                cols.reshape(-1), vals.reshape(-1))
    w = rng.normal(size=d).astype(np.float32)
    with count_compiles() as cold:
        out1 = _device_score_sparse(rows, w)
    assert any("gather_rowsum" in p for p in cold.programs), \
        cold.programs
    with count_compiles() as warm:
        out2 = _device_score_sparse(rows, w)
    assert warm.count == 0, warm.programs
    np.testing.assert_allclose(out1, out2)
