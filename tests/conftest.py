"""Test substrate: single-process simulated 8-device mesh.

TPU analog of the reference's "Spark local[*] mode is the fake cluster"
strategy (SURVEY.md §4): force 8 virtual CPU devices so shard_map/pjit
tests exercise the real collective code paths without hardware.  Must run
before jax initializes its backends, hence env mutation at conftest import.
"""

import os

# The axon TPU plugin in this image pins JAX_PLATFORMS=axon and ignores env
# overrides; dropping the var and using config.update is what actually works.
os.environ.pop("JAX_PLATFORMS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
# x64 available for finite-difference reference math; production arrays are
# created float32 explicitly, so float32 code paths are still what's tested.
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
