"""End-to-end request tracing (ISSUE 14): trace context + header
contract, the tail-sampled ring buffer under concurrency, stage
attribution through the real serving path, Chrome flow-event export,
and the ``serve-report`` cross-process join.

The acceptance checks live here and in the bench: every request above
the tail threshold is retained (tail sampling is COMPLETE, not
probabilistic), the ring buffer stays bounded under sustained
concurrent load, the exported flow events are valid Chrome JSON whose
``s``/``f`` ids join across process ids, request ids ride EVERY
response (sheds included), and a warm traced server still compiles
nothing (guard-pinned).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.analysis.guards import count_compiles
from photon_ml_tpu.config import ServingConfig
from photon_ml_tpu.io import model_io
from photon_ml_tpu.serving import tracing
from photon_ml_tpu.serving.http import HttpEndpoint, HttpError
from photon_ml_tpu.serving.server import ModelServer
from photon_ml_tpu.telemetry import monitor as _mon
from photon_ml_tpu.telemetry.__main__ import main as telemetry_main
from photon_ml_tpu.telemetry.export import serve_trace_events
from photon_ml_tpu.telemetry.serve_report import (
    analyze,
    load_trace_files,
    run_serve_report,
)
from photon_ml_tpu.utils.run_log import RunLogger, read_run_log

from test_serving import TASK, _serve_cfg, _workload

pytestmark = pytest.mark.fast


@pytest.fixture(autouse=True)
def _no_leaked_sessions():
    """Tracing tests must leave every module-global session closed
    (the test_serving/test_monitor discipline), recorder included."""
    assert tracing.active() is None
    assert telemetry.active() is None and _mon.active() is None
    yield
    leaked = []
    if tracing.active() is not None:
        tracing.active().close()
        leaked.append("tracing")
    if _mon.active() is not None:
        _mon.active().close()
        leaked.append("monitor")
    if telemetry.active() is not None:
        telemetry.active().close()
        leaked.append("telemetry")
    assert not leaked, f"leaked sessions: {leaked}"


# ---------------------------------------------------------------------------
# trace context + header parsing
# ---------------------------------------------------------------------------


def test_trace_context_mint_parse_round_trip():
    ctx = tracing.mint()
    assert len(ctx.trace_id) == 20 and ctx.hop == 0
    assert tracing.mint().trace_id != ctx.trace_id     # unique
    # Per-process random prefix: two processes cannot collide.
    assert ctx.trace_id.startswith(tracing._MINT_PREFIX)
    back = tracing.parse_trace_header(ctx.header_value())
    assert back.trace_id == ctx.trace_id and back.hop == 0
    child = tracing.parse_trace_header(ctx.child_header())
    assert child.trace_id == ctx.trace_id and child.hop == 1


def test_trace_header_parsing_rejects_garbage():
    assert tracing.parse_trace_header(None) is None
    assert tracing.parse_trace_header("") is None
    assert tracing.parse_trace_header("bad id with spaces/1") is None
    assert tracing.parse_trace_header("x" * 100 + "/1") is None
    assert tracing.parse_trace_header("abc/notanint") is None
    # Bare id (no hop) is accepted at hop 0; negative hops clamp.
    assert tracing.parse_trace_header("abc123").hop == 0
    assert tracing.parse_trace_header("abc123/-4").hop == 0


def test_from_headers_adoption_order():
    ctx = tracing.from_headers({"X-Photon-Trace": "cafe01/2"})
    assert ctx.trace_id == "cafe01" and ctx.hop == 2
    # A bare client request id is adopted as the trace id.
    ctx = tracing.from_headers({"X-Photon-Request-Id": "client-7"})
    assert ctx.trace_id == "client-7" and ctx.hop == 0
    # Garbage in either header mints instead of echoing it back.
    ctx = tracing.from_headers({"X-Photon-Request-Id": "bad id!"})
    assert ctx.trace_id != "bad id!" and len(ctx.trace_id) == 20
    assert tracing.from_headers({}).hop == 0


def test_serving_config_trace_validation():
    cfg = ServingConfig(model_dir="m")
    cfg.validate()                    # tracing on by default
    assert cfg.trace == "on"
    for field, bad in (("trace", "maybe"), ("trace_threshold_ms", -1.0),
                       ("trace_sample_every", -1), ("trace_buffer", 0)):
        c = ServingConfig(model_dir="m", **{field: bad})
        with pytest.raises(ValueError):
            c.validate()


# ---------------------------------------------------------------------------
# recorder: tail sampling, floor, ring bounds, batch linking
# ---------------------------------------------------------------------------


def _finish_with_duration(rec, dur_s: float, stages: dict | None = None,
                          batch: int | None = None) -> None:
    """Drive one request through the recorder with a synthetic
    duration (t0 shifted back — no sleeps in tier-1)."""
    rt = rec.begin()
    tracing.take_attached()           # tests finish manually
    rt.t0 -= dur_s
    for k, v in (stages or {}).items():
        rt.stamp(k, v)
    rt.batch = batch
    rec.finish(rt, status=200)


def test_tail_sampling_keeps_every_slow_request(tmp_path):
    """COMPLETE tail capture: every request at/above the threshold is
    retained and exported as a request_trace event; fast requests are
    dropped (histograms aside)."""
    log = RunLogger(str(tmp_path / "log.jsonl"))
    rec = tracing.TraceRecorder(threshold_s=0.010, sample_every=0,
                                cap=64, run_logger=log)
    for i in range(40):
        _finish_with_duration(rec, 0.050 if i % 2 else 0.001)
    rec.close()
    log.close()
    events = read_run_log(str(tmp_path / "log.jsonl"))
    traces = [e for e in events if e["event"] == "request_trace"]
    assert len(traces) == 20                     # every slow one
    assert all(t["sampled"] == "tail" for t in traces)
    assert all(t["total_ms"] >= 10.0 for t in traces)
    summary = [e for e in events
               if e["event"] == "serve_trace_summary"][0]
    assert summary["requests"] == 40
    assert summary["sampled_tail"] == 20


def test_floor_sampling_is_deterministic(tmp_path):
    """With an unreachable threshold the 1-in-N floor still samples —
    deterministically (no RNG in the telemetry path)."""
    log = RunLogger(str(tmp_path / "log.jsonl"))
    rec = tracing.TraceRecorder(threshold_s=10.0, sample_every=10,
                                cap=64, run_logger=log)
    for _ in range(35):
        _finish_with_duration(rec, 0.001)
    snap = rec.snapshot()
    rec.close()
    log.close()
    assert snap["sampled_floor"] == 4            # seq 0, 10, 20, 30
    traces = [e for e in read_run_log(str(tmp_path / "log.jsonl"))
              if e["event"] == "request_trace"]
    assert len(traces) == 4
    assert all(t["sampled"] == "floor" for t in traces)


def test_ring_bounded_under_concurrent_load(tmp_path):
    """8 threads x 100 all-tail requests: the in-memory ring stays at
    its cap, the pending-batch window stays bounded, and EVERY request
    still reached the JSONL export (bounded memory, complete tail)."""
    log = RunLogger(str(tmp_path / "log.jsonl"))
    rec = tracing.TraceRecorder(threshold_s=0.0, sample_every=0,
                                cap=32, run_logger=log)

    def worker(seed: int) -> None:
        for j in range(100):
            bt = rec.begin_batch(bucket=8, rows=4, requests=1)
            bt.stamp("dispatch", 0.002)
            rec.finish_batch(bt)
            _finish_with_duration(rec, 0.005,
                                  stages={"queue_wait": 0.001},
                                  batch=bt.batch_id)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = rec.snapshot()
    assert snap["requests"] == 800
    assert snap["sampled_tail"] == 800
    assert snap["buffered"] <= 32                # ring bounded
    assert len(rec._pending) <= tracing._PENDING_BATCH_CAP
    rec.close()
    log.close()
    events = read_run_log(str(tmp_path / "log.jsonl"))
    traces = [e for e in events if e["event"] == "request_trace"]
    assert len(traces) == 800                    # none lost
    assert len({t["trace"] for t in traces}) == 800


def test_batch_ids_unique_across_recorder_incarnations(tmp_path):
    """Review finding (round 19): a restarted replica appends to the
    SAME log with a fresh recorder whose sequence restarts — bare
    integer batch ids would collide across the stitched segments and
    serve-report would join a pre-kill tail request to a post-restart
    batch's stages.  The per-recorder random prefix makes them
    disjoint, and the attribution picks the RIGHT batch."""
    log_path = tmp_path / "replica.jsonl"
    ids = []
    for incarnation in range(2):
        log = RunLogger(str(log_path),
                        mode=("w" if incarnation == 0 else "a"),
                        header=True)
        rec = tracing.TraceRecorder(threshold_s=0.0, sample_every=0,
                                    cap=16, run_logger=log)
        bt = rec.begin_batch(bucket=8, rows=4, requests=1)
        bt.stamp("dispatch", 0.001 * (incarnation + 1))
        rec.finish_batch(bt)
        ids.append(bt.batch_id)
        _finish_with_duration(rec, 0.020, batch=bt.batch_id)
        rec.close()
        log.close()
    assert ids[0] != ids[1]              # no cross-segment collision
    result = analyze(load_trace_files([str(log_path)]))
    # Each tail request joined ITS OWN batch: the two dispatch stamps
    # (1ms and 2ms) both appear, not one batch claimed twice.
    assert result["stages"]["dispatch"]["count"] == 2
    assert result["tail_requests"] == 2


def test_batch_registered_before_members_can_finish():
    """Review finding (round 19): the dispatcher must register the
    completed batch BEFORE waking member slots — a member's finish()
    races it otherwise and the shared span is silently dropped.  Drive
    the real batcher and assert every retained request's batch was
    emitted exactly once per batch."""
    from test_serving import _FakeEngine

    from photon_ml_tpu.serving.batcher import MicroBatcher

    rec = tracing.start(threshold_s=0.0, sample_every=0, cap=64)
    batcher = None
    try:
        engine = _FakeEngine()
        batcher = MicroBatcher(lambda: engine, [4, 8],
                               deadline_s=0.001)
        rts = []
        for _ in range(6):
            rt = rec.begin()
            tracing.take_attached()
            batcher.submit([1.0, 2.0], trace=rt)
            rec.finish(rt, status=200)
            rts.append(rt)
        assert all(rt.batch is not None for rt in rts)
        with rec._lock:
            emitted = {bt.batch_id for bt in rec._batch_ring}
        # Every request's linked batch made it to the retained set —
        # none lost to the registration race.
        assert {rt.batch for rt in rts} <= emitted
    finally:
        if batcher is not None:
            batcher.close()
        rec.close()


def test_batch_trace_emitted_once_for_shared_batch(tmp_path):
    """The shared micro-batch span is recorded ONCE however many
    member requests are retained — members link it by batch id."""
    log = RunLogger(str(tmp_path / "log.jsonl"))
    rec = tracing.TraceRecorder(threshold_s=0.0, sample_every=0,
                                cap=16, run_logger=log)
    bt = rec.begin_batch(bucket=8, rows=6, requests=3)
    bt.stamp("assemble", 0.001)
    bt.stamp("dispatch", 0.004)
    rec.finish_batch(bt)
    for _ in range(3):
        _finish_with_duration(rec, 0.020, batch=bt.batch_id)
    rec.close()
    log.close()
    events = read_run_log(str(tmp_path / "log.jsonl"))
    batches = [e for e in events if e["event"] == "batch_trace"]
    traces = [e for e in events if e["event"] == "request_trace"]
    assert len(batches) == 1                     # once, not per member
    assert len(traces) == 3
    assert all(t["batch"] == bt.batch_id for t in traces)
    assert batches[0]["requests"] == 3
    assert batches[0]["stages_ms"]["dispatch"] == pytest.approx(4.0)


def test_stage_histograms_fold_for_dropped_requests(tmp_path):
    """Requests below the threshold are dropped from the ring but
    still fold into the serve.stage.* histograms — /metrics sees the
    full stream, not the tail."""
    tel = telemetry.start("metrics")
    try:
        rec = tracing.TraceRecorder(threshold_s=10.0, sample_every=0,
                                    cap=8)
        for _ in range(12):
            _finish_with_duration(rec, 0.001,
                                  stages={"queue_wait": 0.002,
                                          "serialize": 0.0005})
        rec.close()
        assert rec.snapshot()["sampled_tail"] == 0
        summary = tracing.stage_summary()
        assert summary["queue_wait"]["count"] == 12
        assert summary["queue_wait"]["p50_ms"] == pytest.approx(
            2.0, rel=0.01)
        dom = tracing.dominant_stage(summary)
        assert dom[0] == "queue_wait"
        # No per-request counter churn (the p50 budget): the
        # recorder's own tally is the request count of record.
        assert tel.counter("serve.trace.requests") == 0
        assert rec.snapshot()["requests"] == 12
    finally:
        tel.close()


# ---------------------------------------------------------------------------
# HTTP core: request-id echo + context adoption
# ---------------------------------------------------------------------------


def _raw_get(port: int, path: str, headers: dict | None = None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, dict(r.headers), r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode()


def test_request_id_echoed_on_every_response():
    """ISSUE 14 satellite: EVERY response — 200, 404, HttpError sheds,
    even /healthz — carries X-Photon-Request-Id (a shed is no longer
    anonymous)."""
    def shed(body):
        raise HttpError(503, headers={"Retry-After": "1"},
                        error="overloaded")

    ep = HttpEndpoint({("GET", "/ok"):
                       (lambda b: (200, "ok", "text/plain")),
                       ("GET", "/shed"): shed})
    ep.start()
    try:
        for path, want_code in (("/ok", 200), ("/shed", 503),
                                ("/nope", 404), ("/healthz", 200)):
            code, headers, _ = _raw_get(ep.port, path)
            assert code == want_code
            rid = headers.get("X-Photon-Request-Id")
            assert rid, f"no request id on {path}"
            assert headers.get("X-Photon-Trace", "").startswith(rid)
        # The shed keeps its own headers too.
        _, headers, _ = _raw_get(ep.port, "/shed")
        assert headers.get("Retry-After") == "1"
    finally:
        ep.close()


def test_client_trace_context_adopted_and_visible_to_routes():
    """A client-sent X-Photon-Trace is adopted (echoed back, hop
    preserved) and visible to the route via tracing.context()."""
    seen: list = []

    def probe(body):
        ctx = tracing.context()
        seen.append((ctx.trace_id, ctx.hop))
        return 200, "ok", "text/plain"

    ep = HttpEndpoint({("GET", "/probe"): probe})
    ep.start()
    try:
        _, headers, _ = _raw_get(
            ep.port, "/probe",
            headers={"X-Photon-Trace": "feedface01/3"})
        assert headers["X-Photon-Request-Id"] == "feedface01"
        assert headers["X-Photon-Trace"] == "feedface01/3"
        assert seen == [("feedface01", 3)]
        # A bare client request id is adopted as the trace id.
        _, headers, _ = _raw_get(
            ep.port, "/probe",
            headers={"X-Photon-Request-Id": "my-req-1"})
        assert headers["X-Photon-Request-Id"] == "my-req-1"
    finally:
        ep.close()


# ---------------------------------------------------------------------------
# flow-event export
# ---------------------------------------------------------------------------


def _request_rec(trace, role, wall_t, total_ms, stages=None,
                 batch=None, **extra):
    return {"event": "request_trace", "trace": trace, "hop": 0,
            "role": role, "wall_t": wall_t, "total_ms": total_ms,
            "stages_ms": stages or {}, "sampled": "tail",
            **({"batch": batch} if batch is not None else {}), **extra}


def _batch_rec(batch, wall_t, total_ms, stages=None):
    return {"event": "batch_trace", "batch": batch, "wall_t": wall_t,
            "total_ms": total_ms, "bucket": 8, "rows": 4,
            "requests": 2, "stages_ms": stages or {}}


def _processes():
    """Frontend + one replica sharing two trace ids and one batch."""
    frontend = {
        "name": "frontend", "requests": [
            _request_rec("t1", "frontend", 100.000, 80.0,
                         {"route": 1.0, "forward": 70.0}),
            _request_rec("t2", "frontend", 100.010, 60.0,
                         {"route": 0.5, "retry": 20.0,
                          "forward": 30.0},
                         attempts=[{"replica": 0, "ms": 20.0,
                                    "outcome": "connect_fail:OSError"},
                                   {"replica": 1, "ms": 30.0,
                                    "outcome": 200}]),
        ], "batches": []}
    replica = {
        "name": "replica_0", "requests": [
            _request_rec("t1", "replica", 100.002, 70.0,
                         {"admission": 1.0, "queue_wait": 40.0,
                          "serialize": 0.5, "write": 1.0}, batch=7),
            _request_rec("t2", "replica", 100.032, 28.0,
                         {"admission": 0.5, "queue_wait": 5.0,
                          "serialize": 0.4, "write": 0.8}, batch=7),
        ], "batches": [
            _batch_rec(7, 100.045, 12.0,
                       {"assemble": 1.0, "store_lookup": 2.0,
                        "dispatch": 6.0, "d2h": 3.0}),
        ]}
    return [frontend, replica]


def test_flow_export_valid_chrome_json_with_cross_process_joins(
        tmp_path):
    """The exported events are valid Chrome trace JSON; every flow
    start (ph s) has a matching finish (ph f) under the same id, and
    the request flow crosses PROCESS boundaries (frontend pid →
    replica pid)."""
    events = serve_trace_events(_processes())
    doc = json.loads(json.dumps({"traceEvents": events,
                                 "displayTimeUnit": "ms"}))
    assert doc["traceEvents"]
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "M", "s", "f")
        assert "pid" in ev and "tid" in ev
        if ev["ph"] in ("X", "s", "f"):
            assert "ts" in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 1
    starts = {e["id"]: e for e in doc["traceEvents"]
              if e["ph"] == "s"}
    finishes = {e["id"]: e for e in doc["traceEvents"]
                if e["ph"] == "f"}
    assert set(starts) == set(finishes)
    # Request flows join ACROSS pids; batch flows join across tids.
    for trace in ("t1", "t2"):
        assert starts[trace]["pid"] != finishes[trace]["pid"]
        assert finishes[f"{trace}:b7"]["tid"] == 2
    # Binding contract: every flow event's ts coincides with a slice
    # that encloses it on the same pid/tid (Perfetto binds s/f events
    # to enclosing slices).
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    for fl in list(starts.values()) + list(finishes.values()):
        assert any(s["pid"] == fl["pid"] and s["tid"] == fl["tid"]
                   and s["ts"] <= fl["ts"] <= s["ts"] + s["dur"]
                   for s in slices)


# ---------------------------------------------------------------------------
# serve-report
# ---------------------------------------------------------------------------


def _write_log(path, records):
    log = RunLogger(str(path))
    for rec in records:
        kind = rec.pop("event")
        log.event(kind, **rec)
    log.close()


def test_serve_report_joins_and_attributes(tmp_path):
    """The cross-process join: 100% of replica tail records match a
    frontend trace; queue_wait dominates t1 (per-request wait), the
    retry cost is surfaced for t2; ok=True, rc 0."""
    procs = _processes()
    _write_log(tmp_path / "frontend.jsonl",
               [dict(r) for r in procs[0]["requests"]])
    _write_log(tmp_path / "replica.jsonl",
               [dict(r) for r in procs[1]["requests"]]
               + [dict(b) for b in procs[1]["batches"]])
    out_path = tmp_path / "flow.json"
    result = run_serve_report(
        [str(tmp_path / "frontend.jsonl"),
         str(tmp_path / "replica.jsonl")],
        trace_out=str(out_path))
    assert result["ok"] is True
    assert result["join_fraction"] == 1.0
    assert result["tail_requests"] == 2
    assert result["retried_requests"] == 1
    assert result["retry_cost_ms"]["total"] == pytest.approx(20.0)
    assert result["stages"]["queue_wait"]["count"] == 2
    assert result["stages"]["retry"]["count"] == 1
    # t1's tail is queue-wait dominated (40ms of an 80ms request).
    t1 = next(r for r in result["slowest"] if r["trace"] == "t1")
    assert t1["dominant"] == "queue_wait"
    # The retried request's attribution includes the retry cost.
    t2 = next(r for r in result["slowest"] if r["trace"] == "t2")
    assert t2["retry_ms"] == pytest.approx(20.0)
    assert json.load(open(out_path))["traceEvents"]


def test_serve_report_fails_when_join_breaks(tmp_path):
    """Replica tail traces with NO frontend match (propagation broke)
    fail the join check: ok False, CLI rc 1."""
    procs = _processes()
    # Frontend logs different trace ids than the replica's.
    fe = [dict(r, trace=f"other-{i}")
          for i, r in enumerate(procs[0]["requests"])]
    _write_log(tmp_path / "frontend.jsonl", fe)
    _write_log(tmp_path / "replica.jsonl",
               [dict(r) for r in procs[1]["requests"]])
    rc = telemetry_main(["serve-report",
                         str(tmp_path / "frontend.jsonl"),
                         str(tmp_path / "replica.jsonl")])
    assert rc == 1
    # And the pure analyzer agrees.
    result = analyze(load_trace_files(
        [str(tmp_path / "frontend.jsonl"),
         str(tmp_path / "replica.jsonl")]))
    assert result["ok"] is False and result["join_fraction"] == 0.0


def test_serve_report_single_log_mode(tmp_path):
    """One server's log (no frontend records): stage table + tail
    attribution still render, the join check is N/A, rc 0."""
    procs = _processes()
    _write_log(tmp_path / "replica.jsonl",
               [dict(r) for r in procs[1]["requests"]]
               + [dict(b) for b in procs[1]["batches"]])
    rc = telemetry_main(["serve-report",
                         str(tmp_path / "replica.jsonl")])
    assert rc == 0
    result = analyze(load_trace_files([str(tmp_path / "replica.jsonl")]))
    assert result["join_fraction"] is None and result["ok"] is True
    assert result["dominant_stage"] == "queue_wait"


def test_serve_report_empty_logs_fail(tmp_path):
    """No trace records at all (tracing off / wrong file) is rc 1 —
    a forensic tool must not report green on nothing."""
    _write_log(tmp_path / "empty.jsonl", [])
    rc = telemetry_main(["serve-report", str(tmp_path / "empty.jsonl")])
    assert rc == 1


# ---------------------------------------------------------------------------
# end to end through the real server
# ---------------------------------------------------------------------------


def _post_rows(port, rows, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/score",
        data=json.dumps({"rows": rows}).encode(),
        headers={"Content-Type": "application/json",
                 **(headers or {})})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read()), dict(r.headers)


def test_server_traces_real_requests_end_to_end(tmp_path):
    """Real server, threshold 0 (everything tails): request_trace +
    batch_trace land in the run log with every replica stage, the
    /status stages table materializes, serve-report attributes each
    request, and a client-supplied trace id joins its record."""
    from photon_ml_tpu.serving.engine import dataset_rows

    model, dataset = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    cfg = _serve_cfg(mdir, tmp_path, telemetry="metrics",
                     monitor="off", trace_threshold_ms=0.0)
    srv = ModelServer(cfg, run_logger=log).start()
    try:
        reqs = dataset_rows(dataset, 0, 4)
        _out, headers = _post_rows(
            srv.port, reqs,
            headers={"X-Photon-Trace": "cafebabe12345678/1"})
        assert headers["X-Photon-Request-Id"] == "cafebabe12345678"
        for _ in range(3):
            _post_rows(srv.port, reqs)
        st, _ = _post_rows(srv.port, reqs[:1])
        import urllib.request as _ur

        with _ur.urlopen(f"http://127.0.0.1:{srv.port}/status",
                         timeout=10) as r:
            status = json.loads(r.read())["serving"]
        assert status["tracing"]["requests"] == 5
        assert status["tracing"]["sampled_tail"] == 5
        for stage in ("admission", "queue_wait", "assemble",
                      "store_lookup", "dispatch", "d2h", "serialize",
                      "write"):
            assert stage in status["stages"], stage
    finally:
        srv.stop()
        log.close()
    events = read_run_log(log_path)
    traces = [e for e in events if e["event"] == "request_trace"]
    batches = [e for e in events if e["event"] == "batch_trace"]
    assert len(traces) == 5 and batches
    adopted = [t for t in traces if t["trace"] == "cafebabe12345678"]
    assert len(adopted) == 1 and adopted[0]["hop"] == 1
    assert all(t["role"] == "replica" for t in traces)
    assert all("batch" in t for t in traces)   # every request linked
    result = analyze(load_trace_files([log_path]))
    assert result["ok"] and result["tail_requests"] == 5
    assert result["dominant_stage"] is not None


def test_server_zero_compiles_with_tracing_on(tmp_path):
    """The guard pin: a warm traced server compiles NOTHING in steady
    state — tracing must never perturb the jit cache."""
    from photon_ml_tpu.serving.engine import dataset_rows

    model, dataset = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    cfg = _serve_cfg(mdir, tmp_path, telemetry="off", monitor="off",
                     trace_threshold_ms=0.0)
    srv = ModelServer(cfg).start()
    try:
        reqs = dataset_rows(dataset, 0, 6)
        _post_rows(srv.port, reqs)          # shapes warm
        with count_compiles() as compiles:
            for _ in range(4):
                _post_rows(srv.port, reqs)
        assert compiles.count == 0
        assert tracing.active().snapshot()["requests"] >= 5
    finally:
        srv.stop()


def test_server_trace_off_takes_no_timestamps(tmp_path):
    """trace='off' is the pre-ISSUE-14 path: no recorder, no
    request_trace events, no stages block — the A/B baseline."""
    model, dataset = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    cfg = _serve_cfg(mdir, tmp_path, telemetry="metrics",
                     monitor="off", trace="off")
    srv = ModelServer(cfg, run_logger=log).start()
    try:
        from photon_ml_tpu.serving.engine import dataset_rows

        assert tracing.active() is None
        _post_rows(srv.port, dataset_rows(dataset, 0, 4))
        import urllib.request as _ur

        with _ur.urlopen(f"http://127.0.0.1:{srv.port}/status",
                         timeout=10) as r:
            status = json.loads(r.read())["serving"]
        assert "tracing" not in status
        assert "stages" not in status
    finally:
        srv.stop()
        log.close()
    assert not [e for e in read_run_log(log_path)
                if e["event"] == "request_trace"]


def test_shed_response_carries_request_id_and_trace(tmp_path):
    """ISSUE 14 satellite through the real server: a 503 shed (server
    warming) is no longer anonymous — the client can correlate its
    failure by request id."""
    model, _ = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    srv = ModelServer(_serve_cfg(mdir, tmp_path, telemetry="off",
                                 monitor="off"))
    try:
        # NOT started: /v1/score sheds 503 "warming".
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/score",
            data=json.dumps({"rows": [{}]}).encode(),
            headers={"X-Photon-Request-Id": "shed-corr-1"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 503
        assert err.value.headers["X-Photon-Request-Id"] == "shed-corr-1"
    finally:
        srv.stop()
