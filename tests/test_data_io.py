"""io/ + statistics tests: LIBSVM round-trip, stats vs numpy (SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.batch import make_dense_batch, make_sparse_batch
from photon_ml_tpu.data.normalization import (
    NormalizationType,
    compute_normalization,
)
from photon_ml_tpu.data.statistics import compute_statistics
from photon_ml_tpu.io import read_libsvm, write_libsvm

pytestmark = pytest.mark.fast


def _random_sparse_rows(rng, n, d, nnz):
    rows = []
    for _ in range(n):
        k = rng.integers(1, nnz + 1)
        cols = rng.choice(d, size=k, replace=False).astype(np.int32)
        vals = rng.normal(0, 1, k).astype(np.float32)
        rows.append((np.sort(cols), vals[np.argsort(cols)]))
    return rows


def test_libsvm_round_trip(rng, tmp_path):
    n, d = 50, 30
    rows = _random_sparse_rows(rng, n, d, 8)
    labels = rng.choice([-1.0, 1.0], size=n)
    path = str(tmp_path / "data.libsvm")
    write_libsvm(path, rows, labels)
    rows2, y2, dim2 = read_libsvm(path, n_features=d)
    assert dim2 == d
    np.testing.assert_array_equal(y2, (labels + 1) / 2)  # {-1,1} → {0,1}
    for (c1, v1), (c2, v2) in zip(rows, rows2):
        np.testing.assert_array_equal(c1, c2)
        np.testing.assert_allclose(v1, v2, rtol=1e-5)


def test_libsvm_sums_duplicate_indices(tmp_path):
    path = str(tmp_path / "dup.libsvm")
    with open(path, "w") as f:
        f.write("1 3:1.5 3:2.5 7:1.0\n")
    rows, y, dim = read_libsvm(path)
    c, v = rows[0]
    np.testing.assert_array_equal(c, [2, 6])
    np.testing.assert_allclose(v, [4.0, 1.0])


def test_statistics_dense_vs_numpy(rng):
    n, d = 120, 9
    x = rng.normal(1.0, 2.0, (n, d))
    x[x < 0.5] = 0.0  # some sparsity for nnz counting
    batch = make_dense_batch(x, np.zeros(n), pad_to=150)
    stats = compute_statistics(batch)
    assert float(stats.count) == n
    np.testing.assert_allclose(stats.mean, x.mean(0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(stats.variance, x.var(0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(stats.min, x.min(0), rtol=1e-5)
    np.testing.assert_allclose(stats.max, x.max(0), rtol=1e-5)
    np.testing.assert_allclose(
        stats.max_abs, np.abs(x).max(0), rtol=1e-5
    )
    np.testing.assert_allclose(stats.num_nonzeros, (x != 0).sum(0))


def test_statistics_sparse_matches_dense(rng):
    n, d = 80, 20
    rows = _random_sparse_rows(rng, n, d, 6)
    labels = np.zeros(n)
    sp = make_sparse_batch(rows, d, labels, pad_to=100)
    dense_x = np.zeros((n, d), np.float32)
    for i, (c, v) in enumerate(rows):
        dense_x[i, c] = v
    de = make_dense_batch(dense_x, labels, pad_to=100)
    s_sp = compute_statistics(sp)
    s_de = compute_statistics(de)
    for field in ("mean", "variance", "min", "max", "max_abs", "num_nonzeros"):
        np.testing.assert_allclose(
            getattr(s_sp, field), getattr(s_de, field), rtol=1e-4, atol=1e-5,
            err_msg=field,
        )


def test_stats_feed_normalization(rng):
    n, d = 60, 5
    x = rng.normal(3.0, 1.5, (n, d))
    batch = make_dense_batch(x, np.zeros(n))
    stats = compute_statistics(batch)
    norm = compute_normalization(
        stats.mean, stats.std, stats.max_abs,
        NormalizationType.STANDARDIZATION,
    )
    np.testing.assert_allclose(norm.factors, 1.0 / x.std(0), rtol=1e-4)
    np.testing.assert_allclose(norm.shifts, x.mean(0), rtol=1e-5)


def test_libsvm_chunked_matches_whole_file(rng, tmp_path):
    from photon_ml_tpu.io import read_libsvm_chunked

    n, d = 120, 40
    rows = _random_sparse_rows(rng, n, d, 6)
    labels = rng.choice([-1.0, 1.0], size=n)
    path = str(tmp_path / "data.libsvm")
    write_libsvm(path, rows, labels)
    whole, y_w, dim_w = read_libsvm(path, n_features=d)
    # Tiny windows force many chunk boundaries mid-file.
    chunked, y_c, dim_c = read_libsvm_chunked(path, n_features=d,
                                              chunk_bytes=256)
    assert dim_c == dim_w
    np.testing.assert_array_equal(y_c, y_w)
    assert len(chunked) == len(whole)
    for (c1, v1), (c2, v2) in zip(whole, chunked):
        np.testing.assert_array_equal(c1, c2)
        np.testing.assert_allclose(v1, v2, rtol=1e-6)


def test_jsonl_chunks_round_trip(tmp_path):
    import json

    from photon_ml_tpu.io import iter_jsonl_chunks

    path = str(tmp_path / "r.jsonl")
    recs = [{"label": i, "features": {}} for i in range(25)]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    got = []
    sizes = []
    for batch in iter_jsonl_chunks(path, chunk_records=10):
        sizes.append(len(batch))
        got.extend(batch)
    assert sizes == [10, 10, 5]
    assert got == recs
