"""Bench budget contract: the driver's capture must never again be
``rc: 124 / parsed: null`` (round-5 verdict).  These run the REAL
bench.py as a subprocess on a tiny CPU shape, so a bench that outgrows
its budget or breaks its JSON contract fails here — in the fast tier —
instead of in the driver.
"""

import json
import os
import subprocess
import sys

import pytest

_BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")
_TINY = ["--n", "4096", "--d", "2048", "--k", "4"]


def _run_bench(tmp_path, *args, timeout=300):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--cache-dir", str(tmp_path / "cache"),
         *args],
        capture_output=True, text=True, timeout=timeout, env=env,
    )
    return proc


@pytest.mark.fast
def test_bench_etl_section_budgeted_json(tmp_path):
    """`bench.py --section etl --budget-s 60` on a tiny shape: rc=0 and
    the last stdout line parses as JSON with the ETL record."""
    proc = _run_bench(tmp_path, "--section", "etl",
                      "--budget-s", "60", *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert lines, f"no stdout; stderr: {proc.stderr[-2000:]}"
    rec = json.loads(lines[-1])
    assert rec["section"] == "etl"
    assert rec["etl_grr_s"] is not None
    assert "etl_phases" in rec
    assert rec.get("errors") is None
    assert rec["sections_skipped"] == []


def test_bench_cached_section_records_warm_vs_cold(tmp_path):
    """etl + cached in one run: the cached section records the warm
    load, the cold reference, the speedup ratio, and plan parity."""
    proc = _run_bench(tmp_path, "--section", "etl,cached",
                      "--budget-s", "120", *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    cached = rec["cached"]
    assert cached["etl_warm_s"] is not None
    assert cached["etl_cold_s"] == rec["etl_grr_s"]
    assert cached["warm_speedup"] is not None
    assert cached["parity_ok"] is True


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_bench_sweep_section_contract(tmp_path):
    """`--section sweep` keeps the budget/JSON-last-line contract and
    records the batched-vs-sequential λ-sweep measurement: wall times,
    speedup, coefficient parity, and the phase breakdown showing the
    data-pass amortization (passes per grid step: ~L·x → ~2)."""
    proc = _run_bench(tmp_path, "--section", "sweep",
                      "--budget-s", "240", *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert rec["section"] == "sweep"
    assert rec.get("errors") is None
    sweep = rec["sweep"]
    assert sweep["lanes"] >= 4
    assert sweep["batched_s"] > 0 and sweep["sequential_s"] > 0
    assert sweep["speedup"] is not None
    assert sweep["parity_max_dw"] < 1e-3
    ph = sweep["phases"]
    # The tentpole invariant: one shared chunk stream feeds all lanes,
    # so the batched grid pays a small constant number of passes per
    # grid step while sequential pays ~L of them.
    assert ph["batched"]["data_passes"] < ph["sequential"]["data_passes"]
    assert ph["batched"]["passes_per_grid_step"] <= 3.0
    assert sweep["pass_amortization"] >= 2.0


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_bench_stream_section_contract(tmp_path):
    """`--section stream` keeps the budget/JSON-last-line contract and
    records the out-of-core measurement: per-arm wall-clock and peak
    host RSS (each arm in its own subprocess), the LRU window bound,
    gradient parity across arms, and the per-section peak_rss_mb
    trajectory satellite."""
    proc = _run_bench(tmp_path, "--section", "stream",
                      "--budget-s", "240", "--guards", *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert rec["section"] == "stream"
    assert rec.get("errors") is None
    s = rec["stream"]
    assert s["host_max_resident"] == 2
    # --guards (ISSUE 6): the timed sweeps ran under the runtime guard
    # harness and the steady state compiled NOTHING (everything was
    # compiled in the warmup; a per-sweep retrace would count here).
    for arm in ("spilled", "resident"):
        assert s[arm]["guards"]["sweep_compiles"] == 0, \
            s[arm]["guards"]
    # ISSUE 7: each arm's record carries the telemetry summary block;
    # the spilled arm streams through the prefetcher, so the overlap
    # derivation is defined and the pinned counters are live.
    for arm in ("spilled", "resident"):
        assert "telemetry" in s[arm], sorted(s[arm])
    # ISSUE 10: monitoring OFF stays the default — no monitor session
    # (no `progress` block in the arm record, no status thread probe)
    # and zero `progress` events counted over the timed sweeps.
    assert s["monitor"] is False
    for arm in ("spilled", "resident"):
        assert "progress" not in s[arm], sorted(s[arm])
        assert "status_ok" not in s[arm], sorted(s[arm])
        assert s[arm]["telemetry"]["progress_events"] == 0
        assert s[arm]["telemetry"]["alerts"] == 0
    tel = s["spilled"]["telemetry"]
    assert tel["sweeps"] == s["sweeps_timed"]
    assert tel["overlap_efficiency"] is not None
    assert 0.0 <= tel["overlap_efficiency"] <= 1.0
    assert tel["consumer_wait_s"] >= 0.0
    assert tel["store_loads"] + tel["store_hits"] > 0
    # Steady-state sweeps under telemetry still compile nothing (the
    # guard budget and the bridge agree) — including across the ISSUE-8
    # device-cost capture, whose AOT relower must not register.
    assert tel["compiles"] == 0, tel
    # ISSUE 8 acceptance: each arm's JSON carries a device_cost block
    # (FLOPs, bytes accessed, roofline estimate) for the per-chunk
    # value+gradient program.
    for arm in ("spilled", "resident"):
        cost = s[arm]["device_cost"]
        assert cost["flops"] > 0
        assert cost["bytes_accessed"] > 0
        assert cost["roofline_est_ms"] > 0
    # Chunks must dwarf the window (the RSS-bound claim's precondition)
    assert s["n_chunks"] >= 6 * s["host_max_resident"]
    # LRU bound held during the spilled arm's sweeps.
    assert 1 <= s["spilled"]["peak_live_chunks"] <= 2
    assert s["spilled"]["disk_loads"] > 0
    for arm in ("spilled", "resident"):
        assert s[arm]["pass_ms"] > 0
        assert s[arm]["peak_rss_mb"] > 0
    assert s["grad_parity_max"] < 1e-3
    assert s["pass_time_ratio"] is not None
    # Satellite: every section records the RSS high-water trajectory.
    assert rec["peak_rss_mb"]["stream"] > 0


@pytest.mark.fast
def test_bench_stream_arm_monitor_contract(tmp_path):
    """A monitoring-ON stream arm (ISSUE 10): one `--stream-arm
    spilled --monitor --guards` subprocess embeds a `progress` block
    (stage snapshots from the live monitor), proves its ephemeral
    /status endpoint answered from inside the measured process, and
    STILL compiles nothing over the timed sweeps — the monitor never
    touches jax."""
    proc = _run_bench(tmp_path, "--stream-arm", "spilled",
                      "--monitor", "--guards", *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert rec["arm"] == "spilled"
    prog = rec["progress"]
    # The chunk loop reported: the sweep stage has done == total and a
    # rolling rate, and at least one snapshot event was emitted.
    assert prog["snapshots"] >= 1
    sweep = prog["stages"]["train.sweep"]
    assert sweep["done"] == sweep["total"] > 0
    assert sweep["unit"] == "chunks"
    # The status endpoint answered a live GET /status with stages.
    assert rec["status_ok"] is True
    # Monitoring must not break the steady-state compile contract.
    assert rec["guards"]["sweep_compiles"] == 0, rec["guards"]
    # The registry counted exactly the emitted snapshots.
    assert rec["telemetry"]["progress_events"] == prog["snapshots"]


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_bench_score_section_contract(tmp_path):
    """`--section score` keeps the budget/JSON-last-line contract and
    records the streaming-fused-scoring measurement (ISSUE 4): per-arm
    rows/s and peak host RSS (each arm in its own subprocess),
    streamed-vs-resident margin parity, and the pass-time ratio."""
    proc = _run_bench(tmp_path, "--section", "score",
                      "--budget-s", "240", *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert rec["section"] == "score"
    assert rec.get("errors") is None
    s = rec["score"]
    # Chunks must dwarf the streamed arm's host window (the bounded-RSS
    # claim's precondition).
    assert s["n_chunks"] >= 6 * s["host_max_resident"]
    for arm in ("streamed", "resident"):
        assert s[arm]["pass_ms"] > 0
        assert s[arm]["rows_per_sec"] > 0
        assert s[arm]["peak_rss_mb"] > 0
    assert s["streamed"]["chunk_rows"] * s["n_chunks"] >= 4096
    # LRU window bound held during the streamed arm's timed passes.
    assert 1 <= s["streamed"]["peak_live_chunks"] <= 2
    assert s["margin_parity_max"] < 1e-4
    assert s["pass_time_ratio"] is not None
    # Satellite discipline from round 8: every section records the RSS
    # high-water trajectory.
    assert rec["peak_rss_mb"]["score"] > 0


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_bench_re_section_contract(tmp_path):
    """`--section re` keeps the budget/JSON-last-line contract and
    records the out-of-core random-effect measurement (ISSUE 5):
    per-arm sweep times, rows/s and peak RSS (subprocess isolation),
    the LRU window bound, streamed-vs-resident coefficient/score
    parity, and the converged-entity retirement work-reduction curve
    (per-sweep solved entities monotone non-increasing, with real
    reduction by the last sweep on the converging schedule)."""
    proc = _run_bench(tmp_path, "--section", "re",
                      "--budget-s", "240", *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert rec["section"] == "re"
    assert rec.get("errors") is None
    r = rec["re"]
    # Entity chunks must dwarf the streamed arm's host window.
    assert r["n_chunks"] >= 4 * r["host_max_resident"]
    assert 1 <= r["streamed"]["peak_live_chunks"] <= r["host_max_resident"]
    assert r["streamed"]["disk_loads"] > 0
    for arm in ("streamed", "resident"):
        assert r[arm]["sweep_s"] > 0
        assert r[arm]["rows_per_sec"] > 0
        assert r[arm]["peak_rss_mb"] > 0
        assert len(r[arm]["sweep_s_all"]) == r["sweeps"]
    # Retirement work-reduction: monotone non-increasing solved counts,
    # strictly fewer by the end (entities froze), none retired at the
    # resident arm (no retirement support there).
    solved = r["streamed"]["entities_solved_per_sweep"]
    assert all(a >= b for a, b in zip(solved, solved[1:]))
    assert solved[-1] < solved[0]
    retired = r["streamed"]["entities_retired_per_sweep"]
    assert all(a <= b for a, b in zip(retired, retired[1:]))
    assert retired[-1] > 0
    assert r["retirement_work_fraction"] < 1.0
    # ISSUE 7: the streamed arm's telemetry block reports the prefetch
    # overlap story for the entity-chunk pipeline.
    tel = r["streamed"]["telemetry"]
    assert tel["sweeps"] == r["sweeps"] - 1      # sweep 0 untelemetered
    assert tel["overlap_efficiency"] is not None
    assert "telemetry" in r["resident"]
    # Retirement must not move the model beyond solver tolerance.
    assert r["coef_parity_max"] < 1e-2
    assert r["score_parity_max"] < 1e-2
    assert r["sweep_time_ratio"] is not None
    assert rec["peak_rss_mb"]["re"] > 0


@pytest.mark.slow   # two subprocess estimator fits per arm
def test_bench_cd_fused_section_contract(tmp_path):
    """`--section cd_fused` keeps the budget/JSON-last-line contract
    and records the fused-vs-per-coordinate measurement (ISSUE 11):
    per-arm pass counts and pass times (subprocess isolation for
    per-arm peak RSS), the fused arm's passes/cycle ≈ 1 against the
    legacy arm's ~C × solver-iterations, zero compiles in the measured
    (post-warmup) fits, and cross-arm coefficient parity within the
    documented tolerance."""
    proc = _run_bench(tmp_path, "--section", "cd_fused",
                      "--budget-s", "280", *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert rec["section"] == "cd_fused"
    assert rec.get("errors") is None
    s = rec["cd_fused"]
    for arm in ("fused", "percoord"):
        a = s[arm]
        assert a["fit_s"] > 0
        assert a["cycles"] > 0 and a["data_passes"] > 0
        assert a["peak_rss_mb"] > 0
        # Zero new compiles across the measured sweeps: the warm-up
        # fit paid every compile (guard-pinned acceptance criterion).
        assert a["telemetry"]["compiles"] == 0, a["telemetry"]
    # THE claim: one pass per cycle (+ the final score pass) fused,
    # C × solver-iterations per cycle legacy.
    assert s["passes_per_cycle_fused"] <= 1.2
    assert s["passes_per_cycle_percoord"] >= 4.0
    assert s["pass_count_ratio"] >= 3.0
    assert s["pass_time_ratio"] is not None
    assert s["coef_parity_max"] < 5e-2
    assert rec["peak_rss_mb"]["cd_fused"] > 0


@pytest.mark.fast
def test_history_spec_watches_cd_fused():
    """The 'gate watches it from round 16 on' satellite: the history
    metric spec carries the cd_fused section's passes/cycle, pass-time
    ratio, and fused throughput."""
    from photon_ml_tpu.telemetry.history import METRICS

    keys = {(s, p) for s, p, _ in METRICS}
    assert ("cd_fused", "cd_fused.passes_per_cycle_fused") in keys
    assert ("cd_fused", "cd_fused.pass_time_ratio") in keys
    assert ("cd_fused", "cd_fused.fused.rows_per_sec") in keys
    directions = {f"{s}:{p}": d for s, p, d in METRICS}
    assert directions["cd_fused:cd_fused.passes_per_cycle_fused"] == "lower"
    assert directions["cd_fused:cd_fused.fused.rows_per_sec"] == "higher"


@pytest.mark.fast
def test_history_spec_watches_serve():
    """ISSUE 12 satellite: the history metric spec carries the serve
    section's p99 latency, sustained rows/s, and batch fill."""
    from photon_ml_tpu.telemetry.history import METRICS

    keys = {(s, p) for s, p, _ in METRICS}
    assert ("serve", "serve.p99_ms") in keys
    assert ("serve", "serve.rows_per_sec") in keys
    assert ("serve", "serve.batch_fill") in keys
    directions = {f"{s}:{p}": d for s, p, d in METRICS}
    assert directions["serve:serve.p99_ms"] == "lower"
    assert directions["serve:serve.rows_per_sec"] == "higher"
    assert directions["serve:serve.batch_fill"] == "higher"


@pytest.mark.fast
def test_history_spec_watches_serve_fleet():
    """ISSUE 13 satellite: the history spec gates the fleet arm's
    claims — failed client requests (the retry-once contract says 0)
    and the killed replica's detect→ready restart latency."""
    from photon_ml_tpu.telemetry.history import METRICS

    keys = {(s, p) for s, p, _ in METRICS}
    assert ("serve", "serve.failed_requests") in keys
    assert ("serve", "serve.restart_s") in keys
    directions = {f"{s}:{p}": d for s, p, d in METRICS}
    assert directions["serve:serve.failed_requests"] == "lower"
    assert directions["serve:serve.restart_s"] == "lower"


@pytest.mark.fast
def test_history_spec_watches_serve_stage_medians():
    """ISSUE 14 satellite: the history spec gates the request-tracing
    stage medians — queue wait creeping up (batcher becoming the
    bottleneck) and dispatch creeping up (device path regressing) are
    history-gated like everything else."""
    from photon_ml_tpu.telemetry.history import METRICS, detect

    keys = {(s, p) for s, p, _ in METRICS}
    assert ("serve", "serve.queue_wait_ms") in keys
    assert ("serve", "serve.dispatch_ms") in keys
    directions = {f"{s}:{p}": d for s, p, d in METRICS}
    assert directions["serve:serve.queue_wait_ms"] == "lower"
    assert directions["serve:serve.dispatch_ms"] == "lower"
    # Contract: an injected 2x queue-wait regression gates (rc-1
    # shape) while a flat trajectory stays clean.
    rounds = [
        {"name": f"r{i}", "rc": 0,
         "record": {"serve": {"queue_wait_ms": 2.0,
                              "dispatch_ms": 3.0}}}
        for i in range(3)
    ]
    assert detect(rounds)["ok"] is True
    rounds.append({"name": "r3", "rc": 0,
                   "record": {"serve": {"queue_wait_ms": 4.0,
                                        "dispatch_ms": 3.0}}})
    result = detect(rounds)
    assert result["ok"] is False
    assert [r["metric"] for r in result["regressions"]] == \
        ["serve:serve.queue_wait_ms"]


@pytest.mark.slow   # server subprocess + client storm
def test_bench_serve_section_contract(tmp_path):
    """`--section serve` keeps the budget/JSON-last-line contract and
    records the serving measurement: client-observed p50/p99 latency
    and rows/s under concurrent open-loop clients, micro-batch fill,
    margin parity vs the batch scorer, the server's own peak RSS, and
    the server subprocess's clean rc."""
    proc = _run_bench(tmp_path, "--section", "serve",
                      "--budget-s", "480", *_TINY, timeout=640)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert rec["section"] == "serve"
    assert rec.get("errors") is None
    s = rec["serve"]
    assert s["clients"] == 4
    assert s["requests"] > 0
    assert s["p50_ms"] > 0 and s["p99_ms"] >= s["p50_ms"]
    assert s["rows_per_sec"] > 0
    assert 0 < s["batch_fill"] <= 1.0
    # Served margins match the batch scorer on identical rows
    # (documented tolerance — same f32 fused program).
    assert s["margin_parity_max"] <= 1e-5
    assert s["server_peak_rss_mb"] > 0
    assert s["server_rc"] == 0
    assert rec["peak_rss_mb"]["serve"] > 0
    # Request tracing (ISSUE 14): stage medians recorded for the
    # history gate, and the paired tracing off/on A/B measured.
    assert s["queue_wait_ms"] is not None and s["queue_wait_ms"] > 0
    assert s["dispatch_ms"] is not None and s["dispatch_ms"] > 0
    ov = s["trace_overhead"]
    assert ov["p50_off_ms"] > 0 and ov["p50_on_ms"] > 0
    assert ov["overhead_frac"] is not None
    # Fleet arm (ISSUE 13): 2 replicas, one SIGKILLed mid-storm —
    # zero failed client requests, the restart latency measured, the
    # shed fraction reported, and a clean frontend exit.
    if "skipped" in s.get("fleet", {}):
        pytest.fail(f"fleet arm skipped: {s['fleet']['skipped']}")
    assert s["failed_requests"] == 0
    assert s["restart_s"] is not None and s["restart_s"] > 0
    assert 0.0 <= s["shed_fraction"] < 1.0
    f = s["fleet"]
    assert f["replicas"] == 2
    assert f["requests"] > 0
    assert f["restarts"] >= 1
    assert f["frontend_rc"] == 0
    # Cross-process trace join (ISSUE 14 acceptance): the frontend's
    # and replicas' trace logs join by trace id at >= 99%, and the
    # SIGKILL guarantees retried requests exercised the retry column.
    tj = s["trace_join"]
    assert tj is not None and "error" not in tj, tj
    assert tj["ok"] is True
    assert tj["join_fraction"] is None or tj["join_fraction"] >= 0.99
    assert tj["retried_requests"] >= 1
    assert tj["dominant_stage"] is not None


def test_bench_history_dir_appends_envelope(tmp_path):
    """`--history-dir` appends the run's JSON record as a
    schema-versioned envelope file that `telemetry history` ingests
    (ISSUE 8 satellite)."""
    hist = tmp_path / "hist"
    proc = _run_bench(tmp_path, "--section", "etl", "--budget-s", "60",
                      "--history-dir", str(hist), *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    files = sorted(os.listdir(hist))
    assert len(files) == 1 and files[0].endswith(".json")
    with open(hist / files[0]) as f:
        env = json.load(f)
    assert env["schema"] == 1
    assert env["kind"] == "bench_record"
    assert env["rc"] == 0
    assert env["record"]["etl_grr_s"] is not None
    # The gate ingests it cleanly.
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.telemetry", "history",
         str(hist)], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout[-2000:]
    tail = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert tail["ok"] is True and tail["rounds"] == files


def test_bench_history_trajectory_gate(tmp_path):
    """The CI gating contract (ISSUE 8 satellite): `telemetry history`
    over a synthetic two-round trajectory exits rc 0 clean and rc 1
    with an injected 20% rows/s regression, naming the section/metric."""
    hist = tmp_path / "hist"
    hist.mkdir()

    def write_round(name, rows_per_sec):
        with open(hist / name, "w") as f:
            json.dump({"schema": 1, "kind": "bench_record", "rc": 0,
                       "record": {"stream": {
                           "spilled": {"examples_per_sec": rows_per_sec},
                           "pass_time_ratio": 1.02}}}, f)

    def gate():
        proc = subprocess.run(
            [sys.executable, "-m", "photon_ml_tpu.telemetry", "history",
             str(hist)], capture_output=True, text=True, timeout=120)
        tail = json.loads(
            [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
        return proc.returncode, tail

    write_round("r01.json", 1_000_000.0)
    write_round("r02.json", 1_020_000.0)
    rc, tail = gate()
    assert rc == 0 and tail["ok"] is True and tail["regressions"] == []

    write_round("r03.json", 800_000.0)       # injected 20% regression
    rc, tail = gate()
    assert rc == 1 and tail["ok"] is False
    assert tail["regressions"][0]["metric"] == (
        "stream:stream.spilled.examples_per_sec")
    assert tail["regressions"][0]["round"] == "r03.json"


def test_bench_zero_budget_still_emits_json(tmp_path):
    """A hopeless budget skips every section but the process still
    exits 0 with one parseable JSON line recording the skips."""
    proc = _run_bench(tmp_path, "--budget-s", "0", *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert set(rec["sections_skipped"]) == {
        "etl", "cached", "grr", "segment_sum", "colmajor"}
    assert rec["value"] is None


@pytest.mark.fast
def test_history_spec_watches_mesh_stream():
    """ISSUE 16 satellite: the history spec gates the multi-host
    section's claims — fleet throughput, the barrier-wait tax, the
    per-host peak-RSS bound, and the replicated odometer's
    passes/cycle."""
    from photon_ml_tpu.telemetry.history import METRICS

    keys = {(s, p) for s, p, _ in METRICS}
    assert ("mesh_stream", "mesh_stream.rows_per_sec") in keys
    assert ("mesh_stream",
            "mesh_stream.barrier_wait_fraction") in keys
    assert ("mesh_stream",
            "mesh_stream.max_host_peak_rss_mb") in keys
    assert ("mesh_stream", "mesh_stream.passes_per_cycle") in keys
    directions = {f"{s}:{p}": d for s, p, d in METRICS}
    assert directions["mesh_stream:mesh_stream.rows_per_sec"] == \
        "higher"
    assert directions[
        "mesh_stream:mesh_stream.barrier_wait_fraction"] == "lower"
    assert directions[
        "mesh_stream:mesh_stream.max_host_peak_rss_mb"] == "lower"
    assert directions["mesh_stream:mesh_stream.passes_per_cycle"] == \
        "lower"


def test_bench_mesh_arm_solo_smoke(tmp_path):
    """The fast mesh smoke: ONE ``--mesh-arm`` worker with no fleet
    environment is a single-host control run — rc 0, one JSON line
    with the arm record (no fleet counters, a live odometer), and the
    per-host ``run_log.jsonl`` the fleet-report join would consume."""
    proc = _run_bench(tmp_path, "--mesh-arm", "solo", *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert rec["host"] == 0
    assert rec["transport"] is None
    assert rec["reduces"] == 0 and rec["chunks_streamed"] == 0
    assert rec["cycles"] > 0 and rec["data_passes"] > 0
    assert rec["passes_per_cycle"] is not None
    assert rec["peak_rss_mb"] > 0
    assert os.path.exists(rec["run_log"])
    # Solo run → NOT host-sharded: the log sits at the mesh base dir.
    assert os.path.dirname(rec["run_log"]).endswith("mesh_stream")


@pytest.mark.slow   # MESH_HOSTS concurrent subprocess estimator fits
def test_bench_mesh_stream_section_contract(tmp_path):
    """`--section mesh_stream` keeps the budget/JSON-last-line
    contract and records the multi-host measurement (ISSUE 16): all
    hosts report one reduce count (barrier agreement), the replicated
    odometer agrees with passes/cycle ≈ 1, coefficients are bitwise
    identical across hosts, and the fleet-report join passes."""
    proc = _run_bench(tmp_path, "--section", "mesh_stream",
                      "--budget-s", "400", *_TINY, timeout=420)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert rec["section"] == "mesh_stream"
    assert rec.get("errors") is None, rec["errors"]
    s = rec["mesh_stream"]
    assert s["transport"] in ("psum", "tcp")
    assert len(s["per_host"]) == s["hosts"] == 3
    assert s["barrier_agreement"] is True
    assert s["odometer_agreement"] is True
    assert s["coef_identical_across_hosts"] is True
    assert s["fleet_report_ok"] is True
    assert s["reduces_per_host"] > 0
    assert s["total_chunks_streamed"] > 0
    assert s["rows_per_sec"] > 0
    assert s["max_host_peak_rss_mb"] > 0
    assert s["passes_per_cycle"] <= 1.5    # fused: ~1 (+ score pass)
    for host in s["per_host"]:
        assert host["reduces"] == s["reduces_per_host"]
        assert host["barrier_wait_s"] >= 0


@pytest.mark.fast
def test_history_spec_watches_tron():
    """ISSUE 17 satellite: the history metric spec carries the tron
    section's passes-to-tolerance, streamed throughput, and peak RSS,
    so the pass advantage is gated from this round on."""
    from photon_ml_tpu.telemetry.history import METRICS

    keys = {(s, p) for s, p, _ in METRICS}
    assert ("tron", "tron.passes_to_tol") in keys
    assert ("tron", "tron.rows_per_sec") in keys
    assert ("tron", "tron.peak_rss_mb") in keys
    directions = {f"{s}:{p}": d for s, p, d in METRICS}
    assert directions["tron:tron.passes_to_tol"] == "lower"
    assert directions["tron:tron.rows_per_sec"] == "higher"
    assert directions["tron:tron.peak_rss_mb"] == "lower"


def test_bench_tron_arm_smoke(tmp_path):
    """The fast tron smoke: ONE ``--tron-arm tron`` subprocess on the
    tiny shape — rc 0, one JSON line whose odometer fields close the
    identity (passes == 1 initial vg + hvp passes + trial evals + the
    preconditioner diagonal) and whose throughput/RSS fields are
    live."""
    proc = _run_bench(tmp_path, "--tron-arm", "tron", *_TINY)
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert rec["arm"] == "tron"
    assert rec["converged"] is True
    assert rec["iterations"] >= 1
    assert rec["passes_to_tol"] == (1 + rec["hvp_passes"]
                                    + rec["ls_trials"]
                                    + rec["aux_passes"])
    assert rec["hvp_passes"] >= 1
    assert rec["aux_passes"] == 1
    assert rec["rows_per_sec"] > 0
    assert rec["solve_peak_rss_mb"] > 0
    assert rec["telemetry"]["sweeps"] == rec["passes_to_tol"]


@pytest.mark.slow   # two subprocess solve-to-tolerance arms
def test_bench_tron_section_contract(tmp_path):
    """`--section tron` keeps the budget/JSON-last-line contract and
    records the second-order measurement (ISSUE 17): both arms
    converge to the shared tolerance, the TRON arm reaches it in
    FEWER data passes (the pass advantage the section exists to
    claim), per-arm RSS is subprocess-isolated, the measured solves
    compile nothing (--guards), and the arms agree on the
    coefficients.  Runs a step above _TINY: at 4096x2048 the logistic
    fit is easy enough that first-order passes tie second-order ones —
    the pass-advantage claim needs the conditioning to actually
    bite."""
    proc = _run_bench(tmp_path, "--section", "tron", "--budget-s",
                      "240", "--guards",
                      "--n", "60000", "--d", "4000", "--k", "8")
    assert proc.returncode == 0, proc.stderr[-3000:]
    rec = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
    assert rec["section"] == "tron"
    assert rec.get("errors") is None, rec.get("errors")
    s = rec["tron"]
    for arm in ("tron", "lbfgs"):
        assert s[arm]["converged"] is True
        assert s[arm]["passes_to_tol"] > 0
        assert s[arm]["solve_peak_rss_mb"] > 0
        assert s[arm]["guards"]["solve_compiles"] == 0, s[arm]["guards"]
        assert "telemetry" in s[arm]
    # The gated numbers ride the section record at the METRICS paths.
    assert s["passes_to_tol"] == s["tron"]["passes_to_tol"]
    assert s["rows_per_sec"] == s["tron"]["rows_per_sec"]
    assert s["peak_rss_mb"] == s["tron"]["solve_peak_rss_mb"]
    # The claim: strictly fewer data passes to the same tolerance.
    assert s["pass_advantage"] is not None
    assert s["pass_advantage"] > 1.0, s
    assert s["coef_parity_max"] < 0.5
    assert rec["peak_rss_mb"]["tron"] > 0
