"""Streaming fused scoring pipeline (ISSUE 4): streamed ≡ resident
margins on every coordinate mix, streaming evaluators ≡ one-shot
evaluators, sink round trips, and the spill-store window bound.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.estimators.game_transformer import GameTransformer
from photon_ml_tpu.estimators.streaming_scorer import StreamingGameScorer
from photon_ml_tpu.game.dataset import GameDataset, group_by_entity
from photon_ml_tpu.game.projector import SubspaceProjection
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import TaskType


# ---------------------------------------------------------------------------
# Fixture: a model × dataset covering every coordinate mix at once —
# sparse fixed effect (with intercept), dense fixed effect, dense
# non-projected RE (with unseen entities), projected RE (with
# out-of-space feature ids), plus dataset offsets.
# ---------------------------------------------------------------------------


def _mixed_workload(rng, n=1000):
    d = 50
    indptr = np.arange(n + 1) * 5
    cols = rng.integers(0, d, n * 5).astype(np.int64)
    vals = rng.normal(size=n * 5)
    rows = SparseRows.from_flat(indptr, cols, vals)

    d_dense = 7
    x_dense = rng.normal(size=(n, d_dense)).astype(np.float32)

    d_re = 3
    x_re = rng.normal(size=(n, d_re)).astype(np.float32)
    ids = rng.integers(0, 20, n)
    ids[0] = 10**9                       # unseen entity scores 0
    grouping = group_by_entity(ids[ids < 10**9][:800])
    blocks = [jnp.asarray(rng.normal(size=(ne, d_re)).astype(np.float32))
              for ne in grouping.n_entities]

    G = 40
    rows_p = []
    for _ in range(n):
        k = int(rng.integers(1, 4))
        # Some ids >= G: out-of-space features must score zero.
        c = np.sort(rng.choice(G + 3, k, replace=False)).astype(np.int64)
        rows_p.append((c, rng.normal(size=k).astype(np.float32)))
    sp_rows = SparseRows.from_rows(rows_p)
    ids2 = rng.integers(0, 12, n)
    g2 = group_by_entity(ids2[:700])
    proj = SubspaceProjection(
        feature_ids=[
            np.where(rng.uniform(size=(ne, 4)) < 0.8,
                     rng.integers(0, G, (ne, 4)), -1).astype(np.int32)
            for ne in g2.n_entities],
        global_dim=G)
    blocks2 = [jnp.asarray(rng.normal(size=(ne, 4)).astype(np.float32))
               for ne in g2.n_entities]

    w = rng.normal(size=d + 1).astype(np.float32)
    w_dense = rng.normal(size=d_dense + 1).astype(np.float32)
    model = GameModel(models={
        "global": FixedEffectModel(
            coefficients=Coefficients(means=jnp.asarray(w)),
            feature_shard="sparse", intercept=True),
        "ctx": FixedEffectModel(
            coefficients=Coefficients(means=jnp.asarray(w_dense)),
            feature_shard="dense", intercept=True),
        "per_user": RandomEffectModel(
            coefficient_blocks=blocks, grouping=grouping,
            feature_shard="re", entity_key="userId"),
        "per_item": RandomEffectModel(
            coefficient_blocks=blocks2, grouping=g2,
            feature_shard="proj", entity_key="itemId",
            projection=proj),
    })
    dataset = GameDataset(
        labels=(rng.uniform(size=n) < 0.5).astype(np.float32),
        features={"sparse": rows, "dense": x_dense, "re": x_re,
                  "proj": sp_rows},
        entity_ids={"userId": ids, "itemId": ids2},
        weights=rng.uniform(0.5, 2.0, n).astype(np.float32),
        offsets=rng.normal(size=n).astype(np.float32),
    )
    return model, dataset


@pytest.mark.parametrize("chunk_rows", [64, 128, 1000, 4096])
def test_streamed_matches_resident_all_mixes(rng, chunk_rows):
    """The tentpole parity claim: the one-pass fused chunk pipeline
    produces the per-coordinate resident transform's margins to float
    tolerance — even/uneven chunk grids, single-chunk, padded tail."""
    model, ds = _mixed_workload(rng)
    tr = GameTransformer(model=model, task=TaskType.LOGISTIC_REGRESSION)
    m_res = tr.transform(ds)
    m_str = tr.transform_streamed(ds, score_chunk_rows=chunk_rows)
    np.testing.assert_allclose(m_str, m_res, atol=2e-4)


def test_streamed_single_coordinate_mixes(rng):
    """Each coordinate kind alone (the fused program's per-kind
    branches are exercised in isolation too)."""
    model, ds = _mixed_workload(rng, n=500)
    for name in model.models:
        sub = GameModel(models={name: model.models[name]})
        tr = GameTransformer(model=sub, task=TaskType.LINEAR_REGRESSION)
        np.testing.assert_allclose(
            tr.transform_streamed(ds, score_chunk_rows=64),
            tr.transform(ds), atol=2e-4, err_msg=name)


def test_streamed_predictions_mean_space(rng):
    """The fused program applies the task mean chunk-wise: predictions
    equal mean(margins) with no full-array device round trip."""
    model, ds = _mixed_workload(rng, n=300)
    scorer = StreamingGameScorer(
        model=model, task=TaskType.LOGISTIC_REGRESSION, chunk_rows=64)
    out = scorer.score(ds, keep_margins=True)
    np.testing.assert_allclose(
        out["predictions"],
        1.0 / (1.0 + np.exp(-out["margins"].astype(np.float64))),
        atol=1e-6)


def test_streamed_spill_window_bounded_and_warm(rng, tmp_path):
    """Disk tier: margins identical, the LRU host window bound holds,
    and a second scorer over the same content reuses the spilled chunk
    files (warm-scoring artifact) without rebuilding."""
    model, ds = _mixed_workload(rng)
    tr = GameTransformer(model=model, task=TaskType.LOGISTIC_REGRESSION)
    m_res = tr.transform(ds)

    scorer = StreamingGameScorer(
        model=model, task=TaskType.LOGISTIC_REGRESSION, chunk_rows=100,
        spill_dir=str(tmp_path), host_max_resident=1, prefetch_depth=2)
    out = scorer.score(ds, keep_margins=True)
    np.testing.assert_allclose(out["margins"], m_res, atol=2e-4)
    assert out["n_chunks"] == 10
    assert out["store"]["spills"] == 10
    assert 1 <= out["store"]["peak_resident"] <= 1

    scorer2 = StreamingGameScorer(
        model=model, task=TaskType.LOGISTIC_REGRESSION, chunk_rows=100,
        spill_dir=str(tmp_path), host_max_resident=2, prefetch_depth=0)
    out2 = scorer2.score(ds, keep_margins=True)
    assert out2["store"]["spills"] == 0          # warm reuse
    np.testing.assert_array_equal(out2["margins"], out["margins"])


def test_streamed_corrupt_chunk_rebuilds(rng, tmp_path):
    """A corrupted spilled score chunk rebuilds from lineage (the store
    must never fail a scoring run)."""
    model, ds = _mixed_workload(rng, n=400)
    scorer = StreamingGameScorer(
        model=model, task=TaskType.LOGISTIC_REGRESSION, chunk_rows=100,
        spill_dir=str(tmp_path), host_max_resident=1)
    out = scorer.score(ds, keep_margins=True)
    chunk_dir = tmp_path / "chunks"
    victim = sorted(os.listdir(chunk_dir))[2]
    with open(chunk_dir / victim, "wb") as f:
        f.write(b"garbage")
    scorer2 = StreamingGameScorer(
        model=model, task=TaskType.LOGISTIC_REGRESSION, chunk_rows=100,
        spill_dir=str(tmp_path), host_max_resident=1)
    out2 = scorer2.score(ds, keep_margins=True)
    np.testing.assert_array_equal(out2["margins"], out["margins"])
    assert out2["store"]["loads"] > 0


def test_streaming_evaluators_match_oneshot(rng):
    """Exact regime: every streaming evaluator reproduces its one-shot
    counterpart over chunked updates (AUC exactly — the fallback IS the
    one-shot evaluator; losses to float64-accumulation tolerance)."""
    from photon_ml_tpu.evaluation.evaluators import EvaluatorType, evaluate
    from photon_ml_tpu.evaluation.streaming import make_streaming_evaluator

    n = 5000
    m = rng.normal(size=n).astype(np.float32)
    p = (1.0 / (1.0 + np.exp(-m))).astype(np.float32)
    y = (rng.uniform(size=n) < p).astype(np.float32)
    w = rng.uniform(0.5, 2.0, n).astype(np.float32)
    for ev in EvaluatorType:
        acc = make_streaming_evaluator(ev)
        for lo in range(0, n, 777):
            hi = min(lo + 777, n)
            acc.update(m[lo:hi], p[lo:hi], y[lo:hi], w[lo:hi])
        scores = p if ev.value in ("RMSE", "SQUARED_LOSS") else m
        ref = float(evaluate(ev, jnp.asarray(scores), jnp.asarray(y),
                             jnp.asarray(w)))
        assert abs(acc.result() - ref) < 5e-5, ev


def test_streaming_auc_histogram_tolerance(rng):
    """Histogram regime (forced): AUC within the documented fixed-bin
    tolerance of the exact answer, including a mid-stream
    exact→histogram transition."""
    from photon_ml_tpu.evaluation.evaluators import auc
    from photon_ml_tpu.evaluation.streaming import StreamingAUC

    n = 50000
    m = (rng.normal(size=n) * 3).astype(np.float32)
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-m))).astype(
        np.float32)
    w = rng.uniform(0.5, 2.0, n).astype(np.float32)
    ref = float(auc(jnp.asarray(m), jnp.asarray(y), jnp.asarray(w)))
    for exact_below in (0, 10000):
        acc = StreamingAUC(exact_below=exact_below)
        for lo in range(0, n, 4096):
            hi = min(lo + 4096, n)
            acc.update(m[lo:hi], y[lo:hi], w[lo:hi])
        assert not acc.exact
        assert abs(acc.result() - ref) < 1e-3


def test_streaming_auc_exact_below_threshold(rng):
    """Below the row threshold the streaming AUC is the one-shot
    evaluator bit-for-bit (the exactness fallback contract)."""
    from photon_ml_tpu.evaluation.evaluators import auc
    from photon_ml_tpu.evaluation.streaming import StreamingAUC

    n = 3000
    m = rng.normal(size=n).astype(np.float32)
    y = (rng.uniform(size=n) < 0.4).astype(np.float32)
    w = rng.uniform(0.5, 2.0, n).astype(np.float32)
    acc = StreamingAUC()           # default threshold >> n
    for lo in range(0, n, 500):
        acc.update(m[lo:lo + 500], y[lo:lo + 500], w[lo:lo + 500])
    assert acc.exact
    ref = float(auc(jnp.asarray(m), jnp.asarray(y), jnp.asarray(w)))
    assert acc.result() == pytest.approx(ref, abs=1e-7)


def test_scorer_streaming_evaluation_matches_driver_convention(rng):
    """End-to-end through the scorer: streaming evaluation equals the
    one-shot evaluation of the resident margins under the driver's
    score conventions (margins vs mean-space per evaluator)."""
    from photon_ml_tpu.evaluation.evaluators import EvaluatorType, evaluate
    from photon_ml_tpu.evaluation.streaming import make_streaming_evaluator

    model, ds = _mixed_workload(rng)
    tr = GameTransformer(model=model, task=TaskType.LOGISTIC_REGRESSION)
    margins = tr.transform(ds)
    preds = np.asarray(jnp.asarray(1.0) /
                       (1.0 + jnp.exp(-jnp.asarray(margins))))
    evaluators = [make_streaming_evaluator(ev) for ev in
                  (EvaluatorType.AUC, EvaluatorType.RMSE,
                   EvaluatorType.LOGISTIC_LOSS)]
    scorer = StreamingGameScorer(
        model=model, task=TaskType.LOGISTIC_REGRESSION, chunk_rows=128)
    out = scorer.score(ds, evaluators=evaluators)
    w = jnp.asarray(ds.weights)
    y = jnp.asarray(ds.labels)
    for ev_type, got in out["evaluation"].items():
        ev = EvaluatorType(ev_type)
        scores = preds if ev.value in ("RMSE", "SQUARED_LOSS") else margins
        ref = float(evaluate(ev, jnp.asarray(scores), y, w))
        assert abs(got - ref) < 5e-4, ev_type


# ---------------------------------------------------------------------------
# Sinks
# ---------------------------------------------------------------------------


def test_npz_stream_sink_roundtrip(rng, tmp_path):
    from photon_ml_tpu.io.score_sink import NpzScoreSink

    n = 1000
    m = rng.normal(size=n).astype(np.float32)
    p = rng.uniform(size=n).astype(np.float32)
    y = rng.integers(0, 2, n).astype(np.float32)
    path = str(tmp_path / "s.npz")
    sink = NpzScoreSink(path, n)
    for lo in range(0, n, 256):
        hi = min(lo + 256, n)
        sink.write(lo, hi, m[lo:hi], p[lo:hi], y[lo:hi])
    sink.close()
    out = np.load(path)
    np.testing.assert_array_equal(out["scores"], m)
    np.testing.assert_array_equal(out["predictions"], p)
    np.testing.assert_array_equal(out["labels"], y)
    # Temp members are gone; only the final artifact remains.
    assert os.listdir(tmp_path) == ["s.npz"]


def test_npz_stream_sink_incomplete_raises(rng, tmp_path):
    from photon_ml_tpu.io.score_sink import NpzScoreSink

    sink = NpzScoreSink(str(tmp_path / "s.npz"), 100)
    z = np.zeros(50, np.float32)
    sink.write(0, 50, z, z, z)
    with pytest.raises(ValueError, match="50 of 100"):
        sink.close()


def test_avro_sink_block_batches_roundtrip(rng, tmp_path):
    """The batched block encoder is byte-compatible with the generic
    SCORING_RESULT_SCHEMA reader: one container block per chunk, field
    values and entity-id maps intact."""
    from photon_ml_tpu.io.avro import read_container
    from photon_ml_tpu.io.score_sink import AvroScoreSink

    n = 700
    p = rng.uniform(size=n).astype(np.float32)
    y = rng.integers(0, 2, n).astype(np.float32)
    ids = rng.integers(0, 99, n)
    path = str(tmp_path / "s.avro")
    sink = AvroScoreSink(path, ids_keys=("userId",))
    for lo in range(0, n, 256):
        hi = min(lo + 256, n)
        sink.write(lo, hi, None, p[lo:hi], y[lo:hi],
                   ids={"userId": ids[lo:hi]})
    sink.close()
    assert sink.blocks_written == 3
    _, recs = read_container(path)
    recs = list(recs)
    assert len(recs) == n
    for j in (0, 255, 256, n - 1):
        assert recs[j]["uid"] == j
        assert recs[j]["predictionScore"] == pytest.approx(
            float(p[j]), abs=1e-9)
        assert recs[j]["label"] == pytest.approx(float(y[j]), abs=1e-9)
        assert recs[j]["ids"]["userId"] == str(int(ids[j]))


# ---------------------------------------------------------------------------
# Device RE path (ISSUE 4 satellite): the chunked gather+dot program
# matches the host einsum (the threshold gate keeps CPU runs on host in
# production; here the device function is tested directly).
# ---------------------------------------------------------------------------


def test_device_score_re_matches_host_einsum(rng):
    from photon_ml_tpu.estimators.game_transformer import _device_score_re

    n, E, d_re = 1000, 30, 5
    x = rng.normal(size=(n, d_re)).astype(np.float32)
    w_all = rng.normal(size=(E, d_re)).astype(np.float32)
    w_pad = np.vstack([w_all, np.zeros((1, d_re), np.float32)])
    idx = rng.integers(-1, E, n)            # −1 = unseen
    got = _device_score_re(x, w_pad, idx)
    ref = np.einsum("nd,nd->n", x, w_pad[idx]).astype(np.float32)
    ref[idx < 0] = np.einsum(
        "nd,nd->n", x[idx < 0],
        np.zeros((int((idx < 0).sum()), d_re), np.float32))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_device_score_re_sparse_rows(rng):
    from photon_ml_tpu.estimators.game_transformer import _device_score_re

    n, E, d_re = 500, 10, 4
    rows = SparseRows.from_rows([
        (np.sort(rng.choice(d_re, 2, replace=False)).astype(np.int64),
         rng.normal(size=2).astype(np.float32))
        for _ in range(n)])
    w_pad = np.vstack([rng.normal(size=(E, d_re)).astype(np.float32),
                       np.zeros((1, d_re), np.float32)])
    idx = rng.integers(0, E, n)
    got = _device_score_re(rows, w_pad, idx)
    ref = np.einsum("nd,nd->n", rows.to_dense(d_re),
                    w_pad[idx]).astype(np.float32)
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_score_random_routes_large_inputs_to_device(rng, monkeypatch):
    """Above the row threshold (and off the CPU backend) _score_random
    takes the chunked device gather+dot — asserted by stubbing the
    backend check and counting device-path calls."""
    import photon_ml_tpu.estimators.game_transformer as gt

    n, E, d_re = 300, 8, 3
    ids = rng.integers(0, E, n)
    grouping = group_by_entity(ids)
    blocks = [jnp.asarray(rng.normal(size=(ne, d_re)).astype(np.float32))
              for ne in grouping.n_entities]
    model = RandomEffectModel(coefficient_blocks=blocks,
                              grouping=grouping, feature_shard="re")
    ds = GameDataset(labels=np.zeros(n, np.float32),
                     features={"re": rng.normal(size=(n, d_re))
                               .astype(np.float32)},
                     entity_ids={"re": ids})
    host = gt._score_random(model, ids, ds)

    calls = []
    real = gt._device_score_re
    monkeypatch.setattr(gt, "_DEVICE_SCORE_MIN_ROWS", 100)
    monkeypatch.setattr(gt.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        gt, "_device_score_re",
        lambda *a, **kw: calls.append(1) or real(*a, **kw))
    routed = gt._score_random(model, ids, ds)
    assert calls == [1]
    np.testing.assert_allclose(routed, host, atol=1e-5)


def test_sink_writer_error_propagates(rng, tmp_path):
    """A sink failure on the writer thread surfaces to the caller (at
    put() mid-stream or at close) and aborts the remaining sinks.  The
    cross-thread error handoff is lock-guarded since ISSUE 6
    (photon-lint unlocked-shared-write on _SinkWriter._error)."""
    model, ds = _mixed_workload(rng, n=600)

    class ExplodingSink:
        def __init__(self):
            self.aborted = False

        def write(self, lo, hi, margins, preds, labels, ids=None):
            raise IOError("sink full")

        def close(self):
            raise AssertionError("close must not follow a failed write")

        def abort(self):
            self.aborted = True

    sink = ExplodingSink()
    scorer = StreamingGameScorer(model, TaskType.LOGISTIC_REGRESSION,
                                 chunk_rows=100)
    with pytest.raises(IOError, match="sink full"):
        scorer.score(ds, sinks=[sink])
    assert sink.aborted


def test_scorer_compile_budget(rng):
    """Guard budget (ISSUE 6): the fused per-chunk program compiles
    once per model STRUCTURE — scoring 2x the data (more chunks, a
    fresh dataset and plan) compiles ZERO new programs, as does
    re-scoring the same dataset warm."""
    from photon_ml_tpu.analysis.guards import count_compiles

    model, ds1 = _mixed_workload(rng, n=700)
    _model2, ds2 = _mixed_workload(rng, n=1400)
    scorer = StreamingGameScorer(model, TaskType.LOGISTIC_REGRESSION,
                                 chunk_rows=96)
    with count_compiles() as cold:
        scorer.score(ds1, keep_margins=True)
    assert any("_run_chunk" in p for p in cold.programs), cold.programs

    with count_compiles() as more_data:
        scorer.score(ds2, keep_margins=True)   # same model, 2x chunks
    assert more_data.count == 0, more_data.programs

    with count_compiles() as warm:
        scorer.score(ds1, keep_margins=True)
    assert warm.count == 0, warm.programs
