"""One-time fixture generator (provenance record — committed outputs
are the source of truth; re-running regenerates byte-identical content
except Avro sync markers, which are random per file write).

Round-4 verdict item #7: config-1/config-4 parity must be data-at-rest
— committed LIBSVM/Avro byte fixtures with golden coefficients — not a
re-derivation from seeds.  Run from the repo root:

    python tests/resources/make_fixtures.py
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

HERE = os.path.dirname(os.path.abspath(__file__))


def make_config1():
    from photon_ml_tpu.io.libsvm import write_libsvm
    from photon_ml_tpu.utils.synthetic import make_a1a_like

    rows, labels, _ = make_a1a_like(n=750, seed=41)
    write_libsvm(os.path.join(HERE, "config1.libsvm"),
                 rows[:600], np.where(labels[:600] > 0, 1, -1))
    write_libsvm(os.path.join(HERE, "config1.t.libsvm"),
                 rows[600:], np.where(labels[600:] > 0, 1, -1))


def make_config4():
    from photon_ml_tpu.io.avro_schemas import (
        dataset_record_to_avro,
        training_example_schema,
    )
    from photon_ml_tpu.io.avro import write_container
    from photon_ml_tpu.utils.synthetic import make_movielens_like

    data = make_movielens_like(n_users=25, n_items=8, n_obs=900,
                               dim_global=6, seed=17)
    schema = training_example_schema(["global", "user_re"], ["userId"])
    recs = []
    for i in range(900):
        recs.append(dataset_record_to_avro({
            "label": float(data["labels"][i]),
            "weight": 1.0,
            "offset": 0.0,
            "features": {
                "global": [(f"g{j}", "", float(data["x"][i, j]))
                           for j in range(6)],
                "user_re": [("bias", "", 1.0)],
            },
            "ids": {"userId": str(int(data["user_ids"][i]))},
        }, ["global", "user_re"], ["userId"]))
    write_container(os.path.join(HERE, "config4_train.avro"),
                    schema, recs[:750])
    write_container(os.path.join(HERE, "config4_valid.avro"),
                    schema, recs[750:])


def make_goldens():
    """Train from the committed files and record golden outputs."""
    import tempfile

    from photon_ml_tpu.cli import game_training_driver
    from photon_ml_tpu.io.model_io import load_game_model

    golden = {}
    with tempfile.TemporaryDirectory() as td:
        cfg1 = {
            "task_type": "LOGISTIC_REGRESSION",
            "coordinates": [{
                "name": "global", "kind": "FIXED_EFFECT",
                "feature_shard": "features",
                "optimizer": {"optimizer": "LBFGS", "reg_weight": 1.0,
                              "max_iters": 100},
            }],
            "update_sequence": ["global"],
            "input_path": os.path.join(HERE, "config1.libsvm"),
            "validation_path": os.path.join(HERE, "config1.t.libsvm"),
            "output_dir": os.path.join(td, "out1"),
            "evaluators": ["AUC"],
        }
        p1 = os.path.join(td, "cfg1.json")
        json.dump(cfg1, open(p1, "w"))
        s1 = game_training_driver.main(["--config", p1])
        model1, _ = load_game_model(os.path.join(td, "out1", "model"))
        w1 = model1.models["global"].coefficients.means
        golden["config1"] = {
            "auc": s1["models"][0]["evaluations"]["AUC"],
            "coefficients": [round(float(v), 6) for v in list(w1)],
        }

        cfg4 = {
            "task_type": "LOGISTIC_REGRESSION",
            "coordinates": [
                {"name": "global", "kind": "FIXED_EFFECT",
                 "feature_shard": "global",
                 "optimizer": {"optimizer": "LBFGS", "reg_weight": 1.0,
                               "max_iters": 100}},
                {"name": "per_user", "kind": "RANDOM_EFFECT",
                 "feature_shard": "user_re", "entity_key": "userId",
                 "optimizer": {"optimizer": "LBFGS", "reg_weight": 2.0,
                               "max_iters": 60}},
            ],
            "update_sequence": ["global", "per_user"],
            "n_iterations": 2,
            "input_path": os.path.join(HERE, "config4_train.avro"),
            "validation_path": os.path.join(HERE, "config4_valid.avro"),
            "output_dir": os.path.join(td, "out4"),
            "evaluators": ["AUC"],
        }
        p4 = os.path.join(td, "cfg4.json")
        json.dump(cfg4, open(p4, "w"))
        s4 = game_training_driver.main(["--config", p4])
        model4, _ = load_game_model(os.path.join(td, "out4", "model"))
        w4 = model4.models["global"].coefficients.means
        golden["config4"] = {
            "auc": s4["models"][0]["evaluations"]["AUC"],
            "fixed_coefficients": [round(float(v), 6) for v in list(w4)],
        }
    with open(os.path.join(HERE, "golden.json"), "w") as f:
        json.dump(golden, f, indent=1)


if __name__ == "__main__":
    # Goldens are generated on the CPU backend — the platform the test
    # suite runs on (conftest recipe; the axon plugin ignores the env
    # var, so config.update is the reliable switch).
    os.environ.pop("JAX_PLATFORMS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    make_config1()
    make_config4()
    make_goldens()
    print("fixtures + goldens written to", HERE)
