"""Online serving tier (ISSUE 12): config, manifest I/O, the entity
store, the micro-batcher, and the model server end to end.

The acceptance checks live here: served margins match
``StreamingGameScorer`` on identical rows (documented tolerance 1e-5 —
same f32 program, same op order), a warm server handles a concurrent
request stream with ZERO new compiles (guard-pinned), and a hot model
swap under sustained load drops no requests and serves the new
checkpoint's scores afterward.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu import telemetry
from photon_ml_tpu.analysis.guards import count_compiles
from photon_ml_tpu.config import (
    ServingConfig,
    config_to_json,
    serving_config_from_json,
)
from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.estimators.streaming_scorer import StreamingGameScorer
from photon_ml_tpu.game.dataset import GameDataset, group_by_entity
from photon_ml_tpu.game.projector import SubspaceProjection
from photon_ml_tpu.io import model_io
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import TaskType
from photon_ml_tpu.serving.batcher import MicroBatcher, ServerClosing
from photon_ml_tpu.serving.engine import (
    BadRequest,
    ScoringEngine,
    dataset_rows,
)
from photon_ml_tpu.serving.entity_store import EntityServeStore
from photon_ml_tpu.serving.http import Readiness
from photon_ml_tpu.serving.server import ModelServer
from photon_ml_tpu.telemetry import monitor as _mon

pytestmark = pytest.mark.fast

TASK = TaskType.LOGISTIC_REGRESSION
N, D, K, D_RE, E = 96, 40, 3, 3, 11
PARITY_TOL = 1e-5   # same f32 fused program, same op order


@pytest.fixture(autouse=True)
def _no_leaked_sessions():
    """Server tests must leave the module-global telemetry/monitor
    sessions closed (the test_monitor discipline) — and since ISSUE 14
    the trace recorder too (servers start one by default)."""
    from photon_ml_tpu.serving import tracing as _tracing

    assert _mon.active() is None and telemetry.active() is None
    assert _tracing.active() is None
    yield
    leaked = []
    if _tracing.active() is not None:
        _tracing.active().close()
        leaked.append("tracing")
    if _mon.active() is not None:
        _mon.active().close()
        leaked.append("monitor")
    if telemetry.active() is not None:
        telemetry.active().close()
        leaked.append("telemetry")
    assert not leaked, f"leaked sessions: {leaked}"


def _workload(seed: int = 3, scale: float = 1.0):
    """Sparse fixed effect + dense random effect + offsets, with some
    request ids UNSEEN in training (they exercise the fixed-effect
    fallback)."""
    rng = np.random.default_rng(seed)
    cols = rng.integers(0, D, (N, K)).astype(np.int64)
    vals = rng.normal(size=(N, K)).astype(np.float32)
    rows = SparseRows.from_flat(
        np.arange(N + 1, dtype=np.int64) * K, cols.reshape(-1),
        vals.reshape(-1))
    train_ids = rng.integers(0, E, N)
    grouping = group_by_entity(train_ids)
    ids = train_ids.copy()
    ids[::7] = 10 ** 9 + np.arange(len(ids[::7]))   # unseen entities
    x_re = rng.normal(size=(N, D_RE)).astype(np.float32)
    blocks = [jnp.asarray((scale * rng.normal(0, 0.1, (ne, D_RE)))
                          .astype(np.float32))
              for ne in grouping.n_entities]
    w = (scale * rng.normal(0, 0.1, D + 1)).astype(np.float32)
    model = GameModel(models={
        "global": FixedEffectModel(
            coefficients=Coefficients(means=jnp.asarray(w)),
            feature_shard="global", intercept=True),
        "per_user": RandomEffectModel(
            coefficient_blocks=blocks, grouping=grouping,
            feature_shard="re", entity_key="userId"),
    })
    dataset = GameDataset(
        labels=np.zeros(N, np.float32),
        features={"global": rows, "re": x_re},
        entity_ids={"userId": ids},
        offsets=rng.normal(0, 0.2, N).astype(np.float32),
        feature_dims={"global": D})
    return model, dataset


def _reference_margins(model, dataset):
    return StreamingGameScorer(model=model, task=TASK, chunk_rows=64) \
        .score(dataset, keep_margins=True)


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


def test_serving_config_validation():
    cfg = ServingConfig(model_dir="m")
    cfg.validate()
    assert cfg.buckets()[-1] == cfg.batch_rows
    assert cfg.buckets() == sorted(set(cfg.buckets()))
    with pytest.raises(ValueError, match="model_dir"):
        ServingConfig(model_dir="").validate()
    with pytest.raises(ValueError, match="batch_rows"):
        ServingConfig(model_dir="m", batch_rows=0).validate()
    with pytest.raises(ValueError, match="ascending"):
        ServingConfig(model_dir="m", batch_rows=8,
                      batch_buckets=[4, 2, 8]).validate()
    with pytest.raises(ValueError, match="end at batch_rows"):
        ServingConfig(model_dir="m", batch_rows=8,
                      batch_buckets=[2, 4]).validate()
    with pytest.raises(ValueError, match="hot_swap_poll_s"):
        ServingConfig(model_dir="m", hot_swap_poll_s=-1).validate()
    with pytest.raises(ValueError, match="telemetry"):
        ServingConfig(model_dir="m", telemetry="loud").validate()


def test_serving_config_json_round_trip():
    cfg = ServingConfig(model_dir="m", batch_rows=32,
                        batch_buckets=[8, 32], batch_deadline_ms=1.5,
                        dense_feature_shards=["re"],
                        spill_dir="/tmp/x", hot_swap_poll_s=0.5)
    back = serving_config_from_json(config_to_json(cfg))
    assert back == cfg
    with pytest.raises(ValueError, match="unknown config keys"):
        serving_config_from_json(json.dumps(
            {"model_dir": "m", "nope": 1}))


# ---------------------------------------------------------------------------
# model manifest (io/model_io.py satellite)
# ---------------------------------------------------------------------------


def test_model_manifest_round_trip_and_legacy_fallback(tmp_path):
    """save_game_model writes the manifest; load prefers it, falls
    back to the legacy layout, and both decode the same model."""
    model, _ = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    assert (tmp_path / "model" / "model_manifest.npz").exists()

    m1, t1 = model_io.load_game_model(mdir)       # manifest path
    (tmp_path / "model" / "model_manifest.npz").unlink()
    m2, t2 = model_io.load_game_model(mdir)       # legacy path
    assert t1 == t2 == TASK
    for m in (m1, m2):
        np.testing.assert_array_equal(
            np.asarray(m["global"].coefficients.means),
            np.asarray(model["global"].coefficients.means))
        assert m["global"].intercept is True
        np.testing.assert_array_equal(
            np.asarray(m["per_user"].coefficient_blocks[0]),
            np.asarray(model["per_user"].coefficient_blocks[0]))
        np.testing.assert_array_equal(
            m["per_user"].grouping.entity_ids,
            model["per_user"].grouping.entity_ids)


def test_model_manifest_corruption_raises_cleanly(tmp_path):
    model, _ = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    with open(model_io.model_manifest_path(mdir), "wb") as f:
        f.write(b"not a zip at all")
    with pytest.raises(Exception):
        model_io.load_model_manifest(mdir)
    # load_game_model with the corrupt manifest raises too (no silent
    # legacy fallback: a torn swap must be LOUD to the watcher, which
    # owns the keep-previous-model policy).
    with pytest.raises(Exception):
        model_io.load_game_model(mdir)


# ---------------------------------------------------------------------------
# entity store
# ---------------------------------------------------------------------------


def test_entity_store_spilled_lookup_and_window(tmp_path):
    model, _ = _workload()
    re_model = model["per_user"]
    store = EntityServeStore.build(
        "per_user", re_model, str(tmp_path), entity_chunk=3,
        host_max_resident=2)
    assert store.spilled
    ids = np.asarray(re_model.grouping.entity_ids)
    q = np.array([ids[0], ids[-1], 10 ** 9, ids[len(ids) // 2]])
    w, hit, deg = store.lookup(q)
    assert not deg.any()
    assert hit.tolist() == [True, True, False, True]
    assert np.all(w[2] == 0.0)                    # unseen → zeros
    for i, eid in enumerate(q):
        exp = re_model.coefficients_for(int(eid))
        if exp is not None:
            np.testing.assert_array_equal(w[i], exp)
    # The decoded-chunk window stays bounded by host_max_resident.
    for eid in ids:
        store.lookup(np.array([eid]))
    assert store._store.peak_resident <= 2
    # Same model, same dir: the second build reuses every chunk file.
    spills_before = store._store.spills
    store2 = EntityServeStore.build(
        "per_user", re_model, str(tmp_path), entity_chunk=3)
    assert store2._store.spills == 0 and spills_before > 0
    w2, _, _deg2 = store2.lookup(q)
    np.testing.assert_array_equal(w, w2)


def test_entity_store_resident_fallback_without_spill_dir():
    model, _ = _workload()
    re_model = model["per_user"]
    store = EntityServeStore.build("per_user", re_model, None)
    assert not store.spilled
    ids = np.asarray(re_model.grouping.entity_ids)
    w, hit, deg = store.lookup(np.array([ids[3], 10 ** 9]))
    assert not deg.any()
    assert hit.tolist() == [True, False]
    np.testing.assert_array_equal(
        w[0], re_model.coefficients_for(int(ids[3])))


def test_entity_store_rejects_projected_models():
    model, _ = _workload()
    re_model = model["per_user"]
    proj = SubspaceProjection(
        feature_ids=[np.zeros((ne, 2), np.int64)
                     for ne in re_model.grouping.n_entities],
        global_dim=D)
    bad = RandomEffectModel(
        coefficient_blocks=[jnp.zeros((ne, 2))
                            for ne in re_model.grouping.n_entities],
        grouping=re_model.grouping, feature_shard="re",
        projection=proj)
    with pytest.raises(ValueError, match="projected"):
        EntityServeStore.build("p", bad, None)


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


def _engine(model, tmp_path=None, **kw):
    kw.setdefault("ell_row_capacity", 8)
    return ScoringEngine(
        model, TASK, version="v-test",
        spill_dir=(str(tmp_path) if tmp_path is not None else None),
        entity_chunk=4, **kw)


def test_engine_margin_parity_vs_streaming_scorer(tmp_path):
    """THE acceptance criterion: identical rows through the request
    path and the batch path produce identical margins (mixed
    known/unseen entities, offsets, intercept)."""
    model, dataset = _workload()
    ref = _reference_margins(model, dataset)
    eng = _engine(model, tmp_path)
    eng.warm([4, 16])
    reqs = dataset_rows(dataset, 0, N)
    margins = np.empty(N, np.float32)
    preds = np.empty(N, np.float32)
    for lo in range(0, N, 16):
        hi = min(lo + 16, N)
        m, p, _deg = eng.score_batch(eng.parse_rows(reqs[lo:hi]), 16)
        margins[lo:hi], preds[lo:hi] = m, p
    assert float(np.max(np.abs(margins - ref["margins"]))) <= PARITY_TOL
    assert float(np.max(np.abs(preds - ref["predictions"]))) \
        <= PARITY_TOL


def test_engine_projected_random_effect_parity(tmp_path):
    """Projected REs score host-side (merge-join fold into base),
    matching the streaming scorer's fold on the same rows."""
    rng = np.random.default_rng(11)
    n = 48
    cols = rng.integers(0, D, (n, K)).astype(np.int64)
    vals = rng.normal(size=(n, K)).astype(np.float32)
    rows = SparseRows.from_flat(
        np.arange(n + 1, dtype=np.int64) * K, cols.reshape(-1),
        vals.reshape(-1))
    ids = rng.integers(0, 6, n)
    grouping = group_by_entity(ids)
    p_local = 2
    feature_ids = [rng.integers(0, D, (ne, p_local)).astype(np.int64)
                   for ne in grouping.n_entities]
    blocks = [jnp.asarray(rng.normal(0, 0.2, (ne, p_local))
                          .astype(np.float32))
              for ne in grouping.n_entities]
    model = GameModel(models={
        "global": FixedEffectModel(
            coefficients=Coefficients(means=jnp.asarray(
                rng.normal(0, 0.1, D).astype(np.float32))),
            feature_shard="global"),
        "proj_re": RandomEffectModel(
            coefficient_blocks=blocks, grouping=grouping,
            feature_shard="global",
            projection=SubspaceProjection(feature_ids=feature_ids,
                                          global_dim=D),
            entity_key="userId"),
    })
    dataset = GameDataset(labels=np.zeros(n, np.float32),
                          features={"global": rows},
                          entity_ids={"userId": ids},
                          feature_dims={"global": D})
    ref = _reference_margins(model, dataset)
    eng = _engine(model)
    eng.warm([8])
    reqs = dataset_rows(dataset, 0, n)
    margins = np.empty(n, np.float32)
    for lo in range(0, n, 8):
        m, _p, _deg = eng.score_batch(eng.parse_rows(reqs[lo:lo + 8]), 8)
        margins[lo:lo + 8] = m
    assert float(np.max(np.abs(margins - ref["margins"]))) <= 1e-4


def test_engine_zero_compiles_after_warm(tmp_path):
    """Guard-pinned acceptance: after bucket warm-up, a request stream
    over every bucket shape compiles NOTHING."""
    model, dataset = _workload()
    eng = _engine(model, tmp_path)
    buckets = [1, 4, 16]
    eng.warm(buckets)
    reqs = dataset_rows(dataset, 0, 32)
    with count_compiles() as log:
        for b in buckets:
            for lo in range(0, 32 - b, b):
                eng.score_batch(eng.parse_rows(reqs[lo:lo + b]), b)
    assert log.count == 0, log.programs


def test_engine_rejects_bad_requests(tmp_path):
    model, dataset = _workload()
    eng = _engine(model, tmp_path)
    good = dataset_rows(dataset, 0, 1)[0]
    with pytest.raises(BadRequest, match="non-empty list"):
        eng.parse_rows([])
    with pytest.raises(BadRequest, match="unknown feature shard"):
        eng.parse_rows([{"features": {"nope": []},
                         "ids": {"userId": 1}}])
    with pytest.raises(BadRequest, match="missing feature shard"):
        eng.parse_rows([{"features": {"global": good["features"]
                                      ["global"]},
                         "ids": {"userId": 1}}])
    with pytest.raises(BadRequest, match="ell_row_capacity"):
        row = json.loads(json.dumps(good))
        row["features"]["global"] = [[i, 1.0] for i in range(9)]
        eng.parse_rows([row])
    with pytest.raises(BadRequest, match=r"in \[0, 40\)"):
        row = json.loads(json.dumps(good))
        row["features"]["global"] = [[D + 5, 1.0]]
        eng.parse_rows([row])
    with pytest.raises(BadRequest, match="length-3 vector"):
        row = json.loads(json.dumps(good))
        row["features"]["re"] = [1.0, 2.0]
        eng.parse_rows([row])
    with pytest.raises(BadRequest, match="missing entity id"):
        row = json.loads(json.dumps(good))
        row["ids"] = {}
        eng.parse_rows([row])
    with pytest.raises(BadRequest, match="offset"):
        row = json.loads(json.dumps(good))
        row["offset"] = "much"
        eng.parse_rows([row])


# ---------------------------------------------------------------------------
# micro-batcher
# ---------------------------------------------------------------------------


class _FakeEngine:
    """Engine stand-in: echoes row payloads, records dispatch shapes."""

    version = "fake-1"

    def __init__(self, fail=False, delay_s=0.0):
        self.calls: list = []
        self.fail = fail
        self.delay_s = delay_s
        self._lock = threading.Lock()

    def score_batch(self, rows, bucket, trace=None):
        with self._lock:
            self.calls.append((len(rows), bucket))
        if self.fail:
            raise RuntimeError("device on fire")
        if self.delay_s:
            time.sleep(self.delay_s)
        if trace is not None:
            trace.stamp("dispatch", 1e-4)
        vals = np.asarray(rows, np.float32)
        return vals, vals * 2.0, np.zeros(len(rows), bool)


def test_batcher_coalesces_concurrent_requests():
    """Concurrent submits coalesce into shared bucket dispatches and
    every request gets exactly its own rows back."""
    eng = _FakeEngine(delay_s=0.01)
    batcher = MicroBatcher(lambda: eng, [1, 2, 4, 8],
                           deadline_s=0.05, max_queue=64)
    try:
        results: dict = {}

        def client(i):
            rows = [float(i * 10 + j) for j in range(2)]
            m, p, v, _deg = batcher.submit(rows, timeout_s=10.0)
            results[i] = (m.tolist(), p.tolist(), v)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 8
        for i, (m, p, v) in results.items():
            assert m == [i * 10.0, i * 10.0 + 1.0]
            assert p == [i * 20.0, i * 20.0 + 2.0]
            assert v == "fake-1"
        # Every dispatch used a closed-set bucket ≥ its rows; 16 rows
        # in ≤ 8-row buckets means at least two dispatches, and
        # coalescing means fewer than eight.
        assert all(b in (1, 2, 4, 8) and n <= b
                   for n, b in eng.calls)
        assert 2 <= len(eng.calls) < 8
        st = batcher.stats()
        assert st["rows"] == 16 and st["batches"] == len(eng.calls)
    finally:
        batcher.close()


def test_batcher_oversized_request_splits():
    eng = _FakeEngine()
    batcher = MicroBatcher(lambda: eng, [2, 4], deadline_s=0.0)
    try:
        m, p, _, _deg = batcher.submit([float(i) for i in range(11)],
                                 timeout_s=10.0)
        assert m.tolist() == [float(i) for i in range(11)]
        assert all(n <= 4 for n, _b in eng.calls)
    finally:
        batcher.close()


def test_batcher_propagates_engine_errors_and_closes():
    eng = _FakeEngine(fail=True)
    batcher = MicroBatcher(lambda: eng, [4], deadline_s=0.0)
    with pytest.raises(RuntimeError, match="device on fire"):
        batcher.submit([1.0], timeout_s=10.0)
    batcher.close()
    with pytest.raises(ServerClosing):
        batcher.submit([1.0], timeout_s=1.0)


# ---------------------------------------------------------------------------
# model server end to end
# ---------------------------------------------------------------------------


def _serve_cfg(mdir, tmp_path, **kw):
    kw.setdefault("batch_rows", 8)
    kw.setdefault("batch_deadline_ms", 1.0)
    kw.setdefault("ell_row_capacity", 8)
    kw.setdefault("spill_dir", str(tmp_path / "spill"))
    kw.setdefault("entity_chunk", 4)
    kw.setdefault("hot_swap_poll_s", 0.0)
    return ServingConfig(model_dir=mdir, port=0, **kw)


def _get(port, route):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{route}", timeout=10) as r:
        return r.status, r.read().decode()


def _post_score(port, rows):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/score",
        data=json.dumps({"rows": rows}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def test_server_healthz_warming_then_ready(tmp_path):
    """The endpoint answers 503 warming from construction (before the
    model loads) and 200 ready after warm-up — the probe contract."""
    model, _ = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    srv = ModelServer(_serve_cfg(mdir, tmp_path, telemetry="off",
                                 monitor="off"))
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(srv.port, "/healthz")
        assert err.value.code == 503
        assert json.loads(err.value.read().decode())["state"] == \
            "warming"
        # /v1/score during warming is an explicit 503, not a hang.
        with pytest.raises(urllib.error.HTTPError) as err:
            _post_score(srv.port, [{"features": {}}])
        assert err.value.code == 503
        srv.start()
        code, body = _get(srv.port, "/healthz")
        assert code == 200 and json.loads(body)["state"] == "ready"
        # "/" doubles as the probe (the round-15 monitor endpoint's
        # behavior, kept by the shared core).
        code, body = _get(srv.port, "/")
        assert code == 200 and json.loads(body)["ok"] is True
    finally:
        srv.stop()


def test_server_concurrent_clients_parity_and_zero_compiles(tmp_path):
    """N threads hammer /v1/score with mixed known/unseen entities:
    every response matches StreamingGameScorer on the same rows, and
    the warm steady state compiles nothing (guard-pinned)."""
    model, dataset = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    ref = _reference_margins(model, dataset)
    reqs = dataset_rows(dataset, 0, N)
    srv = ModelServer(_serve_cfg(mdir, tmp_path)).start()
    try:
        errors: list = []
        results: dict = {}

        def client(c):
            try:
                for lo in range(c * 16, (c + 1) * 16, 4):
                    out = _post_score(srv.port, reqs[lo:lo + 4])
                    results[lo] = out["margins"]
            except Exception as e:   # noqa: BLE001 - collected
                errors.append(e)

        with count_compiles() as log:
            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert not errors, errors
        assert log.count == 0, log.programs
        got = np.concatenate([np.asarray(results[lo], np.float32)
                              for lo in sorted(results)])
        want = ref["margins"][: len(got)]
        assert float(np.max(np.abs(got - want))) <= PARITY_TOL
        # The instrumented surface saw the storm.
        code, body = _get(srv.port, "/status")
        st = json.loads(body)
        assert st["serving"]["batcher"]["rows"] == N
        assert st["serving"]["model"]["version"]
        code, metrics = _get(srv.port, "/metrics")
        assert "photon_serve_request_s" in metrics
        assert "photon_serve_batches_total" in metrics
    finally:
        srv.stop()


def test_server_hot_swap_under_load_drops_nothing(tmp_path):
    """Sustained client load across a manifest publish: zero failed or
    torn responses, the version flips, and post-swap margins match the
    NEW checkpoint exactly."""
    model, dataset = _workload(scale=1.0)
    model2, _ = _workload(scale=-0.5)    # same structure, new weights
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    ref1 = _reference_margins(model, dataset)["margins"]
    ref2 = _reference_margins(model2, dataset)["margins"]
    reqs = dataset_rows(dataset, 0, 8)
    srv = ModelServer(_serve_cfg(mdir, tmp_path,
                                 hot_swap_poll_s=0.05)).start()
    try:
        stop = threading.Event()
        errors: list = []
        seen: list = []

        def hammer():
            while not stop.is_set():
                try:
                    out = _post_score(srv.port, reqs)
                    m = np.asarray(out["margins"], np.float32)
                    # Every response is EXACTLY one model's scores —
                    # never a torn mix.
                    d1 = float(np.max(np.abs(m - ref1[:8])))
                    d2 = float(np.max(np.abs(m - ref2[:8])))
                    seen.append((out["model_version"],
                                 min(d1, d2) <= PARITY_TOL,
                                 d1 <= PARITY_TOL))
                except Exception as e:   # noqa: BLE001 - collected
                    errors.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        time.sleep(0.05)   # mtime_ns tick vs the first manifest
        model_io.save_game_model(model2, TASK, mdir)   # publish
        deadline = time.time() + 20.0
        while srv.swaps == 0 and time.time() < deadline:
            time.sleep(0.05)
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        assert srv.swaps == 1
        versions = {v for v, _ok, _old in seen}
        assert len(versions) == 2, versions
        assert all(ok for _v, ok, _old in seen)      # no torn response
        assert not seen[-1][2]                       # ends on model2
        # Post-swap requests serve the new checkpoint.
        out = _post_score(srv.port, reqs)
        m = np.asarray(out["margins"], np.float32)
        assert float(np.max(np.abs(m - ref2[:8]))) <= PARITY_TOL
    finally:
        srv.stop()


def test_server_corrupt_manifest_keeps_previous_model(tmp_path):
    """A torn/corrupt publish is recorded as a swap failure and the
    previous good model keeps serving."""
    model, dataset = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    ref = _reference_margins(model, dataset)["margins"]
    reqs = dataset_rows(dataset, 0, 4)
    srv = ModelServer(_serve_cfg(mdir, tmp_path,
                                 hot_swap_poll_s=0.05)).start()
    try:
        v1 = _post_score(srv.port, reqs)["model_version"]
        time.sleep(0.05)
        with open(model_io.model_manifest_path(mdir), "wb") as f:
            f.write(b"torn copy, not a zip")
        deadline = time.time() + 20.0
        while srv.swap_failures == 0 and time.time() < deadline:
            time.sleep(0.05)
        assert srv.swap_failures >= 1
        out = _post_score(srv.port, reqs)
        assert out["model_version"] == v1
        m = np.asarray(out["margins"], np.float32)
        assert float(np.max(np.abs(m - ref[:4]))) <= PARITY_TOL
        st = json.loads(_get(srv.port, "/status")[1])["serving"]
        assert st["swap_failures"] >= 1
        assert "last_swap_error" in st
    finally:
        srv.stop()


def test_serve_tail_latency_fires_through_real_request_path(tmp_path):
    """The alert seam end to end (review finding: rules only evaluate
    from progress(), so the batcher must report it): real requests
    through the real server drive monitor rule evaluation — with a
    floor-level threshold, serve_tail_latency fires without any test
    code touching the monitor."""
    model, dataset = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    tel = telemetry.start("metrics")
    mon = _mon.start(every_s=0.0, thresholds={"serve_p99_s": 1e-9,
                                              "serve_min_requests": 1})
    srv = None
    try:
        srv = ModelServer(_serve_cfg(mdir, tmp_path, telemetry="off",
                                     monitor="off")).start()
        reqs = dataset_rows(dataset, 0, 4)
        for _ in range(3):
            _post_score(srv.port, reqs)
        status = mon.status()
        assert "serve" in status["stages"]          # live progress
        assert status["stages"]["serve"]["unit"] == "rows"
        assert [a["rule"] for a in status["alerts"]] == \
            ["serve_tail_latency"]
    finally:
        if srv is not None:
            srv.stop()
        mon.close()
        tel.close()


def test_server_bad_request_answers_400_not_500(tmp_path):
    model, dataset = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    srv = ModelServer(_serve_cfg(mdir, tmp_path, telemetry="off",
                                 monitor="off")).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _post_score(srv.port, [{"features": {"nope": []}}])
        assert err.value.code == 400
        assert "unknown feature shard" in \
            json.loads(err.value.read().decode())["error"]
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/score",
            data=b"{not json", headers={})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400
    finally:
        srv.stop()


def test_readiness_state_machine():
    r = Readiness()
    code, body = r.healthz()
    assert code == 503 and body["state"] == "warming"
    r.set("ready")
    code, body = r.healthz()
    assert code == 200 and body == {"ok": True, "state": "ready"}
    r.set("stopping", reason="draining")
    code, body = r.healthz()
    assert code == 503 and body["reason"] == "draining"
    with pytest.raises(ValueError, match="readiness state"):
        r.set("on fire")


# ---------------------------------------------------------------------------
# request-path hardening (ISSUE 13): degradation, sheds, fault seams
# ---------------------------------------------------------------------------

from photon_ml_tpu.reliability.faults import (  # noqa: E402
    Fault,
    FaultInjector,
    injected,
)
from photon_ml_tpu.serving.batcher import (  # noqa: E402
    DeadlineExceeded,
    ServerOverloaded,
)
from photon_ml_tpu.serving.http import HttpEndpoint, HttpError  # noqa: E402


def _spilled_store(tmp_path):
    model, _ = _workload()
    re_model = model["per_user"]
    store = EntityServeStore.build(
        "per_user", re_model, str(tmp_path), entity_chunk=3)
    assert store.spilled
    return store, re_model


def test_entity_store_slow_fault_only_slows(tmp_path):
    """A slow store read (injected at the serve.store_load seam) is
    latency, not failure: full-fidelity rows, no degradation."""
    store, re_model = _spilled_store(tmp_path)
    ids = np.asarray(re_model.grouping.entity_ids)
    inj = FaultInjector([Fault(site="serve.store_load", kind="slow",
                               at=0, count=2, delay_s=0.01)])
    with injected(inj):
        w, hit, deg = store.lookup(np.array([ids[0]]))
    assert not deg.any() and hit.tolist() == [True]
    np.testing.assert_array_equal(
        w[0], re_model.coefficients_for(int(ids[0])))
    assert inj.fired and inj.fired[0][1] == "slow"


def test_entity_store_transient_error_retries_not_degrades(tmp_path):
    """One transient EIO retries through reliability.retry and serves
    full fidelity — pinned counters: 1 retry, 0 degraded."""
    store, re_model = _spilled_store(tmp_path)
    ids = np.asarray(re_model.grouping.entity_ids)
    tel = telemetry.start("metrics")
    try:
        inj = FaultInjector([Fault(site="serve.store_load",
                                   kind="io_error", at=0, count=1)])
        with injected(inj):
            w, hit, deg = store.lookup(np.array([ids[0]]))
        assert not deg.any() and hit.tolist() == [True]
        np.testing.assert_array_equal(
            w[0], re_model.coefficients_for(int(ids[0])))
        assert tel.counter("serve.store_retries") == 1
        assert tel.counter("serve.store_gave_up") == 0
        assert tel.counter("serve.store_degraded") == 0
    finally:
        tel.close()


def test_entity_store_persistent_failure_degrades_then_recovers(
        tmp_path):
    """A persistently unreadable chunk exhausts its retry budget and
    DEGRADES: the affected rows serve zeros (fixed-effect-only), the
    lookup reports degraded, and the store recovers on the next lookup
    once the fault clears — pinned counters throughout."""
    store, re_model = _spilled_store(tmp_path)
    ids = np.asarray(re_model.grouping.entity_ids)
    q = np.array([ids[0]])
    tel = telemetry.start("metrics")
    try:
        inj = FaultInjector([Fault(site="serve.store_load",
                                   kind="io_error", at=0, count=99)])
        with injected(inj):
            w, hit, deg = store.lookup(q)
        assert deg.tolist() == [True]
        assert hit.tolist() == [True]      # the entity IS in the model
        assert np.all(w == 0.0)            # ...but served as fallback
        assert store.degraded_lookups == 1
        assert tel.counter("serve.store_degraded") == 1
        assert tel.counter("serve.store_gave_up") == 1
        # Fault cleared: the SAME store serves full fidelity again —
        # degradation is per-lookup, never latched.
        w2, hit2, deg2 = store.lookup(q)
        assert not deg2.any()
        np.testing.assert_array_equal(
            w2[0], re_model.coefficients_for(int(ids[0])))
    finally:
        tel.close()


def test_engine_degraded_margins_equal_fixed_effect_only(tmp_path):
    """Degraded scoring IS fixed-effect-only scoring: margins under a
    dead entity store equal margins for the same rows with all-unseen
    entity ids (the tested fallback semantics)."""
    model, dataset = _workload()
    eng = _engine(model, tmp_path)
    eng.warm([8])
    reqs = dataset_rows(dataset, 0, 8)
    unseen = json.loads(json.dumps(reqs))
    for i, r in enumerate(unseen):
        r["ids"]["userId"] = 2 * 10 ** 9 + i
    m_ref, _p, deg_ref = eng.score_batch(eng.parse_rows(unseen), 8)
    assert not deg_ref.any()
    inj = FaultInjector([Fault(site="serve.store_load",
                               kind="io_error", at=0, count=999)])
    with injected(inj):
        m_deg, _p, deg = eng.score_batch(eng.parse_rows(reqs), 8)
    assert deg.any()
    assert float(np.max(np.abs(m_deg - m_ref))) <= PARITY_TOL


def test_server_degraded_response_field_and_counter(tmp_path):
    """End to end: a dead entity store yields 200 + degraded:true (not
    a 500), with serve.degraded_responses counted."""
    model, dataset = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    tel = telemetry.start("metrics")
    srv = None
    try:
        srv = ModelServer(_serve_cfg(mdir, tmp_path,
                                     monitor="off")).start()
        reqs = dataset_rows(dataset, 0, 4)
        out = _post_score(srv.port, reqs)
        assert "degraded" not in out
        inj = FaultInjector([Fault(site="serve.store_load",
                                   kind="io_error", at=0, count=999)])
        with injected(inj):
            out = _post_score(srv.port, reqs)
        assert out["degraded"] is True
        assert len(out["margins"]) == 4
        assert tel.counter("serve.degraded_responses") == 1
    finally:
        if srv is not None:
            srv.stop()
        tel.close()


def test_engine_dispatch_fault_answers_500_not_hang(tmp_path):
    """An injected engine-dispatch failure maps to an answered error
    for every request in the batch — never a hang, never a torn
    response."""
    model, dataset = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    srv = ModelServer(_serve_cfg(mdir, tmp_path, telemetry="off",
                                 monitor="off")).start()
    try:
        reqs = dataset_rows(dataset, 0, 2)
        inj = FaultInjector([Fault(site="serve.dispatch",
                                   kind="error", at=0, count=1)])
        with injected(inj):
            with pytest.raises(urllib.error.HTTPError) as err:
                _post_score(srv.port, reqs)
        assert err.value.code == 500
        assert "injected fault" in \
            json.loads(err.value.read().decode())["error"]
        # The server survives: the next batch scores normally.
        out = _post_score(srv.port, reqs)
        assert len(out["margins"]) == 2
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# batcher overload shedding
# ---------------------------------------------------------------------------


class _GatedEngine:
    """Engine whose dispatch blocks until released (deterministic
    queue buildup)."""

    version = "gated-1"

    def __init__(self):
        self.gate = threading.Event()
        self.calls = 0

    def score_batch(self, rows, bucket):
        self.calls += 1
        assert self.gate.wait(30.0), "test gate never released"
        vals = np.asarray(rows, np.float32)
        return vals, vals * 2.0, np.zeros(len(rows), bool)


def test_batcher_admission_control_sheds_with_retry_after():
    """Once the rolling service estimate exists, a request whose
    deadline budget the queue cannot meet is shed IMMEDIATELY with
    ServerOverloaded (503 + Retry-After), pinned counters."""
    eng = _FakeEngine(delay_s=0.2)
    tel = telemetry.start("metrics")
    batcher = MicroBatcher(lambda: eng, [4], deadline_s=0.0)
    try:
        batcher.submit([1.0], timeout_s=10.0)   # primes the EWMA
        with pytest.raises(ServerOverloaded) as err:
            batcher.submit([2.0], timeout_s=0.01)
        assert err.value.retry_after_s >= 1.0
        assert tel.counter("serve.shed") == 1
        assert tel.counter("serve.shed_overload") == 1
        assert batcher.stats()["shed"] == 1
        # A request with a sane budget is still admitted and served.
        m, _p, _v, _deg = batcher.submit([3.0], timeout_s=10.0)
        assert m.tolist() == [3.0]
    finally:
        batcher.close()
        tel.close()


def test_batcher_expires_queued_requests_past_deadline():
    """A slot whose deadline passes while queued behind a slow batch
    fails with DeadlineExceeded (503) instead of wasting device time —
    the batcher clock is faked, so the expiry is deterministic."""
    eng = _GatedEngine()
    t = [0.0]
    tel = telemetry.start("metrics")
    batcher = MicroBatcher(lambda: eng, [1], deadline_s=0.0,
                           clock=lambda: t[0])
    results: dict = {}

    def client(name, timeout_s):
        try:
            results[name] = batcher.submit([1.0], timeout_s=timeout_s)
        except BaseException as e:  # noqa: BLE001 - recorded
            results[name] = e

    try:
        a = threading.Thread(target=client, args=("a", 60.0))
        a.start()
        deadline = time.time() + 10.0
        while eng.calls == 0 and time.time() < deadline:
            time.sleep(0.005)              # A is on the device (gated)
        b = threading.Thread(target=client, args=("b", 5.0))
        b.start()
        while batcher._q.qsize() == 0 and time.time() < deadline:
            time.sleep(0.005)              # B is queued
        t[0] = 100.0                       # B's deadline (t=5) passes
        eng.gate.set()                     # A completes; B pops expired
        a.join(timeout=30)
        b.join(timeout=30)
        assert results["a"][0].tolist() == [1.0]
        assert isinstance(results["b"], DeadlineExceeded)
        assert tel.counter("serve.shed") == 1
        assert tel.counter("serve.shed_deadline") == 1
        assert eng.calls == 1              # B never reached the device
    finally:
        eng.gate.set()
        batcher.close()
        tel.close()


# ---------------------------------------------------------------------------
# HTTP core hardening
# ---------------------------------------------------------------------------


def test_http_endpoint_bounds_body_size():
    ep = HttpEndpoint({("POST", "/echo"):
                       lambda b: (200, "ok", "text/plain")},
                      max_body=64)
    ep.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{ep.port}/echo", data=b"x" * 100)
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 413
        assert json.loads(err.value.read().decode())["max_bytes"] == 64
    finally:
        ep.close()


def test_http_endpoint_disconnects_stalled_client():
    """A client that declares a body and never sends it is
    disconnected at the per-connection socket timeout instead of
    pinning a handler thread forever."""
    import socket as socket_mod

    ep = HttpEndpoint({("POST", "/echo"):
                       lambda b: (200, "ok", "text/plain")},
                      request_timeout_s=0.5)
    ep.start()
    try:
        s = socket_mod.create_connection(("127.0.0.1", ep.port),
                                         timeout=10)
        try:
            s.sendall(b"POST /echo HTTP/1.1\r\n"
                      b"Host: x\r\nContent-Length: 10\r\n\r\n")
            # ...and stall.  The server must close the connection.
            s.settimeout(10.0)
            t0 = time.monotonic()
            data = s.recv(4096)
            elapsed = time.monotonic() - t0
            assert data == b""             # closed, no response
            assert elapsed < 8.0           # within ~the socket timeout
        finally:
            s.close()
    finally:
        ep.close()


def test_http_error_headers_ride_the_response():
    def shedding_route(body):
        raise HttpError(503, headers={"Retry-After": "7"},
                        error="overloaded")

    ep = HttpEndpoint({("GET", "/shed"): shedding_route,
                       ("GET", "/four"): lambda b: (
                           200, "ok", "text/plain",
                           {"X-Extra": "1"})})
    ep.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{ep.port}/shed", timeout=10)
        assert err.value.code == 503
        assert err.value.headers.get("Retry-After") == "7"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{ep.port}/four", timeout=10) as r:
            assert r.headers.get("X-Extra") == "1"
    finally:
        ep.close()


def test_swap_manifest_fault_seam_keeps_previous_model(tmp_path):
    """The serve.manifest_load fault seam: a corrupt_file fault fired
    at the watcher's load corrupts the REAL manifest on disk — the
    swap fails, the previous good model keeps serving, and the next
    clean publish swaps normally (full recovery, pinned counters)."""
    model, dataset = _workload(scale=1.0)
    model2, _ = _workload(scale=-0.5)
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TASK, mdir)
    reqs = dataset_rows(dataset, 0, 4)
    srv = ModelServer(_serve_cfg(mdir, tmp_path, telemetry="off",
                                 monitor="off",
                                 hot_swap_poll_s=0.05)).start()
    try:
        v1 = _post_score(srv.port, reqs)["model_version"]
        inj = FaultInjector([Fault(site="serve.manifest_load",
                                   kind="corrupt_file", at=0,
                                   count=1)])
        with injected(inj):
            time.sleep(0.05)
            model_io.save_game_model(model2, TASK, mdir)   # publish
            deadline = time.time() + 20.0
            while srv.swap_failures == 0 and time.time() < deadline:
                time.sleep(0.05)
        assert srv.swap_failures == 1
        assert inj.fired == [("serve.manifest_load", "corrupt_file", 0)]
        assert _post_score(srv.port, reqs)["model_version"] == v1
        # Recovery: a clean re-publish swaps normally.
        time.sleep(0.05)
        model_io.save_game_model(model2, TASK, mdir)
        deadline = time.time() + 20.0
        while srv.swaps == 0 and time.time() < deadline:
            time.sleep(0.05)
        assert srv.swaps == 1
        assert _post_score(srv.port, reqs)["model_version"] != v1
    finally:
        srv.stop()


def test_batcher_overload_bounded_admitted_tail():
    """The overload acceptance shape: offered load far above capacity
    produces SOME admitted requests with a bounded tail and the excess
    shed — not a queue-collapse where everyone times out slowly."""
    eng = _FakeEngine(delay_s=0.05)      # capacity ≈ 80 rows/s
    batcher = MicroBatcher(lambda: eng, [4], deadline_s=0.0)
    results = {"ok": 0, "shed": 0, "other": [], "lat": []}
    lock = threading.Lock()
    try:
        batcher.submit([0.0], timeout_s=10.0)     # primes the EWMA

        def client(i):
            t0 = time.perf_counter()
            try:
                batcher.submit([float(i)], timeout_s=0.3)
                with lock:
                    results["ok"] += 1
                    results["lat"].append(time.perf_counter() - t0)
            except (ServerOverloaded, DeadlineExceeded,
                    TimeoutError):
                with lock:
                    results["shed"] += 1
            except BaseException as e:  # noqa: BLE001 - recorded
                with lock:
                    results["other"].append(repr(e))

        # 40 rows offered at once against ~0.3 s of budget ≈ 4x over
        # capacity.
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(40)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not results["other"], results["other"]
        assert results["ok"] > 0                  # admitted work flows
        assert results["shed"] > 0                # the excess is shed
        # Admitted requests kept a bounded tail: nobody rode a
        # collapsed queue for seconds.
        assert max(results["lat"]) < 1.0, results["lat"]
    finally:
        batcher.close()


def test_degraded_marks_only_affected_rows_in_shared_batch(tmp_path):
    """Per-row degraded attribution (review finding): a batch mixing
    rows from a healthy chunk and an unreadable chunk marks ONLY the
    affected rows — a co-batched healthy request is not falsely
    labeled degraded."""
    store, re_model = _spilled_store(tmp_path)      # entity_chunk=3
    ids = np.asarray(re_model.grouping.entity_ids)
    # One id from chunk 0, one from the last chunk.
    q = np.array([ids[0], ids[-1]])
    # run_with_retries makes 3 attempts per chunk: occurrences 0-2 are
    # the FIRST chunk's reads (all fail → degrade), occurrence 3+ the
    # second chunk's (succeed).
    inj = FaultInjector([Fault(site="serve.store_load",
                               kind="io_error", at=0, count=3)])
    with injected(inj):
        w, hit, deg = store.lookup(q)
    assert hit.tolist() == [True, True]
    assert deg.tolist() == [True, False]
    assert np.all(w[0] == 0.0)                      # degraded row
    np.testing.assert_array_equal(                  # healthy row
        w[1], re_model.coefficients_for(int(ids[-1])))
