"""Optimizer tests: convergence to sklearn/scipy/closed-form optima.

Mirrors the reference's optimizer unit tests (LBFGS/OWLQN/TRON on convex
toy problems with known minima, SURVEY.md §4 tier 1) plus the rebuild's
extra obligation: the same solver must converge per-problem under vmap
(the random-effect prerequisite, SURVEY.md §7 "masked while_loop").
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize
from sklearn.linear_model import LogisticRegression

from photon_ml_tpu.data.batch import make_dense_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim import (
    OptimizationProblem,
    OptimizerConfig,
    OptimizerType,
    lbfgs_solve,
    owlqn_solve,
    tron_solve,
)


def _logistic_problem(rng, n=200, d=8, l2=1.0):
    x = rng.normal(0, 1, (n, d))
    w_true = rng.normal(0, 1, d)
    p = 1 / (1 + np.exp(-(x @ w_true)))
    y = (rng.uniform(size=n) < p).astype(np.float64)
    batch = make_dense_batch(x, y)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(l2),
        norm=NormalizationContext.identity(),
    )
    return x, y, batch, obj


def _sklearn_logistic(x, y, l2):
    # sklearn minimizes C·Σℓ + ½‖w‖² ⇔ ours (Σℓ + ½λ‖w‖²) with C = 1/λ.
    clf = LogisticRegression(
        C=1.0 / l2, fit_intercept=False, tol=1e-10, max_iter=10000
    )
    clf.fit(x, y)
    return clf.coef_.ravel()


CFG = OptimizerConfig(max_iters=200, tolerance=1e-5)


def test_lbfgs_logistic_matches_sklearn(rng):
    x, y, batch, obj = _logistic_problem(rng)
    res = lbfgs_solve(
        lambda w: obj.value_and_gradient(w, batch),
        jnp.zeros(x.shape[1], jnp.float32),
        CFG,
    )
    assert bool(res.converged)
    np.testing.assert_allclose(res.w, _sklearn_logistic(x, y, 1.0),
                               rtol=2e-3, atol=2e-4)


def test_tron_logistic_matches_sklearn(rng):
    x, y, batch, obj = _logistic_problem(rng)
    res = tron_solve(
        lambda w: obj.value_and_gradient(w, batch),
        lambda w, v: obj.hessian_vector(w, v, batch),
        jnp.zeros(x.shape[1], jnp.float32),
        CFG,
    )
    assert bool(res.converged)
    np.testing.assert_allclose(res.w, _sklearn_logistic(x, y, 1.0),
                               rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("opt", [OptimizerType.LBFGS, OptimizerType.TRON])
def test_ridge_matches_closed_form(rng, opt):
    n, d, l2 = 300, 10, 2.5
    x = rng.normal(0, 1, (n, d))
    y = x @ rng.normal(0, 1, d) + rng.normal(0, 0.1, n)
    batch = make_dense_batch(x, y)
    obj = GLMObjective(
        loss=losses.SQUARED,
        reg=RegularizationContext.l2(l2),
        norm=NormalizationContext.identity(),
    )
    problem = OptimizationProblem(objective=obj, optimizer=opt, config=CFG)
    res = jax.jit(problem.run)(batch, jnp.zeros(d, jnp.float32))
    w_ref = np.linalg.solve(x.T @ x + l2 * np.eye(d), x.T @ y)
    assert bool(res.converged)
    np.testing.assert_allclose(res.w, w_ref, rtol=1e-4, atol=1e-5)


def test_poisson_matches_scipy(rng):
    n, d, l2 = 250, 6, 0.5
    x = rng.normal(0, 0.5, (n, d))
    lam = np.exp(x @ rng.normal(0, 0.5, d))
    y = rng.poisson(lam).astype(np.float64)
    batch = make_dense_batch(x, y)
    obj = GLMObjective(
        loss=losses.POISSON,
        reg=RegularizationContext.l2(l2),
        norm=NormalizationContext.identity(),
    )

    def np_obj(w):
        z = x @ w
        return np.sum(np.exp(z) - y * z) + 0.5 * l2 * np.sum(w * w)

    ref = scipy.optimize.minimize(np_obj, np.zeros(d), method="L-BFGS-B",
                                  tol=1e-12).x
    for solve in (
        lambda: lbfgs_solve(lambda w: obj.value_and_gradient(w, batch),
                            jnp.zeros(d, jnp.float32), CFG),
        lambda: tron_solve(lambda w: obj.value_and_gradient(w, batch),
                           lambda w, v: obj.hessian_vector(w, v, batch),
                           jnp.zeros(d, jnp.float32), CFG),
    ):
        res = solve()
        assert bool(res.converged)
        np.testing.assert_allclose(res.w, ref, rtol=1e-3, atol=1e-4)


def test_owlqn_l1_logistic_matches_sklearn(rng):
    n, d, l1 = 400, 12, 3.0
    x = rng.normal(0, 1, (n, d))
    w_true = np.zeros(d)
    w_true[:3] = [2.0, -1.5, 1.0]  # sparse ground truth
    p = 1 / (1 + np.exp(-(x @ w_true)))
    y = (rng.uniform(size=n) < p).astype(np.float64)
    batch = make_dense_batch(x, y)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.none(),  # L1 passed to the solver
        norm=NormalizationContext.identity(),
    )
    res = owlqn_solve(
        lambda w: obj.value_and_gradient(w, batch),
        jnp.zeros(d, jnp.float32),
        l1_weight=jnp.asarray(l1, jnp.float32),
        config=OptimizerConfig(max_iters=500, tolerance=1e-7),
    )
    clf = LogisticRegression(
        penalty="l1", C=1.0 / l1, solver="liblinear", fit_intercept=False,
        tol=1e-10, max_iter=10000,
    )
    clf.fit(x, y)
    w_ref = clf.coef_.ravel()
    np.testing.assert_allclose(res.w, w_ref, rtol=5e-2, atol=5e-3)
    # OWL-QN must produce exact zeros where sklearn does.
    assert np.all((np.abs(np.asarray(res.w)) < 1e-6) == (np.abs(w_ref) < 1e-6))


def test_elastic_net_poisson_via_problem(rng):
    """BASELINE config 3 shape: Poisson + elastic net through the problem API."""
    n, d = 300, 8
    x = rng.normal(0, 0.4, (n, d))
    lam = np.exp(x @ rng.normal(0, 0.5, d))
    y = rng.poisson(lam).astype(np.float64)
    batch = make_dense_batch(x, y)
    weight, alpha = 2.0, 0.5
    obj = GLMObjective(
        loss=losses.POISSON,
        reg=RegularizationContext.elastic_net(weight, alpha),
        norm=NormalizationContext.identity(),
    )
    problem = OptimizationProblem(
        objective=obj, optimizer=OptimizerType.LBFGS,
        config=OptimizerConfig(max_iters=500, tolerance=1e-6),
    )
    res = problem.run(batch, jnp.zeros(d, jnp.float32))

    l1_w, l2_w = alpha * weight, (1 - alpha) * weight

    def np_obj(w):
        z = x @ w
        return (np.sum(np.exp(z) - y * z) + 0.5 * l2_w * np.sum(w * w)
                + l1_w * np.sum(np.abs(w)))

    # scipy can't do L1 directly; check optimality by subgradient: for
    # nonzero coords grad_smooth + l1·sign(w) ≈ 0, for zeros |grad| ≤ l1.
    w = np.asarray(res.w, np.float64)
    z = x @ w
    g = x.T @ (np.exp(z) - y) + l2_w * w
    nz = np.abs(w) > 1e-6
    np.testing.assert_allclose(g[nz] + l1_w * np.sign(w[nz]), 0, atol=5e-3)
    assert np.all(np.abs(g[~nz]) <= l1_w + 5e-3)
    # And beats the zero vector.
    assert np_obj(w) < np_obj(np.zeros(d))


def test_vmap_per_problem_convergence(rng):
    """≥100 independent problems under one vmap, each at its own optimum."""
    B, n, d, l2 = 128, 40, 5, 0.3
    xs = rng.normal(0, 1, (B, n, d))
    ws = rng.normal(0, 1, (B, d))
    ps = 1 / (1 + np.exp(-np.einsum("bnd,bd->bn", xs, ws)))
    ys = (rng.uniform(size=(B, n)) < ps).astype(np.float64)

    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(l2),
        norm=NormalizationContext.identity(),
    )
    cfg = OptimizerConfig(max_iters=150, tolerance=1e-6, track_states=False)

    def solve_one(x, y):
        batch = jax.tree.map(jnp.asarray, _as_batch(x, y))
        return lbfgs_solve(
            lambda w: obj.value_and_gradient(w, batch),
            jnp.zeros(d, jnp.float32), cfg,
        )

    def _as_batch(x, y):
        from photon_ml_tpu.data.batch import DenseBatch
        n_ = x.shape[0]
        return DenseBatch(
            x=x.astype(jnp.float32), labels=y.astype(jnp.float32),
            weights=jnp.ones(n_, jnp.float32),
            offsets=jnp.zeros(n_, jnp.float32),
            mask=jnp.ones(n_, jnp.float32),
        )

    res = jax.jit(jax.vmap(solve_one))(
        jnp.asarray(xs, jnp.float32), jnp.asarray(ys, jnp.float32)
    )
    assert bool(jnp.all(res.converged))
    # Iteration counts must differ across lanes (per-lane convergence, not
    # run-to-max): with 128 random problems identical counts would mean the
    # masked-while semantics are broken.
    assert len(np.unique(np.asarray(res.iterations))) > 1

    for b in range(0, B, 17):  # spot-check lanes against sklearn
        w_ref = _sklearn_logistic(xs[b], ys[b], l2)
        np.testing.assert_allclose(res.w[b], w_ref, rtol=5e-3, atol=1e-3)


def test_tron_vmap_converges(rng):
    B, n, d = 64, 30, 4
    xs = rng.normal(0, 1, (B, n, d))
    ys = (rng.uniform(size=(B, n)) < 0.5).astype(np.float64)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(1.0),
        norm=NormalizationContext.identity(),
    )
    cfg = OptimizerConfig(max_iters=100, tolerance=1e-6, track_states=False)

    from photon_ml_tpu.data.batch import DenseBatch

    def solve_one(x, y):
        n_ = x.shape[0]
        batch = DenseBatch(
            x=x, labels=y, weights=jnp.ones(n_, jnp.float32),
            offsets=jnp.zeros(n_, jnp.float32),
            mask=jnp.ones(n_, jnp.float32),
        )
        return tron_solve(
            lambda w: obj.value_and_gradient(w, batch),
            lambda w, v: obj.hessian_vector(w, v, batch),
            jnp.zeros(d, jnp.float32), cfg,
        )

    res = jax.jit(jax.vmap(solve_one))(
        jnp.asarray(xs, jnp.float32), jnp.asarray(ys, jnp.float32)
    )
    assert bool(jnp.all(res.converged))
    w_ref = _sklearn_logistic(xs[0], ys[0], 1.0)
    np.testing.assert_allclose(res.w[0], w_ref, rtol=5e-3, atol=1e-3)


def test_tracker_records_monotone_history(rng):
    x, y, batch, obj = _logistic_problem(rng)
    res = lbfgs_solve(
        lambda w: obj.value_and_gradient(w, batch),
        jnp.zeros(x.shape[1], jnp.float32),
        OptimizerConfig(max_iters=50, tolerance=1e-6),
    )
    k = int(res.tracker.count)
    vals = np.asarray(res.tracker.values)[:k]
    assert k == int(res.iterations) + 1
    assert np.all(np.isfinite(vals))
    assert np.all(np.diff(vals) <= 1e-6)  # non-increasing loss
    assert np.all(np.isnan(np.asarray(res.tracker.values)[k:]))


def test_tracker_records_step_sizes_and_trials(rng):
    """ISSUE 8: the tracker's per-iteration step-size and line-search
    trial planes are populated by both resident solvers (TRON records
    the step norm and inner-CG iteration count)."""
    x, y, batch, obj = _logistic_problem(rng)
    w0 = jnp.zeros(x.shape[1], jnp.float32)
    cfg = OptimizerConfig(max_iters=50, tolerance=1e-6)
    res = lbfgs_solve(lambda w: obj.value_and_gradient(w, batch), w0, cfg)
    k = int(res.tracker.count)
    assert k >= 2
    steps = np.asarray(res.tracker.step_sizes)
    trials = np.asarray(res.tracker.ls_trials)
    # Slot 0 is the initial point: no step taken there.
    assert np.isnan(steps[0]) and np.isnan(trials[0])
    assert np.all(np.isfinite(steps[1:k])) and np.all(steps[1:k] >= 0)
    assert np.all(trials[1:k] >= 1)
    # Accepted α=1 full steps dominate a well-conditioned logistic fit.
    assert np.any(steps[1:k] == 1.0)

    res_t = tron_solve(
        lambda w: obj.value_and_gradient(w, batch),
        lambda w, v: obj.hessian_vector(w, v, batch), w0, cfg)
    kt = int(res_t.tracker.count)
    steps_t = np.asarray(res_t.tracker.step_sizes)[1:kt]
    cg_t = np.asarray(res_t.tracker.ls_trials)[1:kt]
    assert np.all(np.isfinite(steps_t)) and np.all(steps_t >= 0)
    assert np.all(cg_t >= 1)              # every outer iter paid CG work


def test_tron_rejects_l1():
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l1(0.5),
        norm=NormalizationContext.identity(),
    )
    problem = OptimizationProblem(objective=obj, optimizer=OptimizerType.TRON)
    batch = make_dense_batch(np.zeros((2, 3)), np.zeros(2))
    with pytest.raises(ValueError, match="smooth"):
        problem.run(batch, jnp.zeros(3, jnp.float32))


def test_weighted_examples_shift_solution(rng):
    """Example weights must act as replication (reference weight semantics)."""
    n, d = 100, 4
    x = rng.normal(0, 1, (n, d))
    y = (rng.uniform(size=n) < 0.5).astype(np.float64)
    w3 = np.ones(n)
    w3[: n // 2] = 3.0
    batch_w = make_dense_batch(x, y, weights=w3)
    x_rep = np.concatenate([x[: n // 2]] * 3 + [x[n // 2:]])
    y_rep = np.concatenate([y[: n // 2]] * 3 + [y[n // 2:]])
    batch_rep = make_dense_batch(x_rep, y_rep)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(1.0),
        norm=NormalizationContext.identity(),
    )
    r1 = lbfgs_solve(lambda w: obj.value_and_gradient(w, batch_w),
                     jnp.zeros(d, jnp.float32), CFG)
    r2 = lbfgs_solve(lambda w: obj.value_and_gradient(w, batch_rep),
                     jnp.zeros(d, jnp.float32), CFG)
    np.testing.assert_allclose(r1.w, r2.w, atol=1e-3)


def test_boundary_tau_nonnegative_at_f32_boundary_crossing():
    """ISSUE 17 hardening: when ‖p‖ crosses Δ by one f32 rounding step
    (gap = Δ² − ‖p‖² negative by an ulp) while p·d > 0, the textbook
    root (−p·d + √disc)/(d·d) cancels catastrophically and returns a
    small NEGATIVE τ — a backward step that "exits" the trust region
    from inside while the CG loop reports a boundary hit.  The
    conjugate-root form plus the final clamp must return τ ≥ 0 with no
    NaN."""
    from photon_ml_tpu.optim.tron import _boundary_tau

    delta = jnp.float32(1.0)
    p = jnp.asarray([1.0 + 1.2e-7, 0.0], jnp.float32)  # ‖p‖ > Δ by ~1 ulp
    d = jnp.asarray([1.0, 1e-4], jnp.float32)          # p·d > 0
    tau = float(_boundary_tau(p, d, delta))
    assert np.isfinite(tau)
    assert tau >= 0.0
    assert tau < 1e-6   # the true root is within rounding of zero


def test_boundary_tau_roots_and_degenerate_direction():
    """Both quadratic branches return the exact boundary crossing, and
    a zero direction (the d·d floor) stays finite and non-negative."""
    from photon_ml_tpu.optim.tron import _boundary_tau

    delta = jnp.float32(1.0)
    # Forward crossing from inside (p·d > 0): 0.5 + τ = 1 → τ = 0.5.
    tau = float(_boundary_tau(jnp.asarray([0.5, 0.0], jnp.float32),
                              jnp.asarray([1.0, 0.0], jnp.float32),
                              delta))
    np.testing.assert_allclose(tau, 0.5, rtol=1e-6)
    # Backward direction (p·d < 0): 0.5 − τ = −1 → τ = 1.5.
    tau = float(_boundary_tau(jnp.asarray([0.5, 0.0], jnp.float32),
                              jnp.asarray([-1.0, 0.0], jnp.float32),
                              delta))
    np.testing.assert_allclose(tau, 1.5, rtol=1e-6)
    tau = float(_boundary_tau(jnp.asarray([0.5, 0.0], jnp.float32),
                              jnp.zeros(2, jnp.float32), delta))
    assert np.isfinite(tau) and tau >= 0.0
