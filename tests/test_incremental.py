"""Warm start / priors / partial retraining / variance / checkpointing.

Reference coverage class: incremental-training and variance tests of
``GameEstimator``/``GeneralizedLinearOptimizationProblem`` (SURVEY.md
§2.1 variance computation, §2.2 priors, §5.4 warm start / partial
retraining / checkpointing).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.config import (
    CoordinateConfig,
    CoordinateKind,
    OptimizerSettings,
    TrainingConfig,
)
from photon_ml_tpu.data.batch import make_dense_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.estimators import GameEstimator
from photon_ml_tpu.evaluation.evaluators import EvaluatorType
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.io.model_io import load_game_model, save_game_model
from photon_ml_tpu.models.glm import TaskType
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.prior import GaussianPrior
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.variance import (
    VarianceComputationType,
    compute_variances,
    full_variances,
    materialize_hessian,
    simple_variances,
)
from photon_ml_tpu.utils.checkpoint import (
    load_latest_checkpoint,
    save_checkpoint,
)
from photon_ml_tpu.utils.synthetic import make_movielens_like


def _logistic_problem(n=200, d=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    p = 1 / (1 + np.exp(-(x @ w_true)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return make_dense_batch(x, y)


# ---------------------------------------------------------------------------
# Gaussian prior: objective consistency
# ---------------------------------------------------------------------------

def test_prior_value_gradient_hvp_consistency():
    batch = _logistic_problem()
    rng = np.random.default_rng(1)
    mu = jnp.asarray(rng.normal(size=5).astype(np.float32))
    var = jnp.asarray(rng.uniform(0.5, 2.0, size=5).astype(np.float32))
    obj = GLMObjective(
        loss=get_loss("LOGISTIC_REGRESSION"),
        reg=RegularizationContext.l2(0.3),
        norm=NormalizationContext.identity(),
        prior=GaussianPrior.from_model(mu, var, weight=1.7),
    )
    w = jnp.asarray(rng.normal(size=5).astype(np.float32))

    # value/gradient agree with autodiff of value
    val, grad = obj.value_and_gradient(w, batch)
    assert np.isclose(float(val), float(obj.value(w, batch)), rtol=1e-6)
    grad_ad = jax.grad(lambda ww: obj.value(ww, batch))(w)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(grad_ad),
                               rtol=1e-4, atol=1e-4)

    # prior raises the objective away from mu and pulls the optimum
    obj0 = obj.replace(prior=None)
    assert float(obj.value(mu, batch)) < float(obj.value(mu + 1.0, batch))
    assert float(obj.value(w, batch)) > float(obj0.value(w, batch))

    # HVP includes the prior precision (diagonal quadratic)
    v = jnp.ones(5)
    hvp = obj.hessian_vector(w, v, batch)
    hvp0 = obj0.hessian_vector(w, v, batch)
    np.testing.assert_allclose(
        np.asarray(hvp - hvp0), np.asarray(1.7 / var), rtol=1e-5
    )
    # Hessian diagonal too
    hd = obj.hessian_diagonal(w, batch) - obj0.hessian_diagonal(w, batch)
    np.testing.assert_allclose(np.asarray(hd), np.asarray(1.7 / var),
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# Variance computation
# ---------------------------------------------------------------------------

def test_variances_against_materialized_hessian():
    batch = _logistic_problem(n=300, d=4, seed=2)
    obj = GLMObjective(
        loss=get_loss("LOGISTIC_REGRESSION"),
        reg=RegularizationContext.l2(0.5),
        norm=NormalizationContext.identity(),
    )
    w = jnp.asarray(np.random.default_rng(3).normal(size=4), jnp.float32)

    h = np.asarray(materialize_hessian(obj, w, batch))
    # Hessian is symmetric and PD for logistic + L2
    np.testing.assert_allclose(h, h.T, rtol=1e-4, atol=1e-5)

    v_simple = np.asarray(simple_variances(obj, w, batch))
    np.testing.assert_allclose(v_simple, 1.0 / np.diag(h), rtol=1e-4)

    v_full = np.asarray(full_variances(obj, w, batch))
    np.testing.assert_allclose(v_full, np.diag(np.linalg.inv(h)), rtol=1e-3)

    # FULL >= SIMPLE elementwise (Schur complement inequality)
    assert np.all(v_full >= v_simple * (1 - 1e-5))

    assert compute_variances(obj, w, batch,
                             VarianceComputationType.NONE) is None


# ---------------------------------------------------------------------------
# Checkpoint round trip
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip(tmp_path):
    coefs = {
        "global": jnp.arange(4, dtype=jnp.float32),
        "per_user": [jnp.ones((3, 2)), jnp.zeros((2, 5))],
    }
    assert load_latest_checkpoint(str(tmp_path)) is None
    save_checkpoint(str(tmp_path), 1, coefs)
    save_checkpoint(str(tmp_path), 2, coefs)
    it, loaded, _scores = load_latest_checkpoint(str(tmp_path))
    assert it == 2
    np.testing.assert_array_equal(loaded["global"], coefs["global"])
    assert len(loaded["per_user"]) == 2
    np.testing.assert_array_equal(loaded["per_user"][1],
                                  coefs["per_user"][1])


# ---------------------------------------------------------------------------
# Estimator-level: warm start, locking, prior, resume, variance export
# ---------------------------------------------------------------------------

def _game_data(n_obs=1500, seed=23):
    data = make_movielens_like(n_users=25, n_items=10, n_obs=n_obs,
                               dim_global=6, seed=seed)
    n = len(data["labels"])
    return GameDataset(
        labels=data["labels"],
        features={"global": data["x"].astype(np.float32),
                  "user_re": np.ones((n, 1), np.float32)},
        entity_ids={"per_user": data["user_ids"]},
    )


def _game_config(**over):
    base = dict(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[
            CoordinateConfig(
                name="global", kind=CoordinateKind.FIXED_EFFECT,
                feature_shard="global",
                optimizer=OptimizerSettings(reg_weight=1.0, max_iters=80),
            ),
            CoordinateConfig(
                name="per_user", kind=CoordinateKind.RANDOM_EFFECT,
                feature_shard="user_re", entity_key="per_user",
                optimizer=OptimizerSettings(reg_weight=2.0, max_iters=40),
            ),
        ],
        update_sequence=["global", "per_user"],
        n_iterations=2,
        evaluators=[EvaluatorType.AUC],
    )
    base.update(over)
    return TrainingConfig(**base)


def test_warm_start_reaches_same_solution_faster(tmp_path):
    train = _game_data()
    # Cold fit, save.
    est = GameEstimator(_game_config())
    res = est.fit(train)[0]
    save_game_model(res.model, TaskType.LOGISTIC_REGRESSION,
                    str(tmp_path / "m0"))
    w0 = np.asarray(res.model.models["global"].coefficients.means)

    # Warm restart with ONE more CD iteration continues where the cold
    # fit stopped: it must match a cold THREE-iteration fit, not the
    # 2-iteration starting point.
    est2 = GameEstimator(_game_config(
        warm_start_model_dir=str(tmp_path / "m0"), n_iterations=1))
    res2 = est2.fit(train)[0]
    w1 = np.asarray(res2.model.models["global"].coefficients.means)
    res3 = GameEstimator(_game_config(n_iterations=3)).fit(train)[0]
    w3 = np.asarray(res3.model.models["global"].coefficients.means)
    np.testing.assert_allclose(w1, w3, atol=5e-3)
    assert np.linalg.norm(w1 - w3) < np.linalg.norm(w1 - w0)

    # RE warm start maps by entity id.
    re0 = res.model.models["per_user"]
    re1 = res2.model.models["per_user"]
    for eid in re0.grouping.entity_ids[:5]:
        a = re0.coefficients_for(int(eid))
        b = re1.coefficients_for(int(eid))
        np.testing.assert_allclose(a, b, atol=5e-2)


def test_partial_retraining_locks_coordinate(tmp_path):
    train = _game_data()
    est = GameEstimator(_game_config())
    res = est.fit(train)[0]
    save_game_model(res.model, TaskType.LOGISTIC_REGRESSION,
                    str(tmp_path / "m0"))
    w_locked = np.asarray(res.model.models["global"].coefficients.means)

    # Retrain on NEW data with the fixed effect locked.
    train2 = _game_data(seed=31)
    est2 = GameEstimator(_game_config(
        warm_start_model_dir=str(tmp_path / "m0"),
        locked_coordinates=["global"],
    ))
    res2 = est2.fit(train2)[0]
    w_after = np.asarray(res2.model.models["global"].coefficients.means)
    np.testing.assert_allclose(w_after, w_locked, atol=1e-6)

    # The unlocked RE coordinate did move.
    re_a = res.model.models["per_user"]
    re_b = res2.model.models["per_user"]
    eid = int(re_a.grouping.entity_ids[0])
    assert not np.allclose(re_a.coefficients_for(eid),
                           re_b.coefficients_for(eid), atol=1e-4)


def test_locked_requires_warm_start():
    with pytest.raises(ValueError, match="warm_start_model_dir"):
        _game_config(locked_coordinates=["global"]).validate()


def test_prior_pulls_solution_toward_warm_model(tmp_path):
    train = _game_data()
    cfg = _game_config()
    cfg.coordinates[0].optimizer.variance_type = (
        VarianceComputationType.FULL)
    est = GameEstimator(cfg)
    res = est.fit(train)[0]
    assert res.model.models["global"].coefficients.variances is not None
    save_game_model(res.model, TaskType.LOGISTIC_REGRESSION,
                    str(tmp_path / "m0"))
    w_prev = np.asarray(res.model.models["global"].coefficients.means)

    # New data from a different seed; heavy prior keeps the fixed effect
    # near the previous model, no prior lets it drift further.
    train2 = _game_data(seed=41)
    res_free = GameEstimator(_game_config()).fit(train2)[0]
    res_prior = GameEstimator(_game_config(
        warm_start_model_dir=str(tmp_path / "m0"),
        use_warm_start_as_prior=True,
        prior_weight=200.0,
    )).fit(train2)[0]
    d_free = np.linalg.norm(
        np.asarray(res_free.model.models["global"].coefficients.means)
        - w_prev)
    d_prior = np.linalg.norm(
        np.asarray(res_prior.model.models["global"].coefficients.means)
        - w_prev)
    assert d_prior < d_free * 0.5


def test_variance_export_roundtrip(tmp_path):
    train = _game_data()
    cfg = _game_config()
    cfg.coordinates[0].optimizer.variance_type = (
        VarianceComputationType.SIMPLE)
    cfg.coordinates[1].optimizer.variance_type = (
        VarianceComputationType.SIMPLE)
    res = GameEstimator(cfg).fit(train)[0]
    fixed = res.model.models["global"]
    re = res.model.models["per_user"]
    assert fixed.coefficients.variances is not None
    assert np.all(np.asarray(fixed.coefficients.variances) > 0)
    assert re.variance_blocks is not None

    save_game_model(res.model, TaskType.LOGISTIC_REGRESSION,
                    str(tmp_path / "m"))
    loaded, _ = load_game_model(str(tmp_path / "m"))
    np.testing.assert_allclose(
        np.asarray(loaded.models["global"].coefficients.variances),
        np.asarray(fixed.coefficients.variances), rtol=1e-6)
    assert loaded.models["per_user"].variance_blocks is not None


def test_checkpoint_resume_matches_uninterrupted(tmp_path):
    train = _game_data()
    cfg_full = _game_config(n_iterations=3,
                            checkpoint_dir=str(tmp_path / "ck_full"))
    res_full = GameEstimator(cfg_full).fit(train)[0]

    # "Preempted" run: 2 iterations checkpointed, then resume to 3.
    cfg_a = _game_config(n_iterations=2,
                         checkpoint_dir=str(tmp_path / "ck"))
    GameEstimator(cfg_a).fit(train)
    it, _, _ = load_latest_checkpoint(str(tmp_path / "ck"))
    assert it == 2
    cfg_b = _game_config(n_iterations=3,
                         checkpoint_dir=str(tmp_path / "ck"), resume=True)
    res_b = GameEstimator(cfg_b).fit(train)[0]

    np.testing.assert_allclose(
        np.asarray(res_b.model.models["global"].coefficients.means),
        np.asarray(res_full.model.models["global"].coefficients.means),
        atol=1e-4,
    )
