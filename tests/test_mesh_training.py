"""Mesh-integrated training: the estimator drives the 8-device mesh.

Round-2 verdict items 2+3: the production path (GameEstimator) must
construct the mesh itself — example-sharded fixed-effect batches with
the psum-reduced objective (previously dead code), entity-sharded
random-effect blocks — and match single-device results to tolerance.
Runs on the virtual 8-device CPU mesh (conftest), the rebuild's
"Spark local mode" (SURVEY §4).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from photon_ml_tpu.config import (
    CoordinateConfig,
    CoordinateKind,
    OptimizerSettings,
    TrainingConfig,
)
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.evaluation.evaluators import EvaluatorType
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.models.glm import TaskType
from photon_ml_tpu.optim.base import OptimizerType


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _sparse_dataset(rng, n=600, d=40, k=6):
    w_true = rng.normal(0, 1, d)
    rows = []
    y = np.zeros(n, np.float32)
    for i in range(n):
        c = np.sort(rng.choice(d, k, replace=False)).astype(np.int32)
        v = rng.normal(0, 1, k).astype(np.float32)
        rows.append((c, v))
        y[i] = 1.0 if v @ w_true[c] + rng.normal(0, 0.3) > 0 else 0.0
    return GameDataset(labels=y, features={"f": rows}, entity_ids={},
                       feature_dims={"f": d}), w_true


def _game_dataset(rng, n=500, d=8, d_re=3, n_entities=24):
    x = rng.normal(0, 1, (n, d)).astype(np.float32)
    x_re = rng.normal(0, 1, (n, d_re)).astype(np.float32)
    ids = rng.integers(0, n_entities, n)
    w = rng.normal(0, 1, d)
    w_re = rng.normal(0, 1.5, (n_entities, d_re))
    margin = x @ w + np.einsum("nd,nd->n", x_re, w_re[ids])
    y = (margin + rng.normal(0, 0.3, n) > 0).astype(np.float32)
    return GameDataset(
        labels=y, features={"g": x, "per_user": x_re},
        entity_ids={"user": ids},
    )


def _fixed_cfg(n_devices=None, **kw):
    return TrainingConfig(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[CoordinateConfig(
            name="global", kind=CoordinateKind.FIXED_EFFECT,
            feature_shard="f",
            optimizer=OptimizerSettings(max_iters=60, reg_weight=1.0),
        )],
        update_sequence=["global"],
        evaluators=[EvaluatorType.AUC],
        n_devices=n_devices,
        **kw,
    )


def test_config1_sparse_mesh_matches_single_device(rng):
    """BASELINE config-1 shape (sparse logistic, L-BFGS, L2) through the
    estimator: 8-device mesh == single device."""
    ds, _ = _sparse_dataset(rng)
    r1 = GameEstimator(_fixed_cfg()).fit(ds, ds)[0]
    r8 = GameEstimator(_fixed_cfg(n_devices=8)).fit(ds, ds)[0]
    w1 = np.asarray(r1.model.models["global"].coefficients.means)
    w8 = np.asarray(r8.model.models["global"].coefficients.means)
    np.testing.assert_allclose(w8, w1, rtol=5e-3, atol=5e-3)
    assert abs(r8.evaluations[EvaluatorType.AUC]
               - r1.evaluations[EvaluatorType.AUC]) < 1e-3
    assert r8.evaluations[EvaluatorType.AUC] > 0.8


def test_config1_sparse_grr_mesh_matches_single_device(rng):
    """Round-3 verdict #1: the GRR compiled plan IS the sharded layout.
    Estimator with sparse_layout=GRR on the 8-device mesh == the
    single-device GRR fit (tolerance of the colmajor test above)."""
    ds, _ = _sparse_dataset(rng)
    r1 = GameEstimator(_fixed_cfg(sparse_layout="GRR")).fit(ds, ds)[0]
    r8 = GameEstimator(
        _fixed_cfg(n_devices=8, sparse_layout="GRR")).fit(ds, ds)[0]
    w1 = np.asarray(r1.model.models["global"].coefficients.means)
    w8 = np.asarray(r8.model.models["global"].coefficients.means)
    np.testing.assert_allclose(w8, w1, rtol=5e-3, atol=5e-3)
    assert abs(r8.evaluations[EvaluatorType.AUC]
               - r1.evaluations[EvaluatorType.AUC]) < 1e-3
    assert r8.evaluations[EvaluatorType.AUC] > 0.8


def test_config1_tron_mesh_matches_single_device(rng):
    """TRON over the psum objective (the distributed HVP arm)."""
    ds, _ = _sparse_dataset(rng, n=400)
    def cfg(n_devices=None):
        c = _fixed_cfg(n_devices=n_devices)
        c.coordinates[0].optimizer.optimizer = OptimizerType.TRON
        return c
    r1 = GameEstimator(cfg()).fit(ds)[0]
    r8 = GameEstimator(cfg(8)).fit(ds)[0]
    w1 = np.asarray(r1.model.models["global"].coefficients.means)
    w8 = np.asarray(r8.model.models["global"].coefficients.means)
    np.testing.assert_allclose(w8, w1, rtol=5e-3, atol=5e-3)


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_config4_game_mesh_matches_single_device(rng):
    """BASELINE config-4 shape (fixed + per-user random effect) through
    the estimator on the mesh: entity-sharded RE solves + sharded fixed
    solve must reproduce the single-device model."""
    ds = _game_dataset(rng)
    def cfg(n_devices=None):
        return TrainingConfig(
            task_type=TaskType.LOGISTIC_REGRESSION,
            coordinates=[
                CoordinateConfig(
                    name="fixed", kind=CoordinateKind.FIXED_EFFECT,
                    feature_shard="g",
                    optimizer=OptimizerSettings(max_iters=40,
                                                reg_weight=0.5),
                ),
                CoordinateConfig(
                    name="per_user", kind=CoordinateKind.RANDOM_EFFECT,
                    feature_shard="per_user", entity_key="user",
                    optimizer=OptimizerSettings(max_iters=40,
                                                reg_weight=1.0),
                ),
            ],
            update_sequence=["fixed", "per_user"],
            n_iterations=2,
            evaluators=[EvaluatorType.AUC],
            n_devices=n_devices,
        )
    r1 = GameEstimator(cfg()).fit(ds, ds)[0]
    r8 = GameEstimator(cfg(8)).fit(ds, ds)[0]
    w1 = np.asarray(r1.model.models["fixed"].coefficients.means)
    w8 = np.asarray(r8.model.models["fixed"].coefficients.means)
    np.testing.assert_allclose(w8, w1, rtol=1e-2, atol=1e-2)
    auc1 = r1.evaluations[EvaluatorType.AUC]
    auc8 = r8.evaluations[EvaluatorType.AUC]
    assert abs(auc8 - auc1) < 2e-3
    assert auc8 > 0.85
    # RE coefficients agree entity by entity
    m1, m8 = r1.model.models["per_user"], r8.model.models["per_user"]
    for e in range(24):
        c1 = m1.coefficients_for(e)
        c8 = m8.coefficients_for(e)
        if c1 is None:
            assert c8 is None
            continue
        np.testing.assert_allclose(np.asarray(c8), np.asarray(c1),
                                   rtol=2e-2, atol=2e-2)


def test_entity_blocks_balanced_on_mesh(rng):
    """Per-device entity counts are balanced (padded to equal splits)
    and the leading axis is sharded on ENTITY_AXIS."""
    from jax.sharding import NamedSharding

    from photon_ml_tpu.parallel.mesh import ENTITY_AXIS, entity_mesh
    from photon_ml_tpu.parallel.mesh import shard_entity_blocks

    mesh = entity_mesh(8)
    blocks = [np.ones((13, 4, 3), np.float32), np.ones((3, 16), np.float32)]
    sharded = shard_entity_blocks([jax.numpy.asarray(b) for b in blocks],
                                  mesh)
    for s in sharded:
        assert s.shape[0] % 8 == 0
        assert isinstance(s.sharding, NamedSharding)
        assert s.sharding.spec[0] == ENTITY_AXIS


def test_mesh_grr_with_validation_and_traces(rng):
    """Round-4 composition: sharded GRR layout + per-sweep validation +
    solver state traces, one fit."""
    ds, _ = _sparse_dataset(rng, n=400)
    cfg = _fixed_cfg(n_devices=8, sparse_layout="GRR", n_iterations=2)
    cfg.coordinates[0].optimizer.track_states = True
    r = GameEstimator(cfg).fit(ds, ds)[0]
    assert len(r.validation_history) == 2
    assert all(EvaluatorType.AUC in h for h in r.validation_history)
    assert r.evaluations == r.validation_history[-1]
    assert r.evaluations[EvaluatorType.AUC] > 0.8
