"""Tests for the transposed-ELL (column-major) gradient path and the
Pallas gather+rowsum kernel (interpret mode on CPU).

Mirrors the reference's aggregator unit tests (SURVEY.md §4 tier 1):
the scatter-free Xᵀr must agree with the dense contraction and with the
segment-sum path to float tolerance, including under virtual-row
splitting (skewed columns), normalization, and the 8-device mesh.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_ml_tpu.data.batch import make_sparse_batch
from photon_ml_tpu.data.colmajor import build_colmajor, choose_capacity
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.kernels import (
    _pallas_gather_rowsum,
    _xla_gather_rowsum,
)
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext


def _random_rows(rng, n, dim, max_nnz):
    rows = []
    for _ in range(n):
        nnz = int(rng.integers(1, max_nnz + 1))
        cols = rng.choice(dim, size=nnz, replace=False).astype(np.int64)
        vals = rng.normal(0, 1, nnz)
        rows.append((cols, vals))
    return rows


def _skewed_rows(rng, n, dim, max_nnz):
    """Power-law column popularity: column 0 appears in almost every row,
    so virtual-row splitting must kick in at small capacities."""
    rows = []
    for _ in range(n):
        nnz = int(rng.integers(2, max_nnz + 1))
        hot = np.array([0, 1])
        cold = 2 + rng.choice(dim - 2, size=nnz - 2, replace=False)
        cols = np.concatenate([hot, cold]).astype(np.int64)
        vals = rng.normal(0, 1, nnz)
        rows.append((cols, vals))
    return rows


@pytest.mark.parametrize("maker", [_random_rows, _skewed_rows])
@pytest.mark.parametrize("capacity", [8, 16, None])
def test_colmajor_xt_dot_matches_dense(rng, maker, capacity):
    n, dim = 64, 40
    rows = maker(rng, n, dim, max_nnz=12)
    batch = make_sparse_batch(rows, dim, np.zeros(n))
    cm = build_colmajor(
        np.asarray(batch.col_ids), np.asarray(batch.values), dim,
        capacity=capacity,
    )
    r = jnp.asarray(rng.normal(0, 1, n), jnp.float32)
    dense = batch.to_dense()
    np.testing.assert_allclose(
        np.asarray(cm.xt_dot(r)), np.asarray(dense.xt_dot(r)),
        rtol=1e-5, atol=1e-5,
    )


def test_colmajor_splitting_is_exercised(rng):
    """With capacity 8 and a column present in all 64 rows, that column
    must occupy 8 virtual rows."""
    rows = _skewed_rows(rng, 64, 40, max_nnz=6)
    batch = make_sparse_batch(rows, 40, np.zeros(64))
    cm = build_colmajor(
        np.asarray(batch.col_ids), np.asarray(batch.values), 40, capacity=8
    )
    vcol = np.asarray(cm.vcol)
    assert (vcol == 0).sum() >= 8


def test_choose_capacity_bounds():
    assert choose_capacity(np.zeros(10, np.int64)) == 8
    assert choose_capacity(np.full(10, 3)) == 8
    assert choose_capacity(np.full(10, 100000)) == 512
    c = choose_capacity(np.full(10, 100))
    assert c % 8 == 0 and 96 <= c <= 112


def test_sparse_batch_col_major_objective_equivalence(rng):
    """Full objective surface: colmajor and segment-sum paths agree."""
    n, dim = 48, 30
    rows = _random_rows(rng, n, dim, max_nnz=10)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float64)
    weights = rng.uniform(0.5, 2.0, n)
    plain = make_sparse_batch(rows, dim, labels, weights=weights)
    cmb = make_sparse_batch(
        rows, dim, labels, weights=weights, col_major=True, col_capacity=8
    )
    assert cmb.colmajor is not None

    stats_shift = rng.normal(0, 1, dim)
    stats_scale = rng.uniform(0.5, 2.0, dim)
    norm = NormalizationContext(
        factors=jnp.asarray(1.0 / stats_scale, jnp.float32),
        shifts=jnp.asarray(stats_shift, jnp.float32),
    )
    obj = GLMObjective(
        loss=losses.LOGISTIC, reg=RegularizationContext.l2(0.3), norm=norm
    )
    w = jnp.asarray(rng.normal(0, 0.5, dim), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1.0, dim), jnp.float32)

    for name in ("value", "gradient", "hessian_diagonal"):
        a = getattr(obj, name)(w, plain)
        b = getattr(obj, name)(w, cmb)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5, err_msg=name
        )
    np.testing.assert_allclose(
        np.asarray(obj.hessian_vector(w, v, plain)),
        np.asarray(obj.hessian_vector(w, v, cmb)),
        rtol=2e-5, atol=2e-5,
    )


def test_pallas_gather_rowsum_interpret_matches_xla(rng):
    """Kernel-body numerics via the Pallas interpreter (no TPU needed)."""
    L, n, k = 500, 64, 16
    table = jnp.asarray(rng.normal(0, 1, L), jnp.float32)
    vals = jnp.asarray(rng.normal(0, 1, (n, k)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, L, (n, k)), jnp.int32)
    out = _pallas_gather_rowsum(table, vals, ids, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_xla_gather_rowsum(table, vals, ids)),
        rtol=1e-5, atol=1e-5,
    )


def test_shard_sparse_batch_distributed_equivalence(rng):
    """Per-shard transposes + psum == single-device objective (the
    north-star equality, now on the scatter-free path)."""
    from photon_ml_tpu.parallel import (
        DistributedGLMObjective,
        data_parallel_mesh,
        shard_sparse_batch,
    )

    n, dim = 50, 24
    rows = _random_rows(rng, n, dim, max_nnz=8)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float64)
    mesh = data_parallel_mesh(8)
    sharded = shard_sparse_batch(
        rows, dim, labels, mesh, col_major=True, col_capacity=8
    )
    assert sharded.colmajor is not None

    local = make_sparse_batch(rows, dim, labels)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(0.7),
        norm=NormalizationContext.identity(),
    )
    dist = DistributedGLMObjective(objective=obj, mesh=mesh)
    w = jnp.asarray(rng.normal(0, 0.5, dim), jnp.float32)

    v1, g1 = obj.value_and_gradient(w, local)
    v2, g2 = dist.value_and_gradient(w, sharded)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(g1), np.asarray(g2), rtol=1e-5, atol=1e-5
    )


def test_shard_batch_rejects_global_colmajor(rng):
    from photon_ml_tpu.parallel import data_parallel_mesh, shard_batch

    rows = _random_rows(rng, 16, 10, max_nnz=4)
    batch = make_sparse_batch(
        rows, 10, np.zeros(16), col_major=True, col_capacity=8
    )
    with pytest.raises(ValueError, match="shard_sparse_batch"):
        shard_batch(batch, data_parallel_mesh(8))


def test_down_sampling_drops_colmajor(rng):
    """Subsetting a batch by example ids must not index the virtual-row
    arrays (regression: corrupted X^T r under down-sampling)."""
    from photon_ml_tpu.game.coordinates import FixedEffectCoordinate
    from photon_ml_tpu.optim import OptimizerConfig, OptimizerType
    from photon_ml_tpu.optim.problem import OptimizationProblem

    n, dim = 32, 12
    rows = _random_rows(rng, n, dim, max_nnz=4)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float64)
    batch = make_sparse_batch(
        rows, dim, labels, col_major=True, col_capacity=8
    )
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(1.0),
        norm=NormalizationContext.identity(),
    )
    problem = OptimizationProblem(
        objective=obj,
        optimizer=OptimizerType.LBFGS,
        config=OptimizerConfig(max_iters=5),
    )
    idx = jnp.asarray(np.arange(0, n, 2), jnp.int32)
    coord = FixedEffectCoordinate(
        name="fe", batch=batch, problem=problem,
        train_idx=idx, train_weights=jnp.ones((idx.size,), jnp.float32),
    )
    sub = coord._training_batch(jnp.zeros((n,), jnp.float32))
    assert sub.colmajor is None
    # And the subset gradient matches the dense reference.
    w = jnp.asarray(rng.normal(0, 0.3, dim), jnp.float32)
    _, g = obj.value_and_gradient(w, sub)
    _, g_ref = obj.value_and_gradient(w, sub.to_dense())
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(g_ref), rtol=1e-5, atol=1e-5
    )
