"""GameEstimator / GameTransformer / model-IO integration tests
(reference GameEstimatorIntegTest class of coverage, SURVEY.md §4)."""

import numpy as np
import pytest

from photon_ml_tpu.config import (
    CoordinateConfig,
    CoordinateKind,
    OptimizerSettings,
    TrainingConfig,
    config_to_json,
    training_config_from_json,
)
from photon_ml_tpu.estimators import GameEstimator, GameTransformer
from photon_ml_tpu.evaluation.evaluators import EvaluatorType
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.io.model_io import load_game_model, save_game_model
from photon_ml_tpu.models.glm import TaskType
from photon_ml_tpu.ops.regularization import RegularizationType
from photon_ml_tpu.optim.base import OptimizerType
from photon_ml_tpu.utils.synthetic import make_movielens_like


def _split(data, n_train):
    def cut(a):
        return a[:n_train], a[n_train:]

    x_tr, x_va = cut(data["x"])
    y_tr, y_va = cut(data["labels"])
    u_tr, u_va = cut(data["user_ids"])
    n_tr, n_va = len(y_tr), len(y_va)
    train = GameDataset(
        labels=y_tr,
        features={"global": x_tr, "user_re": np.ones((n_tr, 1), np.float32)},
        entity_ids={"per_user": u_tr},
    )
    valid = GameDataset(
        labels=y_va,
        features={"global": x_va, "user_re": np.ones((n_va, 1), np.float32)},
        entity_ids={"per_user": u_va},
    )
    return train, valid


def _config(**over):
    base = dict(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[
            CoordinateConfig(
                name="global",
                kind=CoordinateKind.FIXED_EFFECT,
                feature_shard="global",
                optimizer=OptimizerSettings(reg_weight=1.0, max_iters=100),
            ),
            CoordinateConfig(
                name="per_user",
                kind=CoordinateKind.RANDOM_EFFECT,
                feature_shard="user_re",
                entity_key="per_user",
                optimizer=OptimizerSettings(reg_weight=2.0, max_iters=50),
            ),
        ],
        update_sequence=["global", "per_user"],
        n_iterations=2,
        evaluators=[EvaluatorType.AUC, EvaluatorType.LOGISTIC_LOSS],
    )
    base.update(over)
    return TrainingConfig(**base)


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_estimator_fit_grid_and_selection(tmp_path):
    data = make_movielens_like(n_users=100, n_items=1, n_obs=5000, seed=3)
    train, valid = _split(data, 4000)
    cfg = _config(reg_weight_grid={"global": [0.1, 10.0]})
    est = GameEstimator(cfg)
    results = est.fit(train, valid)
    assert len(results) == 2
    for r in results:
        assert EvaluatorType.AUC in r.evaluations
        assert 0.5 < r.evaluations[EvaluatorType.AUC] <= 1.0
    best = est.best(results)
    assert best.evaluations[EvaluatorType.AUC] == max(
        r.evaluations[EvaluatorType.AUC] for r in results
    )
    # GAME model with user effects must beat 0.8 on this data.
    assert best.evaluations[EvaluatorType.AUC] > 0.8

    # save → load → rescore parity.
    out = str(tmp_path / "model")
    save_game_model(best.model, cfg.task_type, out)
    loaded, task = load_game_model(out)
    t1 = GameTransformer(model=best.model, task=cfg.task_type)
    t2 = GameTransformer(model=loaded, task=task)
    np.testing.assert_allclose(t1.transform(valid), t2.transform(valid),
                               atol=1e-6)


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_estimator_with_intercept_and_standardization():
    from photon_ml_tpu.data.normalization import NormalizationType

    data = make_movielens_like(n_users=60, n_items=1, n_obs=3000, seed=9)
    # Shift features so an intercept + standardization matter.
    data["x"] = data["x"] * 2.5 + 1.7
    train, valid = _split(data, 2400)
    cfg = _config(normalization=NormalizationType.STANDARDIZATION,
                  intercept=True)
    est = GameEstimator(cfg)
    best = est.best(est.fit(train, valid))
    assert best.evaluations[EvaluatorType.AUC] > 0.8


def test_estimator_down_sampling_path():
    data = make_movielens_like(n_users=50, n_items=1, n_obs=3000, seed=17)
    train, valid = _split(data, 2400)
    cfg = _config()
    cfg.coordinates[0].down_sampling_rate = 0.5
    est = GameEstimator(cfg)
    best = est.best(est.fit(train, valid))
    assert best.evaluations[EvaluatorType.AUC] > 0.75


def test_config_json_round_trip():
    cfg = _config(reg_weight_grid={"global": [0.1, 1.0]})
    text = config_to_json(cfg)
    cfg2 = training_config_from_json(text)
    assert cfg2.task_type == cfg.task_type
    assert cfg2.coordinates[1].entity_key == "per_user"
    assert cfg2.coordinates[0].optimizer.optimizer == OptimizerType.LBFGS
    assert cfg2.evaluators == cfg.evaluators
    assert cfg2.reg_weight_grid == {"global": [0.1, 1.0]}


def test_config_validation_rejects_bad():
    import pytest

    cfg = _config()
    cfg.update_sequence = ["nope"]
    with pytest.raises(ValueError, match="update_sequence"):
        cfg.validate()

    cfg2 = _config()
    cfg2.coordinates[0].optimizer.regularization = RegularizationType.L1
    cfg2.coordinates[0].optimizer.optimizer = OptimizerType.TRON
    with pytest.raises(ValueError, match="TRON"):
        cfg2.validate()


def test_grid_points_share_one_compilation():
    """Round-2 verdict: grid/tuning points differing only in reg weight
    must not retrace the coordinate solve (λ is a traced leaf)."""
    import numpy as np

    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
    )
    from photon_ml_tpu.estimators.game_estimator import GameEstimator
    from photon_ml_tpu.game.coordinates import _fixed_train_local
    from photon_ml_tpu.game.dataset import GameDataset
    from photon_ml_tpu.models.glm import TaskType

    rng = np.random.default_rng(0)
    n, d = 200, 12
    x = rng.normal(0, 1, (n, d)).astype(np.float32)
    y = (x @ rng.normal(0, 1, d) > 0).astype(np.float32)
    ds = GameDataset(labels=y, features={"global": x}, entity_ids={})
    cfg = TrainingConfig(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[CoordinateConfig(
            name="fixed", kind=CoordinateKind.FIXED_EFFECT,
            feature_shard="global",
            optimizer=OptimizerSettings(max_iters=15),
        )],
        update_sequence=["fixed"],
        evaluators=[],
        reg_weight_grid={"fixed": [0.1, 1.0, 10.0, 100.0]},
    )
    est = GameEstimator(cfg)
    before = _fixed_train_local._cache_size()
    results = est.fit(ds)
    assert len(results) == 4
    added = _fixed_train_local._cache_size() - before
    assert added <= 1, f"grid retraced the solve {added} times"


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_per_iteration_validation_history():
    """Round-3 verdict #3: one validation entry (every evaluator) per
    CD sweep through GameEstimator.fit, ending at the final model's
    evaluations; run log carries cd_validation events."""
    import json

    from photon_ml_tpu.utils.run_log import RunLogger

    data = make_movielens_like(seed=3)
    train, valid = _split(data, 400)
    cfg = _config(n_iterations=3)

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        log_path = f"{td}/log.jsonl"
        log = RunLogger(path=log_path)
        result = GameEstimator(cfg).fit(train, valid, run_logger=log)[0]
        log.close()
        events = [json.loads(line) for line in open(log_path)]

    hist = result.validation_history
    assert len(hist) == 3
    for entry in hist:
        assert set(entry) == {EvaluatorType.AUC, EvaluatorType.LOGISTIC_LOSS}
        assert 0.0 <= entry[EvaluatorType.AUC] <= 1.0
    # Final evaluations == last sweep's snapshot (same coefficients).
    assert result.evaluations == hist[-1]
    cdv = [e for e in events if e.get("event") == "cd_validation"]
    assert [e["iteration"] for e in cdv] == [1, 2, 3]
    assert all("AUC" in e for e in cdv)


def test_per_iteration_validation_off():
    data = make_movielens_like(seed=3)
    train, valid = _split(data, 400)
    cfg = _config(validate_per_iteration=False)
    result = GameEstimator(cfg).fit(train, valid)[0]
    assert result.validation_history == []
    assert EvaluatorType.AUC in result.evaluations


def test_track_states_in_run_log():
    """Round-3 verdict #6: OptimizerSettings.track_states plumbs a
    per-solver-iteration (value, grad_norm) trace into the run log's
    cd_coordinate events for the fixed effect."""
    import json
    import tempfile

    from photon_ml_tpu.utils.run_log import RunLogger

    data = make_movielens_like(seed=4)
    train, _ = _split(data, 400)
    cfg = _config(n_iterations=1, validate_per_iteration=False)
    cfg.coordinates[0].optimizer.track_states = True

    with tempfile.TemporaryDirectory() as td:
        log = RunLogger(path=f"{td}/log.jsonl")
        GameEstimator(cfg).fit(train, run_logger=log)
        log.close()
        events = [json.loads(line) for line in open(f"{td}/log.jsonl")]

    fixed = [e for e in events if e.get("event") == "cd_coordinate"
             and e.get("coordinate") == "global"]
    assert fixed, "no cd_coordinate event for the fixed effect"
    states = fixed[0].get("states")
    assert states is not None
    n_states = len(states["values"])
    assert n_states == fixed[0]["solver_iterations"] + 1  # slot 0 = w0
    assert len(states["grad_norms"]) == n_states
    # Monotone-ish: the final value must improve on the initial.
    assert states["values"][-1] < states["values"][0]


@pytest.mark.fast
def test_device_score_sparse_matches_host():
    """The chunked device X·w used by GameTransformer for large sparse
    inputs must equal the host numpy pass (round-4 verdict item #6)."""
    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.estimators.game_transformer import (
        _device_score_sparse,
    )

    rng = np.random.default_rng(4)
    n, d, k = 5000, 700, 6
    cols = np.stack([np.sort(rng.choice(d, k, replace=False))
                     for _ in range(n)]).astype(np.int64)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    indptr = np.arange(n + 1, dtype=np.int64) * k
    rows = SparseRows.from_flat(indptr, cols.reshape(-1),
                                vals.reshape(-1))
    w = rng.normal(0, 1, d).astype(np.float32)
    import photon_ml_tpu.estimators.game_transformer as gt
    old = gt._DEVICE_SCORE_CHUNK
    gt._DEVICE_SCORE_CHUNK = 1024   # force multi-chunk + padded tail
    try:
        out = _device_score_sparse(rows, w)
    finally:
        gt._DEVICE_SCORE_CHUNK = old
    np.testing.assert_allclose(out, rows.dot_dense(w.astype(np.float64)),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.fast
def test_device_score_chunk_grid_right_sized(monkeypatch):
    """Small-but-device-eligible inputs compile a right-sized chunk
    grid — min(n, _DEVICE_SCORE_CHUNK) rounded up to the 8192 tile —
    instead of padding to the fixed 2M grid (advisor finding: ~8-10×
    wasted gather/rowsum/transfer at n=250k)."""
    import photon_ml_tpu.ops.kernels as kernels
    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.estimators.game_transformer import (
        _device_score_sparse,
    )

    rng = np.random.default_rng(9)
    n, d, k = 9000, 500, 5
    cols = np.stack([np.sort(rng.choice(d, k, replace=False))
                     for _ in range(n)]).astype(np.int64)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    rows = SparseRows.from_flat(np.arange(n + 1, dtype=np.int64) * k,
                                cols.reshape(-1), vals.reshape(-1))
    w = rng.normal(0, 1, d).astype(np.float32)

    seen = []
    orig = kernels.gather_rowsum

    def spy(w_, vals_, cols_):
        seen.append(vals_.shape)
        return orig(w_, vals_, cols_)

    monkeypatch.setattr(kernels, "gather_rowsum", spy)
    out = _device_score_sparse(rows, w)
    # One chunk at the 8192-rounded grid (16384), not 2,000,000.
    assert seen == [(16384, k)]
    np.testing.assert_allclose(out, rows.dot_dense(w.astype(np.float64)),
                               rtol=2e-4, atol=2e-4)
