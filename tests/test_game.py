"""GAME layer tests: grouping ETL, random-effect solves, coordinate descent.

Mirrors the reference's integration tier (CoordinateDescentIntegTest,
RandomEffectDatasetIntegTest — SURVEY.md §4): BASELINE config-4 gate is
fixed+RE beating fixed-only AUC on mixed-effect data.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.batch import make_dense_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.evaluation import auc
from photon_ml_tpu.game import (
    GameDataset,
    FixedEffectCoordinate,
    build_random_effect_coordinate,
    gather_from_blocks,
    group_by_entity,
    run_coordinate_descent,
    scatter_to_blocks,
)
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim import OptimizationProblem, OptimizerConfig
from photon_ml_tpu.utils.synthetic import make_movielens_like


# ---------------------------------------------------------------------------
# Grouping ETL
# ---------------------------------------------------------------------------

def test_group_by_entity_structure(rng):
    ids = rng.integers(0, 50, 1000)
    g = group_by_entity(ids, bucket_base=4, min_capacity=4)
    assert g.n_examples == 1000
    assert g.n_total_entities == len(np.unique(ids))
    # Every entity's count fits its bucket capacity.
    for e in range(g.n_total_entities):
        assert g.entity_counts[e] <= g.capacities[g.entity_bucket[e]]
    # Example coordinates are consistent: same entity → same (bucket, row).
    for i in rng.choice(1000, 50, replace=False):
        e = np.searchsorted(g.entity_ids, ids[i])
        assert g.example_bucket[i] == g.entity_bucket[e]
        assert g.example_row[i] == g.entity_slot[e]
        assert g.example_col[i] < g.entity_counts[e]


def test_scatter_gather_round_trip(rng):
    ids = rng.integers(0, 30, 500)
    g = group_by_entity(ids)
    vals = rng.normal(0, 1, 500).astype(np.float32)
    blocks = scatter_to_blocks(g, vals)
    back = gather_from_blocks(g, blocks)
    np.testing.assert_array_equal(back, vals)


def test_power_law_bucketing_bounds_padding(rng):
    # Zipf-ish entity sizes: bucketing must keep padding < base× data.
    sizes = np.maximum(1, (2000 / np.arange(1, 201) ** 1.2)).astype(int)
    ids = np.repeat(np.arange(200), sizes)
    g = group_by_entity(ids, bucket_base=4)
    padded = sum(c * ne for c, ne in zip(g.capacities, g.n_entities))
    assert padded < 4 * len(ids) + 4 * 200


# ---------------------------------------------------------------------------
# Random-effect coordinate
# ---------------------------------------------------------------------------

def _re_objective(l2=1.0):
    return GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(l2),
        norm=NormalizationContext.identity(),
    )


def test_random_effect_recovers_entity_effects(rng):
    """Per-entity intercept-only logistic: vmapped solves must recover
    each entity's effect sign/magnitude."""
    n_entities, per_entity = 40, 60
    effects = rng.normal(0, 1.5, n_entities)
    ids = np.repeat(np.arange(n_entities), per_entity)
    p = 1 / (1 + np.exp(-effects[ids]))
    y = (rng.uniform(size=len(ids)) < p).astype(np.float32)
    ds = GameDataset(
        labels=y,
        features={"re": np.ones((len(ids), 1), np.float32)},
        entity_ids={"per_entity": ids},
    )
    coord = build_random_effect_coordinate(
        "per_entity", ds, "re", _re_objective(l2=2.0),
        config=OptimizerConfig(max_iters=50, tolerance=1e-6,
                               track_states=False),
    )
    blocks, results = coord.train(jnp.zeros(len(ids), jnp.float32))
    assert all(bool(jnp.all(r.converged)) for r in results)
    model = coord.as_model(blocks)
    learned = np.array([
        model.coefficients_for(e)[0] for e in range(n_entities)
    ])
    # Shrinkage from L2 means magnitudes compress; correlation stays high.
    assert np.corrcoef(learned, effects)[0, 1] > 0.85


def test_random_effect_scores_match_per_entity_dot(rng):
    n = 400
    ids = rng.integers(0, 25, n)
    x = rng.normal(0, 1, (n, 3)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    ds = GameDataset(labels=y, features={"re": x},
                     entity_ids={"u": ids})
    coord = build_random_effect_coordinate(
        "u", ds, "re", _re_objective(),
        config=OptimizerConfig(max_iters=30, tolerance=1e-5,
                               track_states=False),
    )
    blocks, _ = coord.train(jnp.zeros(n, jnp.float32))
    scores = np.asarray(coord.score(blocks))
    model = coord.as_model(blocks)
    for i in rng.choice(n, 25, replace=False):
        w_e = model.coefficients_for(ids[i])
        np.testing.assert_allclose(scores[i], x[i] @ w_e, rtol=1e-4,
                                   atol=1e-5)


# ---------------------------------------------------------------------------
# Coordinate descent (BASELINE config 4 gate)
# ---------------------------------------------------------------------------

def _movielens_coordinates(data, l2_fixed=1.0, l2_re=2.0):
    n = len(data["labels"])
    fixed_batch = make_dense_batch(data["x"], data["labels"])
    fixed = FixedEffectCoordinate(
        name="global",
        batch=fixed_batch,
        problem=OptimizationProblem(
            objective=GLMObjective(
                loss=losses.LOGISTIC,
                reg=RegularizationContext.l2(l2_fixed),
                norm=NormalizationContext.identity(),
            ),
            config=OptimizerConfig(max_iters=100, tolerance=1e-6,
                                   track_states=False),
        ),
    )
    ds = GameDataset(
        labels=data["labels"],
        features={
            "global": data["x"],
            "user_re": np.ones((n, 1), np.float32),
        },
        entity_ids={"per_user": data["user_ids"]},
    )
    user_re = build_random_effect_coordinate(
        "per_user", ds, "user_re", _re_objective(l2=l2_re),
        config=OptimizerConfig(max_iters=50, tolerance=1e-6,
                               track_states=False),
    )
    return fixed, user_re


def test_game_beats_fixed_only(rng):
    data = make_movielens_like(n_users=150, n_items=1, n_obs=6000)
    labels = jnp.asarray(data["labels"])

    fixed, user_re = _movielens_coordinates(data)

    # Fixed-effect-only AUC.
    w_fixed, _ = fixed.train(jnp.zeros(len(data["labels"]), jnp.float32))
    auc_fixed = float(auc(fixed.score(w_fixed), labels))

    # GAME: fixed + per-user random effect.
    result = run_coordinate_descent(
        coordinates={"global": fixed, "per_user": user_re},
        update_sequence=["global", "per_user"],
        n_iterations=3,
        validator=lambda coefs, total: float(auc(total, labels)),
    )
    auc_game = result.validation_history[-1]
    assert auc_game > auc_fixed + 0.01, (
        f"GAME {auc_game:.4f} must beat fixed-only {auc_fixed:.4f}"
    )
    # Validation must not degrade over CD iterations.
    assert result.validation_history[-1] >= result.validation_history[0] - 1e-3


def test_coordinate_descent_converges_scores(rng):
    """Total scores stabilize across iterations (residual passing works)."""
    data = make_movielens_like(n_users=80, n_items=1, n_obs=3000, seed=23)
    fixed, user_re = _movielens_coordinates(data)
    res = run_coordinate_descent(
        coordinates={"global": fixed, "per_user": user_re},
        update_sequence=["global", "per_user"],
        n_iterations=4,
    )
    # Re-run one more sweep: coefficients should barely move.
    fixed_coefs = res.coefficients["global"]
    offsets = res.total_scores - res.scores["global"]
    w2, _ = fixed.train(offsets, fixed_coefs)
    np.testing.assert_allclose(np.asarray(w2), np.asarray(fixed_coefs),
                               atol=5e-3)


def test_locked_coordinate_not_retrained(rng):
    data = make_movielens_like(n_users=50, n_items=1, n_obs=2000, seed=31)
    fixed, user_re = _movielens_coordinates(data)
    w_fixed, _ = fixed.train(jnp.zeros(len(data["labels"]), jnp.float32))
    res = run_coordinate_descent(
        coordinates={"global": fixed, "per_user": user_re},
        update_sequence=["global", "per_user"],
        n_iterations=2,
        locked_coordinates={"global": w_fixed},
    )
    np.testing.assert_array_equal(np.asarray(res.coefficients["global"]),
                                  np.asarray(w_fixed))
    assert "per_user" in res.coefficients


# ---------------------------------------------------------------------------
# Projector, down-sampling, two-RE GAME (config-5 shape)
# ---------------------------------------------------------------------------

def test_subspace_projection_round_trip(rng):
    from photon_ml_tpu.game import build_subspace_projection

    n, global_dim = 200, 500
    ids = rng.integers(0, 20, n)
    rows = []
    for i in range(n):
        k = rng.integers(2, 6)
        c = np.sort(rng.choice(global_dim, k, replace=False)).astype(np.int32)
        rows.append((c, rng.normal(0, 1, k).astype(np.float32)))
    g = group_by_entity(ids)
    proj, x_blocks = build_subspace_projection(g, rows, global_dim)

    # Every example's features must appear, remapped, in its block row.
    for i in rng.choice(n, 30, replace=False):
        b = g.example_bucket[i]
        r, c_pos = g.example_row[i], g.example_col[i]
        dense_local = x_blocks[b][r, c_pos]
        fids = proj.feature_ids[b][r]
        c, v = rows[i]
        rebuilt = np.zeros(global_dim, np.float32)
        valid = fids >= 0
        rebuilt[fids[valid]] = dense_local[: valid.sum()]
        expect = np.zeros(global_dim, np.float32)
        expect[c] = v
        np.testing.assert_allclose(rebuilt, expect, atol=1e-6)
    # Local widths are bounded by entities' distinct-feature counts.
    for b, fids in enumerate(proj.feature_ids):
        assert fids.shape[1] <= global_dim


def test_sparse_re_coordinate_matches_dense(rng):
    """Projected sparse RE solve == dense RE solve on equivalent data."""
    from photon_ml_tpu.game import build_random_effect_coordinate_sparse

    n, d_re = 300, 6
    ids = rng.integers(0, 15, n)
    x = rng.normal(0, 1, (n, d_re)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)

    # Sparse view of the same dense data (all features explicit).
    rows = [(np.arange(d_re, dtype=np.int32), x[i]) for i in range(n)]

    ds_dense = GameDataset(labels=y, features={"re": x}, entity_ids={"u": ids})
    ds_sparse = GameDataset(labels=y, features={"re": rows},
                            entity_ids={"u": ids})
    cfg = OptimizerConfig(max_iters=50, tolerance=1e-6, track_states=False)
    dense_c = build_random_effect_coordinate(
        "u", ds_dense, "re", _re_objective(), config=cfg)
    sparse_c = build_random_effect_coordinate_sparse(
        "u", ds_sparse, "re", _re_objective(), global_dim=d_re, config=cfg)

    off = jnp.zeros(n, jnp.float32)
    dense_blocks, _ = dense_c.train(off)
    sparse_blocks, _ = sparse_c.train(off)
    np.testing.assert_allclose(
        np.asarray(dense_c.score(dense_blocks)),
        np.asarray(sparse_c.score(sparse_blocks)),
        atol=2e-3,
    )
    # Global-space per-entity coefficients agree.
    dm = dense_c.as_model(dense_blocks)
    sm = sparse_c.as_model(sparse_blocks)
    for e in np.unique(ids)[:5]:
        np.testing.assert_allclose(
            sm.global_coefficients_for(e), dm.coefficients_for(e), atol=2e-3
        )


def test_binary_down_sampling_preserves_objective_scale(rng):
    from photon_ml_tpu.game import binary_classification_down_sample

    n = 20000
    labels = (rng.uniform(size=n) < 0.1).astype(np.float32)
    weights = np.ones(n, np.float32)
    idx, new_w = binary_classification_down_sample(labels, weights, 0.25,
                                                   seed=1)
    # All positives kept.
    assert set(np.where(labels > 0.5)[0]) <= set(idx)
    # Total negative weight approximately preserved (unbiasedness).
    neg_before = float((1 - labels).sum())
    kept_labels = labels[idx]
    neg_after = float(new_w[kept_labels < 0.5].sum())
    assert abs(neg_after - neg_before) / neg_before < 0.05


def test_two_random_effects_config5_shape(rng):
    """BASELINE config-5 shape: fixed + per-user + per-item effects."""
    data = make_movielens_like(n_users=80, n_items=40, n_obs=6000, seed=41)
    labels = jnp.asarray(data["labels"])
    n = len(data["labels"])
    fixed, user_re = _movielens_coordinates(data)
    ds_items = GameDataset(
        labels=data["labels"],
        features={"item_re": np.ones((n, 1), np.float32)},
        entity_ids={"per_item": data["item_ids"]},
    )
    item_re = build_random_effect_coordinate(
        "per_item", ds_items, "item_re", _re_objective(l2=2.0),
        config=OptimizerConfig(max_iters=50, tolerance=1e-6,
                               track_states=False),
    )

    res_1re = run_coordinate_descent(
        coordinates={"global": fixed, "per_user": user_re},
        update_sequence=["global", "per_user"],
        n_iterations=2,
        validator=lambda coefs, t: float(auc(t, labels)),
    )
    res_2re = run_coordinate_descent(
        coordinates={"global": fixed, "per_user": user_re,
                     "per_item": item_re},
        update_sequence=["global", "per_user", "per_item"],
        n_iterations=2,
        validator=lambda coefs, t: float(auc(t, labels)),
    )
    assert res_2re.validation_history[-1] > res_1re.validation_history[-1], (
        "adding the item effect must improve fit on item-effect data"
    )


@pytest.mark.fast
def test_validator_arity_shim():
    """Exactly-one-positional callables are the legacy ``(total_scores)``
    form; TWO OR MORE positional parameters — required or defaulted —
    are the current ``(coefficients, total_scores)`` convention
    (advisor finding: counting only required positionals misrouted a
    ``(coefficients, total_scores=None)`` validator's arguments)."""
    from photon_ml_tpu.game.coordinate_descent import _call_validator

    calls = {}
    _call_validator(lambda total: calls.setdefault("legacy", total),
                    {"c": 1}, "T")
    assert calls["legacy"] == "T"

    def new_style(coefs, total):
        calls["new"] = (coefs, total)
    _call_validator(new_style, {"c": 1}, "T")
    assert calls["new"] == ({"c": 1}, "T")

    def new_optional_total(coefficients, total_scores=None):
        calls["new_opt"] = (coefficients, total_scores)
    _call_validator(new_optional_total, {"c": 1}, "T")
    assert calls["new_opt"] == ({"c": 1}, "T")

    def two_positional_defaults(coefficients=None, total_scores=None):
        calls["two_def"] = (coefficients, total_scores)
    _call_validator(two_positional_defaults, {"c": 1}, "T")
    assert calls["two_def"] == ({"c": 1}, "T")

    _call_validator(lambda *a: calls.setdefault("varpos", a), {"c": 1}, "T")
    assert calls["varpos"] == ({"c": 1}, "T")

    # Legacy with keyword-only extras stays legacy (the extras are not
    # positional, so the positional count is still one).
    def legacy_kwonly(total_scores, *, sample_weight=None):
        calls["kwonly"] = total_scores
    _call_validator(legacy_kwonly, {"c": 1}, "T")
    assert calls["kwonly"] == "T"
