"""End-to-end driver tests: config file in, models/scores/logs out.

Reference coverage class: ``GameTrainingDriverIntegTest`` /
``GameScoringDriverIntegTest`` / ``FeatureIndexingDriver`` tests
(SURVEY.md §4 tier 3) — run the full pipeline on small fixtures from
files alone and assert outputs exist and metrics beat thresholds.
"""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.cli import (
    feature_indexing_driver,
    game_scoring_driver,
    game_training_driver,
)
from photon_ml_tpu.io.dataset import (
    build_index_maps,
    read_game_dataset,
    write_game_dataset,
)
from photon_ml_tpu.io.index_map import (
    IndexMap,
    IndexMapBuilder,
    feature_key,
    load_index_maps,
    save_index_maps,
)
from photon_ml_tpu.io.libsvm import write_libsvm
from photon_ml_tpu.utils.run_log import read_run_log
from photon_ml_tpu.utils.synthetic import make_a1a_like, make_movielens_like


# ---------------------------------------------------------------------------
# Index maps
# ---------------------------------------------------------------------------

def test_index_map_build_and_roundtrip(tmp_path):
    b = IndexMapBuilder()
    for name, term in [("age", ""), ("geo", "us"), ("geo", "uk"), ("age", "")]:
        b.put_feature(name, term)
    m = b.build()
    assert len(m) == 3
    # Deterministic sorted-order assignment, (name, term) distinct from
    # any single-string collision.
    assert m.get_feature("geo", "us") != m.get_feature("geo", "uk")
    assert feature_key("geo", "us") != feature_key("geous", "")
    path = str(tmp_path / "maps" / "m.json")
    m.save(path)
    m2 = IndexMap.load(path)
    assert m2.index == m.index
    assert m2.names()[m2.get_feature("age")] == "age"


def test_index_maps_dir_roundtrip(tmp_path):
    f = {"global": IndexMap(index={"a": 0, "b": 1})}
    e = {"userId": IndexMap(index={"u1": 0})}
    save_index_maps(str(tmp_path / "maps"), f, e)
    f2, e2 = load_index_maps(str(tmp_path / "maps"))
    assert f2["global"].index == f["global"].index
    assert e2["userId"].index == e["userId"].index


# ---------------------------------------------------------------------------
# JSONL dataset reader
# ---------------------------------------------------------------------------

def _write_jsonl_fixture(path, n_users=20, n_obs=300, seed=3):
    data = make_movielens_like(n_users=n_users, n_items=10, n_obs=n_obs,
                               dim_global=6, seed=seed)
    write_game_dataset(
        path,
        labels=data["labels"],
        features={
            "global": data["x"].astype(np.float32),
            "user_re": np.ones((len(data["labels"]), 1), np.float32),
        },
        ids={"userId": data["user_ids"]},
    )
    return data


def test_jsonl_dataset_roundtrip(tmp_path):
    path = str(tmp_path / "train.jsonl")
    data = _write_jsonl_fixture(path)
    fmaps, emaps = build_index_maps(path)
    assert set(fmaps) == {"global", "user_re"}
    assert set(emaps) == {"userId"}
    ds = read_game_dataset(path, fmaps, emaps,
                           dense_shards=("global", "user_re"))
    assert ds.n == len(data["labels"])
    np.testing.assert_allclose(ds.labels, data["labels"])
    # Dense round trip recovers the feature matrix up to column order
    # (index maps sort by name: f0, f1, ...; verify via the map).
    x = np.zeros_like(data["x"], dtype=np.float32)
    for j in range(data["x"].shape[1]):
        x[:, fmaps["global"].get_feature(f"f{j}")] = data["x"][:, j]
    np.testing.assert_allclose(ds.features["global"], x, rtol=1e-6)
    # Entity columns group identically to the original ids.
    uids = data["user_ids"]
    col = ds.entity_ids["userId"]
    for u in np.unique(uids)[:5]:
        sel = uids == u
        assert len(np.unique(col[sel])) == 1


def test_jsonl_reader_handles_avro_style_dicts_and_dups(tmp_path):
    path = str(tmp_path / "d.jsonl")
    recs = [
        {"label": 1.0,
         "features": {"s": [{"name": "a", "term": "t", "value": 2.0},
                            ["a", "t", 3.0], ["b", "", 1.0]]}},
        {"label": 0.0, "weight": 2.5, "offset": 0.5,
         "features": {"s": [["unknown", "", 9.9]]}},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    fmaps = {"s": IndexMap(index={feature_key("a", "t"): 0, "b": 1})}
    ds = read_game_dataset(path, fmaps)
    c0, v0 = ds.features["s"][0]
    # duplicate (a,t) summed; unknown feature dropped
    assert dict(zip(c0.tolist(), v0.tolist())) == {0: 5.0, 1: 1.0}
    assert len(ds.features["s"][1][0]) == 0
    assert ds.weights[1] == 2.5 and ds.offsets[1] == 0.5


# ---------------------------------------------------------------------------
# Drivers end-to-end (from files alone)
# ---------------------------------------------------------------------------

def test_feature_indexing_driver(tmp_path):
    path = str(tmp_path / "train.jsonl")
    _write_jsonl_fixture(path)
    sizes = feature_indexing_driver.main(
        ["--input", path, "--output-dir", str(tmp_path / "maps")]
    )
    assert sizes["features"]["global"] == 6
    assert sizes["entities"]["userId"] >= 10
    fmaps, emaps = load_index_maps(str(tmp_path / "maps"))
    assert len(fmaps["global"]) == 6


def test_training_and_scoring_drivers_libsvm(tmp_path):
    """BASELINE config-1 class: fixed-effect logistic on a1a-like LIBSVM."""
    rows, labels, _ = make_a1a_like(n=1200, seed=5)
    train_path = str(tmp_path / "a1a.libsvm")
    write_libsvm(train_path, rows[:1000], np.where(labels[:1000] > 0, 1, -1))
    valid_path = str(tmp_path / "a1a.t.libsvm")
    write_libsvm(valid_path, rows[1000:], np.where(labels[1000:] > 0, 1, -1))

    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [{
            "name": "global", "kind": "FIXED_EFFECT",
            "feature_shard": "features",
            "optimizer": {"optimizer": "LBFGS", "reg_weight": 1.0,
                          "max_iters": 100},
        }],
        "update_sequence": ["global"],
        "input_path": train_path,
        "validation_path": valid_path,
        "output_dir": str(tmp_path / "out"),
        "evaluators": ["AUC"],
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)

    summary = game_training_driver.main(["--config", cfg_path])
    assert os.path.isdir(tmp_path / "out" / "model")
    auc = summary["models"][0]["evaluations"]["AUC"]
    assert auc > 0.80, f"a1a-class AUC gate failed: {auc}"

    # Phase timers landed in the structured log.
    events = read_run_log(str(tmp_path / "out" / "run_log.jsonl"))
    phases = {e["phase"] for e in events if e["event"] == "phase_end"}
    assert {"read_training_data", "fit", "save_models"} <= phases

    # Score the validation file with the saved model.
    score_cfg = {
        "input_path": valid_path,
        "model_dir": str(tmp_path / "out" / "model"),
        "output_path": str(tmp_path / "scores" / "s.npz"),
        "evaluators": ["AUC"],
    }
    sc_path = str(tmp_path / "score_cfg.json")
    with open(sc_path, "w") as f:
        json.dump(score_cfg, f)
    result = game_scoring_driver.main(["--config", sc_path])
    assert abs(result["evaluation"]["AUC"] - auc) < 1e-5
    out = np.load(score_cfg["output_path"])
    assert out["scores"].shape == (200,)
    # predictions are sigmoid(margins)
    np.testing.assert_allclose(
        out["predictions"], 1 / (1 + np.exp(-out["scores"])), rtol=1e-5
    )


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_training_and_scoring_drivers_game_jsonl(tmp_path):
    """BASELINE config-4 class: fixed + per-user RE from JSONL files."""
    train_path = str(tmp_path / "train.jsonl")
    data = make_movielens_like(n_users=30, n_items=10, n_obs=1500,
                               dim_global=6, seed=9)
    n_tr = 1200
    write_game_dataset(
        train_path,
        labels=data["labels"][:n_tr],
        features={
            "global": data["x"][:n_tr].astype(np.float32),
            "user_re": np.ones((n_tr, 1), np.float32),
        },
        ids={"userId": data["user_ids"][:n_tr]},
    )
    valid_path = str(tmp_path / "valid.jsonl")
    write_game_dataset(
        valid_path,
        labels=data["labels"][n_tr:],
        features={
            "global": data["x"][n_tr:].astype(np.float32),
            "user_re": np.ones((len(data["labels"]) - n_tr, 1), np.float32),
        },
        ids={"userId": data["user_ids"][n_tr:]},
    )

    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [
            {"name": "global", "kind": "FIXED_EFFECT",
             "feature_shard": "global",
             "optimizer": {"reg_weight": 1.0, "max_iters": 80}},
            {"name": "per_user", "kind": "RANDOM_EFFECT",
             "feature_shard": "user_re", "entity_key": "userId",
             "optimizer": {"reg_weight": 2.0, "max_iters": 40}},
        ],
        "update_sequence": ["global", "per_user"],
        "n_iterations": 2,
        "input_path": train_path,
        "validation_path": valid_path,
        "dense_feature_shards": ["global", "user_re"],
        "output_dir": str(tmp_path / "out"),
        "evaluators": ["AUC"],
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)

    summary = game_training_driver.main(["--config", cfg_path])
    auc_game = summary["models"][0]["evaluations"]["AUC"]
    assert auc_game > 0.70

    # Fixed-only comparison: the RE coordinate must add validation AUC.
    config_fixed = dict(config)
    config_fixed["coordinates"] = [config["coordinates"][0]]
    config_fixed["update_sequence"] = ["global"]
    config_fixed["n_iterations"] = 1
    config_fixed["output_dir"] = str(tmp_path / "out_fixed")
    cfg2 = str(tmp_path / "cfg_fixed.json")
    with open(cfg2, "w") as f:
        json.dump(config_fixed, f)
    summary_fixed = game_training_driver.main(["--config", cfg2])
    auc_fixed = summary_fixed["models"][0]["evaluations"]["AUC"]
    assert auc_game > auc_fixed + 0.02

    # Index maps were persisted for scoring parity.
    assert os.path.isdir(tmp_path / "out" / "index_maps")

    # Score validation through the scoring driver; AUC must reproduce.
    # dense_feature_shards deliberately omitted: the driver derives the
    # dense requirement from the model's non-projected random effects.
    score_cfg = {
        "input_path": valid_path,
        "model_dir": str(tmp_path / "out" / "model"),
        "output_path": str(tmp_path / "scores.npz"),
        "evaluators": ["AUC"],
    }
    sc_path = str(tmp_path / "score.json")
    with open(sc_path, "w") as f:
        json.dump(score_cfg, f)
    result = game_scoring_driver.main(["--config", sc_path])
    assert abs(result["evaluation"]["AUC"] - auc_game) < 1e-5


def test_training_driver_validation_split_and_grid(tmp_path):
    """λ-grid model selection with an internal validation split."""
    train_path = str(tmp_path / "train.jsonl")
    _write_jsonl_fixture(train_path, n_users=20, n_obs=800, seed=13)
    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [{
            "name": "global", "kind": "FIXED_EFFECT",
            "feature_shard": "global",
            "optimizer": {"reg_weight": 1.0, "max_iters": 60},
        }],
        "update_sequence": ["global"],
        "input_path": train_path,
        "validation_fraction": 0.25,
        "dense_feature_shards": ["global"],
        # Heavy-regularization point first so the best grid point is NOT
        # index 0 (regression: best_index must use identity, not ==).
        "reg_weight_grid": {"global": [3000.0, 1.0, 0.01]},
        "model_output_mode": "ALL",
        "output_dir": str(tmp_path / "out"),
        "evaluators": ["AUC"],
        "seed": 1,
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    summary = game_training_driver.main(["--config", cfg_path])
    assert len(summary["models"]) == 3
    for i in range(3):
        assert os.path.isdir(tmp_path / "out" / f"model_{i}")
    aucs = [m["evaluations"]["AUC"] for m in summary["models"]]
    assert aucs[summary["best_index"]] == max(aucs)
    assert summary["best_index"] != 0


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_scoring_unseen_entities_and_oov_features(tmp_path):
    """Cold-start: unknown entity ids score 0 from the RE coordinate;
    out-of-vocabulary LIBSVM feature indices are dropped, not dotted."""
    train_path = str(tmp_path / "train.jsonl")
    data = _write_jsonl_fixture(train_path, n_users=15, n_obs=600, seed=17)
    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [
            {"name": "global", "kind": "FIXED_EFFECT",
             "feature_shard": "global",
             "optimizer": {"reg_weight": 1.0, "max_iters": 60}},
            {"name": "per_user", "kind": "RANDOM_EFFECT",
             "feature_shard": "user_re", "entity_key": "userId",
             "optimizer": {"reg_weight": 2.0, "max_iters": 30}},
        ],
        "update_sequence": ["global", "per_user"],
        "input_path": train_path,
        "dense_feature_shards": ["global", "user_re"],
        "output_dir": str(tmp_path / "out"),
        "evaluators": [],
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    game_training_driver.main(["--config", cfg_path])

    # Two identical rows, one with a trained user, one with a never-seen
    # user id: margins must differ exactly by the per-user effect, and
    # the unknown user's margin must equal the fixed-effect-only margin.
    x = data["x"][0].astype(np.float32)
    score_path = str(tmp_path / "score.jsonl")
    feats = {"global": np.stack([x, x]),
             "user_re": np.ones((2, 1), np.float32)}
    write_game_dataset(score_path, labels=np.zeros(2, np.float32),
                       features=feats,
                       ids={"userId": np.asarray(
                           [data["user_ids"][0], 10**9])})
    score_cfg = {
        "input_path": score_path,
        "model_dir": str(tmp_path / "out" / "model"),
        "output_path": str(tmp_path / "s.npz"),
    }
    sc = str(tmp_path / "sc.json")
    with open(sc, "w") as f:
        json.dump(score_cfg, f)
    game_scoring_driver.main(["--config", sc])
    out = np.load(score_cfg["output_path"])

    from photon_ml_tpu.io.model_io import load_game_model
    model, _ = load_game_model(str(tmp_path / "out" / "model"))
    w_fixed = np.asarray(model.models["global"].coefficients.means)
    fixed_margin = float(x @ w_fixed[:-1] + w_fixed[-1])
    assert abs(out["scores"][1] - fixed_margin) < 1e-4
    assert abs(out["scores"][0] - out["scores"][1]) > 1e-3


def test_scoring_driver_avro_roundtrip_and_streamed_parity(tmp_path):
    """ISSUE 4 satellite: scoring-driver end-to-end Avro round trip
    (schema fields, entity ids, prediction-space values) plus the
    streamed pipeline reproducing the resident driver output through a
    config with streaming knobs (npz AND avro sinks, spill tier on)."""
    train_path = str(tmp_path / "train.jsonl")
    data = _write_jsonl_fixture(train_path, n_users=20, n_obs=600,
                                seed=23)
    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [
            {"name": "global", "kind": "FIXED_EFFECT",
             "feature_shard": "global",
             "optimizer": {"reg_weight": 1.0, "max_iters": 60}},
            {"name": "per_user", "kind": "RANDOM_EFFECT",
             "feature_shard": "user_re", "entity_key": "userId",
             "optimizer": {"reg_weight": 2.0, "max_iters": 30}},
        ],
        "update_sequence": ["global", "per_user"],
        "input_path": train_path,
        "dense_feature_shards": ["global", "user_re"],
        "output_dir": str(tmp_path / "out"),
        "evaluators": [],
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    game_training_driver.main(["--config", cfg_path])

    def score(**overrides):
        sc = {"input_path": train_path,
              "model_dir": str(tmp_path / "out" / "model"),
              "evaluators": ["AUC", "RMSE", "LOGISTIC_LOSS"]}
        sc.update(overrides)
        path = str(tmp_path / "sc.json")
        with open(path, "w") as f:
            json.dump(sc, f)
        return game_scoring_driver.main(["--config", path])

    # Resident reference (npz).
    res = score(output_path=str(tmp_path / "resident.npz"))
    ref = np.load(str(tmp_path / "resident.npz"))

    # Avro round trip: ScoringResultAvro fields through the generic
    # container reader.
    from photon_ml_tpu.io.avro import read_container

    score(output_path=str(tmp_path / "scores.avro"))
    _, recs = read_container(str(tmp_path / "scores.avro"))
    recs = list(recs)
    assert len(recs) == len(ref["scores"])
    assert set(recs[0]) == {"uid", "predictionScore", "label", "ids"}
    assert [r["uid"] for r in recs[:5]] == [0, 1, 2, 3, 4]
    np.testing.assert_allclose(
        [r["predictionScore"] for r in recs], ref["predictions"],
        rtol=1e-6)
    np.testing.assert_allclose(
        [r["label"] for r in recs], ref["labels"], atol=1e-9)
    # Entity-id map: every record tags its (index-mapped) userId.
    uid_col = np.asarray(
        [int(r["ids"]["userId"]) for r in recs])
    assert len(np.unique(uid_col)) == len(np.unique(data["user_ids"]))

    # Streamed arm (npz + spill + streaming evaluation): same scores,
    # same evaluation to tolerance.
    streamed = score(output_path=str(tmp_path / "streamed.npz"),
                     score_chunk_rows=128,
                     spill_dir=str(tmp_path / "spill"),
                     host_max_resident=1, prefetch_depth=2)
    out = np.load(str(tmp_path / "streamed.npz"))
    np.testing.assert_allclose(out["scores"], ref["scores"], atol=2e-5)
    np.testing.assert_allclose(out["predictions"], ref["predictions"],
                               atol=2e-5)
    np.testing.assert_array_equal(out["labels"], ref["labels"])
    for k, v in res["evaluation"].items():
        assert abs(streamed["evaluation"][k] - v) < 5e-4, k
    assert os.path.isdir(tmp_path / "spill" / "chunks")

    # Streamed avro equals resident avro record-for-record.
    score(output_path=str(tmp_path / "streamed.avro"),
          score_chunk_rows=128)
    _, recs_s = read_container(str(tmp_path / "streamed.avro"))
    recs_s = list(recs_s)
    np.testing.assert_allclose(
        [r["predictionScore"] for r in recs_s],
        [r["predictionScore"] for r in recs], atol=2e-5)
    assert [r["ids"] for r in recs_s[:20]] == [r["ids"]
                                               for r in recs[:20]]


def test_scoring_config_validation():
    from photon_ml_tpu.config import scoring_config_from_json

    with pytest.raises(ValueError, match="score_chunk_rows"):
        scoring_config_from_json(json.dumps({
            "input_path": "x", "model_dir": "m",
            "score_chunk_rows": 0}))
    with pytest.raises(ValueError, match="spill_dir requires"):
        scoring_config_from_json(json.dumps({
            "input_path": "x", "model_dir": "m", "spill_dir": "/tmp/s"}))
    cfg = scoring_config_from_json(json.dumps({
        "input_path": "x", "model_dir": "m",
        "score_chunk_rows": 4096, "spill_dir": "/tmp/s",
        "prefetch_depth": 0}))
    assert cfg.score_chunk_rows == 4096


def test_read_libsvm_drops_out_of_range_indices(tmp_path):
    from photon_ml_tpu.io.libsvm import read_libsvm

    path = str(tmp_path / "d.libsvm")
    with open(path, "w") as f:
        f.write("+1 1:1.0 5:2.0 9:3.0\n")
    rows, _, dim = read_libsvm(path, n_features=5)
    assert dim == 5
    np.testing.assert_array_equal(rows[0][0], [0, 4])


def test_driver_distributed_init_single_process(tmp_path):
    """Multi-host scaffolding (SURVEY §7 stage 9): distributed_init=true
    joins the JAX coordination service before backend use.  With a
    1-process coordinator config this must work end to end; real DCN
    scale-out only changes the env vars."""
    import subprocess
    import sys

    rows, labels, _ = make_a1a_like(n=300, seed=5)
    train_path = str(tmp_path / "d.libsvm")
    write_libsvm(train_path, rows, np.where(labels > 0, 1, -1))
    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [{
            "name": "global", "kind": "FIXED_EFFECT",
            "feature_shard": "features",
            "optimizer": {"reg_weight": 1.0, "max_iters": 20},
        }],
        "update_sequence": ["global"],
        "input_path": train_path,
        "output_dir": str(tmp_path / "out"),
        "distributed_init": True,
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    import socket

    with socket.socket() as s:  # grab a currently-free port
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "JAX_COORDINATOR_ADDRESS": f"localhost:{port}",
        "JAX_NUM_PROCESSES": "1",
        "JAX_PROCESS_ID": "0",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.cli.game_training_driver",
         "--config", cfg_path],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert os.path.isdir(tmp_path / "out" / "model")
