"""Chunk-accumulated (beyond-HBM) training: chunked ≡ resident.

Round-4 verdict item #2: the objective is a pure sum over examples, so
streaming K congruent chunk batches through the device and accumulating
partials must reproduce the resident path exactly (float reordering
only) — for value/gradient/HVP/Hessian-diagonal, for the host-driven
streaming L-BFGS/OWL-QN solver, through the estimator, and composed
with the 8-device mesh (chunks × shards).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import make_sparse_batch
from photon_ml_tpu.data.chunked_batch import build_chunked_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim.base import OptimizerConfig
from photon_ml_tpu.optim.lbfgs import lbfgs_solve
from photon_ml_tpu.optim.streaming import (
    ChunkedGLMObjective,
    streaming_lbfgs_solve,
)


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _sparse_problem(rng, n=2000, d=900, k=8):
    cols = np.stack([
        np.sort(rng.choice(d, k, replace=False)) for _ in range(n)
    ]).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    w_true = rng.normal(0, 0.8, d) * (rng.uniform(size=d) < 0.3)
    m = np.einsum("nk,nk->n", vals, w_true[cols])
    labels = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float32)
    weights = rng.uniform(0.5, 1.5, n).astype(np.float32)
    offsets = rng.normal(0, 0.1, n).astype(np.float32)
    indptr = np.arange(n + 1, dtype=np.int64) * k
    rows = SparseRows.from_flat(indptr, cols.reshape(-1).astype(np.int64),
                                vals.reshape(-1))
    return rows, cols, vals, labels, weights, offsets


def _objective(reg=None):
    from photon_ml_tpu.ops.regularization import RegularizationContext

    return GLMObjective(
        loss=losses.LOGISTIC,
        reg=reg if reg is not None else RegularizationContext.l2(0.7),
        norm=NormalizationContext.identity(),
    )


@pytest.mark.parametrize("layout", ["ell", "grr"])
@pytest.mark.parametrize("max_resident", [0, 8])
def test_chunked_matches_resident(rng, layout, max_resident):
    rows, cols, vals, labels, weights, offsets = _sparse_problem(rng)
    d = 900
    obj = _objective()
    resident = make_sparse_batch(rows, d, labels, weights=weights,
                                 offsets=offsets)
    cb = build_chunked_batch(rows, d, labels, weights=weights,
                             offsets=offsets, n_chunks=3, layout=layout)
    assert cb.n_chunks == 3
    cobj = ChunkedGLMObjective(obj, cb, max_resident=max_resident)

    w = jnp.asarray(rng.normal(0, 0.2, d), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, d), jnp.float32)

    f_r, g_r = obj.value_and_gradient(w, resident)
    f_c, g_c = cobj.value_and_gradient(w)
    np.testing.assert_allclose(float(f_c), float(f_r), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_r),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(float(cobj.value(w)),
                               float(obj.value(w, resident)), rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(cobj.hessian_vector(w, v)),
        np.asarray(obj.hessian_vector(w, v, resident)),
        rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(cobj.hessian_diagonal(w)),
        np.asarray(obj.hessian_diagonal(w, resident)),
        rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        cobj.predict_margins(w),
        np.asarray(obj.predict_margins(w, resident)),
        rtol=2e-4, atol=2e-4)


def test_chunked_prior_and_reg_added_once(rng):
    """Example-independent terms (L2, Gaussian prior) must not scale
    with the chunk count."""
    from photon_ml_tpu.ops.prior import GaussianPrior

    rows, cols, vals, labels, weights, offsets = _sparse_problem(rng)
    d = 900
    prior = GaussianPrior.from_model(
        jnp.asarray(rng.normal(0, 0.3, d), jnp.float32),
        jnp.ones((d,), jnp.float32), 2.0)
    obj = _objective().replace(prior=prior)
    resident = make_sparse_batch(rows, d, labels, weights=weights,
                                 offsets=offsets)
    for n_chunks in (2, 5):
        cobj = ChunkedGLMObjective(
            obj, build_chunked_batch(rows, d, labels, weights=weights,
                                     offsets=offsets, n_chunks=n_chunks,
                                     layout="ell"))
        w = jnp.asarray(rng.normal(0, 0.2, d), jnp.float32)
        f_r, g_r = obj.value_and_gradient(w, resident)
        f_c, g_c = cobj.value_and_gradient(w)
        np.testing.assert_allclose(float(f_c), float(f_r), rtol=2e-5)
        np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_r),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("l1", [None, 0.05])
def test_streaming_lbfgs_matches_resident(rng, l1):
    from photon_ml_tpu.ops.regularization import RegularizationContext

    reg = (RegularizationContext.l2(0.5) if l1 is None
           else RegularizationContext.elastic_net(0.5, 0.3))
    rows, cols, vals, labels, weights, offsets = _sparse_problem(rng)
    d = 900
    obj = _objective(reg)
    resident = make_sparse_batch(rows, d, labels, weights=weights,
                                 offsets=offsets)
    cb = build_chunked_batch(rows, d, labels, weights=weights,
                             offsets=offsets, n_chunks=4, layout="ell")
    cobj = ChunkedGLMObjective(obj, cb, max_resident=4)
    cfg = OptimizerConfig(max_iters=80, tolerance=1e-5)
    w0 = jnp.zeros((d,), jnp.float32)
    l1_vec = None
    if l1 is not None:
        l1_vec = jnp.broadcast_to(reg.l1_weight, (d,))

    res_r = lbfgs_solve(lambda w: obj.value_and_gradient(w, resident),
                        w0, cfg, l1_weight=l1_vec)
    res_s = streaming_lbfgs_solve(cobj.value_and_gradient, w0, cfg,
                                  l1_weight=l1_vec)
    # Same convex problem, same algorithm: minima must agree tightly.
    np.testing.assert_allclose(float(res_s.value), float(res_r.value),
                               rtol=1e-5)
    # Coefficients: the OWL-QN orthant path can settle near-zero
    # coordinates ~1e-2 apart between float-summation orders while the
    # VALUES agree to 1e-5 (the L1 surface is flat there); 5e-3 was
    # marginal and failed on 1/900 coords at the seed.
    np.testing.assert_allclose(np.asarray(res_s.w), np.asarray(res_r.w),
                               rtol=1e-2, atol=1e-2)
    assert bool(res_s.converged) == bool(res_r.converged)
    if l1 is not None:
        # OWL-QN must produce sparsity, and the zero sets of the two
        # paths must agree in size (same orthant-wise solution).
        zeros_s = int(np.sum(np.asarray(res_s.w) == 0.0))
        zeros_r = int(np.sum(np.asarray(res_r.w) == 0.0))
        assert zeros_s > 20
        assert abs(zeros_s - zeros_r) <= max(10, zeros_r // 5)


def test_chunked_mesh_composes(rng):
    """chunks × shards: each chunk assembled example-sharded on the
    8-device mesh, partials psum-reduced, equal to resident."""
    from photon_ml_tpu.parallel.mesh import data_parallel_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    rows, cols, vals, labels, weights, offsets = _sparse_problem(rng)
    d = 900
    obj = _objective()
    resident = make_sparse_batch(rows, d, labels, weights=weights,
                                 offsets=offsets)
    mesh = data_parallel_mesh(8)
    cb = build_chunked_batch(rows, d, labels, weights=weights,
                             offsets=offsets, n_chunks=2, layout="ell",
                             mesh=mesh)
    cobj = ChunkedGLMObjective(obj, cb, max_resident=2)
    w = jnp.asarray(rng.normal(0, 0.2, d), jnp.float32)
    f_r, g_r = obj.value_and_gradient(w, resident)
    f_c, g_c = cobj.value_and_gradient(w)
    np.testing.assert_allclose(float(f_c), float(f_r), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_r),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        cobj.x_dot(w),
        np.asarray(resident.x_dot(w))[: cb.n],
        rtol=2e-4, atol=2e-4)


def test_estimator_chunked_fit_matches_resident(rng):
    """GameEstimator with chunk_rows ≡ the resident estimator (fixed
    effect + random effect CD, scoring through the transformer)."""
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
    )
    from photon_ml_tpu.estimators.game_estimator import GameEstimator
    from photon_ml_tpu.evaluation.evaluators import EvaluatorType
    from photon_ml_tpu.game.dataset import GameDataset
    from photon_ml_tpu.models.glm import TaskType

    n, d, k = 900, 120, 5
    cols = np.stack([
        np.sort(rng.choice(d, k, replace=False)) for _ in range(n)
    ]).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    ids = rng.integers(0, 12, n)
    w_true = rng.normal(0, 1, d)
    u_true = rng.normal(0, 1.0, 12)
    m = np.einsum("nk,nk->n", vals, w_true[cols]) + u_true[ids]
    y = (m + rng.normal(0, 0.3, n) > 0).astype(np.float32)
    x_re = np.ones((n, 1), np.float32)
    rows = [(cols[i], vals[i]) for i in range(n)]
    ds = GameDataset(labels=y, features={"f": rows, "per_user": x_re},
                     entity_ids={"user": ids}, feature_dims={"f": d})

    def cfg(**kw):
        return TrainingConfig(
            task_type=TaskType.LOGISTIC_REGRESSION,
            coordinates=[
                CoordinateConfig(
                    name="global", kind=CoordinateKind.FIXED_EFFECT,
                    feature_shard="f",
                    optimizer=OptimizerSettings(max_iters=60,
                                                reg_weight=1.0),
                ),
                CoordinateConfig(
                    name="user", kind=CoordinateKind.RANDOM_EFFECT,
                    feature_shard="per_user", entity_key="user",
                    optimizer=OptimizerSettings(max_iters=40,
                                                reg_weight=2.0),
                ),
            ],
            update_sequence=["global", "user"],
            n_iterations=2,
            evaluators=[EvaluatorType.AUC],
            validation_fraction=0.0,
            validate_per_iteration=False,
            intercept=False,
            **kw,
        )

    from photon_ml_tpu.estimators.game_transformer import GameTransformer

    fit_r = GameEstimator(cfg()).fit(ds)[0]
    fit_c = GameEstimator(cfg(chunk_rows=256, chunk_layout="ELL",
                              chunk_max_resident=8)).fit(ds)[0]
    w_r = np.asarray(fit_r.model.models["global"].coefficients.means)
    w_c = np.asarray(fit_c.model.models["global"].coefficients.means)
    np.testing.assert_allclose(w_c, w_r, rtol=5e-3, atol=5e-3)
    task = TaskType.LOGISTIC_REGRESSION
    s_r = GameTransformer(model=fit_r.model, task=task).transform(ds)
    s_c = GameTransformer(model=fit_c.model, task=task).transform(ds)
    np.testing.assert_allclose(np.asarray(s_c), np.asarray(s_r),
                               rtol=5e-3, atol=5e-3)


def test_chunked_config_validation():
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        NormalizationType,
        OptimizerSettings,
        TrainingConfig,
    )
    from photon_ml_tpu.models.glm import TaskType

    base = dict(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[CoordinateConfig(
            name="g", kind=CoordinateKind.FIXED_EFFECT,
            feature_shard="f", optimizer=OptimizerSettings())],
        update_sequence=["g"],
    )
    with pytest.raises(ValueError, match="chunk_rows"):
        TrainingConfig(chunk_rows=0, **base).validate()
    with pytest.raises(ValueError, match="normalization"):
        TrainingConfig(chunk_rows=100,
                       normalization=NormalizationType.STANDARDIZATION,
                       **base).validate()


def test_estimator_chunked_warm_start_prior(rng, tmp_path):
    """Incremental training composes with the chunked path: the
    Gaussian prior (example-independent) is added once, and warm-start
    coefficients seed the streaming solve."""
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
    )
    from photon_ml_tpu.estimators.game_estimator import GameEstimator
    from photon_ml_tpu.game.dataset import GameDataset
    from photon_ml_tpu.io.model_io import save_game_model
    from photon_ml_tpu.models.glm import TaskType

    n, d, k = 600, 80, 5
    cols = np.stack([np.sort(rng.choice(d, k, replace=False))
                     for _ in range(n)]).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    w_true = rng.normal(0, 1, d)
    m = np.einsum("nk,nk->n", vals, w_true[cols])
    y = (m + rng.normal(0, 0.3, n) > 0).astype(np.float32)
    rows = [(cols[i], vals[i]) for i in range(n)]
    ds = GameDataset(labels=y, features={"f": rows}, entity_ids={},
                     feature_dims={"f": d})

    def cfg(**kw):
        return TrainingConfig(
            task_type=TaskType.LOGISTIC_REGRESSION,
            coordinates=[CoordinateConfig(
                name="global", kind=CoordinateKind.FIXED_EFFECT,
                feature_shard="f",
                optimizer=OptimizerSettings(
                    max_iters=50, reg_weight=1.0,
                    variance_type="SIMPLE"))],
            update_sequence=["global"], n_iterations=1,
            validation_fraction=0.0, validate_per_iteration=False,
            intercept=False, **kw)

    # Stage 1: train resident, save with variances.
    fit1 = GameEstimator(cfg()).fit(ds)[0]
    mdir = str(tmp_path / "m")
    save_game_model(fit1.model, TaskType.LOGISTIC_REGRESSION, mdir)

    # Stage 2: chunked fit warm-started with the prior, vs resident
    # same-config fit — must agree.
    kw2 = dict(warm_start_model_dir=mdir, use_warm_start_as_prior=True,
               prior_weight=1.0)
    fit_r = GameEstimator(cfg(**kw2)).fit(ds)[0]
    fit_c = GameEstimator(cfg(chunk_rows=200, chunk_layout="ELL",
                              chunk_max_resident=4, **kw2)).fit(ds)[0]
    w_r = np.asarray(fit_r.model.models["global"].coefficients.means)
    w_c = np.asarray(fit_c.model.models["global"].coefficients.means)
    np.testing.assert_allclose(w_c, w_r, rtol=5e-3, atol=5e-3)
    # SIMPLE variances computed through the chunked Hessian diagonal
    v_c = fit_c.model.models["global"].coefficients.variances
    assert v_c is not None and np.all(np.asarray(v_c) > 0)


@pytest.mark.fast
def test_chunked_offsets_padding_grid_rule(rng):
    """Over-long offsets are accepted ONLY at the chunk padding grid
    (advisor finding: unconditional off[:n] silently mistrained on a
    genuinely mismatched caller); train and compute_variances share the
    rule."""
    from photon_ml_tpu.game.coordinates import ChunkedFixedEffectCoordinate
    from photon_ml_tpu.optim.base import OptimizerType
    from photon_ml_tpu.optim.variance import VarianceComputationType

    rows, cols, vals, labels, weights, offsets = _sparse_problem(
        rng, n=610, d=80, k=4)
    cb = build_chunked_batch(rows, 80, labels, weights=weights,
                             n_chunks=4, layout="ell")
    coord = ChunkedFixedEffectCoordinate(
        name="f", chunked=cb, objective=_objective(),
        optimizer=OptimizerType.LBFGS,
        config=OptimizerConfig(max_iters=2),
    )
    grid = cb.n_chunks * cb.chunk_rows
    assert grid > cb.n  # the shape actually exercises padding

    # Exact length and the padding grid both pass...
    np.testing.assert_array_equal(
        coord._coerce_offsets(np.zeros(cb.n, np.float32)),
        np.zeros(cb.n, np.float32))
    padded = np.arange(grid, dtype=np.float32)
    np.testing.assert_array_equal(
        coord._coerce_offsets(padded), padded[: cb.n])

    # ...anything else over-long raises, in train AND compute_variances.
    bad = np.zeros(cb.n + 7, np.float32)
    with pytest.raises(ValueError, match="padding grid"):
        coord.train(bad)
    with pytest.raises(ValueError, match="padding grid"):
        coord.compute_variances(
            jnp.zeros(cb.dim, jnp.float32), bad,
            VarianceComputationType.SIMPLE)
    # Under-long still fails loudly downstream (set_offsets contract).
    with pytest.raises(ValueError):
        coord.train(np.zeros(cb.n - 3, np.float32))


@pytest.mark.parametrize("precond", [True, False])
def test_streaming_tron_matches_resident(rng, precond):
    """ISSUE 17 tentpole: the host-driven streaming TRON (chunk-
    accumulated HVP passes feeding the Steihaug-CG inner loop) solves
    the same smooth strongly-convex problem as the resident
    ``tron_solve`` — same convergence flag, same final value,
    coefficients within float-accumulation tolerance (the Jacobi-
    preconditioned iterates take a different path; both land at the
    unique minimum)."""
    from photon_ml_tpu.optim.streaming import streaming_tron_solve
    from photon_ml_tpu.optim.tron import tron_solve

    rows, cols, vals, labels, weights, offsets = _sparse_problem(rng)
    d = 900
    obj = _objective()
    resident = make_sparse_batch(rows, d, labels, weights=weights,
                                 offsets=offsets)
    cb = build_chunked_batch(rows, d, labels, weights=weights,
                             offsets=offsets, n_chunks=4, layout="ell")
    cobj = ChunkedGLMObjective(obj, cb, max_resident=4)
    cfg = OptimizerConfig(max_iters=60, tolerance=1e-7)
    w0 = jnp.zeros((d,), jnp.float32)

    res_r = tron_solve(lambda w: obj.value_and_gradient(w, resident),
                       lambda w, v: obj.hessian_vector(w, v, resident),
                       w0, cfg)
    res_s = streaming_tron_solve(
        cobj.value_and_gradient, cobj.hvp_pass, w0, cfg,
        hessian_diag=cobj.hessian_diagonal if precond else None)
    assert bool(res_r.converged) and bool(res_s.converged)
    np.testing.assert_allclose(float(res_s.value), float(res_r.value),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(res_s.w), np.asarray(res_r.w),
                               rtol=1e-2, atol=2e-3)
    # The tracker planes are populated: slot 0 (initial) + one per
    # outer iteration, with the step norm and inner-CG count planes.
    kt = int(res_s.tracker.count)
    assert kt == int(res_s.iterations) + 1
    cg = np.asarray(res_s.tracker.ls_trials)[1:kt]
    assert np.all(cg >= 1)     # every outer iteration paid CG passes


def test_chunked_coordinate_tron_routes_and_swept_rejects(rng):
    """``ChunkedFixedEffectCoordinate`` routes TRON to the streaming
    TRON solver (ISSUE 17 lifts the previous chunked-path rejection)
    and matches the resident coordinate's solution; ``train_swept``
    keeps the documented L-BFGS-lanes-only contract."""
    from photon_ml_tpu.game.coordinates import (
        ChunkedFixedEffectCoordinate,
    )
    from photon_ml_tpu.optim.base import OptimizerType
    from photon_ml_tpu.optim.tron import tron_solve

    rows, cols, vals, labels, weights, offsets = _sparse_problem(
        rng, n=610, d=80, k=4)
    d = 80
    obj = _objective()
    cb = build_chunked_batch(rows, d, labels, weights=weights,
                             n_chunks=4, layout="ell")
    cfg = OptimizerConfig(max_iters=60, tolerance=1e-7)
    coord = ChunkedFixedEffectCoordinate(
        name="f", chunked=cb, objective=obj,
        optimizer=OptimizerType.TRON, config=cfg)
    w, res = coord.train(np.zeros(cb.n, np.float32))
    assert bool(res.converged)

    resident = make_sparse_batch(rows, d, labels, weights=weights)
    ref = tron_solve(
        lambda v: obj.value_and_gradient(v, resident),
        lambda v, u: obj.hessian_vector(v, u, resident),
        jnp.zeros((d,), jnp.float32), cfg)
    np.testing.assert_allclose(np.asarray(w), np.asarray(ref.w),
                               rtol=1e-2, atol=2e-3)

    from photon_ml_tpu.ops.regularization import (
        RegularizationType,
        SweptRegularization,
    )

    with pytest.raises(ValueError, match="LBFGS"):
        coord.train_swept(
            np.zeros(cb.n, np.float32),
            SweptRegularization.from_grid(RegularizationType.L2,
                                          [0.1, 1.0]))
