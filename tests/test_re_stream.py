"""Out-of-core random-effect training (ISSUE 5): streamed entity-bucket
solves must match the resident path to float tolerance on coefficients,
scores, and variances for every bucket mix × chunk grid; the chunk
store's LRU window must bound host residency; spilled chunks must be a
warm artifact across builds and survive corruption via lineage rebuild;
converged-entity retirement must shrink per-sweep work monotonically on
a converging fit without moving the final model beyond solver
tolerance; and the entity-sharded mesh variant must stream per-shard.
"""

import glob
import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.game.coordinates import (
    build_random_effect_coordinate,
    build_random_effect_coordinate_sparse,
    build_streamed_random_effect_coordinate,
)
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim import OptimizerConfig


def _objective(l2=0.5):
    return GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(l2),
        norm=NormalizationContext.identity(),
    )


def _ids(rng, mix: str, n: int) -> np.ndarray:
    if mix == "skewed":
        # Long tail of small entities + a head of heavy ones: several
        # size buckets, uneven fill.
        return np.concatenate([
            rng.integers(0, 30, (2 * n) // 3),
            rng.integers(100, 106, n - (2 * n) // 3),
        ])
    return rng.integers(0, 25, n)


def _dataset(rng, n=420, p=3, mix="skewed"):
    x = rng.normal(0, 1, (n, p)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    w = rng.uniform(0.5, 1.5, n).astype(np.float32)
    return GameDataset(labels=y, features={"re": x},
                       entity_ids={"u": _ids(rng, mix, n)},
                       weights=w)


CFG = OptimizerConfig(max_iters=50, tolerance=1e-7)


def _assert_blocks_close(a, b, atol=1e-6):
    assert len(a) == len(b)
    for ba, bb in zip(a, b):
        np.testing.assert_allclose(np.asarray(ba), np.asarray(bb),
                                   atol=atol, rtol=0)


@pytest.mark.parametrize("mix", ["skewed", "uniform"])
@pytest.mark.parametrize("chunk_entities", [1, 7, 512])
def test_streamed_matches_resident(rng, tmp_path, mix, chunk_entities):
    """Coefficients, scores, AND variances: streamed ≡ resident across
    bucket mixes × chunk grids (chunk 1 = one entity per chunk; 512 =
    one chunk per bucket).  Tolerance note: a different vmap lane count
    compiles a different f32 summation order, so the two solvers walk
    slightly different trajectories to the same optimum — both below
    the 1e-7 gradient tolerance; coefficients agree to the
    tolerance/curvature scale, not bitwise."""
    ds = _dataset(rng, mix=mix)
    offsets = jnp.asarray(rng.normal(0, 0.3, ds.n).astype(np.float32))
    res = build_random_effect_coordinate("u", ds, "re", _objective(),
                                         config=CFG)
    st = build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path),
        chunk_entities=chunk_entities, config=CFG, host_max_resident=2)
    w_r, _ = res.train(offsets)
    w_s, diag = st.train(offsets)
    assert diag["entities_solved"] == st.grouping.n_total_entities
    _assert_blocks_close(w_r, w_s, atol=1e-3)
    np.testing.assert_allclose(np.asarray(res.score(w_r)),
                               np.asarray(st.score(w_s)), atol=2e-3)
    _assert_blocks_close(res.compute_variance_blocks(w_r, offsets),
                         st.compute_variance_blocks(w_s, offsets),
                         atol=1e-3)


def test_streamed_sparse_projected_matches_resident(rng, tmp_path):
    """Sparse (subspace-projected) shards stream too: the projection
    blocks spill chunk-wise and the solve matches the resident
    projected coordinate."""
    n, d_re = 300, 12
    ids = _ids(rng, "skewed", n)
    rows = []
    for _ in range(n):
        k = rng.integers(1, 4)
        cols = rng.choice(d_re, size=k, replace=False).astype(np.int32)
        rows.append((cols, rng.normal(0, 1, k).astype(np.float32)))
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    ds = GameDataset(labels=y, features={"re": rows},
                     entity_ids={"u": ids}, feature_dims={"re": d_re})
    offsets = jnp.asarray(rng.normal(0, 0.3, n).astype(np.float32))
    res = build_random_effect_coordinate_sparse(
        "u", ds, "re", _objective(), global_dim=d_re, config=CFG)
    st = build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path),
        chunk_entities=5, config=CFG)
    assert st.projection is not None
    w_r, _ = res.train(offsets)
    w_s, _ = st.train(offsets)
    _assert_blocks_close(w_r, w_s)
    np.testing.assert_allclose(np.asarray(res.score(w_r)),
                               np.asarray(st.score(w_s)), atol=1e-6)


def test_lru_window_bound_and_sequential_order(rng, tmp_path):
    """At most host_max_resident decoded chunks live through build AND
    every training/scoring sweep; the sweep's store access is the
    deterministic ascending order."""
    ds = _dataset(rng)
    st = build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path),
        chunk_entities=4, config=CFG, host_max_resident=2)
    total = st.store.n_chunks
    assert total >= 6  # the bound must be a real claim
    offsets = jnp.asarray(rng.normal(0, 0.3, ds.n).astype(np.float32))
    w, _ = st.train(offsets)
    st.compute_variance_blocks(w, offsets)
    assert st.store.peak_resident <= 2
    st.store.assert_quiesced()
    # Each full pass visits chunks in ascending global order.
    log = st.store.access_log
    per_pass = [log[i:i + total] for i in range(0, len(log), total)]
    for chunk_ids in per_pass:
        assert chunk_ids == sorted(chunk_ids)


def test_warm_store_reuse_across_builds(rng, tmp_path):
    """Same data + config ⇒ the second build reuses every chunk file
    (spills == 0) and trains to the identical result."""
    ds = _dataset(rng)
    offsets = jnp.asarray(rng.normal(0, 0.3, ds.n).astype(np.float32))
    st1 = build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path),
        chunk_entities=6, config=CFG)
    assert st1.store.spills == st1.store.n_chunks
    w1, _ = st1.train(offsets)
    st2 = build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path),
        chunk_entities=6, config=CFG)
    assert st2.store.spills == 0
    w2, _ = st2.train(offsets)
    _assert_blocks_close(w1, w2)
    # Different data ⇒ different content key, no false sharing.
    ds2 = _dataset(np.random.default_rng(7))
    st3 = build_streamed_random_effect_coordinate(
        "u", ds2, "re", _objective(), spill_dir=str(tmp_path),
        chunk_entities=6, config=CFG)
    assert st3.store.key != st2.store.key


def test_corrupt_and_missing_chunks_rebuild_from_lineage(rng, tmp_path):
    """A deleted chunk file and a truncated one both rebuild from the
    original rows mid-sweep — the store can never fail a run."""
    ds = _dataset(rng)
    offsets = jnp.asarray(rng.normal(0, 0.3, ds.n).astype(np.float32))
    res = build_random_effect_coordinate("u", ds, "re", _objective(),
                                         config=CFG)
    w_r, _ = res.train(offsets)
    st = build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path),
        chunk_entities=4, config=CFG, host_max_resident=1)
    files = sorted(glob.glob(os.path.join(str(tmp_path), "chunks",
                                          f"{st.store.key}-*.npz")))
    assert len(files) == st.store.n_chunks >= 4
    os.remove(files[-1])
    with open(files[2], "r+b") as f:
        f.truncate(10)
    w_s, _ = st.train(offsets)
    assert st.store.rebuilds >= 2
    _assert_blocks_close(w_r, w_s)


def _cd_sweeps(coord, offsets_schedule, use_hook=True):
    """Emulated CD sweeps: train → (hook) per offsets step."""
    w = None
    solved = []
    for off in offsets_schedule:
        w, diag = coord.train(jnp.asarray(off), w)
        solved.append(diag.get("entities_solved")
                      if isinstance(diag, dict) else None)
        if use_hook and hasattr(coord, "retire_converged"):
            coord.retire_converged()
    return w, solved


def test_retirement_monotone_and_model_equivalent(rng, tmp_path):
    """On a converging fit (offsets frozen after the first sweep), the
    retired set grows monotonically — per-sweep solved entities shrink
    — and the final model matches retirement-off within solver
    tolerance.  Offset drift past the tolerance wakes entities."""
    ds = _dataset(rng)
    base = rng.normal(0, 0.3, ds.n).astype(np.float32)
    schedule = [base] * 4
    cfg = OptimizerConfig(max_iters=50, tolerance=1e-6)
    on = build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path / "on"),
        chunk_entities=6, config=cfg, retirement=True)
    off_ = build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path / "off"),
        chunk_entities=6, config=cfg, retirement=False)
    w_on, solved_on = _cd_sweeps(on, schedule)
    w_off, solved_off = _cd_sweeps(off_, schedule)
    E = on.grouping.n_total_entities
    assert solved_off == [E] * 4
    assert solved_on[0] == E
    # Monotone non-increasing, with real reduction by the last sweep.
    assert all(a >= b for a, b in zip(solved_on, solved_on[1:]))
    assert solved_on[-1] < E
    assert on.entities_retired > 0
    for ba, bb in zip(w_on, w_off):
        assert float(jnp.max(jnp.abs(ba - bb))) < 1e-5
    # Offsets drift wakes every retired entity.
    w_on, diag = on.train(jnp.asarray(base + 0.5), w_on)
    assert diag["entities_solved"] == E


def test_streamed_cd_loop_matches_resident(rng, tmp_path):
    """Full run_coordinate_descent (fixed + streamed RE, retirement ON
    via the CD hook) vs the all-resident loop: final coefficients and
    total scores agree within solver tolerance."""
    from photon_ml_tpu.data.batch import make_dense_batch
    from photon_ml_tpu.game.coordinate_descent import (
        run_coordinate_descent,
    )
    from photon_ml_tpu.game.coordinates import FixedEffectCoordinate
    from photon_ml_tpu.optim import OptimizationProblem

    ds = _dataset(rng)
    xg = rng.normal(0, 1, (ds.n, 5)).astype(np.float32)
    batch = make_dense_batch(xg, ds.labels, weights=ds.weight_array())
    fixed = FixedEffectCoordinate(
        name="fixed", batch=batch,
        problem=OptimizationProblem(objective=_objective(1.0),
                                    config=CFG))

    def run(re_coord):
        return run_coordinate_descent(
            coordinates={"fixed": fixed, "u": re_coord},
            update_sequence=["fixed", "u"], n_iterations=4)

    cd_r = run(build_random_effect_coordinate(
        "u", ds, "re", _objective(), config=CFG))
    cd_s = run(build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path),
        chunk_entities=6, config=CFG, retirement=True))
    np.testing.assert_allclose(np.asarray(cd_s.total_scores),
                               np.asarray(cd_r.total_scores), atol=1e-4)
    np.testing.assert_allclose(np.asarray(cd_s.coefficients["fixed"]),
                               np.asarray(cd_r.coefficients["fixed"]),
                               atol=1e-4)
    for br, bs in zip(cd_r.coefficients["u"], cd_s.coefficients["u"]):
        np.testing.assert_allclose(np.asarray(bs), np.asarray(br),
                                   atol=1e-4)


def test_mesh_streamed_matches_single_device(rng, tmp_path):
    """Entity-sharded streamed variant: chunk size rounds up to the
    mesh grid, every chunk entity-shards, results match the
    single-device streamed and resident paths."""
    from photon_ml_tpu.parallel.mesh import entity_mesh

    ds = _dataset(rng)
    offsets = jnp.asarray(rng.normal(0, 0.3, ds.n).astype(np.float32))
    res = build_random_effect_coordinate("u", ds, "re", _objective(),
                                         config=CFG)
    w_r, _ = res.train(offsets)
    mesh = entity_mesh(4)
    st = build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path),
        chunk_entities=6, config=CFG, mesh=mesh)
    assert st.chunk_entities % 4 == 0
    w_s, _ = st.train(offsets)
    _assert_blocks_close(w_r, w_s)
    np.testing.assert_allclose(np.asarray(res.score(w_r)),
                               np.asarray(st.score(w_s)), atol=1e-6)


def test_score_external_blocks_and_zero_shortcut(rng, tmp_path):
    """score() on blocks the coordinate did not train (warm-start /
    locked-coordinate scoring) streams a pass that matches the resident
    score; all-zero blocks short-circuit without touching the store."""
    ds = _dataset(rng)
    res = build_random_effect_coordinate("u", ds, "re", _objective(),
                                         config=CFG)
    st = build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path),
        chunk_entities=5, config=CFG)
    blocks = [jnp.asarray(rng.normal(0, 0.2, (e, p)).astype(np.float32))
              for (e, p) in st.coefficient_shapes]
    np.testing.assert_allclose(np.asarray(st.score(blocks)),
                               np.asarray(res.score(blocks)), atol=1e-6)
    loads_before = st.store.loads + st.store.hits
    zeros = st.initial_coefficients()
    assert not np.any(np.asarray(st.score(zeros)))
    assert st.store.loads + st.store.hits == loads_before


def test_external_warm_start_adopted(rng, tmp_path):
    """An externally supplied warm start (model import / checkpoint
    resume) is adopted — the solve continues from it exactly as the
    resident path does."""
    ds = _dataset(rng)
    offsets = jnp.asarray(rng.normal(0, 0.3, ds.n).astype(np.float32))
    cfg = OptimizerConfig(max_iters=3, tolerance=1e-7)
    res = build_random_effect_coordinate("u", ds, "re", _objective(),
                                         config=cfg)
    st = build_streamed_random_effect_coordinate(
        "u", ds, "re", _objective(), spill_dir=str(tmp_path),
        chunk_entities=6, config=cfg)
    warm = [jnp.asarray(rng.normal(0, 0.1, (e, p)).astype(np.float32))
            for (e, p) in st.coefficient_shapes]
    w_r, _ = res.train(offsets, [jnp.asarray(w) for w in warm])
    w_s, _ = st.train(offsets, warm)
    _assert_blocks_close(w_r, w_s)


def test_estimator_streamed_fit_matches_resident(rng, tmp_path):
    """GameEstimator end to end: re_chunk_entities produces the same
    model (coefficients + variances) as the resident fit, including
    the warm chunk-store second fit."""
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
    )
    from photon_ml_tpu.estimators.game_estimator import GameEstimator
    from photon_ml_tpu.models.glm import TaskType

    n = 300
    ds = GameDataset(
        labels=(rng.uniform(size=n) < 0.5).astype(np.float32),
        features={"g": rng.normal(0, 1, (n, 6)).astype(np.float32),
                  "re": rng.normal(0, 1, (n, 3)).astype(np.float32)},
        entity_ids={"u": _ids(rng, "skewed", n)})

    def cfg(re_chunk, spill):
        return TrainingConfig(
            task_type=TaskType.LOGISTIC_REGRESSION,
            coordinates=[
                CoordinateConfig(name="fixed",
                                 kind=CoordinateKind.FIXED_EFFECT,
                                 feature_shard="g",
                                 optimizer=OptimizerSettings(
                                     max_iters=25)),
                CoordinateConfig(name="per_u",
                                 kind=CoordinateKind.RANDOM_EFFECT,
                                 feature_shard="re", entity_key="u",
                                 optimizer=OptimizerSettings(
                                     max_iters=25,
                                     variance_type="SIMPLE")),
            ],
            update_sequence=["fixed", "per_u"], n_iterations=2,
            evaluators=[], re_chunk_entities=re_chunk, spill_dir=spill)

    m_r = GameEstimator(cfg(None, None)).fit(ds)[0].model.models
    m_s = GameEstimator(cfg(5, str(tmp_path))).fit(ds)[0].model.models
    np.testing.assert_allclose(
        np.asarray(m_s["fixed"].coefficients.means),
        np.asarray(m_r["fixed"].coefficients.means), atol=1e-5)
    for br, bs in zip(m_r["per_u"].coefficient_blocks,
                      m_s["per_u"].coefficient_blocks):
        np.testing.assert_allclose(np.asarray(bs), np.asarray(br),
                                   atol=1e-5)
    for vr, vs in zip(m_r["per_u"].variance_blocks,
                      m_s["per_u"].variance_blocks):
        np.testing.assert_allclose(np.asarray(vs), np.asarray(vr),
                                   atol=1e-5)


def test_config_validation_re_knobs(tmp_path):
    """re_chunk_entities is validated and wired: positivity, the
    spill-dir requirement (env fallback honored), spill_dir accepted
    for streamed REs without chunk_rows, JSON round trip."""
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        TrainingConfig,
        config_to_json,
        training_config_from_json,
    )
    from photon_ml_tpu.models.glm import TaskType

    def cfg(**kw):
        return TrainingConfig(
            task_type=TaskType.LOGISTIC_REGRESSION,
            coordinates=[CoordinateConfig(
                name="per_u", kind=CoordinateKind.RANDOM_EFFECT,
                feature_shard="re", entity_key="u")],
            update_sequence=["per_u"], **kw)

    with pytest.raises(ValueError, match="re_chunk_entities"):
        cfg(re_chunk_entities=0, spill_dir=str(tmp_path)).validate()
    with pytest.raises(ValueError, match="spill_dir"):
        cfg(re_chunk_entities=4).validate()
    env = os.environ.pop("PHOTON_ML_TPU_SPILL_DIR", None)
    try:
        os.environ["PHOTON_ML_TPU_SPILL_DIR"] = str(tmp_path)
        cfg(re_chunk_entities=4).validate()   # env fallback OK
    finally:
        os.environ.pop("PHOTON_ML_TPU_SPILL_DIR", None)
        if env is not None:
            os.environ["PHOTON_ML_TPU_SPILL_DIR"] = env
    # spill_dir legal with streamed REs and no chunked fixed effect.
    c = cfg(re_chunk_entities=4, spill_dir=str(tmp_path),
            re_retirement=False)
    c.validate()
    c2 = training_config_from_json(config_to_json(c))
    assert c2.re_chunk_entities == 4 and c2.re_retirement is False


def test_bucket_occupancy_stats(rng):
    """Occupancy satellite: fill fractions and padded-slot ratio are
    exact for a hand-checkable grouping."""
    from photon_ml_tpu.game.dataset import (
        bucket_occupancy,
        group_by_entity,
    )

    # 4 entities with 2 examples (cap 4), 1 entity with 16 (cap 16).
    ids = np.concatenate([np.repeat(np.arange(4), 2),
                          np.full(16, 99)])
    occ = bucket_occupancy(group_by_entity(ids, bucket_base=4))
    assert occ["entities"] == 5 and occ["examples"] == 24
    by_cap = {b["capacity"]: b for b in occ["buckets"]}
    assert by_cap[4]["entities"] == 4
    assert by_cap[4]["fill_fraction"] == pytest.approx(8 / 16)
    assert by_cap[16]["fill_fraction"] == pytest.approx(1.0)
    assert occ["total_slots"] == 32
    assert occ["padded_slot_ratio"] == pytest.approx(8 / 32)


def test_diag_fields_batched_reduce_and_dict(rng):
    """_diag_fields satellite: the batched-RE aggregation is one jitted
    reduction with the same numbers as the old per-bucket loop, and
    streamed-RE dict diagnostics pass through as-is."""
    from photon_ml_tpu.game.coordinate_descent import _diag_fields

    ds = _dataset(rng)
    coord = build_random_effect_coordinate("u", ds, "re", _objective(),
                                           config=CFG)
    offsets = jnp.asarray(rng.normal(0, 0.3, ds.n).astype(np.float32))
    _, results = coord.train(offsets)
    fields = _diag_fields(results)
    assert fields["entities"] == coord.grouping.n_total_entities
    assert fields["entities_converged"] == sum(
        int(jnp.sum(r.converged)) for r in results)
    assert fields["max_solver_iterations"] == max(
        int(jnp.max(r.iterations)) for r in results)
    d = {"entities": 5, "entities_solved": 3}
    assert _diag_fields(d) == d
