"""Resilient serving fleet (ISSUE 13): supervisor, frontend, and the
serving fault matrix.

The tier-1 matrix drives ``FleetSupervisor._step()`` directly against
IN-PROCESS stub replicas on a fake clock — no subprocesses, no sleeps
— so restart backoff, the circuit breaker, wedge detection, and the
rolling swap are deterministic.  The slow-marked e2e at the bottom
runs the real thing: two replica subprocesses, one SIGKILLed
mid-traffic, zero client-visible failures.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from photon_ml_tpu import telemetry
from photon_ml_tpu.config import ServingConfig
from photon_ml_tpu.reliability.faults import (
    Fault,
    FaultInjector,
    injected,
)
from photon_ml_tpu.serving.fleet import (
    BROKEN,
    DOWN,
    DRAINING,
    READY,
    STARTING,
    FleetSupervisor,
    ReplicaHandle,
)
from photon_ml_tpu.serving.frontend import FleetFrontend
from photon_ml_tpu.serving.http import HttpEndpoint, Readiness
from photon_ml_tpu.telemetry import monitor as _mon

pytestmark = pytest.mark.fast


@pytest.fixture(autouse=True)
def _no_leaked_sessions():
    assert _mon.active() is None and telemetry.active() is None
    yield
    leaked = []
    if _mon.active() is not None:
        _mon.active().close()
        leaked.append("monitor")
    if telemetry.active() is not None:
        telemetry.active().close()
        leaked.append("telemetry")
    assert not leaked, f"leaked sessions: {leaked}"


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


class StubReplica:
    """In-process fake replica: the real HTTP core (healthz + score
    echo), controllable readiness, killable."""

    def __init__(self, version: str = "v1"):
        self.version = version
        self.readiness = Readiness(READY)
        self.rc: int | None = None
        self.scored = 0
        self._ep = HttpEndpoint(
            {("POST", "/v1/score"): self._score},
            readiness=self.readiness, port=0)
        self._ep.start()
        self.url = f"http://127.0.0.1:{self._ep.port}"

    def _score(self, body: bytes):
        rows = json.loads(body)["rows"]
        self.scored += 1
        return 200, json.dumps({
            "margins": [float(r) for r in rows],
            "predictions": [2.0 * float(r) for r in rows],
            "model_version": self.version,
            "n": len(rows),
        }), "application/json"

    def kill(self, rc: int = -9) -> None:
        if self.rc is None:
            self.rc = rc
            self._ep.close()


class StubHandle(ReplicaHandle):
    def __init__(self, replica: StubReplica | None, rc: int = 1):
        self.replica = replica       # None = born dead (failed start)
        self._dead_rc = rc

    def poll(self):
        return self._dead_rc if self.replica is None \
            else self.replica.rc

    def url(self):
        return self.replica.url if self.replica is not None else None

    def terminate(self):
        self.kill()

    def kill(self):
        if self.replica is not None:
            self.replica.kill()

    def wait(self, timeout_s):
        return self.poll()


class StubLauncher:
    def __init__(self):
        self.launches: list[tuple[int, StubHandle]] = []
        self.dead_launches: dict[int, int] = {}   # idx -> born-dead n
        self.version = "v1"

    def launch(self, idx: int) -> StubHandle:
        if self.dead_launches.get(idx, 0) > 0:
            self.dead_launches[idx] -= 1
            h = StubHandle(None)
        else:
            h = StubHandle(StubReplica(self.version))
        self.launches.append((idx, h))
        return h

    def stub(self, idx: int) -> StubReplica:
        """Latest LIVE stub launched for replica ``idx``."""
        for i, h in reversed(self.launches):
            if i == idx and h.replica is not None:
                return h.replica
        raise AssertionError(f"no live stub for replica {idx}")

    def launch_count(self, idx: int | None = None) -> int:
        return len([1 for i, _h in self.launches
                    if idx is None or i == idx])

    def close(self):
        for _i, h in self.launches:
            h.kill()


def _cfg(tmp_path, **kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("probe_every_s", 0.05)
    kw.setdefault("probe_timeout_s", 1.0)
    kw.setdefault("unhealthy_after", 3)
    kw.setdefault("restart_backoff_s", 1.0)
    kw.setdefault("restart_backoff_max_s", 8.0)
    kw.setdefault("breaker_threshold", 3)
    kw.setdefault("breaker_window_s", 100.0)
    kw.setdefault("breaker_reset_s", 50.0)
    kw.setdefault("replica_ready_timeout_s", 30.0)
    kw.setdefault("request_timeout_s", 10.0)
    kw.setdefault("telemetry", "off")
    kw.setdefault("monitor", "off")
    return ServingConfig(model_dir=str(tmp_path / "mdl"), port=0, **kw)


def _fleet(tmp_path, watch_manifest=False, **kw):
    cfg = _cfg(tmp_path, **kw)
    launcher = StubLauncher()
    clock = _FakeClock()
    sup = FleetSupervisor(cfg, launcher=launcher, clock=clock,
                          workdir=str(tmp_path / "fleet"),
                          watch_manifest=watch_manifest)
    return sup, launcher, clock


def _states(sup):
    return [r.state for r in sup.replicas]


# ---------------------------------------------------------------------------
# supervisor: spawn / probe / restart / breaker
# ---------------------------------------------------------------------------


def test_supervisor_spawns_probes_and_reports_ready(tmp_path):
    sup, launcher, _clock = _fleet(tmp_path)
    try:
        sup.spawn_all()
        assert _states(sup) == [STARTING, STARTING]
        sup._step()
        assert _states(sup) == [READY, READY]
        st = sup.status()
        assert st["ready"] == 2 and st["size"] == 2
        assert st["restarts"] == 0
        assert all(r["url"] for r in st["replicas"])
    finally:
        sup.stop()
        launcher.close()


def test_supervisor_restarts_crashed_replica_with_backoff(tmp_path):
    """Crash → DOWN with the backoff delay, restarted after it, back
    READY with restart latency recorded and the counter pinned."""
    sup, launcher, clock = _fleet(tmp_path)
    tel = telemetry.start("metrics")
    try:
        sup.spawn_all()
        sup._step()
        launcher.stub(0).kill()          # crash replica 0
        sup._step()                      # death detected
        assert sup.replicas[0].state == DOWN
        assert sup.ready_count() == 1
        sup._step()                      # backoff (1 s) not elapsed
        assert sup.replicas[0].state == DOWN
        assert launcher.launch_count(0) == 1
        clock.tick(1.1)
        sup._step()                      # respawn
        assert sup.replicas[0].state == STARTING
        sup._step()                      # probe → ready
        assert sup.replicas[0].state == READY
        assert sup.replicas[0].restarts == 1
        # Detect→ready on the fake clock: the 1.1 s backoff window.
        assert sup.replicas[0].last_restart_s == pytest.approx(
            1.1, abs=0.01)
        assert tel.counter("fleet.replica_restarts") == 1
        assert sup.status()["last_restart_s"] == pytest.approx(
            1.1, abs=0.01)
    finally:
        sup.stop()
        launcher.close()
        tel.close()


def test_supervisor_backoff_doubles_until_ready_resets(tmp_path):
    """Consecutive failed starts double the backoff (bounded); a
    successful ready resets it."""
    sup, launcher, clock = _fleet(tmp_path, replicas=1,
                                  breaker_threshold=100)
    try:
        sup.spawn_all()
        sup._step()
        backoffs = []
        launcher.dead_launches[0] = 2    # next two launches born dead
        launcher.stub(0).kill()
        sup._step()                      # death → backoff 1
        backoffs.append(sup.replicas[0].backoff_s)
        clock.tick(sup.replicas[0].backoff_s + 0.01)
        sup._step()                      # respawn (born dead)
        sup._step()                      # death → backoff 2
        backoffs.append(sup.replicas[0].backoff_s)
        clock.tick(sup.replicas[0].backoff_s + 0.01)
        sup._step()
        sup._step()                      # death → backoff 4
        backoffs.append(sup.replicas[0].backoff_s)
        assert backoffs == [1.0, 2.0, 4.0]
        clock.tick(sup.replicas[0].backoff_s + 0.01)
        sup._step()                      # respawn (live now)
        sup._step()                      # ready
        assert sup.replicas[0].state == READY
        assert sup.replicas[0].backoff_s == 0.0
    finally:
        sup.stop()
        launcher.close()


def test_supervisor_wedge_via_healthz_fault_seam(tmp_path):
    """The serve.replica_healthz fault seam: unhealthy_after
    consecutive failed probes on a LIVE process kill and restart it
    (the wedged-replica path), with the wedge counter pinned."""
    sup, launcher, clock = _fleet(tmp_path, replicas=1)
    tel = telemetry.start("metrics")
    try:
        sup.spawn_all()
        sup._step()
        assert sup.replicas[0].state == READY
        inj = FaultInjector([Fault(site="serve.replica_healthz",
                                   kind="error", at=0, count=3)])
        with injected(inj):
            sup._step()                  # occurrence 0: fail 1
            sup._step()                  # fail 2
            assert sup.replicas[0].state == READY   # below threshold
            sup._step()                  # fail 3 → wedged
        assert sup.replicas[0].state == DOWN
        assert tel.counter("fleet.replica_wedged") == 1
        assert "wedged" in sup.replicas[0].last_error
        clock.tick(1.1)
        sup._step()                      # respawn
        sup._step()
        assert sup.replicas[0].state == READY
        assert sup.replicas[0].restarts == 1
    finally:
        sup.stop()
        launcher.close()
        tel.close()


def test_circuit_breaker_opens_then_half_open_closes(tmp_path):
    """breaker_threshold rapid failures open the breaker (no restarts
    for breaker_reset_s); the half-open attempt closes it when the
    replica comes back healthy."""
    sup, launcher, clock = _fleet(tmp_path, replicas=1,
                                  restart_backoff_s=0.0,
                                  restart_backoff_max_s=0.0,
                                  breaker_threshold=3,
                                  breaker_reset_s=50.0)
    tel = telemetry.start("metrics")
    try:
        sup.spawn_all()
        sup._step()
        launcher.dead_launches[0] = 99   # everything born dead now
        launcher.stub(0).kill()
        # Failure 1 (crash), then born-dead spawn/death cycles; the
        # third failure inside the window opens the breaker.
        for _ in range(8):
            clock.tick(0.01)
            sup._step()
            if sup.replicas[0].state == BROKEN:
                break
        assert sup.replicas[0].state == BROKEN
        assert tel.counter("fleet.breaker_opened") == 1
        spawns_at_open = launcher.launch_count(0)
        # Open breaker: NO restarts while the reset window runs.
        for _ in range(5):
            clock.tick(5.0)
            if clock.t - 1000.0 > 45.0:
                break
            sup._step()
            assert launcher.launch_count(0) == spawns_at_open
        # Past the reset: ONE half-open attempt.
        launcher.dead_launches[0] = 0    # healthy again
        clock.tick(60.0)
        sup._step()                      # half-open spawn
        assert launcher.launch_count(0) == spawns_at_open + 1
        sup._step()                      # probe → ready, breaker closes
        assert sup.replicas[0].state == READY
        assert sup.replicas[0].restart_times == []
        assert not sup.replicas[0].half_open
    finally:
        sup.stop()
        launcher.close()
        tel.close()


def test_circuit_breaker_failed_half_open_reopens(tmp_path):
    sup, launcher, clock = _fleet(tmp_path, replicas=1,
                                  restart_backoff_s=0.0,
                                  restart_backoff_max_s=0.0,
                                  breaker_threshold=2,
                                  breaker_reset_s=10.0)
    tel = telemetry.start("metrics")
    try:
        sup.spawn_all()
        sup._step()
        launcher.dead_launches[0] = 99
        launcher.stub(0).kill()
        for _ in range(6):
            clock.tick(0.01)
            sup._step()
            if sup.replicas[0].state == BROKEN:
                break
        assert sup.replicas[0].state == BROKEN
        clock.tick(11.0)
        sup._step()                      # half-open spawn (born dead)
        sup._step()                      # death → re-open
        assert sup.replicas[0].state == BROKEN
        assert tel.counter("fleet.breaker_opened") == 2
    finally:
        sup.stop()
        launcher.close()
        tel.close()


# ---------------------------------------------------------------------------
# frontend: routing, retry-once, shedding
# ---------------------------------------------------------------------------


def _frontend(tmp_path, **kw):
    sup, launcher, clock = _fleet(tmp_path, **kw)
    fe = FleetFrontend(sup.config, sup)
    fe.start()
    sup.spawn_all()
    sup._step()
    return sup, launcher, clock, fe


def _post(port, rows, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/score",
        data=json.dumps({"rows": rows}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def test_frontend_routes_and_balances(tmp_path):
    sup, launcher, _clock, fe = _frontend(tmp_path)
    try:
        for i in range(8):
            out = _post(fe.port, [float(i)])
            assert out["margins"] == [float(i)]
        # Least-outstanding with fewest-served tie-break: sequential
        # load spreads across both replicas.
        assert launcher.stub(0).scored == 4
        assert launcher.stub(1).scored == 4
        assert fe.stats()["requests"] == 8
        assert fe.stats()["retries"] == 0
    finally:
        fe.close()
        sup.stop()
        launcher.close()


def test_frontend_healthz_follows_fleet(tmp_path):
    sup, launcher, _clock, fe = _frontend(tmp_path)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fe.port}/healthz", timeout=10) as r:
            assert json.loads(r.read())["state"] == "ready"
        launcher.stub(0).kill()
        launcher.stub(1).kill()
        sup._step()                      # both dead → 0 ready
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{fe.port}/healthz", timeout=10)
        assert err.value.code == 503
    finally:
        fe.close()
        sup.stop()
        launcher.close()


def test_frontend_retries_exactly_once_on_dead_replica(tmp_path):
    """THE retry contract: a connection failure retries ONCE on a
    different replica; the client sees one success, the frontend
    counts one retry, and the dead replica's failure feedback lands in
    the supervisor."""
    sup, launcher, _clock, fe = _frontend(tmp_path)
    tel = telemetry.start("metrics")
    try:
        # Kill replica 0's socket WITHOUT telling the supervisor (no
        # _step): the frontend discovers it the hard way.
        launcher.stub(0).kill()
        out = _post(fe.port, [7.0])
        assert out["margins"] == [7.0]
        st = fe.stats()
        assert st["requests"] == 1
        assert st["retries"] == 1
        assert st["failed"] == 0
        assert tel.counter("serve.frontend_retries") == 1
        assert sup.replicas[0].probe_failures >= 1   # note_failure
    finally:
        fe.close()
        sup.stop()
        launcher.close()
        tel.close()


def test_frontend_sheds_503_with_retry_after_when_fleet_down(tmp_path):
    sup, launcher, _clock, fe = _frontend(tmp_path)
    tel = telemetry.start("metrics")
    try:
        launcher.stub(0).kill()
        launcher.stub(1).kill()
        sup._step()
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(fe.port, [1.0])
        assert err.value.code == 503
        assert err.value.headers.get("Retry-After") == "1"
        assert "no ready replica" in \
            json.loads(err.value.read().decode())["error"]
        assert fe.stats()["shed"] == 1
        assert tel.counter("serve.shed") == 1
    finally:
        fe.close()
        sup.stop()
        launcher.close()
        tel.close()


def test_frontend_retry_exhausted_is_502_not_hang(tmp_path):
    """Both replicas' sockets dead but the supervisor has not noticed
    yet: first attempt + one retry both fail → an answered 502."""
    sup, launcher, _clock, fe = _frontend(tmp_path)
    try:
        launcher.stub(0).kill()
        launcher.stub(1).kill()          # no _step: both look READY
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(fe.port, [1.0])
        assert err.value.code in (502, 503)
        st = fe.stats()
        assert st["retries"] == 1        # exactly one retry, no more
        assert st["failed"] + st["shed"] >= 1
    finally:
        fe.close()
        sup.stop()
        launcher.close()


def test_frontend_forwards_replica_sheds_verbatim(tmp_path):
    """A replica's 429/503 (admission shed) is the replica's verdict:
    forwarded with its Retry-After, counted as fleet-level shed, and
    NEVER retried on another replica."""
    sup, launcher, _clock, fe = _frontend(tmp_path, replicas=1)
    tel = telemetry.start("metrics")
    try:
        stub = launcher.stub(0)

        def shedding(body):
            from photon_ml_tpu.serving.http import HttpError

            raise HttpError(503, headers={"Retry-After": "9"},
                            error="estimated queue wait exceeds "
                                  "deadline")

        stub._ep.routes[("POST", "/v1/score")] = shedding
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(fe.port, [1.0])
        assert err.value.code == 503
        assert err.value.headers.get("Retry-After") == "9"
        st = fe.stats()
        assert st["shed"] == 1 and st["retries"] == 0
        assert tel.counter("serve.shed_replica") == 1
    finally:
        fe.close()
        sup.stop()
        launcher.close()
        tel.close()


def test_frontend_status_aggregates_fleet_view(tmp_path):
    sup, launcher, _clock, fe = _frontend(tmp_path)
    try:
        _post(fe.port, [1.0])
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fe.port}/status", timeout=10) as r:
            st = json.loads(r.read())
        assert st["state"] == "ready"
        assert st["fleet"]["ready"] == 2
        assert len(st["fleet"]["replicas"]) == 2
        assert st["frontend"]["requests"] == 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fe.port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "photon_fleet_ready_replicas 2" in text
        assert "photon_frontend_requests_total 1" in text
    finally:
        fe.close()
        sup.stop()
        launcher.close()


# ---------------------------------------------------------------------------
# rolling hot swap
# ---------------------------------------------------------------------------


def _publish(tmp_path, content: str) -> None:
    mdl = tmp_path / "mdl"
    mdl.mkdir(exist_ok=True)
    (mdl / "metadata.json").write_text(content)


def test_rolling_swap_recycles_one_replica_at_a_time(tmp_path):
    """A new manifest rolls the fleet: cordon → drain → recycle →
    ready, one replica at a time — the fleet NEVER dips below N−1
    ready, and both replicas end on fresh processes."""
    _publish(tmp_path, "model-v1")
    sup, launcher, clock = _fleet(tmp_path, watch_manifest=True)
    try:
        sup.spawn_all()
        sup._step()
        assert sup.ready_count() == 2
        launcher.version = "v2"
        _publish(tmp_path, "model-v2-longer")   # signature changes
        min_ready = 2
        for _ in range(20):
            clock.tick(0.1)
            sup._step()
            min_ready = min(min_ready, sup.ready_count())
            if sup.swaps == 1:
                break
        assert sup.swaps == 1
        assert sup.swap_aborts == 0
        assert min_ready == 1               # never below N−1
        assert sup.ready_count() == 2
        # Four launches total: 2 initial + 2 recycles; recycles did
        # not count as crash restarts (the replica_restarts alert must
        # not fire on a deploy).
        assert launcher.launch_count() == 4
        assert sup.status()["restarts"] == 0
        # Recycle latency recorded (the restart-latency plumbing).
        assert all(r.last_restart_s is not None for r in sup.replicas)
    finally:
        sup.stop()
        launcher.close()


def test_rolling_swap_waits_for_draining_requests(tmp_path):
    _publish(tmp_path, "model-v1")
    sup, launcher, clock = _fleet(tmp_path, watch_manifest=True)
    try:
        sup.spawn_all()
        sup._step()
        # Pin an outstanding request on replica 0.
        r0 = sup.acquire_replica()
        assert r0.idx == 0
        _publish(tmp_path, "model-v2-longer")
        clock.tick(0.1)
        sup._step()                      # swap starts, cordons 0
        assert sup.replicas[0].state == DRAINING
        clock.tick(0.1)
        sup._step()                      # outstanding=1 → still waiting
        assert sup.replicas[0].state == DRAINING
        assert launcher.stub(0).rc is None      # NOT killed yet
        sup.release_replica(r0)
        clock.tick(0.1)
        sup._step()                      # drained → terminate
        assert launcher.launches[0][1].replica.rc is not None
        for _ in range(10):
            clock.tick(0.1)
            sup._step()
            if sup.swaps == 1:
                break
        assert sup.swaps == 1
    finally:
        sup.stop()
        launcher.close()


def test_rolling_swap_aborts_on_corrupt_publish_under_load(tmp_path):
    """The corrupt-swap matrix case: the first recycled replica cannot
    come up on the new manifest → the swap ABORTS, the other replica
    keeps serving the previous model, and clients see zero failures."""
    _publish(tmp_path, "model-v1")
    sup, launcher, clock = _fleet(tmp_path, watch_manifest=True,
                                  restart_backoff_s=0.0,
                                  restart_backoff_max_s=0.0,
                                  breaker_threshold=3)
    fe = FleetFrontend(sup.config, sup)
    fe.start()
    try:
        sup.spawn_all()
        sup._step()
        launcher.dead_launches[0] = 99   # replica 0 reborn dead forever
        _publish(tmp_path, "model-v2-corrupt")
        for _ in range(30):
            clock.tick(0.1)
            sup._step()
            # Under load THROUGHOUT the doomed swap: every request
            # must still succeed via the surviving replica.
            out = _post(fe.port, [3.0])
            assert out["margins"] == [3.0]
            if sup.swap_aborts == 1:
                break
        assert sup.swap_aborts == 1
        assert sup.last_swap_error is not None
        assert sup.replicas[1].state == READY    # old model serving
        assert fe.stats()["failed"] == 0
        # The aborted signature is adopted: no swap-retry storm.
        clock.tick(0.5)
        sup._step()
        assert sup.status()["swap_in_progress"] is False
    finally:
        fe.close()
        sup.stop()
        launcher.close()


def test_dead_replica_during_rolling_swap_pauses_then_completes(
        tmp_path):
    """The OTHER replica dying mid-swap pauses the roll (cordoning
    would drop the fleet to zero); the normal restart machinery
    revives it, then the swap resumes and completes."""
    _publish(tmp_path, "model-v1")
    sup, launcher, clock = _fleet(tmp_path, watch_manifest=True,
                                  restart_backoff_s=1.0)
    try:
        sup.spawn_all()
        sup._step()
        _publish(tmp_path, "model-v2-longer")
        # Kill replica 1 in the same instant the swap begins.
        launcher.stub(1).kill()
        clock.tick(0.1)
        sup._step()      # swap detected; replica 1 death detected
        # Replica 1 down → the swap must NOT cordon replica 0.
        assert sup.replicas[0].state == READY
        assert sup.status()["swap_in_progress"] is True
        clock.tick(0.1)
        sup._step()
        assert sup.replicas[0].state == READY    # still paused
        clock.tick(1.1)                          # backoff elapses
        for _ in range(20):
            clock.tick(0.1)
            sup._step()
            if sup.swaps == 1:
                break
        assert sup.swaps == 1
        assert sup.ready_count() == 2
        assert sup.replicas[1].restarts == 1     # the crash restart
    finally:
        sup.stop()
        launcher.close()


# ---------------------------------------------------------------------------
# e2e: real subprocess fleet, SIGKILL mid-traffic
# ---------------------------------------------------------------------------


@pytest.mark.slow   # two replica subprocesses + warm-up + kill/restart
def test_fleet_sigkill_e2e_zero_client_failures(tmp_path):
    """THE acceptance criterion: SIGKILL one of two replicas under
    sustained client traffic → zero failed client requests (affected
    requests succeed via the single bounded retry), the replica is
    restarted, re-warmed, and back in rotation, and the fleet reports
    the restart."""
    import os
    import signal

    from photon_ml_tpu.io import model_io
    from photon_ml_tpu.models.glm import TaskType
    from photon_ml_tpu.serving.engine import dataset_rows
    from photon_ml_tpu.serving.fleet import FleetServer
    from tests.test_serving import _workload

    model, dataset = _workload()
    mdir = str(tmp_path / "model")
    model_io.save_game_model(model, TaskType.LOGISTIC_REGRESSION, mdir)
    cfg = ServingConfig(
        model_dir=mdir, port=0, replicas=2, batch_rows=8,
        batch_deadline_ms=1.0, ell_row_capacity=8,
        spill_dir=str(tmp_path / "spill"), entity_chunk=4,
        probe_every_s=0.2, probe_timeout_s=2.0,
        restart_backoff_s=0.2, telemetry="off", monitor="off",
        compilation_cache_dir=str(tmp_path / "xla"))
    server = FleetServer(cfg, workdir=str(tmp_path / "fleet"))
    reqs = dataset_rows(dataset, 0, 8)
    try:
        server.start()
        assert server.supervisor.wait_ready(2, timeout_s=240.0), \
            server.supervisor.status()
        stop = threading.Event()
        errors: list = []
        ok = [0]
        lock = threading.Lock()

        def hammer():
            while not stop.is_set():
                try:
                    out = _post(server.port, reqs, timeout=30)
                    assert len(out["margins"]) == 8
                    with lock:
                        ok[0] += 1
                except Exception as e:   # noqa: BLE001 - collected
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for th in threads:
            th.start()
        time.sleep(1.0)
        victim = next(r for r in server.supervisor.status()["replicas"]
                      if r["state"] == "ready" and r["pid"])
        os.kill(victim["pid"], signal.SIGKILL)
        # Keep the traffic up across detection + restart + re-warm.
        deadline = time.time() + 180.0
        while time.time() < deadline:
            st = server.supervisor.status()
            if st["restarts"] >= 1 and st["ready"] == 2:
                break
            time.sleep(0.3)
        time.sleep(1.0)
        stop.set()
        for th in threads:
            th.join(timeout=60)
        st = server.supervisor.status()
        assert not errors, errors[:5]            # ZERO client failures
        assert ok[0] > 50
        assert st["restarts"] >= 1               # replica came back
        assert st["ready"] == 2
        assert st["last_restart_s"] is not None
        assert st["last_restart_s"] > 0
        # Post-recovery requests still score correctly.
        out = _post(server.port, reqs, timeout=30)
        assert len(out["margins"]) == 8
        fe = server.frontend.stats()
        assert fe["failed"] == 0
    finally:
        server.stop()
