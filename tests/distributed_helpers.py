"""Shared skip-guard for tests that need REAL multi-process CPU
collectives (``jax.distributed`` + cross-process psum).

Some jaxlib CPU backends cannot run multiprocess computations at all —
a worker that tries dies with the error text pinned as
``fleet.MULTIPROC_UNSUPPORTED_MARKER``.  These guards share the fleet
module's single cached capability probe instead of per-test ad-hoc
marker scans, so every multi-process test skips (or runs) on the same
verdict the bench's transport selection uses:

- ``require_multiprocess_collectives()`` — probe up front (one cached
  2-worker probe per test process) and ``pytest.skip`` when
  unsupported; for tests whose own workers are expensive enough that
  learning the answer first is cheaper.
- ``skip_if_multiprocess_wall(outs)`` — post-hoc: for tests whose own
  workers double as the probe, skip when any worker's output hit the
  backend's multiprocess wall.
"""

from __future__ import annotations

from collections.abc import Iterable

import pytest

from photon_ml_tpu.parallel import fleet

SKIP_REASON = ("this jaxlib's CPU backend has no multiprocess "
               "collectives; needs a newer jaxlib or real devices")


def require_multiprocess_collectives() -> None:
    """Skip the calling test unless this box can run real 2-process
    CPU collectives."""
    if not fleet.probe_cpu_multiprocess_collectives():
        pytest.skip(SKIP_REASON)


def skip_if_multiprocess_wall(outs: Iterable[str | None]) -> None:
    """Skip the calling test when any worker output shows the CPU
    backend's multiprocess wall."""
    if any(fleet.MULTIPROC_UNSUPPORTED_MARKER in (o or "")
           for o in outs):
        pytest.skip(SKIP_REASON)
