"""Out-of-core chunk store: spilled ≡ resident, RSS bounded, safe.

Round-8 tentpole (ISSUE 3): chunks spill to atomic content-keyed
``.npz`` files (``data/chunk_store.py``) with an LRU host window and a
background disk→host→device prefetch thread in ``optim.streaming``.
The contracts under test:

- round-trip equality — a spilled sweep reproduces the RAM-resident
  chunked path to float tolerance on value/grad/HVP/Hessian-diagonal,
  margins, the swept-λ surface, the streaming solver, the estimator,
  and composed with the 8-device mesh;
- the LRU bound holds (live decoded chunks never exceed
  ``host_max_resident``) and the chunk visit order stays deterministic
  under prefetch (the float-summation-order parity guarantee);
- corrupt or missing chunk files fall back to a lineage rebuild (and
  re-spill) — the store can never fail a run;
- spilled files are a warm-ETL artifact (same content key ⇒ rebuild
  skipped);
- ``invalidate()`` quiesces the prefetch pipeline before buffers are
  freed (no use-after-evict), stress-tested interleaved with sweeps.
"""

from __future__ import annotations

import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import make_sparse_batch
from photon_ml_tpu.data.chunked_batch import build_chunked_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import (
    RegularizationContext,
    RegularizationType,
    SweptRegularization,
)
from photon_ml_tpu.optim.base import OptimizerConfig
from photon_ml_tpu.optim.streaming import (
    ChunkedGLMObjective,
    streaming_lbfgs_solve,
)


@pytest.fixture
def rng():
    return np.random.default_rng(23)


def _sparse_problem(rng, n=2000, d=900, k=8):
    cols = np.stack([
        np.sort(rng.choice(d, k, replace=False)) for _ in range(n)
    ]).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    weights = rng.uniform(0.5, 1.5, n).astype(np.float32)
    offsets = rng.normal(0, 0.1, n).astype(np.float32)
    indptr = np.arange(n + 1, dtype=np.int64) * k
    rows = SparseRows.from_flat(indptr, cols.reshape(-1).astype(np.int64),
                                vals.reshape(-1))
    return rows, labels, weights, offsets


def _objective(reg=None):
    return GLMObjective(
        loss=losses.LOGISTIC,
        reg=reg if reg is not None else RegularizationContext.l2(0.7),
        norm=NormalizationContext.identity(),
    )


def _spilled(rng, tmp_path, layout="ell", n_chunks=6, window=2, depth=2,
             mesh=None, **prob_kw):
    rows, labels, weights, offsets = _sparse_problem(rng, **prob_kw)
    cb = build_chunked_batch(
        rows, 900, labels, weights=weights, offsets=offsets,
        n_chunks=n_chunks, layout=layout, mesh=mesh,
        spill_dir=str(tmp_path / "spill"), host_max_resident=window)
    cobj = ChunkedGLMObjective(_objective(), cb, max_resident=0,
                               prefetch_depth=depth)
    return rows, labels, weights, offsets, cb, cobj


@pytest.mark.parametrize("layout", ["ell", "grr"])
def test_spilled_matches_resident(rng, tmp_path, layout):
    """Spilled sweep ≡ resident batch on every objective surface."""
    rows, labels, weights, offsets, cb, cobj = _spilled(
        rng, tmp_path, layout=layout)
    assert cb.store is not None and cb.store.spills == cb.n_chunks
    resident = make_sparse_batch(rows, 900, labels, weights=weights,
                                 offsets=offsets)
    obj = _objective()
    w = jnp.asarray(rng.normal(0, 0.2, 900), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, 900), jnp.float32)

    f_r, g_r = obj.value_and_gradient(w, resident)
    f_c, g_c = cobj.value_and_gradient(w)
    np.testing.assert_allclose(float(f_c), float(f_r), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_r),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(float(cobj.value(w)),
                               float(obj.value(w, resident)), rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(cobj.hessian_vector(w, v)),
        np.asarray(obj.hessian_vector(w, v, resident)),
        rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(cobj.hessian_diagonal(w)),
        np.asarray(obj.hessian_diagonal(w, resident)),
        rtol=2e-4, atol=2e-4)
    # _per_example sweeps run the same prefetch pipeline.
    np.testing.assert_allclose(
        cobj.predict_margins(w),
        np.asarray(obj.predict_margins(w, resident)),
        rtol=2e-4, atol=2e-4)


def test_spilled_swept_lanes_match_resident_chunked(rng, tmp_path):
    """Swept-λ surface: spilled lanes ≡ resident chunked lanes (the
    batched grid path composes with the disk tier)."""
    rows, labels, weights, offsets, cb, cobj = _spilled(rng, tmp_path)
    reg = SweptRegularization.from_grid(RegularizationType.L2,
                                        [3.0, 0.7, 0.05])
    cb_res = build_chunked_batch(rows, 900, labels, weights=weights,
                                 offsets=offsets, n_chunks=6,
                                 layout="ell")
    co_res = ChunkedGLMObjective(_objective(), cb_res, max_resident=6)
    W = jnp.asarray(rng.normal(0, 0.2, (3, 900)), jnp.float32)
    F_r, G_r = co_res.value_and_gradient_swept(W, reg)
    F_s, G_s = cobj.value_and_gradient_swept(W, reg)
    np.testing.assert_allclose(np.asarray(F_s), np.asarray(F_r),
                               rtol=2e-5)
    np.testing.assert_allclose(np.asarray(G_s), np.asarray(G_r),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(cobj.value_swept(W, reg)),
                               np.asarray(co_res.value_swept(W, reg)),
                               rtol=2e-5)


def test_streaming_solver_spilled_matches_ram_resident(rng, tmp_path):
    """The full host-driven solve over the disk tier lands on the same
    optimum as the all-in-RAM chunked solve (chunk visit order and
    accumulation order are identical, so this is tight)."""
    rows, labels, weights, offsets, cb, cobj = _spilled(rng, tmp_path)
    cb_res = build_chunked_batch(rows, 900, labels, weights=weights,
                                 offsets=offsets, n_chunks=6,
                                 layout="ell")
    co_res = ChunkedGLMObjective(_objective(), cb_res, max_resident=6)
    cfg = OptimizerConfig(max_iters=40, tolerance=1e-5)
    w0 = jnp.zeros((900,), jnp.float32)
    res_r = streaming_lbfgs_solve(co_res.value_and_gradient, w0, cfg,
                                  value_fn=co_res.value)
    res_s = streaming_lbfgs_solve(cobj.value_and_gradient, w0, cfg,
                                  value_fn=cobj.value)
    np.testing.assert_allclose(float(res_s.value), float(res_r.value),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(res_s.w), np.asarray(res_r.w),
                               rtol=1e-3, atol=1e-3)
    assert cobj.sweeps == co_res.sweeps   # odometer parity


def test_lru_bound_and_deterministic_order(rng, tmp_path):
    """Live decoded chunks never exceed ``host_max_resident`` (the RSS
    proxy), and the store sees chunks in exactly the sweep order,
    sweep after sweep, despite the prefetch thread."""
    rows, labels, weights, offsets, cb, cobj = _spilled(
        rng, tmp_path, n_chunks=8, window=2, depth=3)
    w = jnp.asarray(rng.normal(0, 0.2, 900), jnp.float32)
    for _ in range(3):
        cobj.value_and_gradient(w)
    assert cb.store.peak_resident <= 2
    assert cb.store.n_resident <= 2
    assert cb.store.access_log == list(range(8)) * 3
    assert cb.store.rebuilds == 0


def test_corrupt_and_missing_chunk_fall_back_to_rebuild(rng, tmp_path):
    """A truncated or deleted chunk file degrades to a lineage rebuild
    (+ re-spill), never to a failure — plan-cache discipline."""
    rows, labels, weights, offsets, cb, cobj = _spilled(rng, tmp_path)
    resident = make_sparse_batch(rows, 900, labels, weights=weights,
                                 offsets=offsets)
    obj = _objective()
    w = jnp.asarray(rng.normal(0, 0.2, 900), jnp.float32)
    f_r = float(obj.value(w, resident))

    with open(cb.store.path(3), "wb") as f:
        f.write(b"not a zip")
    os.remove(cb.store.path(5))
    np.testing.assert_allclose(float(cobj.value(w)), f_r, rtol=2e-5)
    assert cb.store.rebuilds == 2
    # The fallback re-spilled both: the next sweep reads clean files.
    np.testing.assert_allclose(float(cobj.value(w)), f_r, rtol=2e-5)
    assert cb.store.rebuilds == 2


def test_spilled_store_is_warm_etl_artifact(rng, tmp_path):
    """Rebuilding the same dataset against the same spill_dir writes
    nothing: the content-keyed files double as a persistent warm-ETL
    cache, and the warm batch still sweeps correctly."""
    rows, labels, weights, offsets, cb, cobj = _spilled(rng, tmp_path)
    w = jnp.asarray(rng.normal(0, 0.2, 900), jnp.float32)
    f1 = float(cobj.value(w))
    mtimes = {i: os.path.getmtime(cb.store.path(i))
              for i in range(cb.n_chunks)}

    cb2 = build_chunked_batch(
        rows, 900, labels, weights=weights, offsets=offsets,
        n_chunks=6, layout="ell", spill_dir=str(tmp_path / "spill"),
        host_max_resident=2)
    assert cb2.store.spills == 0          # nothing rebuilt
    for i in range(cb2.n_chunks):
        assert os.path.getmtime(cb2.store.path(i)) == mtimes[i]
    cobj2 = ChunkedGLMObjective(_objective(), cb2, max_resident=0)
    np.testing.assert_allclose(float(cobj2.value(w)), f1, rtol=1e-6)

    # Different content (weights perturbed) keys a DIFFERENT store —
    # never a silent stale hit.
    cb3 = build_chunked_batch(
        rows, 900, labels, weights=weights * 2.0, offsets=offsets,
        n_chunks=6, layout="ell", spill_dir=str(tmp_path / "spill"),
        host_max_resident=2)
    assert cb3.store.key != cb2.store.key
    assert cb3.store.spills == cb3.n_chunks


def test_set_offsets_external_to_spilled_payload(rng, tmp_path):
    """``set_offsets`` must not rewrite chunk files (offsets are CD
    state, overlaid at access time) and the next sweep must see the
    new offsets."""
    rows, labels, weights, offsets, cb, cobj = _spilled(rng, tmp_path)
    w = jnp.asarray(rng.normal(0, 0.2, 900), jnp.float32)
    cobj.value(w)
    mtimes = [os.path.getmtime(cb.store.path(i))
              for i in range(cb.n_chunks)]
    new_off = rng.normal(0, 0.3, cb.n).astype(np.float32)
    cb.set_offsets(new_off)
    cobj.invalidate()
    resident = make_sparse_batch(rows, 900, labels, weights=weights,
                                 offsets=new_off)
    np.testing.assert_allclose(
        float(cobj.value(w)), float(_objective().value(w, resident)),
        rtol=2e-5)
    assert [os.path.getmtime(cb.store.path(i))
            for i in range(cb.n_chunks)] == mtimes


def test_invalidate_interleaved_with_sweeps_stress(rng, tmp_path):
    """Satellite: invalidate() quiesces the prefetch thread before
    anything is freed.  Interleave sweeps, offset updates, and
    invalidations across every surface; thread count must return to
    baseline (no leaked prefetchers) and values stay exact."""
    rows, labels, weights, offsets, cb, cobj = _spilled(
        rng, tmp_path, n_chunks=8, window=1, depth=3, n=1600)
    obj = _objective()
    w = jnp.asarray(rng.normal(0, 0.2, 900), jnp.float32)
    base_threads = threading.active_count()
    for step in range(6):
        off = rng.normal(0, 0.2, cb.n).astype(np.float32)
        cb.set_offsets(off)
        cobj.invalidate()
        resident = make_sparse_batch(rows, 900, labels,
                                     weights=weights, offsets=off)
        np.testing.assert_allclose(float(cobj.value(w)),
                                   float(obj.value(w, resident)),
                                   rtol=2e-5)
        if step % 2:
            cobj.predict_margins(w)   # _per_example pipeline too
        cobj.invalidate()             # idempotent, quiesced
    assert threading.active_count() <= base_threads + 1
    cb.store.assert_quiesced()        # no reader left behind
    cb.store.drop_resident()          # legal only when quiesced
    assert cb.store.n_resident == 0


def test_store_asserts_on_unquiesced_free():
    """Freeing the window under an active reader is a loud error."""
    from photon_ml_tpu.data.chunk_store import ChunkStore

    store = ChunkStore("/tmp/unused", "k", 1, host_max_resident=1)
    store.begin_read()
    with pytest.raises(RuntimeError, match="quiesce"):
        store.drop_resident()
    store.end_read()
    store.drop_resident()


def test_spilled_mesh_composes(rng, tmp_path):
    """chunks × shards × disk: spilled chunks assembled example-sharded
    on the 8-device mesh equal the resident batch."""
    from photon_ml_tpu.parallel.mesh import data_parallel_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    mesh = data_parallel_mesh(8)
    rows, labels, weights, offsets, cb, cobj = _spilled(
        rng, tmp_path, n_chunks=2, window=1, mesh=mesh)
    resident = make_sparse_batch(rows, 900, labels, weights=weights,
                                 offsets=offsets)
    obj = _objective()
    w = jnp.asarray(rng.normal(0, 0.2, 900), jnp.float32)
    f_r, g_r = obj.value_and_gradient(w, resident)
    f_c, g_c = cobj.value_and_gradient(w)
    np.testing.assert_allclose(float(f_c), float(f_r), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_r),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        cobj.x_dot(w), np.asarray(resident.x_dot(w))[: cb.n],
        rtol=2e-4, atol=2e-4)


def test_estimator_spilled_fit_matches_resident(rng, tmp_path):
    """GameEstimator with spill_dir ≡ the RAM-resident chunked fit,
    through CD + swept-λ grid training and transformer scoring."""
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
    )
    from photon_ml_tpu.estimators.game_estimator import GameEstimator
    from photon_ml_tpu.game.dataset import GameDataset
    from photon_ml_tpu.models.glm import TaskType

    n, d, k = 800, 100, 5
    cols = np.stack([
        np.sort(rng.choice(d, k, replace=False)) for _ in range(n)
    ]).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    w_true = rng.normal(0, 1, d)
    m = np.einsum("nk,nk->n", vals, w_true[cols])
    y = (m + rng.normal(0, 0.3, n) > 0).astype(np.float32)
    rows = [(cols[i], vals[i]) for i in range(n)]
    ds = GameDataset(labels=y, features={"f": rows}, entity_ids={},
                     feature_dims={"f": d})

    def cfg(**kw):
        return TrainingConfig(
            task_type=TaskType.LOGISTIC_REGRESSION,
            coordinates=[CoordinateConfig(
                name="global", kind=CoordinateKind.FIXED_EFFECT,
                feature_shard="f",
                optimizer=OptimizerSettings(max_iters=40,
                                            reg_weight=1.0))],
            update_sequence=["global"], n_iterations=1,
            reg_weight_grid={"global": [2.0, 0.5]},
            validation_fraction=0.0, validate_per_iteration=False,
            intercept=False, chunk_rows=192, chunk_layout="ELL", **kw)

    fits_r = GameEstimator(cfg(chunk_max_resident=8)).fit(ds)
    fits_s = GameEstimator(cfg(
        spill_dir=str(tmp_path / "est_spill"), host_max_resident=1,
        prefetch_depth=2, chunk_max_resident=0)).fit(ds)
    assert len(fits_s) == len(fits_r) == 2
    for fr, fs in zip(fits_r, fits_s):
        w_r = np.asarray(fr.model.models["global"].coefficients.means)
        w_s = np.asarray(fs.model.models["global"].coefficients.means)
        np.testing.assert_allclose(w_s, w_r, rtol=5e-3, atol=5e-3)
    spill_root = tmp_path / "est_spill" / "chunks"
    assert spill_root.is_dir() and any(spill_root.iterdir())


@pytest.mark.fast
def test_spill_config_validation():
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
    )
    from photon_ml_tpu.models.glm import TaskType

    base = dict(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[CoordinateConfig(
            name="g", kind=CoordinateKind.FIXED_EFFECT,
            feature_shard="f", optimizer=OptimizerSettings())],
        update_sequence=["g"],
    )
    with pytest.raises(ValueError, match="spill_dir"):
        TrainingConfig(spill_dir="/tmp/s", **base).validate()
    with pytest.raises(ValueError, match="host_max_resident"):
        TrainingConfig(chunk_rows=100, spill_dir="/tmp/s",
                       host_max_resident=0, **base).validate()
    with pytest.raises(ValueError, match="prefetch_depth"):
        TrainingConfig(chunk_rows=100, prefetch_depth=-1,
                       **base).validate()
    TrainingConfig(chunk_rows=100, spill_dir="/tmp/s",
                   host_max_resident=2, prefetch_depth=0,
                   **base).validate()


def test_env_spill_default_applies_at_config_layer_only(
        rng, tmp_path, monkeypatch):
    """$PHOTON_ML_TPU_SPILL_DIR must flow through the config/estimator
    layer and NEVER flip a direct `build_chunked_batch` caller to the
    spill store — bench control arms and parity baselines build
    resident batches through that API (review finding: an ambient env
    var silently turned the resident arm into spilled-vs-spilled)."""
    from photon_ml_tpu.data.chunk_store import resolve_spill_dir

    rows, labels, weights, offsets = _sparse_problem(rng, n=400, d=50,
                                                     k=4)
    monkeypatch.setenv("PHOTON_ML_TPU_SPILL_DIR",
                       str(tmp_path / "env_spill"))
    cb = build_chunked_batch(rows, 50, labels, n_chunks=2, layout="ell")
    assert cb.store is None                      # library API: explicit
    assert not (tmp_path / "env_spill").exists()
    assert resolve_spill_dir(None) == str(tmp_path / "env_spill")
    cb2 = build_chunked_batch(rows, 50, labels, n_chunks=2,
                              layout="ell",
                              spill_dir=resolve_spill_dir(None))
    assert cb2.store is not None                 # config-layer route


def test_grr_store_key_tracks_planner_version(rng, tmp_path,
                                              monkeypatch):
    """GRR chunk files embed compiled plans: a PLANNER_VERSION bump
    must orphan them (clean rebuild), exactly like plan-cache entries
    (review finding: stale plans would warm-load into new kernels)."""
    import photon_ml_tpu.data.grr as grr_mod
    from photon_ml_tpu.data.chunk_store import store_key

    rows, labels, weights, offsets = _sparse_problem(rng, n=400, d=50,
                                                     k=4)
    kw = dict(dim=50, chunk_rows=200, n_dev=1, row_capacity=4)
    k1 = store_key(rows, labels, weights, layout="grr", **kw)
    # drop_ell_with_grr changes the payload, so it changes the key.
    assert store_key(rows, labels, weights, layout="grr",
                     drop_ell_with_grr=False, **kw) != k1
    k_ell = store_key(rows, labels, weights, layout="ell", **kw)
    monkeypatch.setattr(grr_mod, "PLANNER_VERSION",
                        grr_mod.PLANNER_VERSION + 1)
    assert store_key(rows, labels, weights, layout="grr", **kw) != k1
    # ELL payloads embed no plans: planner version is not in their key.
    assert store_key(rows, labels, weights, layout="ell", **kw) == k_ell


@pytest.mark.fast
def test_mmap_npz_roundtrip(tmp_path):
    """The zip-member mmap reader returns exactly what was saved, as
    file-backed views (no anonymous copy)."""
    from photon_ml_tpu.cache.plan_cache import atomic_savez
    from photon_ml_tpu.data.chunk_store import _open_npz_mmap

    arrays = {
        "a": np.arange(1000, dtype=np.int32).reshape(50, 20),
        "b": np.linspace(0, 1, 37, dtype=np.float32),
        "c": np.zeros(0, np.float32),
    }
    path = str(tmp_path / "x" / "t.npz")
    atomic_savez(path, {"hello": 1}, arrays)
    out = _open_npz_mmap(path)
    for name, a in arrays.items():
        got = out[name]
        assert isinstance(got, np.memmap)
        np.testing.assert_array_equal(np.asarray(got), a)
    import json

    assert json.loads(bytes(np.asarray(out["__meta__"])))["hello"] == 1


@pytest.mark.fast
def test_prefetcher_error_delivered_in_band():
    """A producer-thread failure surfaces at the consumer's ``next()``
    as the original exception.  Since ISSUE 6 the error RIDES THE QUEUE
    (sentinel item) instead of a shared attribute — the lint
    unlocked-shared-write fix — so delivery needs no lock and cannot
    race the consumer."""
    from photon_ml_tpu.optim.streaming import ChunkPrefetcher

    def load(i):
        if i == 2:
            raise OSError("disk went away")
        return np.full(4, i, np.float32)

    pf = ChunkPrefetcher(load, lambda h: h, depth=2)
    pf.start(range(4))
    try:
        assert pf.next(0)[0] == 0
        assert pf.next(1)[0] == 1
        with pytest.raises(OSError, match="disk went away"):
            pf.next(2)
    finally:
        pf.close()


@pytest.mark.fast
def test_prefetch_stream_error_and_cleanup(tmp_path):
    """Same contract through the generator wrapper: the error raises at
    the failing chunk and the store reader count still drains to zero
    (quiescence is structural)."""
    from photon_ml_tpu.data.chunk_store import ChunkStore
    from photon_ml_tpu.optim.streaming import prefetch_stream

    store = ChunkStore(str(tmp_path), "k", n_chunks=3)

    def load(i):
        if i == 1:
            raise ValueError("bad chunk")
        return i

    with pytest.raises(ValueError, match="bad chunk"):
        for _i, _c in prefetch_stream(load, lambda h: h, range(3),
                                      depth=2, store=store):
            pass
    store.assert_quiesced()   # reader released despite the error
