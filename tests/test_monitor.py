"""Live run monitoring (ISSUE 10): progress snapshots, online alert
rules, counter ``rate()``, cadence flushing, the ``telemetry watch``
CLI, the status endpoint, and the history ``--known-bad`` waiver.

The alert-rule tests are the acceptance check: synthetic event streams
pin EXACTLY which rules fire (an injected divergence produces one
``alert``, a healthy stream produces none) — a rule that over- or
under-fires is an operator paging themselves at 3am for nothing, or
sleeping through a dead run.
"""

from __future__ import annotations

import json
import math
import os
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu import telemetry
from photon_ml_tpu.analysis.guards import count_compiles
from photon_ml_tpu.data.chunked_batch import build_chunked_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.streaming import ChunkedGLMObjective
from photon_ml_tpu.telemetry import monitor
from photon_ml_tpu.telemetry import watch as watch_mod
from photon_ml_tpu.telemetry.__main__ import main as telemetry_main
from photon_ml_tpu.telemetry.history import parse_known_bad
from photon_ml_tpu.utils.run_log import RunLogger, read_run_log

pytestmark = pytest.mark.fast

D = 61
K = 4


@pytest.fixture(autouse=True)
def _no_leaked_monitor():
    """Every test must leave the module-global monitor AND telemetry
    session closed (the same discipline as test_telemetry)."""
    assert monitor.active() is None
    assert telemetry.active() is None
    yield
    leaked = []
    m = monitor.active()
    if m is not None:
        m.close()
        leaked.append("monitor")
    t = telemetry.active()
    if t is not None:
        t.close()
        leaked.append("telemetry")
    if leaked:
        raise AssertionError(f"test leaked active sessions: {leaked}")


class _FakeClock:
    """Deterministic monotonic clock for cadence/rate math."""

    def __init__(self, t=100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> float:
        self.t += dt
        return self.t


class _EventSink:
    """RunLogger stand-in collecting (kind, fields) pairs."""

    def __init__(self, clock=None):
        self.events: list = []
        self._clock = clock or _FakeClock()

    def now(self) -> float:
        return self._clock()

    def event(self, kind: str, **fields) -> None:
        self.events.append({"event": kind, **fields})

    def close(self) -> None:
        pass

    def kinds(self) -> list:
        return [e["event"] for e in self.events]

    def of(self, kind: str) -> list:
        return [e for e in self.events if e["event"] == kind]


def _registry(clock=None):
    """A raw (never-activated) Telemetry registry on a fake clock —
    pure counter/gauge/rate state, no threads, no global session."""
    sink = _EventSink(clock)
    return telemetry.Telemetry("metrics", sink, None)


def _monitor(clock=None, every_s=0.0, session=None, **kw):
    """A Monitor wired to an event sink + fake clock, NOT activated as
    the module global (rule evaluation is driven by progress())."""
    clock = clock or _FakeClock()
    sink = _EventSink(clock)
    m = monitor.Monitor(run_logger=sink, every_s=every_s, clock=clock,
                        telemetry_session=session
                        if session is not None else _registry(clock),
                        **kw)
    return m, sink, clock


# ---------------------------------------------------------------------------
# off path + lifecycle
# ---------------------------------------------------------------------------


def test_off_module_helpers_are_noops():
    """No active monitor: progress/phase helpers early-return — the
    hot-loop contract instrumented pipelines rely on."""
    assert monitor.active() is None
    monitor.progress("stage", 1, 10, loss=float("nan"))
    monitor.phase_begin("fit")
    monitor.phase_end("fit")


def test_start_close_lifecycle_and_double_start():
    m = monitor.start()
    try:
        assert monitor.active() is m
        with pytest.raises(RuntimeError, match="already active"):
            monitor.start()
        assert monitor.active() is m     # failed start didn't clobber
    finally:
        m.close()
    assert monitor.active() is None
    m.close()                            # idempotent


def test_maybe_monitor_gating():
    with monitor.maybe_monitor(False) as m:
        assert m is None and monitor.active() is None
    with monitor.maybe_monitor(True) as m:
        assert monitor.active() is m
        # Nested request no-ops (driver-over-estimator rule).
        with monitor.maybe_monitor(True) as inner:
            assert inner is m
    assert monitor.active() is None
    # A requested endpoint implies monitoring even with enabled=False.
    with monitor.maybe_monitor(False, status_port=0) as m:
        assert m is not None and m.status_port > 0
    assert monitor.active() is None


def test_monitor_validates_knobs():
    with pytest.raises(ValueError, match="every_s"):
        monitor.Monitor(_EventSink(), every_s=-1.0)
    with pytest.raises(ValueError, match="unknown alert thresholds"):
        monitor.Monitor(_EventSink(), thresholds={"no_such_knob": 1})


# ---------------------------------------------------------------------------
# progress snapshots: cadence, rate, ETA
# ---------------------------------------------------------------------------


def test_progress_throttles_to_cadence():
    """A hot loop reporting every 10ms at a 1s cadence emits the first
    call, one event per elapsed second, and the completion call — not
    one event per call."""
    m, sink, clock = _monitor(every_s=1.0)
    n = 300
    for i in range(n):
        clock.tick(0.01)
        m.progress("hot", i + 1, n, unit="chunks")
    evs = sink.of("progress")
    # 3s of wall clock: first + ~3 cadence emissions + completion.
    assert 3 <= len(evs) <= 6, [e["done"] for e in evs]
    assert evs[0]["done"] == 1.0
    assert evs[-1]["done"] == float(n)   # completion always emits
    m.close()
    # The run-end summary event carries the final stage state.
    summ = sink.of("monitor_summary")[0]
    assert summ["stages"]["hot"]["done"] == float(n)


def test_progress_rate_and_eta_from_observed_throughput():
    """10 units/s observed → rate ≈ 10, ETA == remaining/rate (the
    ISSUE acceptance: ETA derived from observed chunk rates)."""
    m, sink, clock = _monitor(every_s=0.0)
    for i in range(50):
        clock.tick(0.1)
        m.progress("sweep", i + 1, 100, unit="chunks")
    st = m.status()["stages"]["sweep"]
    assert st["rate"] == pytest.approx(10.0, rel=1e-6)
    assert st["eta_s"] == pytest.approx(5.0, rel=1e-6)
    # The emitted event carries the same derivation.
    last = sink.of("progress")[-1]
    assert last["rate"] == pytest.approx(10.0, abs=0.01)
    assert last["eta_s"] == pytest.approx(5.0, abs=0.1)
    m.close()


def test_progress_restart_resets_rate_window():
    """A new pass restarting the unit count (done decreasing) resets
    the rolling window — throughput never goes negative."""
    m, _, clock = _monitor(every_s=0.0)
    for i in range(10):
        clock.tick(0.1)
        m.progress("pass", i + 1, 10)
    clock.tick(0.1)
    m.progress("pass", 1, 10)            # second pass begins
    clock.tick(0.1)
    m.progress("pass", 2, 10)
    st = m.status()["stages"]["pass"]
    assert st["rate"] is not None and st["rate"] > 0
    m.close()


def test_phase_tracking_nested():
    m, _, _ = _monitor()
    m.phase_begin("fit")
    m.phase_begin("sweep")
    assert m.status()["phase"] == "sweep"
    m.phase_end("sweep")
    assert m.status()["phase"] == "fit"
    m.phase_end("no_such_phase")         # missed begin must not corrupt
    assert m.status()["phase"] == "fit"
    m.phase_end("fit")
    assert m.status()["phase"] is None
    m.close()


# ---------------------------------------------------------------------------
# online alert rules: synthetic streams pin exactly which rules fire
# ---------------------------------------------------------------------------


def _rules(sink) -> list:
    return [e["rule"] for e in sink.of("alert")]


def test_healthy_stream_fires_no_rules():
    """Steady throughput, monotone loss, quiet registry → ZERO alerts
    (the false-positive gate for every rule at once)."""
    m, sink, clock = _monitor(every_s=0.0)
    loss = 100.0
    for i in range(60):
        clock.tick(0.5)
        loss *= 0.98
        m.progress("solver", i + 1, 100, unit="iters", loss=loss)
    assert _rules(sink) == []
    assert m.status()["alerts"] == []
    m.close()


def test_loss_nonfinite_fires_once_latched():
    """An injected NaN loss produces EXACTLY ONE alert event no matter
    how many snapshots repeat it (the rule latches per rule×stage)."""
    m, sink, clock = _monitor(every_s=0.0)
    for i in range(10):
        clock.tick(0.5)
        m.progress("solver", i + 1, 100, loss=float("nan"))
    assert _rules(sink) == ["loss_nonfinite"]
    alert = sink.of("alert")[0]
    assert alert["severity"] == "error"
    assert alert["stage"] == "solver"
    m.close()


def test_loss_divergence_fires_exactly_one_alert():
    """The ISSUE-10 acceptance fault: loss improves, then blows past
    divergence_ratio × best → one loss_diverging alert, nothing else."""
    m, sink, clock = _monitor(every_s=0.0)
    for i, loss in enumerate([100.0, 80.0, 60.0, 50.0,   # improving
                              70.0, 90.0,                # worse, < 2x best
                              150.0, 400.0, 900.0]):     # diverged
        clock.tick(0.5)
        m.progress("solver", i + 1, 20, loss=loss)
    assert _rules(sink) == ["loss_diverging"]
    alert = sink.of("alert")[0]
    assert alert["severity"] == "error" and alert["best"] == 50.0
    assert alert["loss"] == 150.0        # fired at first crossing
    m.close()


def test_throughput_collapse_vs_rolling_median():
    m, sink, clock = _monitor(every_s=0.0)
    done = 0
    for _ in range(8):                   # healthy: 20 units/s
        clock.tick(0.5)
        done += 10
        m.progress("sweep", done, 10_000, unit="chunks")
    for _ in range(40):                  # collapse: 0.2 units/s
        clock.tick(5.0)
        done += 1
        m.progress("sweep", done, 10_000, unit="chunks")
    assert "throughput_collapse" in _rules(sink)
    assert _rules(sink).count("throughput_collapse") == 1   # latched
    m.close()


def test_retry_storm_rate_and_gave_up():
    """Transient retries above the windowed rate threshold fire
    retry_storm; any store.gave_up fires it as an error."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    for i in range(20):
        clock.tick(0.5)
        reg.count("store.retries")       # 2/s >> 0.5/s threshold
        m.progress("sweep", i + 1, 100)
    assert _rules(sink) == ["retry_storm"]
    assert sink.of("alert")[0]["severity"] == "warn"
    m.close()

    clock2 = _FakeClock()
    reg2 = _registry(clock2)
    m2, sink2, _ = _monitor(clock=clock2, session=reg2)
    reg2.count("store.gave_up")
    clock2.tick(0.5)
    m2.progress("sweep", 1, 100)
    assert _rules(sink2) == ["retry_storm"]
    assert sink2.of("alert")[0]["severity"] == "error"
    m2.close()


def test_prefetch_stall_rules():
    """A hard stall timeout fires immediately (error); absent that, a
    consumer blocked most of recent wall clock fires the soft rule."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    reg.count("prefetch.stall_timeouts")
    clock.tick(0.5)
    m.progress("sweep", 1, 100)
    assert _rules(sink) == ["prefetch_stall"]
    assert sink.of("alert")[0]["severity"] == "error"
    m.close()

    clock2 = _FakeClock()
    reg2 = _registry(clock2)
    m2, sink2, _ = _monitor(clock=clock2, session=reg2)
    for i in range(10):                  # blocked 0.45s of every 0.5s
        clock2.tick(0.5)
        reg2.count("prefetch.consumer_wait_s", 0.45)
        m2.progress("sweep", i + 1, 100)
    assert _rules(sink2) == ["prefetch_stall"]
    m2.close()


def test_sink_saturation_needs_a_streak():
    """One deep-queue sample is normal burst; a sustained streak at
    snapshot cadence names the sink tier as the bottleneck."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    reg.gauge("sink.queue_depth", 4.0)
    clock.tick(0.5)
    m.progress("score", 1, 100)          # streak 1: no alert yet
    assert _rules(sink) == []
    reg.gauge("sink.queue_depth", 1.0)   # drained: streak resets
    clock.tick(0.5)
    m.progress("score", 2, 100)
    reg.gauge("sink.queue_depth", 4.0)
    for i in range(3, 5):
        clock.tick(0.5)
        m.progress("score", i, 100)
    assert _rules(sink) == ["sink_saturation"]
    m.close()


def test_device_memory_growth_needs_ratio_and_floor():
    """Fires only when device memory grew by BOTH the ratio and the
    absolute floor since monitoring started — a tiny run tripling a
    10MB footprint is not a leak."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    reg.gauge("device.bytes_in_use", 1e9)
    clock.tick(0.5)
    m.progress("sweep", 1, 100)
    reg.gauge("device.bytes_in_use", 1.4e9)   # +400MB but < 1.5x
    clock.tick(0.5)
    m.progress("sweep", 2, 100)
    assert _rules(sink) == []
    reg.gauge("device.bytes_in_use", 2.1e9)   # 2.1x AND +1100MB
    clock.tick(0.5)
    m.progress("sweep", 3, 100)
    assert _rules(sink) == ["device_memory_growth"]
    m.close()


def test_serve_tail_latency_fires_over_threshold_latched():
    """ISSUE 12 satellite (positive): a request stream whose p99 sits
    above the threshold fires serve_tail_latency exactly once, stamped
    with the observed p99."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    for _ in range(30):
        reg.count("serve.requests")
        reg.observe("serve.request_s", 0.9)     # every request slow
    clock.tick(0.5)
    m.progress("serve", 30, unit="requests")
    assert _rules(sink) == ["serve_tail_latency"]
    alert = sink.of("alert")[0]
    assert alert["stage"] == "serve"
    assert alert["p99_ms"] > 500.0
    # Latched: the next snapshot with the same registry re-fires
    # nothing.
    clock.tick(0.5)
    m.progress("serve", 60, unit="requests")
    assert _rules(sink) == ["serve_tail_latency"]
    m.close()


def test_serve_tail_latency_negative_paths():
    """ISSUE 12 satellite (negative): a fast stream never fires, and a
    slow p99 below the minimum request count is start-up noise, not an
    SLO breach."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    for _ in range(200):                        # fast stream
        reg.count("serve.requests")
        reg.observe("serve.request_s", 0.005)
    clock.tick(0.5)
    m.progress("serve", 200, unit="requests")
    assert _rules(sink) == []
    m.close()

    clock2 = _FakeClock()
    reg2 = _registry(clock2)
    m2, sink2, _ = _monitor(clock=clock2, session=reg2)
    for _ in range(5):                          # slow but too few
        reg2.count("serve.requests")
        reg2.observe("serve.request_s", 2.0)
    clock2.tick(0.5)
    m2.progress("serve", 5, unit="requests")
    assert _rules(sink2) == []
    m2.close()


def test_serve_shed_rate_fires_on_sustained_shedding_latched():
    """ISSUE 13 satellite (positive): a shed fraction above the
    threshold over the rolling window fires serve_shed_rate exactly
    once, stamped with the observed fraction."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    for _ in range(10):                 # 50% shed, well over 20%
        clock.tick(0.1)
        reg.count("serve.requests")
        reg.count("serve.shed")
    clock.tick(0.1)
    m.progress("serve", 10, unit="requests")
    assert _rules(sink) == ["serve_shed_rate"]
    alert = sink.of("alert")[0]
    assert alert["stage"] == "serve"
    assert alert["shed_fraction"] == pytest.approx(0.5, abs=0.05)
    # Latched: continued shedding re-fires nothing.
    clock.tick(0.5)
    reg.count("serve.shed")
    m.progress("serve", 11, unit="requests")
    assert _rules(sink) == ["serve_shed_rate"]
    m.close()


def test_serve_shed_rate_negative_paths():
    """ISSUE 13 satellite (negative): a small shed fraction never
    fires, and heavy shedding below the minimum event count is
    start-up noise."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    for i in range(40):                 # 2.5% shed, under 20%
        clock.tick(0.1)
        reg.count("serve.requests")
        if i == 0:
            reg.count("serve.shed")
    clock.tick(0.1)
    m.progress("serve", 40, unit="requests")
    assert _rules(sink) == []
    m.close()

    clock2 = _FakeClock()
    reg2 = _registry(clock2)
    m2, sink2, _ = _monitor(clock=clock2, session=reg2)
    for _ in range(5):                  # 100% shed but too few events
        clock2.tick(0.1)
        reg2.count("serve.shed")
    clock2.tick(0.1)
    m2.progress("serve", 0, unit="requests")
    assert _rules(sink2) == []
    m2.close()


def test_replica_restarts_any_restart_latches():
    """ISSUE 13 satellite (positive): ANY replica restart fires the
    rule once — and only once, however many more restarts follow."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    reg.count("fleet.replica_restarts")
    clock.tick(0.5)
    m.progress("serve", 1, unit="requests")
    assert _rules(sink) == ["replica_restarts"]
    assert sink.of("alert")[0]["restarts"] == 1
    reg.count("fleet.replica_restarts", 3)
    clock.tick(0.5)
    m.progress("serve", 2, unit="requests")
    assert _rules(sink) == ["replica_restarts"]      # latched
    m.close()


def test_replica_restarts_negative_without_restarts():
    """ISSUE 13 satellite (negative): recycles (deploy bounces) and
    ordinary traffic never fire replica_restarts."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    reg.count("fleet.replica_recycles", 2)   # rolling swap, not crash
    for _ in range(30):
        clock.tick(0.1)
        reg.count("serve.requests")
        reg.observe("serve.request_s", 0.005)
    m.progress("serve", 30, unit="requests")
    assert _rules(sink) == []
    m.close()


def test_alerts_disabled_evaluates_nothing():
    m, sink, clock = _monitor(every_s=0.0, alerts=False)
    for i in range(5):
        clock.tick(0.5)
        m.progress("solver", i + 1, 10, loss=float("nan"))
    assert _rules(sink) == []
    m.close()


# ---------------------------------------------------------------------------
# metrics registry: rolling-window counter rate()
# ---------------------------------------------------------------------------


def test_counter_rate_bounded_error():
    """The satellite's bounded-error contract: a rate step is resolved
    within one inter-sample spacing of the window boundary — a counter
    that was fast an hour ago and stalled NOW reports the NOW rate."""
    clock = _FakeClock()
    reg = _registry(clock)
    for _ in range(500):                 # phase A: 10/s for 50s
        clock.tick(0.1)
        reg.count("x")
    for _ in range(1000):                # phase B: 100/s for 10s
        clock.tick(0.01)
        reg.count("x")
    # A 5s trailing window sits entirely inside phase B: exact.
    assert reg.rate("x", 5.0) == pytest.approx(100.0, rel=0.01)
    # A 60s window spans both phases: the true mean over the bracketed
    # interval (1500 increments / 60s = 25/s), within one spacing.
    assert reg.rate("x", 60.0) == pytest.approx(1500 / 60.0, rel=0.02)
    # Lifetime average would be 1500/60 too here, so pin the contrast
    # explicitly: a stall after phase B collapses the windowed rate
    # while the lifetime counter stays put.
    clock.tick(30.0)
    reg.count("x")
    assert reg.counter("x") == 1501
    assert reg.rate("x", 5.0, now=clock()) < 1.0
    m = reg.rate("x", 5.0)
    assert m is not None


def test_counter_rate_decimation_stays_bounded():
    """Overflowing the per-counter series cap decimates to every-other
    sample; a constant-rate stream's reported rate must stay exact to
    within two sample spacings (the documented error bound)."""
    clock = _FakeClock()
    reg = _registry(clock)
    n = 10_000                           # >> _RATE_SERIES_CAP (4096)
    for _ in range(n):
        clock.tick(0.01)                 # 100/s, all within horizon
        reg.count("y")
    r = reg.rate("y", 10.0)
    # Window bracket error ≤ 2 spacings of the DECIMATED series; at
    # ~4096 retained samples over 100s that is ~0.05s on a 10s window.
    assert r == pytest.approx(100.0, rel=0.02)


def test_counter_rate_edge_contracts():
    clock = _FakeClock()
    reg = _registry(clock)
    assert reg.rate("unknown") is None
    reg.count("z")
    assert reg.rate("z") is None         # one sample: no interval
    clock.tick(1.0)
    reg.count("z", 5)
    assert reg.rate("z", 30.0) == pytest.approx(5.0)
    with pytest.raises(ValueError, match="window_s"):
        reg.rate("z", 0.0)
    assert reg.gauge_value("no.gauge") is None
    reg.gauge("g", 2.0)
    assert reg.gauge_value("g")["last"] == 2.0


# ---------------------------------------------------------------------------
# RunLogger cadence flushing
# ---------------------------------------------------------------------------


def test_runlogger_cadence_batches_ordinary_events(tmp_path):
    """With a long cadence an ordinary event may sit in the userspace
    buffer, but _FLUSH_NOW kinds (alerts, progress, phase boundaries)
    hit disk immediately — `watch` and kill-forensics stay current."""
    path = str(tmp_path / "log.jsonl")
    log = RunLogger(path, flush_every_s=3600.0)
    log.event("ordinary", x=1)
    buffered = read_run_log(path)
    # run_header is _FLUSH_NOW; the ordinary event is cadence-buffered.
    assert [e["event"] for e in buffered] == ["run_header"]
    log.event("alert", rule="loss_diverging")
    flushed = read_run_log(path)
    assert [e["event"] for e in flushed] == [
        "run_header", "ordinary", "alert"]
    log.event("ordinary2", x=2)
    log.flush()                          # explicit force
    assert read_run_log(path)[-1]["event"] == "ordinary2"
    log.close()
    assert [e["event"] for e in read_run_log(path)] == [
        "run_header", "ordinary", "alert", "ordinary2"]


def test_runlogger_flush_validation(tmp_path):
    with pytest.raises(ValueError, match="flush_every_s"):
        RunLogger(str(tmp_path / "x.jsonl"), flush_every_s=-1.0)
    # None (default) keeps the flush-every-event behavior.
    path = str(tmp_path / "y.jsonl")
    log = RunLogger(path)
    log.event("anything", x=1)
    assert read_run_log(path)[-1]["event"] == "anything"
    log.close()


# ---------------------------------------------------------------------------
# telemetry watch
# ---------------------------------------------------------------------------


def _write_live_log(path, alerts=0, done=False, segments=1):
    """A driver-shaped run log: header, open `fit` phase, progress
    snapshots with a loss trajectory — optionally still-running (no
    `done`, phase left open), resumed (extra segments), alerted."""
    for seg in range(segments):
        log = RunLogger(path, mode=("w" if seg == 0 else "a"),
                        header=True, run_info={"driver": "test"})
        log.event("phase_start", phase="fit")
        for i in range(5):
            log.event("progress", stage="solver", done=float(i + 1),
                      total=20.0, unit="iters", rate=2.0, eta_s=7.5,
                      loss=100.0 * (0.9 ** i), phase="fit")
        for k in range(alerts if seg == segments - 1 else 0):
            log.event("alert", rule="loss_diverging", severity="error",
                      stage="solver", message="loss 900 is 18x best")
        final = seg == segments - 1
        if done or not final:
            log.event("phase_end", phase="fit", duration_s=2.5)
            if done and final:
                log.event("done", best_index=0)
        log.close()


def test_watch_once_on_live_unterminated_log(tmp_path, capsys):
    """`watch --once` on a log whose run is still mid-fit: live=true,
    the open phase, per-stage progress/ETA/loss — and the JSON last
    line carries all of it (the scripting contract)."""
    path = str(tmp_path / "run_log.jsonl")
    _write_live_log(path)
    rc = telemetry_main(["watch", path, "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    snap = json.loads(out.strip().splitlines()[-1])
    assert snap["live"] is True
    assert snap["phase"] == "fit"
    assert snap["current_stage"] == "solver"
    assert snap["stages"]["solver"]["done"] == 5.0
    assert snap["eta_s"] == 7.5
    assert snap["loss"] == pytest.approx(100.0 * 0.9 ** 4)
    assert snap["losses"]["solver"][0] == 100.0
    assert snap["alerts"] == []
    # The human view leads with the run state and the current stage.
    assert "[RUNNING]" in out and "solver" in out


def test_watch_once_on_stitched_resumed_log(tmp_path, capsys):
    """A resumed run appends a fresh header: watch reports the LAST
    segment (the live one), not the interrupted predecessor."""
    path = str(tmp_path / "run_log.jsonl")
    _write_live_log(path, segments=2, done=True)
    rc = telemetry_main(["watch", path, "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    snap = json.loads(out.strip().splitlines()[-1])
    assert snap["segments"] == 2
    assert snap["live"] is False         # last segment logged done
    assert snap["stages"]["solver"]["done"] == 5.0
    assert "segment 2 of a resumed run" in out


def test_watch_once_tolerates_torn_final_line(tmp_path, capsys):
    """A live writer's partial final line (the kill-mid-write case) is
    counted, not fatal."""
    path = str(tmp_path / "run_log.jsonl")
    _write_live_log(path)
    with open(path, "a") as f:
        f.write('{"event": "progress", "stage": "solver", "done": 6')
    rc = telemetry_main(["watch", path, "--once"])
    snap = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert snap["torn_lines"] == 1
    assert snap["stages"]["solver"]["done"] == 5.0   # torn line skipped
    assert snap["live"] is True


def test_watch_follow_bounded_by_max_wait(tmp_path, capsys):
    """Follow mode on a log that stops growing without `done` (a
    killed run) exits at --max-wait-s instead of watching forever."""
    path = str(tmp_path / "run_log.jsonl")
    _write_live_log(path)
    rc = telemetry_main(["watch", path, "--interval", "0.05",
                         "--max-wait-s", "0.2"])
    snap = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and snap["live"] is True


def test_watch_surfaces_alerts_and_thread_deaths(tmp_path, capsys):
    path = str(tmp_path / "run_log.jsonl")
    _write_live_log(path, alerts=1)
    with open(path, "a") as f:
        f.write(json.dumps({"event": "thread_exception",
                            "stage": "prefetch", "error": "boom",
                            "thread": "chunk-prefetch"}) + "\n")
    rc = telemetry_main(["watch", path, "--once"])
    out = capsys.readouterr().out
    snap = json.loads(out.strip().splitlines()[-1])
    assert rc == 1                       # a dead thread is a failure
    assert [a["rule"] for a in snap["alerts"]] == ["loss_diverging"]
    assert snap["thread_exceptions"][0]["stage"] == "prefetch"
    assert "ALERTS:" in out and "DIED prefetch" in out


def test_watch_rejects_bad_interval(tmp_path):
    path = str(tmp_path / "run_log.jsonl")
    _write_live_log(path)
    with pytest.raises(ValueError, match="interval_s"):
        watch_mod.watch(path, interval_s=0.0)


# ---------------------------------------------------------------------------
# status endpoint
# ---------------------------------------------------------------------------


def _get(port, route):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{route}", timeout=5) as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


def test_status_endpoint_routes():
    """/status serves the live JSON snapshot, /metrics the Prometheus
    text exposition, unknown routes 404 with the route list."""
    m = monitor.start(status_port=0)
    try:
        port = m.status_port
        assert port and port > 0
        # Warming until work flows (ISSUE 12 satellite): before the
        # first progress snapshot — the plan/compile window — a probe
        # gets 503, not the old unconditional 200.
        with pytest.raises(urllib.error.HTTPError) as warm:
            _get(port, "/healthz")
        assert warm.value.code == 503
        assert json.loads(warm.value.read().decode())["state"] == \
            "warming"
        monitor.progress("sweep", 3, 12, unit="chunks")
        code, ctype, body = _get(port, "/status")
        assert code == 200 and ctype == "application/json"
        st = json.loads(body)
        assert st["stages"]["sweep"]["done"] == 3.0
        assert st["stages"]["sweep"]["total"] == 12.0
        assert st["alerts"] == []
        code, ctype, body = _get(port, "/metrics")
        assert code == 200 and "version=0.0.4" in ctype
        assert 'photon_monitor_progress_done{stage="sweep"} 3.0' in body
        assert "photon_monitor_alerts_total 0" in body
        code, _, body = _get(port, "/healthz")
        assert code == 200
        assert json.loads(body) == {"ok": True, "state": "ready"}
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(port, "/no_such")
        assert err.value.code == 404
        assert "/status" in err.value.read().decode()
    finally:
        m.close()
    # The server thread is down with the monitor.
    with pytest.raises(OSError):
        _get(port, "/status")


def test_prometheus_text_exposition_format():
    """Counters → counter, gauges → gauge, histograms → summary with
    reservoir quantiles; metric names sanitized to the charset."""
    clock = _FakeClock()
    reg = _registry(clock)
    reg.count("store.loads", 7)
    reg.gauge("sink.queue_depth", 2.0)
    for v in range(100):
        reg.observe("sink.write_s", float(v))
    m, _, _ = _monitor(clock=clock, session=reg)
    m.progress("score", 5, 10, unit="rows")
    text = monitor.prometheus_text(m, session=reg)
    lines = text.splitlines()
    assert "# TYPE photon_store_loads_total counter" in lines
    assert "photon_store_loads_total 7" in lines
    assert "photon_sink_queue_depth 2.0" in lines
    assert "# TYPE photon_sink_write_s summary" in lines
    assert any(l.startswith('photon_sink_write_s{quantile="0.5"}')
               for l in lines)
    assert "photon_sink_write_s_count 100" in lines
    assert 'photon_monitor_progress_total{stage="score"} 10.0' in lines
    m.close()


# ---------------------------------------------------------------------------
# history --known-bad waiver
# ---------------------------------------------------------------------------


def test_parse_known_bad_requires_reason():
    assert parse_known_bad(["r05.json=rc-124 budget timeout"]) == {
        "r05.json": "rc-124 budget timeout"}
    for bad in ("r05.json", "r05.json=", "=why", "r05.json=  "):
        with pytest.raises(ValueError, match="reason"):
            parse_known_bad([bad])


def test_history_known_bad_waives_repo_r05(capsys):
    """THE satellite acceptance: the real BENCH_r01..r05 trajectory
    rc-1s on r05's rc-124 — waived with a reason, the gate passes and
    the markdown echoes the acknowledgment."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rounds = [os.path.join(root, f"BENCH_r0{i}.json")
              for i in range(1, 6)]
    rc = telemetry_main(["history", *rounds])
    capsys.readouterr()
    assert rc == 1                       # unwaived: r05 fails the gate

    rc = telemetry_main([
        "history", *rounds, "--known-bad",
        "BENCH_r05.json=rc-124 budget timeout, see PERF.md round 10"])
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert rc == 0 and tail["ok"] is True
    assert tail["failed_rounds"] == []
    assert tail["waived"][0]["round"] == "BENCH_r05.json"
    assert "budget timeout" in tail["waived"][0]["reason"]
    assert "WAIVED" in out and "budget timeout" in out


def test_history_known_bad_unknown_round_is_surfaced(tmp_path, capsys):
    """A waiver matching no loaded round (typo) is named in the output
    instead of silently doing nothing."""
    hist = tmp_path / "hist"
    hist.mkdir()
    with open(str(hist / "r01.json"), "w") as f:
        json.dump({"schema": 1, "kind": "bench_record", "rc": 0,
                   "argv": [], "record": {"stream": {
                       "spilled": {"examples_per_sec": 1000.0},
                       "pass_time_ratio": 1.0}}}, f)
    rc = telemetry_main(["history", str(hist),
                         "--known-bad", "r99.json=typo"])
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert rc == 0
    assert tail["unknown_waivers"] == ["r99.json"]
    assert "UNKNOWN WAIVER" in out


# ---------------------------------------------------------------------------
# guard budget: monitoring compiles nothing
# ---------------------------------------------------------------------------


def _tiny_spilled_objective(tmp_path, n_chunks=4, chunk_rows=100):
    rng = np.random.default_rng(11)
    n = chunk_rows * n_chunks
    cols = np.stack([np.sort(rng.choice(D, K, replace=False))
                     for _ in range(n)]).astype(np.int64)
    vals = rng.normal(size=(n, K)).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    rows = SparseRows.from_flat(np.arange(n + 1, dtype=np.int64) * K,
                                cols.reshape(-1), vals.reshape(-1))
    obj = GLMObjective(loss=losses.LOGISTIC,
                       reg=RegularizationContext.l2(1.0),
                       norm=NormalizationContext.identity())
    cb = build_chunked_batch(rows, D, labels, n_chunks=n_chunks,
                             layout="ell",
                             spill_dir=str(tmp_path / "spill"),
                             host_max_resident=2)
    return ChunkedGLMObjective(obj, cb, max_resident=0, prefetch_depth=1)


def test_monitored_sweeps_compile_nothing_new(tmp_path):
    """The guard-pinned acceptance budget: warm streamed sweeps with
    the live monitor ON (snapshots + alert evaluation at a hot
    cadence + the status thread) add ZERO compile records — the
    monitor never touches jax."""
    cobj = _tiny_spilled_objective(tmp_path)
    w = jnp.zeros(D, jnp.float32)
    import jax

    jax.block_until_ready(cobj.value_and_gradient(w)[1])   # warm
    m = monitor.start(every_s=0.0, status_port=0)
    try:
        with count_compiles() as log:
            for _ in range(2):
                jax.block_until_ready(cobj.value_and_gradient(w)[1])
        assert log.count == 0, log.programs
        # The hot loop DID report through the live monitor.
        assert m.status()["stages"]["train.sweep"]["done"] == 4.0
    finally:
        m.close()


# ---------------------------------------------------------------------------
# e2e: one injected divergence → one alert in watch + /status + report
# ---------------------------------------------------------------------------


def test_divergence_alert_visible_in_watch_status_and_report(
        tmp_path, capsys):
    """The ISSUE-10 acceptance chain: an injected loss divergence
    produces EXACTLY ONE alert event, and that one alert surfaces in
    all three consumers — `watch --once`, GET /status, and the
    report's Alerts section."""
    path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(path, header=True,
                    run_info={"driver": "game_training"})
    m = monitor.start(run_logger=log, every_s=0.0, status_port=0)
    try:
        with log.timed("fit"):
            for i, loss in enumerate([100.0, 50.0, 40.0,
                                      90.0, 200.0, 500.0]):
                m.progress("solver", i + 1, 10, unit="iters",
                           loss=loss)
        _, _, body = _get(m.status_port, "/status")
        status_alerts = json.loads(body)["alerts"]
    finally:
        m.close()
        log.close()

    events = read_run_log(path)
    assert [e["rule"] for e in events
            if e["event"] == "alert"] == ["loss_diverging"]

    assert [a["rule"] for a in status_alerts] == ["loss_diverging"]

    rc = telemetry_main(["watch", path, "--once"])
    out = capsys.readouterr().out
    snap = json.loads(out.strip().splitlines()[-1])
    assert rc == 0
    assert [a["rule"] for a in snap["alerts"]] == ["loss_diverging"]
    assert "loss_diverging" in out

    rc = telemetry_main(["report", path])
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert rc == 0
    assert [a["rule"] for a in tail["alerts"]] == ["loss_diverging"]
    assert "Alerts" in out and "loss_diverging" in out


# ---------------------------------------------------------------------------
# Request tracing (ISSUE 14): serve_queue_wait rule, dominant-stage
# naming, the labeled stage family, and the watch stage table
# ---------------------------------------------------------------------------


def test_serve_queue_wait_fires_when_batcher_dominates_latched():
    """ISSUE 14 satellite (positive): queue-wait p99 above the
    configured fraction of the request p99 fires serve_queue_wait
    exactly once — the 'batcher is the bottleneck' signal."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    for _ in range(30):
        reg.count("serve.requests")
        reg.observe("serve.request_s", 0.100)
        reg.observe("serve.stage.queue_wait_s", 0.080)   # 80% wait
    clock.tick(0.5)
    m.progress("serve", 30, unit="requests")
    assert _rules(sink) == ["serve_queue_wait"]
    alert = sink.of("alert")[0]
    assert alert["stage"] == "serve"
    assert alert["fraction"] == pytest.approx(0.8, abs=0.05)
    assert "batcher" in alert["message"]
    # Latched: the next snapshot re-fires nothing.
    clock.tick(0.5)
    m.progress("serve", 60, unit="requests")
    assert _rules(sink) == ["serve_queue_wait"]
    m.close()


def test_serve_queue_wait_negative_paths():
    """ISSUE 14 satellite (negative): a compute-dominated tail never
    fires, and a wait-dominated tail below the minimum request count
    is start-up noise."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    for _ in range(50):                   # 10% queue wait: healthy
        reg.count("serve.requests")
        reg.observe("serve.request_s", 0.100)
        reg.observe("serve.stage.queue_wait_s", 0.010)
    clock.tick(0.5)
    m.progress("serve", 50, unit="requests")
    assert _rules(sink) == []
    m.close()

    clock2 = _FakeClock()
    reg2 = _registry(clock2)
    m2, sink2, _ = _monitor(clock=clock2, session=reg2)
    for _ in range(5):                    # dominated, but too few
        reg2.count("serve.requests")
        reg2.observe("serve.request_s", 0.100)
        reg2.observe("serve.stage.queue_wait_s", 0.090)
    clock2.tick(0.5)
    m2.progress("serve", 5, unit="requests")
    assert _rules(sink2) == []
    m2.close()


def test_serve_tail_latency_names_dominant_stage():
    """ISSUE 14: with the stage histograms populated, the
    serve_tail_latency alert names the dominant stage — the first
    diagnostic step rides the page."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    for _ in range(30):
        reg.count("serve.requests")
        reg.observe("serve.request_s", 0.9)
        reg.observe("serve.stage.dispatch_s", 0.7)
        reg.observe("serve.stage.queue_wait_s", 0.1)
    clock.tick(0.5)
    m.progress("serve", 30, unit="requests")
    assert _rules(sink) == ["serve_tail_latency"]
    alert = sink.of("alert")[0]
    assert alert["dominant_stage"] == "dispatch"
    assert "dominant stage: dispatch" in alert["message"]
    m.close()


def test_serve_progress_event_carries_stage_table():
    """Serve progress snapshots embed the stage p50/p99 table so
    `telemetry watch` renders the live latency decomposition."""
    clock = _FakeClock()
    reg = _registry(clock)
    m, sink, _ = _monitor(clock=clock, session=reg)
    for _ in range(4):
        reg.observe("serve.stage.queue_wait_s", 0.004)
        reg.observe("serve.stage.dispatch_s", 0.002)
    clock.tick(0.5)
    m.progress("serve", 4, unit="requests")
    prog = sink.of("progress")[0]
    assert prog["stages_ms"]["queue_wait"]["p50_ms"] == pytest.approx(
        4.0, rel=0.01)
    assert prog["stages_ms"]["dispatch"]["count"] == 4
    # Non-serve stages stay lean: no table attached.
    clock.tick(0.5)
    m.progress("solver", 1, 10, unit="iters")
    assert "stages_ms" not in sink.of("progress")[-1]
    m.close()


def test_prometheus_serve_stage_labeled_family():
    """serve.stage.<stage>_s histograms export as ONE labeled family
    photon_serve_stage_seconds{stage=...} (ISSUE 14) instead of N
    flat-named series; other histograms keep the flat form."""
    clock = _FakeClock()
    reg = _registry(clock)
    for _ in range(10):
        reg.observe("serve.stage.queue_wait_s", 0.004)
        reg.observe("serve.stage.dispatch_s", 0.002)
        reg.observe("serve.request_s", 0.01)
    text = monitor.prometheus_text(session=reg)
    lines = text.splitlines()
    assert lines.count("# TYPE photon_serve_stage_seconds summary") == 1
    assert any(l.startswith(
        'photon_serve_stage_seconds{stage="queue_wait",quantile="0.5"}')
        for l in lines)
    assert 'photon_serve_stage_seconds_count{stage="dispatch"} 10' \
        in lines
    # The plain request histogram keeps the flat exposition.
    assert "# TYPE photon_serve_request_s summary" in lines
    assert not any("photon_serve_stage_queue_wait" in l for l in lines)


def test_watch_renders_serve_stage_table(tmp_path, capsys):
    """ISSUE 14 satellite: watching a SERVER run log renders the serve
    stage table (p50/p99 per stage) and the dominant-stage line."""
    path = str(tmp_path / "serve_log.jsonl")
    log = RunLogger(path, run_info={"driver": "serving"})
    log.event("progress", stage="serve", done=100.0, unit="rows",
              stages_ms={
                  "queue_wait": {"count": 40, "p50_ms": 2.1,
                                 "p99_ms": 9.5},
                  "dispatch": {"count": 12, "p50_ms": 3.3,
                               "p99_ms": 6.2},
              })
    log.close()
    rc = telemetry_main(["watch", path, "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    snap = json.loads(out.strip().splitlines()[-1])
    assert snap["serve_stages"]["queue_wait"]["p99_ms"] == 9.5
    assert snap["serve_dominant"] == {"stage": "queue_wait",
                                      "p99_ms": 9.5}
    assert "serve stages (request tracing):" in out
    assert "dominant stage: queue_wait" in out
    # A training log (no serve stage) renders no serve table.
    path2 = str(tmp_path / "train_log.jsonl")
    _write_live_log(path2, done=True)
    rc = telemetry_main(["watch", path2, "--once"])
    out2 = capsys.readouterr().out
    assert rc == 0
    snap2 = json.loads(out2.strip().splitlines()[-1])
    assert snap2["serve_stages"] is None
    assert "serve stages" not in out2
