"""Multi-host fleet training tests (ISSUE 16).

Three tiers:

- **Schedule unit tests**: ``shard_chunk_ids`` — contiguous shards,
  ragged grids padded with ``EMPTY_CHUNK`` sentinels to one COMMON
  per-host step count (the no-collective-deadlock invariant), hosts
  past the end of the grid, and the per-host directory convention.
- **Transport tests**: the tcp ``ReduceCoordinator`` star allreduce
  in-process — deterministic host-order sums, monotone sequence
  numbers, and the done-cache answering a replayed sequence (the
  killed-host fast-forward primitive).
- **End-to-end drills** (subprocess fleets on the tcp transport, so
  they run on boxes whose jaxlib lacks multiprocess CPU collectives):
  a 3-host fused-CD fit whose coefficients are BITWISE identical
  across hosts and match a single-host reference fit, and the fault
  matrix's kill-one-host drill — one host SIGKILLed mid-sweep at the
  ``fleet.reduce`` seam, restarted alone with ``resume=True`` while
  its peer holds the chunk barrier, finishing bitwise-equal to an
  uninterrupted fleet run.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

from photon_ml_tpu.parallel import fleet

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# shard_chunk_ids: the chunk-synchronized schedule
# ---------------------------------------------------------------------------


def test_shard_chunk_ids_even_split():
    locals_, schedules = zip(*(fleet.shard_chunk_ids(12, h, 3)
                               for h in range(3)))
    assert locals_ == ([0, 1, 2, 3], [4, 5, 6, 7], [8, 9, 10, 11])
    # No padding on an even grid: schedule == local shard.
    assert schedules == locals_


def test_shard_chunk_ids_ragged_pads_sentinels_last():
    pairs = [fleet.shard_chunk_ids(7, h, 3) for h in range(3)]
    # Every chunk owned exactly once, by contiguous ranges.
    owned = [c for local, _ in pairs for c in local]
    assert sorted(owned) == list(range(7))
    # One COMMON step count; sentinels pad at the END (real chunks
    # first, so prefetch never idles behind a sentinel).
    schedules = [sched for _, sched in pairs]
    assert [len(s) for s in schedules] == [3, 3, 3]
    assert schedules[2] == [6, fleet.EMPTY_CHUNK, fleet.EMPTY_CHUNK]
    for local, sched in pairs:
        assert sched[:len(local)] == local
        assert all(c == fleet.EMPTY_CHUNK for c in sched[len(local):])


def test_shard_chunk_ids_host_past_grid_is_all_sentinels():
    local, sched = fleet.shard_chunk_ids(2, 3, 4)
    assert local == []
    assert sched == [fleet.EMPTY_CHUNK]
    # Zero chunks: zero steps everywhere (degenerate but legal).
    assert fleet.shard_chunk_ids(0, 1, 4) == ([], [])


def test_shard_chunk_ids_validates_host():
    with pytest.raises(ValueError):
        fleet.shard_chunk_ids(8, 3, 3)
    with pytest.raises(ValueError):
        fleet.shard_chunk_ids(-1, 0, 2)


def test_host_dir_shards_only_in_fleet(tmp_path):
    base = str(tmp_path / "out")
    ctx = fleet.FleetContext(host_id=2, n_hosts=3, transport="tcp",
                             coordinator="127.0.0.1:1")
    assert fleet.host_dir(base, ctx) == os.path.join(base, "host_002")
    assert fleet.host_dir(base, None) == base
    solo = fleet.FleetContext(host_id=0, n_hosts=1, transport="tcp")
    assert fleet.host_dir(base, solo) == base


# ---------------------------------------------------------------------------
# tcp transport: coordinator round trip + replay cache
# ---------------------------------------------------------------------------


def _tree(v: float) -> dict:
    return {"grad": np.arange(4, dtype=np.float32) * v,
            "loss": np.float32(v)}


def test_tcp_reduce_round_trip_and_replay_cache():
    coord = fleet.ReduceCoordinator(2)
    reds = [fleet.FleetReducer(fleet.FleetContext(
        host_id=h, n_hosts=2, transport="tcp",
        coordinator=coord.address), stall_timeout_s=30.0)
        for h in range(2)]
    try:
        results: list = [None, None]

        def run(h):
            for step in range(3):
                results[h] = reds[h].reduce(_tree(float(h + 1 + step)))

        threads = [threading.Thread(target=run, args=(h,))
                   for h in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        assert not any(t.is_alive() for t in threads)
        # Both hosts hold the SAME fleet total (last step: 3 + 4).
        for h in range(2):
            np.testing.assert_array_equal(
                results[h]["grad"], np.arange(4, dtype=np.float32) * 7)
            assert float(results[h]["loss"]) == 7.0
        assert [r.seq for r in reds] == [3, 3]
        assert coord.reduces == 3
        assert coord.replays == 0

        # The killed-host fast-forward: rewind ONE host's sequence and
        # replay — answered from the done cache without any peer at
        # the barrier, bitwise-equal to the original total.
        reds[0].seq = 1
        replayed = reds[0].reduce(_tree(123.0))   # payload irrelevant
        np.testing.assert_array_equal(
            replayed["grad"], np.arange(4, dtype=np.float32) * 5)
        assert coord.replays == 1
        assert coord.reduces == 3                 # never re-summed
    finally:
        for r in reds:
            r.close()
        coord.close()


def test_single_host_reduce_is_identity():
    red = fleet.FleetReducer(fleet.FleetContext(host_id=0, n_hosts=1,
                                                transport="tcp"))
    tree = _tree(2.0)
    out = red.reduce(tree)
    assert out is tree
    assert red.seq == 0


# ---------------------------------------------------------------------------
# End-to-end fleet drills (subprocess workers, tcp transport)
# ---------------------------------------------------------------------------

_FLEET_WORKER = r'''
import json
import os
import sys

sys.path.insert(0, os.environ["PML_REPO"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def _workload(n=240, d=24, k=4, d_re=2):
    rng = np.random.default_rng(7)
    cols = np.stack([np.sort(rng.choice(d, k, replace=False))
                     for _ in range(n)]).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    w_true = rng.normal(0, 1, d)
    ids = np.concatenate([rng.integers(0, 10, (2 * n) // 3),
                          rng.integers(50, 53, n - (2 * n) // 3)])
    b_true = rng.normal(0, 0.7, 60)
    m = np.einsum("nk,nk->n", vals, w_true[cols]) + b_true[ids % 60]
    y = (m + rng.normal(0, 0.3, n) > 0).astype(np.float32)
    rows = [(cols[i], vals[i]) for i in range(n)]
    from photon_ml_tpu.game.dataset import GameDataset
    return GameDataset(
        labels=y,
        features={"f": rows,
                  "re": rng.normal(0, 1, (n, d_re)).astype(np.float32)},
        entity_ids={"u": ids}, feature_dims={"f": d})


def main():
    from photon_ml_tpu.parallel import fleet
    from photon_ml_tpu.reliability import faults

    fleet.initialize_from_env()
    kill_at = os.environ.get("FLEET_T_KILL_AT")
    if kill_at:
        faults.install(faults.FaultInjector([
            faults.Fault(site="fleet.reduce", kind="kill",
                         at=int(kill_at))]))

    from photon_ml_tpu.config import (
        CoordinateConfig, CoordinateKind, OptimizerSettings,
        TrainingConfig)
    from photon_ml_tpu.estimators.game_estimator import GameEstimator
    from photon_ml_tpu.models.glm import TaskType

    out_base = os.environ["FLEET_T_OUT"]
    cfg = TrainingConfig(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[
            CoordinateConfig(name="global",
                             kind=CoordinateKind.FIXED_EFFECT,
                             feature_shard="f",
                             optimizer=OptimizerSettings(
                                 max_iters=40, reg_weight=1.0,
                                 tolerance=1e-6)),
            CoordinateConfig(name="per_u",
                             kind=CoordinateKind.RANDOM_EFFECT,
                             feature_shard="re", entity_key="u",
                             optimizer=OptimizerSettings(
                                 max_iters=30, reg_weight=2.0,
                                 tolerance=1e-6)),
        ],
        update_sequence=["global", "per_u"],
        n_iterations=int(os.environ.get("FLEET_T_CYCLES", "6")),
        intercept=False, chunk_rows=40, chunk_layout="ELL",
        cd_fused=True, validation_fraction=0.0,
        validate_per_iteration=False,
        spill_dir=os.path.join(out_base, "spill"),
        checkpoint_dir=(os.path.join(out_base, "ckpt")
                        if os.environ.get("FLEET_T_CKPT") else None),
        resume=os.environ.get("FLEET_T_RESUME") == "1",
    )
    cfg.validate()
    models = GameEstimator(cfg).fit(_workload())[0].model.models
    red = fleet.reducer()
    ctx = fleet.active()
    print("RESULT " + json.dumps({
        "fe": np.asarray(
            models["global"].coefficients.means).tolist(),
        "re0": np.asarray(
            models["per_u"].coefficient_blocks[0]).ravel().tolist(),
        "seq": red.seq if red is not None else -1,
        "host": ctx.host_id if ctx is not None else -1,
    }))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
'''


def _spawn_worker(script: str, extra_env: dict) -> subprocess.Popen:
    env = dict(os.environ)
    env.update({"PML_REPO": _REPO, "JAX_PLATFORMS": "cpu"})
    env.update(extra_env)
    return subprocess.Popen([sys.executable, script], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _result(proc: subprocess.Popen, tag: str, timeout=300.0) -> dict:
    out, err = proc.communicate(timeout=timeout)
    assert proc.returncode == 0, (
        f"{tag} rc={proc.returncode}\n{out[-2000:]}\n{err[-3000:]}")
    lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
    assert lines, f"{tag} printed no RESULT line:\n{out}\n{err[-2000:]}"
    return json.loads(lines[-1][len("RESULT "):])


def _fleet_env(coord: fleet.ReduceCoordinator, host: int,
               n_hosts: int, out_dir: str, **extra) -> dict:
    env = {"PHOTON_FLEET_NUM_HOSTS": str(n_hosts),
           "PHOTON_FLEET_HOST_ID": str(host),
           "PHOTON_FLEET_COORDINATOR": coord.address,
           "FLEET_T_OUT": out_dir}
    env.update({k: str(v) for k, v in extra.items()})
    return env


@pytest.mark.slow   # 4 subprocess estimator fits
def test_fleet_fused_fit_bitwise_across_hosts_and_matches_solo(
        tmp_path):
    """3 tcp-fleet hosts train the fused-CD workload over sharded
    chunks; every host ends with BITWISE-identical coefficients (the
    replicated-state invariant: all hosts apply the same
    globally-reduced statistics in the same order) that match a
    single-host fit of the same workload to float tolerance (summation
    order across chunk shards differs — bitwise is not expected
    against the solo run, only across fleet hosts)."""
    script = tmp_path / "worker.py"
    script.write_text(_FLEET_WORKER)
    n_hosts = 3
    coord = fleet.ReduceCoordinator(n_hosts)
    try:
        procs = [_spawn_worker(str(script), _fleet_env(
            coord, h, n_hosts, str(tmp_path / "fleet")))
            for h in range(n_hosts)]
        results = [_result(p, f"host{h}")
                   for h, p in enumerate(procs)]
    finally:
        coord.close()
    solo = _result(_spawn_worker(str(script),
                                 {"FLEET_T_OUT": str(tmp_path / "solo")}),
                   "solo")

    fe = [np.asarray(r["fe"]) for r in results]
    re0 = [np.asarray(r["re0"]) for r in results]
    for h in range(1, n_hosts):
        np.testing.assert_array_equal(fe[0], fe[h])
        np.testing.assert_array_equal(re0[0], re0[h])
    # Same reduce count on every host == the barrier never skewed.
    assert len({r["seq"] for r in results}) == 1
    assert results[0]["seq"] > 0
    assert coord.reduces == results[0]["seq"]
    np.testing.assert_allclose(fe[0], np.asarray(solo["fe"]),
                               atol=5e-4, rtol=0)
    np.testing.assert_allclose(re0[0], np.asarray(solo["re0"]),
                               atol=5e-4, rtol=0)


@pytest.mark.slow   # 5 subprocess estimator fits incl. the kill drill
def test_fleet_kill_one_host_resumes_bitwise(tmp_path):
    """The fault matrix's kill-one-host drill: host 1 of a 2-host tcp
    fleet is SIGKILLed at its 7th ``fleet.reduce`` (mid-sweep, after
    at least one per-host checkpoint).  Host 0 is NEVER restarted — it
    holds the chunk barrier while host 1 alone restarts with
    ``resume=True``, restores its own ``host_001/`` checkpoint
    (including the reduce sequence) and fast-forwards through the
    coordinator's done-cache to the live barrier.  The resumed fleet's
    coefficients must be BITWISE equal to an uninterrupted fleet
    run's."""
    script = tmp_path / "worker.py"
    script.write_text(_FLEET_WORKER)
    n_hosts, kill_at = 2, 7

    # Reference: the same 2-host fleet, uninterrupted.
    coord = fleet.ReduceCoordinator(n_hosts)
    try:
        procs = [_spawn_worker(str(script), _fleet_env(
            coord, h, n_hosts, str(tmp_path / "ref"), FLEET_T_CKPT=1))
            for h in range(n_hosts)]
        ref = [_result(p, f"ref-host{h}")
               for h, p in enumerate(procs)]
    finally:
        coord.close()

    # The drill: kill host 1, let host 0 wait, restart ONLY host 1.
    coord = fleet.ReduceCoordinator(n_hosts)
    try:
        out = str(tmp_path / "drill")
        survivor = _spawn_worker(str(script), _fleet_env(
            coord, 0, n_hosts, out, FLEET_T_CKPT=1))
        victim = _spawn_worker(str(script), _fleet_env(
            coord, 1, n_hosts, out, FLEET_T_CKPT=1,
            FLEET_T_KILL_AT=kill_at))
        victim.wait(timeout=300)
        victim_out, victim_err = victim.communicate()
        assert victim.returncode == -signal.SIGKILL, (
            f"victim exited rc={victim.returncode}, not SIGKILL:\n"
            f"{victim_out[-1000:]}\n{victim_err[-2000:]}")
        assert survivor.poll() is None, "survivor died with the victim"

        restarted = _spawn_worker(str(script), _fleet_env(
            coord, 1, n_hosts, out, FLEET_T_CKPT=1, FLEET_T_RESUME=1))
        r1 = _result(restarted, "restarted-host1")
        r0 = _result(survivor, "survivor-host0")
        # The restart replayed its pre-kill reduce prefix from the
        # coordinator's done-cache instead of re-summing it.
        assert coord.replays > 0
    finally:
        coord.close()

    for r in (r0, r1):
        np.testing.assert_array_equal(np.asarray(ref[0]["fe"]),
                                      np.asarray(r["fe"]))
        np.testing.assert_array_equal(np.asarray(ref[0]["re0"]),
                                      np.asarray(r["re0"]))
    assert r0["seq"] == ref[0]["seq"]


# ---------------------------------------------------------------------------
# Streamed TRON across the fleet (ISSUE 17)
# ---------------------------------------------------------------------------

_TRON_WORKER = r'''
import json
import os
import sys

sys.path.insert(0, os.environ["PML_REPO"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    from photon_ml_tpu.parallel import fleet

    fleet.initialize_from_env()

    import jax.numpy as jnp

    from photon_ml_tpu.data.chunked_batch import build_chunked_batch
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.base import OptimizerConfig
    from photon_ml_tpu.optim.streaming import (
        ChunkedGLMObjective,
        streaming_tron_solve,
    )

    # Every host builds the SAME dataset (seeded); build_chunked_batch
    # shards the chunk schedule by the fleet context, and the per-chunk
    # psum inside value/gradient/HVP passes re-totals the statistics.
    n, d, k = 640, 48, 4
    rng = np.random.default_rng(17)
    cols = np.stack([np.sort(rng.choice(d, k, replace=False))
                     for _ in range(n)]).astype(np.int64)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    vals = vals * np.power(
        10.0, -1.5 * cols / max(d - 1, 1)).astype(np.float32)
    w_true = rng.normal(0, 1, d).astype(np.float32)
    m = np.einsum("nk,nk->n", vals, w_true[cols])
    y = (m + rng.normal(0, 0.3, n) > 0).astype(np.float32)
    rows = SparseRows.from_flat(np.arange(n + 1, dtype=np.int64) * k,
                                cols.reshape(-1), vals.reshape(-1))
    obj = GLMObjective(loss=losses.LOGISTIC,
                       reg=RegularizationContext.l2(0.1),
                       norm=NormalizationContext.identity())
    cb = build_chunked_batch(rows, d, y, n_chunks=4, layout="ell")
    cobj = ChunkedGLMObjective(obj, cb, max_resident=4)
    res = streaming_tron_solve(
        cobj.value_and_gradient, cobj.hvp_pass,
        jnp.zeros(d, jnp.float32),
        OptimizerConfig(max_iters=40, tolerance=1e-8),
        hessian_diag=cobj.hessian_diagonal)
    red = fleet.reducer()
    ctx = fleet.active()
    print("RESULT " + json.dumps({
        "w": np.asarray(res.w).tolist(),
        "iterations": int(res.iterations),
        "converged": bool(res.converged),
        "seq": red.seq if red is not None else -1,
        "host": ctx.host_id if ctx is not None else -1,
    }))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
'''


@pytest.mark.slow   # 3 subprocess streamed TRON fits
def test_fleet_streaming_tron_bitwise_across_hosts_and_matches_solo(
        tmp_path):
    """2 tcp-fleet hosts run the streamed TRON fit over sharded chunks
    (value/gradient, Hessian-diag, and every CG HVP pass psum-reduced
    per chunk); both hosts end with BITWISE-identical coefficients at
    the same iteration count and reduce sequence, and the fit matches
    a solo run of the same workload to float tolerance (chunk-shard
    summation order differs, so bitwise is only expected ACROSS fleet
    hosts)."""
    script = tmp_path / "tron_worker.py"
    script.write_text(_TRON_WORKER)
    n_hosts = 2
    coord = fleet.ReduceCoordinator(n_hosts)
    try:
        procs = [_spawn_worker(str(script), _fleet_env(
            coord, h, n_hosts, str(tmp_path / "fleet")))
            for h in range(n_hosts)]
        results = [_result(p, f"host{h}")
                   for h, p in enumerate(procs)]
    finally:
        coord.close()
    solo = _result(_spawn_worker(str(script), {}), "solo")

    w = [np.asarray(r["w"], np.float32) for r in results]
    np.testing.assert_array_equal(w[0], w[1])
    assert results[0]["iterations"] == results[1]["iterations"]
    assert results[0]["converged"] is True
    assert solo["converged"] is True
    # Same reduce count on every host == the barrier never skewed, and
    # the HVP passes actually went through the fleet reducer.
    assert len({r["seq"] for r in results}) == 1
    assert results[0]["seq"] > 0
    assert solo["seq"] == -1
    np.testing.assert_allclose(w[0], np.asarray(solo["w"], np.float32),
                               rtol=1e-3, atol=1e-3)
