"""GP / EI / search / tuner tests (reference hyperparameter suite class
of coverage: kernels vs closed forms, GP posterior sanity, EI math,
search convergence on a known function — SURVEY.md §2.7, §4)."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.hyperparameter import (
    GaussianProcessSearch,
    HyperparameterTuner,
    KernelType,
    ParamRange,
    ParamScale,
    RandomSearch,
    SearchSpace,
    TunerMode,
    expected_improvement,
    fit_gp,
)
from photon_ml_tpu.hyperparameter.kernels import matern52, rbf


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------

def test_kernels_closed_form():
    x = jnp.asarray([[0.0], [1.0]])
    k = rbf(x, x, amplitude=2.0, lengthscale=0.5)
    # k(0,0) = σ² = 4; k(0,1) = 4·exp(−0.5·(1/0.5)²) = 4·exp(−2)
    np.testing.assert_allclose(float(k[0, 0]), 4.0, rtol=1e-6)
    np.testing.assert_allclose(float(k[0, 1]), 4.0 * np.exp(-2.0),
                               rtol=1e-5)

    m = matern52(x, x, amplitude=1.0, lengthscale=1.0)
    r = 1.0
    s5 = np.sqrt(5.0) * r
    expected = (1.0 + s5 + 5.0 / 3.0 * r * r) * np.exp(-s5)
    np.testing.assert_allclose(float(m[0, 1]), expected, rtol=1e-4)
    # PSD: eigenvalues of a random gram are non-negative
    pts = jnp.asarray(np.random.default_rng(0).uniform(size=(20, 3)),
                      jnp.float32)
    gram = np.asarray(matern52(pts, pts, 1.0, 0.3))
    assert np.linalg.eigvalsh(gram).min() > -1e-5


# ---------------------------------------------------------------------------
# GP regression
# ---------------------------------------------------------------------------

def test_gp_interpolates_and_reverts_to_prior():
    rng = np.random.default_rng(1)
    x = rng.uniform(size=(25, 1)).astype(np.float32)
    y = np.sin(6.0 * x[:, 0]).astype(np.float32)
    gp = fit_gp(x, y, kind=KernelType.MATERN52)

    mean, std = gp.predict(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(mean), y, atol=0.1)
    assert float(jnp.max(std)) < 0.5

    # Far from data: mean → prior mean, std → prior amplitude.
    far = jnp.asarray([[25.0]])
    mean_far, std_far = gp.predict(far)
    np.testing.assert_allclose(float(mean_far[0]), float(np.mean(y)),
                               atol=0.2)
    assert float(std_far[0]) > 0.8 * gp.amplitude


def test_expected_improvement_math():
    # Degenerate σ→0: EI = max(μ − best, 0)
    ei_hi = expected_improvement(jnp.asarray(2.0), jnp.asarray(1e-9),
                                 jnp.asarray(1.0))
    np.testing.assert_allclose(float(ei_hi), 1.0, atol=1e-6)
    ei_lo = expected_improvement(jnp.asarray(0.0), jnp.asarray(1e-9),
                                 jnp.asarray(1.0))
    np.testing.assert_allclose(float(ei_lo), 0.0, atol=1e-6)
    # At μ = best, EI = σ/√(2π)
    ei_eq = expected_improvement(jnp.asarray(1.0), jnp.asarray(0.5),
                                 jnp.asarray(1.0))
    np.testing.assert_allclose(float(ei_eq), 0.5 / np.sqrt(2 * np.pi),
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# Search space / rescaling
# ---------------------------------------------------------------------------

def test_search_space_rescaling_roundtrip():
    space = SearchSpace([
        ParamRange("lin", 2.0, 10.0, ParamScale.LINEAR),
        ParamRange("log", 1e-3, 1e3, ParamScale.LOG),
    ])
    cfg = {"lin": 4.0, "log": 1.0}
    u = space.to_unit(cfg)
    np.testing.assert_allclose(u, [0.25, 0.5], rtol=1e-6)
    back = space.from_unit(u)
    np.testing.assert_allclose(back["lin"], 4.0, rtol=1e-6)
    np.testing.assert_allclose(back["log"], 1.0, rtol=1e-6)

    with pytest.raises(ValueError, match="low > 0"):
        SearchSpace([ParamRange("bad", 0.0, 1.0, ParamScale.LOG)])


# ---------------------------------------------------------------------------
# Search strategies: GP search beats random on a smooth target
# ---------------------------------------------------------------------------

def _objective(cfg: dict) -> float:
    # Max at log10(x) = 0.5 → x ≈ 3.16
    lx = np.log10(cfg["x"])
    return float(-((lx - 0.5) ** 2))


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_gp_search_converges_to_optimum():
    space = SearchSpace([ParamRange("x", 1e-3, 1e3, ParamScale.LOG)])
    tuner = HyperparameterTuner(space, mode=TunerMode.BAYESIAN, seed=3)
    trials = tuner.run(lambda c: (_objective(c), None), n_trials=18)
    best = tuner.best(trials)
    assert abs(np.log10(best.config["x"]) - 0.5) < 0.35
    # The GP phase (post-seeding) concentrates near the optimum: the
    # best of the GP-proposed trials beats the best random seed.
    seeds = trials[:3]
    gp_phase = trials[3:]
    assert max(t.metric for t in gp_phase) >= max(t.metric for t in seeds)


def test_random_search_covers_space():
    space = SearchSpace([ParamRange("x", 1e-2, 1e2, ParamScale.LOG)])
    rs = RandomSearch(space, seed=0)
    xs = [rs.propose([])["x"] for _ in range(200)]
    assert min(xs) < 0.1 and max(xs) > 10.0  # spans decades


def test_smaller_is_better_metric():
    space = SearchSpace([ParamRange("x", 1e-3, 1e3, ParamScale.LOG)])
    tuner = HyperparameterTuner(space, mode=TunerMode.BAYESIAN,
                                larger_is_better=False, seed=5)
    # Minimize (log10 x − 0.5)²
    trials = tuner.run(lambda c: (-_objective(c), None), n_trials=15)
    best = tuner.best(trials)
    assert best.metric == min(t.metric for t in trials)
    assert abs(np.log10(best.config["x"]) - 0.5) < 0.35


# ---------------------------------------------------------------------------
# End-to-end: tuned training through the driver
# ---------------------------------------------------------------------------

def test_tuned_training_driver(tmp_path):
    from photon_ml_tpu.cli import game_training_driver
    from photon_ml_tpu.io.dataset import write_game_dataset
    from photon_ml_tpu.utils.synthetic import make_movielens_like

    data = make_movielens_like(n_users=20, n_items=10, n_obs=900,
                               dim_global=6, seed=7)
    path = str(tmp_path / "train.jsonl")
    write_game_dataset(
        path, labels=data["labels"],
        features={"global": data["x"].astype(np.float32)},
        ids={},
    )
    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [{
            "name": "global", "kind": "FIXED_EFFECT",
            "feature_shard": "global",
            "optimizer": {"reg_weight": 1.0, "max_iters": 60},
        }],
        "update_sequence": ["global"],
        "input_path": path,
        "validation_fraction": 0.3,
        "dense_feature_shards": ["global"],
        "tuning": {"n_trials": 5, "mode": "BAYESIAN",
                   "reg_weight_ranges": {
                       "global": {"low": 1e-3, "high": 1e3}}},
        "output_dir": str(tmp_path / "out"),
        "evaluators": ["AUC"],
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    summary = game_training_driver.main(["--config", cfg_path])
    # BEST mode: one saved model, the best of 5 trials.
    assert len(summary["models"]) == 1
    assert summary["models"][0]["evaluations"]["AUC"] > 0.7
    # Trials were logged.
    from photon_ml_tpu.utils.run_log import read_run_log
    events = read_run_log(str(tmp_path / "out" / "run_log.jsonl"))
    assert sum(e["event"] == "tuning_trial" for e in events) == 5