"""Fused CD super-sweep (ISSUE 11): one streamed store pass per
coordinate-descent cycle must converge to the same block-stationary
point as the per-coordinate path — coefficients, scores, and final
validation metric — across coordinate mixes (fixed-only, fixed + dense
RE, fixed + sparse/projected RE, with retirement) × chunk grids; the
sweep odometer must attribute every pass (passes/cycle ≈ 1 through
``telemetry report``); checkpoint/resume at cycle boundaries must
restore to parity; warm fused sweeps must compile nothing; the
``train.cd_fused`` monitor stage must emit per-chunk progress; and the
shared LRU window must bound TOTAL residency across coordinates in the
legacy path too.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.config import (
    CoordinateConfig,
    CoordinateKind,
    OptimizerSettings,
    TrainingConfig,
)
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.models.glm import TaskType

# The documented fused-vs-per-coordinate tolerance (README "Fused CD
# training"): both paths stop within solver tolerance of the same
# block-stationary point, not bitwise-identically — the fused path
# walks damped Jacobi Newton steps, the legacy path full inner solves.
PARITY_ATOL = 5e-3


def _workload(rng, n=360, d=30, k=4, d_re=2, re_kind="dense"):
    """Sparse fixed-effect shard + optional random effect (dense or
    sparse/projected), labels driven by both planes."""
    cols = np.stack([np.sort(rng.choice(d, k, replace=False))
                     for _ in range(n)]).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    w_true = rng.normal(0, 1, d)
    ids = np.concatenate([
        rng.integers(0, 20, (2 * n) // 3),
        rng.integers(100, 104, n - (2 * n) // 3),
    ])
    b_true = rng.normal(0, 0.7, 200)
    m = np.einsum("nk,nk->n", vals, w_true[cols]) + b_true[ids % 200]
    y = (m + rng.normal(0, 0.3, n) > 0).astype(np.float32)
    rows = [(cols[i], vals[i]) for i in range(n)]
    features = {"f": rows}
    feature_dims = {"f": d}
    if re_kind == "dense":
        features["re"] = rng.normal(0, 1, (n, d_re)).astype(np.float32)
    elif re_kind == "sparse":
        d_sp = 10
        re_rows = []
        for _ in range(n):
            kk = rng.integers(1, 4)
            rc = rng.choice(d_sp, size=kk, replace=False).astype(np.int32)
            re_rows.append((rc, rng.normal(0, 1, kk).astype(np.float32)))
        features["re"] = re_rows
        feature_dims["re"] = d_sp
    entity_ids = {} if re_kind == "none" else {"u": ids}
    return GameDataset(labels=y, features=features,
                       entity_ids=entity_ids, feature_dims=feature_dims)


def _cfg(fused, iters, re=True, chunk_rows=96, tolerance=1e-6, **kw):
    coords = [CoordinateConfig(
        name="global", kind=CoordinateKind.FIXED_EFFECT,
        feature_shard="f",
        optimizer=OptimizerSettings(max_iters=60, reg_weight=1.0,
                                    tolerance=tolerance))]
    seq = ["global"]
    if re:
        coords.append(CoordinateConfig(
            name="per_u", kind=CoordinateKind.RANDOM_EFFECT,
            feature_shard="re", entity_key="u",
            optimizer=OptimizerSettings(max_iters=40, reg_weight=2.0,
                                        tolerance=tolerance)))
        seq.append("per_u")
    kw.setdefault("validation_fraction", 0.0)
    kw.setdefault("validate_per_iteration", False)
    cfg = TrainingConfig(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=coords, update_sequence=seq, n_iterations=iters,
        intercept=False, chunk_rows=chunk_rows, chunk_layout="ELL",
        cd_fused=fused, **kw)
    cfg.validate()
    return cfg


def _fe(models):
    return np.asarray(models["global"].coefficients.means)


def _re_blocks(models):
    return [np.asarray(b) for b in models["per_u"].coefficient_blocks]


def _assert_model_parity(m_a, m_b, atol=PARITY_ATOL):
    np.testing.assert_allclose(_fe(m_a), _fe(m_b), atol=atol, rtol=0)
    if "per_u" in m_a:
        for ba, bb in zip(_re_blocks(m_a), _re_blocks(m_b)):
            np.testing.assert_allclose(ba, bb, atol=atol, rtol=0)


# ---------------------------------------------------------------------------
# Fused ≡ per-coordinate parity across coordinate mixes × chunk grids
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("re_kind,chunk_rows", [
    ("none", 96), ("dense", 96), ("dense", 64), ("sparse", 96),
])
def test_fused_matches_percoord(rng, re_kind, chunk_rows):
    """The documented-tolerance parity matrix: final coefficients agree
    across fixed-only / fixed+dense-RE / fixed+sparse-projected-RE ×
    chunk grids (the fused path runs more, cheaper cycles)."""
    ds = _workload(rng, re_kind=re_kind)
    re = re_kind != "none"
    m_l = GameEstimator(_cfg(False, 3, re=re, chunk_rows=chunk_rows)
                        ).fit(ds)[0].model.models
    m_f = GameEstimator(_cfg(True, 80, re=re, chunk_rows=chunk_rows)
                        ).fit(ds)[0].model.models
    _assert_model_parity(m_l, m_f)


def test_fused_scores_match_percoord(rng):
    """Score parity one level deeper than coefficients: the two fits'
    models transform identically (within the documented tolerance) on
    the training data."""
    from photon_ml_tpu.estimators import GameTransformer

    ds = _workload(rng)
    r_l = GameEstimator(_cfg(False, 4)).fit(ds)[0]
    r_f = GameEstimator(_cfg(True, 80)).fit(ds)[0]
    s_l = np.asarray(GameTransformer(
        model=r_l.model, task=TaskType.LOGISTIC_REGRESSION).transform(ds))
    s_f = np.asarray(GameTransformer(
        model=r_f.model, task=TaskType.LOGISTIC_REGRESSION).transform(ds))
    np.testing.assert_allclose(s_f, s_l, atol=1e-2, rtol=0)


def test_fused_validation_trajectory(rng):
    """Per-cycle validation rides the fused loop like the legacy one:
    one entry per cycle, and both paths end at the same metric."""
    from photon_ml_tpu.evaluation.evaluators import EvaluatorType

    ds = _workload(rng, n=420)
    val = _workload(np.random.default_rng(7), n=200)
    kw = dict(validate_per_iteration=True,
              evaluators=[EvaluatorType.AUC])
    r_l = GameEstimator(_cfg(False, 3, **kw)).fit(ds, val)[0]
    r_f = GameEstimator(_cfg(True, 60, **kw)).fit(ds, val)[0]
    assert len(r_f.validation_history) == 60
    auc_l = r_l.evaluations[EvaluatorType.AUC]
    auc_f = r_f.evaluations[EvaluatorType.AUC]
    assert abs(auc_l - auc_f) < 0.02
    # The fused trajectory improves (first → best) like a descent.
    first = r_f.validation_history[0][EvaluatorType.AUC]
    assert auc_f >= first - 1e-6


def test_fused_retirement_equivalent_and_active(rng, tmp_path):
    """Retirement gates per-entity Gram accumulation without moving
    the final model beyond tolerance, and actually retires entities on
    a converging fit (the PR 5 semantics on the fused path)."""
    from photon_ml_tpu.utils.run_log import RunLogger, read_run_log

    ds = _workload(rng)
    kw = dict(tolerance=1e-4)

    def run(retirement, tag):
        log_path = str(tmp_path / f"log_{tag}.jsonl")
        with RunLogger(log_path) as log:
            r = GameEstimator(_cfg(True, 80, re_retirement=retirement,
                                   **kw)).fit(ds, run_logger=log)[0]
        cycles = [e for e in read_run_log(log_path)
                  if e.get("event") == "cd_fused_cycle"]
        return r, cycles

    r_on, cyc_on = run(True, "on")
    r_off, cyc_off = run(False, "off")
    _assert_model_parity(r_on.model.models, r_off.model.models,
                         atol=1e-2)
    assert max(e["entities_retired"] for e in cyc_on) > 0, \
        "no entity ever retired on a converging fit"
    assert all(e["entities_retired"] == 0 for e in cyc_off)


def test_fused_spilled_matches_resident_sidecars(rng, tmp_path):
    """Sidecar chunks through the content-keyed chunk store (spill_dir)
    ≡ resident sidecars, and the second fit reuses the spilled files
    (warm across runs)."""
    import glob
    import os

    ds = _workload(rng)
    m_res = GameEstimator(_cfg(True, 40)).fit(ds)[0].model.models
    cfg = _cfg(True, 40, spill_dir=str(tmp_path), host_max_resident=2)
    est = GameEstimator(cfg)
    m_sp = est.fit(ds)[0].model.models
    _assert_model_parity(m_res, m_sp, atol=1e-6)
    # FE chunks and sidecar chunks share ONE host_max_resident budget
    # (third review round: per-store windows doubled the documented
    # bound in exactly this shape).
    group = est._chunk_window_group
    assert group is not None and group.budget == 2
    assert group.n_resident <= 2
    files = glob.glob(str(tmp_path / "chunks" / "*.npz"))
    assert files, "no sidecar chunks spilled"
    mtimes = {f: os.path.getmtime(f) for f in files}
    m_sp2 = GameEstimator(cfg).fit(ds)[0].model.models
    _assert_model_parity(m_sp, m_sp2, atol=0)
    assert {f: os.path.getmtime(f) for f in files} == mtimes, \
        "warm fit re-spilled sidecar chunks"


# ---------------------------------------------------------------------------
# Odometer accounting + passes/cycle through telemetry report
# ---------------------------------------------------------------------------


def test_fused_odometer_and_passes_per_cycle(rng, tmp_path, capsys):
    """The fused extension of the sweep-odometer identity: every data
    pass is claimed (cycles by ``solver.fused_cycle_sweeps``, the final
    score pass by ``solver.aux_sweeps``), ``telemetry report`` holds rc
    0, and ``passes_per_cycle`` ≈ 1 lands in its JSON and Convergence
    table — the ISSUE 11 deliverable as a first-class metric."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main
    from photon_ml_tpu.utils.run_log import RunLogger

    ds = _workload(rng)
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log)
    try:
        GameEstimator(_cfg(True, 10)).fit(ds, run_logger=log)
        summary = t.summary()
    finally:
        t.close()
        log.close()
    c = summary["counters"]
    # The raw identity: N cycle passes + 1 final score pass, no
    # unattributed sweeps, one pass per cycle plus the epilogue.
    assert c["solver.fused_cycle_sweeps"] == 10
    assert c["solver.aux_sweeps"] == 1
    assert c["cd.cycles"] == 10
    assert c["solver.sweeps"] == (c["solver.fused_cycle_sweeps"]
                                  + c["solver.aux_sweeps"])
    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "passes/cycle" in out and "PASS" in out
    tail = json.loads(out.strip().splitlines()[-1])
    conv = tail["convergence"]
    assert conv["ok"] is True
    assert conv["unattributed_sweeps"] == 0
    assert conv["fused_cycle_sweeps"] == 10
    assert conv["cd_cycles"] == 10
    assert conv["passes_per_cycle"] == pytest.approx(1.1)


def test_legacy_report_passes_per_cycle_counts_c(rng, tmp_path, capsys):
    """The same metric on the per-coordinate path reports the C× pass
    bill the fused path removes (and the identity still holds)."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main
    from photon_ml_tpu.utils.run_log import RunLogger

    ds = _workload(rng)
    log_path = str(tmp_path / "run_log.jsonl")
    log = RunLogger(log_path)
    t = telemetry.start("metrics", run_logger=log)
    try:
        GameEstimator(_cfg(False, 2)).fit(ds, run_logger=log)
    finally:
        t.close()
        log.close()
    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    assert rc == 0
    conv = json.loads(out.strip().splitlines()[-1])["convergence"]
    assert conv["ok"] is True
    assert conv["cd_cycles"] == 2
    # Each cycle pays the fixed effect's full inner solve (multiple
    # passes: solve init + line-search trials + grad recoveries).
    assert conv["passes_per_cycle"] > 2.0


def test_training_driver_cd_fused_e2e(rng, tmp_path, capsys):
    """The acceptance criterion end to end: `--cd-fused on` through the
    training driver, then `telemetry report` over the run log shows
    passes/cycle ≈ 1 with the odometer identity holding (rc 0)."""
    from photon_ml_tpu.cli import game_training_driver
    from photon_ml_tpu.io.libsvm import write_libsvm
    from photon_ml_tpu.telemetry.__main__ import main as telemetry_main
    from photon_ml_tpu.utils.synthetic import make_a1a_like

    rows, labels, _ = make_a1a_like(n=600, seed=5)
    train_path = str(tmp_path / "a1a.libsvm")
    write_libsvm(train_path, rows, np.where(labels > 0, 1, -1))
    config = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [{
            "name": "global", "kind": "FIXED_EFFECT",
            "feature_shard": "features",
            "optimizer": {"optimizer": "LBFGS", "reg_weight": 1.0,
                          "max_iters": 60},
        }],
        "update_sequence": ["global"],
        "n_iterations": 20,
        "input_path": train_path,
        "output_dir": str(tmp_path / "out"),
        "chunk_rows": 200,
        "chunk_layout": "ELL",
        "intercept": False,
        "validation_fraction": 0.0,
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    game_training_driver.main(["--config", cfg_path,
                               "--cd-fused", "on",
                               "--telemetry", "metrics"])
    log_path = str(tmp_path / "out" / "run_log.jsonl")
    rc = telemetry_main(["report", log_path])
    out = capsys.readouterr().out
    assert rc == 0
    conv = json.loads(out.strip().splitlines()[-1])["convergence"]
    assert conv["ok"] is True
    assert conv["unattributed_sweeps"] == 0
    assert conv["cd_cycles"] == 20
    assert conv["passes_per_cycle"] <= 1.1


# ---------------------------------------------------------------------------
# Checkpoint / resume at fused-cycle boundaries (PR 9 granularities)
# ---------------------------------------------------------------------------


def test_fused_checkpoint_resume_parity(rng, tmp_path):
    """Kill-free equivalent of the SIGKILL e2e: 3 checkpointed cycles,
    then a --resume run to 8 total, must land where the uninterrupted
    8-cycle run lands (the engine's alpha/prev-value/retirement state
    rides re_state['__cd_fused__'])."""
    ds = _workload(rng)
    full = GameEstimator(_cfg(True, 8, tolerance=1e-4)
                         ).fit(ds)[0].model.models

    ck = str(tmp_path / "ckpt")
    GameEstimator(_cfg(True, 3, tolerance=1e-4, checkpoint_dir=ck)
                  ).fit(ds)
    resumed = GameEstimator(
        _cfg(True, 8, tolerance=1e-4, checkpoint_dir=ck, resume=True)
    ).fit(ds)[0].model.models
    _assert_model_parity(full, resumed, atol=1e-5)


def test_fused_checkpoint_refuses_cross_mode_resume(rng, tmp_path):
    """A fused checkpoint pairs post-step coefficients with cycle-start
    score planes; resuming it with cd_fused OFF would train every
    coordinate against one-step-stale offsets — the loop refuses
    instead (review finding)."""
    ds = _workload(rng)
    ck = str(tmp_path / "ckpt")
    GameEstimator(_cfg(True, 3, checkpoint_dir=ck)).fit(ds)
    with pytest.raises(ValueError, match="fused"):
        GameEstimator(_cfg(False, 6, checkpoint_dir=ck,
                           resume=True)).fit(ds)


def test_legacy_checkpoint_refuses_fused_resume(rng, tmp_path):
    """The symmetric direction (second review round): a legacy
    checkpoint's iteration budget means FULL inner solves — adopting
    it as a fused start would 'complete' under-converged silently."""
    ds = _workload(rng)
    ck = str(tmp_path / "ckpt")
    GameEstimator(_cfg(False, 2, checkpoint_dir=ck)).fit(ds)
    with pytest.raises(ValueError, match="per-coordinate"):
        GameEstimator(_cfg(True, 40, checkpoint_dir=ck,
                           resume=True)).fit(ds)


def test_fused_resume_rejects_config_edit(rng, tmp_path):
    """The engine snapshot carries a config-identity fingerprint
    (second review round, the PR 9 solver-snapshot rule): resuming
    after a regularization edit must reject the stale retirement /
    step-scale state instead of adopting it."""
    ds = _workload(rng)
    ck = str(tmp_path / "ckpt")
    GameEstimator(_cfg(True, 3, checkpoint_dir=ck)).fit(ds)
    edited = _cfg(True, 6, checkpoint_dir=ck, resume=True)
    edited.coordinates[1].optimizer.reg_weight = 50.0
    with pytest.raises(ValueError, match="different configuration"):
        GameEstimator(edited).fit(ds)


def test_fused_resume_rejects_retirement_flip(rng, tmp_path):
    """Retirement mode is part of the snapshot's identity (third
    review round): a mask frozen under retirement=True adopted by a
    retirement=False run would gate those entities off forever — the
    wake branch is skipped when retirement is off."""
    ds = _workload(rng)
    ck = str(tmp_path / "ckpt")
    GameEstimator(_cfg(True, 3, tolerance=1e-4, checkpoint_dir=ck,
                       re_retirement=True)).fit(ds)
    with pytest.raises(ValueError, match="different configuration"):
        GameEstimator(_cfg(True, 6, tolerance=1e-4, checkpoint_dir=ck,
                           resume=True, re_retirement=False)).fit(ds)


@pytest.mark.fast
def test_find_shard_ambiguity_is_an_error(rng):
    """Direct-caller shard probing must refuse to guess between two
    same-kind same-length shards (second review round: the first
    sparse match could be the FIXED EFFECT's shard)."""
    from photon_ml_tpu.game.fused_sweep import _find_shard

    n = 40
    rows_a = [(np.array([0], np.int32), np.ones(1, np.float32))
              for _ in range(n)]
    rows_b = [(np.array([1], np.int32), np.ones(1, np.float32))
              for _ in range(n)]
    ds = GameDataset(labels=np.zeros(n, np.float32),
                     features={"fe": rows_a, "re": rows_b},
                     entity_ids={"u": np.zeros(n, np.int64)},
                     feature_dims={"fe": 4, "re": 4})

    class _Coord:
        name = "per_u"

        class grouping:
            n_examples = n

    with pytest.raises(ValueError, match="ambiguous"):
        _find_shard(ds, _Coord, sparse=True)


@pytest.mark.fast
def test_re_step_retirement_movement_is_undamped():
    """The retirement movement plane is the FULL Newton step's norm,
    not the α-damped step applied: at α = 1/64 a still-moving entity
    must not read as converged (review finding — the damped gate
    loosened the effective threshold to tolerance/α)."""
    from photon_ml_tpu.game.fused_sweep import _re_step

    tab = jnp.zeros((3, 2), jnp.float32)
    g = jnp.ones((3, 2), jnp.float32) * 0.1
    G = jnp.tile(jnp.eye(2, dtype=jnp.float32), (3, 1, 1))
    active = jnp.ones(3, jnp.float32)
    _, move_full = _re_step(tab, g, G, active, 0.0, 1.0)
    tab_d, move_damped = _re_step(tab, g, G, active, 0.0, 1.0 / 64)
    np.testing.assert_allclose(np.asarray(move_damped),
                               np.asarray(move_full), rtol=1e-6)
    # ...while the APPLIED step is still damped.
    assert float(jnp.max(jnp.abs(tab_d))) < float(move_full[0])


# ---------------------------------------------------------------------------
# Compile budget + monitor stage
# ---------------------------------------------------------------------------


def test_fused_zero_new_compiles_after_warmup(rng):
    """Warm fused sweeps replay module-level jitted programs: a second
    fit (same shapes) compiles NOTHING — the guard-pinned acceptance
    criterion."""
    from photon_ml_tpu.analysis.guards import count_compiles

    ds = _workload(rng)
    cfg = _cfg(True, 4)
    GameEstimator(cfg).fit(ds)                      # warmup
    with count_compiles() as log:
        GameEstimator(cfg).fit(ds)
    assert log.count == 0, [r.name for r in log.records]


def test_fused_monitor_progress_stage(rng, tmp_path):
    """The ``train.cd_fused`` monitor stage (ISSUE 11 satellite): a
    monitored fused fit emits per-chunk progress snapshots whose final
    snapshot per cycle reads done == total == n_chunks, so ``telemetry
    watch`` and /status show fused-cycle progress like every other
    instrumented loop."""
    from photon_ml_tpu.utils.run_log import RunLogger, read_run_log

    ds = _workload(rng)
    log_path = str(tmp_path / "run_log.jsonl")
    with RunLogger(log_path) as log:
        GameEstimator(_cfg(True, 3, monitor="on",
                           monitor_every_s=0.001)).fit(ds, run_logger=log)
    events = read_run_log(log_path)
    fused = [e for e in events if e.get("event") == "progress"
             and e.get("stage") == "train.cd_fused"]
    assert fused, "no train.cd_fused progress events"
    n_chunks = -(-ds.n // 96)
    assert any(e["done"] == e.get("total") == n_chunks for e in fused)
    assert all(e["unit"] == "chunks" for e in fused)
    # The CD loop's cycle-level stage rides alongside.
    cd = [e for e in events if e.get("event") == "progress"
          and e.get("stage") == "cd"]
    assert any(e.get("unit") == "cycles" for e in cd)


# ---------------------------------------------------------------------------
# Config validation + shared LRU window (legacy-path satellite)
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_cd_fused_config_validation():
    base = dict(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[CoordinateConfig(
            name="g", kind=CoordinateKind.FIXED_EFFECT,
            feature_shard="f", optimizer=OptimizerSettings())],
        update_sequence=["g"],
    )
    with pytest.raises(ValueError, match="chunk_rows"):
        TrainingConfig(cd_fused=True, **base).validate()
    with pytest.raises(ValueError, match="locked"):
        TrainingConfig(cd_fused=True, chunk_rows=100,
                       locked_coordinates=["g"],
                       warm_start_model_dir="/tmp/m", **base).validate()
    with pytest.raises(ValueError, match="single-device"):
        TrainingConfig(cd_fused=True, chunk_rows=100, n_devices=2,
                       **base).validate()
    two_fe = dict(base)
    two_fe["coordinates"] = base["coordinates"] + [CoordinateConfig(
        name="g2", kind=CoordinateKind.FIXED_EFFECT, feature_shard="f2",
        optimizer=OptimizerSettings())]
    two_fe["update_sequence"] = ["g", "g2"]
    with pytest.raises(ValueError, match="exactly one fixed-effect"):
        TrainingConfig(cd_fused=True, chunk_rows=100,
                       **two_fe).validate()
    from photon_ml_tpu.ops.regularization import RegularizationType

    l1 = dict(base)
    l1["coordinates"] = [CoordinateConfig(
        name="g", kind=CoordinateKind.FIXED_EFFECT, feature_shard="f",
        optimizer=OptimizerSettings(
            regularization=RegularizationType.L1))]
    with pytest.raises(ValueError, match="smooth regularization"):
        TrainingConfig(cd_fused=True, chunk_rows=100, **l1).validate()
    TrainingConfig(cd_fused=True, chunk_rows=100, **base).validate()
    # JSON round trip carries the knob.
    from photon_ml_tpu.config import (
        config_to_json,
        training_config_from_json,
    )

    cfg = TrainingConfig(cd_fused=True, chunk_rows=100, **base)
    assert training_config_from_json(config_to_json(cfg)).cd_fused is True


@pytest.mark.fast
def test_shared_chunk_window_bounds_total_residency(tmp_path):
    """SharedChunkWindow unit contract: the budget bounds the SUM of
    resident chunks across member stores; eviction takes the globally
    least-recently-used chunk whichever store owns it."""
    from photon_ml_tpu.data.chunk_store import (
        ChunkStore,
        SharedChunkWindow,
        encode_array_chunk,
        decode_array_chunk,
    )

    codec = (encode_array_chunk, decode_array_chunk)
    group = SharedChunkWindow(2)
    stores = [ChunkStore(str(tmp_path), f"k{j}", 4, host_max_resident=4,
                         codec=codec, window_group=group)
              for j in range(2)]
    for j, store in enumerate(stores):
        for i in range(4):
            store.put(i, {"a": np.full(8, 10 * j + i, np.float32)},
                      keep_resident=False)
    # Interleaved access: the group, not the per-store window, governs.
    for i in range(4):
        for store in stores:
            store.get(i)
            total = sum(s.n_resident for s in stores)
            assert total <= 2, f"group budget violated: {total}"
    assert group.evictions > 0
    # LRU across stores: after touching (s0, 3) then (s1, 3), loading a
    # fresh chunk into s0 evicts the group-oldest — (s0, 3) stays if
    # touched last... touch s0's chunk, then load into s1: s1's OLD
    # chunk is the victim, not s0's fresh one.
    stores[0].get(3)
    stores[1].get(0)
    stores[0].get(3)                      # touch → most recent
    stores[1].get(1)                      # evicts (s1, 0), not (s0, 3)
    assert 3 in stores[0]._resident
    # join/leave bookkeeping: dropping a store forgets its entries.
    stores[0].drop_resident()
    assert stores[0].n_resident == 0
    assert group.n_resident == sum(s.n_resident for s in stores)


def test_estimator_shares_window_across_coordinates(rng, tmp_path):
    """Legacy-path satellite e2e: with a chunked fixed effect AND a
    streamed random effect both spilling, the estimator groups their
    stores under ONE host_max_resident budget — the per-coordinate
    descent no longer pins (window × coordinates) chunks."""
    ds = _workload(rng)
    cfg = _cfg(False, 2, spill_dir=str(tmp_path), host_max_resident=2,
               re_chunk_entities=6)
    est = GameEstimator(cfg)
    est.fit(ds)
    group = est._chunk_window_group
    assert group is not None
    assert group.budget == 2
    assert group.n_resident <= 2
