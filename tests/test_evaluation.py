"""Evaluator unit tests vs sklearn hand-computed values (SURVEY.md §4
tier 1: evaluator metrics vs hand computation)."""

import jax.numpy as jnp
import numpy as np
import sklearn.metrics

from photon_ml_tpu.evaluation import (
    EvaluatorType,
    auc,
    better_than,
    evaluate,
    logistic_loss,
    rmse,
)


def test_auc_matches_sklearn(rng):
    n = 500
    scores = rng.normal(0, 1, n)
    labels = (rng.uniform(size=n) < 0.4).astype(np.float64)
    ref = sklearn.metrics.roc_auc_score(labels, scores)
    got = auc(jnp.asarray(scores), jnp.asarray(labels))
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_auc_with_ties_matches_sklearn(rng):
    n = 400
    scores = rng.integers(0, 5, n).astype(np.float64)  # heavy ties
    labels = (rng.uniform(size=n) < 0.5).astype(np.float64)
    ref = sklearn.metrics.roc_auc_score(labels, scores)
    got = auc(jnp.asarray(scores), jnp.asarray(labels))
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_auc_weighted_matches_sklearn(rng):
    n = 300
    scores = rng.normal(0, 1, n)
    labels = (rng.uniform(size=n) < 0.3).astype(np.float64)
    weights = rng.uniform(0.5, 3.0, n)
    ref = sklearn.metrics.roc_auc_score(labels, scores, sample_weight=weights)
    got = auc(jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights))
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_auc_mask_equals_subset(rng):
    n = 200
    scores = rng.normal(0, 1, n)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float64)
    mask = (rng.uniform(size=n) < 0.7).astype(np.float64)
    got = auc(jnp.asarray(scores), jnp.asarray(labels), mask=jnp.asarray(mask))
    keep = mask > 0
    ref = sklearn.metrics.roc_auc_score(labels[keep], scores[keep])
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_auc_degenerate_single_class():
    s = jnp.asarray([0.1, 0.5, 0.9])
    assert float(auc(s, jnp.asarray([1.0, 1.0, 1.0]))) == 0.5
    assert float(auc(s, jnp.asarray([0.0, 0.0, 0.0]))) == 0.5


def test_rmse_and_logloss(rng):
    n = 150
    pred = rng.normal(0, 1, n)
    y = rng.normal(0, 1, n)
    np.testing.assert_allclose(
        rmse(jnp.asarray(pred), jnp.asarray(y)),
        np.sqrt(sklearn.metrics.mean_squared_error(y, pred)),
        rtol=1e-6,
    )
    yb = (rng.uniform(size=n) < 0.5).astype(np.float64)
    margins = rng.normal(0, 2, n)
    probs = 1 / (1 + np.exp(-margins))
    np.testing.assert_allclose(
        logistic_loss(jnp.asarray(margins), jnp.asarray(yb)),
        sklearn.metrics.log_loss(yb, probs),
        rtol=1e-5,
    )


def test_evaluate_dispatch_and_ordering(rng):
    s = jnp.asarray(rng.normal(0, 1, 50))
    y = jnp.asarray((rng.uniform(size=50) < 0.5).astype(np.float64))
    a = evaluate(EvaluatorType.AUC, s, y)
    assert 0.0 <= float(a) <= 1.0
    assert bool(better_than(EvaluatorType.AUC, 0.9, 0.8))
    assert bool(better_than(EvaluatorType.RMSE, 0.8, 0.9))


def test_sharded_auc_matches_manual_average(rng):
    from photon_ml_tpu.evaluation import sharded_auc

    n = 600
    ids = rng.integers(0, 12, n)
    scores = rng.normal(0, 1, n)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float64)
    got = sharded_auc(scores, labels, ids)
    vals = []
    for e in np.unique(ids):
        m = ids == e
        if len(np.unique(labels[m])) == 2:
            vals.append(sklearn.metrics.roc_auc_score(labels[m], scores[m]))
    np.testing.assert_allclose(got, np.mean(vals), rtol=1e-6)


def test_sharded_precision_at_k_matches_manual(rng):
    from photon_ml_tpu.evaluation import sharded_precision_at_k

    n, k = 400, 5
    ids = rng.integers(0, 20, n)
    scores = rng.normal(0, 1, n)
    labels = (rng.uniform(size=n) < 0.3).astype(np.float64)
    got = sharded_precision_at_k(scores, labels, ids, k)
    vals = []
    for e in np.unique(ids):
        m = np.where(ids == e)[0]
        top = m[np.argsort(-scores[m])][:k]
        vals.append(labels[top].mean())
    np.testing.assert_allclose(got, np.mean(vals), rtol=1e-6)
