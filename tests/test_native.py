"""Tests for the native C++ ETL library (photon_ml_tpu.native).

The native and numpy paths must be byte-identical: the native library is
a drop-in accelerator, not a second implementation with its own
semantics.  If no toolchain is available these tests skip (the fallback
path is what every other test exercises).
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.native import (
    colmajor_build_native,
    lib,
    libsvm_parse_native,
)

pytestmark = pytest.mark.skipif(
    lib() is None, reason="native library unavailable (no toolchain?)"
)


def test_libsvm_native_matches_python(tmp_path, rng):
    from photon_ml_tpu.io.libsvm import read_libsvm

    path = str(tmp_path / "data.libsvm")
    lines = [
        "+1 1:0.5 3:1 7:-2.25  # trailing comment",
        "-1 2:1e-3 3:0.75",
        "# full-line comment",
        "",
        "-1 5:4 5:1 9:2",        # duplicate index -> summed
        "+1 12:1",
    ]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")

    os.environ["PHOTON_ML_TPU_NATIVE"] = "1"
    rows_n, y_n, dim_n = read_libsvm(path)

    # Python reference: call the body with native disabled.
    os.environ["PHOTON_ML_TPU_NATIVE"] = "0"
    try:
        import photon_ml_tpu.native as nat

        nat._lib = None  # force fallback despite cached lib
        rows_p, y_p, dim_p = read_libsvm(path)
    finally:
        os.environ.pop("PHOTON_ML_TPU_NATIVE", None)
        nat._lib = False  # restore lazy load

    assert dim_n == dim_p
    np.testing.assert_array_equal(y_n, y_p)
    assert len(rows_n) == len(rows_p)
    for (cn, vn), (cp, vp) in zip(rows_n, rows_p):
        np.testing.assert_array_equal(cn, cp)
        np.testing.assert_allclose(vn, vp, rtol=1e-6)


def test_libsvm_native_zero_based(tmp_path):
    from photon_ml_tpu.io.libsvm import read_libsvm

    path = str(tmp_path / "zb.libsvm")
    with open(path, "w") as f:
        f.write("1 0:2.0 4:1.0\n0 1:3.0\n")
    rows, y, dim = read_libsvm(path, zero_based=True,
                               binary_labels_to_01=False)
    assert dim == 5
    np.testing.assert_array_equal(rows[0][0], [0, 4])
    np.testing.assert_array_equal(y, [1.0, 0.0])


def test_libsvm_native_malformed_raises(tmp_path):
    path = str(tmp_path / "bad.libsvm")
    with open(path, "w") as f:
        f.write("1 3:abc\n")
    with open(path, "rb") as f:
        data = f.read()
    with pytest.raises(ValueError):
        libsvm_parse_native(data)


@pytest.mark.parametrize("capacity", [8, 16])
def test_colmajor_native_matches_numpy(rng, capacity):
    import photon_ml_tpu.native as nat
    from photon_ml_tpu.data.colmajor import build_colmajor

    n, k, dim = 64, 6, 40
    cols = rng.integers(0, dim, (n, k)).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    vals[rng.uniform(size=(n, k)) < 0.2] = 0.0    # ELL padding holes

    native = colmajor_build_native(cols, vals, dim, capacity)
    assert native is not None
    tvals_n, trows_n, vcol_n = native

    nat._lib = None  # numpy path
    try:
        cm = build_colmajor(cols, vals, dim, capacity=capacity)
    finally:
        nat._lib = False
    np.testing.assert_array_equal(tvals_n, np.asarray(cm.tvals))
    np.testing.assert_array_equal(trows_n, np.asarray(cm.trows))
    np.testing.assert_array_equal(vcol_n, np.asarray(cm.vcol))


def test_colmajor_native_pad_vrows_to(rng):
    cols = rng.integers(0, 10, (16, 3)).astype(np.int32)
    vals = np.ones((16, 3), np.float32)
    out = colmajor_build_native(cols, vals, 10, 8, pad_vrows_to=64)
    assert out is not None and out[0].shape == (64, 8)
    with pytest.raises(ValueError, match="pad_vrows_to"):
        colmajor_build_native(cols, vals, 10, 1, pad_vrows_to=2)
