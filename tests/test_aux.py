"""Aux-subsystem tests: buffer donation + profiler hooks (SURVEY §5.1/§5.2)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.batch import make_dense_batch
from photon_ml_tpu.game.coordinates import (
    _fixed_train_local,
    _fixed_train_local_donating,
)
from photon_ml_tpu.models.glm import TaskType
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim.base import OptimizerType
from photon_ml_tpu.utils.run_log import RunLogger

pytestmark = pytest.mark.fast


def _solve_args(rng, donate=False):
    n, d = 64, 5
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    batch = make_dense_batch(x, y)
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.ops.regularization import RegularizationContext

    objective = GLMObjective(
        loss=TaskType.LOGISTIC_REGRESSION.loss,
        reg=RegularizationContext.none(),
        norm=NormalizationContext.identity(),
    )
    cfg = OptimizerConfig(max_iters=5, track_states=False)
    offsets = jnp.zeros(n)
    w0 = jnp.zeros(d)
    return (OptimizerType.LBFGS, cfg, False, objective, batch, offsets,
            None, None, w0)


def test_donating_solve_aliases_warm_start(rng):
    """The donating jit marks the warm-start buffer as aliased into the
    outputs; the plain variant must not (direct callers reuse arrays)."""
    args = _solve_args(rng)
    donating = _fixed_train_local_donating.lower(*args).as_text()
    plain = _fixed_train_local.lower(*args).as_text()
    assert "tf.aliasing_output" in donating
    assert "tf.aliasing_output" not in plain


def test_donating_solve_matches_plain(rng):
    args = _solve_args(rng)
    res_plain = _fixed_train_local(*args)
    # Fresh w0 buffer for the donating call (its HBM may be reused).
    args_d = args[:8] + (jnp.zeros_like(args[8]),)
    res_don = _fixed_train_local_donating(*args_d)
    np.testing.assert_allclose(np.asarray(res_plain.w),
                               np.asarray(res_don.w), rtol=1e-6)


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_timed_profile_dir_writes_trace(tmp_path):
    log = RunLogger(path=None)
    prof_dir = str(tmp_path / "trace")
    with log.timed("profiled_phase", profile_dir=prof_dir):
        jnp.sum(jnp.arange(128.0)).block_until_ready()
    found = []
    for root, _, files in os.walk(prof_dir):
        found.extend(os.path.join(root, f) for f in files)
    assert found, "jax.profiler.trace wrote no files"


def test_timed_without_profile_is_plain(tmp_path):
    path = str(tmp_path / "log.jsonl")
    log = RunLogger(path=path)
    with log.timed("plain_phase"):
        pass
    log.close()
    from photon_ml_tpu.utils.run_log import read_run_log

    ends = [e for e in read_run_log(path) if e["event"] == "phase_end"]
    assert ends and "profile_dir" not in ends[0]
