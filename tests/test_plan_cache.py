"""Plan cache (photon_ml_tpu.cache): round-trip equality, keyed
invalidation, and corruption fallback.

The warm path must be bit-compatible with the cold path (a cached plan
contracts identically to a fresh build) and must NEVER be able to make
a run fail — every bad-entry mode degrades to a rebuild.
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.cache import plan_cache
from photon_ml_tpu.data import grr as grr_mod
from photon_ml_tpu.data.grr import (
    build_grr_pair,
    build_sharded_grr_pairs,
)


@pytest.fixture
def rng():
    return np.random.default_rng(17)


def _ell(rng, n=3000, d=1200, k=6):
    cols = np.stack([
        np.sort(rng.choice(d, k, replace=False)) for _ in range(n)
    ]).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    return cols, vals, d


def _contract_both(pair, rng, n, d):
    w = rng.normal(0, 1, d).astype(np.float32)
    r = rng.normal(0, 1, n).astype(np.float32)
    return np.asarray(pair.dot(w)), np.asarray(pair.t_dot(r))


@pytest.mark.fast
def test_cache_round_trip_contraction_equality(rng, tmp_path):
    """Second build of identical inputs is a hit, and the cached plan's
    contractions equal the fresh build's in both directions."""
    cols, vals, d = _ell(rng)
    fresh = build_grr_pair(cols, vals, d, cache_dir=str(tmp_path))
    assert grr_mod.last_build_phases["cache_hit"] == 0.0
    dot_f, tdot_f = _contract_both(fresh, np.random.default_rng(5),
                                   cols.shape[0], d)

    cached = build_grr_pair(cols, vals, d, cache_dir=str(tmp_path))
    assert grr_mod.last_build_phases["cache_hit"] == 1.0
    assert "cache_load_s" in grr_mod.last_build_phases
    dot_c, tdot_c = _contract_both(cached, np.random.default_rng(5),
                                   cols.shape[0], d)
    np.testing.assert_allclose(dot_c, dot_f, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(tdot_c, tdot_f, rtol=1e-4, atol=1e-4)


@pytest.mark.fast
def test_cache_invalidation_on_data_config_version(rng, tmp_path):
    """Any of (data bytes, plan options, planner version) changing is a
    clean miss — never a stale hit."""
    td = str(tmp_path)
    cols, vals, d = _ell(rng)
    build_grr_pair(cols, vals, d, cache_dir=td)

    # Data change: one value flips -> different fingerprint.
    vals2 = vals.copy()
    vals2[0, 0] += 1.0
    build_grr_pair(cols, vals2, d, cache_dir=td)
    assert grr_mod.last_build_phases["cache_hit"] == 0.0

    # Config change: explicit cap -> different config key.
    build_grr_pair(cols, vals, d, cache_dir=td, cap=8)
    assert grr_mod.last_build_phases["cache_hit"] == 0.0

    # Version change: a planner bump orphans every old entry.
    old = grr_mod.PLANNER_VERSION
    grr_mod.PLANNER_VERSION = old + 1
    try:
        build_grr_pair(cols, vals, d, cache_dir=td)
        assert grr_mod.last_build_phases["cache_hit"] == 0.0
    finally:
        grr_mod.PLANNER_VERSION = old

    # Unchanged inputs still hit.
    build_grr_pair(cols, vals, d, cache_dir=td)
    assert grr_mod.last_build_phases["cache_hit"] == 1.0


@pytest.mark.fast
def test_cache_rebuild_flag_skips_read_but_saves(rng, tmp_path):
    """cache_rebuild=True never reads (the bench's honest-cold mode)
    but still warms the cache for the next reader."""
    td = str(tmp_path)
    cols, vals, d = _ell(rng, n=1500)
    build_grr_pair(cols, vals, d, cache_dir=td)
    build_grr_pair(cols, vals, d, cache_dir=td, cache_rebuild=True)
    assert grr_mod.last_build_phases["cache_hit"] == 0.0
    assert "cache_save_s" in grr_mod.last_build_phases
    build_grr_pair(cols, vals, d, cache_dir=td)
    assert grr_mod.last_build_phases["cache_hit"] == 1.0


@pytest.mark.fast
def test_corrupt_cache_entry_falls_back_to_rebuild(rng, tmp_path):
    """Truncated or garbage entries are rebuilt (and the rebuild
    overwrites them), never crash."""
    td = str(tmp_path)
    cols, vals, d = _ell(rng, n=1500)
    build_grr_pair(cols, vals, d, cache_dir=td)
    plans_dir = os.path.join(td, "plans")
    [entry] = os.listdir(plans_dir)
    path = os.path.join(plans_dir, entry)

    # Truncate to half: a partial write a crash could have left behind
    # (the atomic rename makes this near-impossible, but readers must
    # survive it anyway).
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2])
    assert plan_cache.load_plan(path) is None
    pair = build_grr_pair(cols, vals, d, cache_dir=td)
    assert grr_mod.last_build_phases["cache_hit"] == 0.0
    assert pair.row_dir.n_segments == cols.shape[0]

    # Pure garbage (not even a zip).
    with open(path, "wb") as f:
        f.write(b"not a plan at all")
    assert plan_cache.load_plan(path) is None
    build_grr_pair(cols, vals, d, cache_dir=td)
    # The rebuild re-saved a good entry; next read hits.
    build_grr_pair(cols, vals, d, cache_dir=td)
    assert grr_mod.last_build_phases["cache_hit"] == 1.0


@pytest.mark.fast
def test_sharded_cache_round_trip(rng, tmp_path):
    """The sharded builder's congruent pair list round-trips as one
    entry with host leaves and per-shard contraction equality."""
    td = str(tmp_path)
    d = 800
    shard_cols, shard_vals = [], []
    for _ in range(2):
        c, v, _ = _ell(rng, n=1024, d=d, k=5)
        shard_cols.append(c)
        shard_vals.append(v)
    fresh = build_sharded_grr_pairs(shard_cols, shard_vals, d,
                                    cache_dir=td)
    cached = build_sharded_grr_pairs(shard_cols, shard_vals, d,
                                     cache_dir=td)
    assert len(cached) == len(fresh) == 2
    w = rng.normal(0, 1, d).astype(np.float32)
    for a, b in zip(fresh, cached):
        np.testing.assert_allclose(np.asarray(b.dot(w)),
                                   np.asarray(a.dot(w)),
                                   rtol=1e-5, atol=1e-5)
    # Host leaves preserved (the mesh assembly contract).
    leaf = (cached[0].col_dir.vals if not hasattr(
        cached[0].col_dir, "parts") else cached[0].col_dir.parts[0].vals)
    assert isinstance(leaf, np.ndarray)

    # Different shard count = different key.
    build_sharded_grr_pairs(shard_cols + shard_cols,
                            shard_vals + shard_vals, d, cache_dir=td)
    assert len(os.listdir(os.path.join(td, "plans"))) == 2


@pytest.mark.fast
def test_chunked_batch_uses_plan_cache(rng, tmp_path):
    """build_chunked_batch(cache_dir=...) round-trips its chunk plans:
    the second build hits (one plans/ entry) and evaluates
    identically."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.chunked_batch import build_chunked_batch
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.streaming import ChunkedGLMObjective

    td = str(tmp_path)
    n, d, k = 2048, 600, 5
    cols, vals, _ = _ell(rng, n=n, d=d, k=k)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    rows = SparseRows.from_flat(
        np.arange(n + 1, dtype=np.int64) * k,
        cols.reshape(-1).astype(np.int64), vals.reshape(-1))
    obj = GLMObjective(loss=losses.LOGISTIC,
                       reg=RegularizationContext.l2(1.0),
                       norm=NormalizationContext.identity())
    w = jnp.asarray(rng.normal(0, 0.2, d), jnp.float32)

    cb1 = build_chunked_batch(rows, d, labels, n_chunks=2, layout="grr",
                              cache_dir=td)
    v1, g1 = ChunkedGLMObjective(obj, cb1).value_and_gradient(w)
    assert len(os.listdir(os.path.join(td, "plans"))) == 1
    cb2 = build_chunked_batch(rows, d, labels, n_chunks=2, layout="grr",
                              cache_dir=td)
    v2, g2 = ChunkedGLMObjective(obj, cb2).value_and_gradient(w)
    assert len(os.listdir(os.path.join(td, "plans"))) == 1
    np.testing.assert_allclose(float(v2), float(v1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g1),
                               rtol=1e-5, atol=1e-5)
