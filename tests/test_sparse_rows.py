"""SparseRows (CSR scale-ETL container) + vectorized grouping/projection.

Strategy: every vectorized path is pinned against a brute-force
per-row/per-entity reference on random data — the same parity discipline
the optimizer tests use against scipy/sklearn.
"""

import numpy as np
import pytest

from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.game.dataset import group_by_entity

pytestmark = pytest.mark.fast


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def random_raw(rng, n=200, dim=50, max_nnz=8, dupes=True):
    """Raw (indptr, cols, vals) with unsorted cols and duplicates."""
    counts = rng.integers(0, max_nnz, n)
    indptr = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=indptr[1:])
    nnz = int(indptr[-1])
    cols = rng.integers(0, dim, nnz)
    if not dupes:
        # resample rows to unique ids
        parts = []
        for i in range(n):
            c = rng.choice(dim, size=min(int(counts[i]), dim), replace=False)
            parts.append(c)
        counts = np.asarray([len(p) for p in parts])
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        cols = (np.concatenate(parts) if parts else np.zeros(0, np.int64))
        nnz = int(indptr[-1])
    vals = rng.normal(size=nnz)
    return indptr, cols, vals


def brute_canonical(indptr, cols, vals, clip_dim=None):
    rows = []
    for i in range(len(indptr) - 1):
        c = cols[indptr[i]:indptr[i + 1]]
        v = vals[indptr[i]:indptr[i + 1]]
        if clip_dim is not None:
            keep = c < clip_dim
            c, v = c[keep], v[keep]
        if len(c):
            cu, inv = np.unique(c, return_inverse=True)
            vu = np.bincount(inv, weights=v)
        else:
            cu, vu = c, v
        rows.append((cu.astype(np.int32), vu.astype(np.float32)))
    return rows


class TestFromFlat:
    def test_canonicalizes(self, rng):
        indptr, cols, vals = random_raw(rng)
        sr = SparseRows.from_flat(indptr, cols, vals)
        ref = brute_canonical(indptr, cols, vals)
        assert len(sr) == len(ref)
        for i, (c, v) in enumerate(ref):
            sc, sv = sr[i]
            np.testing.assert_array_equal(sc, c)
            np.testing.assert_allclose(sv, v, rtol=1e-6)

    def test_clip_dim(self, rng):
        indptr, cols, vals = random_raw(rng, dim=50)
        sr = SparseRows.from_flat(indptr, cols, vals, clip_dim=20)
        ref = brute_canonical(indptr, cols, vals, clip_dim=20)
        assert sr.max_col < 20
        for i, (c, v) in enumerate(ref):
            sc, sv = sr[i]
            np.testing.assert_array_equal(sc, c)
            np.testing.assert_allclose(sv, v, rtol=1e-6)

    def test_negative_col_raises(self):
        with pytest.raises(ValueError, match="negative"):
            SparseRows.from_flat(np.array([0, 1]), np.array([-1]),
                                 np.array([1.0]))

    def test_empty(self):
        sr = SparseRows.from_flat(np.zeros(1, np.int64),
                                  np.zeros(0), np.zeros(0))
        assert len(sr) == 0 and sr.nnz == 0 and sr.max_col == -1


class TestRowListProtocol:
    def test_round_trip_from_rows(self, rng):
        indptr, cols, vals = random_raw(rng, dupes=False)
        ref = brute_canonical(indptr, cols, vals)
        sr = SparseRows.from_rows(ref)
        for (c, v), (sc, sv) in zip(ref, sr):
            np.testing.assert_array_equal(sc, c)
            np.testing.assert_allclose(sv, v, rtol=1e-6)

    def test_slice_matches_take(self, rng):
        indptr, cols, vals = random_raw(rng)
        sr = SparseRows.from_flat(indptr, cols, vals)
        sl = sr[10:50]
        tk = sr.take(np.arange(10, 50))
        np.testing.assert_array_equal(sl.indptr, tk.indptr)
        np.testing.assert_array_equal(sl.cols, tk.cols)
        np.testing.assert_array_equal(sl.vals, tk.vals)

    def test_take_reorders(self, rng):
        indptr, cols, vals = random_raw(rng)
        sr = SparseRows.from_flat(indptr, cols, vals)
        idx = rng.permutation(len(sr))[:60]
        sub = sr.take(idx)
        for j, i in enumerate(idx):
            sc, sv = sr[int(i)]
            tc, tv = sub[j]
            np.testing.assert_array_equal(tc, sc)
            np.testing.assert_array_equal(tv, sv)


class TestTransforms:
    def test_with_constant_col(self, rng):
        indptr, cols, vals = random_raw(rng, dim=30)
        sr = SparseRows.from_flat(indptr, cols, vals)
        out = sr.with_constant_col(30, 1.0)
        assert len(out) == len(sr)
        for i in range(len(sr)):
            c0, v0 = sr[i]
            c1, v1 = out[i]
            np.testing.assert_array_equal(c1, np.append(c0, 30))
            np.testing.assert_allclose(v1, np.append(v0, 1.0))

    def test_with_constant_col_rejects_low_id(self, rng):
        indptr, cols, vals = random_raw(rng, dim=30)
        sr = SparseRows.from_flat(indptr, cols, vals)
        with pytest.raises(ValueError, match="intercept"):
            sr.with_constant_col(int(sr.max_col))

    def test_to_ell_matches_legacy(self, rng):
        from photon_ml_tpu.data.batch import make_sparse_batch

        indptr, cols, vals = random_raw(rng, dupes=False)
        ref_rows = brute_canonical(indptr, cols, vals)
        sr = SparseRows.from_rows(ref_rows)
        labels = rng.normal(size=len(sr)).astype(np.float32)
        b_list = make_sparse_batch(ref_rows, 50, labels, pad_to=256)
        b_sr = make_sparse_batch(sr, 50, labels, pad_to=256)
        np.testing.assert_array_equal(np.asarray(b_list.col_ids),
                                      np.asarray(b_sr.col_ids))
        np.testing.assert_array_equal(np.asarray(b_list.values),
                                      np.asarray(b_sr.values))

    def test_to_ell_capacity_error(self, rng):
        sr = SparseRows.from_rows([(np.arange(5), np.ones(5))])
        with pytest.raises(ValueError, match="capacity"):
            sr.to_ell(row_capacity=3)

    def test_concat(self, rng):
        parts = []
        for s in range(3):
            indptr, cols, vals = random_raw(np.random.default_rng(s), n=40)
            parts.append(SparseRows.from_flat(indptr, cols, vals))
        cat = SparseRows.concat(parts)
        assert len(cat) == 120
        i = 0
        for p in parts:
            for c, v in p:
                cc, cv = cat[i]
                np.testing.assert_array_equal(cc, c)
                np.testing.assert_array_equal(cv, v)
                i += 1

    def test_dot_dense(self, rng):
        indptr, cols, vals = random_raw(rng, dim=30)
        sr = SparseRows.from_flat(indptr, cols, vals)
        w = rng.normal(size=30)
        ref = np.asarray([float(v @ w[c]) for c, v in sr], np.float32)
        np.testing.assert_allclose(sr.dot_dense(w), ref, rtol=1e-5,
                                   atol=1e-6)

    def test_to_dense(self, rng):
        indptr, cols, vals = random_raw(rng, dim=30, dupes=False)
        sr = SparseRows.from_flat(indptr, cols, vals)
        x = sr.to_dense(30)
        for i, (c, v) in enumerate(sr):
            ref = np.zeros(30, np.float32)
            ref[c] = v
            np.testing.assert_allclose(x[i], ref)


class TestVectorizedGrouping:
    """group_by_entity's vectorized form vs first-principles invariants."""

    def test_slots_dense_and_cols_stable(self, rng):
        ids = rng.integers(0, 97, 3000)
        g = group_by_entity(ids)
        # Every (bucket, slot) pair dense and unique.
        for b in range(len(g.capacities)):
            slots = np.sort(g.entity_slot[g.entity_bucket == b])
            np.testing.assert_array_equal(slots, np.arange(len(slots)))
        # Within an entity, cols are 0..count-1 in original example order.
        for e in rng.choice(g.n_total_entities, 10, replace=False):
            sel = np.flatnonzero(ids == g.entity_ids[e])
            np.testing.assert_array_equal(
                g.example_col[sel], np.arange(len(sel)))
        # example_entity maps back to the right ids.
        np.testing.assert_array_equal(g.entity_ids[g.example_entity], ids)

    def test_capacity_bound(self, rng):
        ids = np.repeat(np.arange(30), rng.integers(1, 300, 30))
        g = group_by_entity(ids, bucket_base=4)
        counts = np.bincount(ids)
        for e in range(g.n_total_entities):
            cap = g.capacities[g.entity_bucket[e]]
            assert counts[e] <= cap < max(4 * counts[e], 5)


class TestVectorizedProjection:
    def test_matches_bruteforce(self, rng):
        from photon_ml_tpu.game.projector import build_subspace_projection

        n, G = 400, 60
        ids = rng.integers(0, 37, n)
        indptr, cols, vals = random_raw(rng, n=n, dim=G)
        sr = SparseRows.from_flat(indptr, cols, vals)
        g = group_by_entity(ids)
        proj, x_blocks = build_subspace_projection(g, sr, G)
        # Brute-force: each entity's subspace is its sorted distinct
        # cols; each example's block row holds its values at local idx.
        for e in rng.choice(g.n_total_entities, 12, replace=False):
            sel = np.flatnonzero(ids == g.entity_ids[e])
            feats = np.unique(np.concatenate(
                [sr[int(i)][0] for i in sel]
                or [np.zeros(0, np.int32)]))
            b, s = int(g.entity_bucket[e]), int(g.entity_slot[e])
            fids = proj.feature_ids[b][s]
            np.testing.assert_array_equal(fids[fids >= 0], feats)
            for i in sel:
                c, v = sr[int(i)]
                row = x_blocks[b][s, int(g.example_col[i])]
                ref = np.zeros(len(fids), np.float32)
                ref[np.searchsorted(feats, c)] = v
                np.testing.assert_allclose(row, ref)

    def test_projection_without_example_entity(self, rng):
        # Groupings reloaded from saved models lack example maps only;
        # in-ETL groupings may predate the example_entity field.
        from photon_ml_tpu.game.projector import build_subspace_projection

        n, G = 100, 20
        ids = rng.integers(0, 11, n)
        indptr, cols, vals = random_raw(rng, n=n, dim=G)
        sr = SparseRows.from_flat(indptr, cols, vals)
        g = group_by_entity(ids)
        ref_proj, ref_blocks = build_subspace_projection(g, sr, G)
        g.example_entity = None
        proj, blocks = build_subspace_projection(g, sr, G)
        for a, b in zip(ref_proj.feature_ids, proj.feature_ids):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(ref_blocks, blocks):
            np.testing.assert_allclose(a, b)


class TestReviewRegressions:
    """Round-3 review findings, pinned."""

    def test_negative_indexing(self, rng):
        indptr, cols, vals = random_raw(rng, n=5)
        sr = SparseRows.from_flat(indptr, cols, vals)
        c_last, v_last = sr[-1]
        c_ref, v_ref = sr[4]
        np.testing.assert_array_equal(c_last, c_ref)
        np.testing.assert_array_equal(v_last, v_ref)
        with pytest.raises(IndexError):
            sr[5]
        with pytest.raises(IndexError):
            sr[-6]

    def test_join_ids_empty_grouping(self):
        from photon_ml_tpu.game.dataset import sorted_id_join

        out = sorted_id_join(np.zeros(0, np.int64), np.array([1, 2]))
        np.testing.assert_array_equal(out, [-1, -1])

    def test_projected_scoring_out_of_space_feature_scores_zero(self):
        # A feature id >= global_dim must not alias into the next
        # entity's key range (review finding: key = entity*G + col).
        from photon_ml_tpu.estimators.game_transformer import _score_random
        from photon_ml_tpu.game.dataset import GameDataset
        from photon_ml_tpu.game.projector import SubspaceProjection
        from photon_ml_tpu.models.game import RandomEffectModel

        G = 3
        ids = np.array([10, 11, 10, 11])
        g = group_by_entity(ids)
        proj = SubspaceProjection(
            feature_ids=[np.array([[0, -1], [0, 1]], np.int32)],
            global_dim=G,
        )
        blocks = [np.array([[5.0, 0.0], [7.0, 2.0]], np.float32)]
        model = RandomEffectModel(
            coefficient_blocks=blocks, grouping=g, feature_shard="re",
            projection=proj,
        )
        # Example 0 (entity 10): feature col 3 == G aliases to
        # (entity 11, col 0) under the flat key without the bound.
        feats = SparseRows.from_rows([
            (np.array([3]), np.array([1.0], np.float32)),
            (np.array([0]), np.array([1.0], np.float32)),
            (np.array([0]), np.array([2.0], np.float32)),
            (np.array([1]), np.array([1.0], np.float32)),
        ])
        ds = GameDataset(
            labels=np.zeros(4, np.float32), features={"re": feats},
            entity_ids={"e": ids},
        )
        scores = _score_random(model, ids, ds)
        ent10 = int(g.join_ids(np.array([10]))[0])
        w10 = blocks[0][int(g.entity_slot[ent10])] \
            if int(g.entity_bucket[ent10]) == 0 else None
        np.testing.assert_allclose(
            scores, [0.0, 7.0, 2 * 5.0, 2.0] if w10[0] == 5.0
            else [0.0, 5.0, 2 * 7.0, 2.0])

    def test_concat_with_empty_parts(self, rng):
        indptr, cols, vals = random_raw(rng, n=10)
        full = SparseRows.from_flat(indptr, cols, vals)
        empty = SparseRows.from_flat(np.zeros(1, np.int64),
                                     np.zeros(0), np.zeros(0))
        cat = SparseRows.concat([empty, full, empty, full])
        assert len(cat) == 20
        for i in range(10):
            a, b = cat[i], full[i]
            np.testing.assert_array_equal(a[0], b[0])
            a2, b2 = cat[10 + i], full[i]
            np.testing.assert_array_equal(a2[0], b2[0])

    def test_chunked_reader_comment_only_window(self, tmp_path):
        from photon_ml_tpu.io import read_libsvm_chunked

        path = str(tmp_path / "c.libsvm")
        with open(path, "w") as f:
            f.write("1 1:2.0\n")
            for _ in range(5):
                f.write("# filler comment line\n")
            f.write("1 2:3.0\n")
        rows, y, dim = read_libsvm_chunked(path, chunk_bytes=40)
        assert len(rows) == 2 and dim == 2
        np.testing.assert_array_equal(rows[0][0], [0])
        np.testing.assert_array_equal(rows[1][0], [1])

    def test_sorted_key_join(self, rng):
        from photon_ml_tpu.game.dataset import sorted_key_join

        keys = rng.choice(1000, 50, replace=False)
        vals = rng.normal(size=50)
        q = np.concatenate([keys[:20], np.array([2000, 3000])])
        got, hit = sorted_key_join(keys, vals, q)
        np.testing.assert_array_equal(hit, [True] * 20 + [False] * 2)
        np.testing.assert_allclose(got[:20], vals[:20])
        got_e, hit_e = sorted_key_join(np.zeros(0, np.int64),
                                       np.zeros(0), q)
        assert not hit_e.any()
