"""End-to-end BASELINE config-1 slice (SURVEY.md §7 stage 4).

The driver-level integration tier (§4 tier 3): LIBSVM file on disk →
reader → sparse batch → feature stats → normalization → L-BFGS fit →
held-out AUC over a threshold → coefficients save/load round-trip.
This is the permanent parity fixture for "fixed-effect logistic GLM on
a1a (L-BFGS, L2 reg)".
"""

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import make_sparse_batch
from photon_ml_tpu.data.normalization import (
    NormalizationType,
    compute_normalization,
)
from photon_ml_tpu.data.statistics import compute_statistics
from photon_ml_tpu.evaluation import auc
from photon_ml_tpu.io import read_libsvm, write_libsvm
from photon_ml_tpu.models import Coefficients, GeneralizedLinearModel, TaskType
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim import OptimizationProblem, OptimizerConfig
from photon_ml_tpu.utils.synthetic import make_a1a_like


def test_config1_a1a_end_to_end(tmp_path):
    # --- fixture on disk (generated: no network; a1a-shaped) -------------
    rows, labels, _ = make_a1a_like(n=3000)
    path = str(tmp_path / "a1a_like.libsvm")
    write_libsvm(path, rows, 2.0 * labels - 1.0)  # write as {-1,+1}

    # --- read → split → batches -----------------------------------------
    rows_r, y, dim = read_libsvm(path, n_features=123)
    assert dim == 123 and len(rows_r) == 3000
    n_train = 2000
    train_rows, test_rows = rows_r[:n_train], rows_r[n_train:]
    y_train, y_test = y[:n_train], y[n_train:]

    train = make_sparse_batch(train_rows, dim, y_train)
    test = make_sparse_batch(test_rows, dim, y_test)

    # --- stats → normalization ------------------------------------------
    stats = compute_statistics(train)
    norm = compute_normalization(
        stats.mean, stats.std, stats.max_abs,
        NormalizationType.STANDARDIZATION,
    )

    # --- fit (config 1: logistic, L-BFGS, L2) ----------------------------
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(1.0),
        norm=norm,
    )
    problem = OptimizationProblem(
        objective=obj,
        config=OptimizerConfig(max_iters=200, tolerance=1e-6),
    )
    res = jax.jit(problem.run)(train, jnp.zeros(dim, jnp.float32))
    assert bool(res.converged)

    # --- model + held-out AUC -------------------------------------------
    # Solution lives in normalized model space; store raw-space
    # coefficients on the model so scoring needs no normalization context.
    w_raw = norm.model_to_raw(res.w)
    model = GeneralizedLinearModel(
        coefficients=Coefficients(means=w_raw),
        task=TaskType.LOGISTIC_REGRESSION,
    )
    margins = model.compute_score(test)
    shift = norm.margin_correction(res.w)
    test_auc = float(auc(margins - shift, test.labels, mask=test.mask))
    assert test_auc >= 0.80, f"held-out AUC {test_auc:.4f} below gate"

    # Train AUC should beat test slightly but both in the same class.
    train_auc = float(
        auc(model.compute_score(train) - shift, train.labels, mask=train.mask)
    )
    assert train_auc >= test_auc - 0.02

    # --- save / load round trip ------------------------------------------
    out = tmp_path / "model.npz"
    np.savez(out, means=np.asarray(model.coefficients.means))
    loaded = np.load(out)
    np.testing.assert_array_equal(loaded["means"],
                                  np.asarray(model.coefficients.means))


def test_normalization_improves_conditioning_not_solution_quality(tmp_path):
    """Normalized and raw fits must reach comparable AUC (the reference's
    normalization changes conditioning, not the model class)."""
    rows, labels, _ = make_a1a_like(n=1500, seed=13)
    dim = 123
    n_train = 1000
    train = make_sparse_batch(rows[:n_train], dim, labels[:n_train])
    test = make_sparse_batch(rows[n_train:], dim, labels[n_train:])

    def fit_auc(norm):
        obj = GLMObjective(
            loss=losses.LOGISTIC, reg=RegularizationContext.l2(1.0), norm=norm
        )
        problem = OptimizationProblem(
            objective=obj, config=OptimizerConfig(max_iters=200, tolerance=1e-6)
        )
        res = problem.run(train, jnp.zeros(dim, jnp.float32))
        margins = test.margins(norm.model_to_raw(res.w)) - norm.margin_correction(res.w)
        return float(auc(margins, test.labels, mask=test.mask))

    from photon_ml_tpu.data.normalization import NormalizationContext

    stats = compute_statistics(train)
    auc_raw = fit_auc(NormalizationContext.identity())
    auc_std = fit_auc(compute_normalization(
        stats.mean, stats.std, stats.max_abs, NormalizationType.STANDARDIZATION
    ))
    assert abs(auc_raw - auc_std) < 0.02
    assert min(auc_raw, auc_std) >= 0.78
