"""GLMObjective tests: gradient/HVP vs autodiff and finite differences,
sparse-vs-dense equivalence, normalization algebra, padding invariance.

Mirrors the reference's aggregator unit tests (SURVEY.md §4 tier 1:
ValueAndGradientAggregator / HessianVectorAggregator checks).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.batch import make_dense_batch, make_sparse_batch
from photon_ml_tpu.data.normalization import (
    NormalizationContext,
    NormalizationType,
    compute_normalization,
)
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext


def _random_problem(rng, n=40, d=7, sparse=False, k=4):
    labels = rng.integers(0, 2, n).astype(np.float64)
    weights = rng.uniform(0.5, 2.0, n)
    offsets = rng.normal(0, 0.3, n)
    if sparse:
        rows = []
        dense = np.zeros((n, d))
        for i in range(n):
            nnz = rng.integers(1, k + 1)
            cols = rng.choice(d, nnz, replace=False).astype(np.int32)
            vals = rng.normal(0, 1, nnz)
            rows.append((cols, vals))
            dense[i, cols] = vals
        batch = make_sparse_batch(
            rows, d, labels, weights, offsets, row_capacity=k
        )
        return batch, dense
    x = rng.normal(0, 1, (n, d))
    return make_dense_batch(x, labels, weights, offsets), x


def _numpy_reference(loss, x, labels, weights, offsets, w, l2):
    """Straight-line numpy recomputation of value and gradient."""
    z = x @ w + offsets
    lv = np.asarray(jax.vmap(loss.loss)(jnp.asarray(z, jnp.float32),
                                        jnp.asarray(labels, jnp.float32)))
    val = float(np.sum(weights * lv) + 0.5 * l2 * w @ w)
    d1 = np.asarray(jax.vmap(loss.d1)(jnp.asarray(z, jnp.float32),
                                      jnp.asarray(labels, jnp.float32)))
    grad = x.T @ (weights * d1) + l2 * w
    return val, grad


@pytest.mark.parametrize("loss", [losses.LOGISTIC, losses.SQUARED,
                                  losses.POISSON, losses.SMOOTHED_HINGE],
                         ids=lambda l: l.name)
@pytest.mark.parametrize("sparse", [False, True], ids=["dense", "sparse"])
def test_value_and_gradient_vs_numpy(rng, loss, sparse):
    batch, x = _random_problem(rng, sparse=sparse)
    d = x.shape[1]
    w = rng.normal(0, 0.4, d)
    l2 = 0.7
    obj = GLMObjective(
        loss=loss,
        reg=RegularizationContext.l2(l2),
        norm=NormalizationContext.identity(),
    )
    val, grad = obj.value_and_gradient(jnp.asarray(w, jnp.float32), batch)
    n = x.shape[0]
    ref_val, ref_grad = _numpy_reference(
        loss, x, np.asarray(batch.labels)[:n],
        np.asarray(batch.weights)[:n],
        np.asarray(batch.offsets)[:n], w, l2)
    np.testing.assert_allclose(val, ref_val, rtol=1e-4)
    np.testing.assert_allclose(grad, ref_grad, rtol=1e-3, atol=1e-4)


def test_gradient_matches_jax_autodiff(rng):
    batch, x = _random_problem(rng)
    d = x.shape[1]
    w = jnp.asarray(rng.normal(0, 0.5, d), jnp.float32)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(0.3),
        norm=NormalizationContext.identity(),
    )
    g_manual = obj.gradient(w, batch)
    g_auto = jax.grad(lambda ww: obj.value(ww, batch))(w)
    np.testing.assert_allclose(g_manual, g_auto, rtol=1e-4, atol=1e-5)


def test_hvp_matches_jax_autodiff(rng):
    batch, x = _random_problem(rng)
    d = x.shape[1]
    w = jnp.asarray(rng.normal(0, 0.5, d), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1.0, d), jnp.float32)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(0.3),
        norm=NormalizationContext.identity(),
    )
    hvp_manual = obj.hessian_vector(w, v, batch)
    hvp_auto = jax.jvp(lambda ww: obj.gradient(ww, batch), (w,), (v,))[1]
    np.testing.assert_allclose(hvp_manual, hvp_auto, rtol=1e-3, atol=1e-4)


def test_hessian_diagonal_matches_full_hessian(rng):
    batch, x = _random_problem(rng, n=25, d=5)
    w = jnp.asarray(rng.normal(0, 0.5, 5), jnp.float32)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(0.2),
        norm=NormalizationContext.identity(),
    )
    H = jax.hessian(lambda ww: obj.value(ww, batch))(w)
    np.testing.assert_allclose(
        obj.hessian_diagonal(w, batch), jnp.diagonal(H), rtol=1e-3, atol=1e-4
    )


def test_sparse_dense_equivalence(rng):
    sbatch, dense_x = _random_problem(rng, sparse=True)
    dbatch = make_dense_batch(
        dense_x,
        np.asarray(sbatch.labels), np.asarray(sbatch.weights),
        np.asarray(sbatch.offsets))
    w = jnp.asarray(rng.normal(0, 0.5, sbatch.dim), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1.0, sbatch.dim), jnp.float32)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(0.1),
        norm=NormalizationContext.identity(),
    )
    vs, gs = obj.value_and_gradient(w, sbatch)
    vd, gd = obj.value_and_gradient(w, dbatch)
    np.testing.assert_allclose(vs, vd, rtol=1e-5)
    np.testing.assert_allclose(gs, gd, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        obj.hessian_vector(w, v, sbatch), obj.hessian_vector(w, v, dbatch),
        rtol=1e-4, atol=1e-5)


def test_padding_rows_do_not_change_results(rng):
    x = rng.normal(0, 1, (10, 4))
    labels = rng.integers(0, 2, 10).astype(float)
    b1 = make_dense_batch(x, labels)
    b2 = make_dense_batch(x, labels, pad_to=32)
    w = jnp.asarray(rng.normal(0, 0.5, 4), jnp.float32)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.none(),
        norm=NormalizationContext.identity(),
    )
    v1, g1 = obj.value_and_gradient(w, b1)
    v2, g2 = obj.value_and_gradient(w, b2)
    np.testing.assert_allclose(v1, v2, rtol=1e-5)
    np.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-6)


def test_normalization_equals_materialized_transform(rng):
    """Objective with in-kernel normalization == objective on pre-transformed
    data — the invariant the reference's NormalizationContext guarantees."""
    n, d = 30, 6
    x = rng.normal(2.0, 3.0, (n, d))
    labels = rng.integers(0, 2, n).astype(float)
    mean, std = x.mean(0), x.std(0)
    norm = compute_normalization(
        jnp.asarray(mean, jnp.float32), jnp.asarray(std, jnp.float32),
        jnp.asarray(np.abs(x).max(0), jnp.float32),
        NormalizationType.STANDARDIZATION)
    raw = make_dense_batch(x, labels)
    transformed = make_dense_batch((x - mean) / std, labels)
    w = jnp.asarray(rng.normal(0, 0.5, d), jnp.float32)
    obj_norm = GLMObjective(
        loss=losses.LOGISTIC, reg=RegularizationContext.l2(0.4), norm=norm)
    obj_plain = GLMObjective(
        loss=losses.LOGISTIC, reg=RegularizationContext.l2(0.4),
        norm=NormalizationContext.identity())
    v1, g1 = obj_norm.value_and_gradient(w, raw)
    v2, g2 = obj_plain.value_and_gradient(w, transformed)
    np.testing.assert_allclose(v1, v2, rtol=1e-4)
    np.testing.assert_allclose(g1, g2, rtol=1e-3, atol=1e-4)
    # HVP under normalization also matches.
    v = jnp.asarray(rng.normal(0, 1, d), jnp.float32)
    np.testing.assert_allclose(
        obj_norm.hessian_vector(w, v, raw),
        obj_plain.hessian_vector(w, v, transformed), rtol=1e-3, atol=1e-4)
    # Hessian diagonal with shifts (cross-term path).
    H = jax.hessian(lambda ww: obj_norm.value(ww, raw))(w)
    np.testing.assert_allclose(
        obj_norm.hessian_diagonal(w, raw), jnp.diagonal(H),
        rtol=1e-3, atol=1e-4)


def test_objective_jit_and_vmap(rng):
    batch, x = _random_problem(rng, n=16, d=5)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(0.1),
        norm=NormalizationContext.identity(),
    )
    f = jax.jit(obj.value_and_gradient)
    w = jnp.zeros(5)
    v, g = f(w, batch)
    assert np.isfinite(v)
    # vmap over a batch of coefficient vectors (random-effect pattern).
    ws = jnp.asarray(rng.normal(0, 0.3, (6, 5)), jnp.float32)
    vals = jax.vmap(lambda ww: obj.value(ww, batch))(ws)
    assert vals.shape == (6,)
