"""GRR layout + kernel tests (CPU: jnp plan execution + interpret kernel).

The GRR plan is validated semantically: executing the compiled plan must
reproduce the direct COO contraction exactly (same products, reordered
sums only), for random matrices across shapes, skews, spills, and hot
columns — plus the crossbar router invariants the advisor asked for.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.data.grr import (
    GrrPair,
    build_grr_direction,
    build_grr_pair,
    dense_hot_split,
)
from photon_ml_tpu.ops.crossbar import apply_route_numpy, route_tile


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def _coo(rng, nnz, L, S):
    idx = rng.integers(0, L, nnz)
    seg = rng.integers(0, S, nnz)
    val = rng.normal(0, 1, nnz).astype(np.float32)
    return idx, seg, val


def _direct(idx, seg, val, table, S):
    out = np.zeros(S, np.float64)
    np.add.at(out, seg, val.astype(np.float64) * table[idx])
    return out.astype(np.float32)


@pytest.mark.parametrize("nnz,L,S,cap", [
    (2000, 300, 150, None),       # single window both sides
    (5000, 40000, 5000, 4),       # multiple gather windows
    (5000, 5000, 40000, 8),       # multiple segment windows
    (30000, 70000, 70000, None),  # multiple both
    (64, 17000, 17, 4),           # nearly empty blocks + dummy ows
])
def test_direction_matches_direct(rng, nnz, L, S, cap):
    idx, seg, val = _coo(rng, nnz, L, S)
    d = build_grr_direction(idx, seg, val, L, S, cap=cap)
    table = rng.normal(0, 1, L).astype(np.float32)
    out = np.asarray(d.contract(jnp.asarray(table)))
    want = _direct(idx, seg, val, table, S)
    np.testing.assert_allclose(out, want, rtol=2e-5, atol=2e-4)


def test_direction_spill_overflow(rng):
    # One segment with far more entries in one window than cap → spill.
    L, S = 1000, 64
    idx = rng.integers(0, 128, 600)          # all in window 0
    seg = np.zeros(600, np.int64)            # all in segment 0
    val = rng.normal(0, 1, 600).astype(np.float32)
    d = build_grr_direction(idx, seg, val, L, S, cap=4)
    assert d.n_spill > 0
    table = rng.normal(0, 1, L).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(d.contract(jnp.asarray(table))),
        _direct(idx, seg, val, table, S), rtol=2e-5, atol=2e-4,
    )


def test_direction_duplicate_entries(rng):
    # Repeated (idx, seg) pairs must sum, not overwrite.
    idx = np.array([5, 5, 5, 7], np.int64)
    seg = np.array([1, 1, 2, 2], np.int64)
    val = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    d = build_grr_direction(idx, seg, val, 10, 4, cap=4)
    table = np.arange(10, dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(d.contract(jnp.asarray(table))),
        _direct(idx, seg, val, table, 4), rtol=1e-6,
    )


def test_direction_empty(rng):
    d = build_grr_direction(
        np.empty(0, np.int64), np.empty(0, np.int64),
        np.empty(0, np.float32), 100, 50,
    )
    out = np.asarray(d.contract(jnp.zeros(100)))
    assert out.shape == (50,)
    assert np.all(out == 0)


def test_squared_direction(rng):
    idx, seg, val = _coo(rng, 3000, 2000, 1500)
    d = build_grr_direction(idx, seg, val, 2000, 1500)
    table = rng.normal(0, 1, 2000).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(d.squared().contract(jnp.asarray(table))),
        _direct(idx, seg, val * val, table, 1500), rtol=2e-5, atol=2e-4,
    )


# -- hot split ---------------------------------------------------------------

def test_dense_hot_split(rng):
    n, k, dim = 512, 6, 300
    cols = rng.integers(1, dim, (n, k)).astype(np.int32)
    cols[:, 0] = 0                             # column 0 in every row → hot
    # make per-row cols unique to mirror SparseBatch's contract
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    hot_ids, x_hot, keep = dense_hot_split(cols, vals, dim, n)
    assert 0 in hot_ids
    assert x_hot.shape == (n, len(hot_ids))
    # hot entries are dropped from the sparse side
    assert not keep[:, 0].any()
    # dense + sparse together reproduce every nonzero exactly once
    total_dense = x_hot.sum()
    total_sparse = vals[keep].sum()
    np.testing.assert_allclose(total_dense + total_sparse,
                               vals[vals != 0].sum(), rtol=1e-4)


def test_pair_matches_dense(rng):
    n, k, dim = 700, 8, 900
    cols = np.stack([rng.choice(dim, k, replace=False) for _ in range(n)])
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    cols[:, 0] = 0                             # hot column
    pair = build_grr_pair(cols, vals, dim)

    x = np.zeros((n, dim), np.float64)
    np.add.at(x, (np.repeat(np.arange(n), k), cols.reshape(-1)),
              vals.reshape(-1).astype(np.float64))

    w = rng.normal(0, 1, dim).astype(np.float32)
    r = rng.normal(0, 1, n).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(pair.dot(jnp.asarray(w))), x @ w, rtol=2e-5, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(pair.t_dot(jnp.asarray(r))), x.T @ r, rtol=2e-5, atol=2e-4)
    # squared (Hessian diagonal side)
    np.testing.assert_allclose(
        np.asarray(pair.squared().dot(jnp.asarray(w))), (x * x) @ w,
        rtol=2e-5, atol=2e-4)


def test_pair_autodiff(rng):
    """jax.grad through the pair must equal the transposed contraction."""
    import jax

    n, k, dim = 200, 5, 150
    cols = np.stack([rng.choice(dim, k, replace=False) for _ in range(n)])
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    pair = build_grr_pair(cols, vals, dim)
    r = jnp.asarray(rng.normal(0, 1, n).astype(np.float32))

    def loss(w):
        return jnp.sum(pair.dot(w) * r)

    g = jax.grad(loss)(jnp.zeros(dim))
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(pair.t_dot(r)), rtol=2e-5, atol=2e-4)


# -- kernel (interpret mode) -------------------------------------------------

def test_kernel_interpret_matches_jnp(rng):
    from photon_ml_tpu.ops.grr_kernel import (
        grr_contract_jnp,
        grr_contract_kernel,
    )

    idx, seg, val = _coo(rng, 4000, 40000, 5000)
    d = build_grr_direction(idx, seg, val, 40000, 5000, cap=8,
                            dense_grid=False)
    table = jnp.asarray(rng.normal(0, 1, 40000).astype(np.float32))
    pad = d.n_gw * 16384 - d.table_len
    t = jnp.concatenate([table, jnp.zeros(pad, jnp.float32)])
    table_t = t.reshape(d.n_gw, 128, 128)
    out_j = grr_contract_jnp(table_t, d.g1, d.g2, d.g3, d.vals,
                             d.gw_of_st, d.ow_of_st, n_ow=d.n_ow, cap=d.cap)
    out_k = grr_contract_kernel(table_t, d.g1, d.g2, d.g3, d.vals,
                                d.gw_of_st, d.ow_of_st, d.first_of_ow,
                                n_ow=d.n_ow, cap=d.cap, interpret=True)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_j),
                               rtol=1e-5, atol=1e-5)


def test_dense_kernel_interpret_matches_jnp(rng):
    from photon_ml_tpu.ops.grr_kernel import (
        grr_contract_jnp_dense,
        grr_contract_kernel_dense,
    )

    idx, seg, val = _coo(rng, 40000, 40000, 5000)
    d = build_grr_direction(idx, seg, val, 40000, 5000, cap=8,
                            dense_grid=True)
    assert d.dense_grid
    table = jnp.asarray(rng.normal(0, 1, 40000).astype(np.float32))
    pad = d.n_gw * 16384 - d.table_len
    t = jnp.concatenate([table, jnp.zeros(pad, jnp.float32)])
    table_t = t.reshape(d.n_gw, 128, 128)
    out_j = grr_contract_jnp_dense(table_t, d.g1, d.g2, d.g3, d.vals,
                                   n_ow_p=d.n_ow_padded, cap=d.cap)
    out_k = grr_contract_kernel_dense(table_t, d.g1, d.g2, d.g3, d.vals,
                                      d.gw_of_st, n_ow_p=d.n_ow_padded,
                                      cap=d.cap, interpret=True)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_j),
                               rtol=1e-5, atol=1e-5)


def test_dense_grid_matches_legacy_layout(rng):
    """Same COO compiled both ways contracts identically."""
    idx, seg, val = _coo(rng, 30000, 70000, 70000)
    table = rng.normal(0, 1, 70000).astype(np.float32)
    want = _direct(idx, seg, val, table, 70000)
    for force in (True, False):
        d = build_grr_direction(idx, seg, val, 70000, 70000,
                                dense_grid=force)
        assert d.dense_grid == force
        np.testing.assert_allclose(
            np.asarray(d.contract(jnp.asarray(table))), want,
            rtol=2e-5, atol=2e-4)


# -- crossbar router (advisor findings) --------------------------------------

@pytest.mark.parametrize("native", [True, False])
def test_route_tile_random_permutations(rng, native, monkeypatch):
    if not native:
        monkeypatch.setenv("PHOTON_ML_TPU_NATIVE", "0")
        import photon_ml_tpu.native as nat
        monkeypatch.setattr(nat, "_lib", False)
    perm = rng.permutation(128 * 128).reshape(128, 128)
    g1, g2, g3 = route_tile(perm)
    x = rng.normal(0, 1, (128, 128)).astype(np.float32)
    out = apply_route_numpy(x, g1, g2, g3)
    want = np.empty_like(x)
    want.reshape(-1)[perm.reshape(-1)] = x.reshape(-1)
    np.testing.assert_array_equal(out, want)


def test_route_tile_identity_and_transpose(rng):
    iota = np.arange(128 * 128).reshape(128, 128)
    for perm in (iota, iota.T):
        g1, g2, g3 = route_tile(perm)
        x = rng.normal(0, 1, (128, 128)).astype(np.float32)
        out = apply_route_numpy(x, g1, g2, g3)
        want = np.empty_like(x)
        want.reshape(-1)[perm.reshape(-1)] = x.reshape(-1)
        np.testing.assert_array_equal(out, want)


def test_edge_color_native_rejects_bad_vertices(rng):
    """Out-of-range vertex ids must error, not corrupt memory."""
    from photon_ml_tpu.native import edge_color_native, native_available

    if not native_available():
        pytest.skip("native library unavailable")
    src = np.array([0, 1, 200, 3] * 32, np.int32)   # 200 >= n_left
    dst = np.array([0, 1, 2, 3] * 32, np.int32)
    with pytest.raises(ValueError):
        edge_color_native(src, dst, 128, 128, 128)


# -- objective integration ---------------------------------------------------

def test_objective_grr_matches_ell(rng):
    """Full GLM objective (value, grad, HVP, Hdiag) must agree between
    the GRR batch and the plain-ELL batch."""
    import jax

    from photon_ml_tpu.data.batch import make_sparse_batch
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.utils.synthetic import make_a1a_like

    rows, labels, _ = make_a1a_like(n=600, seed=3)
    dim = 123
    b_ell = make_sparse_batch(rows, dim, labels)
    b_grr = make_sparse_batch(rows, dim, labels, grr=True)
    assert b_grr.grr is not None
    obj = GLMObjective(
        loss=losses.LOGISTIC, reg=RegularizationContext.l2(0.5),
        norm=NormalizationContext.identity(),
    )
    w = jnp.asarray(rng.normal(0, 0.2, dim).astype(np.float32))
    v = jnp.asarray(rng.normal(0, 1, dim).astype(np.float32))

    v1, g1_ = obj.value_and_gradient(w, b_ell)
    v2, g2_ = obj.value_and_gradient(w, b_grr)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g1_), np.asarray(g2_),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(obj.hessian_vector(w, v, b_ell)),
        np.asarray(obj.hessian_vector(w, v, b_grr)), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(obj.hessian_diagonal(w, b_ell)),
        np.asarray(obj.hessian_diagonal(w, b_grr)), rtol=2e-4, atol=2e-4)
    # autodiff through the batch (bench's naive baseline path)
    ga = jax.grad(lambda w: obj.value(w, b_grr))(w)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(g1_),
                               rtol=2e-4, atol=2e-4)


def test_native_plan_matches_python_plan(rng):
    """The C++ plan builder (pml_grr_plan) and the numpy path choose
    ranks differently (scan vs sort order) but must produce plans whose
    contractions agree — and match the dense reference."""
    import jax.numpy as jnp

    import photon_ml_tpu.native as nat
    from photon_ml_tpu.data.grr import build_grr_pair

    if not nat.native_available():
        pytest.skip("native library unavailable")
    n, d, k = 700, 17000, 6
    block = d // k
    cols = np.minimum(
        (np.arange(k)[None, :] * block) + rng.integers(0, block, (n, k)),
        d - 1).astype(np.int32)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    vals[rng.random((n, k)) < 0.15] = 0.0   # real zero entries drop

    pair_native = build_grr_pair(cols, vals, d)
    saved = nat._lib
    nat._lib = None   # force the numpy path
    try:
        pair_python = build_grr_pair(cols, vals, d)
    finally:
        nat._lib = saved

    w = jnp.asarray(rng.normal(size=d), jnp.float32)
    r = jnp.asarray(rng.normal(size=n), jnp.float32)
    np.testing.assert_allclose(np.asarray(pair_native.dot(w)),
                               np.asarray(pair_python.dot(w)),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(pair_native.t_dot(r)),
                               np.asarray(pair_python.t_dot(r)),
                               rtol=2e-4, atol=2e-4)
    x = np.zeros((n, d), np.float32)
    np.add.at(x, (np.repeat(np.arange(n), k), cols.reshape(-1)),
              vals.reshape(-1))
    np.testing.assert_allclose(np.asarray(pair_native.dot(w)),
                               x @ np.asarray(w), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(pair_native.t_dot(r)),
                               x.T @ np.asarray(r), rtol=2e-3, atol=2e-3)


def test_bad_cap_rejected_both_paths(rng):
    from photon_ml_tpu.data.grr import build_grr_pair

    cols = rng.integers(0, 50, (20, 3)).astype(np.int32)
    vals = rng.normal(size=(20, 3)).astype(np.float32)
    with pytest.raises(ValueError, match="cap"):
        build_grr_pair(cols, vals, 50, cap=48)


def test_overflow_level_absorbs_spill(rng):
    """Two-level plan: heavy-tail spill recompiled at a larger cap; the
    overflow contraction must reproduce the single-level result and the
    dense reference."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.grr import build_grr_pair

    n, d, k = 600, 300, 6
    # Skewed columns: a few columns soak up most entries (below the
    # dense-hot threshold, above per-window cap) -> guaranteed spill.
    cols = np.where(
        rng.random((n, k)) < 0.5,
        rng.integers(0, 8, (n, k)),
        rng.integers(0, d, (n, k)),
    ).astype(np.int32)
    cols = np.sort(cols, axis=1)
    for j in range(1, k):
        bump = cols[:, j] <= cols[:, j - 1]
        cols[bump, j] = cols[bump, j - 1] + 1
    cols = np.minimum(cols, d - 1)
    vals = rng.normal(size=(n, k)).astype(np.float32)

    plain = build_grr_pair(cols, vals, d, hot_threshold=10**9,
                           overflow_threshold=10**9)
    two_level = build_grr_pair(cols, vals, d, hot_threshold=10**9,
                               overflow_threshold=1)
    assert (two_level.col_dir.overflow is not None
            or two_level.row_dir.overflow is not None), \
        "expected at least one direction to carry an overflow plan"

    w = jnp.asarray(rng.normal(size=d), jnp.float32)
    r = jnp.asarray(rng.normal(size=n), jnp.float32)
    np.testing.assert_allclose(np.asarray(two_level.dot(w)),
                               np.asarray(plain.dot(w)),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(two_level.t_dot(r)),
                               np.asarray(plain.t_dot(r)),
                               rtol=2e-4, atol=2e-4)
    # Hessian-diagonal path recurses into the overflow too.
    np.testing.assert_allclose(
        np.asarray(two_level.squared().t_dot(jnp.abs(r))),
        np.asarray(plain.squared().t_dot(jnp.abs(r))),
        rtol=2e-4, atol=2e-4)


def test_mid_hot_columns_split(rng):
    """Power-law columns: mega-hot → dense side, mid-hot → compact
    col_mid plan, tail → main plan; contraction exact throughout."""
    n, k, dim = 4096, 8, 2000
    # ~6 mega-hot columns (0..5 in most rows), a band of mid-hot
    # columns (6..29 frequently), and a uniform tail.
    cols = np.zeros((n, k), np.int64)
    cols[:, 0] = rng.integers(0, 6, n)                  # mega-hot
    cols[:, 1] = rng.integers(6, 30, n)                 # mid-hot band
    cols[:, 2:] = rng.integers(30, dim, (n, k - 2))
    # de-duplicate per row (resample collisions into distinct slots)
    for j in range(1, k):
        for _ in range(6):
            dup = (cols[:, j:j + 1] == cols[:, :j]).any(axis=1)
            if not dup.any():
                break
            lo = 6 if j == 1 else 30
            cols[dup, j] = rng.integers(lo, dim, int(dup.sum()))
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    pair = build_grr_pair(cols.astype(np.int32), vals, dim,
                          hot_threshold=500, mid_threshold=40)
    assert pair.hot_ids.shape[0] > 0          # mega-hot split happened
    assert pair.col_mid is not None           # mid plan exists
    assert pair.mid_ids.shape[0] > 0

    x = np.zeros((n, dim), np.float64)
    np.add.at(x, (np.repeat(np.arange(n), k), cols.reshape(-1)),
              vals.reshape(-1).astype(np.float64))
    w = rng.normal(0, 1, dim).astype(np.float32)
    r = rng.normal(0, 1, n).astype(np.float32)
    np.testing.assert_allclose(np.asarray(pair.dot(jnp.asarray(w))),
                               x @ w, rtol=2e-5, atol=3e-4)
    np.testing.assert_allclose(np.asarray(pair.t_dot(jnp.asarray(r))),
                               x.T @ r, rtol=2e-5, atol=3e-4)
    np.testing.assert_allclose(
        np.asarray(pair.squared().t_dot(jnp.asarray(r))),
        (x * x).T @ r, rtol=2e-5, atol=3e-4)


def test_max_hot_bytes_budget(rng):
    """The dense hot side respects its HBM byte budget."""
    n, k, dim = 2048, 4, 64
    cols = np.stack([rng.choice(dim, k, replace=False)
                     for _ in range(n)]).astype(np.int32)
    vals = np.ones((n, k), np.float32)
    # Without a budget nearly every column densifies (small-d regime);
    # with a tight budget H collapses to the allowance.
    free = build_grr_pair(cols, vals, dim)
    tight = build_grr_pair(cols, vals, dim, max_hot_bytes=4 * n * 3)
    assert free.hot_ids.shape[0] > 3
    assert tight.hot_ids.shape[0] <= 3
    w = rng.normal(0, 1, dim).astype(np.float32)
    np.testing.assert_allclose(np.asarray(tight.dot(jnp.asarray(w))),
                               np.asarray(free.dot(jnp.asarray(w))),
                               rtol=2e-5, atol=3e-4)


def test_overflow_chain_recurses(rng):
    """Power-law tails absorb through MULTIPLE overflow levels: the
    chain leaves less COO residual than a single level, and the
    contraction stays exact."""
    nnz, L, S = 120_000, 3000, 3000
    # Zipf-ish segments: heavy repeat groups spanning several levels.
    seg = (S * rng.random(nnz) ** 3.0).astype(np.int64)
    idx = rng.integers(0, L, nnz)
    val = rng.normal(0, 1, nnz).astype(np.float32)
    chain = build_grr_direction(idx, seg, val, L, S, cap=4,
                                overflow_threshold=500)
    shallow = build_grr_direction(idx, seg, val, L, S, cap=4,
                                  overflow_threshold=500,
                                  overflow_depth=1)

    def walk(d):
        depth, residual = 0, 0
        while d is not None:
            residual = int(np.count_nonzero(np.asarray(d.spill_val)))
            depth += 1
            d = d.overflow
        return depth, residual

    depth, residual = walk(chain)
    depth1, residual1 = walk(shallow)
    assert depth >= 3          # lvl1 + at least two overflow levels
    assert depth1 == 2
    assert residual < residual1   # deeper chain absorbs more
    table = rng.normal(0, 1, L).astype(np.float32)
    for d in (chain, shallow):
        np.testing.assert_allclose(
            np.asarray(d.contract(jnp.asarray(table))),
            _direct(idx, seg, val, table, S), rtol=2e-5, atol=5e-4)


def test_overflow_chain_depth_capped(rng):
    """A single mega-segment (each level absorbs only ~cap·n_gw
    entries) must terminate at the depth cap, not recurse unboundedly
    (review-confirmed RecursionError without the cap)."""
    nnz, L, S = 300_000, 100_000, 3000
    idx = rng.integers(0, L, nnz)
    seg = np.zeros(nnz, np.int64)
    val = rng.normal(0, 1, nnz).astype(np.float32)
    d = build_grr_direction(idx, seg, val, L, S, cap=4,
                            overflow_threshold=500)
    depth = 0
    while d is not None:
        depth += 1
        d = d.overflow
    assert depth <= 5          # lvl1 + at most overflow_depth=4 levels


def _powerlaw_ell(rng, n, k, dim, x0=3000.0):
    """Reciprocal (CTR-shaped) column popularity: P(col) ∝ 1/(col+x0),
    concentrating ~half the mass in table window 0 while spreading it
    across the window (the KDD shape PERF.md's range-split lever
    targets)."""
    u = rng.uniform(size=(n, k))
    cols = np.minimum(x0 * np.exp(u * np.log((dim + x0) / x0)) - x0,
                      dim - 1).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    return cols, vals


def test_plan_col_ranges_uniform_none(rng):
    from photon_ml_tpu.data.grr import _plan_col_ranges

    n, k, dim = 5000, 8, 70000
    cols = rng.integers(0, dim, (n, k)).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    assert _plan_col_ranges(cols, vals, dim) is None
    # single-window dims can never split
    assert _plan_col_ranges(cols % 9000, vals, 9000) is None
    # denser uniform data with an UNALIGNED dim must not split either:
    # the partial trailing window's occupancy is lower only because the
    # window is narrower (review finding — this exact shape used to
    # return a spurious 2-part split)
    n, k = 12000, 20
    cols = rng.integers(0, dim, (n, k)).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    assert _plan_col_ranges(cols, vals, dim) is None


def test_plan_col_ranges_powerlaw(rng):
    from photon_ml_tpu.data.grr import WIN, _plan_col_ranges

    n, k, dim = 12000, 20, 70000
    cols, vals = _powerlaw_ell(rng, n, k, dim)
    ranges = _plan_col_ranges(cols, vals, dim)
    assert ranges is not None and len(ranges) >= 2
    # window-aligned contiguous partition of [0, dim)
    assert ranges[0][0] == 0 and ranges[-1][1] == dim
    for (lo, hi, frac), (lo2, _, _) in zip(ranges, ranges[1:]):
        assert hi == lo2 and lo % WIN == 0
    assert abs(sum(f for _, _, f in ranges) - 1.0) < 1e-9


def test_col_range_split_matches_global(rng):
    """The split row plan must reproduce the global plan's contraction
    exactly (same products, reordered sums) and the direct reference."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.grr import GrrRangeSplit

    n, k, dim = 12000, 20, 70000
    cols, vals = _powerlaw_ell(rng, n, k, dim)
    pg = build_grr_pair(cols, vals, dim, col_range_split=False)
    ps = build_grr_pair(cols, vals, dim, col_range_split=True)
    assert isinstance(ps.row_dir, GrrRangeSplit)
    assert not isinstance(pg.row_dir, GrrRangeSplit)

    w = rng.normal(0, 1, dim).astype(np.float32)
    a = np.asarray(pg.dot(jnp.asarray(w)))
    b = np.asarray(ps.dot(jnp.asarray(w)))
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)
    direct = np.zeros(n, np.float64)
    np.add.at(direct, np.repeat(np.arange(n), k),
              (vals.astype(np.float64) * w[cols]).reshape(-1))
    np.testing.assert_allclose(b, direct, rtol=2e-3, atol=2e-3)
    r = rng.normal(0, 1, n).astype(np.float32)
    np.testing.assert_allclose(np.asarray(pg.t_dot(jnp.asarray(r))),
                               np.asarray(ps.t_dot(jnp.asarray(r))),
                               rtol=2e-4, atol=2e-4)
    # squared() (hessian-diagonal path) survives the split
    np.testing.assert_allclose(
        np.asarray(pg.squared().dot(jnp.asarray(w))),
        np.asarray(ps.squared().dot(jnp.asarray(w))),
        rtol=2e-4, atol=2e-4)


def test_col_range_split_reduces_spill(rng):
    """On power-law columns the per-range capacities must hold in the
    level-1 kernel what the single global cap pushed to overflow/COO
    (round-4 verdict item #1's 'done' criterion)."""
    n, k, dim = 12000, 20, 70000
    cols, vals = _powerlaw_ell(rng, n, k, dim)
    sg = build_grr_pair(
        cols, vals, dim, col_range_split=False).row_dir.plan_stats()
    ss = build_grr_pair(
        cols, vals, dim, col_range_split=True).row_dir.plan_stats()
    assert ss["spill_frac"] < sg["spill_frac"] / 3
    assert ss["coo_frac"] < 0.01
    assert len(set(ss["cap"])) >= 2   # ranges actually chose own caps


def test_idx_range_native_matches_numpy(rng):
    """The C++ builder's in-stream range filter must agree with the
    numpy fallback's filtered-COO build."""
    import jax.numpy as jnp

    import photon_ml_tpu.native as nat
    from photon_ml_tpu.data.grr import WIN, _build_direction_ell

    if not nat.native_available():
        pytest.skip("native library unavailable")
    n, k, dim = 3000, 10, 50000
    cols, vals = _powerlaw_ell(rng, n, k, dim, x0=2000.0)
    vals[rng.random((n, k)) < 0.1] = 0.0
    lo, hi = WIN, 3 * WIN
    d_native = _build_direction_ell(cols, vals, 0, dim, n, None, True,
                                    None, idx_range=(lo, hi))
    saved = nat._lib
    nat._lib = None
    try:
        d_numpy = _build_direction_ell(cols, vals, 0, dim, n, None, True,
                                       None, idx_range=(lo, hi))
    finally:
        nat._lib = saved
    assert d_native.table_len == hi - lo == d_numpy.table_len
    w = rng.normal(0, 1, dim).astype(np.float32)
    out_n = np.asarray(d_native.contract(jnp.asarray(w[lo:hi])))
    out_p = np.asarray(d_numpy.contract(jnp.asarray(w[lo:hi])))
    np.testing.assert_allclose(out_n, out_p, rtol=2e-4, atol=2e-4)
    keep = (cols >= lo) & (cols < hi)
    direct = np.zeros(n, np.float64)
    np.add.at(direct, np.repeat(np.arange(n), k),
              (np.where(keep, vals, 0).astype(np.float64)
               * w[np.minimum(cols, dim - 1)]).reshape(-1))
    np.testing.assert_allclose(out_n, direct, rtol=2e-3, atol=2e-3)


def test_spill_warning_rate_limited(caplog):
    """Satellite (round 8): inside a plan build the per-direction "GRR
    spill fraction" warning aggregates into ONE count/min/max/mean
    summary (MULTICHIP_r05's tail drowned the dryrun in ~20 identical
    lines); outside any build scope (ISSUE 16 satellite) a flagged
    burst dedupes into a time-windowed summary instead of one raw line
    per call."""
    import logging

    from photon_ml_tpu.data.grr import _spill_warnings

    with caplog.at_level(logging.WARNING, logger="photon_ml_tpu.data.grr"):
        caplog.clear()
        _spill_warnings.note(1, 100)            # stale unscoped clean
        with _spill_warnings:                   # build: discarded on
            # scope entry — must NOT inflate this scope's denominator
            for _ in range(20):
                _spill_warnings.note(20, 100)   # 20% on the XLA path
            _spill_warnings.note(1, 100)        # under threshold
            assert not caplog.records           # silent while collecting
        assert len(caplog.records) == 1
        msg = caplog.records[0].getMessage()
        assert "20 of 21 direction builds" in msg
        assert ("min 20.0%" in msg and "max 20.0%" in msg
                and "mean 20.0%" in msg)

        caplog.clear()
        with _spill_warnings:                   # clean builds: no line
            _spill_warnings.note(0, 100)
        assert not caplog.records

        caplog.clear()
        _spill_warnings._last_emit = None       # fresh dedupe window
        _spill_warnings.note(20, 100)           # outside a build scope
        assert len(caplog.records) == 1         # first one is immediate
        assert "1 of 1 direction builds" in \
            caplog.records[0].getMessage()
        for _ in range(10):                     # burst inside the window
            _spill_warnings.note(30, 100)
        assert len(caplog.records) == 1         # ...buffers silently
        _spill_warnings._last_emit = -1e9       # window elapsed
        _spill_warnings.note(40, 100)
        assert len(caplog.records) == 2         # ONE summary for the burst
        msg = caplog.records[1].getMessage()
        assert "11 of 11 direction builds" in msg
        assert "min 30.0%" in msg and "max 40.0%" in msg


def test_spill_warning_unscoped_burst_flushed_by_scope(caplog):
    """An unscoped buffered burst is flushed (as its own summary) when
    a build scope opens, so the scope's summary counts only its own
    direction builds."""
    import logging

    from photon_ml_tpu.data.grr import _spill_warnings

    with caplog.at_level(logging.WARNING, logger="photon_ml_tpu.data.grr"):
        caplog.clear()
        _spill_warnings._last_emit = None
        _spill_warnings.note(20, 100)           # immediate (1 of 1)
        _spill_warnings.note(25, 100)           # buffered in the window
        assert len(caplog.records) == 1
        with _spill_warnings:
            assert len(caplog.records) == 2     # burst flushed at enter
            assert "1 of 1 direction builds" in \
                caplog.records[1].getMessage()
            _spill_warnings.note(30, 100)
        assert len(caplog.records) == 3
        assert "1 of 1 direction builds" in \
            caplog.records[2].getMessage()


def test_spill_warning_aggregates_across_sharded_builds(caplog):
    """Satellite (round 9): a multi-build operation — several plan
    builds inside one ``collect_spill_warnings`` scope, the shape of
    ``build_chunked_batch``/``shard_sparse_batch`` — emits ONE summary
    for the whole sharded build, not one line per sub-plan (the
    MULTICHIP_r05 tail printed 15+)."""
    import logging

    from photon_ml_tpu.data.grr import (
        _spill_warnings,
        collect_spill_warnings,
    )

    with caplog.at_level(logging.WARNING, logger="photon_ml_tpu.data.grr"):
        caplog.clear()
        with collect_spill_warnings():
            for _ in range(3):            # three sibling plan builds
                with _spill_warnings:     # each with its own scope
                    for _ in range(5):    # five direction builds each
                        _spill_warnings.note(20, 100)
            assert not caplog.records     # silent until outermost exit
        assert len(caplog.records) == 1
        assert "15 of 15 direction builds" in \
            caplog.records[0].getMessage()


def test_chunked_grr_build_one_spill_summary(rng, caplog):
    """The real path: a GRR-layout chunked build (per-chunk sub-plans
    through build_sharded_grr_pairs) logs at most one spill summary."""
    import logging

    from photon_ml_tpu.data.chunked_batch import build_chunked_batch
    from photon_ml_tpu.data.sparse_rows import SparseRows

    n, d, k = 2048, 4000, 6
    x0 = d / 14.0
    u = rng.uniform(size=(n, k))
    cols = np.minimum(x0 * np.exp(u * np.log((d + x0) / x0)) - x0,
                      d - 1).astype(np.int64)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    rows = SparseRows.from_flat(np.arange(n + 1, dtype=np.int64) * k,
                                cols.reshape(-1), vals.reshape(-1))
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    with caplog.at_level(logging.WARNING,
                         logger="photon_ml_tpu.data.grr"):
        caplog.clear()
        build_chunked_batch(rows, d, labels, n_chunks=4, layout="grr",
                            row_capacity=k)
        spill_lines = [r for r in caplog.records
                       if "spill fraction" in r.getMessage()]
        assert len(spill_lines) <= 1
