"""Reliability tier (ISSUE 9): checkpoint/resume, the fault matrix,
bounded-retry I/O, and sink hardening.

The contracts under test:

- **Checkpoint/resume**: the state-tree codec round-trips; CD-level
  snapshots restore (including mid-sweep position and the corrupt-
  newest-falls-back-to-previous rule); the streaming solvers resume
  mid-solve BITWISE (the continuation is the run the kill
  interrupted); streamed-RE retirement state survives a resume.
- **Fault matrix**: every injected fault — corrupt chunk, deleted
  chunk, slow read, transient/persistent read errors, ENOSPC on spill,
  prefetcher/sink thread death, device_put failure, wedged pipeline —
  ends in a bounded retry, a documented degradation, or ONE actionable
  error, never a hang or a torn output; the ``store.retries`` /
  ``store.gave_up`` / ``reliability.*`` telemetry counters are pinned.
- **Sinks**: a failed write can never publish a torn container.
- **Report**: a stitched (kill + resume, append-mode) run log
  reconciles segment by segment.
"""

from __future__ import annotations

import contextlib
import glob
import json
import os
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu import telemetry
from photon_ml_tpu.data.batch import make_dense_batch
from photon_ml_tpu.data.chunk_store import (
    ChunkStoreSpillError,
    probe_spill_dir,
)
from photon_ml_tpu.data.chunked_batch import build_chunked_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.game.coordinates import FixedEffectCoordinate
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim import OptimizationProblem, OptimizerConfig
from photon_ml_tpu.optim.streaming import (
    ChunkPrefetcher,
    ChunkedGLMObjective,
    streaming_lbfgs_solve,
    streaming_lbfgs_solve_swept,
    streaming_tron_solve,
)
from photon_ml_tpu.reliability import checkpoint as ckpt
from photon_ml_tpu.reliability import faults
from photon_ml_tpu.reliability import retry as retry_mod
from photon_ml_tpu.reliability.checkpoint import RunCheckpointer
from photon_ml_tpu.reliability.faults import Fault, FaultInjector


@pytest.fixture
def rng():
    return np.random.default_rng(77)


@contextlib.contextmanager
def metrics_session():
    t = telemetry.start("metrics")
    try:
        yield t
    finally:
        t.close()


def _counters(t):
    return t.summary()["counters"]


# ---------------------------------------------------------------------------
# State-tree codec + RunCheckpointer units
# ---------------------------------------------------------------------------


def test_tree_codec_roundtrip():
    tree = {
        "w": np.arange(6, dtype=np.float32).reshape(2, 3),
        "jax": jnp.ones((4,)),
        "nested": {"lists": [1, 2.5, "s", None, True,
                             np.zeros(2, bool)]},
        "scalar": np.float32(3.5),
        "empty": {},
    }
    meta, arrays = ckpt.flatten_tree(tree)
    json.dumps(meta)   # the manifest must be pure JSON
    back = ckpt.unflatten_tree(meta, arrays)
    np.testing.assert_array_equal(back["w"], tree["w"])
    np.testing.assert_array_equal(back["jax"], np.ones(4))
    assert back["nested"]["lists"][:5] == [1, 2.5, "s", None, True]
    np.testing.assert_array_equal(back["nested"]["lists"][5],
                                  np.zeros(2, bool))
    assert float(back["scalar"]) == 3.5 and back["empty"] == {}


def test_checkpointer_cd_roundtrip_partial_and_corrupt_fallback(tmp_path):
    ck = RunCheckpointer(str(tmp_path), every_solver_iters=1)
    coefs = {"a": jnp.arange(4, dtype=jnp.float32),
             "re": [jnp.ones((2, 3)), jnp.zeros((1, 3))]}
    scores = {"a": jnp.ones(5), "__cd_total__": jnp.full(5, 2.0)}
    ck.save_cd(1, coefs, scores, re_state={"re": {"x": np.arange(3)}},
               extra={"prev_values": {"a": 1.5}})
    st = ck.load_latest_cd()
    assert (st["iteration"], st["coord_pos"]) == (1, 0)
    np.testing.assert_array_equal(st["coefs"]["a"], [0, 1, 2, 3])
    assert len(st["coefs"]["re"]) == 2
    np.testing.assert_array_equal(st["scores"]["__cd_total__"],
                                  np.full(5, 2.0))
    np.testing.assert_array_equal(st["re_state"]["re"]["x"],
                                  np.arange(3))
    assert st["extra"]["prev_values"] == {"a": 1.5}

    # A partial (mid-sweep) snapshot is more advanced than its own
    # sweep boundary and wins.
    ck.save_cd_partial(1, 2, coefs, scores)
    st = ck.load_latest_cd()
    assert (st["iteration"], st["coord_pos"]) == (1, 2)

    # A sweep-boundary save supersedes (and purges) the partial.
    ck.save_cd(2, coefs, scores)
    assert not os.path.exists(tmp_path / "cd_partial.npz")
    st = ck.load_latest_cd()
    assert (st["iteration"], st["coord_pos"]) == (2, 0)

    # Corrupt newest snapshot degrades to the previous good one — one
    # interval lost, never the run.
    with open(tmp_path / "cd_iter_2.npz", "wb") as f:
        f.write(b"garbage")
    st = ck.load_latest_cd()
    assert (st["iteration"], st["coord_pos"]) == (1, 0)


def test_checkpointer_utils_compat(tmp_path):
    """The new CD snapshot format stays readable by the legacy
    ``utils.checkpoint`` loader (pointer is a plain int; reserved keys
    are skipped by its parser)."""
    from photon_ml_tpu.utils.checkpoint import load_latest_checkpoint

    ck = RunCheckpointer(str(tmp_path))
    ck.save_cd(3, {"a": jnp.arange(2, dtype=jnp.float32)},
               {"a": jnp.ones(4)}, re_state={"z": np.ones(2)})
    it, coefs, scores = load_latest_checkpoint(str(tmp_path))
    assert it == 3
    np.testing.assert_array_equal(coefs["a"], [0, 1])
    np.testing.assert_array_equal(scores["a"], np.ones(4))


def test_solver_checkpoint_cadence_scope_and_clear(tmp_path):
    ck = RunCheckpointer(str(tmp_path), every_solver_iters=2,
                         resume=True)
    with ck.scope("it1", "coord"):
        label = ck.solver_label("lbfgs")
        assert label == "it1/coord/lbfgs"
        assert not ck.maybe_save_solver(label, 1, {"w": np.ones(2)})
        assert ck.maybe_save_solver(label, 2, {"w": np.ones(2)})
        st = ck.load_solver(label)
        assert st["it"] == 2
        # Foreign scope cannot adopt this state.
        assert ck.load_solver("it2/coord/lbfgs") is None
        ck.clear_solver(label)
        assert ck.load_solver(label) is None
    # A sweep-boundary save purges any remaining solver files.
    ck.maybe_save_solver("it1/x/lbfgs", 2, {"w": np.zeros(1)})
    ck.save_cd(1, {}, {})
    assert glob.glob(str(tmp_path / "solver_*.npz")) == []


def test_stage_roundtrip(tmp_path):
    ck = RunCheckpointer(str(tmp_path))
    ck.save_stage("swept", {"W": np.ones((2, 3)), "sweep": 1,
                            "lams": [1.0, 0.1]})
    st = ck.load_stage("swept")
    assert st["sweep"] == 1 and st["lams"] == [1.0, 0.1]
    assert ck.load_stage("other") is None
    ck.clear_stage("swept")
    assert ck.load_stage("swept") is None


# ---------------------------------------------------------------------------
# Mid-solve resume parity (streaming solvers)
# ---------------------------------------------------------------------------


class _Interrupt(Exception):
    pass


def _quadratic(rng, n=300, d=10):
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) + 0.1 * rng.normal(size=n)).astype(
        np.float32)

    def vg(w):
        w = jnp.asarray(w, jnp.float32)
        r = X @ w - y
        return 0.5 * jnp.mean(r * r), X.T @ r / n

    def vgs(W):
        W = jnp.asarray(W, jnp.float32)
        R = W @ X.T - y
        return 0.5 * jnp.mean(R * R, axis=-1), R @ X / n

    def vs(W):
        W = jnp.asarray(W, jnp.float32)
        R = W @ X.T - y
        return 0.5 * jnp.mean(R * R, axis=-1)

    return d, vg, vgs, vs


def _flaky(fn, fail_after: int):
    calls = {"n": 0}

    def wrapped(*a):
        calls["n"] += 1
        if calls["n"] > fail_after:
            raise _Interrupt()
        return fn(*a)

    return wrapped


def test_streaming_solver_mid_solve_resume_is_bitwise(rng, tmp_path):
    d, vg, _, _ = _quadratic(rng)
    cfg = OptimizerConfig(max_iters=40, tolerance=1e-9)
    ref = streaming_lbfgs_solve(vg, jnp.zeros(d), cfg, label="q")
    ck = RunCheckpointer(str(tmp_path), every_solver_iters=1,
                         resume=True)
    with ckpt.session(ck), ck.scope("it1", "q"):
        with pytest.raises(_Interrupt):
            streaming_lbfgs_solve(_flaky(vg, 6), jnp.zeros(d), cfg,
                                  label="q")
        assert glob.glob(str(tmp_path / "solver_*.npz"))
        res = streaming_lbfgs_solve(vg, jnp.zeros(d), cfg, label="q")
    np.testing.assert_array_equal(np.asarray(res.w), np.asarray(ref.w))
    assert int(res.iterations) == int(ref.iterations)
    # The solver state file is cleared once the solve completes.
    assert glob.glob(str(tmp_path / "solver_*.npz")) == []


def test_streaming_swept_solver_mid_solve_resume_is_bitwise(rng, tmp_path):
    d, _, vgs, vs = _quadratic(rng)
    cfg = OptimizerConfig(max_iters=40, tolerance=1e-9)
    W0 = jnp.zeros((3, d))
    ref = streaming_lbfgs_solve_swept(vgs, vs, W0, cfg, label="s")
    ck = RunCheckpointer(str(tmp_path), every_solver_iters=1,
                         resume=True)
    with ckpt.session(ck), ck.scope("sweep1"):
        with pytest.raises(_Interrupt):
            streaming_lbfgs_solve_swept(_flaky(vgs, 4), vs, W0, cfg,
                                        label="s")
        res = streaming_lbfgs_solve_swept(vgs, vs, W0, cfg, label="s")
    np.testing.assert_array_equal(np.asarray(res.w), np.asarray(ref.w))
    np.testing.assert_array_equal(np.asarray(res.iterations),
                                  np.asarray(ref.iterations))


def test_resumed_solver_odometer_counts_resume_not_solve(rng, tmp_path):
    """A resumed solve must NOT claim the initial fused evaluation it
    never streamed (the report's sweep-odometer identity)."""
    d, vg, _, _ = _quadratic(rng)
    cfg = OptimizerConfig(max_iters=40, tolerance=1e-9)
    ck = RunCheckpointer(str(tmp_path), every_solver_iters=1,
                         resume=True)
    with ckpt.session(ck), ck.scope("it1", "q"):
        with pytest.raises(_Interrupt):
            streaming_lbfgs_solve(_flaky(vg, 6), jnp.zeros(d), cfg,
                                  label="q")
        with metrics_session() as t:
            streaming_lbfgs_solve(vg, jnp.zeros(d), cfg, label="q")
        c = _counters(t)
    assert c.get("solver.resumed_solves") == 1
    assert "solver.streamed_solves" not in c


def _quadratic_newton(rng, n=300, d=10):
    """Least-squares quadratic with exact HVP / Hessian diagonal for
    the TRON resume tests; per-column scales make CG take several
    steps per outer iteration, so a small ``fail_after`` lands the
    interrupt INSIDE the inner loop."""
    X = (rng.normal(size=(n, d)).astype(np.float32)
         * np.logspace(0, -2, d).astype(np.float32))
    y = (X @ rng.normal(size=d) + 0.1 * rng.normal(size=n)).astype(
        np.float32)

    def vg(w):
        w = jnp.asarray(w, jnp.float32)
        r = X @ w - y
        return 0.5 * jnp.mean(r * r), X.T @ r / n

    def hvp(w, v):
        return X.T @ (X @ jnp.asarray(v, jnp.float32)) / n

    def diag(w):
        return jnp.asarray((X * X).mean(axis=0))

    return d, vg, hvp, diag


def test_streaming_tron_mid_cg_resume_is_bitwise(rng, tmp_path, caplog):
    """Kill inside Steihaug-CG (the HVP callable raises mid-inner-loop,
    the stand-in for a SIGKILL between chunk passes); the resume
    re-enters at the exact HVP boundary — outer point, radius, frozen
    preconditioner, AND the CG basis vectors — and reproduces the
    uninterrupted fit bitwise.  The resumed solve's odometer counts the
    resume, not a fresh solve: neither the initial fused evaluation nor
    the preconditioner pass is repaid (ISSUE 17)."""
    d, vg, hvp, diag = _quadratic_newton(rng)
    cfg = OptimizerConfig(max_iters=40, tolerance=1e-9)
    ref = streaming_tron_solve(vg, hvp, jnp.zeros(d), cfg,
                               hessian_diag=diag, label="q")
    ck = RunCheckpointer(str(tmp_path), every_solver_iters=1,
                         resume=True)
    caplog.set_level("INFO", logger="photon_ml_tpu.optim.streaming")
    with ckpt.session(ck), ck.scope("it1", "q"):
        with pytest.raises(_Interrupt):
            streaming_tron_solve(vg, _flaky(hvp, 3), jnp.zeros(d), cfg,
                                 hessian_diag=diag, label="q")
        assert glob.glob(str(tmp_path / "solver_*.npz"))
        with metrics_session() as t:
            res = streaming_tron_solve(vg, hvp, jnp.zeros(d), cfg,
                                       hessian_diag=diag, label="q")
        c = _counters(t)
    # The interrupt landed mid-CG and the resume says so.
    assert "mid-CG" in caplog.text
    np.testing.assert_array_equal(np.asarray(res.w), np.asarray(ref.w))
    assert int(res.iterations) == int(ref.iterations)
    assert c.get("solver.resumed_solves") == 1
    assert "solver.streamed_solves" not in c
    assert "solver.aux_sweeps" not in c      # preconditioner not repaid
    # The solver state file is cleared once the solve completes.
    assert glob.glob(str(tmp_path / "solver_*.npz")) == []


# ---------------------------------------------------------------------------
# CD-level resume: mid-sweep position
# ---------------------------------------------------------------------------


def _two_coordinate_cd(rng, n=400):
    x1 = rng.normal(size=(n, 5)).astype(np.float32)
    x2 = rng.normal(size=(n, 3)).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)

    def coord(name, x):
        batch = make_dense_batch(x, labels)
        return FixedEffectCoordinate(
            name=name, batch=batch,
            problem=OptimizationProblem(
                objective=GLMObjective(
                    loss=losses.LOGISTIC,
                    reg=RegularizationContext.l2(0.5),
                    norm=NormalizationContext.identity()),
                config=OptimizerConfig(max_iters=30)))

    return {"a": coord("a", x1), "b": coord("b", x2)}


class _FailingCoordinate:
    """Wraps a coordinate; ``train`` raises at a planned call (the
    in-process stand-in for a SIGKILL mid-sweep)."""

    def __init__(self, inner, fail_at_call: int):
        self._inner = inner
        self._fail_at = fail_at_call
        self._calls = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def train(self, *a, **kw):
        self._calls += 1
        if self._calls == self._fail_at:
            raise _Interrupt()
        return self._inner.train(*a, **kw)


def test_cd_mid_sweep_resume_parity(tmp_path):
    """Kill during sweep 2's SECOND coordinate; resume completes it
    and matches the uninterrupted run (restored scores make offsets
    bitwise, so the tolerance is float-tight)."""
    # Each build must see the SAME dataset: fresh seeded generators.
    coords_ref = _two_coordinate_cd(np.random.default_rng(5))
    ref = run_coordinate_descent(coords_ref, ["a", "b"], 3)

    ck_dir = str(tmp_path / "ck")
    coords = _two_coordinate_cd(np.random.default_rng(5))
    # every_solver_iters > 0 enables coordinate-boundary partials.
    ck = RunCheckpointer(ck_dir, every_solver_iters=1)
    coords_failing = dict(coords)
    # "b" trains once per sweep; its 2nd call is sweep 2's "b".
    coords_failing["b"] = _FailingCoordinate(coords["b"], 2)
    with pytest.raises(_Interrupt):
        run_coordinate_descent(coords_failing, ["a", "b"], 3,
                               checkpointer=ck)
    st = ck.load_latest_cd()
    assert (st["iteration"], st["coord_pos"]) == (1, 1)

    coords2 = _two_coordinate_cd(np.random.default_rng(5))
    res = run_coordinate_descent(coords2, ["a", "b"], 3,
                                 checkpointer=RunCheckpointer(
                                     ck_dir, every_solver_iters=1,
                                     resume=True),
                                 resume=True)
    for name in ("a", "b"):
        np.testing.assert_allclose(np.asarray(res.coefficients[name]),
                                   np.asarray(ref.coefficients[name]),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(res.total_scores),
                               np.asarray(ref.total_scores),
                               rtol=1e-5, atol=1e-5)
    assert len(res.history) == 3
    # The resumed (partial) sweep's history entry carries BOTH
    # coordinates: the pre-kill one rode in the partial snapshot.
    assert set(res.history[1]) == {"a", "b"}
    # History is uniformly typed across restored and fresh sweeps
    # (review finding): every entry is the plain-dict diagnostic form,
    # matching an uninterrupted run's record.
    for result in (res, ref):
        for entry in result.history:
            assert all(isinstance(d, dict) for d in entry.values())


# ---------------------------------------------------------------------------
# Streamed-RE runtime state
# ---------------------------------------------------------------------------


def test_streamed_re_runtime_state_roundtrip(rng, tmp_path):
    from photon_ml_tpu.game.coordinates import (
        build_streamed_random_effect_coordinate,
    )
    from photon_ml_tpu.game.dataset import GameDataset

    n = 600
    ids = rng.integers(0, 40, n)
    x = rng.normal(size=(n, 3)).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    ds = GameDataset(labels=labels, features={"re": x},
                     entity_ids={"user": ids}, feature_dims={"re": 3})
    obj = GLMObjective(loss=losses.LOGISTIC,
                       reg=RegularizationContext.l2(1.0),
                       norm=NormalizationContext.identity())

    def build():
        return build_streamed_random_effect_coordinate(
            "user", ds, "re", obj, spill_dir=str(tmp_path / "spill"),
            chunk_entities=8, config=OptimizerConfig(max_iters=25),
            retirement=True)

    offsets = rng.normal(0, 0.1, n).astype(np.float32)
    c1 = build()
    blocks1, _ = c1.train(jnp.asarray(offsets))
    c1.retire_converged()
    retired = c1.entities_retired
    state = c1.runtime_state()

    # A fresh coordinate (fresh process stand-in) restores the state:
    # the returned blocks satisfy train's warm-start identity check, so
    # retirement bookkeeping survives and the cached scores serve.
    c2 = build()
    blocks2, cached = c2.restore_runtime_state(state)
    assert c2.entities_retired == retired
    np.testing.assert_array_equal(np.asarray(c2.score(blocks2)),
                                  np.asarray(cached))
    b_next_1, diag1 = c1.train(jnp.asarray(offsets), warm_start=blocks1)
    b_next_2, diag2 = c2.train(jnp.asarray(offsets), warm_start=blocks2)
    assert diag2["entities_retired"] == diag1["entities_retired"]
    assert diag2["entities_solved"] == diag1["entities_solved"]
    for w1, w2 in zip(b_next_1, b_next_2):
        np.testing.assert_allclose(np.asarray(w1), np.asarray(w2),
                                   rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# The fault matrix
# ---------------------------------------------------------------------------


def _sparse_problem(rng, n=1200, d=300, k=6):
    cols = np.stack([np.sort(rng.choice(d, k, replace=False))
                     for _ in range(n)]).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    indptr = np.arange(n + 1, dtype=np.int64) * k
    rows = SparseRows.from_flat(indptr, cols.reshape(-1).astype(np.int64),
                                vals.reshape(-1))
    return rows, labels, d


def _spilled_objective(rng, spill_dir, n_chunks=6, window=2):
    rows, labels, d = _sparse_problem(rng)
    cb = build_chunked_batch(rows, d, labels, n_chunks=n_chunks,
                             layout="ell", spill_dir=spill_dir,
                             host_max_resident=window)
    obj = GLMObjective(loss=losses.LOGISTIC,
                       reg=RegularizationContext.l2(0.7),
                       norm=NormalizationContext.identity())
    return cb, ChunkedGLMObjective(obj, cb, max_resident=0,
                                   prefetch_depth=2), d


@pytest.mark.parametrize("kind,expect_counter", [
    ("corrupt_file", "store.rebuilds"),
    ("delete_file", "store.rebuilds"),
    ("slow", "store.loads"),
])
def test_fault_matrix_degradations_preserve_the_run(rng, tmp_path, kind,
                                                    expect_counter):
    """Corrupt chunk / deleted chunk / slow read: the sweep completes
    with the SAME value (rebuild-from-lineage or patience), and the
    telemetry counters say what happened."""
    cb, cobj, d = _spilled_objective(rng, str(tmp_path / "spill"))
    w = jnp.asarray(rng.normal(0, 0.2, d), jnp.float32)
    clean = float(cobj.value(w))

    inj = FaultInjector([Fault(site="store.load", kind=kind, at=1,
                               delay_s=0.2)])
    with faults.injected(inj), metrics_session() as t:
        val = float(cobj.value(w))
    c = _counters(t)
    assert val == pytest.approx(clean, rel=1e-6)
    assert c.get("reliability.faults_injected", 0) >= 1
    assert c.get(expect_counter, 0) >= 1
    if kind in ("corrupt_file", "delete_file"):
        # The rebuild re-spilled a good file: the NEXT sweep is clean.
        with metrics_session() as t2:
            assert float(cobj.value(w)) == pytest.approx(clean,
                                                         rel=1e-6)
        assert _counters(t2).get("store.rebuilds", 0) == 0


def test_fault_matrix_transient_read_error_retries(rng, tmp_path,
                                                   monkeypatch):
    monkeypatch.setattr(retry_mod, "IO_BASE_DELAY_S", 0.01)
    cb, cobj, d = _spilled_objective(rng, str(tmp_path / "spill"))
    w = jnp.asarray(rng.normal(0, 0.2, d), jnp.float32)
    clean = float(cobj.value(w))
    inj = FaultInjector([Fault(site="store.load", kind="io_error",
                               at=1, count=1)])
    with faults.injected(inj), metrics_session() as t:
        val = float(cobj.value(w))
    c = _counters(t)
    assert val == pytest.approx(clean, rel=1e-6)
    assert c.get("store.retries", 0) == 1      # one backoff retry won
    assert c.get("store.gave_up", 0) == 0
    assert c.get("store.rebuilds", 0) == 0     # never reached lineage


def test_fault_matrix_persistent_read_error_gives_up_then_rebuilds(
        rng, tmp_path, monkeypatch):
    monkeypatch.setattr(retry_mod, "IO_BASE_DELAY_S", 0.01)
    cb, cobj, d = _spilled_objective(rng, str(tmp_path / "spill"))
    w = jnp.asarray(rng.normal(0, 0.2, d), jnp.float32)
    clean = float(cobj.value(w))
    inj = FaultInjector([Fault(site="store.load", kind="io_error",
                               at=1, count=3)])   # the whole budget
    with faults.injected(inj), metrics_session() as t:
        val = float(cobj.value(w))
    c = _counters(t)
    assert val == pytest.approx(clean, rel=1e-6)
    assert c.get("store.retries", 0) == 2      # attempts 2 and 3
    assert c.get("store.gave_up", 0) == 1      # budget exhausted once
    assert c.get("store.rebuilds", 0) == 1     # lineage took over


def test_fault_matrix_enospc_is_one_actionable_error(rng, tmp_path):
    rows, labels, d = _sparse_problem(rng)
    inj = FaultInjector([Fault(site="store.spill", kind="enospc",
                               at=0, count=100)])
    spill = str(tmp_path / "spill")
    with faults.injected(inj), metrics_session() as t:
        with pytest.raises(ChunkStoreSpillError) as ei:
            build_chunked_batch(rows, d, labels, n_chunks=6,
                                layout="ell", spill_dir=spill)
    msg = str(ei.value)
    assert spill in msg and "MB" in msg and "out of space" in msg
    assert ei.value.bytes_needed > 0
    assert _counters(t).get("reliability.actionable_errors", 0) == 1


@pytest.mark.parametrize("site", ["prefetch.load", "prefetch.place"])
def test_fault_matrix_prefetch_thread_death_is_in_band(rng, tmp_path,
                                                       site):
    """A dead prefetcher (disk-read or device_put stage) surfaces as
    the ONE injected error on the consumer thread — no hang, and the
    store quiesces (no leaked reader)."""
    cb, cobj, d = _spilled_objective(rng, str(tmp_path / "spill"))
    w = jnp.asarray(rng.normal(0, 0.2, d), jnp.float32)
    inj = FaultInjector([Fault(site=site, kind="error", at=2)])
    with faults.injected(inj):
        with pytest.raises(faults.InjectedFault):
            cobj.value(w)
    cb.store.assert_quiesced()
    # The pipeline is reusable after the failure.
    assert np.isfinite(float(cobj.value(w)))


def test_fault_matrix_wedged_pipeline_times_out_not_hangs():
    """A load that never returns trips the consumer's stall deadline
    into an actionable TimeoutError instead of an eternal q.get."""
    block = threading.Event()

    def load(i):
        block.wait(30)
        return i

    pf = ChunkPrefetcher(load, lambda h: h, depth=2,
                         stall_timeout_s=0.3)
    pf.start(range(2))
    try:
        with pytest.raises(TimeoutError, match="stalled"):
            pf.next(0)
        # close() must not re-hang while the producer is STILL wedged
        # inside the load (review finding): bounded join, then abandon
        # the daemon thread.
        pf.close(join_timeout_s=0.2)
        assert pf._thread is None
    finally:
        block.set()


def test_fault_matrix_dead_producer_is_actionable():
    """A producer thread that vanished without a sentinel (the
    killed-thread shape) raises immediately, never blocks forever."""
    pf = ChunkPrefetcher(lambda i: i, lambda h: h, depth=1,
                         stall_timeout_s=5.0)
    pf.start(range(1))
    assert pf.next(0) == 0
    # The thread has exhausted its order and exited; asking for more
    # is the orphaned-consumer shape.
    pf._thread.join(timeout=5)
    with pytest.raises(RuntimeError, match="died without delivering"):
        pf.next(1)
    pf.close()


def test_fault_matrix_unwritable_spill_dir_degrades_resident(rng,
                                                             tmp_path):
    """An unwritable spill dir degrades to the resident build with one
    warning — the run loses the memory bound, not its life."""
    blocker = tmp_path / "blocked"
    blocker.write_text("a file, not a dir")
    spill = str(blocker / "spill")
    assert probe_spill_dir(spill) is None
    rows, labels, d = _sparse_problem(rng)
    with metrics_session() as t:
        cb = build_chunked_batch(rows, d, labels, n_chunks=4,
                                 layout="ell", spill_dir=spill)
    assert cb.store is None          # resident fallback
    assert cb.n_chunks == 4


def test_fault_matrix_seeded_plan_is_deterministic():
    p1 = faults.seeded_plan(7, {"store.load": "io_error",
                                "store.spill": "enospc"})
    p2 = faults.seeded_plan(7, {"store.load": "io_error",
                                "store.spill": "enospc"})
    at1 = sorted((f.site, f.kind, f.at)
                 for fs in p1._by_site.values() for f in fs)
    at2 = sorted((f.site, f.kind, f.at)
                 for fs in p2._by_site.values() for f in fs)
    assert at1 == at2


# ---------------------------------------------------------------------------
# Sink hardening: no torn containers, ever
# ---------------------------------------------------------------------------


def _scoring_workload(rng, n=400):
    from photon_ml_tpu.game.dataset import GameDataset
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import FixedEffectModel, GameModel
    from photon_ml_tpu.models.glm import TaskType

    d = 20
    x = rng.normal(size=(n, d)).astype(np.float32)
    model = GameModel(models={
        "global": FixedEffectModel(
            coefficients=Coefficients(
                means=jnp.asarray(rng.normal(size=d + 1)
                                  .astype(np.float32))),
            feature_shard="dense", intercept=True)})
    ds = GameDataset(
        labels=(rng.uniform(size=n) < 0.5).astype(np.float32),
        features={"dense": x}, entity_ids={})
    return model, ds, TaskType.LOGISTIC_REGRESSION


def test_sink_writer_death_leaves_no_torn_output(rng, tmp_path):
    from photon_ml_tpu.estimators.streaming_scorer import (
        StreamingGameScorer,
    )
    from photon_ml_tpu.io.score_sink import AvroScoreSink, NpzScoreSink

    model, ds, task = _scoring_workload(rng)
    npz_path = str(tmp_path / "scores.npz")
    avro_path = str(tmp_path / "scores.avro")
    sinks = [NpzScoreSink(npz_path, ds.n),
             AvroScoreSink(avro_path, codec="null")]
    scorer = StreamingGameScorer(model, task, chunk_rows=64)
    inj = FaultInjector([Fault(site="sink.write", kind="error", at=1)])
    with faults.injected(inj):
        with pytest.raises(faults.InjectedFault):
            scorer.score(ds, sinks=sinks)
    # No published outputs, no tmp orphans: the failure is loud and
    # the directory is clean.
    leftovers = [p for p in os.listdir(tmp_path)]
    assert leftovers == [], leftovers


def test_avro_sink_refuses_close_after_torn_write(rng, tmp_path):
    from photon_ml_tpu.io.score_sink import AvroScoreSink

    path = str(tmp_path / "s.avro")
    sink = AvroScoreSink(path, codec="null")
    sink.write(0, 4, None, np.ones(4), np.zeros(4))
    good_end = sink._f.tell()

    class _FailingFile:
        def __init__(self, f):
            self._f = f
            self._writes = 0

        def write(self, b):
            self._writes += 1
            if self._writes >= 2:     # fail mid-block
                raise OSError("disk error")
            return self._f.write(b)

        def __getattr__(self, name):
            return getattr(self._f, name)

    real = sink._f
    sink._f = _FailingFile(real)
    with pytest.raises(OSError):
        sink.write(4, 8, None, np.ones(4), np.zeros(4))
    sink._f = real
    # Rolled back to the block boundary; close refuses to publish.
    assert real.tell() == good_end
    with pytest.raises(ValueError, match="partial container"):
        sink.close()
    assert not os.path.exists(path)
    assert not os.path.exists(path + ".tmp")


def test_npz_sink_refuses_close_after_failed_write(tmp_path):
    from photon_ml_tpu.io.score_sink import NpzScoreSink

    path = str(tmp_path / "s.npz")
    sink = NpzScoreSink(path, 8)
    sink.write(0, 4, np.ones(4), np.ones(4), np.zeros(4))
    with pytest.raises(Exception):
        sink.write(4, 8, np.ones(3), np.ones(4), np.zeros(4))  # bad shape
    with pytest.raises(ValueError, match="rows written"):
        sink.close()
    assert os.listdir(tmp_path) == []   # all tmp members cleaned


# ---------------------------------------------------------------------------
# Stitched run-log report (kill + resume, append mode)
# ---------------------------------------------------------------------------


def test_report_splits_stitched_segments(tmp_path, capsys):
    from photon_ml_tpu.telemetry.report import report
    from photon_ml_tpu.utils.run_log import RunLogger

    path = str(tmp_path / "run_log.jsonl")
    with RunLogger(path, run_info={"telemetry": "off"}) as log:
        log.event("phase_end", phase="fit", duration_s=1.0)
    # Torn tail: the killed run died mid-write.
    with open(path, "a") as f:
        f.write('{"t": 9.9, "event": "cd_coo')
    with RunLogger(path, mode="a", header=True,
                   run_info={"telemetry": "off", "resume": True}) as log:
        log.event("cd_resume", iteration=1)
        log.event("phase_end", phase="fit", duration_s=0.5)

    result = report(path, out=None)
    out = capsys.readouterr().out
    assert result["segments"] == 2
    assert result["ok"] is True
    # The report of record is the LAST segment's.
    assert result["phases"] == {"fit": 0.5}
    assert "Stitched log: 2 run segments" in out
    assert "malformed line" in out


# ---------------------------------------------------------------------------
# Driver-level resume: the swept streamed fit
# ---------------------------------------------------------------------------


def _driver_config(tmp_path, out, n_iterations=2, resume=False,
                   train="train.jsonl"):
    return {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [{
            "name": "global", "kind": "FIXED_EFFECT",
            "feature_shard": "global",
            "optimizer": {"optimizer": "LBFGS", "max_iters": 40,
                          "tolerance": 1e-8},
        }],
        "update_sequence": ["global"],
        "input_path": str(tmp_path / train),
        "validation_fraction": 0.25,
        "output_dir": str(tmp_path / out),
        "n_iterations": n_iterations,
        "reg_weight_grid": {"global": [2.0, 0.5, 0.1]},
        "chunk_rows": 128,
        "spill_dir": str(tmp_path / "spill"),
        "checkpoint_dir": str(tmp_path / "ck"),
        "checkpoint_every_solver_iters": 1,
        "resume": resume,
        "seed": 3,
    }


def _fixed_coefs(model_dir):
    from photon_ml_tpu.io.model_io import load_game_model

    model, _task = load_game_model(str(model_dir))
    return np.asarray(model.models["global"].coefficients.means)


def test_driver_swept_streamed_resume_parity(tmp_path):
    """The acceptance shape in-process: a swept streamed grid fit that
    completed only sweep 1 of 2 resumes (--resume semantics through the
    driver) and lands on the uninterrupted run's coefficients."""
    import json as _json

    from photon_ml_tpu.cli import game_training_driver

    from test_drivers import _write_jsonl_fixture

    _write_jsonl_fixture(str(tmp_path / "train.jsonl"), n_users=10,
                         n_obs=800, seed=9)

    # Uninterrupted 2-sweep fit (its own checkpoint dir).
    cfg = _driver_config(tmp_path, "out_full")
    cfg["checkpoint_dir"] = str(tmp_path / "ck_full")
    p = str(tmp_path / "cfg_full.json")
    with open(p, "w") as f:
        _json.dump(cfg, f)
    summary_full = game_training_driver.main(["--config", p])

    # "Interrupted": sweep 1 only, checkpointed...
    cfg1 = _driver_config(tmp_path, "out_resumed", n_iterations=1)
    p1 = str(tmp_path / "cfg1.json")
    with open(p1, "w") as f:
        _json.dump(cfg1, f)
    game_training_driver.main(["--config", p1])
    assert os.path.exists(tmp_path / "ck" / "stage_swept.npz")

    # ...then resumed to the full 2 sweeps.
    cfg2 = _driver_config(tmp_path, "out_resumed", n_iterations=2,
                          resume=True)
    p2 = str(tmp_path / "cfg2.json")
    with open(p2, "w") as f:
        _json.dump(cfg2, f)
    summary_res = game_training_driver.main(["--config", p2])

    assert summary_res["best_index"] == summary_full["best_index"]
    w_full = _fixed_coefs(tmp_path / "out_full" / "model")
    w_res = _fixed_coefs(tmp_path / "out_resumed" / "model")
    np.testing.assert_allclose(w_res, w_full, rtol=1e-5, atol=1e-6)
    # The stitched run log carries both segments.
    from photon_ml_tpu.telemetry.report import split_segments
    from photon_ml_tpu.utils.run_log import read_run_log

    segs = split_segments(read_run_log(
        str(tmp_path / "out_resumed" / "run_log.jsonl")))
    assert len(segs) == 2


@pytest.mark.slow
def test_driver_sigkill_then_resume_e2e(tmp_path):
    """THE acceptance e2e: SIGKILL a subprocess swept streamed driver
    fit mid-solve, ``--resume``, assert coefficient parity with an
    uninterrupted run and that ``telemetry report`` reconciles the
    stitched log (rc 0, two segments)."""
    import json as _json
    import signal
    import subprocess
    import sys
    import time

    from test_drivers import _write_jsonl_fixture

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    _write_jsonl_fixture(str(tmp_path / "train.jsonl"), n_users=12,
                         n_obs=6000, seed=11)

    def cfg(out, ck, resume):
        c = _driver_config(tmp_path, out, n_iterations=2, resume=resume)
        c["checkpoint_dir"] = str(tmp_path / ck)
        c["telemetry"] = "trace"
        # Long enough to be killable mid-solve on any box.
        c["coordinates"][0]["optimizer"]["max_iters"] = 400
        c["coordinates"][0]["optimizer"]["tolerance"] = 1e-12
        return c

    def run(name, config, wait=True):
        path = str(tmp_path / f"{name}.json")
        with open(path, "w") as f:
            _json.dump(config, f)
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "photon_ml_tpu.cli.game_training_driver",
             "--config", path],
            cwd=repo, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        if wait:
            assert proc.wait(timeout=900) == 0
        return proc

    # Uninterrupted reference.
    run("full", cfg("out_full", "ck_full", False))

    # Victim: SIGKILL once the first mid-solve snapshot lands.
    proc = run("victim", cfg("out_res", "ck", False), wait=False)
    deadline = time.monotonic() + 600
    ck_dir = str(tmp_path / "ck")
    try:
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                pytest.fail("driver finished before a mid-solve "
                            "checkpoint appeared; shape too small")
            if glob.glob(os.path.join(ck_dir, "solver_*.npz")):
                break
            time.sleep(0.2)
        else:
            pytest.fail("no solver checkpoint appeared in time")
        time.sleep(0.5)   # let a cadence tick or two land
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()

    # Resume to completion.
    run("resume", cfg("out_res", "ck", True))

    w_full = _fixed_coefs(tmp_path / "out_full" / "model")
    w_res = _fixed_coefs(tmp_path / "out_res" / "model")
    np.testing.assert_allclose(w_res, w_full, rtol=1e-4, atol=1e-5)

    # telemetry report reconciles the stitched (kill + resume) log.
    import subprocess as sp

    proc = sp.run(
        [sys.executable, "-m", "photon_ml_tpu.telemetry", "report",
         str(tmp_path / "out_res" / "run_log.jsonl")],
        cwd=repo, env=env, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    tail = json.loads(proc.stdout.strip().splitlines()[-1])
    assert tail["segments"] == 2
    assert tail["ok"] is True


# ---------------------------------------------------------------------------
# Tuner history checkpointing (swept batched tuning)
# ---------------------------------------------------------------------------


def test_tuned_swept_checkpoint_restores_history_and_models(tmp_path):
    """A resumed swept batched tuning run replays the checkpointed
    rounds as observations and materializes completed trials' models
    from the saved lane matrices — no re-training."""
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
        TuningConfig,
    )
    from photon_ml_tpu.estimators.game_estimator import GameEstimator
    from photon_ml_tpu.evaluation.evaluators import EvaluatorType
    from photon_ml_tpu.game.dataset import GameDataset
    from photon_ml_tpu.models.glm import TaskType

    rng = np.random.default_rng(3)
    rows, labels, d = _sparse_problem(rng, n=800, d=60, k=4)
    train = GameDataset(labels=labels, features={"global": rows},
                        entity_ids={}, feature_dims={"global": d})
    rows_v, labels_v, _ = _sparse_problem(rng, n=300, d=60, k=4)
    valid = GameDataset(labels=labels_v, features={"global": rows_v},
                        entity_ids={}, feature_dims={"global": d})

    def config(resume):
        return TrainingConfig(
            task_type=TaskType.LOGISTIC_REGRESSION,
            coordinates=[CoordinateConfig(
                name="global", kind=CoordinateKind.FIXED_EFFECT,
                feature_shard="global",
                optimizer=OptimizerSettings(max_iters=25))],
            update_sequence=["global"],
            evaluators=[EvaluatorType.AUC],
            tuning=TuningConfig(
                n_trials=4, mode="RANDOM", trial_batch=2, seed=1,
                reg_weight_ranges={"global": {"low": 0.01,
                                              "high": 10.0}}),
            checkpoint_dir=str(tmp_path / "ck"),
            output_dir=str(tmp_path / "out"),
            resume=resume, seed=0)

    res1 = GameEstimator(config(False)).fit_tuned(train, valid)
    # One stage file per completed round (2 rounds of trial_batch=2) —
    # each round writes only its own lane matrix (review finding: a
    # cumulative snapshot re-serialized every prior round each round).
    assert os.path.exists(tmp_path / "ck" / "stage_tuner_hist_0.npz")
    assert os.path.exists(tmp_path / "ck" / "stage_tuner_hist_1.npz")

    res2 = GameEstimator(config(True)).fit_tuned(train, valid)
    assert len(res2) == len(res1) == 4
    for a, b in zip(res1, res2):
        assert a.reg_weights == b.reg_weights
        assert a.evaluations == b.evaluations
        # Restored trials keep the per-sweep validation trace a live
        # run carries (review finding).
        assert a.validation_history == b.validation_history
        assert len(b.validation_history) > 0
        np.testing.assert_allclose(
            np.asarray(a.model.models["global"].coefficients.means),
            np.asarray(b.model.models["global"].coefficients.means),
            rtol=1e-6, atol=1e-7)


def test_fresh_run_claims_dir_so_resume_never_jumps_runs(tmp_path):
    """A fresh run's first checkpoint write removes a PREVIOUS run's
    snapshots from the directory (review finding): without the claim, a
    fresh run killed at sweep 2 into a dir holding an older run's
    cd_iter_5 would --resume at the foreign sweep 5."""
    old = RunCheckpointer(str(tmp_path), every_solver_iters=1)
    old.save_cd(5, {"a": jnp.full(3, 9.0)}, {})
    old.save_stage("swept", {"sweep": 5, "lams": [1.0]})
    assert old.maybe_save_solver("it5/a/lbfgs", 1, {"w": np.ones(2)})

    fresh = RunCheckpointer(str(tmp_path))
    fresh.save_cd(1, {"a": jnp.arange(3, dtype=jnp.float32)}, {})
    assert not os.path.exists(tmp_path / "cd_iter_5.npz")
    assert not os.path.exists(tmp_path / "stage_swept.npz")
    assert glob.glob(str(tmp_path / "solver_*.npz")) == []

    resumed = RunCheckpointer(str(tmp_path), resume=True)
    st = resumed.load_latest_cd()
    assert st["iteration"] == 1
    np.testing.assert_array_equal(st["coefs"]["a"], [0, 1, 2])
    # A RESUMED run never claims: its own predecessor's files survive
    # its writes.
    resumed.save_cd(2, {"a": jnp.zeros(3)}, {})
    assert os.path.exists(tmp_path / "cd_iter_1.npz")


def test_legacy_format_checkpoint_resumes(tmp_path):
    """--resume into a directory checkpointed by the pre-reliability
    release (``utils.checkpoint``: plain np.savez, no manifest) must
    restore the run, not silently restart at sweep 0 (review
    finding)."""
    from photon_ml_tpu.utils.checkpoint import save_checkpoint

    save_checkpoint(str(tmp_path), 4,
                    {"a": jnp.arange(3, dtype=jnp.float32),
                     "re": [jnp.ones((2, 2))]},
                    {"a": jnp.ones(5)})
    ck = RunCheckpointer(str(tmp_path), resume=True)
    st = ck.load_latest_cd()
    assert st is not None
    assert (st["iteration"], st["coord_pos"]) == (4, 0)
    np.testing.assert_array_equal(st["coefs"]["a"], [0, 1, 2])
    np.testing.assert_array_equal(st["coefs"]["re"][0], np.ones((2, 2)))
    np.testing.assert_array_equal(st["scores"]["a"], np.ones(5))
    assert st["re_state"] == {} and st["extra"] == {}

    # A newer new-format snapshot still dominates the legacy one.
    ck.save_cd(5, {"a": jnp.zeros(3)}, {})
    st = ck.load_latest_cd()
    assert st["iteration"] == 5
    np.testing.assert_array_equal(st["coefs"]["a"], np.zeros(3))


def test_resumed_random_search_continues_the_proposal_stream():
    """A resumed random search proposes the rounds AFTER the restored
    ones, not round 0's draws again (review finding): run_batched
    replays the strategy's proposal stream past the restored trials."""
    from photon_ml_tpu.hyperparameter.search import (
        ParamRange,
        SearchSpace,
    )
    from photon_ml_tpu.hyperparameter.tuner import (
        HyperparameterTuner,
        TunerMode,
    )

    def make():
        return HyperparameterTuner(
            SearchSpace([ParamRange("lam", 0.01, 10.0)]),
            mode=TunerMode.RANDOM, seed=7)

    proposed: list[list[dict]] = []

    def evaluate(configs):
        proposed.append([dict(c) for c in configs])
        return [(float(c["lam"]), None) for c in configs]

    trials = make().run_batched(evaluate, 6, batch_size=2)
    rounds_full = list(proposed)
    assert len(rounds_full) == 3

    proposed.clear()
    restored = [(t.config, t.metric, t.payload) for t in trials[:2]]
    trials2 = make().run_batched(evaluate, 6, batch_size=2,
                                 restored=restored)
    # Rounds 1 and 2 are evaluated — never a re-draw of round 0.
    assert proposed == rounds_full[1:]
    assert [t.config for t in trials2] == [t.config for t in trials]


def test_swept_stage_checkpoint_honors_sweep_cadence(tmp_path,
                                                     monkeypatch):
    """``checkpoint_every_sweeps`` gates the swept path's per-sweep
    lane snapshot exactly like maybe_save_cd (review finding); the
    final sweep always saves."""
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
    )
    from photon_ml_tpu.estimators.game_estimator import GameEstimator
    from photon_ml_tpu.game.dataset import GameDataset
    from photon_ml_tpu.models.glm import TaskType

    rng = np.random.default_rng(5)
    rows, labels, d = _sparse_problem(rng, n=600, d=40, k=4)
    train = GameDataset(labels=labels, features={"global": rows},
                        entity_ids={}, feature_dims={"global": d})

    saves: list[tuple[str, int]] = []
    orig = RunCheckpointer.save_stage

    def spy(self, name, tree):
        saves.append((name, tree.get("sweep")))
        return orig(self, name, tree)

    monkeypatch.setattr(RunCheckpointer, "save_stage", spy)
    cfg = TrainingConfig(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[CoordinateConfig(
            name="global", kind=CoordinateKind.FIXED_EFFECT,
            feature_shard="global",
            optimizer=OptimizerSettings(max_iters=15))],
        update_sequence=["global"],
        reg_weight_grid={"global": [2.0, 0.5]},
        n_iterations=3,
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every_sweeps=2,
        output_dir=str(tmp_path / "out"),
        seed=0)
    GameEstimator(cfg).fit(train)
    # Sweeps 1..3 at cadence 2: sweep 2 (on cadence) + sweep 3 (final).
    assert [s for s in saves if s[0] == "swept"] == [("swept", 2),
                                                     ("swept", 3)]


def test_fresh_run_never_adopts_stale_solver_state(rng, tmp_path):
    """A NON-resume run into a dirty checkpoint dir (crashed
    predecessor) must not inherit its mid-solve state (review
    finding): only --resume adopts solver snapshots."""
    d, vg, _, _ = _quadratic(rng)
    cfg = OptimizerConfig(max_iters=40, tolerance=1e-9)
    crashed = RunCheckpointer(str(tmp_path), every_solver_iters=1,
                              resume=True)
    with ckpt.session(crashed), crashed.scope("it1", "q"):
        with pytest.raises(_Interrupt):
            streaming_lbfgs_solve(_flaky(vg, 6), jnp.zeros(d), cfg,
                                  label="q")
    assert glob.glob(str(tmp_path / "solver_*.npz"))

    fresh = RunCheckpointer(str(tmp_path), every_solver_iters=1)
    assert fresh.load_solver("it1/streaming_lbfgs:q/q") is None
    with ckpt.session(fresh), fresh.scope("it1", "q"), \
            metrics_session() as t:
        streaming_lbfgs_solve(vg, jnp.zeros(d), cfg, label="q")
    c = _counters(t)
    # A full fresh solve: counted as a solve, never as a resume.
    assert c.get("solver.streamed_solves") == 1
    assert "solver.resumed_solves" not in c


def test_solver_snapshot_rejected_on_objective_change(rng, tmp_path):
    """Mid-solve snapshots are identity-stamped (warm start + l1 + m):
    resuming after a config edit that keeps shapes and scope (new λ
    values, changed warm path) runs a FULL solve instead of silently
    adopting the stale loop state (review finding)."""
    d, vg, _, _ = _quadratic(rng)
    cfg = OptimizerConfig(max_iters=40, tolerance=1e-9)
    crashed = RunCheckpointer(str(tmp_path), every_solver_iters=1,
                              resume=True)
    with ckpt.session(crashed), crashed.scope("it1", "q"):
        with pytest.raises(_Interrupt):
            streaming_lbfgs_solve(_flaky(vg, 6), jnp.zeros(d), cfg,
                                  label="q")
    assert glob.glob(str(tmp_path / "solver_*.npz"))

    resumed = RunCheckpointer(str(tmp_path), every_solver_iters=1,
                              resume=True)
    with ckpt.session(resumed), resumed.scope("it1", "q"), \
            metrics_session() as t:
        streaming_lbfgs_solve(vg, jnp.ones(d), cfg, label="q")
    c = _counters(t)
    # Different warm start ⇒ fingerprint mismatch ⇒ full solve.
    assert c.get("solver.streamed_solves") == 1
    assert "solver.resumed_solves" not in c


def test_run_logger_append_to_empty_file_is_clean(tmp_path):
    """--resume pointed at an empty (or never-flushed) predecessor log
    must not crash the torn-tail repair (review finding)."""
    path = str(tmp_path / "run_log.jsonl")
    open(path, "w").close()
    from photon_ml_tpu.utils.run_log import RunLogger, read_run_log

    with RunLogger(path, mode="a", header=True,
                   run_info={"resume": True}) as log:
        log.event("x")
    events = read_run_log(path)
    assert [e["event"] for e in events] == ["run_header", "x"]
