"""Mesh-sharded GRR plans: the fast path IS the distributed path.

Round-3 verdict item #1 / BASELINE north star: per-device GrrPairs over
shard-local rows, gradient partials met by the existing psum.  These
tests check (a) shard-local plan semantics against the global plan,
(b) mesh-uniform structure (congruent pytrees, equal leaf shapes),
(c) the assembled batch through shard_map + DistributedGLMObjective
matches the single-device GRR objective, on the virtual 8-device mesh.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.grr import build_grr_pair, build_sharded_grr_pairs


@pytest.fixture
def rng():
    return np.random.default_rng(3)


def _ell(rng, n, d, k, hot_col=None, skew=False):
    """Synthetic ELL with optional forced-hot column and power-law cols."""
    if skew:
        # Zipf-ish column draw → heavy per-column tails (spill pressure).
        raw = rng.zipf(1.3, (n, k)) % d
        cols = raw.astype(np.int64)
        # De-duplicate within each row by re-rolling dups to random cols.
        for _ in range(4):
            s = np.sort(cols, axis=1)
            dup_rows = (s[:, 1:] == s[:, :-1]).any(axis=1)
            if not dup_rows.any():
                break
            cols[dup_rows] = rng.choice(d, (int(dup_rows.sum()), k),
                                        replace=True)
        # Final pass: force uniqueness per row deterministically.
        base = np.arange(k) * (d // k)
        for i in np.flatnonzero([len(set(r)) < k for r in cols]):
            cols[i] = base + rng.integers(0, d // k, k)
    else:
        block = d // k
        cols = (np.arange(k) * block)[None, :] + rng.integers(
            0, block, (n, k))
    if hot_col is not None:
        cols[:, 0] = hot_col
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    return cols.astype(np.int32), vals


def _pair_dot(pair, w):
    return np.asarray(pair.dot(jnp.asarray(w)))


def _pair_tdot(pair, r):
    return np.asarray(pair.t_dot(jnp.asarray(r)))


@pytest.mark.parametrize("hot,skew", [(None, False), (7, False), (None, True)])
def test_sharded_pairs_match_global(rng, hot, skew):
    n, d, k, n_dev = 1024, 600, 8, 8
    cols, vals = _ell(rng, n, d, k, hot_col=hot, skew=skew)
    per = n // n_dev
    pairs = build_sharded_grr_pairs(
        [cols[i * per:(i + 1) * per] for i in range(n_dev)],
        [vals[i * per:(i + 1) * per] for i in range(n_dev)],
        d, overflow_threshold=64,
    )
    ref = build_grr_pair(cols, vals, d)

    w = rng.normal(0, 1, d).astype(np.float32)
    r = rng.normal(0, 1, n).astype(np.float32)
    # margins: concat of shard-local dots == global dot
    got = np.concatenate([_pair_dot(p, w) for p in pairs])
    np.testing.assert_allclose(got, _pair_dot(ref, w), rtol=2e-5, atol=2e-4)
    # gradient: sum of shard partials == global t_dot
    got_g = sum(_pair_tdot(p, r[i * per:(i + 1) * per])
                for i, p in enumerate(pairs))
    np.testing.assert_allclose(got_g, _pair_tdot(ref, r),
                               rtol=2e-4, atol=5e-4)


def test_sharded_pairs_mesh_uniform(rng):
    """Congruent pytrees + equal leaf shapes: the assembly contract."""
    n, d, k, n_dev = 512, 400, 6, 8
    cols, vals = _ell(rng, n, d, k, hot_col=3)
    per = n // n_dev
    pairs = build_sharded_grr_pairs(
        [cols[i * per:(i + 1) * per] for i in range(n_dev)],
        [vals[i * per:(i + 1) * per] for i in range(n_dev)],
        d,
    )
    t0, s0 = jax.tree.flatten(pairs[0])[1], [
        lf.shape for lf in jax.tree.leaves(pairs[0])]
    for p in pairs[1:]:
        leaves, tdef = jax.tree.flatten(p)
        assert tdef == t0
        assert [lf.shape for lf in leaves] == s0
    # Static metadata forced common
    assert len({p.row_dir.cap for p in pairs}) == 1
    assert len({p.col_dir.cap for p in pairs}) == 1
    # hot ids identical across shards
    for p in pairs[1:]:
        np.testing.assert_array_equal(np.asarray(p.hot_ids),
                                      np.asarray(pairs[0].hot_ids))


def test_pooled_overflow_absorbs_spill(rng):
    """Heavy per-(segment, window) tails spill at level 1; the pooled
    level-2 build must absorb them (uniform across shards) and keep the
    contraction exact."""
    n, d, k, n_dev = 512, 256, 8, 4
    cols, vals = _ell(rng, n, d, k)
    cols[:, :4] = np.arange(4)[None, :]       # 4 super-hot columns...
    per = n // n_dev
    pairs = build_sharded_grr_pairs(
        [cols[i * per:(i + 1) * per] for i in range(n_dev)],
        [vals[i * per:(i + 1) * per] for i in range(n_dev)],
        d, hot_threshold=10 ** 9,             # ...forced OFF the dense side
        overflow_threshold=4,
    )
    ovfs = [p.col_dir.overflow is not None for p in pairs]
    assert all(ovfs)                          # pooled level-2 built...
    for p in pairs:                           # ...and spill absorbed
        assert p.col_dir.n_spill == 0
    ref = build_grr_pair(cols, vals, d, hot_threshold=10 ** 9)
    r = rng.normal(0, 1, n).astype(np.float32)
    got = sum(_pair_tdot(p, r[i * per:(i + 1) * per])
              for i, p in enumerate(pairs))
    np.testing.assert_allclose(got, _pair_tdot(ref, r), rtol=2e-4,
                               atol=5e-4)


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_shard_sparse_batch_grr_objective_equivalence(rng):
    """Assembled GRR-sharded batch through the psum objective == the
    single-device GRR objective (value, gradient, Hdiag, margins)."""
    from photon_ml_tpu.data.batch import make_sparse_batch
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.parallel import (
        DistributedGLMObjective,
        data_parallel_mesh,
        shard_sparse_batch,
    )

    n, d, k = 512, 300, 6
    cols, vals = _ell(rng, n, d, k, hot_col=5)
    rows = [(cols[i], vals[i]) for i in range(n)]
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    weights = rng.uniform(0.5, 1.5, n)

    mesh = data_parallel_mesh(8)
    sharded = shard_sparse_batch(rows, d, labels, mesh, weights=weights,
                                 layout="grr")
    assert sharded.grr is not None
    local = make_sparse_batch(rows, d, labels, weights=weights, grr=True)

    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=__import__(
            "photon_ml_tpu.ops.regularization",
            fromlist=["RegularizationContext"],
        ).RegularizationContext.l2(0.3),
        norm=NormalizationContext.identity(),
    )
    dist = DistributedGLMObjective(objective=obj, mesh=mesh)
    w = jnp.asarray(rng.normal(0, 0.5, d).astype(np.float32))

    v1, g1 = obj.value_and_gradient(w, local)
    v8, g8 = dist.value_and_gradient(w, sharded)
    np.testing.assert_allclose(float(v8), float(v1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g8), np.asarray(g1),
                               rtol=2e-4, atol=5e-4)

    hd1 = obj.hessian_diagonal(w, local)
    hd8 = dist.hessian_diagonal(w, sharded)
    np.testing.assert_allclose(np.asarray(hd8), np.asarray(hd1),
                               rtol=2e-4, atol=5e-4)

    m1 = obj.predict_margins(w, local)
    m8 = dist.predict_margins(w, sharded)
    np.testing.assert_allclose(np.asarray(m8), np.asarray(m1),
                               rtol=2e-4, atol=5e-4)
    # raw scoring path (FixedEffectCoordinate.score contract)
    x1 = local.x_dot(w)
    x8 = dist.x_dot(w, sharded)
    np.testing.assert_allclose(np.asarray(x8), np.asarray(x1),
                               rtol=2e-4, atol=5e-4)


def test_sharded_mid_hot_columns(rng):
    """The sharded build routes mid-hot columns to per-shard compact
    plans with mesh-uniform structure; partial t_dots still sum to the
    global contraction."""
    n, k, dim, n_dev = 2048, 6, 1500, 4
    cols = np.zeros((n, k), np.int64)
    cols[:, 0] = rng.integers(0, 12, n)                # mid-hot band
    cols[:, 1:] = rng.integers(12, dim, (n, k - 1))
    for j in range(1, k):
        for _ in range(6):
            dup = (cols[:, j:j + 1] == cols[:, :j]).any(axis=1)
            if not dup.any():
                break
            cols[dup, j] = rng.integers(12, dim, int(dup.sum()))
    cols = cols.astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    per = n // n_dev
    pairs = build_sharded_grr_pairs(
        [cols[i * per:(i + 1) * per] for i in range(n_dev)],
        [vals[i * per:(i + 1) * per] for i in range(n_dev)],
        dim, hot_threshold=10 ** 9, mid_threshold=30,
    )
    assert all(p.col_mid is not None for p in pairs)
    shapes = {tuple(lf.shape for lf in jax.tree.leaves(p.col_mid))
              for p in pairs}
    assert len(shapes) == 1                   # mesh-uniform
    for p in pairs[1:]:
        np.testing.assert_array_equal(np.asarray(p.mid_ids),
                                      np.asarray(pairs[0].mid_ids))
    ref = build_grr_pair(cols, vals, dim, hot_threshold=10 ** 9,
                         mid_threshold=30)
    r = rng.normal(0, 1, n).astype(np.float32)
    got = sum(_pair_tdot(p, r[i * per:(i + 1) * per])
              for i, p in enumerate(pairs))
    np.testing.assert_allclose(got, _pair_tdot(ref, r), rtol=2e-4,
                               atol=5e-4)
    w = rng.normal(0, 1, dim).astype(np.float32)
    got_m = np.concatenate([_pair_dot(p, w) for p in pairs])
    np.testing.assert_allclose(got_m, _pair_dot(ref, w), rtol=2e-4,
                               atol=5e-4)


def test_sharded_mid_cap_seeded_from_heaviest_shard(rng):
    """Mid mass concentrated AWAY from shard 0: the mid cap must come
    from a shard that carries mid entries, not shard 0's empty plan."""
    n, k, dim, n_dev = 2048, 4, 800, 4
    per = n // n_dev
    cols = rng.integers(10, dim, (n, k)).astype(np.int64)
    # Shards 1-3: column ids 0..15 appear densely; shard 0 never sees
    # them (per-(col, window) occupancy ~32 — mid class, under the 64
    # capacity ceiling).
    cols[per:, 0] = rng.integers(0, 16, n - per)
    for j in range(1, k):
        for _ in range(6):
            dup = (cols[:, j:j + 1] == cols[:, :j]).any(axis=1)
            if not dup.any():
                break
            cols[dup, j] = rng.integers(10, dim, int(dup.sum()))
    cols = cols.astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    pairs = build_sharded_grr_pairs(
        [cols[i * per:(i + 1) * per] for i in range(n_dev)],
        [vals[i * per:(i + 1) * per] for i in range(n_dev)],
        dim, hot_threshold=10 ** 9, mid_threshold=64,
    )
    assert all(p.col_mid is not None for p in pairs)
    # Cap sized for the heavy shards' occupancy (~32 entries per mid
    # col per shard-window) — an empty-shard seed would give 4.
    assert pairs[0].col_mid.cap >= 32
    # At most start-lane fluctuation on the COO fallback (tiny 512-row
    # shards expose only 4 start rows); a bad cap seed spills ~90%.
    for p in pairs[1:]:
        m = int(np.count_nonzero(np.asarray(p.col_mid.spill_val)))
        assert m < 0.05 * 512, m
    ref = build_grr_pair(cols, vals, dim, hot_threshold=10 ** 9,
                         mid_threshold=64)
    r = rng.normal(0, 1, n).astype(np.float32)
    got = sum(_pair_tdot(p, r[i * per:(i + 1) * per])
              for i, p in enumerate(pairs))
    np.testing.assert_allclose(got, _pair_tdot(ref, r), rtol=2e-4,
                               atol=5e-4)


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_sharded_pairs_multiwindow_zipf(rng):
    """Round-4 verdict weak #5: the sharded suite topped out below one
    table window per direction (d=600, per-shard rows=128), so the
    dense-grid multi-window machinery was never exercised on a mesh.
    Here BOTH directions span multiple windows per shard (d >= 2·WIN
    columns; per-shard rows > WIN) with zipf skew, so spill + pooled
    overflow are active per shard."""
    from photon_ml_tpu.data.grr import WIN

    n, d, k, n_dev = 8 * 20480, 40_000, 6, 8
    cols, vals = _ell(rng, n, d, k, skew=True)
    per = n // n_dev
    assert per > WIN and d > 2 * WIN   # the shapes this test exists for
    pairs = build_sharded_grr_pairs(
        [cols[i * per:(i + 1) * per] for i in range(n_dev)],
        [vals[i * per:(i + 1) * per] for i in range(n_dev)],
        d, overflow_threshold=256,
    )
    # Multi-window in both directions on every shard.
    assert pairs[0].row_dir.n_gw >= 2    # table = column space
    assert pairs[0].col_dir.n_gw >= 2    # table = shard row space
    ref = build_grr_pair(cols, vals, d, col_range_split=False)

    w = rng.normal(0, 1, d).astype(np.float32)
    r = rng.normal(0, 1, n).astype(np.float32)
    got = np.concatenate([_pair_dot(p, w) for p in pairs])
    np.testing.assert_allclose(got, _pair_dot(ref, w), rtol=2e-4,
                               atol=5e-4)
    got_g = sum(_pair_tdot(p, r[i * per:(i + 1) * per])
                for i, p in enumerate(pairs))
    np.testing.assert_allclose(got_g, _pair_tdot(ref, r),
                               rtol=2e-4, atol=2e-3)
    # Congruence still holds at multi-window shapes.
    t0 = jax.tree.flatten(pairs[0])[1]
    s0 = [lf.shape for lf in jax.tree.leaves(pairs[0])]
    for p in pairs[1:]:
        leaves, tdef = jax.tree.flatten(p)
        assert tdef == t0
        assert [lf.shape for lf in leaves] == s0


@pytest.mark.slow   # 10s+ in tests/tier1_durations.json
def test_sharded_pairs_col_range_split(rng):
    """Round-5: the column-range split engages on sharded builds too —
    same ranges on every shard (pooled sample), per-range caps common,
    overflow pooled and padded per range — and reproduces the global
    plan's contraction."""
    from photon_ml_tpu.data.grr import WIN, GrrRangeSplit

    n, d, k, n_dev = 8 * WIN, 70_000, 16, 8
    x0 = 5000.0
    u = rng.uniform(size=(n, k))
    cols = np.minimum(x0 * np.exp(u * np.log((d + x0) / x0)) - x0,
                      d - 1).astype(np.int32)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    per = n // n_dev
    shard_c = [cols[i * per:(i + 1) * per] for i in range(n_dev)]
    shard_v = [vals[i * per:(i + 1) * per] for i in range(n_dev)]
    pairs = build_sharded_grr_pairs(shard_c, shard_v, d)
    assert isinstance(pairs[0].row_dir, GrrRangeSplit)
    bounds = pairs[0].row_dir.bounds
    for p in pairs[1:]:
        assert p.row_dir.bounds == bounds          # same ranges everywhere
    caps0 = [q.cap for q in pairs[0].row_dir.parts]
    for p in pairs[1:]:
        assert [q.cap for q in p.row_dir.parts] == caps0
    assert len(set(caps0)) >= 2                    # ranges chose own caps

    unsplit = build_sharded_grr_pairs(shard_c, shard_v, d,
                                      col_range_split=False)
    s = pairs[0].row_dir.plan_stats()
    su = unsplit[0].row_dir.plan_stats()
    assert s["spill_frac"] < su["spill_frac"] / 3

    ref = build_grr_pair(cols, vals, d, col_range_split=False)
    w = rng.normal(0, 1, d).astype(np.float32)
    got = np.concatenate([_pair_dot(p, w) for p in pairs])
    np.testing.assert_allclose(got, _pair_dot(ref, w), rtol=2e-4,
                               atol=5e-4)
    r = rng.normal(0, 1, n).astype(np.float32)
    got_g = sum(_pair_tdot(p, r[i * per:(i + 1) * per])
                for i, p in enumerate(pairs))
    np.testing.assert_allclose(got_g, _pair_tdot(ref, r), rtol=2e-4,
                               atol=2e-3)
    t0 = jax.tree.flatten(pairs[0])[1]
    s0 = [lf.shape for lf in jax.tree.leaves(pairs[0])]
    for p in pairs[1:]:
        leaves, tdef = jax.tree.flatten(p)
        assert tdef == t0
        assert [lf.shape for lf in leaves] == s0
