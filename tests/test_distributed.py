"""Distributed-objective tests on the simulated 8-device mesh.

The tier-2 "Spark local mode" analog (SURVEY.md §4): shard_map/psum code
paths exercised single-process on 8 virtual CPU devices.  Gates:
equality with the single-device objective, and an unchanged optimizer
converging on top of the distributed objective.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.linear_model import LogisticRegression

from photon_ml_tpu.data.batch import make_dense_batch, make_sparse_batch
from photon_ml_tpu.data.normalization import (
    NormalizationContext,
    NormalizationType,
    compute_normalization,
)
from photon_ml_tpu.data.statistics import compute_statistics
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim import OptimizerConfig, lbfgs_solve, tron_solve
from photon_ml_tpu.parallel import (
    DistributedGLMObjective,
    data_parallel_mesh,
    padded_rows,
    shard_batch,
)
from photon_ml_tpu.utils.synthetic import make_a1a_like


@pytest.fixture(scope="module")
def mesh():
    m = data_parallel_mesh()
    assert m.devices.size == 8, "conftest must force 8 CPU devices"
    return m


def _problem(rng, n=333, d=12, norm=None):
    x = rng.normal(0, 1, (n, d))
    y = (rng.uniform(size=n) < 0.5).astype(np.float64)
    w = rng.normal(0, 0.5, d).astype(np.float32)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(0.8),
        norm=norm or NormalizationContext.identity(),
    )
    return x, y, jnp.asarray(w), obj


def test_distributed_equals_local(rng, mesh):
    x, y, w, obj = _problem(rng)
    n = x.shape[0]
    local = make_dense_batch(x, y)
    sharded_host = make_dense_batch(x, y, pad_to=padded_rows(n, 8))
    sharded = shard_batch(sharded_host, mesh)
    dist = DistributedGLMObjective(objective=obj, mesh=mesh)

    v_l, g_l = obj.value_and_gradient(w, local)
    v_d, g_d = dist.value_and_gradient(w, sharded)
    np.testing.assert_allclose(v_d, v_l, rtol=1e-6)
    np.testing.assert_allclose(g_d, g_l, rtol=1e-5, atol=1e-5)

    np.testing.assert_allclose(dist.value(w, sharded), obj.value(w, local),
                               rtol=1e-6)

    v = jnp.asarray(np.asarray(rng.normal(0, 1, x.shape[1]), np.float32))
    np.testing.assert_allclose(
        dist.hessian_vector(w, v, sharded),
        obj.hessian_vector(w, v, local),
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_allclose(
        dist.hessian_diagonal(w, sharded),
        obj.hessian_diagonal(w, local),
        rtol=1e-5, atol=1e-5,
    )


def test_distributed_with_normalization_equals_local(rng, mesh):
    x, y, w, _ = _problem(rng, n=200, d=6)
    # Shift+factor normalization stresses the linearity argument (Σr term).
    local = make_dense_batch(x, y)
    stats = compute_statistics(local)
    norm = compute_normalization(
        stats.mean, stats.std, stats.max_abs, NormalizationType.STANDARDIZATION
    )
    obj = GLMObjective(
        loss=losses.LOGISTIC, reg=RegularizationContext.l2(0.5), norm=norm
    )
    sharded = shard_batch(make_dense_batch(x, y, pad_to=padded_rows(200, 8)),
                          mesh)
    dist = DistributedGLMObjective(objective=obj, mesh=mesh)
    v_l, g_l = obj.value_and_gradient(w, local)
    v_d, g_d = dist.value_and_gradient(w, sharded)
    np.testing.assert_allclose(v_d, v_l, rtol=1e-6)
    np.testing.assert_allclose(g_d, g_l, rtol=1e-5, atol=1e-5)


def test_sparse_distributed_equals_local(rng, mesh):
    rows, labels, _ = make_a1a_like(n=500, seed=3)
    dim = 123
    local = make_sparse_batch(rows, dim, labels)
    sharded = shard_batch(
        make_sparse_batch(rows, dim, labels, pad_to=padded_rows(500, 8)), mesh
    )
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(1.0),
        norm=NormalizationContext.identity(),
    )
    dist = DistributedGLMObjective(objective=obj, mesh=mesh)
    w = jnp.asarray(np.random.default_rng(0).normal(0, 0.3, dim), jnp.float32)
    v_l, g_l = obj.value_and_gradient(w, local)
    v_d, g_d = dist.value_and_gradient(w, sharded)
    np.testing.assert_allclose(v_d, v_l, rtol=1e-6)
    np.testing.assert_allclose(g_d, g_l, rtol=1e-5, atol=1e-4)


def test_lbfgs_on_distributed_objective_matches_sklearn(rng, mesh):
    """The north-star composition: unchanged L-BFGS over the shard_mapped
    objective — the reference's broadcast/treeAggregate loop as one jitted
    program."""
    n, d, l2 = 400, 10, 1.0
    x = rng.normal(0, 1, (n, d))
    p = 1 / (1 + np.exp(-(x @ rng.normal(0, 1, d))))
    y = (rng.uniform(size=n) < p).astype(np.float64)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(l2),
        norm=NormalizationContext.identity(),
    )
    sharded = shard_batch(make_dense_batch(x, y, pad_to=padded_rows(n, 8)),
                          mesh)
    dist = DistributedGLMObjective(objective=obj, mesh=mesh)
    res = jax.jit(
        lambda b, w0: lbfgs_solve(
            lambda w: dist.value_and_gradient(w, b), w0,
            OptimizerConfig(max_iters=200, tolerance=1e-6),
        )
    )(sharded, jnp.zeros(d, jnp.float32))
    assert bool(res.converged)
    clf = LogisticRegression(C=1.0 / l2, fit_intercept=False, tol=1e-10,
                             max_iter=10000)
    clf.fit(x, y)
    np.testing.assert_allclose(res.w, clf.coef_.ravel(), rtol=5e-3, atol=5e-4)


def test_tron_on_distributed_objective(rng, mesh):
    n, d = 320, 8
    x = rng.normal(0, 1, (n, d))
    y = x @ rng.normal(0, 1, d) + rng.normal(0, 0.1, n)
    obj = GLMObjective(
        loss=losses.SQUARED,
        reg=RegularizationContext.l2(2.0),
        norm=NormalizationContext.identity(),
    )
    sharded = shard_batch(make_dense_batch(x, y, pad_to=padded_rows(n, 8)),
                          mesh)
    dist = DistributedGLMObjective(objective=obj, mesh=mesh)
    res = jax.jit(
        lambda b, w0: tron_solve(
            lambda w: dist.value_and_gradient(w, b),
            lambda w, v: dist.hessian_vector(w, v, b),
            w0, OptimizerConfig(max_iters=100, tolerance=1e-6),
        )
    )(sharded, jnp.zeros(d, jnp.float32))
    w_ref = np.linalg.solve(x.T @ x + 2.0 * np.eye(d), x.T @ y)
    np.testing.assert_allclose(res.w, w_ref, rtol=1e-3, atol=1e-4)


def test_shard_batch_requires_divisible_rows(rng, mesh):
    batch = make_dense_batch(rng.normal(0, 1, (13, 3)), np.zeros(13))
    with pytest.raises(ValueError, match="not divisible"):
        shard_batch(batch, mesh)


_TWO_PROC_WORKER = r'''
import os, sys
sys.path.insert(0, os.environ["PML_REPO"])
# Force CPU before any backend init (the axon plugin pins JAX_PLATFORMS).
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")

from photon_ml_tpu.cli.game_training_driver import distributed_init_from_env
distributed_init_from_env()           # the driver's multi-host entry

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_ml_tpu.data.batch import DenseBatch, make_dense_batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.parallel import DistributedGLMObjective
from photon_ml_tpu.parallel.mesh import data_parallel_mesh

assert jax.process_count() == 2, jax.process_count()
pid = jax.process_index()

# Identical synthetic data on both processes; each holds half the rows.
rng = np.random.default_rng(0)
n, d = 64, 5
x = rng.normal(0, 1, (n, d)).astype(np.float32)
y = (rng.uniform(size=n) < 0.5).astype(np.float32)
full = make_dense_batch(x, y)

mesh = data_parallel_mesh()          # both processes' devices
assert mesh.devices.size == 2
per = n // 2
sharding = NamedSharding(mesh, P("data"))
dev0 = jax.local_devices()[0]

def place(a):
    a = np.asarray(a)
    local = jnp.asarray(a[pid * per:(pid + 1) * per])
    return jax.make_array_from_single_device_arrays(
        a.shape, sharding, [jax.device_put(local, dev0)])

batch = jax.tree.map(place, full)
obj = GLMObjective(loss=losses.LOGISTIC,
                   reg=RegularizationContext.l2(0.5),
                   norm=NormalizationContext.identity())
dist = DistributedGLMObjective(objective=obj, mesh=mesh)
w_np = rng.normal(0, 0.3, d).astype(np.float32)
w = jax.make_array_from_single_device_arrays(
    (d,), NamedSharding(mesh, P()),
    [jax.device_put(jnp.asarray(w_np), dev0)])

v, g = dist.value_and_gradient(w, batch)     # psum ACROSS processes
v_ref, g_ref = obj.value_and_gradient(jnp.asarray(w_np), full)
np.testing.assert_allclose(float(v), float(v_ref), rtol=1e-5)
np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                           rtol=1e-4, atol=1e-5)
print(f"TWO_PROC_OK pid={pid} value={float(v):.6f}", flush=True)
'''


def test_two_process_psum_objective(tmp_path):
    """Round-3 verdict #5: a REAL cross-process collective.  Two
    subprocesses join via jax.distributed.initialize (the driver's
    distributed_init path) and one psum-reduced objective step runs
    across them, matching the single-process full-batch value."""
    import os
    import socket
    import subprocess
    import sys

    script = tmp_path / "worker.py"
    script.write_text(_TWO_PROC_WORKER)
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update({
            "PML_REPO": os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            "JAX_COORDINATOR_ADDRESS": f"localhost:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
            "XLA_FLAGS": "",  # no virtual-device forcing in workers
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outs.append(out)
    from distributed_helpers import skip_if_multiprocess_wall

    skip_if_multiprocess_wall(outs)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert "TWO_PROC_OK" in out, out[-3000:]
    # Both processes saw the SAME psum'd value (replicated output).
    v0 = [ln for ln in outs[0].splitlines() if "TWO_PROC_OK" in ln][0]
    v1 = [ln for ln in outs[1].splitlines() if "TWO_PROC_OK" in ln][0]
    assert v0.split("value=")[1] == v1.split("value=")[1]
