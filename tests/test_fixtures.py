"""Byte-fixture parity: committed data files + golden models.

Round-4 verdict item #7 / SURVEY §4 tier 3: the reference's integTests
run against committed Avro fixtures with golden models and AUC
thresholds.  Here parity is data-at-rest — the LIBSVM/Avro bytes in
``tests/resources/`` are the contract (generated once by
``make_fixtures.py``, committed), and training from those files must
reproduce the recorded golden coefficients and AUC, not a re-derivation
from seeds.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

HERE = os.path.join(os.path.dirname(__file__), "resources")


@pytest.fixture(scope="module")
def golden():
    with open(os.path.join(HERE, "golden.json")) as f:
        return json.load(f)


def test_config1_libsvm_fixture_parity(tmp_path, golden):
    """BASELINE config-1 class from committed LIBSVM bytes."""
    from photon_ml_tpu.cli import game_training_driver
    from photon_ml_tpu.io.model_io import load_game_model

    cfg = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [{
            "name": "global", "kind": "FIXED_EFFECT",
            "feature_shard": "features",
            "optimizer": {"optimizer": "LBFGS", "reg_weight": 1.0,
                          "max_iters": 100},
        }],
        "update_sequence": ["global"],
        "input_path": os.path.join(HERE, "config1.libsvm"),
        "validation_path": os.path.join(HERE, "config1.t.libsvm"),
        "output_dir": str(tmp_path / "out"),
        "evaluators": ["AUC"],
    }
    p = str(tmp_path / "cfg.json")
    json.dump(cfg, open(p, "w"))
    summary = game_training_driver.main(["--config", p])
    want = golden["config1"]
    got_auc = summary["models"][0]["evaluations"]["AUC"]
    assert abs(got_auc - want["auc"]) < 2e-3, (got_auc, want["auc"])
    model, _ = load_game_model(str(tmp_path / "out" / "model"))
    w = np.asarray(model.models["global"].coefficients.means)
    np.testing.assert_allclose(w, np.asarray(want["coefficients"]),
                               rtol=2e-3, atol=2e-3)


def test_config4_avro_fixture_parity(tmp_path, golden):
    """BASELINE config-4 class (fixed + per-user RE) from committed
    Avro container bytes — exercises the from-spec Avro reader on
    data-at-rest."""
    from photon_ml_tpu.cli import game_training_driver
    from photon_ml_tpu.io.model_io import load_game_model

    cfg = {
        "task_type": "LOGISTIC_REGRESSION",
        "coordinates": [
            {"name": "global", "kind": "FIXED_EFFECT",
             "feature_shard": "global",
             "optimizer": {"optimizer": "LBFGS", "reg_weight": 1.0,
                           "max_iters": 100}},
            {"name": "per_user", "kind": "RANDOM_EFFECT",
             "feature_shard": "user_re", "entity_key": "userId",
             "optimizer": {"optimizer": "LBFGS", "reg_weight": 2.0,
                           "max_iters": 60}},
        ],
        "update_sequence": ["global", "per_user"],
        "n_iterations": 2,
        "input_path": os.path.join(HERE, "config4_train.avro"),
        "validation_path": os.path.join(HERE, "config4_valid.avro"),
        "output_dir": str(tmp_path / "out"),
        "evaluators": ["AUC"],
    }
    p = str(tmp_path / "cfg.json")
    json.dump(cfg, open(p, "w"))
    summary = game_training_driver.main(["--config", p])
    want = golden["config4"]
    got_auc = summary["models"][0]["evaluations"]["AUC"]
    assert abs(got_auc - want["auc"]) < 2e-3, (got_auc, want["auc"])
    model, _ = load_game_model(str(tmp_path / "out" / "model"))
    w = np.asarray(model.models["global"].coefficients.means)
    np.testing.assert_allclose(
        w, np.asarray(want["fixed_coefficients"]), rtol=2e-3, atol=2e-3)


def test_fixture_bytes_are_stable():
    """The committed files ARE the contract: catch accidental
    regeneration/corruption by size+checksum (sync markers make Avro
    bytes random per write, so a silent regen would change these)."""
    import hashlib

    sizes = {}
    for name in ("config1.libsvm", "config1.t.libsvm",
                 "config4_train.avro", "config4_valid.avro"):
        with open(os.path.join(HERE, name), "rb") as f:
            raw = f.read()
        sizes[name] = (len(raw), hashlib.sha256(raw).hexdigest()[:16])
    with open(os.path.join(HERE, "checksums.json")) as f:
        want = {k: tuple(v) for k, v in json.load(f).items()}
    assert sizes == want
