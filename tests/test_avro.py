"""Tests for the stdlib Avro codec + photon-parity schemas.

Byte-level fixtures come straight from the Avro 1.x specification's
binary-encoding examples, so the container files written here stay
readable by any conforming Avro implementation (the reference's pipelines
included) even though no Avro library exists in this environment to
cross-check against.
"""

import io

import numpy as np
import pytest

from photon_ml_tpu.io.avro import (
    Schema,
    decode_datum,
    encode_datum,
    read_container,
    read_long,
    write_container,
    write_long,
)
from photon_ml_tpu.io.avro_schemas import (
    bayesian_linear_model_schema,
    iter_avro_dataset,
    read_model_avro,
    training_example_schema,
    write_avro_dataset,
    write_model_avro,
)

pytestmark = pytest.mark.fast


# ---------------------------------------------------------------------------
# Spec fixtures
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("value,raw", [
    (0, b"\x00"), (-1, b"\x01"), (1, b"\x02"), (-2, b"\x03"), (2, b"\x04"),
    (-64, b"\x7f"), (64, b"\x80\x01"), (-65, b"\x81\x01"),
    (8192, b"\x80\x80\x01"), (-(2**63), b"\xff" * 9 + b"\x01"),
])
def test_zigzag_varint_spec_fixtures(value, raw):
    buf = io.BytesIO()
    write_long(buf, value)
    assert buf.getvalue() == raw
    assert read_long(io.BytesIO(raw)) == value


def test_string_and_record_spec_fixture():
    # Spec example: {"a": 27, "b": "foo"} → 36 06 66 6f 6f
    s = Schema({
        "type": "record", "name": "test",
        "fields": [{"name": "a", "type": "long"},
                   {"name": "b", "type": "string"}],
    })
    raw = encode_datum(s, {"a": 27, "b": "foo"})
    assert raw == b"\x36\x06foo"
    assert decode_datum(s, raw) == {"a": 27, "b": "foo"}


def test_array_spec_fixture():
    # Spec example: array<long> [3, 27] → 04 06 36 00
    s = Schema({"type": "array", "items": "long"})
    assert encode_datum(s, [3, 27]) == b"\x04\x06\x36\x00"
    assert decode_datum(s, b"\x04\x06\x36\x00") == [3, 27]


def test_union_spec_fixture():
    # Spec example: union ["null","string"], "a" → 02 02 61; null → 00
    s = Schema(["null", "string"])
    assert encode_datum(s, "a") == b"\x02\x02a"
    assert encode_datum(s, None) == b"\x00"
    assert decode_datum(s, b"\x02\x02a") == "a"
    assert decode_datum(s, b"\x00") is None


# ---------------------------------------------------------------------------
# Round trips
# ---------------------------------------------------------------------------


def test_all_types_round_trip():
    s = Schema({
        "type": "record", "name": "Everything",
        "fields": [
            {"name": "b", "type": "boolean"},
            {"name": "i", "type": "int"},
            {"name": "l", "type": "long"},
            {"name": "f", "type": "float"},
            {"name": "d", "type": "double"},
            {"name": "by", "type": "bytes"},
            {"name": "s", "type": "string"},
            {"name": "e", "type": {"type": "enum", "name": "Color",
                                   "symbols": ["RED", "GREEN"]}},
            {"name": "fx", "type": {"type": "fixed", "name": "Sync",
                                    "size": 4}},
            {"name": "arr", "type": {"type": "array", "items": "double"}},
            {"name": "m", "type": {"type": "map", "values": "long"}},
            {"name": "u", "type": ["null", "double", "string"]},
            {"name": "nested", "type": ["null", "Everything"],
             "default": None},
        ],
    })
    datum = {
        "b": True, "i": -123, "l": 2**40, "f": 0.5, "d": -2.25,
        "by": b"\x00\xff", "s": "héllo", "e": "GREEN", "fx": b"abcd",
        "arr": [1.0, -2.0], "m": {"x": 1, "y": -9},
        "u": 3.5,
        "nested": {
            "b": False, "i": 0, "l": 0, "f": 0.0, "d": 0.0, "by": b"",
            "s": "", "e": "RED", "fx": b"zzzz", "arr": [], "m": {},
            "u": None, "nested": None,
        },
    }
    assert decode_datum(s, encode_datum(s, datum)) == datum


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_container_round_trip(tmp_path, codec):
    s = Schema({
        "type": "record", "name": "Point",
        "fields": [{"name": "x", "type": "double"},
                   {"name": "y", "type": "double"}],
    })
    records = [{"x": float(i), "y": float(-i)} for i in range(1000)]
    path = str(tmp_path / "points.avro")
    n = write_container(path, s, records, codec=codec,
                        records_per_block=64)   # multi-block
    assert n == 1000
    schema, got = read_container(path)
    assert schema.root["name"] == "Point"
    assert list(got) == records


def test_container_rejects_bad_magic(tmp_path):
    path = str(tmp_path / "bad.avro")
    with open(path, "wb") as f:
        f.write(b"NOPE" + b"\x00" * 32)
    with pytest.raises(ValueError, match="container"):
        read_container(path)


# ---------------------------------------------------------------------------
# Photon-parity schemas
# ---------------------------------------------------------------------------


def test_training_examples_round_trip(tmp_path):
    recs = [
        {"label": 1.0, "weight": 2.0, "offset": 0.5,
         "features": {"global": [("age", "", 0.3), ("geo", "us", 1.0)],
                      "user": [("clicks", "7d", 4.0)]},
         "ids": {"userId": "u1"}},
        {"label": 0.0,
         "features": {"global": [("age", "", -1.0)], "user": []},
         "ids": {"userId": "u2"}},
    ]
    path = str(tmp_path / "train.avro")
    n = write_avro_dataset(path, recs, feature_bags=("global", "user"),
                           id_fields=("userId",))
    assert n == 2
    got = list(iter_avro_dataset(path))       # bags/ids introspected
    assert got[0]["label"] == 1.0
    assert got[0]["weight"] == 2.0
    assert got[0]["offset"] == 0.5
    assert got[0]["features"]["global"] == [("age", "", 0.3),
                                            ("geo", "us", 1.0)]
    assert got[0]["ids"] == {"userId": "u1"}
    assert got[1]["weight"] == 1.0            # default applied
    assert got[1]["features"]["user"] == []
    assert got[1]["ids"] == {"userId": "u2"}


def test_avro_reads_through_game_dataset_pipeline(tmp_path):
    """The .avro file flows through the same index/ETL path as JSONL."""
    from photon_ml_tpu.io.dataset import (
        build_index_maps,
        detect_format,
        read_game_dataset,
    )

    recs = [
        {"label": float(i % 2),
         "features": {"g": [("f%d" % (i % 3), "", 1.0 + i)]},
         "ids": {"userId": "u%d" % (i % 2)}}
        for i in range(6)
    ]
    path = str(tmp_path / "data.avro")
    write_avro_dataset(path, recs, feature_bags=("g",),
                       id_fields=("userId",))
    assert detect_format(path, "auto") == "avro"
    fmaps, emaps = build_index_maps(path, ["g"], ["userId"])
    assert len(fmaps["g"]) == 3 and len(emaps["userId"]) == 2
    ds = read_game_dataset(path, fmaps, emaps)
    assert ds.n == 6
    np.testing.assert_array_equal(
        ds.labels, np.asarray([0, 1, 0, 1, 0, 1], np.float32))
    assert set(ds.entity_ids) == {"userId"}


def test_model_avro_round_trip(tmp_path):
    from photon_ml_tpu.io.index_map import IndexMap, feature_key

    imap = IndexMap(index={feature_key("age"): 0,
                           feature_key("geo", "us"): 1,
                           feature_key("zero"): 2})
    names = imap.names()

    def index_to_key(i):
        key = names[i]
        return (key.split("\x1f") + [""])[:2] if "\x1f" in key else (key, "")

    means = np.asarray([0.5, -1.5, 0.0], np.float32)
    var = np.asarray([0.1, 0.2, 0.0], np.float32)
    path = str(tmp_path / "model.avro")
    write_model_avro(path, "fe", means, index_to_key, variances=var,
                     loss_function="logisticLoss")

    model_id, got_means, got_var = read_model_avro(
        path, lambda n, t: imap.get_feature(n, t), dim=3
    )
    assert model_id == "fe"
    np.testing.assert_allclose(got_means, means, rtol=1e-6)
    np.testing.assert_allclose(got_var, var, rtol=1e-6)


def test_schema_by_name_reference():
    s = training_example_schema(("a", "b"), ("uid",))
    # Second bag refers to NameTermValueAvro by name — still decodable.
    raw = encode_datum(s, {
        "label": 1.0, "weight": 1.0, "offset": 0.0,
        "a": [{"name": "x", "term": "", "value": 1.0}],
        "b": [{"name": "y", "term": "t", "value": 2.0}],
        "uid": "e9",
    })
    back = decode_datum(s, raw)
    assert back["b"] == [{"name": "y", "term": "t", "value": 2.0}]
    assert back["uid"] == "e9"


def test_bayesian_model_schema_has_reference_fields():
    s = bayesian_linear_model_schema()
    fields = {f["name"] for f in s.root["fields"]}
    assert {"modelId", "lossFunction", "means", "variances"} <= fields


def test_export_model_avro_round_trip(tmp_path):
    import jax.numpy as jnp

    from photon_ml_tpu.game.dataset import EntityGrouping
    from photon_ml_tpu.io.avro import read_container
    from photon_ml_tpu.io.index_map import IndexMap, feature_key
    from photon_ml_tpu.io.model_io import export_model_avro
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.models.glm import TaskType

    gmap = IndexMap(index={feature_key("age"): 0,
                           feature_key("geo", "us"): 1})
    umap = IndexMap(index={feature_key("clicks"): 0,
                           feature_key("views"): 1})

    grouping = EntityGrouping(
        n_examples=0,
        entity_ids=np.asarray([11, 42]),
        entity_counts=np.asarray([3, 2]),
        entity_bucket=np.asarray([0, 0]),
        entity_slot=np.asarray([0, 1]),
        capacities=[4],
        n_entities=[2],
        example_bucket=np.empty(0, np.int64),
        example_row=np.empty(0, np.int64),
        example_col=np.empty(0, np.int64),
    )
    model = GameModel(models={
        "fixed": FixedEffectModel(
            coefficients=Coefficients(
                means=jnp.asarray([0.5, -1.0, 0.25])),  # +intercept col
            feature_shard="global",
            intercept=True,
        ),
        "perUser": RandomEffectModel(
            coefficient_blocks=[jnp.asarray([[1.0, 0.0], [0.0, -2.0]])],
            grouping=grouping,
            feature_shard="user",
            entity_key="userId",
        ),
    })
    paths = export_model_avro(
        model, TaskType.LOGISTIC_REGRESSION,
        {"global": gmap, "user": umap}, str(tmp_path),
    )
    assert len(paths) == 2

    # Fixed effect: read back through the (name, term) keying, intercept
    # in the extra column.
    def key_to_index(n, t):
        if n == "(INTERCEPT)":
            return 2
        return gmap.get_feature(n, t)

    model_id, means, _ = read_model_avro(
        str(tmp_path / "fixed.avro"), key_to_index, dim=3)
    assert model_id == "fixed"
    np.testing.assert_allclose(means, [0.5, -1.0, 0.25], rtol=1e-6)

    # Random effect: one record per entity, sparse means.
    _, recs = read_container(str(tmp_path / "perUser.avro"))
    by_id = {r["modelId"]: r for r in recs}
    assert set(by_id) == {"11", "42"}
    assert by_id["11"]["means"] == [
        {"name": "clicks", "term": "", "value": 1.0}]
    assert by_id["42"]["means"] == [
        {"name": "views", "term": "", "value": -2.0}]


# ---------------------------------------------------------------------------
# Schema resolution (evolution): writer-layout data → reader shape
# (round-4 verdict item #8; Avro spec §"Schema Resolution")
# ---------------------------------------------------------------------------


def _rec_schema(fields, name="R"):
    return {"type": "record", "name": name, "fields": fields}


def test_resolution_added_field_default_and_dropped_field(tmp_path):
    from photon_ml_tpu.io.avro import read_container, write_container

    writer = _rec_schema([
        {"name": "a", "type": "int"},
        {"name": "gone", "type": "string"},   # dropped by the reader
    ])
    reader = _rec_schema([
        {"name": "a", "type": "int"},
        {"name": "added", "type": "double", "default": 2.5},
    ])
    p = str(tmp_path / "evo.avro")
    write_container(p, writer, [{"a": 1, "gone": "x"},
                                {"a": 2, "gone": "yy"}])
    _, recs = read_container(p, reader_schema=reader)
    assert list(recs) == [{"a": 1, "added": 2.5}, {"a": 2, "added": 2.5}]
    # missing reader field with NO default is a loud error
    bad = _rec_schema([{"name": "nope", "type": "int"}])
    _, recs = read_container(p, reader_schema=bad)
    with pytest.raises(TypeError, match="no default"):
        list(recs)


def test_resolution_promotions_and_union(tmp_path):
    from photon_ml_tpu.io.avro import read_container, write_container

    writer = _rec_schema([
        {"name": "i", "type": "int"},
        {"name": "f", "type": "float"},
        {"name": "s", "type": "string"},
        {"name": "u", "type": ["null", "int"]},
    ])
    reader = _rec_schema([
        {"name": "i", "type": "double"},          # int → double
        {"name": "f", "type": "double"},          # float → double
        {"name": "s", "type": "bytes"},           # string → bytes
        {"name": "u", "type": ["null", "long"]},  # union branch promote
    ])
    p = str(tmp_path / "promo.avro")
    write_container(p, writer,
                    [{"i": 3, "f": 1.5, "s": "hi", "u": 7},
                     {"i": -1, "f": 0.25, "s": "", "u": None}])
    _, recs = read_container(p, reader_schema=reader)
    got = list(recs)
    assert got[0] == {"i": 3.0, "f": 1.5, "s": b"hi", "u": 7}
    assert got[1] == {"i": -1.0, "f": 0.25, "s": b"", "u": None}
    assert isinstance(got[0]["i"], float)


def test_resolution_nested_records_and_arrays(tmp_path):
    from photon_ml_tpu.io.avro import read_container, write_container

    inner_w = _rec_schema([{"name": "x", "type": "int"},
                           {"name": "old", "type": "int"}], name="Inner")
    inner_r = _rec_schema([{"name": "x", "type": "long"},
                           {"name": "y", "type": "string",
                            "default": "d"}], name="Inner")
    writer = _rec_schema([{"name": "items",
                           "type": {"type": "array", "items": inner_w}}])
    reader = _rec_schema([{"name": "items",
                           "type": {"type": "array", "items": inner_r}}])
    p = str(tmp_path / "nested.avro")
    write_container(p, writer, [
        {"items": [{"x": 1, "old": 9}, {"x": 2, "old": 8}]},
    ])
    _, recs = read_container(p, reader_schema=reader)
    assert list(recs) == [{"items": [{"x": 1, "y": "d"},
                                     {"x": 2, "y": "d"}]}]


def test_resolution_evolved_model_file(tmp_path):
    """The framework's own model files stay readable when the reader's
    model schema gains a defaulted field — the interop case the
    reference's Avro dependency handles (SURVEY §2.4 AvroDataReader)."""
    import json

    from photon_ml_tpu.io.avro import read_container, write_container
    from photon_ml_tpu.io.avro_schemas import bayesian_linear_model_schema

    writer = bayesian_linear_model_schema()
    p = str(tmp_path / "m.avro")
    write_container(p, writer, [
        {"modelId": "1", "modelClass": "", "lossFunction": "",
         "means": [{"name": "f0", "term": "", "value": 0.5}],
         "variances": None},
    ])
    evolved = json.loads(writer.to_json())
    evolved["fields"].append(
        {"name": "trainedAt", "type": "long", "default": 0})
    _, recs = read_container(p, reader_schema=evolved)
    (rec,) = list(recs)
    assert rec["trainedAt"] == 0
    assert rec["means"][0]["value"] == 0.5


def test_resolution_fixed_size_mismatch_is_loud(tmp_path):
    from photon_ml_tpu.io.avro import read_container, write_container

    writer = _rec_schema([{"name": "h", "type": {
        "type": "fixed", "name": "H", "size": 4}}])
    reader = _rec_schema([{"name": "h", "type": {
        "type": "fixed", "name": "H", "size": 8}}])
    p = str(tmp_path / "fix.avro")
    write_container(p, writer, [{"h": b"abcd"}])
    _, recs = read_container(p, reader_schema=reader)
    with pytest.raises(TypeError, match="size mismatch"):
        list(recs)


def test_resolution_aliases(tmp_path):
    """Spec §Aliases: a reader that RENAMED a field (or a named type)
    still reads writer data under the old name via aliases."""
    from photon_ml_tpu.io.avro import read_container, write_container

    writer = {"type": "record", "name": "Old", "fields": [
        {"name": "score", "type": "double"},
        {"name": "kind", "type": {"type": "enum", "name": "KindOld",
                                  "symbols": ["A", "B"]}},
    ]}
    reader = {"type": "record", "name": "New", "aliases": ["Old"],
              "fields": [
        {"name": "value", "type": "double", "aliases": ["score"]},
        {"name": "kind", "type": {"type": "enum", "name": "Kind",
                                  "aliases": ["KindOld"],
                                  "symbols": ["A", "B"]}},
    ]}
    p = str(tmp_path / "alias.avro")
    write_container(p, writer, [{"score": 1.5, "kind": "B"}])
    _, recs = read_container(p, reader_schema=reader)
    assert list(recs) == [{"value": 1.5, "kind": "B"}]


def test_resolution_alias_named_type_inside_reader_union(tmp_path):
    """A RENAMED named type nested inside a reader union resolves via
    aliases (advisor finding: _schemas_match ignored reader aliases, so
    the rename that works outside a union failed branch matching with
    'matches no reader union branch')."""
    from photon_ml_tpu.io.avro import read_container, write_container

    writer = {"type": "record", "name": "Top", "fields": [
        {"name": "inner", "type": {
            "type": "record", "name": "OldInner", "fields": [
                {"name": "x", "type": "long"},
            ]}},
        {"name": "tag", "type": {"type": "enum", "name": "OldTag",
                                 "symbols": ["P", "Q"]}},
    ]}
    reader = {"type": "record", "name": "Top", "fields": [
        {"name": "inner", "type": ["null", {
            "type": "record", "name": "NewInner",
            "aliases": ["OldInner"], "fields": [
                {"name": "x", "type": "long"},
            ]}]},
        {"name": "tag", "type": ["null", {
            "type": "enum", "name": "NewTag", "aliases": ["OldTag"],
            "symbols": ["P", "Q"]}]},
    ]}
    p = str(tmp_path / "union_alias.avro")
    write_container(p, writer, [{"inner": {"x": 7}, "tag": "Q"}])
    _, recs = read_container(p, reader_schema=reader)
    assert list(recs) == [{"inner": {"x": 7}, "tag": "Q"}]
