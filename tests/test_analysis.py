"""photon-lint checker suite (ISSUE 6): known-bad fixture snippets per
rule (positive + negative + waiver cases), the whole-repo clean-pass
gate, and the CLI contract (rc 0/1, JSON last line, github format).

``test_repo_clean`` IS the CI wiring: ``pytest tests/`` fails if any
package file regresses a lint contract, exactly like a broken unit
test.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from photon_ml_tpu.analysis.checkers import (
    RULES,
    check_slow_unmarked,
    check_source,
    run_checks,
)

pytestmark = pytest.mark.fast

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(violations):
    return [v.rule for v in violations]


def _src(snippet: str) -> str:
    return textwrap.dedent(snippet).lstrip("\n")


# ---------------------------------------------------------------------------
# jit-in-function
# ---------------------------------------------------------------------------


def test_jit_in_function_flags_body_construction():
    vs = check_source(_src("""
        import jax

        def g(x):
            return x

        def scorer(x):
            f = jax.jit(g)
            return f(x)
    """))
    assert _rules(vs) == ["jit-in-function"]
    assert vs[0].line == 7


def test_jit_in_function_flags_partial_and_loops():
    vs = check_source(_src("""
        import jax
        from functools import partial

        def g(x):
            return x

        def build():
            return partial(jax.jit, static_argnums=0)(g)

        fns = []
        for _ in range(3):
            fns.append(jax.jit(g))
    """))
    assert _rules(vs) == ["jit-in-function", "jit-in-function"]


def test_jit_in_function_flags_nested_decorated_def():
    vs = check_source(_src("""
        import jax

        def outer():
            @jax.jit
            def inner(x):
                return x
            return inner
    """))
    assert _rules(vs) == ["jit-in-function"]


def test_jit_at_module_level_is_clean():
    vs = check_source(_src("""
        import jax
        from functools import partial

        def g(x):
            return x

        f1 = jax.jit(g)
        f2 = jax.jit(lambda x: x + 1)

        @jax.jit
        def f3(x):
            return x

        @partial(jax.jit, static_argnums=(0,))
        def f4(k, x):
            return x * k
    """))
    assert vs == []


def test_jit_in_memoized_factory_is_clean():
    vs = check_source(_src("""
        import functools
        import jax

        def g(x):
            return x

        @functools.lru_cache(maxsize=None)
        def jitted():
            return jax.jit(g)
    """))
    assert vs == []


def test_jit_waiver_with_reason_suppresses():
    vs = check_source(_src("""
        import jax

        def g(x):
            return x

        def harness():
            # photon-lint: disable=jit-in-function (measured by design)
            return jax.jit(g)
    """))
    assert vs == []


def test_waiver_without_reason_is_rejected():
    vs = check_source(_src("""
        import jax

        def g(x):
            return x

        def harness():
            return jax.jit(g)  # photon-lint: disable=jit-in-function
    """))
    assert sorted(_rules(vs)) == ["bad-waiver", "jit-in-function"]


# ---------------------------------------------------------------------------
# tracer-hygiene
# ---------------------------------------------------------------------------


def test_tracer_hygiene_flags_numpy_on_traced():
    vs = check_source(_src("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.sum(x)
    """))
    assert _rules(vs) == ["tracer-hygiene"]
    assert "np.sum" in vs[0].message


def test_tracer_hygiene_flags_casts_and_item():
    vs = check_source(_src("""
        import jax

        @jax.jit
        def f(x):
            a = float(x)
            b = int(x)
            c = x.item()
            return a + b + c
    """))
    assert _rules(vs) == ["tracer-hygiene"] * 3


def test_tracer_hygiene_flags_branch_on_traced():
    vs = check_source(_src("""
        import jax

        @jax.jit
        def f(x):
            y = x * 2
            if y > 0:
                return y
            return -y
    """))
    assert _rules(vs) == ["tracer-hygiene"]
    assert "branch" in vs[0].message


def test_tracer_hygiene_follows_module_level_jit_assignment():
    vs = check_source(_src("""
        import jax
        import numpy as np

        def f(x):
            return np.asarray(x)

        f_jit = jax.jit(f)
    """))
    assert _rules(vs) == ["tracer-hygiene"]


def test_tracer_hygiene_respects_static_argnums_and_identity():
    vs = check_source(_src("""
        import jax
        import jax.numpy as jnp
        from functools import partial

        @partial(jax.jit, static_argnums=(0,))
        def f(cfg, x, l1=None):
            if cfg.use_bias:          # static arg: trace-time branch OK
                x = x + 1.0
            if l1 is None:            # identity test never reads value
                return jnp.sum(x)
            return jnp.sum(x) + jnp.sum(l1)
    """))
    assert vs == []


def test_tracer_hygiene_clean_jnp_body():
    vs = check_source(_src("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = jnp.where(x > 0, x, -x)
            return jnp.sum(y)
    """))
    assert vs == []


# ---------------------------------------------------------------------------
# unlocked-shared-write
# ---------------------------------------------------------------------------

_THREADED_BAD = """
    import threading

    class Worker:
        def __init__(self):
            self._thread = None
            self.result = None

        def start(self):
            self._thread = threading.Thread(target=self._run)
            self._thread.start()

        def _run(self):
            self.result = 42

        def get(self):
            return self.result
"""


def test_thread_discipline_flags_unlocked_shared_write():
    vs = check_source(_src(_THREADED_BAD))
    assert _rules(vs) == ["unlocked-shared-write"]
    assert "Worker.result" in vs[0].message


def test_thread_discipline_accepts_locked_and_queue():
    vs = check_source(_src("""
        import queue
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()
                self._thread = None
                self.result = None

            def start(self):
                self._thread = threading.Thread(target=self._run)
                self._thread.start()

            def _run(self):
                with self._lock:
                    self.result = 42
                self._q.put(42)

            def get(self):
                with self._lock:
                    return self.result
    """))
    assert vs == []


def test_thread_discipline_lock_owner_must_hold_lock():
    vs = check_source(_src("""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self.loads = 0
                self.spills = 0

            def load(self):
                with self._lock:
                    self.loads += 1

            def spill(self):
                self.spills += 1
    """))
    assert _rules(vs) == ["unlocked-shared-write"]
    assert "Store.spills" in vs[0].message


def test_thread_discipline_waiver():
    bad = _src(_THREADED_BAD).replace(
        "self.result = 42",
        "self.result = 42  "
        "# photon-lint: disable=unlocked-shared-write (join fences it)",
        1)
    assert check_source(bad) == []


# ---------------------------------------------------------------------------
# accumulator-dtype
# ---------------------------------------------------------------------------


def test_accumulator_dtype_flags_device_fold():
    vs = check_source(_src("""
        import jax.numpy as jnp

        class StreamingLoss:
            def __init__(self):
                self._num = 0.0

            def update(self, scores):
                self._num += jnp.sum(scores)

            def result(self):
                return self._num
    """))
    assert _rules(vs) == ["accumulator-dtype"]
    assert "jnp" in vs[0].message


def test_accumulator_dtype_flags_f32_fold():
    vs = check_source(_src("""
        import numpy as np

        class StreamingLoss:
            def __init__(self):
                self._num = 0.0

            def update(self, scores):
                self._num += np.sum(scores.astype(np.float32))

            def result(self):
                return self._num
    """))
    assert _rules(vs) == ["accumulator-dtype"]


def test_accumulator_dtype_accepts_host_f64():
    vs = check_source(_src("""
        import numpy as np

        class StreamingLoss:
            def __init__(self):
                self._num = 0.0
                self._den = 0.0

            def update(self, scores, weights):
                w = np.asarray(weights, np.float64)
                self._num += float(np.sum(w * scores))
                self._den += float(np.sum(w))

            def result(self):
                return self._num / self._den
    """))
    assert vs == []


def test_accumulator_dtype_ignores_non_accumulator_classes():
    vs = check_source(_src("""
        import jax.numpy as jnp

        class NotAnAccumulator:
            def update(self, x):
                self._x += jnp.sum(x)   # no result(): protocol not met
    """))
    assert vs == []


# ---------------------------------------------------------------------------
# env-read
# ---------------------------------------------------------------------------


def test_env_read_flags_all_forms():
    vs = check_source(_src("""
        import os
        from os import environ

        a = os.environ.get("PHOTON_X")
        b = os.environ["PHOTON_Y"]
        c = os.getenv("PHOTON_Z")
        d = environ.get("PHOTON_W")
    """))
    assert _rules(vs) == ["env-read"] * 4


def test_env_read_sanctioned_in_config():
    vs = check_source(_src("""
        import os

        def read_env(name):
            return os.environ.get(name)
    """), path="photon_ml_tpu/config.py")
    assert vs == []


def test_env_read_waiver():
    vs = check_source(_src("""
        import os

        # photon-lint: disable=env-read (documented bootstrap read)
        a = os.environ.get("PHOTON_X")
    """))
    assert vs == []


# ---------------------------------------------------------------------------
# naked-clock
# ---------------------------------------------------------------------------


def test_naked_clock_flags_direct_subtraction():
    vs = check_source(_src("""
        import time

        def f():
            t0 = time.time()
            work()
            return time.time() - t0
    """))
    assert _rules(vs) == ["naked-clock"]
    assert vs[0].line == 6


def test_naked_clock_flags_assigned_name_and_self_attr():
    vs = check_source(_src("""
        import time

        class Budget:
            def __init__(self, budget_s):
                self.deadline = time.time() + budget_s

            def remaining(self):
                return self.deadline - time.time()

        def g():
            start = time.time()
            return later() - start
    """))
    assert _rules(vs) == ["naked-clock", "naked-clock"]


def test_naked_clock_accepts_monotonic_and_timestamps():
    vs = check_source(_src("""
        import time

        def f():
            t0 = time.perf_counter()
            started_at = time.time()     # epoch timestamp: legal
            record(started_at)
            dur = time.perf_counter() - t0
            m = time.monotonic()
            return dur, time.monotonic() - m
    """))
    assert vs == []


def test_naked_clock_taint_is_function_scoped():
    """A wall-clock assignment in one function must not flag another
    function's monotonic math on the same conventional name (review
    finding: a file-global taint set made `t0` radioactive
    everywhere)."""
    vs = check_source(_src("""
        import time

        def a():
            t0 = time.time()        # epoch timestamp, never subtracted
            record(t0)

        def b():
            t0 = time.perf_counter()
            work()
            return time.perf_counter() - t0
    """))
    assert vs == []


def test_naked_clock_waiver():
    vs = check_source(_src("""
        import time

        def f(remote_epoch):
            # photon-lint: disable=naked-clock (cross-process epoch delta)
            return time.time() - remote_epoch
    """))
    assert vs == []


# ---------------------------------------------------------------------------
# slow-unmarked (repo-level, recorded durations)
# ---------------------------------------------------------------------------


def test_slow_unmarked_against_recorded_durations(tmp_path):
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "test_things.py").write_text(_src("""
        import pytest

        @pytest.mark.slow
        def test_marked():
            pass

        def test_unmarked():
            pass

        def test_fast_one():
            pass
    """))
    (tests_dir / "tier1_durations.json").write_text(json.dumps({
        "durations": {
            "tests/test_things.py::test_marked": 19.0,
            "tests/test_things.py::test_unmarked[a]": 17.5,
            "tests/test_things.py::test_unmarked[b]": 1.0,
            "tests/test_things.py::test_fast_one": 0.2,
        }}))
    vs = list(check_slow_unmarked(str(tmp_path)))
    assert _rules(vs) == ["slow-unmarked"]
    assert "test_unmarked" in vs[0].message and "17.5" in vs[0].message


def test_slow_unmarked_not_fooled_by_slow_substring(tmp_path):
    """Only a real ``pytest.mark.slow`` counts — a skipif reason (or
    any decorator) merely CONTAINING "slow" must not satisfy the
    audit."""
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "test_things.py").write_text(_src("""
        import pytest

        @pytest.mark.skipif(False, reason="too slow without gpu")
        def test_heavy():
            pass
    """))
    (tests_dir / "tier1_durations.json").write_text(json.dumps(
        {"durations": {"tests/test_things.py::test_heavy": 30.0}}))
    vs = list(check_slow_unmarked(str(tmp_path)))
    assert _rules(vs) == ["slow-unmarked"]


def test_waiver_in_docstring_is_inert():
    """A waiver example quoted inside a string/docstring is not a real
    waiver: it must neither suppress the next code line nor be
    reported as a bad waiver."""
    vs = check_source(_src('''
        import os

        DOC = """
        Example:
            # photon-lint: disable=env-read (docs example)
        """
        a = os.environ.get("PHOTON_X")

        BAD_DOC = "# photon-lint: disable=env-read"
    '''))
    assert _rules(vs) == ["env-read"]


def test_slow_unmarked_class_based_nodeids(tmp_path):
    """Class-based node ids (file.py::TestCls::test_x) resolve to the
    method: a marked method passes, an unmarked sibling is flagged at
    its own def line (not line 1)."""
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "test_cls.py").write_text(_src("""
        import pytest

        class TestTransforms:
            @pytest.mark.slow
            def test_marked(self):
                pass

            def test_unmarked(self):
                pass
    """))
    (tests_dir / "tier1_durations.json").write_text(json.dumps(
        {"durations": {
            "tests/test_cls.py::TestTransforms::test_marked": 42.0,
            "tests/test_cls.py::TestTransforms::test_unmarked": 12.0,
        }}))
    vs = list(check_slow_unmarked(str(tmp_path)))
    assert _rules(vs) == ["slow-unmarked"]
    assert "test_unmarked" in vs[0].message and vs[0].line > 1


def test_slow_unmarked_accepts_module_pytestmark(tmp_path):
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "test_mod.py").write_text(_src("""
        import pytest

        pytestmark = pytest.mark.slow

        def test_heavy():
            pass
    """))
    (tests_dir / "tier1_durations.json").write_text(json.dumps(
        {"durations": {"tests/test_mod.py::test_heavy": 30.0}}))
    assert list(check_slow_unmarked(str(tmp_path))) == []


# ---------------------------------------------------------------------------
# metric-name (ISSUE 8)
# ---------------------------------------------------------------------------


def test_metric_name_flags_flat_and_mixed_case():
    vs = check_source(_src("""
        from photon_ml_tpu import telemetry

        def f(t):
            telemetry.count("sweeps")
            t.gauge("Queue.Depth", 3)
            telemetry.observe("solver.lsTrials", 0.5)
    """))
    assert _rules(vs) == ["metric-name"] * 3
    assert vs[0].line == 4 and "'sweeps'" in vs[0].message


def test_metric_name_accepts_dotted_lowercase_and_skips_non_registry():
    vs = check_source(_src("""
        from photon_ml_tpu import telemetry

        def f(t, line, items):
            telemetry.count("solver.sweeps")
            t.observe("prefetch.consumer_wait_s", 0.1)
            t.gauge("store.lru.window_hits", 2)
            line.count(",")             # str.count: not the registry
            items.count(3)              # list.count: not the registry
            telemetry.count(name_var)   # dynamic: caller's contract
    """))
    assert vs == []


def test_metric_name_session_methods_and_waiver():
    vs = check_source(_src("""
        class Telemetry:
            def emit(self):
                self._t.count("BadName")
                # photon-lint: disable=metric-name (legacy dashboard key)
                self._t.gauge("LegacyKey", 1)
                self.observe("also_flat", 2)
    """))
    assert _rules(vs) == ["metric-name", "metric-name"]
    assert {v.line for v in vs} == {3, 6}   # the waivered line is clean


# ---------------------------------------------------------------------------
# swallowed-exception (ISSUE 9)
# ---------------------------------------------------------------------------


def test_swallowed_exception_flags_silent_discards():
    vs = check_source(_src("""
        import os

        def cleanup(paths):
            for p in paths:
                try:
                    os.remove(p)
                except OSError:
                    pass

        def probe(path):
            try:
                return os.path.getsize(path)
            except OSError:
                return None

        def drain(q):
            while True:
                try:
                    return q.get_nowait()
                except Exception:
                    continue
    """))
    assert _rules(vs) == ["swallowed-exception"] * 3
    assert {v.line for v in vs} == {7, 13, 20}


def test_swallowed_exception_clean_when_reported_or_handled():
    vs = check_source(_src("""
        import logging
        import warnings

        logger = logging.getLogger(__name__)

        def a(fn):
            try:
                return fn()
            except OSError as e:
                logger.warning("fn failed: %r", e)
                return None

        def b(fn):
            try:
                return fn()
            except ValueError:
                raise RuntimeError("bad input")

        def c(v, enum_cls):
            try:
                return enum_cls(v)
            except ValueError:
                return enum_cls[v]      # real fallback: handled

        def d(fn):
            try:
                fn()
            except Exception as e:
                warnings.warn(str(e))
    """))
    assert vs == []


def test_swallowed_exception_waiver_with_reason():
    vs = check_source(_src("""
        import os

        def cleanup(p):
            try:
                os.remove(p)
            except OSError:  # photon-lint: disable=swallowed-exception (idempotent tmp cleanup)
                pass
            try:
                os.remove(p + ".bak")
            except OSError:
                pass
    """))
    assert _rules(vs) == ["swallowed-exception"]
    assert vs[0].line == 10


# ---------------------------------------------------------------------------
# eternal-wait (ISSUE 13)
# ---------------------------------------------------------------------------


def test_eternal_wait_flags_unbounded_waits_in_thread_classes():
    vs = check_source(_src("""
        import queue
        import threading

        class Worker:
            def __init__(self):
                self._q = queue.Queue()
                self._done = threading.Event()
                self._thread = threading.Thread(target=self._run)
                self._thread.start()

            def _run(self):
                item = self._q.get()
                return item

            def wait_done(self):
                self._done.wait()

            def close(self):
                self._thread.join()
    """))
    assert _rules(vs) == ["eternal-wait"] * 3
    assert "blocks with no timeout" in vs[0].message


def test_eternal_wait_clean_with_timeouts_and_outside_threads():
    vs = check_source(_src("""
        import queue
        import threading

        class Worker:
            def __init__(self):
                self._q = queue.Queue()
                self._done = threading.Event()
                self._thread = threading.Thread(target=self._run)

            def _run(self):
                return self._q.get(timeout=1.0)

            def wait_done(self):
                self._done.wait(5.0)

            def close(self):
                self._thread.join(timeout=10.0)

            def config(self, d):
                return d.get("key")        # dict get: has args

        class NotThreaded:
            def __init__(self):
                self._q = queue.Queue()

            def drain(self):
                return self._q.get()       # no thread spawned here
    """))
    assert vs == []


def test_eternal_wait_flags_socket_recv():
    vs = check_source(_src("""
        import threading

        class Net:
            def __init__(self, sock):
                self._sock = sock
                threading.Thread(target=self._run).start()

            def _run(self):
                return self._sock.recv(4096)
    """))
    assert _rules(vs) == ["eternal-wait"]
    assert "settimeout" in vs[0].message


def test_eternal_wait_waiver_with_reason():
    vs = check_source(_src("""
        import queue
        import threading

        class Worker:
            def __init__(self):
                self._q = queue.Queue()
                threading.Thread(target=self._run).start()

            def _run(self):
                # photon-lint: disable=eternal-wait (close() always enqueues the sentinel)
                return self._q.get()
    """))
    assert vs == []


# ---------------------------------------------------------------------------
# collective-in-host-branch
# ---------------------------------------------------------------------------


def test_collective_in_host_branch_flags_if():
    vs = check_source(_src("""
        import jax

        def reduce_partials(x):
            if jax.process_index() == 0:
                return jax.lax.psum(x, "hosts")
            return x
    """))
    assert _rules(vs) == ["collective-in-host-branch"]
    assert vs[0].line == 5


def test_collective_in_host_branch_flags_host_id_and_ifexp():
    vs = check_source(_src("""
        from jax import lax

        def f(ctx, x):
            while ctx.host_id > 0:
                x = lax.all_gather(x, "hosts")
            return lax.pmean(x, "h") if ctx.host_id else x
    """))
    assert _rules(vs) == ["collective-in-host-branch",
                          "collective-in-host-branch"]


def test_collective_in_host_branch_clean_cases():
    vs = check_source(_src("""
        import jax

        def uniform(x):
            # process_count() is the same on every host: not divergent.
            if jax.process_count() > 1:
                return jax.lax.psum(x, "hosts")
            return x

        def hoisted(x):
            total = jax.lax.psum(x, "hosts")
            if jax.process_index() == 0:
                print(total)
            return total

        def defined_not_run(x):
            if jax.process_index() == 0:
                def helper(y):
                    # a def boundary ends the lexical branch
                    return jax.lax.psum(y, "hosts")
                return helper
            return None
    """))
    assert vs == []


def test_collective_in_host_branch_waiver():
    vs = check_source(_src("""
        import jax

        def f(x):
            if jax.process_index() == 0:
                return jax.lax.psum(x, "hosts")  # photon-lint: disable=collective-in-host-branch (single-host test harness, no peers to deadlock)
            return x
    """))
    assert vs == []


# ---------------------------------------------------------------------------
# while-loop-carry-dtype
# ---------------------------------------------------------------------------


def test_while_carry_dtype_flags_float_literal_into_int_carry():
    vs = check_source(_src("""
        import jax
        import jax.numpy as jnp

        def count(x):
            def body(carry):
                it, v = carry
                return it + 1.0, v * 0.5
            return jax.lax.while_loop(lambda c: c[0] < 8, body,
                                      (0, x))
    """))
    assert _rules(vs) == ["while-loop-carry-dtype"]
    assert vs[0].line == 7
    assert "int carry 'it'" in vs[0].message


def test_while_carry_dtype_flags_bool_and_f64_folds():
    vs = check_source(_src("""
        from jax import lax
        import numpy as np

        def run(x):
            def body(carry):
                done, acc = carry
                done = done + 1
                acc = acc * np.float64(0.5)
                return done, acc
            return lax.while_loop(lambda c: ~c[0], body,
                                  (False, lax.full((3,), 0.0)))
    """))
    assert sorted(_rules(vs)) == ["while-loop-carry-dtype",
                                  "while-loop-carry-dtype"]
    assert "bool carry 'done'" in vs[0].message
    assert "float64 cast" in vs[1].message


def test_while_carry_dtype_flags_single_leaf_lambda_body():
    vs = check_source(_src("""
        import jax

        def spin(n):
            return jax.lax.while_loop(lambda it: it < n,
                                      lambda it: it + 0.5, 0)
    """))
    assert _rules(vs) == ["while-loop-carry-dtype"]


def test_while_carry_dtype_clean_cases():
    vs = check_source(_src("""
        import jax
        import jax.numpy as jnp

        def clean(x, w0):
            def body(carry):
                it, v = carry
                # int literal into int carry keeps the dtype.
                return it + 1, v * 0.5
            out = jax.lax.while_loop(lambda c: c[0] < 8, body,
                                     (0, x))

            def body2(carry):
                a, b = carry
                return a + 1.0, b * 2.0
            # Name init: dtype not statically inferable, never flagged.
            return jax.lax.while_loop(lambda c: c[0] < 9.0, body2,
                                      (w0, out[1]))
    """))
    assert vs == []


def test_while_carry_dtype_waiver():
    vs = check_source(_src("""
        import jax

        def spin(n):
            return jax.lax.while_loop(
                lambda it: it < n,
                lambda it: it + 1.0, 0)  # photon-lint: disable=while-loop-carry-dtype (carry is rebound to int inside the cond wrapper)
    """))
    assert vs == []


# ---------------------------------------------------------------------------
# the acceptance corpus + whole-repo gate + CLI contract
# ---------------------------------------------------------------------------

_CORPUS = """
    import os
    import threading
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np


    def per_call(x):
        return jax.jit(lambda y: y)(x)


    def wall_clock_duration():
        t0 = time.time()
        per_call(jnp.ones(3))
        return time.time() - t0


    @jax.jit
    def concretizes(x):
        return float(np.sum(x))


    class Pipeline:
        def __init__(self):
            self._thread = None
            self.state = 0

        def start(self):
            self._thread = threading.Thread(target=self._run)
            self._thread.start()

        def _run(self):
            self.state = 1

        def poll(self):
            return self.state

        def join(self):
            self._thread.join()


    class StreamingThing:
        def __init__(self):
            self._acc = 0.0

        def update(self, x):
            self._acc += jnp.sum(x)

        def result(self):
            return self._acc


    FLAG = os.environ.get("SOME_UNSANCTIONED_FLAG")


    def best_effort(path):
        try:
            os.remove(path)
        except OSError:
            pass


    def counter_loop(x):
        def body(carry):
            it, v = carry
            return it + 1.0, v * 0.5
        return jax.lax.while_loop(lambda c: c[0] < 8, body, (0, x))
"""


def test_fixture_corpus_detects_five_distinct_rules():
    """The ISSUE acceptance check: one source exercising the suite
    trips >= 5 distinct rules."""
    vs = check_source(_src(_CORPUS))
    distinct = set(_rules(vs))
    assert {"jit-in-function", "tracer-hygiene", "unlocked-shared-write",
            "accumulator-dtype", "env-read", "naked-clock",
            "swallowed-exception", "eternal-wait",
            "while-loop-carry-dtype"} <= distinct
    assert len(distinct) >= 9


def test_repo_clean():
    """Tier-1 gate: the package (and the recorded-duration audit) is
    lint-clean.  A failure here reads exactly like the CLI output —
    fix the violation or add a reasoned waiver."""
    violations, n_files = run_checks(REPO_ROOT)
    assert n_files > 50
    assert violations == [], "\n".join(str(v) for v in violations)


def test_cli_contract_clean_and_violating(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # Clean run over the repo: rc 0 + JSON last line.
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.analysis"],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    tail = json.loads(proc.stdout.strip().splitlines()[-1])
    assert tail["ok"] is True and tail["violations"] == 0
    assert set(tail["rules_run"]) == set(RULES)

    # Violating file: rc 1, one line per violation, JSON tail counts.
    bad = tmp_path / "bad.py"
    bad.write_text(_src(_CORPUS))
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.analysis", str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env)
    assert proc.returncode == 1
    lines = proc.stdout.strip().splitlines()
    tail = json.loads(lines[-1])
    assert tail["ok"] is False
    assert tail["violations"] == len(lines) - 1 >= 5
    assert all(":" in ln for ln in lines[:-1])


def test_cli_github_format(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_src("""
        import os

        FLAG = os.environ.get("SOME_FLAG")
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.analysis",
         "--format", "github", str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 1
    lines = proc.stdout.strip().splitlines()
    assert lines[0].startswith("::error file=")
    assert "title=env-read" in lines[0]
    # Annotation paths are emitted repo-relative (GitHub only attaches
    # `file=` values relative to the workspace), never absolute.
    assert "file=/" not in lines[0]
    json.loads(lines[-1])


def test_cli_rule_filter(tmp_path):
    bad = tmp_path / "bad.py"
    # A reasonless waiver rides along: the bad-waiver meta-rule must
    # honor the filter too (a job scoped to env-read must not fail on
    # an unrelated finding class).
    bad.write_text(_src(_CORPUS) + "\nX = 1  # photon-lint: disable=env-read\n")
    proc = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.analysis",
         "--rules", "env-read", str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 1
    tail = json.loads(proc.stdout.strip().splitlines()[-1])
    assert tail["by_rule"] == {"env-read": 1}


def test_run_checks_explicit_files_still_audit_slow(tmp_path):
    """Passing explicit files must not silently drop a requested
    slow-unmarked audit — it runs scoped to those files."""
    from photon_ml_tpu.analysis.checkers import run_checks

    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    tfile = tests_dir / "test_big.py"
    tfile.write_text("def test_heavy():\n    pass\n")
    (tests_dir / "tier1_durations.json").write_text(json.dumps(
        {"durations": {"tests/test_big.py::test_heavy": 25.0}}))
    vs, _n = run_checks(str(tmp_path), rules={"slow-unmarked"},
                        files=[str(tfile)])
    assert [v.rule for v in vs] == ["slow-unmarked"]
    other = tests_dir / "test_other.py"
    other.write_text("def test_ok():\n    pass\n")
    vs, _n = run_checks(str(tmp_path), rules={"slow-unmarked"},
                        files=[str(other)])
    assert vs == []   # scoped: the flagged file was not requested
