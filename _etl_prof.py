import logging, time, sys
logging.basicConfig(level=logging.DEBUG, stream=sys.stderr,
                    format="%(message)s")
logging.getLogger("jax").setLevel(logging.WARNING)
import numpy as np
n, d, k = 1_000_000, 100_000, 30
rng = np.random.default_rng(0)
block = d // k
cols = ((np.arange(k, dtype=np.int64) * block)[None, :] + rng.integers(0, block, (n, k))).astype(np.int32)
vals = rng.normal(0, 1, (n, k)).astype(np.float32)
from photon_ml_tpu.data.grr import build_grr_direction
r_idx = np.repeat(np.arange(n, dtype=np.int64), k)
c = cols.reshape(-1).astype(np.int64)
v = vals.reshape(-1)
t0 = time.time()
d_row = build_grr_direction(idx=c, seg=r_idx, val=v, table_len=d, n_segments=n)
print(f"row dir total {time.time()-t0:.1f}s", file=sys.stderr)
t0 = time.time()
d_col = build_grr_direction(idx=r_idx, seg=c, val=v, table_len=n, n_segments=d)
print(f"col dir total {time.time()-t0:.1f}s", file=sys.stderr)
