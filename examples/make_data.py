"""Generate the small example datasets the configs in this directory use.

Usage::

    python examples/make_data.py          # writes examples/data/*

Produces:
  - ``a1a_like.libsvm`` / ``a1a_like.t.libsvm`` — binary-classification
    LIBSVM fixtures shaped like the reference's a1a (Adult) examples
    (photon-ml ``examples`` [expected path, mount unavailable — see
    SURVEY.md §2.8]).
  - ``game_train.jsonl`` / ``game_valid.jsonl`` — movielens-shaped GAME
    records (global features + per-user random effect), the reference's
    GAME training-tutorial shape.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from photon_ml_tpu.io.dataset import write_game_dataset  # noqa: E402
from photon_ml_tpu.io.libsvm import write_libsvm  # noqa: E402
from photon_ml_tpu.utils.synthetic import (  # noqa: E402
    make_a1a_like,
    make_movielens_like,
)


def main(out_dir=None):
    out = out_dir or os.path.join(os.path.dirname(__file__), "data")
    os.makedirs(out, exist_ok=True)

    rows, labels, _ = make_a1a_like(n=2000, seed=5)
    write_libsvm(os.path.join(out, "a1a_like.libsvm"),
                 rows[:1600], np.where(labels[:1600] > 0, 1, -1))
    write_libsvm(os.path.join(out, "a1a_like.t.libsvm"),
                 rows[1600:], np.where(labels[1600:] > 0, 1, -1))

    data = make_movielens_like(n_users=40, n_items=12, n_obs=2400,
                               dim_global=8, seed=9)
    n_tr = 2000
    for path, sl in (("game_train.jsonl", slice(0, n_tr)),
                     ("game_valid.jsonl", slice(n_tr, None))):
        write_game_dataset(
            os.path.join(out, path),
            labels=data["labels"][sl],
            features={
                "global": data["x"][sl].astype(np.float32),
                "user_re": np.ones((len(data["labels"][sl]), 1),
                                   np.float32),
            },
            ids={"userId": data["user_ids"][sl]},
        )
    print(f"wrote example data under {out}")


if __name__ == "__main__":
    main()
