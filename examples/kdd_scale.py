"""KDD2012-shaped synthetic scale run: the reference's north-star class.

Shape (SURVEY.md §6/§7 "entity-grouping ETL at KDD2012 scale"):
  - n = 10^7 examples (KDD2012 CTR has ~1.5x10^8; one v5e chip's HBM
    comfortably holds 10^7 with the sparse fixed effect below),
  - sparse global fixed effect, d = 10^5, ~10 nnz/example,
  - TWO random effects with 10^5 entities each (user: 2 features,
    item: per-entity intercept), power-law entity skew,
  - one full GAME coordinate-descent sweep on one chip.

Prints ONE JSON line with phase timings, peak host RSS, and validation
AUC.  Everything host-side is the vectorized SparseRows/grouping ETL —
no per-example Python anywhere.

Usage::

    python examples/kdd_scale.py            # full size (TPU, ~minutes)
    python examples/kdd_scale.py --small    # 10^5-example smoke run
"""

import argparse
import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from photon_ml_tpu.config import (  # noqa: E402
    CoordinateConfig,
    CoordinateKind,
    OptimizerSettings,
    TrainingConfig,
)
from photon_ml_tpu.data.sparse_rows import SparseRows  # noqa: E402
from photon_ml_tpu.estimators.game_estimator import GameEstimator  # noqa: E402
from photon_ml_tpu.evaluation import EvaluatorType  # noqa: E402
from photon_ml_tpu.game.dataset import GameDataset  # noqa: E402
from photon_ml_tpu.models.glm import TaskType  # noqa: E402
from photon_ml_tpu.utils.run_log import RunLogger  # noqa: E402


def max_rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def synthesize(n: int, d: int, k: int, n_users: int, n_items: int,
               seed: int = 0):
    """Vectorized KDD-shaped generator (no per-example Python)."""
    rng = np.random.default_rng(seed)
    # Skewed column popularity (power-law, like hashed CTR features),
    # made strictly increasing within each row so the CSR is canonical
    # by construction — no 10⁸-element sort needed to build it.
    cols_mat = np.sort(
        ((d - k) * rng.random((n, k)) ** 2.2).astype(np.int64), axis=1)
    for j in range(1, k):
        bump = cols_mat[:, j] <= cols_mat[:, j - 1]
        cols_mat[bump, j] = cols_mat[bump, j - 1] + 1
    indptr = np.arange(n + 1, dtype=np.int64) * k
    fixed = SparseRows.from_flat(indptr, cols_mat.reshape(-1),
                                 np.ones(n * k, np.float32))

    # Power-law entity popularity for both random effects.
    user = (n_users * rng.random(n) ** 1.8).astype(np.int64)
    item = (n_items * rng.random(n) ** 1.8).astype(np.int64)

    # Ground truth: sparse global weights + per-entity offsets.
    w_true = np.zeros(d)
    n_active = max(d // 20, 200)
    active = rng.choice(d, size=n_active, replace=False)
    w_true[active] = rng.normal(0, 1.2, n_active)
    u_eff = rng.normal(0, 1.2, n_users)
    i_eff = rng.normal(0, 0.8, n_items)
    x_user = np.concatenate(
        [np.ones((n, 1), np.float32),
         rng.normal(size=(n, 1)).astype(np.float32)], axis=1)
    margins = (fixed.dot_dense(w_true).astype(np.float64)
               + u_eff[user] + i_eff[item] - 1.0)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-margins))).astype(np.float32)

    return GameDataset(
        labels=y,
        features={
            "global": fixed,
            "user_re": x_user,
            "item_re": np.ones((n, 1), np.float32),
        },
        entity_ids={"userId": user, "itemId": item},
        feature_dims={"global": d},
    )


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="10^5-example smoke run (CPU-friendly)")
    ap.add_argument("--n", type=int, default=None,
                    help="example count (default 10^7; ~9x10^6 is the "
                         "largest class whose GRR plans fit one v5e's "
                         "16 GB HBM resident — beyond that, use "
                         "--chunked or shard over a mesh)")
    ap.add_argument("--chunked", type=int, default=None, metavar="ROWS",
                    help="chunk-accumulated fixed-effect training "
                         "(data/chunked_batch.py): examples per chunk; "
                         "breaks the HBM residency wall")
    ap.add_argument("--chunk-layout", default="AUTO",
                    choices=["AUTO", "GRR", "ELL"],
                    help="per-chunk layout: GRR = kernel-speed steps, "
                         "~1.6 GB/1e6 examples streamed per pass (PCIe-"
                         "class hosts); ELL = 8 B/nnz, ~20x smaller "
                         "stream (transfer-bound links, e.g. this "
                         "build box's axon tunnel)")
    ap.add_argument("--chunk-resident", type=int, default=1,
                    help="chunks kept live in HBM across passes (set "
                         ">= n/chunk_rows when the compact layout fits "
                         "— transfer then happens once)")
    ap.add_argument("--spill-dir", default=None,
                    help="out-of-core chunk store (data/chunk_store.py):"
                         " chunk batches spill to disk here and only "
                         "--host-resident decoded chunks stay in host "
                         "RAM — breaks the host-RAM wall the same way "
                         "--chunked breaks HBM's (default also "
                         "$PHOTON_ML_TPU_SPILL_DIR)")
    ap.add_argument("--host-resident", type=int, default=2,
                    help="decoded chunks kept live in host RAM when "
                         "spilling (the LRU window)")
    ap.add_argument("--prefetch-depth", type=int, default=2,
                    help="chunks prefetched disk->host->device ahead "
                         "of compute when spilling (0 = synchronous)")
    ap.add_argument("--out", default=None, help="also write the JSON here")
    args = ap.parse_args(argv)

    if args.small:
        n, d, k, ents = 100_000, 10_000, 10, 1_000
    else:
        n, d, k, ents = 10_000_000, 100_000, 10, 100_000
    if args.n is not None:
        n = args.n

    import tempfile

    log_path = os.path.join(tempfile.mkdtemp(prefix="kdd_scale_"),
                            "run_log.jsonl")
    log = RunLogger(path=log_path)
    t0 = time.time()
    with log.timed("synthesize"):
        data = synthesize(n, d, k, n_users=ents, n_items=ents)
    n_valid = min(n // 50, 200_000)
    with log.timed("split"):
        valid = data.take(np.arange(n - n_valid, n))
        train = data.take(np.arange(n - n_valid))

    cfg = TrainingConfig(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[
            CoordinateConfig(
                name="global", kind=CoordinateKind.FIXED_EFFECT,
                feature_shard="global",
                optimizer=OptimizerSettings(reg_weight=1.0, max_iters=30)),
            CoordinateConfig(
                name="per_user", kind=CoordinateKind.RANDOM_EFFECT,
                feature_shard="user_re", entity_key="userId",
                optimizer=OptimizerSettings(reg_weight=1.0, max_iters=10)),
            CoordinateConfig(
                name="per_item", kind=CoordinateKind.RANDOM_EFFECT,
                feature_shard="item_re", entity_key="itemId",
                optimizer=OptimizerSettings(reg_weight=1.0, max_iters=10)),
        ],
        update_sequence=["global", "per_user", "per_item"],
        n_iterations=1,
        evaluators=[EvaluatorType.AUC],
        intercept=True,
        chunk_rows=args.chunked,
        chunk_layout=args.chunk_layout,
        chunk_max_resident=args.chunk_resident,
        spill_dir=args.spill_dir,
        host_max_resident=args.host_resident,
        prefetch_depth=args.prefetch_depth,
    )
    est = GameEstimator(cfg)
    with log.timed("fit"):
        results = est.fit(train, valid, run_logger=log)
    auc = results[0].evaluations[EvaluatorType.AUC]

    from photon_ml_tpu.utils.run_log import read_run_log

    log.close()
    phases = {e["phase"]: round(e["duration_s"], 2)
              for e in read_run_log(log_path)
              if e.get("event") == "phase_end"}
    out = {
        "metric": "kdd_scale_wall_seconds",
        "value": round(time.time() - t0, 2),
        "unit": "s",
        "n_examples": n,
        "fixed_dim": d,
        "entities_per_re": ents,
        "n_random_effects": 2,
        "validation_auc": round(float(auc), 4),
        "peak_host_rss_gb": round(max_rss_gb(), 2),
        "phases": phases,
        "chunked": (None if args.chunked is None else {
            "chunk_rows": args.chunked,
            "layout": args.chunk_layout,
            "max_resident": args.chunk_resident,
            "spill_dir": args.spill_dir,
            "host_max_resident": args.host_resident,
            "prefetch_depth": args.prefetch_depth,
        }),
    }
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    assert auc > 0.70, f"scale-run AUC gate failed: {auc}"


if __name__ == "__main__":
    main()
