"""Time the GRR fused step only (ETL cached to disk across runs)."""
import sys, time, os, pickle
import numpy as np
import jax, jax.numpy as jnp
def log(m): print(m, file=sys.stderr, flush=True)

from photon_ml_tpu.data.batch import SparseBatch
from photon_ml_tpu.data.grr import build_grr_pair
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.utils.timing import measure

n, d, k = 1_000_000, 100_000, 30
rng = np.random.default_rng(0)
block = d // k
cols = ((np.arange(k, dtype=np.int64) * block)[None, :]
        + rng.integers(0, block, (n, k))).astype(np.int32)
vals = rng.normal(0, 1, (n, k)).astype(np.float32)
labels = (rng.uniform(size=n) < 0.5).astype(np.float32)

cachef = "/tmp/grr_pair_cache.pkl"
if os.path.exists(cachef):
    with open(cachef, "rb") as f:
        host = pickle.load(f)
    pair = jax.tree.map(jnp.asarray, host[0], is_leaf=lambda x: isinstance(x, np.ndarray))
    log("pair loaded from cache")
else:
    t0 = time.time()
    pair = build_grr_pair(cols, vals, d)
    log(f"ETL {time.time()-t0:.0f}s")
    host = (jax.tree.map(np.asarray, pair),)
    with open(cachef, "wb") as f:
        pickle.dump(host, f)

batch = SparseBatch(
    values=jnp.asarray(vals), col_ids=jnp.asarray(cols),
    labels=jnp.asarray(labels), weights=jnp.ones((n,), jnp.float32),
    offsets=jnp.zeros((n,), jnp.float32), mask=jnp.ones((n,), jnp.float32),
    dim=d, grr=pair)
obj = GLMObjective(loss=losses.LOGISTIC, reg=RegularizationContext.l2(1.0),
                   norm=NormalizationContext.identity())
w = jnp.asarray(rng.normal(0, 0.1, d), jnp.float32)

def chain(w, batch, length=20):
    def body(c, _):
        v, g = obj.value_and_gradient(c, batch)
        return c - 1e-6 * g, None
    out, _ = jax.lax.scan(body, w, None, length=length)
    return out

f = jax.jit(chain)
t0 = time.time(); jax.block_until_ready(f(w, batch)); log(f"compile {time.time()-t0:.1f}s")
s = measure(f, w, batch, iters=3) / 20
log(f"GRR fused value+grad: {s*1e3:.2f} ms/step  {n/s:.3e} ex/s")

# margins-only and grad-only pieces
def chain_m(w, batch, length=20):
    def body(c, _):
        m = batch.margins(c[:d])
        return c.at[0].add(m[0] * 1e-20), None
    out, _ = jax.lax.scan(body, w, None, length=length)
    return out
fm = jax.jit(chain_m)
jax.block_until_ready(fm(w, batch))
log(f"margins only: {measure(fm, w, batch, iters=3)/20*1e3:.2f} ms")

r = jnp.asarray(rng.normal(0, 1, n), jnp.float32)
def chain_g(r, batch, length=20):
    def body(c, _):
        g = batch.xt_dot(c)
        return c.at[0].add(g[0] * 1e-20), None
    out, _ = jax.lax.scan(body, r, None, length=length)
    return out
fg = jax.jit(chain_g)
jax.block_until_ready(fg(r, batch))
log(f"xt_dot only: {measure(fg, r, batch, iters=3)/20*1e3:.2f} ms")
