"""Native C++ ETL bindings: compile-on-first-use, ctypes, numpy fallback.

Reference counterpart: the JVM data plane (Spark executors deserializing
Avro, shuffling, building per-partition iterables — SURVEY.md §5.8).
The rebuild's data plane is host-side array construction; the hot parts
(LIBSVM text parsing, the transposed-ELL counting sort) live in
``fast_etl.cpp`` and are bound here.

Build model: ``g++ -O3 -shared -fPIC`` into a per-version cached .so
next to the source on first use (seconds, once).  Every caller treats
``lib()`` returning None as "no native library" and falls back to the
numpy implementation, so the framework works on machines with no
toolchain.  ``PHOTON_ML_TPU_NATIVE=0`` forces the fallback (bench
comparisons, debugging).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fast_etl.cpp")
_SO = os.path.join(_HERE, f"_fast_etl_{sys.implementation.cache_tag}.so")

_lock = threading.Lock()
_lib: "ctypes.CDLL | None | bool" = False  # False = not yet attempted


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
        _SRC, "-o", _SO,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        sys.stderr.write(
            f"photon_ml_tpu.native: g++ unavailable ({e!r}); using the "
            "numpy fallbacks (GRR plan compilation will be much slower)\n"
        )
        return False
    if proc.returncode != 0:
        sys.stderr.write(
            f"photon_ml_tpu.native: build failed, using numpy fallback\n"
            f"{proc.stderr[:2000]}\n"
        )
        return False
    return True


def lib() -> "ctypes.CDLL | None":
    """The loaded native library, or None (fallback path)."""
    global _lib
    if _lib is not False:
        return _lib  # type: ignore[return-value]
    from photon_ml_tpu.config import read_env

    with _lock:
        if _lib is not False:
            return _lib  # type: ignore[return-value]
        if read_env("PHOTON_ML_TPU_NATIVE") == "0":
            _lib = None
            return None
        if not os.path.exists(_SO) or (
            os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            if not _build():
                _lib = None
                return None
        try:
            dll = ctypes.CDLL(_SO)
        except OSError:
            _lib = None
            return None
        dll.pml_libsvm_parse.restype = ctypes.c_void_p
        dll.pml_libsvm_parse.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        dll.pml_libsvm_sizes.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
        ]
        dll.pml_libsvm_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        dll.pml_libsvm_free.argtypes = [ctypes.c_void_p]
        dll.pml_colmajor_vrows.restype = ctypes.c_int64
        dll.pml_colmajor_vrows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
        ]
        dll.pml_colmajor_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        dll.pml_edge_color.restype = ctypes.c_int32
        dll.pml_edge_color.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p,
        ]
        dll.pml_grr_routes.restype = ctypes.c_int32
        dll.pml_grr_routes.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        dll.pml_grr_plan.restype = ctypes.c_void_p
        dll.pml_grr_plan.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int64,
        ]
        dll.pml_grr_plan_sizes.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        dll.pml_grr_plan_fill.argtypes = [ctypes.c_void_p] + [
            ctypes.c_void_p] * 9
        dll.pml_grr_plan_free.argtypes = [ctypes.c_void_p]
        _lib = dll
        return dll


def native_available() -> bool:
    """True when the native library is loaded (or loadable)."""
    return lib() is not None


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def libsvm_parse_native(data: bytes):
    """Parse LIBSVM text → (labels, row_ptr, cols, vals, max_col), or
    None if the native library is unavailable.  Raises ValueError on
    malformed input (same contract as the Python parser)."""
    dll = lib()
    if dll is None:
        return None
    handle = dll.pml_libsvm_parse(data, len(data))
    if not handle:
        raise ValueError("malformed LIBSVM input (native parser)")
    try:
        n = ctypes.c_int64()
        nnz = ctypes.c_int64()
        max_col = ctypes.c_int32()
        dll.pml_libsvm_sizes(handle, ctypes.byref(n), ctypes.byref(nnz),
                             ctypes.byref(max_col))
        labels = np.empty(n.value, np.float32)
        row_ptr = np.empty(n.value + 1, np.int64)
        cols = np.empty(nnz.value, np.int32)
        vals = np.empty(nnz.value, np.float32)
        dll.pml_libsvm_fill(handle, _ptr(labels), _ptr(row_ptr),
                            _ptr(cols), _ptr(vals))
        return labels, row_ptr, cols, vals, int(max_col.value)
    finally:
        dll.pml_libsvm_free(handle)


def edge_color_native(
    src: np.ndarray, dst: np.ndarray, n_left: int, n_right: int,
    n_colors: int,
) -> "np.ndarray | None":
    """Proper edge coloring of a bipartite multigraph (Euler split).

    Every vertex's degree must be divisible by ``n_colors`` (a power of
    two).  Returns int32 colors per edge, or None when the native
    library is unavailable (callers fall back to the Python colorer in
    ``ops.crossbar``)."""
    dll = lib()
    if dll is None:
        return None
    src = np.ascontiguousarray(src, np.int32)
    dst = np.ascontiguousarray(dst, np.int32)
    color = np.empty(src.size, np.int32)
    rc = dll.pml_edge_color(_ptr(src), _ptr(dst), src.size, n_left,
                            n_right, n_colors, _ptr(color))
    if rc != 0:
        raise ValueError("pml_edge_color: invalid arguments")
    return color


def grr_routes_native(dst: np.ndarray, hi: np.ndarray):
    """Batched GRR supertile routing → (g1, g2, g3) int8 arrays, or None
    when the native library is unavailable (Python fallback in
    ``data.grr``).  ``dst``: [n_st,128,128] int32 slot bijections;
    ``hi``: [n_st,128,128] int8 gather planes.  Raises ValueError if a
    tile is not a bijection."""
    dll = lib()
    if dll is None:
        return None
    dst = np.ascontiguousarray(dst, np.int32)
    hi = np.ascontiguousarray(hi, np.int8)
    n_st = dst.shape[0]
    g1 = np.empty_like(hi)
    g2 = np.empty_like(hi)
    g3 = np.empty_like(hi)
    rc = dll.pml_grr_routes(_ptr(dst), _ptr(hi), n_st, _ptr(g1), _ptr(g2),
                            _ptr(g3))
    if rc != 0:
        raise ValueError("pml_grr_routes: dst tile is not a bijection")
    return g1, g2, g3


def colmajor_build_native(
    cols: np.ndarray,
    vals: np.ndarray,
    dim: int,
    capacity: int,
    pad_vrows_to_multiple: int | None = None,
    pad_vrows_to: int | None = None,
):
    """Transposed-ELL build → (tvals, trows, vcol) or None (no native).

    Same semantics as the numpy path in ``data.colmajor.build_colmajor``
    except entry order within a column follows row-scan order (both are
    valid orderings of the same multiset; sums agree).
    """
    dll = lib()
    if dll is None:
        return None
    n, k = cols.shape
    cols = np.ascontiguousarray(cols, np.int32)
    vals = np.ascontiguousarray(vals, np.float32)
    counts = np.zeros(dim, np.int64)
    v = dll.pml_colmajor_vrows(_ptr(cols), _ptr(vals), n, k, dim,
                               capacity, _ptr(counts))
    if v < 0:
        raise ValueError("column id out of range in colmajor build")
    from photon_ml_tpu.ops.kernels import vrow_pad

    v_pad = vrow_pad(int(v), pad_vrows_to_multiple)
    if pad_vrows_to is not None:
        if pad_vrows_to < v:
            raise ValueError(f"pad_vrows_to={pad_vrows_to} < V={v}")
        v_pad = pad_vrows_to
    tvals = np.zeros((v_pad, capacity), np.float32)
    trows = np.zeros((v_pad, capacity), np.int32)
    vcol = np.zeros(v_pad, np.int32)
    dll.pml_colmajor_fill(_ptr(cols), _ptr(vals), n, k, dim, capacity,
                          _ptr(counts), v_pad, _ptr(tvals), _ptr(trows),
                          _ptr(vcol))
    return tvals, trows, vcol


def grr_plan_native(
    cols: np.ndarray,
    vals: np.ndarray,
    direction: int,
    table_len: int,
    n_segments: int,
    cap: int | None = None,
    idx_range: "tuple[int, int] | None" = None,
):
    """One GRR direction's plan straight from the row-ELL arrays, or
    None when the native library is unavailable (numpy path in
    ``data.grr.build_grr_direction``).

    ``direction`` 0: idx=column, seg=row (the margins X·w direction);
    1: idx=row, seg=column (the gradient Xᵀr direction).  Entries with
    value 0 are dropped (zero the hot-column entries before calling).
    ``idx_range=(lo, hi)`` restricts the plan to table indices in
    [lo, hi) — entries outside are skipped (they belong to a sibling
    column-range sub-plan), indices are rebased to idx-lo, and the
    returned plan's table axis is [0, hi-lo); ``lo`` must be
    window-aligned (a multiple of 16384).  Indices outside
    [0, table_len) are still an error.
    Returns a dict with the plan arrays (hi/vals/dst per supertile,
    block maps, spill COO) and the chosen cap; route coloring is the
    caller's next step (``grr_routes_native``).
    """
    dll = lib()
    if dll is None:
        return None
    # int32 narrowing must not wrap (advisor finding: a wrapped 64-bit
    # column id landing back inside [0, table_len) would pass the C++
    # range check and yield a silently wrong plan).
    cols = np.asarray(cols)
    if cols.dtype.itemsize > 4 and cols.size and (
        int(cols.max()) > np.iinfo(np.int32).max
        or int(cols.min()) < np.iinfo(np.int32).min
    ):
        raise ValueError("column id exceeds int32 range in GRR plan build")
    cols = np.ascontiguousarray(cols, np.int32)
    vals = np.ascontiguousarray(vals, np.float32)
    n, k = cols.shape
    # cap=0 is rejected (same contract as the numpy path); only None
    # means "choose from occupancy".
    if cap is not None and cap not in (1, 2, 4, 8, 16, 32, 64, 128):
        raise ValueError(f"cap must be a power of two ≤ 128, got {cap}")
    lo, hi = idx_range if idx_range is not None else (0, int(table_len))
    handle = dll.pml_grr_plan(
        _ptr(cols), _ptr(vals), n, k, int(direction), int(table_len),
        int(n_segments), 0 if cap is None else int(cap), int(lo), int(hi),
    )
    if not handle:
        raise MemoryError("pml_grr_plan allocation failed")
    try:
        n_st = ctypes.c_int64()
        n_spill = ctypes.c_int64()
        cap_out = ctypes.c_int32()
        n_gw = ctypes.c_int32()
        n_ow = ctypes.c_int32()
        error = ctypes.c_int32()
        dll.pml_grr_plan_sizes(
            handle, ctypes.byref(n_st), ctypes.byref(n_spill),
            ctypes.byref(cap_out), ctypes.byref(n_gw), ctypes.byref(n_ow),
            ctypes.byref(error),
        )
        if error.value == 1:
            raise ValueError("idx or seg out of range in GRR plan build")
        if error.value:
            return None  # size overflow: numpy path decides
        st = int(n_st.value)
        m = int(n_spill.value)
        hi = np.empty((st, 128, 128), np.int8)
        v_out = np.empty((st, 128, 128), np.float32)
        dst = np.empty((st, 128, 128), np.int32)
        gw_of_st = np.empty(st, np.int32)
        ow_of_st = np.empty(st, np.int32)
        first_of_ow = np.empty(st, np.int32)
        spill_idx = np.zeros(m, np.int32)
        spill_seg = np.zeros(m, np.int32)
        spill_val = np.zeros(m, np.float32)
        dll.pml_grr_plan_fill(
            handle, _ptr(hi), _ptr(v_out), _ptr(dst), _ptr(gw_of_st),
            _ptr(ow_of_st), _ptr(first_of_ow), _ptr(spill_idx),
            _ptr(spill_seg), _ptr(spill_val),
        )
    finally:
        dll.pml_grr_plan_free(handle)
    return {
        "hi": hi, "vals": v_out, "dst": dst, "gw_of_st": gw_of_st,
        "ow_of_st": ow_of_st, "first_of_ow": first_of_ow,
        "spill_idx": spill_idx, "spill_seg": spill_seg,
        "spill_val": spill_val, "cap": int(cap_out.value),
        "n_gw": int(n_gw.value), "n_ow": int(n_ow.value),
    }
