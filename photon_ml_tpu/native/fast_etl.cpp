// Native host-side ETL: the rebuild's C++ runtime for data preparation.
//
// Reference counterpart: the JVM executors' deserialization + shuffle
// machinery (Spark's netty/torrent substrate, SURVEY.md §5.8) and the
// Avro decode path of AvroDataReader [expected reference structure;
// mount unavailable].  The reference leans on the JVM for its data
// plane; the TPU rebuild's data plane is this library + numpy, feeding
// statically-shaped HBM arrays.
//
// Everything here is single-pass, cache-friendly C++ with no
// dependencies beyond the C++17 standard library.  The Python side
// (photon_ml_tpu.native) binds via ctypes and falls back to numpy
// implementations when the shared object is unavailable, so the
// framework never hard-depends on a compiler at runtime.
//
// Exposed surface (extern "C", handle-based two-phase protocol so the
// caller allocates numpy arrays of exactly the right size):
//
//   LIBSVM text  -> CSR-ish (row_ptr, cols, vals, labels)
//   row-ELL      -> transposed-ELL (the colmajor build: counting sort
//                   by column + virtual-row splitting; O(nnz + dim))

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>
#include <vector>

namespace {

struct LibsvmResult {
  std::vector<float> labels;
  std::vector<int64_t> row_ptr;  // [n+1]
  std::vector<int32_t> cols;
  std::vector<float> vals;
  int32_t max_col = -1;
};

// Minimal fast float parse: LIBSVM files carry plain decimal floats.
// strtof handles all forms; the win over Python is avoiding per-token
// object allocation, not exotic float parsing.
inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// LIBSVM parsing
// ---------------------------------------------------------------------------

void* pml_libsvm_parse(const char* buf, int64_t len) {
  auto* r = new (std::nothrow) LibsvmResult();
  if (!r) return nullptr;
  r->row_ptr.push_back(0);
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!line_end) line_end = end;
    p = skip_ws(p, line_end);
    if (p < line_end && *p != '#') {
      char* q = nullptr;
      float label = strtof(p, &q);
      if (q == p || q > line_end) {
        delete r;
        return nullptr;
      }
      p = q;
      while (p < line_end) {
        p = skip_ws(p, line_end);
        if (p >= line_end || *p == '#') break;
        long idx = strtol(p, &q, 10);
        if (q == p || q >= line_end || *q != ':') {
          delete r;
          return nullptr;
        }
        p = q + 1;
        float v = strtof(p, &q);
        // strtof may legally run past line_end (the buffer is contiguous
        // across lines), which would silently consume the next line's
        // tokens; a value must both exist and end within its own line.
        if (q == p || q > line_end) {
          delete r;
          return nullptr;
        }
        p = q;
        // Raw file index; 0/1-based conversion happens in Python
        // (vectorized), which also validates the resulting range.
        if (idx < 0 || idx > INT32_MAX) {
          delete r;
          return nullptr;
        }
        int32_t c = static_cast<int32_t>(idx);
        r->cols.push_back(c);
        r->vals.push_back(v);
        if (c > r->max_col) r->max_col = c;
      }
      r->labels.push_back(label);
      r->row_ptr.push_back(static_cast<int64_t>(r->cols.size()));
    }
    p = line_end + 1;
  }
  return r;
}

void pml_libsvm_sizes(void* handle, int64_t* n_rows, int64_t* nnz,
                      int32_t* max_col) {
  auto* r = static_cast<LibsvmResult*>(handle);
  *n_rows = static_cast<int64_t>(r->labels.size());
  *nnz = static_cast<int64_t>(r->cols.size());
  *max_col = r->max_col;
}

void pml_libsvm_fill(void* handle, float* labels, int64_t* row_ptr,
                     int32_t* cols, float* vals) {
  auto* r = static_cast<LibsvmResult*>(handle);
  memcpy(labels, r->labels.data(), r->labels.size() * sizeof(float));
  memcpy(row_ptr, r->row_ptr.data(), r->row_ptr.size() * sizeof(int64_t));
  memcpy(cols, r->cols.data(), r->cols.size() * sizeof(int32_t));
  memcpy(vals, r->vals.data(), r->vals.size() * sizeof(float));
}

void pml_libsvm_free(void* handle) {
  delete static_cast<LibsvmResult*>(handle);
}

// ---------------------------------------------------------------------------
// Transposed-ELL (colmajor) build — see data/colmajor.py for the design.
// Counting sort by column: O(nnz + dim), one read pass + one write pass.
// ---------------------------------------------------------------------------

// Phase 1: count virtual rows for (cols, vals, capacity).  Returns V, or
// -1 on invalid input.  col_counts must be a caller-zeroed [dim] int64
// scratch; it is left holding the per-column nonzero counts for phase 2.
int64_t pml_colmajor_vrows(const int32_t* cols, const float* vals,
                           int64_t n, int64_t k, int64_t dim,
                           int64_t capacity, int64_t* col_counts) {
  const int64_t total = n * k;
  for (int64_t e = 0; e < total; ++e) {
    if (vals[e] != 0.0f) {
      const int32_t c = cols[e];
      if (c < 0 || c >= dim) return -1;
      ++col_counts[c];
    }
  }
  int64_t v = 0;
  for (int64_t j = 0; j < dim; ++j) {
    v += (col_counts[j] + capacity - 1) / capacity;
  }
  return v;
}

// Phase 2: fill caller-allocated tvals [v_pad*capacity] (zeroed),
// trows [v_pad*capacity] (zeroed), vcol [v_pad] (zeroed).  col_counts is
// the phase-1 output.  Entries keep row order within each column
// (counting sort is stable in row-scan order).
void pml_colmajor_fill(const int32_t* cols, const float* vals,
                       int64_t n, int64_t k, int64_t dim,
                       int64_t capacity, const int64_t* col_counts,
                       int64_t v_pad, float* tvals, int32_t* trows,
                       int32_t* vcol) {
  // Per-column virtual-row base and running cursor.
  std::vector<int64_t> vrow_base(static_cast<size_t>(dim) + 1, 0);
  for (int64_t j = 0; j < dim; ++j) {
    vrow_base[static_cast<size_t>(j) + 1] =
        vrow_base[static_cast<size_t>(j)] +
        (col_counts[j] + capacity - 1) / capacity;
  }
  for (int64_t j = 0; j < dim; ++j) {
    const int64_t first = vrow_base[static_cast<size_t>(j)];
    const int64_t nv = vrow_base[static_cast<size_t>(j) + 1] - first;
    for (int64_t t = 0; t < nv; ++t) {
      vcol[first + t] = static_cast<int32_t>(j);
    }
  }
  std::vector<int64_t> cursor(static_cast<size_t>(dim), 0);
  for (int64_t i = 0; i < n; ++i) {
    const int64_t row_off = i * k;
    for (int64_t s = 0; s < k; ++s) {
      const float v = vals[row_off + s];
      if (v == 0.0f) continue;
      const int32_t c = cols[row_off + s];
      const int64_t pos = cursor[c]++;
      const int64_t vr = vrow_base[static_cast<size_t>(c)] + pos / capacity;
      const int64_t slot = pos % capacity;
      tvals[vr * capacity + slot] = v;
      trows[vr * capacity + slot] = static_cast<int32_t>(i);
    }
  }
  (void)v_pad;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Bipartite Euler-split edge coloring — the router for in-tile crossbars.
//
// A static permutation of a [R,128] VMEM tile is executed on TPU as
// lane-perm ∘ transpose ∘ lane-perm ∘ transpose ∘ lane-perm (see
// ops/crossbar.py).  The middle lane-perm is legal iff the edges
// (src_row → dst_row) are properly colored with 128 colors such that no
// two edges at the same vertex share a color.  With every vertex of
// degree exactly n_colors (a power of two; padding slots make this true
// by construction), repeated Euler splitting yields an exact coloring in
// O(m log n_colors): each split walks Euler circuits and alternates
// edges between halves, preserving even degrees.
// ---------------------------------------------------------------------------

namespace {

// One Euler-split level: partition edges[lo:hi) (indices into src/dst)
// into first half = color bit 0, second half = bit 1, by walking Euler
// circuits and alternating.  src[e] in [0,L), dst[e] in [0,R_n).
// Every vertex degree within the subset must be even.
void euler_split_level(const int32_t* src, const int32_t* dst,
                       int64_t* edge_ids, int64_t lo, int64_t hi,
                       int32_t n_left, int32_t n_right,
                       std::vector<int64_t>& head,
                       std::vector<int64_t>& nxt,
                       std::vector<int64_t>& prv,
                       std::vector<uint8_t>& used,
                       std::vector<uint8_t>& side_out) {
  // Build doubly-linked adjacency over vertices 0..n_left-1 (left) and
  // n_left..n_left+n_right-1 (right); each edge appears once per side
  // via two arc slots (2e, 2e+1).
  const int32_t nv = n_left + n_right;
  for (int32_t v = 0; v < nv; ++v) head[v] = -1;
  for (int64_t i = lo; i < hi; ++i) {
    const int64_t e = edge_ids[i];
    used[e] = 0;
    const int64_t a0 = 2 * e, a1 = 2 * e + 1;
    const int32_t u = src[e], w = n_left + dst[e];
    nxt[a0] = head[u]; prv[a0] = -1;
    if (head[u] >= 0) prv[head[u]] = a0;
    head[u] = a0;
    nxt[a1] = head[w]; prv[a1] = -1;
    if (head[w] >= 0) prv[head[w]] = a1;
    head[w] = a1;
  }
  auto detach = [&](int64_t arc, int32_t v) {
    if (prv[arc] >= 0) nxt[prv[arc]] = nxt[arc];
    else head[v] = nxt[arc];
    if (nxt[arc] >= 0) prv[nxt[arc]] = prv[arc];
  };
  // Walk circuits: from any vertex with remaining edges, follow unused
  // edges until returning; alternate sides along the walk.  On a graph
  // with all even degrees the walk can only get stuck at its start
  // vertex, at which point we continue from any still-incident vertex.
  for (int64_t i = lo; i < hi; ++i) {
    const int64_t e0 = edge_ids[i];
    if (used[e0]) continue;
    int32_t v = src[e0];
    uint8_t side = 0;
    while (head[v] >= 0) {
      const int64_t arc = head[v];
      const int64_t e = arc >> 1;
      const int32_t u = src[e], w = n_left + dst[e];
      detach(2 * e, u);
      detach(2 * e + 1, w);
      used[e] = 1;
      side_out[e] = side;
      side ^= 1;
      v = (v == u) ? w : u;
    }
  }
}

}  // namespace

extern "C" {

// Color m edges (src[e] in [0,n_left), dst[e] in [0,n_right)) with
// n_colors colors (power of two).  Every left/right vertex must have
// degree divisible by n_colors... in the crossbar use-case degree ==
// n_colors exactly.  Writes color[e] in [0, n_colors).  Returns 0, or
// -1 on invalid arguments.
int32_t pml_edge_color(const int32_t* src, const int32_t* dst, int64_t m,
                       int32_t n_left, int32_t n_right, int32_t n_colors,
                       int32_t* color) {
  if (n_colors <= 0 || (n_colors & (n_colors - 1)) != 0) return -1;
  if (m < 0 || n_left <= 0 || n_right <= 0) return -1;
  // Vertex-range validation before touching the adjacency arrays: an
  // out-of-range id would index head/nxt/prv out of bounds (heap
  // corruption reachable from Python via edge_color_native).
  for (int64_t e = 0; e < m; ++e) {
    if (src[e] < 0 || src[e] >= n_left || dst[e] < 0 || dst[e] >= n_right)
      return -1;
  }
  std::vector<int64_t> edge_ids(static_cast<size_t>(m));
  for (int64_t e = 0; e < m; ++e) { edge_ids[e] = e; color[e] = 0; }
  std::vector<int64_t> head(static_cast<size_t>(n_left + n_right));
  std::vector<int64_t> nxt(static_cast<size_t>(2 * m));
  std::vector<int64_t> prv(static_cast<size_t>(2 * m));
  std::vector<uint8_t> used(static_cast<size_t>(m));
  std::vector<uint8_t> side(static_cast<size_t>(m));
  std::vector<int64_t> scratch(static_cast<size_t>(m));

  // Iterative halving: ranges of edge_ids sharing a color prefix are
  // split; bit b of the color is assigned at level b (MSB first).
  int32_t levels = 0;
  for (int32_t c = n_colors; c > 1; c >>= 1) ++levels;
  std::vector<std::pair<int64_t, int64_t>> ranges{{0, m}};
  for (int32_t level = 0; level < levels; ++level) {
    std::vector<std::pair<int64_t, int64_t>> next_ranges;
    for (auto [lo, hi] : ranges) {
      if (hi - lo == 0) continue;
      euler_split_level(src, dst, edge_ids.data(), lo, hi, n_left,
                        n_right, head, nxt, prv, used, side);
      // Stable partition: side 0 first.
      int64_t w0 = lo;
      for (int64_t i = lo; i < hi; ++i)
        if (!side[edge_ids[i]]) scratch[w0++] = edge_ids[i];
      int64_t mid = w0;
      for (int64_t i = lo; i < hi; ++i)
        if (side[edge_ids[i]]) scratch[w0++] = edge_ids[i];
      for (int64_t i = lo; i < hi; ++i) edge_ids[i] = scratch[i];
      const int32_t bit = 1 << (levels - 1 - level);
      for (int64_t i = mid; i < hi; ++i) color[edge_ids[i]] |= bit;
      next_ranges.emplace_back(lo, mid);
      next_ranges.emplace_back(mid, hi);
    }
    ranges = std::move(next_ranges);
  }
  return 0;
}

// Batched GRR route builder: for each [128,128] supertile, color the
// start→final slot permutation (dst[t][r*128+l] = final slot of the
// element starting at (r, l)) and emit the three lane-gather stages the
// kernel executes (ops/grr_kernel.py), with route stage 1 pre-composed
// with the gather index plane hi.  This is the hot part of compiling a
// sparse matrix into the GRR plan (data/grr.py) — one Euler-split
// coloring per supertile, O(slots · log 128) each.
// Returns 0, or -1 if any tile's dst is not a bijection / coloring
// arguments are invalid.
int32_t pml_grr_routes(const int32_t* dst, const int8_t* hi, int64_t n_st,
                       int8_t* g1, int8_t* g2, int8_t* g3) {
  constexpr int32_t T = 128;
  constexpr int64_t S = static_cast<int64_t>(T) * T;
  std::vector<int32_t> src_row(S), dst_row(S), color(S);
  std::vector<uint8_t> seen(S);
  for (int64_t e = 0; e < S; ++e) src_row[e] = static_cast<int32_t>(e >> 7);

  for (int64_t t = 0; t < n_st; ++t) {
    const int32_t* d = dst + t * S;
    const int8_t* h = hi + t * S;
    std::memset(seen.data(), 0, static_cast<size_t>(S));
    for (int64_t e = 0; e < S; ++e) {
      const int32_t v = d[e];
      if (v < 0 || v >= S || seen[v]) return -1;
      seen[v] = 1;
      dst_row[e] = v >> 7;
    }
    if (pml_edge_color(src_row.data(), dst_row.data(), S, T, T, T,
                       color.data()) != 0)
      return -1;
    int8_t* G1 = g1 + t * S;
    int8_t* G2 = g2 + t * S;
    int8_t* G3 = g3 + t * S;
    for (int64_t e = 0; e < S; ++e) {
      const int32_t r = src_row[e];
      const int32_t l = static_cast<int32_t>(e & (T - 1));
      const int32_t c = color[e];
      const int32_t dr = dst_row[e];
      const int32_t dl = d[e] & (T - 1);
      G1[r * T + c] = h[r * T + l];
      G2[c * T + dr] = static_cast<int8_t>(r);
      G3[dr * T + dl] = static_cast<int8_t>(c);
    }
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// GRR plan construction (the layout half of the sparse engine)
// ---------------------------------------------------------------------------
//
// Builds one direction's gather-route-reduce plan straight from the
// row-ELL arrays: the same pipeline as photon_ml_tpu.data.grr
// .build_grr_direction (group-capacity ranks, supertile blocking,
// start/final slot placement, padding bijection, spill COO), but as a
// handful of streaming passes over the entries with small cache-local
// counter tables — no 10^8-element comparison sorts, no full-size
// temporaries.  Rank assignment within a group follows entry scan
// order; the Python path's sort-based ranks may differ, but rank choice
// is explicitly arbitrary (both produce valid plans whose contractions
// agree — tested in tests/test_grr.py).
//
// Protocol: pml_grr_plan(...) -> handle; pml_grr_plan_sizes(handle,..);
// pml_grr_plan_fill(handle, ...); pml_grr_plan_free(handle).
// Route coloring stays in pml_grr_routes (shared with the Python path).

namespace {

constexpr int64_t GRR_WIN = 16384;
constexpr int32_t GRR_TILE = 128;
constexpr int64_t GRR_SLOTS = GRR_WIN;  // 128*128 slots per supertile

struct GrrPlan {
  int32_t error = 0;  // 1 = idx/seg out of range, 2 = size overflow
  int32_t cap = 0, n_gw = 0, n_ow = 0;
  int64_t n_st = 0, n_spill = 0;  // n_spill already padded to 8
  std::vector<int8_t> hi;
  std::vector<float> vals;
  std::vector<int32_t> dst;
  std::vector<int32_t> gw_of_st, ow_of_st, first_of_ow;
  std::vector<int32_t> spill_idx, spill_seg;
  std::vector<float> spill_val;
};

inline int32_t grr_next_pow2(int64_t x) {
  // Callers clamp the result to <= 64; clamp the input too so an
  // extreme occupancy mean can't overflow the int32 shift (UB).
  if (x > 128) x = 128;
  int32_t p = 1;
  while (p < x) p <<= 1;
  return p;
}

// Body behind an exception firewall: std::bad_alloc must not unwind
// through the extern "C"/ctypes boundary (that would terminate the
// process instead of letting the caller fall back to numpy).
//
// [idx_lo, idx_hi) restricts the plan to a contiguous sub-range of the
// table axis (the column-range split of data/grr.py): entries outside
// the range are SKIPPED (they belong to a sibling sub-plan — not spill,
// not an error), in-range indices are rebased to idx - idx_lo, and the
// emitted plan's table axis is [0, idx_hi - idx_lo).  Indices outside
// [0, table_len) are still a hard error — every entry belongs to
// exactly one range of a full partition, so a genuinely out-of-range
// id must not be silently dropped by all parts.
void grr_plan_body(GrrPlan* plan, const int32_t* cols, const float* vals,
                   int64_t n, int64_t k, int32_t direction,
                   int64_t table_len, int64_t n_segments, int32_t cap_in,
                   int64_t idx_lo, int64_t idx_hi) {
  // Same cap validation as the numpy path (data/grr.py): a non-power-
  // of-two cap makes distinct (q, b) pairs collide on one final slot.
  if (cap_in != 0 && cap_in != 1 && cap_in != 2 && cap_in != 4 &&
      cap_in != 8 && cap_in != 16 && cap_in != 32 && cap_in != 64 &&
      cap_in != 128) {
    plan->error = 3;
    return;
  }
  constexpr int64_t kMaxCounterBytes = int64_t{1} << 33;  // 8 GB
  if (idx_hi <= 0) idx_hi = table_len;
  if (idx_lo < 0 || idx_hi > table_len || idx_lo >= idx_hi ||
      (idx_lo % GRR_WIN) != 0) {
    plan->error = 3;
    return;
  }
  const int64_t range_len = idx_hi - idx_lo;
  const int64_t n_gw = (range_len + GRR_WIN - 1) / GRR_WIN;
  plan->n_gw = static_cast<int32_t>(n_gw);
  const int64_t m_ell = n * k;

  // Pass A: count nonzeros, validate ranges, check (seg, gw) sortedness.
  int64_t m_nz = 0;
  bool sorted = true;
  int64_t prev_key = -1;
  for (int64_t e = 0; e < m_ell; ++e) {
    const float v = vals[e];
    if (v == 0.0f) continue;
    const int64_t r = e / k;
    const int64_t c = cols[e];
    int64_t idx = direction ? r : c;
    const int64_t seg = direction ? c : r;
    if (idx < 0 || idx >= table_len || seg < 0 || seg >= n_segments) {
      plan->error = 1;
      return;
    }
    if (idx < idx_lo || idx >= idx_hi) continue;
    idx -= idx_lo;
    const int64_t key = seg * n_gw + idx / GRR_WIN;
    if (key < prev_key) sorted = false;
    prev_key = key;
    ++m_nz;
  }

  // Capacity: 1.5x the exact mean nonempty (seg, window) occupancy
  // (the Python path estimates this mean by sampling segments; exact
  // is strictly better and free here).
  int32_t cap = cap_in;
  if (cap <= 0) {
    int64_t n_groups = 0;
    if (sorted) {
      prev_key = -1;
      for (int64_t e = 0; e < m_ell; ++e) {
        if (vals[e] == 0.0f) continue;
        const int64_t r = e / k;
        const int64_t c = cols[e];
        const int64_t idx = direction ? r : c;
        if (idx < idx_lo || idx >= idx_hi) continue;
        const int64_t key = (direction ? c : r) * n_gw +
                            (idx - idx_lo) / GRR_WIN;
        if (key != prev_key) ++n_groups;
        prev_key = key;
      }
    } else {
      const int64_t n_keys = n_segments * n_gw;
      if (n_keys > kMaxCounterBytes) {
        plan->error = 2;
        return;
      }
      std::vector<uint8_t> visited(static_cast<size_t>(n_keys), 0);
      for (int64_t e = 0; e < m_ell; ++e) {
        if (vals[e] == 0.0f) continue;
        const int64_t r = e / k;
        const int64_t c = cols[e];
        const int64_t idx = direction ? r : c;
        if (idx < idx_lo || idx >= idx_hi) continue;
        const int64_t key = (direction ? c : r) * n_gw +
                            (idx - idx_lo) / GRR_WIN;
        if (!visited[key]) { visited[key] = 1; ++n_groups; }
      }
    }
    const double mean = n_groups ? double(m_nz) / double(n_groups) : 1.0;
    cap = grr_next_pow2(static_cast<int64_t>(mean * 1.5 + 0.999999));
    if (cap < 4) cap = 4;
    if (cap > 64) cap = 64;
  }
  plan->cap = cap;
  const int64_t segwin = GRR_WIN / cap;
  const int32_t group = GRR_TILE / cap;
  const int64_t n_ow = n_segments > 0 ? (n_segments + segwin - 1) / segwin : 1;
  plan->n_ow = static_cast<int32_t>(n_ow);
  const int64_t n_bk = n_ow * n_gw;
  if (n_bk * GRR_TILE * 2 > kMaxCounterBytes) {  // r2cnt bytes
    plan->error = 2;
    return;
  }

  // Rank counters.  q: per (seg, window) among all entries (uint8,
  // cap <= 64 < 255 so saturate at 255 = spilled anyway).  rank2: per
  // (block, lane residue) among cap-kept entries.
  std::vector<uint8_t> qcnt;
  if (!sorted) {
    if (n_segments * n_gw > kMaxCounterBytes) {
      plan->error = 2;
      return;
    }
    qcnt.assign(static_cast<size_t>(n_segments * n_gw), 0);
  }
  std::vector<uint16_t> r2cnt(static_cast<size_t>(n_bk) * GRR_TILE, 0);
  std::vector<int64_t> cnt_bk(static_cast<size_t>(n_bk), 0);

  // Pass B: count kept entries per block (q + rank2 logic, no fills).
  {
    int64_t run_key = -1, run_q = 0;
    for (int64_t e = 0; e < m_ell; ++e) {
      const float v = vals[e];
      if (v == 0.0f) continue;
      const int64_t r = e / k;
      const int64_t c = cols[e];
      int64_t idx = direction ? r : c;
      const int64_t seg = direction ? c : r;
      if (idx < idx_lo || idx >= idx_hi) continue;
      idx -= idx_lo;
      const int64_t gw = idx / GRR_WIN;
      int64_t q;
      if (sorted) {
        const int64_t key = seg * n_gw + gw;
        if (key != run_key) { run_key = key; run_q = 0; }
        q = run_q++;
      } else {
        uint8_t& qc = qcnt[seg * n_gw + gw];
        q = qc;
        if (qc < 255) ++qc;
      }
      if (q >= cap) continue;  // spill1
      const int64_t bk = (seg / segwin) * n_gw + gw;
      uint16_t& r2 = r2cnt[bk * GRR_TILE + (idx % GRR_TILE)];
      if (r2 >= GRR_TILE) { ++r2; continue; }  // spill2 (sat. anyway)
      ++r2;
      ++cnt_bk[bk];
    }
  }

  // Block list: non-empty blocks ascending + a dummy per empty ow.
  std::vector<int32_t> st_of_bk(static_cast<size_t>(n_bk), -1);
  {
    std::vector<uint8_t> ow_present(static_cast<size_t>(n_ow), 0);
    for (int64_t b = 0; b < n_bk; ++b)
      if (cnt_bk[b] > 0) ow_present[b / n_gw] = 1;
    int64_t n_st = 0;
    for (int64_t ow = 0; ow < n_ow; ++ow) {
      if (ow_present[ow]) {
        for (int64_t g = 0; g < n_gw; ++g)
          if (cnt_bk[ow * n_gw + g] > 0) ++n_st;
      } else {
        ++n_st;  // dummy at (ow, gw=0)
      }
    }
    plan->n_st = n_st;
    plan->hi.assign(static_cast<size_t>(n_st) * GRR_SLOTS, 0);
    plan->vals.assign(static_cast<size_t>(n_st) * GRR_SLOTS, 0.0f);
    plan->dst.assign(static_cast<size_t>(n_st) * GRR_SLOTS, 0);
    plan->gw_of_st.resize(static_cast<size_t>(n_st));
    plan->ow_of_st.resize(static_cast<size_t>(n_st));
    plan->first_of_ow.resize(static_cast<size_t>(n_st));
    int32_t st = 0;
    int64_t prev_ow = -1;
    for (int64_t ow = 0; ow < n_ow; ++ow) {
      if (ow_present[ow]) {
        for (int64_t g = 0; g < n_gw; ++g) {
          const int64_t b = ow * n_gw + g;
          if (cnt_bk[b] <= 0) continue;
          st_of_bk[b] = st;
          plan->gw_of_st[st] = static_cast<int32_t>(g);
          plan->ow_of_st[st] = static_cast<int32_t>(ow);
          plan->first_of_ow[st] = (ow != prev_ow) ? 1 : 0;
          prev_ow = ow;
          ++st;
        }
      } else {
        plan->gw_of_st[st] = 0;
        plan->ow_of_st[st] = static_cast<int32_t>(ow);
        plan->first_of_ow[st] = 1;
        prev_ow = ow;
        ++st;
      }
    }
  }

  // Pass C: fill HI/VALS/DST + occupancy bitmaps + spill COO.
  const int64_t n_st = plan->n_st;
  std::vector<uint64_t> occ_s(static_cast<size_t>(n_st) * (GRR_SLOTS / 64), 0);
  std::vector<uint64_t> occ_f(static_cast<size_t>(n_st) * (GRR_SLOTS / 64), 0);
  {
    std::fill(r2cnt.begin(), r2cnt.end(), 0);
    if (!sorted) std::fill(qcnt.begin(), qcnt.end(), 0);
    int64_t run_key = -1, run_q = 0;
    for (int64_t e = 0; e < m_ell; ++e) {
      const float v = vals[e];
      if (v == 0.0f) continue;
      const int64_t r = e / k;
      const int64_t c = cols[e];
      int64_t idx = direction ? r : c;
      const int64_t seg = direction ? c : r;
      if (idx < idx_lo || idx >= idx_hi) continue;
      idx -= idx_lo;
      const int64_t gw = idx / GRR_WIN;
      int64_t q;
      if (sorted) {
        const int64_t key = seg * n_gw + gw;
        if (key != run_key) { run_key = key; run_q = 0; }
        q = run_q++;
      } else {
        uint8_t& qc = qcnt[seg * n_gw + gw];
        q = qc;
        if (qc < 255) ++qc;
      }
      bool spilled = q >= cap;
      int64_t l_s = 0;
      const int64_t bk = (seg / segwin) * n_gw + gw;
      // Start ROW = the entry's window sub-tile (idx%WIN)/128, so the
      // kernel gathers from the UNtransposed table window: row s of the
      // window holds table[gw*WIN + s*128 .. +127] and the gather plane
      // carries the lane residue idx%128.  (Previously rows were keyed
      // by residue, which required transposing every window per step —
      // two ~100 us XLA transpose fusions per objective pass at bench
      // shape.)
      const int64_t hrow = (idx % GRR_WIN) / GRR_TILE;
      if (!spilled) {
        uint16_t& r2 = r2cnt[bk * GRR_TILE + hrow];
        l_s = r2;
        ++r2;
        spilled = l_s >= GRR_TILE;
      }
      if (spilled) {
        plan->spill_idx.push_back(static_cast<int32_t>(idx));
        plan->spill_seg.push_back(static_cast<int32_t>(seg));
        plan->spill_val.push_back(v);
        continue;
      }
      const int64_t st = st_of_bk[bk];
      const int64_t b = seg % segwin;
      const int64_t s_start = hrow * GRR_TILE + l_s;
      const int64_t s_final =
          (q * group + b / GRR_TILE) * GRR_TILE + (b % GRR_TILE);
      const int64_t base = st * GRR_SLOTS;
      plan->hi[base + s_start] = static_cast<int8_t>(idx % GRR_TILE);
      plan->vals[base + s_final] = v;
      plan->dst[base + s_start] = static_cast<int32_t>(s_final);
      occ_s[(base + s_start) >> 6] |= (uint64_t{1} << (s_start & 63));
      occ_f[(base + s_final) >> 6] |= (uint64_t{1} << (s_final & 63));
    }
  }

  // Pass D: padding bijection — pair free starts with free finals in
  // order (same construction as the Python path).
  for (int64_t st = 0; st < n_st; ++st) {
    const int64_t base = st * GRR_SLOTS;
    int64_t f = 0;  // next candidate free final
    for (int64_t s = 0; s < GRR_SLOTS; ++s) {
      if (occ_s[(base + s) >> 6] & (uint64_t{1} << (s & 63))) continue;
      while (f < GRR_SLOTS &&
             (occ_f[(base + f) >> 6] & (uint64_t{1} << (f & 63))))
        ++f;
      plan->dst[base + s] = static_cast<int32_t>(f);
      ++f;
    }
  }

  // Spill padding to a multiple of 8.
  {
    const int64_t m = static_cast<int64_t>(plan->spill_idx.size());
    const int64_t m_pad = m ? ((m + 7) / 8) * 8 : 0;
    plan->spill_idx.resize(static_cast<size_t>(m_pad), 0);
    plan->spill_seg.resize(static_cast<size_t>(m_pad), 0);
    plan->spill_val.resize(static_cast<size_t>(m_pad), 0.0f);
    plan->n_spill = m_pad;
  }
}

}  // namespace

extern "C" {

void* pml_grr_plan(const int32_t* cols, const float* vals, int64_t n,
                   int64_t k, int32_t direction, int64_t table_len,
                   int64_t n_segments, int32_t cap_in, int64_t idx_lo,
                   int64_t idx_hi) {
  auto* plan = new (std::nothrow) GrrPlan();
  if (!plan) return nullptr;
  try {
    grr_plan_body(plan, cols, vals, n, k, direction, table_len,
                  n_segments, cap_in, idx_lo, idx_hi);
  } catch (const std::bad_alloc&) {
    plan->error = 2;  // caller falls back to the numpy path
  }
  return plan;
}

void pml_grr_plan_sizes(void* handle, int64_t* n_st, int64_t* n_spill,
                        int32_t* cap, int32_t* n_gw, int32_t* n_ow,
                        int32_t* error) {
  auto* p = static_cast<GrrPlan*>(handle);
  *n_st = p->n_st;
  *n_spill = p->n_spill;
  *cap = p->cap;
  *n_gw = p->n_gw;
  *n_ow = p->n_ow;
  *error = p->error;
}

void pml_grr_plan_fill(void* handle, int8_t* hi, float* vals, int32_t* dst,
                       int32_t* gw_of_st, int32_t* ow_of_st,
                       int32_t* first_of_ow, int32_t* spill_idx,
                       int32_t* spill_seg, float* spill_val) {
  auto* p = static_cast<GrrPlan*>(handle);
  std::memcpy(hi, p->hi.data(), p->hi.size());
  std::memcpy(vals, p->vals.data(), p->vals.size() * sizeof(float));
  std::memcpy(dst, p->dst.data(), p->dst.size() * sizeof(int32_t));
  std::memcpy(gw_of_st, p->gw_of_st.data(),
              p->gw_of_st.size() * sizeof(int32_t));
  std::memcpy(ow_of_st, p->ow_of_st.data(),
              p->ow_of_st.size() * sizeof(int32_t));
  std::memcpy(first_of_ow, p->first_of_ow.data(),
              p->first_of_ow.size() * sizeof(int32_t));
  if (p->n_spill) {
    std::memcpy(spill_idx, p->spill_idx.data(),
                p->spill_idx.size() * sizeof(int32_t));
    std::memcpy(spill_seg, p->spill_seg.data(),
                p->spill_seg.size() * sizeof(int32_t));
    std::memcpy(spill_val, p->spill_val.data(),
                p->spill_val.size() * sizeof(float));
  }
}

void pml_grr_plan_free(void* handle) { delete static_cast<GrrPlan*>(handle); }

}  // extern "C"
