// Native host-side ETL: the rebuild's C++ runtime for data preparation.
//
// Reference counterpart: the JVM executors' deserialization + shuffle
// machinery (Spark's netty/torrent substrate, SURVEY.md §5.8) and the
// Avro decode path of AvroDataReader [expected reference structure;
// mount unavailable].  The reference leans on the JVM for its data
// plane; the TPU rebuild's data plane is this library + numpy, feeding
// statically-shaped HBM arrays.
//
// Everything here is single-pass, cache-friendly C++ with no
// dependencies beyond the C++17 standard library.  The Python side
// (photon_ml_tpu.native) binds via ctypes and falls back to numpy
// implementations when the shared object is unavailable, so the
// framework never hard-depends on a compiler at runtime.
//
// Exposed surface (extern "C", handle-based two-phase protocol so the
// caller allocates numpy arrays of exactly the right size):
//
//   LIBSVM text  -> CSR-ish (row_ptr, cols, vals, labels)
//   row-ELL      -> transposed-ELL (the colmajor build: counting sort
//                   by column + virtual-row splitting; O(nnz + dim))

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>
#include <vector>

namespace {

struct LibsvmResult {
  std::vector<float> labels;
  std::vector<int64_t> row_ptr;  // [n+1]
  std::vector<int32_t> cols;
  std::vector<float> vals;
  int32_t max_col = -1;
};

// Minimal fast float parse: LIBSVM files carry plain decimal floats.
// strtof handles all forms; the win over Python is avoiding per-token
// object allocation, not exotic float parsing.
inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// LIBSVM parsing
// ---------------------------------------------------------------------------

void* pml_libsvm_parse(const char* buf, int64_t len) {
  auto* r = new (std::nothrow) LibsvmResult();
  if (!r) return nullptr;
  r->row_ptr.push_back(0);
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!line_end) line_end = end;
    p = skip_ws(p, line_end);
    if (p < line_end && *p != '#') {
      char* q = nullptr;
      float label = strtof(p, &q);
      if (q == p || q > line_end) {
        delete r;
        return nullptr;
      }
      p = q;
      while (p < line_end) {
        p = skip_ws(p, line_end);
        if (p >= line_end || *p == '#') break;
        long idx = strtol(p, &q, 10);
        if (q == p || q >= line_end || *q != ':') {
          delete r;
          return nullptr;
        }
        p = q + 1;
        float v = strtof(p, &q);
        // strtof may legally run past line_end (the buffer is contiguous
        // across lines), which would silently consume the next line's
        // tokens; a value must both exist and end within its own line.
        if (q == p || q > line_end) {
          delete r;
          return nullptr;
        }
        p = q;
        // Raw file index; 0/1-based conversion happens in Python
        // (vectorized), which also validates the resulting range.
        if (idx < 0 || idx > INT32_MAX) {
          delete r;
          return nullptr;
        }
        int32_t c = static_cast<int32_t>(idx);
        r->cols.push_back(c);
        r->vals.push_back(v);
        if (c > r->max_col) r->max_col = c;
      }
      r->labels.push_back(label);
      r->row_ptr.push_back(static_cast<int64_t>(r->cols.size()));
    }
    p = line_end + 1;
  }
  return r;
}

void pml_libsvm_sizes(void* handle, int64_t* n_rows, int64_t* nnz,
                      int32_t* max_col) {
  auto* r = static_cast<LibsvmResult*>(handle);
  *n_rows = static_cast<int64_t>(r->labels.size());
  *nnz = static_cast<int64_t>(r->cols.size());
  *max_col = r->max_col;
}

void pml_libsvm_fill(void* handle, float* labels, int64_t* row_ptr,
                     int32_t* cols, float* vals) {
  auto* r = static_cast<LibsvmResult*>(handle);
  memcpy(labels, r->labels.data(), r->labels.size() * sizeof(float));
  memcpy(row_ptr, r->row_ptr.data(), r->row_ptr.size() * sizeof(int64_t));
  memcpy(cols, r->cols.data(), r->cols.size() * sizeof(int32_t));
  memcpy(vals, r->vals.data(), r->vals.size() * sizeof(float));
}

void pml_libsvm_free(void* handle) {
  delete static_cast<LibsvmResult*>(handle);
}

// ---------------------------------------------------------------------------
// Transposed-ELL (colmajor) build — see data/colmajor.py for the design.
// Counting sort by column: O(nnz + dim), one read pass + one write pass.
// ---------------------------------------------------------------------------

// Phase 1: count virtual rows for (cols, vals, capacity).  Returns V, or
// -1 on invalid input.  col_counts must be a caller-zeroed [dim] int64
// scratch; it is left holding the per-column nonzero counts for phase 2.
int64_t pml_colmajor_vrows(const int32_t* cols, const float* vals,
                           int64_t n, int64_t k, int64_t dim,
                           int64_t capacity, int64_t* col_counts) {
  const int64_t total = n * k;
  for (int64_t e = 0; e < total; ++e) {
    if (vals[e] != 0.0f) {
      const int32_t c = cols[e];
      if (c < 0 || c >= dim) return -1;
      ++col_counts[c];
    }
  }
  int64_t v = 0;
  for (int64_t j = 0; j < dim; ++j) {
    v += (col_counts[j] + capacity - 1) / capacity;
  }
  return v;
}

// Phase 2: fill caller-allocated tvals [v_pad*capacity] (zeroed),
// trows [v_pad*capacity] (zeroed), vcol [v_pad] (zeroed).  col_counts is
// the phase-1 output.  Entries keep row order within each column
// (counting sort is stable in row-scan order).
void pml_colmajor_fill(const int32_t* cols, const float* vals,
                       int64_t n, int64_t k, int64_t dim,
                       int64_t capacity, const int64_t* col_counts,
                       int64_t v_pad, float* tvals, int32_t* trows,
                       int32_t* vcol) {
  // Per-column virtual-row base and running cursor.
  std::vector<int64_t> vrow_base(static_cast<size_t>(dim) + 1, 0);
  for (int64_t j = 0; j < dim; ++j) {
    vrow_base[static_cast<size_t>(j) + 1] =
        vrow_base[static_cast<size_t>(j)] +
        (col_counts[j] + capacity - 1) / capacity;
  }
  for (int64_t j = 0; j < dim; ++j) {
    const int64_t first = vrow_base[static_cast<size_t>(j)];
    const int64_t nv = vrow_base[static_cast<size_t>(j) + 1] - first;
    for (int64_t t = 0; t < nv; ++t) {
      vcol[first + t] = static_cast<int32_t>(j);
    }
  }
  std::vector<int64_t> cursor(static_cast<size_t>(dim), 0);
  for (int64_t i = 0; i < n; ++i) {
    const int64_t row_off = i * k;
    for (int64_t s = 0; s < k; ++s) {
      const float v = vals[row_off + s];
      if (v == 0.0f) continue;
      const int32_t c = cols[row_off + s];
      const int64_t pos = cursor[c]++;
      const int64_t vr = vrow_base[static_cast<size_t>(c)] + pos / capacity;
      const int64_t slot = pos % capacity;
      tvals[vr * capacity + slot] = v;
      trows[vr * capacity + slot] = static_cast<int32_t>(i);
    }
  }
  (void)v_pad;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Bipartite Euler-split edge coloring — the router for in-tile crossbars.
//
// A static permutation of a [R,128] VMEM tile is executed on TPU as
// lane-perm ∘ transpose ∘ lane-perm ∘ transpose ∘ lane-perm (see
// ops/crossbar.py).  The middle lane-perm is legal iff the edges
// (src_row → dst_row) are properly colored with 128 colors such that no
// two edges at the same vertex share a color.  With every vertex of
// degree exactly n_colors (a power of two; padding slots make this true
// by construction), repeated Euler splitting yields an exact coloring in
// O(m log n_colors): each split walks Euler circuits and alternates
// edges between halves, preserving even degrees.
// ---------------------------------------------------------------------------

namespace {

// One Euler-split level: partition edges[lo:hi) (indices into src/dst)
// into first half = color bit 0, second half = bit 1, by walking Euler
// circuits and alternating.  src[e] in [0,L), dst[e] in [0,R_n).
// Every vertex degree within the subset must be even.
void euler_split_level(const int32_t* src, const int32_t* dst,
                       int64_t* edge_ids, int64_t lo, int64_t hi,
                       int32_t n_left, int32_t n_right,
                       std::vector<int64_t>& head,
                       std::vector<int64_t>& nxt,
                       std::vector<int64_t>& prv,
                       std::vector<uint8_t>& used,
                       std::vector<uint8_t>& side_out) {
  // Build doubly-linked adjacency over vertices 0..n_left-1 (left) and
  // n_left..n_left+n_right-1 (right); each edge appears once per side
  // via two arc slots (2e, 2e+1).
  const int32_t nv = n_left + n_right;
  for (int32_t v = 0; v < nv; ++v) head[v] = -1;
  for (int64_t i = lo; i < hi; ++i) {
    const int64_t e = edge_ids[i];
    used[e] = 0;
    const int64_t a0 = 2 * e, a1 = 2 * e + 1;
    const int32_t u = src[e], w = n_left + dst[e];
    nxt[a0] = head[u]; prv[a0] = -1;
    if (head[u] >= 0) prv[head[u]] = a0;
    head[u] = a0;
    nxt[a1] = head[w]; prv[a1] = -1;
    if (head[w] >= 0) prv[head[w]] = a1;
    head[w] = a1;
  }
  auto detach = [&](int64_t arc, int32_t v) {
    if (prv[arc] >= 0) nxt[prv[arc]] = nxt[arc];
    else head[v] = nxt[arc];
    if (nxt[arc] >= 0) prv[nxt[arc]] = prv[arc];
  };
  // Walk circuits: from any vertex with remaining edges, follow unused
  // edges until returning; alternate sides along the walk.  On a graph
  // with all even degrees the walk can only get stuck at its start
  // vertex, at which point we continue from any still-incident vertex.
  for (int64_t i = lo; i < hi; ++i) {
    const int64_t e0 = edge_ids[i];
    if (used[e0]) continue;
    int32_t v = src[e0];
    uint8_t side = 0;
    while (head[v] >= 0) {
      const int64_t arc = head[v];
      const int64_t e = arc >> 1;
      const int32_t u = src[e], w = n_left + dst[e];
      detach(2 * e, u);
      detach(2 * e + 1, w);
      used[e] = 1;
      side_out[e] = side;
      side ^= 1;
      v = (v == u) ? w : u;
    }
  }
}

}  // namespace

extern "C" {

// Color m edges (src[e] in [0,n_left), dst[e] in [0,n_right)) with
// n_colors colors (power of two).  Every left/right vertex must have
// degree divisible by n_colors... in the crossbar use-case degree ==
// n_colors exactly.  Writes color[e] in [0, n_colors).  Returns 0, or
// -1 on invalid arguments.
int32_t pml_edge_color(const int32_t* src, const int32_t* dst, int64_t m,
                       int32_t n_left, int32_t n_right, int32_t n_colors,
                       int32_t* color) {
  if (n_colors <= 0 || (n_colors & (n_colors - 1)) != 0) return -1;
  if (m < 0 || n_left <= 0 || n_right <= 0) return -1;
  // Vertex-range validation before touching the adjacency arrays: an
  // out-of-range id would index head/nxt/prv out of bounds (heap
  // corruption reachable from Python via edge_color_native).
  for (int64_t e = 0; e < m; ++e) {
    if (src[e] < 0 || src[e] >= n_left || dst[e] < 0 || dst[e] >= n_right)
      return -1;
  }
  std::vector<int64_t> edge_ids(static_cast<size_t>(m));
  for (int64_t e = 0; e < m; ++e) { edge_ids[e] = e; color[e] = 0; }
  std::vector<int64_t> head(static_cast<size_t>(n_left + n_right));
  std::vector<int64_t> nxt(static_cast<size_t>(2 * m));
  std::vector<int64_t> prv(static_cast<size_t>(2 * m));
  std::vector<uint8_t> used(static_cast<size_t>(m));
  std::vector<uint8_t> side(static_cast<size_t>(m));
  std::vector<int64_t> scratch(static_cast<size_t>(m));

  // Iterative halving: ranges of edge_ids sharing a color prefix are
  // split; bit b of the color is assigned at level b (MSB first).
  int32_t levels = 0;
  for (int32_t c = n_colors; c > 1; c >>= 1) ++levels;
  std::vector<std::pair<int64_t, int64_t>> ranges{{0, m}};
  for (int32_t level = 0; level < levels; ++level) {
    std::vector<std::pair<int64_t, int64_t>> next_ranges;
    for (auto [lo, hi] : ranges) {
      if (hi - lo == 0) continue;
      euler_split_level(src, dst, edge_ids.data(), lo, hi, n_left,
                        n_right, head, nxt, prv, used, side);
      // Stable partition: side 0 first.
      int64_t w0 = lo;
      for (int64_t i = lo; i < hi; ++i)
        if (!side[edge_ids[i]]) scratch[w0++] = edge_ids[i];
      int64_t mid = w0;
      for (int64_t i = lo; i < hi; ++i)
        if (side[edge_ids[i]]) scratch[w0++] = edge_ids[i];
      for (int64_t i = lo; i < hi; ++i) edge_ids[i] = scratch[i];
      const int32_t bit = 1 << (levels - 1 - level);
      for (int64_t i = mid; i < hi; ++i) color[edge_ids[i]] |= bit;
      next_ranges.emplace_back(lo, mid);
      next_ranges.emplace_back(mid, hi);
    }
    ranges = std::move(next_ranges);
  }
  return 0;
}

// Batched GRR route builder: for each [128,128] supertile, color the
// start→final slot permutation (dst[t][r*128+l] = final slot of the
// element starting at (r, l)) and emit the three lane-gather stages the
// kernel executes (ops/grr_kernel.py), with route stage 1 pre-composed
// with the gather index plane hi.  This is the hot part of compiling a
// sparse matrix into the GRR plan (data/grr.py) — one Euler-split
// coloring per supertile, O(slots · log 128) each.
// Returns 0, or -1 if any tile's dst is not a bijection / coloring
// arguments are invalid.
int32_t pml_grr_routes(const int32_t* dst, const int8_t* hi, int64_t n_st,
                       int8_t* g1, int8_t* g2, int8_t* g3) {
  constexpr int32_t T = 128;
  constexpr int64_t S = static_cast<int64_t>(T) * T;
  std::vector<int32_t> src_row(S), dst_row(S), color(S);
  std::vector<uint8_t> seen(S);
  for (int64_t e = 0; e < S; ++e) src_row[e] = static_cast<int32_t>(e >> 7);

  for (int64_t t = 0; t < n_st; ++t) {
    const int32_t* d = dst + t * S;
    const int8_t* h = hi + t * S;
    std::memset(seen.data(), 0, static_cast<size_t>(S));
    for (int64_t e = 0; e < S; ++e) {
      const int32_t v = d[e];
      if (v < 0 || v >= S || seen[v]) return -1;
      seen[v] = 1;
      dst_row[e] = v >> 7;
    }
    if (pml_edge_color(src_row.data(), dst_row.data(), S, T, T, T,
                       color.data()) != 0)
      return -1;
    int8_t* G1 = g1 + t * S;
    int8_t* G2 = g2 + t * S;
    int8_t* G3 = g3 + t * S;
    for (int64_t e = 0; e < S; ++e) {
      const int32_t r = src_row[e];
      const int32_t l = static_cast<int32_t>(e & (T - 1));
      const int32_t c = color[e];
      const int32_t dr = dst_row[e];
      const int32_t dl = d[e] & (T - 1);
      G1[r * T + c] = h[r * T + l];
      G2[c * T + dr] = static_cast<int8_t>(r);
      G3[dr * T + dl] = static_cast<int8_t>(c);
    }
  }
  return 0;
}

}  // extern "C"
