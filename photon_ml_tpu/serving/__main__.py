"""Model-server CLI: ``python -m photon_ml_tpu.serving --config
serve.json``.

Runs the persistent scoring process until SIGTERM/SIGINT, then drains
gracefully (queued requests finish, then the endpoint closes).  The
last stdout line is one JSON object (the repo's CLI contract) carrying
the final serving status — requests, swaps, peak RSS.

With ``replicas > 1`` (config or ``--replicas``) the process runs the
SUPERVISED FLEET instead (ISSUE 13): N replica server subprocesses
behind one health-routed frontend — the frontend binds the configured
port, replicas take ephemeral ports and are restarted on crash/wedge
with backoff + circuit breaker, and a newly published manifest rolls
through the replicas one at a time.

``--info-file`` writes ``{"port", "pid", "url"}`` as soon as the
socket binds (atomic tmp + replace), so a supervisor or the bench's
client harness can discover an ephemeral port and poll ``/healthz``
for warming → ready.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

from photon_ml_tpu.config import load_serving_config
from photon_ml_tpu.utils.run_log import DEFAULT_FLUSH_EVERY_S, RunLogger


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.serving",
        description="photon-ml-tpu online model server")
    p.add_argument("--config", required=True,
                   help="serving config JSON (ServingConfig)")
    p.add_argument("--port", type=int, default=None,
                   help="override config port (0 = ephemeral)")
    p.add_argument("--model-dir", default=None,
                   help="override config model_dir")
    p.add_argument("--spill-dir", default=None,
                   help="override config spill_dir (entity store disk "
                        "tier)")
    p.add_argument("--hot-swap-poll-s", type=float, default=None,
                   dest="hot_swap_poll_s",
                   help="override config hot_swap_poll_s (0 = off)")
    p.add_argument("--replicas", type=int, default=None,
                   help="override config replicas (>1 = supervised "
                        "fleet behind one frontend)")
    p.add_argument("--fleet-dir", default=None,
                   help="fleet workdir (replica configs/logs/info "
                        "files; default: a temp dir)")
    p.add_argument("--info-file", default=None,
                   help="write {port, pid, url} JSON here once the "
                        "socket binds (atomic)")
    args = p.parse_args(argv)
    config = load_serving_config(args.config)
    for name in ("port", "model_dir", "spill_dir", "hot_swap_poll_s",
                 "replicas"):
        val = getattr(args, name)
        if val is not None:
            setattr(config, name, val)
    config.validate()

    log = RunLogger(config.log_path,
                    run_info={"driver": "serving",
                              "model_dir": config.model_dir,
                              "replicas": config.replicas},
                    flush_every_s=DEFAULT_FLUSH_EVERY_S)
    if config.replicas > 1:
        from photon_ml_tpu.serving.fleet import FleetServer

        server = FleetServer(config, run_logger=log,
                             workdir=args.fleet_dir)
    else:
        from photon_ml_tpu.serving.server import ModelServer

        server = ModelServer(config, run_logger=log)
    if args.info_file:
        info = {"port": server.port, "pid": os.getpid(),
                "url": f"http://{config.host}:{server.port}"}
        tmp = args.info_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, args.info_file)

    def _stop(signum, frame):
        # Idempotent: the drain happens in the main thread below.
        server._stop_evt.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    rc = 0
    try:
        server.start()
        server.serve_forever()
    except Exception as e:
        print(f"serving failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        rc = 1
    finally:
        status = server.serving_status()
        server.stop()
        log.close()
        print(json.dumps({"serving": status, "rc": rc}))
    return rc


if __name__ == "__main__":
    sys.exit(main())
