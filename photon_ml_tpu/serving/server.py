"""ModelServer: the persistent online scoring process (ISSUE 12).

Lifecycle:

1. **Bind first** — the HTTP endpoint comes up immediately with
   ``/healthz`` = 503 ``warming``, so orchestrators can probe from the
   first second of the process's life.
2. **Load** the model through the one shared loading path
   (``io.model_io.load_game_model`` — checkpoint manifest preferred,
   legacy layout accepted) and build the ``ScoringEngine`` (device
   tables + mmap'd entity stores).
3. **Warm** every micro-batch bucket: each closed shape compiles (or
   warm-loads from the persistent XLA cache) before readiness flips,
   so the FIRST request pays zero compiles.
4. **Serve**: ``POST /v1/score`` → parse → micro-batch → one fused
   device dispatch; ``/status`` + ``/metrics`` + ``/healthz`` ride the
   same port (the monitor's observer routes, shared code).
5. **Hot swap**: a watcher thread polls the model dir's manifest
   signature; a newly published manifest (``os.replace`` atomic) loads
   and warms OFF the request path, then swaps in between batches —
   zero dropped requests, old entity-store windows dropped after the
   in-flight batch drains.  A corrupt/unreadable manifest keeps the
   previous good model and counts a ``serve.swap_failures``.

Instrumentation rides the existing tiers: a telemetry session
(request/batch latency histograms, queue-depth gauge, batch-fill
counters — all visible at ``/metrics``) and a monitor session whose
online alert rules (incl. ``serve_tail_latency``) watch the registry.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.config import ServingConfig
from photon_ml_tpu.serving.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    ServerClosing,
    ServerOverloaded,
    ServerSaturated,
)
from photon_ml_tpu.serving import tracing
from photon_ml_tpu.serving.engine import BadRequest, ScoringEngine
from photon_ml_tpu.serving.http import (
    READY,
    STOPPING,
    WARMING,
    HttpEndpoint,
    HttpError,
    Readiness,
)

logger = logging.getLogger(__name__)


def _manifest_signature(model_dir: str) -> tuple | None:
    """Change-detection signature of the model source: the manifest
    file's (mtime_ns, size) when present, else the legacy
    metadata.json's.  ``os.replace`` publication always moves it."""
    from photon_ml_tpu.io.model_io import model_manifest_path

    for path in (model_manifest_path(model_dir),
                 os.path.join(model_dir, "metadata.json")):
        try:
            st = os.stat(path)
            return (path, st.st_mtime_ns, st.st_size)
        except OSError:  # photon-lint: disable=swallowed-exception (an absent candidate means "try the next layout"; a fully absent model dir returns None and the caller raises with context)
            continue
    return None


def _peak_rss_mb() -> float | None:
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return ru / 1024.0 if os.uname().sysname == "Linux" \
            else ru / (1024.0 * 1024.0)
    except Exception:  # photon-lint: disable=swallowed-exception (RSS is advisory status info; platforms without rusage report null)
        return None


class ModelServer:
    """The serving process.  ``start()`` binds, loads, warms, and
    flips ready; ``serve_forever()`` blocks until ``stop()`` (or
    SIGTERM via ``__main__``)."""

    def __init__(self, config: ServingConfig, run_logger=None):
        config.validate()
        self.config = config
        self._log = run_logger
        self._lock = threading.Lock()
        self._engine: ScoringEngine | None = None
        self._engine_sig: tuple | None = None
        self.readiness = Readiness(
            WARMING, reason="model load + bucket warm-up in progress")
        self._batcher: MicroBatcher | None = None
        self._watcher: threading.Thread | None = None
        # _stop_evt wakes serve_forever()/the watcher (the CLI's signal
        # handler sets it directly); _stopped is stop()'s OWN idempotency
        # latch — reusing the event would make a signal-initiated stop()
        # skip the entire drain (the event is already set by then).
        self._stop_evt = threading.Event()
        self._stopped = False
        self._monitor = None
        self._telemetry = None
        self._tracer = None
        self.swaps = 0
        self.swap_failures = 0
        self.last_swap_error: str | None = None
        self.t0 = time.monotonic()
        # Bind AND serve immediately: a probe must get its 503
        # ``warming`` from the first moment of the process's life, not
        # hang in the accept backlog until the model is loaded.
        self._http = HttpEndpoint(self._routes(),
                                  readiness=self.readiness,
                                  port=config.port, host=config.host,
                                  request_timeout_s=config.http_timeout_s)
        self._http.start()
        self.port = self._http.port

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ModelServer":
        from photon_ml_tpu.cache import enable_compilation_cache
        from photon_ml_tpu.telemetry import monitor as _mon

        cfg = self.config
        enable_compilation_cache(cfg.compilation_cache_dir)
        logger.info("model server bound on http://%s:%d (warming)",
                    cfg.host, self.port)
        if cfg.telemetry != "off" and telemetry.active() is None:
            self._telemetry = telemetry.start(
                cfg.telemetry, run_logger=self._log)
        if cfg.monitor == "on" and _mon.active() is None:
            self._monitor = _mon.start(
                run_logger=self._log, every_s=cfg.monitor_every_s)
        if cfg.trace == "on" and tracing.active() is None:
            self._tracer = tracing.start(
                role="replica",
                threshold_s=cfg.trace_threshold_ms / 1e3,
                sample_every=cfg.trace_sample_every,
                cap=cfg.trace_buffer, run_logger=self._log)
        try:
            engine = self._load_engine()
            engine.warm(cfg.buckets())
            with self._lock:
                self._engine = engine
                self._engine_sig = _manifest_signature(cfg.model_dir)
            self._batcher = MicroBatcher(
                self._current_engine, cfg.buckets(),
                deadline_s=cfg.batch_deadline_ms / 1e3,
                max_queue=cfg.max_queue)
        except BaseException:
            self.readiness.set(STOPPING, reason="startup failed")
            raise
        self.readiness.set(READY)
        if self._monitor is not None:
            self._monitor.mark_ready()
        self._event("serving_ready", port=self.port,
                    model_version=engine.version,
                    buckets=cfg.buckets())
        logger.info("model server READY on http://%s:%d "
                    "(model %s, buckets %s)", cfg.host, self.port,
                    engine.version, cfg.buckets())
        if cfg.hot_swap_poll_s > 0:
            self._watcher = threading.Thread(
                target=self._watch, daemon=True,
                name="photon-serve-swap-watcher")
            self._watcher.start()
        return self

    def serve_forever(self) -> None:
        # photon-lint: disable=eternal-wait (the main thread parks until stop() or the CLI signal handler sets the event; there is nothing to time out toward)
        self._stop_evt.wait()

    def stop(self) -> None:
        """Graceful drain: refuse new work, score the queue, stop the
        watcher and endpoint, close sessions.  Idempotent."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self.readiness.set(STOPPING, reason="draining")
        self._stop_evt.set()
        if self._watcher is not None:
            self._watcher.join(timeout=10.0)
        if self._batcher is not None:
            self._batcher.close()
        self._http.close()
        with self._lock:
            engine, self._engine = self._engine, None
        if engine is not None:
            engine.close()
        if self._tracer is not None:
            self._tracer.close()
        if self._monitor is not None:
            self._monitor.close()
        if self._telemetry is not None:
            self._telemetry.close()
        self._event("serving_stopped", swaps=self.swaps,
                    swap_failures=self.swap_failures)

    def _event(self, kind: str, **fields) -> None:
        if self._log is not None:
            self._log.event(kind, **fields)

    # -- model load / hot swap ----------------------------------------------

    def _load_engine(self) -> ScoringEngine:
        from photon_ml_tpu.io.model_io import load_game_model
        from photon_ml_tpu.reliability import faults

        cfg = self.config
        sig = _manifest_signature(cfg.model_dir)
        if sig is None:
            raise FileNotFoundError(
                f"no model manifest or metadata.json under "
                f"{cfg.model_dir!r}")
        version = f"{sig[1]:x}-{sig[2]:x}"
        t0 = time.perf_counter()
        # The swap-manifest fault seam: corrupt_file/delete_file kinds
        # hit the real manifest on disk, so the watcher's
        # keep-previous-good-model contract is injectable (ISSUE 13).
        faults.fire("serve.manifest_load", path=sig[0])
        with telemetry.span("serve_model_load", cat="serve"):
            model, task = load_game_model(cfg.model_dir)
            engine = ScoringEngine(
                model, task, version=version,
                ell_row_capacity=cfg.ell_row_capacity,
                dense_feature_shards=tuple(cfg.dense_feature_shards),
                spill_dir=cfg.spill_dir, entity_chunk=cfg.entity_chunk,
                host_max_resident=cfg.host_max_resident)
        logger.info("loaded model %s from %s in %.2fs", version,
                    cfg.model_dir, time.perf_counter() - t0)
        return engine

    def _current_engine(self) -> ScoringEngine:
        with self._lock:
            engine = self._engine
        if engine is None:
            raise ServerClosing("no engine (server stopping)")
        return engine

    def _watch(self) -> None:
        """Swap watcher: poll the manifest signature; load + warm a
        changed model OFF the request path, then swap atomically."""
        cfg = self.config
        while not self._stop_evt.wait(cfg.hot_swap_poll_s):
            sig = None
            try:
                sig = _manifest_signature(cfg.model_dir)
                with self._lock:
                    current = self._engine_sig
                if sig is None or sig == current:
                    continue
                self._event("serving_swap_detected", signature=list(sig))
                engine = self._load_engine()
                # Warm BEFORE the swap: with an unchanged model
                # structure every bucket hits the in-process jit cache
                # (zero compiles); a changed structure compiles here,
                # off the request path.
                engine.warm(cfg.buckets())
                with self._lock:
                    old, self._engine = self._engine, engine
                    self._engine_sig = sig
                    self.swaps += 1
                    self.last_swap_error = None
                telemetry.count("serve.swaps")
                # In-flight batches resolved the old engine before the
                # swap; the single dispatcher thread means at most ONE
                # such batch — drained by the time any close matters.
                # Retiring = dropping its entity-store windows.
                if old is not None:
                    old.close()
                self._event("serving_swapped",
                            model_version=engine.version)
                logger.info("hot-swapped to model %s", engine.version)
            except Exception as e:
                # A bad manifest (torn copy, corrupt file, wrong
                # schema) must never take the server down: keep the
                # previous good model, record, keep polling — the NEXT
                # good publish swaps normally.
                with self._lock:
                    self.swap_failures += 1
                    self.last_swap_error = f"{type(e).__name__}: {e}"
                    # Remember the bad signature so one corrupt file
                    # logs one failure, not one per poll.
                    self._engine_sig = sig
                telemetry.count("serve.swap_failures")
                self._event("serving_swap_failed",
                            error=self.last_swap_error)
                logger.warning("hot swap failed (%s); keeping model %s",
                               self.last_swap_error,
                               self._current_engine().version)

    # -- HTTP surface --------------------------------------------------------

    def _routes(self) -> dict:
        return {
            ("POST", "/v1/score"): self._route_score,
            ("GET", "/status"): self._route_status,
            ("GET", "/metrics"): self._route_metrics,
        }

    def _route_score(self, body: bytes):
        # Request trace (ISSUE 14): begun here, finished by the HTTP
        # core after the response write — sheds and errors included.
        t0 = time.perf_counter()
        rt = tracing.begin()
        if self.readiness.state != READY:
            state, reason = self.readiness.snapshot()
            raise HttpError(503, error=f"server is {state}",
                            **({"reason": reason} if reason else {}))
        try:
            payload = json.loads(body) if body else None
        except json.JSONDecodeError as e:
            raise HttpError(400, error=f"malformed JSON body: {e}")
        if not isinstance(payload, dict) or "rows" not in payload:
            raise HttpError(400, error="body must be a JSON object "
                                       "with a 'rows' list")
        engine = self._current_engine()
        try:
            parsed = engine.parse_rows(payload["rows"])
        except BadRequest as e:
            raise HttpError(400, error=str(e))
        try:
            margins, preds, version, degraded = self._batcher.submit(
                parsed, timeout_s=self.config.request_timeout_s,
                trace=rt, t_admit=t0)
        except ServerSaturated as e:
            raise HttpError(429, error=str(e), headers={
                "Retry-After": f"{e.retry_after_s:.0f}"})
        except (ServerOverloaded, DeadlineExceeded) as e:
            # Overload sheds (admission control / queued-past-deadline)
            # answer 503 + Retry-After: a fast, honest "not now", never
            # a queue-collapse timeout.
            if rt is not None and rt.shed is None:
                rt.shed = "deadline"
            raise HttpError(503, error=str(e), headers={
                "Retry-After": f"{e.retry_after_s:.0f}"})
        except ServerClosing as e:
            raise HttpError(503, error=str(e))
        except TimeoutError as e:
            raise HttpError(503, error=str(e))
        if degraded:
            telemetry.count("serve.degraded_responses")
        t_ser = 0.0 if rt is None else time.perf_counter()
        out = {"margins": [float(v) for v in margins],
               "predictions": [float(v) for v in preds],
               "model_version": version,
               "n": int(len(margins)),
               **({"degraded": True} if degraded else {})}
        payload_json = json.dumps(out)
        if rt is not None:
            rt.stamp("serialize", time.perf_counter() - t_ser)
            rt.rows = int(len(margins))
            rt.degraded = bool(degraded)
        return 200, payload_json, "application/json"

    def serving_status(self) -> dict:
        with self._lock:
            engine = self._engine
            swaps, failures = self.swaps, self.swap_failures
            last_err = self.last_swap_error
        rec = tracing.active()
        stages = tracing.stage_summary()
        return {
            "state": self.readiness.state,
            "uptime_s": round(time.monotonic() - self.t0, 1),
            "model": engine.describe() if engine is not None else None,
            "batcher": (self._batcher.stats()
                        if self._batcher is not None else None),
            "swaps": swaps,
            "swap_failures": failures,
            **({"last_swap_error": last_err} if last_err else {}),
            **({"tracing": rec.snapshot()} if rec is not None else {}),
            **({"stages": stages} if stages else {}),
            "peak_rss_mb": _peak_rss_mb(),
        }

    def _route_status(self, body: bytes):
        st = {"serving": self.serving_status()}
        if self._monitor is not None:
            st.update(self._monitor.status())
        return 200, json.dumps(st), "application/json"

    def _route_metrics(self, body: bytes):
        from photon_ml_tpu.telemetry.monitor import prometheus_text

        text = prometheus_text(self._monitor)
        return 200, text, "text/plain; version=0.0.4"
