"""Supervised replica fleet (ISSUE 13 tentpole).

PR 12's ``ModelServer`` is one process: one wedged handler, one corrupt
mmap, or one OOM takes the whole scoring path down.  This module is the
Snap ML cluster→node hierarchy one level up — a fleet of replica
``ModelServer`` subprocesses, each wrapping the already-warmed fused
engine, behind one supervised frontend (``serving.frontend``):

- **Spawn**: ``FleetSupervisor`` launches ``config.replicas`` replica
  processes (``python -m photon_ml_tpu.serving``) on ephemeral ports,
  discovered through the existing ``--info-file`` contract.  Replicas
  run with their own hot-swap watcher OFF — the supervisor owns swap
  coordination (rolling, below).
- **Probe**: each replica's ``/healthz`` is polled every
  ``probe_every_s`` (the ``serve.replica_healthz`` fault seam).  A
  crashed process, or a live one failing ``unhealthy_after``
  consecutive probes (wedged), is killed and restarted.
- **Restart policy**: bounded exponential backoff per replica
  (``restart_backoff_s`` doubling to ``restart_backoff_max_s``), and a
  circuit breaker — ``breaker_threshold`` restarts inside
  ``breaker_window_s`` opens the breaker for ``breaker_reset_s``
  (state ``broken``, no restarts), then ONE half-open attempt either
  closes it (ready) or re-opens it.  A flapping replica cannot consume
  the host in a restart storm.
- **Rolling hot swap**: a newly published model manifest recycles
  replicas ONE at a time — cordon (the frontend stops routing), drain
  outstanding requests, SIGTERM, respawn against the new manifest,
  wait ready — and the next recycle only starts when every other
  replica is ready, so the fleet never dips below N−1 ready.  A
  replica that cannot come up on the new manifest (corrupt publish)
  aborts the swap: the remaining replicas keep serving the previous
  good model.

Everything observable rides the existing tiers: ``fleet.*`` telemetry
counters/gauges (``fleet.replica_restarts`` is the monitor's
``replica_restarts`` alert rule input), ``fleet_*`` run-log events, and
the aggregated ``/status`` fleet view served by the frontend.

Testability: replica processes hide behind the ``launch()`` seam — the
tier-1 fault matrix drives the supervisor against in-process stub
replicas with a fake clock (no subprocess, no sleeps), while the
slow-marked e2e and the bench fleet arm use the real
``SubprocessReplicaLauncher``.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

from photon_ml_tpu import telemetry
from photon_ml_tpu.config import ServingConfig, config_to_json
from photon_ml_tpu.reliability import faults
from photon_ml_tpu.serving import tracing as _tracing
from photon_ml_tpu.serving.server import _manifest_signature

logger = logging.getLogger(__name__)

# Replica lifecycle states (frontend routes only READY replicas).
STARTING = "starting"     # spawned, warming (or info file pending)
READY = "ready"           # probed healthy; in rotation
DRAINING = "draining"     # cordoned for rolling swap
DOWN = "down"             # dead/wedged; restart scheduled (backoff)
BROKEN = "broken"         # circuit breaker open; no restarts

# Rolling-swap drain/exit budgets (seconds on the supervisor clock).
DRAIN_TIMEOUT_S = 30.0
EXIT_TIMEOUT_S = 10.0


class Replica:
    """One replica's supervised record.  All mutable fields are
    guarded by the supervisor's lock; the control thread is the only
    state writer, the frontend only bumps ``outstanding``."""

    def __init__(self, idx: int):
        self.idx = idx
        self.handle: "ReplicaHandle | None" = None
        self.state = DOWN
        self.url: str | None = None
        self.outstanding = 0          # in-flight frontend requests
        self.served = 0               # total requests routed here
        self.restarts = 0             # restarts after a failure
        self.probe_failures = 0       # consecutive
        self.restart_times: list[float] = []   # breaker window
        self.backoff_s = 0.0          # next restart delay
        self.restart_at: float | None = None   # scheduled restart time
        self.breaker_open_until: float | None = None
        self.half_open = False
        self.recycling = False        # down for a rolling swap, not a
        self.down_since: float | None = None     # ...crash
        self.spawned_at: float | None = None
        self.last_restart_s: float | None = None
        self.last_error: str | None = None

    def snapshot(self) -> dict:
        return {
            "idx": self.idx,
            "state": self.state,
            "url": self.url,
            "pid": self.handle.pid() if self.handle else None,
            "outstanding": self.outstanding,
            "served": self.served,
            "restarts": self.restarts,
            "probe_failures": self.probe_failures,
            "last_restart_s": self.last_restart_s,
            **({"last_error": self.last_error}
               if self.last_error else {}),
        }


class ReplicaHandle:
    """The process seam: what the supervisor needs from a replica
    process.  ``SubprocessReplicaHandle`` is the real one; tests stub
    it with in-process endpoints."""

    def poll(self) -> int | None:          # None = alive
        raise NotImplementedError

    def url(self) -> str | None:           # None until discovered
        raise NotImplementedError

    def pid(self) -> int | None:
        return None

    def terminate(self) -> None:           # graceful (SIGTERM)
        raise NotImplementedError

    def kill(self) -> None:                # hard (SIGKILL)
        raise NotImplementedError

    def wait(self, timeout_s: float) -> int | None:
        raise NotImplementedError


class SubprocessReplicaHandle(ReplicaHandle):
    def __init__(self, proc: subprocess.Popen, info_path: str):
        self._proc = proc
        self._info_path = info_path
        self._url: str | None = None

    def poll(self) -> int | None:
        return self._proc.poll()

    def url(self) -> str | None:
        if self._url is None:
            try:
                with open(self._info_path) as f:
                    self._url = json.load(f)["url"]
            except (OSError, ValueError, KeyError):  # photon-lint: disable=swallowed-exception (the info file simply has not been written yet; the caller treats None as still-starting)
                return None
        return self._url

    def pid(self) -> int | None:
        return self._proc.pid

    def terminate(self) -> None:
        if self._proc.poll() is None:
            self._proc.terminate()

    def kill(self) -> None:
        if self._proc.poll() is None:
            self._proc.kill()

    def wait(self, timeout_s: float) -> int | None:
        try:
            return self._proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:  # photon-lint: disable=swallowed-exception (the timeout IS the result: None tells the caller the process is still alive and escalation — SIGKILL — is its decision)
            return None


class SubprocessReplicaLauncher:
    """Launches real replica processes: one derived single-replica
    config each (ephemeral port, supervisor-owned swap), stdout/stderr
    to per-replica files under the fleet workdir, port discovery via
    ``--info-file``."""

    def __init__(self, config: ServingConfig, workdir: str):
        self.config = config
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)

    def _replica_config_path(self, idx: int) -> str:
        import dataclasses

        cfg = dataclasses.replace(
            self.config, replicas=1, port=0, hot_swap_poll_s=0.0,
            log_path=os.path.join(self.workdir,
                                  f"replica_{idx}.jsonl"))
        path = os.path.join(self.workdir, f"replica_{idx}.json")
        with open(path, "w") as f:
            f.write(config_to_json(cfg))
        return path

    @staticmethod
    def _replica_env() -> dict:
        """The child environment: inherit, but pin JAX_PLATFORMS to
        the supervisor's RESOLVED backend when the env does not pin
        one.  An unset JAX_PLATFORMS makes the replica probe every
        plugin at its own jax init — on a TPU-less box the libtpu
        plugin spends MINUTES timing out against the cloud metadata
        endpoint before falling back to CPU, which reads as a replica
        that never warms.  Where the env already pins a platform
        (production images do) this is a no-op."""
        env = dict(os.environ)  # photon-lint: disable=env-read (whole-environment passthrough for the replica subprocess, not a config-knob read; JAX_PLATFORMS is jax's own variable, not a photon knob for the sanctioned registry)
        if "JAX_PLATFORMS" not in env:
            try:
                import jax

                # Prefer the CONFIGURED platform string (set by e.g.
                # jax.config.update("jax_platforms", ...) — reading it
                # initializes nothing); only fall back to
                # default_backend(), which initializes the supervisor's
                # backend — a one-time cost here, amortized over every
                # replica spawn/restart that would otherwise each pay
                # the full plugin probe.
                platforms = None
                try:
                    platforms = jax.config.jax_platforms
                except Exception:  # photon-lint: disable=swallowed-exception (older jax without the config attr: fall through to default_backend)
                    pass
                env["JAX_PLATFORMS"] = (platforms
                                        or jax.default_backend())
            except Exception:  # photon-lint: disable=swallowed-exception (no jax in the supervisor process: the replica resolves its own platform exactly as before)
                pass
        return env

    def launch(self, idx: int) -> ReplicaHandle:
        cfg_path = self._replica_config_path(idx)
        info_path = os.path.join(self.workdir, f"replica_{idx}.info")
        # A stale info file from the previous incarnation would hand
        # the supervisor a dead port; remove before spawn.
        if os.path.exists(info_path):
            os.remove(info_path)
        out = open(os.path.join(self.workdir, f"replica_{idx}.out"),
                   "ab")
        err = open(os.path.join(self.workdir, f"replica_{idx}.err"),
                   "ab")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "photon_ml_tpu.serving",
                 "--config", cfg_path, "--info-file", info_path],
                stdout=out, stderr=err, env=self._replica_env())
        finally:
            out.close()
            err.close()
        logger.info("fleet: launched replica %d (pid %d)", idx,
                    proc.pid)
        return SubprocessReplicaHandle(proc, info_path)


class FleetSupervisor:
    """The control loop: spawn, probe, restart, breaker, rolling swap.

    The thread started by ``start()`` calls ``_step()`` every
    ``probe_every_s``; tests drive ``_step()`` directly with a fake
    clock and a stub launcher.  One lock guards every replica record;
    network probes run outside it.
    """

    def __init__(self, config: ServingConfig, launcher=None,
                 run_logger=None, workdir: str | None = None,
                 clock=time.monotonic, watch_manifest: bool = True):
        config.validate()
        self.config = config
        self.workdir = workdir or tempfile.mkdtemp(
            prefix="photon-fleet-")
        self.launcher = launcher if launcher is not None else \
            SubprocessReplicaLauncher(config, self.workdir)
        self._log = run_logger
        self._clock = clock
        self._lock = threading.Lock()
        self.replicas = [Replica(i) for i in range(config.replicas)]
        self._watch_manifest = watch_manifest
        self._last_sig: tuple | None = None
        self._pending_sig: tuple | None = None
        self._swap: dict | None = None
        self.swaps = 0
        self.swap_aborts = 0
        self.last_swap_error: str | None = None
        self._frontend = None
        self._stop_evt = threading.Event()
        self._stopped = False
        self._thread: threading.Thread | None = None

    # -- wiring --------------------------------------------------------------

    def attach_frontend(self, frontend) -> None:
        """The frontend's readiness follows the fleet's ready count
        (updated at the end of every step)."""
        with self._lock:
            self._frontend = frontend

    def _event(self, kind: str, **fields) -> None:
        if self._log is not None:
            self._log.event(kind, **fields)

    # -- lifecycle -----------------------------------------------------------

    def spawn_all(self) -> None:
        if self._watch_manifest:
            sig = _manifest_signature(self.config.model_dir)
            with self._lock:
                self._last_sig = sig
        now = self._clock()
        for r in self.replicas:
            self._spawn(r, now)

    def start(self) -> "FleetSupervisor":
        self.spawn_all()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="photon-fleet-supervisor")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.config.probe_every_s):
            try:
                self._step()
            except Exception as e:
                # The control loop must survive its own bugs: a failed
                # step is logged and the next tick retries.
                telemetry.count("fleet.supervisor_errors")
                logger.exception("fleet supervisor step failed: %r", e)

    def stop(self) -> None:
        """Terminate every replica (SIGTERM, grace, SIGKILL) and stop
        the control loop.  Idempotent."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        with self._lock:
            handles = [r.handle for r in self.replicas
                       if r.handle is not None]
            for r in self.replicas:
                r.state = DOWN
        for h in handles:
            h.terminate()
        deadline = time.monotonic() + 15.0
        for h in handles:
            if h.wait(max(0.1, deadline - time.monotonic())) is None:
                h.kill()
                h.wait(5.0)
        self._event("fleet_stopped",
                    restarts=sum(r.restarts for r in self.replicas),
                    swaps=self.swaps)

    # -- spawn / restart policy ----------------------------------------------

    def _spawn(self, r: Replica, now: float) -> None:
        try:
            handle = self.launcher.launch(r.idx)
        except Exception as e:
            # A failed exec is a failed start: schedule the next
            # attempt through the same backoff/breaker policy.
            with self._lock:
                r.last_error = f"launch failed: {type(e).__name__}: {e}"
            logger.warning("fleet: replica %d launch failed (%r)",
                           r.idx, e)
            self._schedule_restart(r, now, reason="launch failed")
            return
        with self._lock:
            r.handle = handle
            r.state = STARTING
            r.url = None
            r.probe_failures = 0
            r.spawned_at = now
            r.restart_at = None
        self._event("fleet_replica_spawned", replica=r.idx,
                    pid=handle.pid())

    def _schedule_restart(self, r: Replica, now: float,
                          reason: str) -> None:
        """A replica failed (crash, wedge, failed start): kill what is
        left, open the breaker if it is flapping, else schedule the
        restart after the current backoff."""
        if r.handle is not None:
            r.handle.kill()
        with self._lock:
            r.state = DOWN
            r.url = None
            if r.down_since is None:
                r.down_since = now
            r.last_error = reason
            # Breaker bookkeeping: restarts inside the rolling window.
            window = self.config.breaker_window_s
            r.restart_times = [t for t in r.restart_times
                               if now - t <= window]
            r.restart_times.append(now)
            flapping = len(r.restart_times) >= \
                self.config.breaker_threshold
            if r.half_open or flapping:
                # A failed half-open attempt re-opens; a flapping
                # replica opens.  Either way: no restarts until the
                # reset window passes.
                r.state = BROKEN
                r.breaker_open_until = now + self.config.breaker_reset_s
                r.half_open = False
                r.restart_at = None
                opened = True
            else:
                r.backoff_s = min(
                    max(self.config.restart_backoff_s, r.backoff_s * 2),
                    self.config.restart_backoff_max_s)
                r.restart_at = now + r.backoff_s
                opened = False
        telemetry.count("fleet.replica_failures")
        if opened:
            telemetry.count("fleet.breaker_opened")
            self._event("fleet_breaker_opened", replica=r.idx,
                        reason=reason,
                        reset_s=self.config.breaker_reset_s)
            logger.warning("fleet: replica %d circuit breaker OPEN "
                           "(%s); no restarts for %.1fs", r.idx,
                           reason, self.config.breaker_reset_s)
        else:
            self._event("fleet_replica_down", replica=r.idx,
                        reason=reason, restart_in_s=round(r.backoff_s, 3))
            logger.warning("fleet: replica %d down (%s); restart in "
                           "%.2fs", r.idx, reason, r.backoff_s)

    def _mark_ready(self, r: Replica, now: float) -> None:
        with self._lock:
            was_down = r.down_since is not None
            recycled = r.recycling
            restart_s = (now - r.down_since) if was_down else None
            r.state = READY
            r.probe_failures = 0
            r.backoff_s = 0.0
            r.down_since = None
            r.recycling = False
            r.last_error = None
            if r.half_open:
                r.half_open = False
                r.restart_times = []
                closed = True
            else:
                closed = False
            if was_down:
                # A rolling-swap recycle is a DELIBERATE bounce: its
                # latency is recorded, but it is not a crash restart —
                # the replica_restarts alert must not fire on deploys.
                if not recycled:
                    r.restarts += 1
                r.last_restart_s = round(restart_s, 3)
        if closed:
            self._event("fleet_breaker_closed", replica=r.idx)
            logger.info("fleet: replica %d circuit breaker closed",
                        r.idx)
        if was_down:
            telemetry.count("fleet.replica_recycles" if recycled
                            else "fleet.replica_restarts")
            telemetry.observe("fleet.restart_s", restart_s)
            self._event("fleet_replica_ready", replica=r.idx,
                        restart_s=round(restart_s, 3),
                        recycled=recycled)
            logger.info("fleet: replica %d %s and ready in %.2fs",
                        r.idx, "recycled" if recycled else "restarted",
                        restart_s)
        else:
            self._event("fleet_replica_ready", replica=r.idx)

    # -- probing -------------------------------------------------------------

    def _probe(self, r: Replica) -> str:
        """One /healthz probe → "ready" | "warming" | "error" (the
        ``serve.replica_healthz`` fault seam fires per probe)."""
        url = r.url
        if url is None:
            return "warming"      # info file not discovered yet
        try:
            faults.fire("serve.replica_healthz", replica=r.idx)
            req = url + "/healthz"
            with urllib.request.urlopen(
                    req, timeout=self.config.probe_timeout_s) as resp:
                state = json.loads(resp.read()).get("state")
                return "ready" if state == "ready" else "warming"
        except urllib.error.HTTPError as e:
            try:
                state = json.loads(e.read()).get("state")
            except Exception:  # photon-lint: disable=swallowed-exception (a non-JSON 5xx body is simply an unhealthy probe; the caller counts it)
                state = None
            return "warming" if state == "warming" else "error"
        except Exception:  # photon-lint: disable=swallowed-exception (any transport failure IS the probe result; the caller counts consecutive failures toward the wedge threshold)
            return "error"

    def note_failure(self, idx: int) -> None:
        """Frontend feedback: a connection-level failure against a
        replica counts like a failed probe, so a wedged replica is
        detected at request rate, not just probe cadence."""
        r = self.replicas[idx]
        with self._lock:
            r.probe_failures += 1

    # -- the control step ----------------------------------------------------

    def _step(self) -> None:
        now = self._clock()
        self._step_swap_detect()
        with self._lock:
            swap = self._swap
            frontend = self._frontend
        swap_active, swap_phase = None, None
        if swap is not None:
            # The swap dict is only ever mutated by this (control)
            # thread; the lock above guards the reference hand-off.
            swap_active = swap.get("active")
            swap_phase = swap.get("phase")
        for r in self.replicas:
            if r.idx == swap_active and swap_phase in ("drain", "exit"):
                continue   # the swap machinery owns this replica
            self._step_replica(r, now)
        self._step_swap(now)
        ready = self.ready_count()
        telemetry.gauge("fleet.ready_replicas", ready)
        if frontend is not None:
            frontend.update_readiness(ready)

    def _step_replica(self, r: Replica, now: float) -> None:
        with self._lock:
            state = r.state
            handle = r.handle
        if state == BROKEN:
            if now >= (r.breaker_open_until or 0.0):
                with self._lock:
                    r.half_open = True
                self._event("fleet_breaker_half_open", replica=r.idx)
                self._spawn(r, now)
            return
        if state == DOWN:
            if r.restart_at is not None and now >= r.restart_at:
                self._spawn(r, now)
            return
        if handle is None:
            return
        rc = handle.poll()
        if rc is not None:
            self._schedule_restart(r, now, reason=f"exited rc={rc}")
            return
        if r.url is None:
            url = handle.url()
            if url is not None:
                with self._lock:
                    r.url = url
        result = self._probe(r)
        if result == "ready":
            if state in (STARTING, READY):
                if state == STARTING or r.down_since is not None:
                    self._mark_ready(r, now)
                else:
                    with self._lock:
                        r.probe_failures = 0
            return
        if state == STARTING:
            # Warming (or failing while warming): only the ready
            # timeout kills a starting replica — compiles can be slow.
            if (r.spawned_at is not None
                    and now - r.spawned_at
                    > self.config.replica_ready_timeout_s):
                self._schedule_restart(
                    r, now, reason="never became ready "
                    f"(> {self.config.replica_ready_timeout_s:g}s)")
            return
        if state == READY:
            # Any non-ready answer from an in-rotation replica —
            # transport error, 5xx, or a bogus "warming" regression —
            # counts toward the wedge threshold.
            with self._lock:
                r.probe_failures += 1
                failures = r.probe_failures
            if failures >= self.config.unhealthy_after:
                telemetry.count("fleet.replica_wedged")
                self._event("fleet_replica_wedged", replica=r.idx,
                            probe_failures=failures)
                self._schedule_restart(
                    r, now, reason=f"wedged ({failures} consecutive "
                    "failed probes)")

    # -- rolling swap --------------------------------------------------------

    def _step_swap_detect(self) -> None:
        with self._lock:
            watching = self._watch_manifest and self._swap is None
            last = self._last_sig
        if not watching:
            return
        sig = _manifest_signature(self.config.model_dir)
        if sig is None or sig == last:
            return
        with self._lock:
            self._pending_sig = sig
            self._swap = {"queue": [r.idx for r in self.replicas],
                          "active": None, "phase": None}
        self._event("fleet_swap_started", signature=list(sig))
        logger.info("fleet: new manifest detected; rolling swap over "
                    "%d replica(s)", len(self.replicas))

    def _swap_abort(self, reason: str) -> None:
        with self._lock:
            self.swap_aborts += 1
            self.last_swap_error = reason
            for r in self.replicas:
                # Whatever happens to the failed replica from here on
                # is crash-restart territory, not a deploy bounce.
                r.recycling = False
            # Adopt the signature anyway: a corrupt publish must not
            # re-trigger the same doomed swap every step — the NEXT
            # publish (new signature) swaps normally, and the failed
            # replica stays with the normal restart/breaker machinery.
            self._last_sig = self._pending_sig
            self._swap = None
        telemetry.count("fleet.swap_aborts")
        self._event("fleet_swap_aborted", reason=reason)
        logger.warning("fleet: rolling swap ABORTED (%s); remaining "
                       "replicas keep the previous model", reason)

    def _step_swap(self, now: float) -> None:
        with self._lock:
            s = self._swap
        if s is None:
            return
        if s["active"] is None:
            if not s["queue"]:
                with self._lock:
                    self._last_sig = self._pending_sig
                    self._swap = None
                    self.swaps += 1
                telemetry.count("fleet.swaps")
                self._event("fleet_swap_done")
                logger.info("fleet: rolling swap complete")
                return
            nxt = self.replicas[s["queue"][0]]
            with self._lock:
                others_ready = all(
                    x.state == READY for x in self.replicas
                    if x.idx != nxt.idx)
                if others_ready:
                    # Cordon: the frontend stops routing here; the
                    # fleet stays at N−1 ready throughout the recycle.
                    s["queue"].pop(0)
                    s["active"] = nxt.idx
                    s["phase"] = "drain"
                    s["deadline"] = now + DRAIN_TIMEOUT_S
                    nxt.state = DRAINING
            if s["active"] is not None:
                self._event("fleet_swap_recycling", replica=s["active"])
            return
        r = self.replicas[s["active"]]
        if s["phase"] == "drain":
            with self._lock:
                drained = r.outstanding == 0
            if drained or now > s["deadline"]:
                if r.handle is not None:
                    r.handle.terminate()
                s["phase"] = "exit"
                s["deadline"] = now + EXIT_TIMEOUT_S
            return
        if s["phase"] == "exit":
            if r.handle is None or r.handle.poll() is not None:
                with self._lock:
                    r.down_since = now     # restart latency = recycle
                    r.recycling = True
                self._spawn(r, now)
                s["phase"] = "warm"
                s["deadline"] = now + self.config.replica_ready_timeout_s
            elif now > s["deadline"]:
                r.handle.kill()
            return
        if s["phase"] == "warm":
            # The normal probe/restart machinery owns the replica
            # here; the swap just watches the outcome.
            with self._lock:
                state = r.state
            if state == READY:
                s["active"] = None
                s["phase"] = None
                return
            if state == BROKEN or now > s["deadline"]:
                self._swap_abort(
                    f"replica {r.idx} failed to come up on the new "
                    f"manifest (state {state})")

    # -- frontend-facing reads ------------------------------------------------

    def ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self.replicas if r.state == READY)

    def acquire_replica(self, exclude: set[int] = frozenset()
                        ) -> Replica | None:
        """Least-outstanding ready replica (outside ``exclude``), with
        its outstanding count bumped — call ``release`` when done."""
        with self._lock:
            ready = [r for r in self.replicas
                     if r.state == READY and r.idx not in exclude
                     and r.url is not None]
            if not ready:
                return None
            # Least-outstanding, ties broken by fewest-served: under
            # sequential load (everything at 0 outstanding) requests
            # still spread instead of pinning the first replica.
            r = min(ready, key=lambda x: (x.outstanding, x.served,
                                          x.idx))
            r.outstanding += 1
            r.served += 1
            return r

    def release_replica(self, r: Replica) -> None:
        with self._lock:
            r.outstanding = max(0, r.outstanding - 1)

    def wait_ready(self, count: int | None = None,
                   timeout_s: float = 300.0) -> bool:
        """Block (wall clock) until ``count`` replicas are ready
        (default: the whole fleet).  Driven by the control thread —
        only meaningful after ``start()``."""
        want = count if count is not None else len(self.replicas)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.ready_count() >= want:
                return True
            time.sleep(0.05)
        return False

    def status(self) -> dict:
        with self._lock:
            replicas = [r.snapshot() for r in self.replicas]
            swaps, aborts = self.swaps, self.swap_aborts
            swapping = self._swap is not None
            swap_err = self.last_swap_error
        restarts = sum(r["restarts"] for r in replicas)
        last_restart = max(
            (r["last_restart_s"] for r in replicas
             if r["last_restart_s"] is not None), default=None)
        return {
            "replicas": replicas,
            "ready": sum(1 for r in replicas if r["state"] == READY),
            "size": len(replicas),
            "restarts": restarts,
            "last_restart_s": last_restart,
            "swaps": swaps,
            "swap_aborts": aborts,
            "swap_in_progress": swapping,
            **({"last_swap_error": swap_err} if swap_err else {}),
        }


class FleetServer:
    """The CLI composition: supervisor + frontend + telemetry/monitor
    sessions, with the single-server lifecycle shape (``start()``,
    ``serve_forever()``, ``stop()``) so ``__main__`` treats
    ``replicas > 1`` as a drop-in."""

    def __init__(self, config: ServingConfig, run_logger=None,
                 launcher=None, workdir: str | None = None):
        from photon_ml_tpu.serving.frontend import FleetFrontend

        config.validate()
        self.config = config
        self._log = run_logger
        self._monitor = None
        self._telemetry = None
        self._tracer = None
        self._stop_evt = threading.Event()
        self._stop_lock = threading.Lock()
        self._stopped = False
        self.supervisor = FleetSupervisor(
            config, launcher=launcher, run_logger=run_logger,
            workdir=workdir)
        # Bind-first, like ModelServer: probes get an honest 503
        # ``warming`` from the frontend while replicas come up.
        self.frontend = FleetFrontend(config, self.supervisor,
                                      run_logger=run_logger)
        self.frontend.start()
        self.port = self.frontend.port

    def start(self) -> "FleetServer":
        from photon_ml_tpu.telemetry import monitor as _mon

        cfg = self.config
        if cfg.telemetry != "off" and telemetry.active() is None:
            self._telemetry = telemetry.start(
                cfg.telemetry, run_logger=self._log)
        if cfg.monitor == "on" and _mon.active() is None:
            self._monitor = _mon.start(
                run_logger=self._log, every_s=cfg.monitor_every_s)
        if cfg.trace == "on" and _tracing.active() is None:
            # The frontend-side recorder (ISSUE 14): frontend traces
            # carry routing/forward/retry stages and join the replica
            # processes' records by trace id in serve-report.
            self._tracer = _tracing.start(
                role="frontend",
                threshold_s=cfg.trace_threshold_ms / 1e3,
                sample_every=cfg.trace_sample_every,
                cap=cfg.trace_buffer, run_logger=self._log)
        self.supervisor.start()
        if self._log is not None:
            self._log.event("fleet_started", port=self.port,
                            replicas=cfg.replicas)
        logger.info("fleet frontend bound on http://%s:%d "
                    "(%d replicas warming)", cfg.host, self.port,
                    cfg.replicas)
        return self

    def serve_forever(self) -> None:
        # photon-lint: disable=eternal-wait (the main thread parks until stop() or the CLI signal handler sets the event; there is nothing to time out toward)
        self._stop_evt.wait()

    def stop(self) -> None:
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        self._stop_evt.set()
        self.supervisor.stop()
        self.frontend.close()
        if self._tracer is not None:
            self._tracer.close()
        if self._monitor is not None:
            self._monitor.close()
        if self._telemetry is not None:
            self._telemetry.close()

    def serving_status(self) -> dict:
        return {
            "state": self.frontend.readiness.state,
            "frontend": self.frontend.stats(),
            "fleet": self.supervisor.status(),
        }
