"""Deadline-based micro-batcher: concurrent requests → fused batches.

The serving tier's throughput lever (ISSUE 12): a single request row
would waste the fused program's parallelism, so concurrent requests
COALESCE — the dispatcher collects queued requests until either the
largest bucket fills or the oldest request has waited
``deadline_s``, then dispatches them as ONE padded device call.  Under
light load a request pays at most the deadline of extra latency; under
heavy load batches fill and the deadline never binds — throughput
scales with batch fill, the Snap ML pipelining argument one level up.

Shape discipline: batches pad to the CLOSED ``buckets`` set (compiled
at warm-up), so the steady state never compiles.  A request larger
than the biggest bucket splits across several dispatches and
reassembles transparently.

Overload shedding (ISSUE 13 tentpole): queuing a request that cannot
meet its deadline only converts a fast failure into a slow one AND
drags every admitted request's tail with it (queue collapse).  Two
guards keep the admitted tail bounded:

- **Admission control**: ``submit`` estimates the queue wait from the
  rows already queued and the rolling (EWMA) batch service time; a
  request whose estimated start lies beyond its deadline budget is
  shed immediately with ``ServerOverloaded`` (HTTP 503 +
  ``Retry-After``) instead of queued to die.
- **Expiry at dispatch**: a queued slot whose deadline has already
  passed when the dispatcher reaches it is failed with
  ``DeadlineExceeded`` (503) rather than spending device time on an
  answer its client stopped waiting for.

Both sheds count ``serve.shed`` (plus a per-cause counter), the signal
the monitor's ``serve_shed_rate`` rule watches.

Hot swap: the batcher holds NO model state — every dispatch fetches
the current engine through ``engine_fn`` at batch-formation time, so a
swap lands between batches by construction: in-flight batches finish
on the old engine, the next batch opens on the new one, and no request
is ever dropped or torn across models.

Thread contract (photon-lint ``unlocked-shared-write``): request slots
hand results across threads under their own condition variable; the
dispatcher is the only thread forming batches; counters shared with
the stats endpoint mutate under one lock.
"""

from __future__ import annotations

import logging
import math
import queue
import threading
import time

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.serving import tracing
from photon_ml_tpu.telemetry import monitor as _mon

logger = logging.getLogger(__name__)

# EWMA weight for the rolling batch service time (the admission
# estimator): ~last 5 batches dominate, so the estimate tracks load
# shifts within a second at serving batch rates.
_SERVICE_EWMA = 0.2


class ServerClosing(RuntimeError):
    """Submitted while the server is draining (HTTP 503)."""


class ServerSaturated(RuntimeError):
    """The request queue is full (HTTP 429): shed load instead of
    queueing into timeout."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class ServerOverloaded(RuntimeError):
    """Admission control shed: the estimated queue wait exceeds the
    request's deadline budget (HTTP 503 + Retry-After)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed while it sat in the queue
    (HTTP 503 + Retry-After): the batcher refuses to spend device time
    on an answer the client has stopped waiting for."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class _Slot:
    """One request's result hand-off (condition-guarded)."""

    __slots__ = ("rows", "n", "deadline", "_cv", "_done", "result",
                 "error", "version", "degraded", "t_enq", "queue_wait",
                 "batch")

    def __init__(self, rows, n: int, deadline: float = math.inf):
        self.rows = rows
        self.n = n
        self.deadline = deadline     # batcher-clock time; inf = none
        self._cv = threading.Condition()
        self._done = False
        self.result = None       # (margins, preds) slices
        self.error: BaseException | None = None
        self.version: str | None = None
        self.degraded = False
        self.t_enq = time.perf_counter()   # tracing: queue-wait basis
        self.queue_wait: float | None = None
        self.batch: str | None = None      # linked BatchTrace id

    def finish(self, result=None, error=None, version=None,
               degraded: bool = False) -> None:
        with self._cv:
            self.result = result
            self.error = error
            self.version = version
            self.degraded = degraded
            self._done = True
            self._cv.notify_all()

    def wait(self, timeout: float):
        with self._cv:
            if not self._cv.wait_for(lambda: self._done, timeout):
                raise TimeoutError(
                    f"scoring request timed out after {timeout:g}s "
                    "(server overloaded or wedged)")
        if self.error is not None:
            raise self.error
        return self.result, self.version, self.degraded


class MicroBatcher:
    """The dispatcher thread + bounded request queue.

    ``engine_fn() -> ScoringEngine`` resolves the CURRENT engine per
    batch (the hot-swap seam).  ``buckets`` is the closed, ascending
    shape set; ``deadline_s`` the max coalescing wait for the oldest
    queued request.
    """

    _SENTINEL = object()

    def __init__(self, engine_fn, buckets: list[int],
                 deadline_s: float = 0.002, max_queue: int = 1024,
                 clock=time.monotonic):
        if not buckets or sorted(buckets) != list(buckets):
            raise ValueError("buckets must be non-empty ascending")
        self._engine_fn = engine_fn
        self.buckets = [int(b) for b in buckets]
        self.max_rows = self.buckets[-1]
        self.deadline_s = float(deadline_s)
        self._clock = clock
        # Unbounded Queue; max_queue is enforced in submit() under the
        # batcher lock — puts then never block, so both submit() and
        # close() can enqueue while HOLDING the lock (the ordering
        # guarantee vs the drain sentinel).
        self._q: queue.Queue = queue.Queue()
        self.max_queue = int(max_queue)
        self._lock = threading.Lock()
        self._closing = False
        self.batches = 0
        self.rows = 0
        self.padded_rows = 0
        self.shed = 0                  # all sheds (saturated/overload/
        self._queued_rows = 0          # ...deadline-expired)
        self._service_ewma_s: float | None = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="photon-serve-batcher")
        self._thread.start()

    # -- client side ---------------------------------------------------------

    def _estimated_wait_s(self, extra_rows: int) -> float | None:
        """Estimated queue delay before a request enqueued NOW (behind
        ``_queued_rows`` + its own ``extra_rows``) finishes, from the
        rolling batch service time.  None while cold (no batch has
        been measured — admission never sheds blind).  Caller holds
        the lock."""
        if self._service_ewma_s is None:
            return None
        batches_ahead = math.ceil(
            (self._queued_rows + extra_rows) / self.max_rows)
        return batches_ahead * self._service_ewma_s

    def _shed(self, cause: str) -> None:
        with self._lock:
            self.shed += 1
        telemetry.count("serve.shed")
        telemetry.count(f"serve.shed_{cause}")

    def submit(self, parsed_rows: list, timeout_s: float = 30.0,
               trace=None, t_admit: float | None = None):
        """Block until scored: → (margins [n], preds [n], version,
        degraded).  Called from HTTP handler threads; oversized
        requests split across ≤max_rows slots and reassemble here.

        ``trace`` (ISSUE 14): the request's ``RequestTrace`` —
        admission (from ``t_admit``, the route's entry clock, so the
        parse is included) and queue-wait stamp onto it, and the
        dispatched batch's id links it to the shared batch trace."""
        t0 = time.perf_counter()
        deadline = self._clock() + timeout_s
        slots = []
        shed_exc: Exception | None = None
        shed_cause = None
        # Enqueue UNDER the closing lock (put_nowait never blocks, so
        # holding it is safe): close() sets _closing and appends the
        # drain sentinel under the same lock, so no slot can ever land
        # BEHIND the sentinel and hang its client until timeout.
        with self._lock:
            if self._closing:
                raise ServerClosing("server is draining")
            est = self._estimated_wait_s(len(parsed_rows))
            if est is not None and est > timeout_s:
                # Deadline-aware admission control: this request would
                # time out in the queue — shed NOW with a 503 +
                # Retry-After instead of queuing it to die (and
                # dragging every admitted request's tail with it).
                self.shed += 1
                shed_exc = ServerOverloaded(
                    f"estimated queue wait {est:.2f}s exceeds the "
                    f"request deadline budget {timeout_s:g}s; retry "
                    "after backoff or raise capacity",
                    retry_after_s=max(1.0, est - timeout_s))
                shed_cause = "overload"
            else:
                for lo in range(0, len(parsed_rows), self.max_rows):
                    piece = parsed_rows[lo: lo + self.max_rows]
                    if self._q.qsize() >= self.max_queue:
                        # Shed load; requests already queued from this
                        # submit still score (their slots just get
                        # abandoned results).
                        self.shed += 1
                        shed_exc = ServerSaturated(
                            f"request queue full ({self.max_queue}); "
                            "shed load or raise max_queue",
                            retry_after_s=max(1.0, est or 1.0))
                        shed_cause = "saturated"
                        break
                    slot = _Slot(piece, len(piece), deadline=deadline)
                    self._q.put(slot)
                    self._queued_rows += len(piece)
                    slots.append(slot)
        if trace is not None:
            # Admission = route entry (parse included) → enqueued (or
            # shed): the client-visible pre-queue stage.
            trace.stamp("admission", time.perf_counter()
                        - (t_admit if t_admit is not None else t0))
        if shed_exc is not None:
            if trace is not None:
                trace.shed = shed_cause
            telemetry.count("serve.shed")
            telemetry.count(f"serve.shed_{shed_cause}")
            raise shed_exc
        telemetry.gauge("serve.queue_depth", self._q.qsize())
        margins, preds, version = [], [], None
        degraded = False
        for slot in slots:
            (m, p), version, deg = slot.wait(timeout_s)
            degraded = degraded or deg
            margins.append(m)
            preds.append(p)
        if trace is not None:
            # Queue wait is PER REQUEST (a split request's slowest
            # slot); the shared batch stages live on the linked batch
            # trace — the per-request vs shared-compute attribution.
            # An oversize request spans several batches: link the one
            # the request actually WAITED on (the max-queue-wait
            # slot's), so the stamp and the link tell one story —
            # attribution for the rare multi-batch request is
            # approximate by construction (batches are shared).
            slowest = max(
                (s for s in slots if s.queue_wait is not None),
                key=lambda s: s.queue_wait, default=None)
            if slowest is not None:
                trace.stamp("queue_wait", slowest.queue_wait)
                trace.batch = slowest.batch
            elif slots:
                trace.batch = slots[-1].batch
        dt = time.perf_counter() - t0
        telemetry.count("serve.requests")
        telemetry.observe("serve.request_s", dt)
        return (np.concatenate(margins), np.concatenate(preds), version,
                degraded)

    # -- dispatcher ----------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_rows

    def _pop_accounted(self, timeout=None):
        """Queue pop that keeps ``_queued_rows`` honest."""
        # photon-lint: disable=eternal-wait (drain contract: close() always enqueues the sentinel under the submit lock, so the unbounded get is terminated by shutdown)
        item = self._q.get() if timeout is None \
            else self._q.get(timeout=timeout)
        if item is not self._SENTINEL:
            with self._lock:
                self._queued_rows -= item.n
        return item

    def _expired(self, slot) -> bool:
        """Fail a queued slot whose deadline has already passed (shed
        at dispatch): its client is gone or about to give up — device
        time spent on it is pure waste under overload."""
        if self._clock() <= slot.deadline:
            return False
        self._shed("deadline")
        slot.finish(error=DeadlineExceeded(
            "request deadline passed while queued (server overloaded); "
            "retry after backoff"))
        return True

    def _run(self) -> None:
        carry = None
        while True:
            if carry is not None:
                item, carry = carry, None
            else:
                item = self._pop_accounted()
            if item is self._SENTINEL:
                return
            if self._expired(item):
                continue
            batch = [item]
            total = item.n
            deadline = self._clock() + self.deadline_s
            while total < self.max_rows:
                wait = deadline - self._clock()
                if wait <= 0:
                    break
                try:
                    nxt = self._pop_accounted(timeout=wait)
                except queue.Empty:  # photon-lint: disable=swallowed-exception (the deadline expiring IS the dispatch signal, not a failure)
                    break
                if nxt is self._SENTINEL:
                    carry = nxt        # dispatch, then exit next loop
                    break
                if self._expired(nxt):
                    continue
                if total + nxt.n > self.max_rows:
                    carry = nxt        # opens the next batch
                    break
                batch.append(nxt)
                total += nxt.n
            self._dispatch(batch, total)

    def _dispatch(self, batch: list, total: int) -> None:
        t0 = time.perf_counter()
        bucket = self._bucket_for(total)
        rec = tracing.active()
        bt = None
        if rec is not None:
            # The shared micro-batch span (ISSUE 14): recorded ONCE
            # per dispatch; member request traces link by batch id and
            # each slot's queue wait is measured against this moment.
            bt = rec.begin_batch(bucket, total, len(batch))
            for slot in batch:
                slot.queue_wait = t0 - slot.t_enq
                slot.batch = bt.batch_id
        bt_registered = False
        try:
            # The hot-swap seam: the engine is resolved HERE, once per
            # batch — a swap between batches is atomic for every
            # request in flight.
            engine = self._engine_fn()
            rows = [r for slot in batch for r in slot.rows]
            # Keyword only when tracing: engine-shaped test stubs (and
            # the tracing-off path) keep the pre-ISSUE-14 signature.
            margins, preds, degraded = (
                engine.score_batch(rows, bucket, trace=bt)
                if bt is not None
                else engine.score_batch(rows, bucket))
            if bt is not None:
                # Register the completed batch BEFORE any member slot
                # wakes: a handler thread can finish its request (and
                # look the batch up in the recorder's pending window)
                # the instant slot.finish releases it — registering in
                # the finally would race and silently drop the shared
                # span for that request.
                rec.finish_batch(bt)
                bt_registered = True
            lo = 0
            for slot in batch:
                hi = lo + slot.n
                # Per-slot degraded attribution: only the requests
                # whose OWN rows were served fallback get the flag —
                # a co-batched healthy request must not be marked.
                slot.finish(result=(margins[lo:hi], preds[lo:hi]),
                            version=engine.version,
                            degraded=bool(np.any(degraded[lo:hi])))
                lo = hi
        except BaseException as e:
            telemetry.thread_exception("serve-batcher", e)
            if bt is not None and not bt_registered:
                # Error path: register the partial batch first for the
                # same reason — failed members' traces still link it.
                rec.finish_batch(bt)
                bt_registered = True
            for slot in batch:
                slot.finish(error=e)
            return
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.batches += 1
                self.rows += total
                self.padded_rows += bucket
                # Rolling batch service time — the admission
                # estimator's denominator.
                self._service_ewma_s = dt if self._service_ewma_s \
                    is None else ((1 - _SERVICE_EWMA)
                                  * self._service_ewma_s
                                  + _SERVICE_EWMA * dt)
        telemetry.count("serve.batches")
        telemetry.count("serve.batch_rows", total)
        telemetry.observe("serve.batch_fill", total / bucket)
        telemetry.observe("serve.batch_s", time.perf_counter() - t0)
        telemetry.gauge("serve.queue_depth", self._q.qsize())
        # Live progress + the alert seam: rule evaluation (incl.
        # serve_tail_latency) runs at the monitor's snapshot cadence
        # FROM progress() — without this call the serving process
        # would record latencies nothing ever judges.  One global read
        # when monitoring is off.
        _mon.progress("serve", self.rows, unit="rows",
                      batches=self.batches)

    # -- stats / lifecycle ---------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            batches, rows, padded, shed = (self.batches, self.rows,
                                           self.padded_rows, self.shed)
            ewma = self._service_ewma_s
        return {
            "batches": batches, "rows": rows,
            "queue_depth": self._q.qsize(),
            "batch_fill": (round(rows / padded, 4) if padded else None),
            "shed": shed,
            "service_ewma_ms": (None if ewma is None
                                else round(ewma * 1e3, 3)),
            "buckets": list(self.buckets),
            "deadline_ms": round(self.deadline_s * 1e3, 3),
        }

    def close(self) -> None:
        """Drain: refuse new submits, score everything queued, stop."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
            # Sentinel goes in under the SAME lock submits enqueue
            # under: every accepted slot is in front of it, so the
            # drain contract ("score everything queued") holds.  The
            # queue is unbounded, so this put never blocks while the
            # lock is held.
            self._q.put(self._SENTINEL)
        self._thread.join(timeout=30.0)
        if self._thread.is_alive():   # pragma: no cover - wedged device
            logger.warning("serve batcher did not drain within 30s")
