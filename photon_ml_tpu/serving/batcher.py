"""Deadline-based micro-batcher: concurrent requests → fused batches.

The serving tier's throughput lever (ISSUE 12): a single request row
would waste the fused program's parallelism, so concurrent requests
COALESCE — the dispatcher collects queued requests until either the
largest bucket fills or the oldest request has waited
``deadline_s``, then dispatches them as ONE padded device call.  Under
light load a request pays at most the deadline of extra latency; under
heavy load batches fill and the deadline never binds — throughput
scales with batch fill, the Snap ML pipelining argument one level up.

Shape discipline: batches pad to the CLOSED ``buckets`` set (compiled
at warm-up), so the steady state never compiles.  A request larger
than the biggest bucket splits across several dispatches and
reassembles transparently.

Hot swap: the batcher holds NO model state — every dispatch fetches
the current engine through ``engine_fn`` at batch-formation time, so a
swap lands between batches by construction: in-flight batches finish
on the old engine, the next batch opens on the new one, and no request
is ever dropped or torn across models.

Thread contract (photon-lint ``unlocked-shared-write``): request slots
hand results across threads under their own condition variable; the
dispatcher is the only thread forming batches; counters shared with
the stats endpoint mutate under one lock.
"""

from __future__ import annotations

import logging
import queue
import threading
import time

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import monitor as _mon

logger = logging.getLogger(__name__)


class ServerClosing(RuntimeError):
    """Submitted while the server is draining (HTTP 503)."""


class ServerSaturated(RuntimeError):
    """The request queue is full (HTTP 429): shed load instead of
    queueing into timeout."""


class _Slot:
    """One request's result hand-off (condition-guarded)."""

    __slots__ = ("rows", "n", "_cv", "_done", "result", "error",
                 "version")

    def __init__(self, rows, n: int):
        self.rows = rows
        self.n = n
        self._cv = threading.Condition()
        self._done = False
        self.result = None       # (margins, preds) slices
        self.error: BaseException | None = None
        self.version: str | None = None

    def finish(self, result=None, error=None, version=None) -> None:
        with self._cv:
            self.result = result
            self.error = error
            self.version = version
            self._done = True
            self._cv.notify_all()

    def wait(self, timeout: float):
        with self._cv:
            if not self._cv.wait_for(lambda: self._done, timeout):
                raise TimeoutError(
                    f"scoring request timed out after {timeout:g}s "
                    "(server overloaded or wedged)")
        if self.error is not None:
            raise self.error
        return self.result, self.version


class MicroBatcher:
    """The dispatcher thread + bounded request queue.

    ``engine_fn() -> ScoringEngine`` resolves the CURRENT engine per
    batch (the hot-swap seam).  ``buckets`` is the closed, ascending
    shape set; ``deadline_s`` the max coalescing wait for the oldest
    queued request.
    """

    _SENTINEL = object()

    def __init__(self, engine_fn, buckets: list[int],
                 deadline_s: float = 0.002, max_queue: int = 1024,
                 clock=time.monotonic):
        if not buckets or sorted(buckets) != list(buckets):
            raise ValueError("buckets must be non-empty ascending")
        self._engine_fn = engine_fn
        self.buckets = [int(b) for b in buckets]
        self.max_rows = self.buckets[-1]
        self.deadline_s = float(deadline_s)
        self._clock = clock
        # Unbounded Queue; max_queue is enforced in submit() under the
        # batcher lock — puts then never block, so both submit() and
        # close() can enqueue while HOLDING the lock (the ordering
        # guarantee vs the drain sentinel).
        self._q: queue.Queue = queue.Queue()
        self.max_queue = int(max_queue)
        self._lock = threading.Lock()
        self._closing = False
        self.batches = 0
        self.rows = 0
        self.padded_rows = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="photon-serve-batcher")
        self._thread.start()

    # -- client side ---------------------------------------------------------

    def submit(self, parsed_rows: list, timeout_s: float = 30.0):
        """Block until scored: → (margins [n], preds [n], version).
        Called from HTTP handler threads; oversized requests split
        across ≤max_rows slots and reassemble here."""
        t0 = time.perf_counter()
        slots = []
        # Enqueue UNDER the closing lock (put_nowait never blocks, so
        # holding it is safe): close() sets _closing and appends the
        # drain sentinel under the same lock, so no slot can ever land
        # BEHIND the sentinel and hang its client until timeout.
        with self._lock:
            if self._closing:
                raise ServerClosing("server is draining")
            for lo in range(0, len(parsed_rows), self.max_rows):
                piece = parsed_rows[lo: lo + self.max_rows]
                if self._q.qsize() >= self.max_queue:
                    # Shed load; requests already queued from this
                    # submit still score (their slots just get
                    # abandoned results).
                    raise ServerSaturated(
                        f"request queue full ({self.max_queue}); "
                        "shed load or raise max_queue")
                slot = _Slot(piece, len(piece))
                self._q.put(slot)
                slots.append(slot)
        telemetry.gauge("serve.queue_depth", self._q.qsize())
        margins, preds, version = [], [], None
        for slot in slots:
            (m, p), version = slot.wait(timeout_s)
            margins.append(m)
            preds.append(p)
        dt = time.perf_counter() - t0
        telemetry.count("serve.requests")
        telemetry.observe("serve.request_s", dt)
        return (np.concatenate(margins), np.concatenate(preds), version)

    # -- dispatcher ----------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_rows

    def _run(self) -> None:
        carry = None
        while True:
            item = carry if carry is not None else self._q.get()
            carry = None
            if item is self._SENTINEL:
                return
            batch = [item]
            total = item.n
            deadline = self._clock() + self.deadline_s
            while total < self.max_rows:
                wait = deadline - self._clock()
                if wait <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=wait)
                except queue.Empty:  # photon-lint: disable=swallowed-exception (the deadline expiring IS the dispatch signal, not a failure)
                    break
                if nxt is self._SENTINEL:
                    carry = nxt        # dispatch, then exit next loop
                    break
                if total + nxt.n > self.max_rows:
                    carry = nxt        # opens the next batch
                    break
                batch.append(nxt)
                total += nxt.n
            self._dispatch(batch, total)

    def _dispatch(self, batch: list, total: int) -> None:
        t0 = time.perf_counter()
        bucket = self._bucket_for(total)
        try:
            # The hot-swap seam: the engine is resolved HERE, once per
            # batch — a swap between batches is atomic for every
            # request in flight.
            engine = self._engine_fn()
            rows = [r for slot in batch for r in slot.rows]
            margins, preds = engine.score_batch(rows, bucket)
            lo = 0
            for slot in batch:
                hi = lo + slot.n
                slot.finish(result=(margins[lo:hi], preds[lo:hi]),
                            version=engine.version)
                lo = hi
        except BaseException as e:
            telemetry.thread_exception("serve-batcher", e)
            for slot in batch:
                slot.finish(error=e)
            return
        finally:
            with self._lock:
                self.batches += 1
                self.rows += total
                self.padded_rows += bucket
        telemetry.count("serve.batches")
        telemetry.count("serve.batch_rows", total)
        telemetry.observe("serve.batch_fill", total / bucket)
        telemetry.observe("serve.batch_s", time.perf_counter() - t0)
        telemetry.gauge("serve.queue_depth", self._q.qsize())
        # Live progress + the alert seam: rule evaluation (incl.
        # serve_tail_latency) runs at the monitor's snapshot cadence
        # FROM progress() — without this call the serving process
        # would record latencies nothing ever judges.  One global read
        # when monitoring is off.
        _mon.progress("serve", self.rows, unit="rows",
                      batches=self.batches)

    # -- stats / lifecycle ---------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            batches, rows, padded = (self.batches, self.rows,
                                     self.padded_rows)
        return {
            "batches": batches, "rows": rows,
            "queue_depth": self._q.qsize(),
            "batch_fill": (round(rows / padded, 4) if padded else None),
            "buckets": list(self.buckets),
            "deadline_ms": round(self.deadline_s * 1e3, 3),
        }

    def close(self) -> None:
        """Drain: refuse new submits, score everything queued, stop."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
            # Sentinel goes in under the SAME lock submits enqueue
            # under: every accepted slot is in front of it, so the
            # drain contract ("score everything queued") holds.  The
            # queue is unbounded, so this put never blocks while the
            # lock is held.
            self._q.put(self._SENTINEL)
        self._thread.join(timeout=30.0)
        if self._thread.is_alive():   # pragma: no cover - wedged device
            logger.warning("serve batcher did not drain within 30s")
