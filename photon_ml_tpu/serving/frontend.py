"""Health-routed fleet frontend (ISSUE 13 tentpole).

One HTTP surface over the supervised replica fleet
(``serving.fleet``): clients talk to the frontend exactly as they
would to a single ``ModelServer`` — same ``POST /v1/score`` wire
shape, same ``/healthz`` readiness semantics — and the frontend owns
the fleet-level resilience:

- **Health routing**: requests go to the READY replica with the
  fewest outstanding requests (least-outstanding beats round-robin
  under heterogeneous batch latencies).  Draining/broken/starting
  replicas receive nothing.
- **Bounded retry**: a CONNECTION-level failure (refused, reset,
  timeout, torn response — the replica never answered) retries
  exactly ONCE on a DIFFERENT ready replica, inside the request's
  remaining deadline budget.  An HTTP response from a replica — any
  status — is forwarded verbatim, never retried: scoring is
  idempotent so the one retry is safe, but an answered error is the
  replica's verdict.
- **Shedding**: no ready replica → immediate 503 + Retry-After;
  replica sheds (429/503 from admission control) forward with their
  Retry-After and count into the frontend's ``serve.shed`` — the
  monitor's ``serve_shed_rate`` rule sees fleet-level shed pressure.
- **Aggregated fleet view**: ``/status`` embeds the supervisor's
  per-replica state (restarts, breaker, rolling-swap progress) next
  to the frontend's own counters; ``/metrics`` exposes both in
  Prometheus text.

The frontend carries NO model state: a rolling swap or replica
restart is invisible here beyond the routing table.
"""

from __future__ import annotations

import http.client
import json
import logging
import socket
import threading
import time
import urllib.error
import urllib.request

from photon_ml_tpu import telemetry
from photon_ml_tpu.config import ServingConfig
from photon_ml_tpu.serving import tracing
from photon_ml_tpu.serving.http import (
    READY,
    STOPPING,
    WARMING,
    HttpEndpoint,
    HttpError,
    Readiness,
)
from photon_ml_tpu.telemetry import monitor as _mon

logger = logging.getLogger(__name__)

# Connection-level failures: the replica never produced an HTTP
# response, so a single retry on a different replica is safe (scoring
# is a pure read).  urllib wraps most of these in URLError; the rest
# leak through on response-read paths.
_RETRIABLE = (urllib.error.URLError, ConnectionError, socket.timeout,
              TimeoutError, http.client.HTTPException)

# Minimum remaining deadline budget worth spending on a retry.
_MIN_RETRY_BUDGET_S = 0.05


class FleetFrontend:
    """The fleet's request-path endpoint.  Binds at construction
    (``config.port``; 0 = ephemeral), serves after ``start()``;
    readiness follows the fleet's ready count via
    ``update_readiness`` (wired by the supervisor's control step)."""

    def __init__(self, config: ServingConfig, supervisor,
                 run_logger=None):
        self.config = config
        self.supervisor = supervisor
        self._log = run_logger
        self.readiness = Readiness(
            WARMING, reason="no replica is ready yet")
        self._lock = threading.Lock()
        self.requests = 0
        self.retries = 0
        self.failed = 0
        self.shed = 0
        self.t0 = time.monotonic()
        self._http = HttpEndpoint(
            {
                ("POST", "/v1/score"): self._route_score,
                ("GET", "/status"): self._route_status,
                ("GET", "/metrics"): self._route_metrics,
            },
            readiness=self.readiness, port=config.port,
            host=config.host,
            request_timeout_s=config.http_timeout_s)
        self.port = self._http.port
        supervisor.attach_frontend(self)

    def start(self) -> "FleetFrontend":
        self._http.start()
        logger.info("fleet frontend on http://%s:%d (%d replica(s))",
                    self.config.host, self.port,
                    self.config.replicas)
        return self

    def close(self) -> None:
        self.readiness.set(STOPPING, reason="fleet stopping")
        self._http.close()

    def update_readiness(self, ready_count: int) -> None:
        """Supervisor hook: ≥1 ready replica = the fleet serves."""
        state = self.readiness.state
        if state == STOPPING:
            return
        if ready_count > 0 and state != READY:
            self.readiness.set(READY)
        elif ready_count == 0 and state == READY:
            self.readiness.set(
                WARMING, reason="no replica is ready")

    # -- request path --------------------------------------------------------

    def _forward(self, url: str, body: bytes, timeout_s: float,
                 trace_headers: dict | None = None):
        """One attempt against one replica → (code, payload, ctype,
        headers) for ANY HTTP response; raises a ``_RETRIABLE`` on
        connection-level failure.  ``trace_headers`` propagate the
        trace context (one more hop) so the replica's trace record
        joins this request's (ISSUE 14)."""
        req = urllib.request.Request(
            url + "/v1/score", data=body,
            headers={"Content-Type": "application/json",
                     **(trace_headers or {})})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return (r.status, r.read().decode(),
                        r.headers.get("Content-Type",
                                      "application/json"), {})
        except urllib.error.HTTPError as e:
            # The replica ANSWERED: forward its verdict verbatim
            # (incl. Retry-After on sheds) — never retried.
            payload = e.read().decode()
            headers = {}
            ra = e.headers.get("Retry-After")
            if ra is not None:
                headers["Retry-After"] = ra
            return (e.code, payload,
                    e.headers.get("Content-Type", "application/json"),
                    headers)

    def _count(self, field: str, telemetry_name: str | None = None
               ) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)
        if telemetry_name:
            telemetry.count(telemetry_name)

    def _route_score(self, body: bytes):
        t0 = time.perf_counter()
        # Request trace (ISSUE 14): minted/adopted by the HTTP core;
        # forwarded one hop deeper so the replica-side record joins
        # this one by trace id.  Finished by the core after the write.
        rt = tracing.begin()
        ctx = tracing.context()
        fwd_headers = None
        if ctx is not None:
            fwd_headers = {tracing.TRACE_HEADER: ctx.child_header(),
                           tracing.REQUEST_ID_HEADER: ctx.trace_id}
        deadline = time.monotonic() + self.config.request_timeout_s
        tried: set[int] = set()
        attempt = 0
        while True:
            replica = self.supervisor.acquire_replica(exclude=tried)
            if replica is None:
                # Nothing to route to (all down/draining, or the one
                # untried replica died): shed honestly.
                if rt is not None:
                    rt.shed = "no_replica"
                self._count("shed", "serve.shed")
                telemetry.count("serve.shed_no_replica")
                raise HttpError(
                    503, headers={"Retry-After": "1"},
                    error="no ready replica"
                          + (" (retry exhausted)" if tried else ""))
            url = replica.url
            tried.add(replica.idx)
            attempt += 1
            budget = deadline - time.monotonic()
            if budget <= 0:
                self.supervisor.release_replica(replica)
                self._count("failed", "serve.frontend_failed")
                raise HttpError(503, error="request deadline exhausted "
                                           "before a replica answered")
            t_f = time.perf_counter()
            if rt is not None and attempt == 1:
                # Routing cost: route entry → first forward attempt.
                rt.stamp("route", t_f - t0)
            try:
                code, payload, ctype, headers = self._forward(
                    url, body, budget, trace_headers=fwd_headers)
            except _RETRIABLE as e:
                # The replica never answered: count the failure
                # toward its wedge detection and retry EXACTLY once
                # on a different replica inside the remaining budget.
                if rt is not None:
                    dt = time.perf_counter() - t_f
                    # Failed-attempt time is the RETRY COST — the
                    # serve-report decomposition's retry column.
                    rt.stamp("retry", dt)
                    rt.attempts.append({
                        "replica": replica.idx,
                        "ms": round(dt * 1e3, 3),
                        "outcome": f"connect_fail:{type(e).__name__}"})
                self.supervisor.note_failure(replica.idx)
                remaining = deadline - time.monotonic()
                retriable = (attempt == 1
                             and remaining > _MIN_RETRY_BUDGET_S)
                logger.warning(
                    "fleet frontend: replica %d connection failed "
                    "(%s: %s); %s", replica.idx, type(e).__name__, e,
                    "retrying once on another replica" if retriable
                    else "giving up")
                if retriable:
                    self._count("retries", "serve.frontend_retries")
                    self._event("fleet_retry", replica=replica.idx,
                                error=f"{type(e).__name__}: {e}")
                    continue
                self._count("failed", "serve.frontend_failed")
                raise HttpError(
                    502, error=f"replica connection failed after "
                               f"{attempt} attempt(s): "
                               f"{type(e).__name__}: {e}")
            finally:
                self.supervisor.release_replica(replica)
            if rt is not None:
                dt = time.perf_counter() - t_f
                rt.stamp("forward", dt)
                rt.attempts.append({"replica": replica.idx,
                                    "ms": round(dt * 1e3, 3),
                                    "outcome": code})
            if code == 200:
                self._count("requests", "serve.requests")
                telemetry.observe("serve.request_s",
                                  time.perf_counter() - t0)
            elif code in (429, 503):
                # Replica-side shed (saturation/admission/deadline):
                # fleet-level shed pressure, the serve_shed_rate
                # rule's input.
                self._count("shed", "serve.shed")
                telemetry.count("serve.shed_replica")
            with self._lock:
                total = self.requests
                retries, shed = self.retries, self.shed
            _mon.progress("serve", total, unit="requests",
                          retries=retries, shed=shed)
            return code, payload, ctype, headers or None

    def _event(self, kind: str, **fields) -> None:
        if self._log is not None:
            self._log.event(kind, **fields)

    # -- observer routes -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "requests": self.requests,
                "retries": self.retries,
                "failed": self.failed,
                "shed": self.shed,
                "uptime_s": round(time.monotonic() - self.t0, 1),
            }

    def _route_status(self, body: bytes):
        rec = tracing.active()
        stages = tracing.stage_summary()
        st = {
            "state": self.readiness.state,
            "frontend": self.stats(),
            "fleet": self.supervisor.status(),
            **({"tracing": rec.snapshot()} if rec is not None else {}),
            **({"stages": stages} if stages else {}),
        }
        mon = _mon.active()
        if mon is not None:
            st.update(mon.status())
        return 200, json.dumps(st), "application/json"

    def _route_metrics(self, body: bytes):
        from photon_ml_tpu.telemetry.monitor import prometheus_text

        lines = [prometheus_text(_mon.active()).rstrip("\n")]
        fleet = self.supervisor.status()
        fe = self.stats()
        lines.append("# TYPE photon_fleet_ready_replicas gauge")
        lines.append(f"photon_fleet_ready_replicas {fleet['ready']}")
        lines.append("# TYPE photon_fleet_replica_restarts_total "
                     "counter")
        lines.append("photon_fleet_replica_restarts_total "
                     f"{fleet['restarts']}")
        for name in ("requests", "retries", "failed", "shed"):
            lines.append(f"# TYPE photon_frontend_{name}_total counter")
            lines.append(f"photon_frontend_{name}_total {fe[name]}")
        return 200, "\n".join(lines) + "\n", \
            "text/plain; version=0.0.4"
