"""Model-only scoring plan + bucketed fused dispatch (ISSUE 12).

The streaming scorer's plan (``estimators.streaming_scorer._plan``) is
DATASET-bound: its builders close over the pass's arrays.  The serving
tier scores rows that do not exist yet, so this module derives the same
``(_CoordSpec tuple, device tables)`` plan from the MODEL alone and
builds each micro-batch's chunk dict from parsed request rows:

- **The device program is the scorer's** — ``_run_chunk``, jitted at
  module level with the spec tuple and mean function static.  Serving
  adds no second fused program: a bucket batch is just a (small) score
  chunk, and the jit cache (plus the persistent XLA compile cache
  across restarts) is shared with the batch path.
- **Closed shape set**: batches pad to ``ServingConfig.buckets()`` row
  counts, sparse rows densify to ELL at ``ell_row_capacity``, dense
  and random-effect widths come from the model — every steady-state
  dispatch hits a warm compile (guard-pinned by the tests).
- **Random effects** gather per-request coefficient rows from the
  mmap'd ``EntityServeStore`` into a per-batch MINI-table
  ``[R+1, p]`` (row i serves request-row i; the last row is the zero
  fallback shared by unseen entities and padding), so the device never
  holds the [E, p] table — the program's gather-dot is unchanged, only
  the table it gathers from is batch-local.
- **Projected random effects** score host-side per batch (the
  transformer's pre-sorted merge-join table) and fold into ``base``,
  exactly as the streaming scorer folds them per chunk.

``BadRequest`` marks client errors (unknown shard, over-capacity row,
out-of-range column) — the HTTP layer answers 400, never 500.
"""

from __future__ import annotations

import logging

import jax
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.estimators.streaming_scorer import (
    _CoordSpec,
    _run_chunk,
)
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import TaskType
from photon_ml_tpu.serving.entity_store import EntityServeStore

logger = logging.getLogger(__name__)


class BadRequest(ValueError):
    """A malformed scoring request (client error → HTTP 400)."""


def _parse_sparse(feat, dim: int, cap: int, shard: str
                  ) -> tuple[np.ndarray, np.ndarray]:
    """One row's sparse features → (cols int32, vals float32), from
    either ``{"col": val}`` maps or ``[[col, val], ...]`` pairs."""
    if isinstance(feat, dict):
        items = [(int(c), float(v)) for c, v in feat.items()]
    else:
        try:
            items = [(int(c), float(v)) for c, v in feat]
        except (TypeError, ValueError) as e:
            raise BadRequest(
                f"shard '{shard}': sparse features must be a "
                f"col->value map or [[col, value], ...] pairs ({e})")
    if len(items) > cap:
        raise BadRequest(
            f"shard '{shard}': {len(items)} non-zeros exceeds the "
            f"server's ell_row_capacity={cap}; raise the knob or "
            "split the row")
    cols = np.fromiter((c for c, _ in items), np.int32, len(items))
    vals = np.fromiter((v for _, v in items), np.float32, len(items))
    if len(cols) and (cols.min() < 0 or cols.max() >= dim):
        raise BadRequest(
            f"shard '{shard}': column ids must be in [0, {dim})")
    return cols, vals


def _parse_dense(feat, dim: int, shard: str) -> np.ndarray:
    x = np.asarray(feat, np.float32)
    if x.shape != (dim,):
        raise BadRequest(
            f"shard '{shard}': dense features must be a length-{dim} "
            f"vector, got shape {x.shape}")
    return x


class ParsedRow:
    """One validated request row, ready for batch assembly."""

    __slots__ = ("offset", "sparse", "dense", "ids")

    def __init__(self, offset: float, sparse: dict, dense: dict,
                 ids: dict):
        self.offset = offset
        self.sparse = sparse     # shard -> (cols, vals)
        self.dense = dense       # shard -> [d] float32
        self.ids = ids           # entity key -> int


class ScoringEngine:
    """One model version's request-path scorer."""

    def __init__(self, model: GameModel, task: TaskType, *,
                 version: str = "0", ell_row_capacity: int = 64,
                 dense_feature_shards: tuple = (),
                 spill_dir: str | None = None, entity_chunk: int = 4096,
                 host_max_resident: int = 4):
        import jax.numpy as jnp

        from photon_ml_tpu.estimators.game_transformer import (
            _projected_score_table,
        )

        self.model = model
        self.task = task
        self.version = str(version)
        self._mean = task.loss.mean
        self.ell_row_capacity = int(ell_row_capacity)

        specs: list[_CoordSpec] = []
        tables: dict = {}
        # Input schema: shard -> ("sparse", dim) | ("dense", dim);
        # entity key -> required.  Two coordinates sharing a shard must
        # agree on its form (validated below).
        self._shards: dict[str, tuple[str, int]] = {}
        self._entity_keys: list[str] = []
        self._fixed_sparse: list[tuple[str, str]] = []  # (coord, shard)
        self._fixed_dense: list[tuple[str, str]] = []
        self._re: list[tuple[str, str, str, EntityServeStore]] = []
        self._proj: list[tuple[str, RandomEffectModel, tuple, str, str]] \
            = []
        dense_shards = set(dense_feature_shards)

        def declare(shard: str, form: str, dim: int) -> None:
            prev = self._shards.get(shard)
            if prev is not None and prev != (form, dim):
                raise ValueError(
                    f"feature shard '{shard}' is used as {prev} and as "
                    f"({form}, {dim}) by different coordinates; serving "
                    "needs one form per shard")
            self._shards[shard] = (form, dim)

        for name, comp in model.models.items():
            if isinstance(comp, FixedEffectModel):
                w = np.asarray(comp.coefficients.means, np.float32)
                dim = len(w) - (1 if comp.intercept else 0)
                if comp.feature_shard in dense_shards:
                    specs.append(_CoordSpec(name, "fixed_dense"))
                    tables[name] = jnp.asarray(
                        w[:-1] if comp.intercept else w)
                    tables[name + ".base"] = jnp.float32(
                        w[-1] if comp.intercept else 0.0)
                    declare(comp.feature_shard, "dense", dim)
                    self._fixed_dense.append((name, comp.feature_shard))
                else:
                    specs.append(_CoordSpec(name, "fixed_sparse"))
                    tables[name] = jnp.asarray(w)
                    tables[name + ".base"] = jnp.float32(
                        w[-1] if comp.intercept else 0.0)
                    declare(comp.feature_shard, "sparse", dim)
                    self._fixed_sparse.append((name, comp.feature_shard))
            elif isinstance(comp, RandomEffectModel):
                key = comp.entity_key or name
                self._entity_keys.append(key)
                if comp.projection is not None:
                    table = _projected_score_table(comp)
                    declare(comp.feature_shard, "sparse",
                            comp.projection.global_dim)
                    self._proj.append((name, comp, table,
                                       comp.feature_shard, key))
                    continue
                store = EntityServeStore.build(
                    name, comp, spill_dir, entity_chunk=entity_chunk,
                    host_max_resident=host_max_resident)
                specs.append(_CoordSpec(name, "re"))
                declare(comp.feature_shard, "dense", store.dim)
                self._re.append((name, comp.feature_shard, key, store))
            else:
                raise TypeError(f"unknown component model {type(comp)}")

        self.specs = tuple(specs)
        self._tables = tables          # device-resident, model-constant
        self.warmed_buckets: list[int] = []

    # -- request parsing ----------------------------------------------------

    def parse_row(self, row) -> ParsedRow:
        if not isinstance(row, dict):
            raise BadRequest("each row must be a JSON object")
        feats = row.get("features")
        if not isinstance(feats, dict):
            raise BadRequest("each row needs a 'features' object "
                             "(shard -> features)")
        unknown = set(feats) - set(self._shards)
        if unknown:
            raise BadRequest(
                f"unknown feature shard(s) {sorted(unknown)}; the "
                f"model serves {sorted(self._shards)}")
        sparse: dict = {}
        dense: dict = {}
        for shard, (form, dim) in self._shards.items():
            if shard not in feats:
                raise BadRequest(f"row is missing feature shard "
                                 f"'{shard}'")
            if form == "sparse":
                sparse[shard] = _parse_sparse(
                    feats[shard], dim, self.ell_row_capacity, shard)
            else:
                dense[shard] = _parse_dense(feats[shard], dim, shard)
        raw_ids = row.get("ids") or {}
        ids: dict = {}
        for key in self._entity_keys:
            if key not in raw_ids:
                raise BadRequest(f"row is missing entity id '{key}'")
            try:
                ids[key] = int(raw_ids[key])
            except (TypeError, ValueError):
                raise BadRequest(f"entity id '{key}' must be an "
                                 "integer")
        try:
            offset = float(row.get("offset", 0.0))
        except (TypeError, ValueError):
            raise BadRequest("'offset' must be a number")
        return ParsedRow(offset, sparse, dense, ids)

    def parse_rows(self, rows) -> list[ParsedRow]:
        if not isinstance(rows, list) or not rows:
            raise BadRequest("'rows' must be a non-empty list")
        return [self.parse_row(r) for r in rows]

    # -- batch assembly + dispatch ------------------------------------------

    def _zero_rows(self, n: int) -> list[ParsedRow]:
        """Synthetic all-zeros rows (bucket warm-up)."""
        sparse = {s: (np.zeros(0, np.int32), np.zeros(0, np.float32))
                  for s, (f, _) in self._shards.items() if f == "sparse"}
        dense = {s: np.zeros(d, np.float32)
                 for s, (f, d) in self._shards.items() if f == "dense"}
        ids = {k: -1 for k in self._entity_keys}
        return [ParsedRow(0.0, dict(sparse), dict(dense), dict(ids))
                for _ in range(n)]

    def _build_chunk(self, rows: list[ParsedRow], R: int,
                     timings: dict | None = None
                     ) -> tuple[dict, dict, np.ndarray]:
        """(chunk arrays, per-batch tables, degraded [n] bool) for
        ``rows`` padded to ``R`` — all host numpy; placement is the
        caller's explicit ``device_put``.  ``degraded[i]`` marks row i
        served fixed-effect-only fallback by an entity store
        (ISSUE 13) — per row, so co-batched healthy requests stay
        unmarked.  ``timings`` (ISSUE 14): accumulates the
        entity-store lookup seconds under ``"store_lookup"`` so the
        batch trace can split lookup out of assembly."""
        import time as _time

        n = len(rows)
        k = self.ell_row_capacity
        base = np.zeros(R, np.float32)
        for i, r in enumerate(rows):
            base[i] = r.offset
        chunk: dict = {}
        # Shared per-shard staging (coordinates reusing a shard reuse
        # the staged arrays instead of re-padding).
        ell: dict = {}
        for shard, (form, dim) in self._shards.items():
            if form == "sparse":
                cols = np.zeros((R, k), np.int32)
                vals = np.zeros((R, k), np.float32)
                for i, r in enumerate(rows):
                    c, v = r.sparse[shard]
                    cols[i, : len(c)] = c
                    vals[i, : len(v)] = v
                ell[shard] = (cols, vals)
            else:
                x = np.zeros((R, dim), np.float32)
                for i, r in enumerate(rows):
                    x[i] = r.dense[shard]
                ell[shard] = x
        for name, shard in self._fixed_sparse:
            chunk[name + ".cols"], chunk[name + ".vals"] = ell[shard]
        for name, shard in self._fixed_dense:
            chunk[name + ".x"] = ell[shard]
        batch_tables: dict = {}
        degraded = np.zeros(n, bool)
        for name, shard, key, store in self._re:
            ids = np.fromiter((r.ids[key] for r in rows), np.int64, n)
            if timings is None:
                w_rows, _hit, deg = store.lookup(ids)
            else:
                t_l = _time.perf_counter()
                w_rows, _hit, deg = store.lookup(ids)
                timings["store_lookup"] = (
                    timings.get("store_lookup", 0.0)
                    + _time.perf_counter() - t_l)
            degraded |= deg
            # Mini-table: row i serves request-row i; row R is the
            # shared zero fallback (unseen entities + padding) — the
            # batch path's unseen-entity semantics, bitwise.
            mt = np.zeros((R + 1, store.dim), np.float32)
            mt[:n] = w_rows
            idx = np.full(R, R, np.int32)
            idx[:n] = np.arange(n, dtype=np.int32)
            chunk[name + ".x"] = ell[shard]
            chunk[name + ".idx"] = idx
            batch_tables[name] = mt
        for name, comp, table, shard, key in self._proj:
            cols, vals = zip(*(r.sparse[shard] for r in rows)) \
                if n else ((), ())
            lens = np.fromiter((len(c) for c in cols), np.int64, n)
            indptr = np.zeros(n + 1, np.int64)
            np.cumsum(lens, out=indptr[1:])
            srows = SparseRows.from_flat(
                indptr,
                (np.concatenate(cols) if n else
                 np.zeros(0, np.int64)).astype(np.int64),
                np.concatenate(vals).astype(np.float32) if n
                else np.zeros(0, np.float32))
            ids = np.fromiter((r.ids[key] for r in rows), np.int64, n)
            idx = comp.grouping.join_ids(ids)
            from photon_ml_tpu.estimators.game_transformer import (
                _score_projected_rows,
            )

            base[:n] += _score_projected_rows(comp, table, idx, srows)
        chunk["base"] = base
        return chunk, batch_tables, degraded

    def score_batch(self, rows: list[ParsedRow], bucket: int,
                    trace=None
                    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Score ``rows`` padded to ``bucket`` → (margins [n],
        predictions [n], degraded [n] bool) as host numpy.  One fused
        device dispatch; ``degraded`` marks the fixed-effect-only
        fallback rows from an unreadable entity-store chunk
        (ISSUE 13).

        ``trace`` (ISSUE 14): the shared ``BatchTrace`` — stage
        durations stamp onto it (``assemble`` = chunk build minus
        lookups, ``store_lookup`` = entity-store reads, ``dispatch`` =
        H2D placement + program enqueue, ``d2h`` = block-until-done +
        harvest; the async dispatch means device compute time lands in
        ``d2h``).  None keeps the pre-tracing path: no timestamps
        taken."""
        import time as _time

        from photon_ml_tpu.reliability import faults

        n = len(rows)
        if n > bucket:
            raise ValueError(f"{n} rows > bucket {bucket}")
        timings = None if trace is None else {}
        t_a = 0.0 if trace is None else _time.perf_counter()
        chunk, batch_tables, degraded = self._build_chunk(
            rows, bucket, timings)
        if trace is not None:
            lookup_s = timings.get("store_lookup", 0.0)
            trace.stamp("store_lookup", lookup_s)
            trace.stamp("assemble",
                        _time.perf_counter() - t_a - lookup_s)
        # The engine-dispatch fault seam: a wedged/failing device
        # dispatch is injectable here (the batcher maps the error to
        # the whole batch's slots — an answered 500, never a hang).
        faults.fire("serve.dispatch", bucket=bucket)
        # Explicit placement + harvest (the no_implicit_transfers
        # contract): the batch chunk and the RE mini-tables go up in
        # one planned device_put; margins/preds come back in one
        # device_get.
        t_d = 0.0 if trace is None else _time.perf_counter()
        buf = jax.device_put(chunk)
        tables = self._tables
        if batch_tables:
            tables = {**tables, **jax.device_put(batch_tables)}
        m_dev, p_dev = _run_chunk(self.specs, self._mean, tables, buf)
        t_h = 0.0 if trace is None else _time.perf_counter()
        if trace is not None:
            trace.stamp("dispatch", t_h - t_d)
        m = np.asarray(jax.device_get(m_dev)[:n])
        p = np.asarray(jax.device_get(p_dev)[:n])
        if trace is not None:
            trace.stamp("d2h", _time.perf_counter() - t_h)
        return m, p, degraded

    def warm(self, buckets: list[int]) -> dict:
        """Compile (or warm-load from the persistent XLA cache) every
        bucket shape so the first request pays zero compiles."""
        import time

        t0 = time.perf_counter()
        for b in sorted(buckets):
            self.score_batch(self._zero_rows(1), b)
            self.warmed_buckets.append(int(b))
        warm_s = time.perf_counter() - t0
        telemetry.observe("serve.warm_s", warm_s)
        logger.info("scoring engine warmed %d bucket(s) %s in %.2fs",
                    len(self.warmed_buckets), self.warmed_buckets,
                    warm_s)
        return {"buckets": list(self.warmed_buckets),
                "warm_s": round(warm_s, 3)}

    # -- introspection / retirement -----------------------------------------

    def describe(self) -> dict:
        return {
            "version": self.version,
            "coordinates": {s.name: s.kind for s in self.specs}
            | {name: "re_projected" for name, *_ in self._proj},
            "shards": {s: {"form": f, "dim": d}
                       for s, (f, d) in self._shards.items()},
            "entity_keys": list(self._entity_keys),
            "ell_row_capacity": self.ell_row_capacity,
            "buckets": list(self.warmed_buckets),
            "entity_stores": [store.stats()
                              for *_x, store in self._re],
        }

    def close(self) -> None:
        """Retire this engine (after in-flight batches drained): drop
        the entity stores' decoded windows."""
        for *_x, store in self._re:
            store.close()


def dataset_rows(dataset, lo: int, hi: int) -> list[dict]:
    """``GameDataset`` rows [lo, hi) → request-row JSON objects (the
    ``/v1/score`` wire shape).  Test/bench/client helper: the parity
    suites and the bench's open-loop clients replay real dataset rows
    against the server."""
    offsets = dataset.offset_array()
    sparse = {s: (f if isinstance(f, SparseRows)
                  else SparseRows.from_rows(f))
              for s, f in dataset.features.items()
              if not isinstance(f, np.ndarray)}
    rows = []
    for i in range(lo, hi):
        feats: dict = {}
        for shard, f in dataset.features.items():
            if isinstance(f, np.ndarray):
                feats[shard] = [float(v) for v in f[i]]
            else:
                f = sparse[shard]
                s0, s1 = int(f.indptr[i]), int(f.indptr[i + 1])
                feats[shard] = [[int(c), float(v)]
                                for c, v in zip(f.cols[s0:s1],
                                                f.vals[s0:s1])]
        rows.append({
            "features": feats,
            "ids": {k: int(v[i])
                    for k, v in dataset.entity_ids.items()},
            "offset": float(offsets[i]),
        })
    return rows
