"""End-to-end request tracing across the serving path (ISSUE 14).

The serving fleet's p99 is one opaque reservoir number: nothing
decomposes a slow request into frontend routing, retry cost, batcher
queue wait, shared device dispatch, entity-store lookup, or response
write — and a frontend hop cannot be joined to the replica-side work
it caused.  This module is the tracing layer that closes that gap,
stage-level latency attribution in the Spark-ML study's sense
(PAPERS.md) applied to the request path's own hierarchy
(frontend → replica → batcher → device):

- **Trace context**: a trace id + hop count minted at the frontend (or
  adopted from a client ``X-Photon-Trace: <id>/<hop>`` header),
  propagated on the forwarded request and echoed on EVERY response —
  including 503 sheds and retry-exhausted 502s — as
  ``X-Photon-Request-Id``, so a client can correlate any failure with
  fleet ``/status`` and the run logs.
- **Per-request stage marks**: each request slot records monotonic
  stage durations (``admission``, ``queue_wait``, ``serialize``,
  ``write``; frontend: ``route``, ``forward``, ``retry``) while the
  SHARED micro-batch work (``assemble``, ``store_lookup``,
  ``dispatch``, ``d2h``) is recorded ONCE as a batch trace that member
  request traces link to by batch id — per-request queue-wait vs
  shared-compute attribution falls out of the join.
- **Tail-based sampling**: a request slower than ``threshold_s`` (or
  every ``sample_every``-th request — a deterministic floor, no RNG in
  the telemetry path) is retained in a bounded per-process ring buffer
  and written as a ``request_trace`` JSONL event (its batch as ONE
  ``batch_trace`` event, however many members are retained).
  Everything else is dropped after updating the
  ``serve.stage.<stage>_s`` latency histograms — the
  ``photon_serve_stage_seconds{stage=...}`` series on ``/metrics``.
- **Cross-process join**: ``python -m photon_ml_tpu.telemetry
  serve-report`` joins frontend and replica trace logs by trace id
  into the latency-decomposition table, and exports Perfetto flow
  events (``ph: s/f``) so a request renders flowing
  frontend → replica → batcher thread → dispatch
  (``telemetry.serve_report`` / ``telemetry.export``).

Overhead discipline: tracing off is the pre-ISSUE-14 path (no
timestamps taken); tracing on costs a handful of ``perf_counter``
calls and histogram folds per request — budgeted ≤2% on p50 with zero
new steady-state compiles (guard-pinned, PERF.md round 19).  Stage
durations use the monotonic clock throughout; ``wall_t`` (one
``time.time()`` call at request start, never subtracted) only anchors
cross-process timelines for the exporters.

Import discipline: stdlib-only at import time (``serving.http``
imports this module, and ``telemetry.monitor`` imports ``serving.http``
— the telemetry package is reached lazily inside functions).
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import re
import threading
import time

logger = logging.getLogger(__name__)

TRACE_HEADER = "X-Photon-Trace"
REQUEST_ID_HEADER = "X-Photon-Request-Id"

# Client-supplied ids are echoed back into headers and logs: accept
# only a conservative token alphabet, mint otherwise.
_ID_RE = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")

# Stage vocabulary (the serve-report table's row order).  Request-side
# stages are recorded per request; batch-side stages once per
# micro-batch (member requests link by batch id); frontend-side stages
# on the fleet frontend's own trace record.
REQUEST_STAGES = ("admission", "queue_wait", "serialize", "write")
BATCH_STAGES = ("assemble", "store_lookup", "dispatch", "d2h")
FRONTEND_STAGES = ("route", "forward", "retry")
ALL_STAGES = FRONTEND_STAGES + REQUEST_STAGES + BATCH_STAGES

# Batches whose members were ALL dropped by sampling age out of this
# pending window (a batch must outlive its member requests' finish —
# the write stage lands after the batch completes).
_PENDING_BATCH_CAP = 256


def _telemetry():
    """Lazy handle on the telemetry package (import discipline above)."""
    from photon_ml_tpu import telemetry

    return telemetry


# stage -> "serve.stage.<stage>_s", interned once (the finish path
# folds several histograms per request; no f-string per fold).
_STAGE_METRIC: dict[str, str] = {}


def _stage_metric(stage: str) -> str:
    name = _STAGE_METRIC.get(stage)
    if name is None:
        name = _STAGE_METRIC[stage] = f"serve.stage.{stage}_s"
    return name


class TraceContext:
    """The propagated identity: trace id + hop count.  Hop 0 is the
    process that minted the id (frontend, or a direct client's
    replica); each forward increments."""

    __slots__ = ("trace_id", "hop")

    def __init__(self, trace_id: str, hop: int = 0):
        self.trace_id = trace_id
        self.hop = int(hop)

    def header_value(self) -> str:
        return f"{self.trace_id}/{self.hop}"

    def child_header(self) -> str:
        """The value forwarded downstream (one more hop)."""
        return f"{self.trace_id}/{self.hop + 1}"


# Minted ids are a per-process random prefix + a counter: unique
# across the fleet (the prefix), unique within the process (the
# counter), and ~30x cheaper than an os.urandom syscall per request —
# minting happens on EVERY request (tracing on or off, the id-echo
# contract), so it must cost nanoseconds, not microseconds.
_MINT_PREFIX = os.urandom(6).hex()
_MINT_SEQ = itertools.count()


def mint() -> TraceContext:
    return TraceContext(f"{_MINT_PREFIX}{next(_MINT_SEQ) & 0xFFFFFFFF:08x}",
                        0)


def parse_trace_header(value: str | None) -> TraceContext | None:
    """``X-Photon-Trace: <id>/<hop>`` → context, or None on anything
    malformed (the caller mints instead — a bad header must never 400
    a scoring request)."""
    if not value:
        return None
    trace_id, sep, hop = value.partition("/")
    if not _ID_RE.match(trace_id):
        return None
    if not sep:
        return TraceContext(trace_id, 0)
    try:
        return TraceContext(trace_id, max(0, int(hop)))
    except ValueError:  # photon-lint: disable=swallowed-exception (a malformed client hop means "no adoptable context"; the caller mints a fresh one — logging per hostile header would be a log-spam vector)
        return None


def from_headers(headers) -> TraceContext:
    """Adopt the request's trace context: ``X-Photon-Trace`` first,
    a bare client ``X-Photon-Request-Id`` second, else mint."""
    ctx = parse_trace_header(headers.get(TRACE_HEADER))
    if ctx is not None:
        return ctx
    rid = headers.get(REQUEST_ID_HEADER)
    if rid and _ID_RE.match(rid):
        return TraceContext(rid, 0)
    return mint()


# ---------------------------------------------------------------------------
# Per-handler-thread request state (set by the HTTP core, read by the
# route handlers; each request runs start-to-finish on one thread).
# ---------------------------------------------------------------------------

_LOCAL = threading.local()


def set_context(ctx: TraceContext) -> None:
    _LOCAL.ctx = ctx


def context() -> TraceContext | None:
    return getattr(_LOCAL, "ctx", None)


def attach(rt: "RequestTrace") -> None:
    """Hand the live request trace to the HTTP core: it stamps the
    response-write stage and finishes the trace after the bytes go
    out — on EVERY outcome, sheds and errors included."""
    _LOCAL.rt = rt


def take_attached() -> "RequestTrace | None":
    rt = getattr(_LOCAL, "rt", None)
    _LOCAL.rt = None
    return rt


def clear() -> None:
    _LOCAL.ctx = None
    _LOCAL.rt = None


class RequestTrace:
    """One request's stage record.  ``stages`` maps stage name →
    seconds (monotonic durations); ``batch`` links the shared
    micro-batch trace; ``attempts`` (frontend) records one entry per
    forward attempt (the retry-cost decomposition)."""

    __slots__ = ("trace_id", "hop", "role", "wall_t", "t0", "stages",
                 "batch", "status", "rows", "attempts", "shed",
                 "degraded", "total_s", "sampled")

    def __init__(self, ctx: TraceContext, role: str):
        self.trace_id = ctx.trace_id
        self.hop = ctx.hop
        self.role = role
        self.wall_t = time.time()      # timeline anchor, never subtracted
        self.t0 = time.perf_counter()
        self.stages: dict[str, float] = {}
        self.batch: str | None = None      # linked BatchTrace id
        self.status: int | None = None
        self.rows = 0
        self.attempts: list[dict] = []
        self.shed: str | None = None
        self.degraded = False
        self.total_s: float | None = None
        self.sampled: str | None = None

    def stamp(self, stage: str, dur_s: float) -> None:
        self.stages[stage] = self.stages.get(stage, 0.0) + float(dur_s)


class BatchTrace:
    """One micro-batch's shared-stage record (assemble / store_lookup /
    dispatch / d2h), recorded ONCE however many member requests are
    retained."""

    __slots__ = ("batch_id", "wall_t", "t0", "bucket", "rows",
                 "requests", "stages", "total_s", "emitted")

    def __init__(self, batch_id: str, bucket: int, rows: int,
                 requests: int):
        self.batch_id = batch_id
        self.wall_t = time.time()
        self.t0 = time.perf_counter()
        self.bucket = bucket
        self.rows = rows
        self.requests = requests
        self.stages: dict[str, float] = {}
        self.total_s: float | None = None
        self.emitted = False

    def stamp(self, stage: str, dur_s: float) -> None:
        self.stages[stage] = self.stages.get(stage, 0.0) + float(dur_s)


class TraceRecorder:
    """The per-process tracing session (one per process, module-global
    via ``start()`` — the telemetry/monitor pattern).

    Retention: a finished request is kept when its total latency is at
    least ``threshold_s`` (tail) or its sequence number hits the
    deterministic ``sample_every`` floor; kept requests land in a
    bounded ring (``cap``) AND as ``request_trace`` JSONL events on
    ``run_logger``, with the linked batch emitted once as
    ``batch_trace``.  Dropped requests still fold their stage durations
    into the ``serve.stage.<stage>_s`` histograms, so ``/metrics`` and
    the alert rules see the full stream, not the tail."""

    def __init__(self, role: str = "replica", threshold_s: float = 0.05,
                 sample_every: int = 100, cap: int = 512,
                 run_logger=None, owns_logger: bool = False):
        if threshold_s < 0:
            raise ValueError(f"threshold_s must be >= 0, got "
                             f"{threshold_s!r}")
        if sample_every < 0:
            raise ValueError(f"sample_every must be >= 0 (0 = no "
                             f"floor), got {sample_every!r}")
        if cap < 1:
            raise ValueError(f"cap must be >= 1, got {cap!r}")
        self.role = role
        self.threshold_s = float(threshold_s)
        self.sample_every = int(sample_every)
        self.cap = int(cap)
        self._log = run_logger
        self._owns_logger = owns_logger
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=cap)
        self._batch_ring: collections.deque = collections.deque(
            maxlen=cap)
        self._pending: collections.OrderedDict = collections.OrderedDict()
        self._req_seq = 0
        self._batch_seq = 0
        # Batch ids carry a per-RECORDER random prefix: a restarted
        # replica (new process) or a stop/start server (new recorder)
        # restarts the sequence, and a bare integer would collide
        # across a stitched log's segments — serve-report would join a
        # pre-kill tail request to a post-restart batch's stages.
        self._bid_prefix = os.urandom(4).hex()
        self.requests = 0
        self.sampled_tail = 0
        self.sampled_floor = 0
        self.batches = 0
        self._closed = False

    # -- request side --------------------------------------------------------

    def begin(self) -> RequestTrace:
        """New request trace on the current thread's context (minted
        if the HTTP core set none — library callers), attached for the
        core's finish-at-write."""
        ctx = context() or mint()
        rt = RequestTrace(ctx, self.role)
        attach(rt)
        return rt

    def finish(self, rt: RequestTrace, status: int | None = None) -> None:
        rt.total_s = time.perf_counter() - rt.t0
        if status is not None and rt.status is None:
            rt.status = status
        tel = _telemetry().active()
        if tel is not None:
            # No per-request counter here: a count() appends to the
            # rolling rate series, and the recorder's own `requests`
            # tally already feeds /status — the finish path stays at
            # the histogram folds only (the ≤2% p50 budget).
            for stage, dur in rt.stages.items():
                tel.observe(_stage_metric(stage), dur)
        sampled = "tail" if rt.total_s >= self.threshold_s else None
        emit_batch = None
        with self._lock:
            if self._closed:
                return
            self.requests += 1
            seq = self._req_seq
            self._req_seq += 1
            if (sampled is None and self.sample_every
                    and seq % self.sample_every == 0):
                sampled = "floor"
            if sampled is None:
                return
            rt.sampled = sampled
            if sampled == "tail":
                self.sampled_tail += 1
            else:
                self.sampled_floor += 1
            self._ring.append(rt)
            if rt.batch is not None:
                bt = self._pending.get(rt.batch)
                if bt is not None and not bt.emitted:
                    # The shared batch span is emitted ONCE, when its
                    # first retained member links it.
                    bt.emitted = True
                    self._batch_ring.append(bt)
                    emit_batch = bt
        if tel is not None:
            tel.count("serve.trace.sampled")
        if emit_batch is not None:
            self._log_batch(emit_batch)
        self._log_request(rt)

    # -- batch side ----------------------------------------------------------

    def begin_batch(self, bucket: int, rows: int, requests: int
                    ) -> BatchTrace:
        with self._lock:
            seq = self._batch_seq
            self._batch_seq += 1
        return BatchTrace(f"{self._bid_prefix}.{seq}", bucket, rows,
                          requests)

    def finish_batch(self, bt: BatchTrace) -> None:
        bt.total_s = time.perf_counter() - bt.t0
        tel = _telemetry().active()
        if tel is not None:
            for stage, dur in bt.stages.items():
                tel.observe(_stage_metric(stage), dur)
        with self._lock:
            if self._closed:
                return
            self.batches += 1
            self._pending[bt.batch_id] = bt
            while len(self._pending) > _PENDING_BATCH_CAP:
                self._pending.popitem(last=False)

    # -- export / lifecycle --------------------------------------------------

    def _log_request(self, rt: RequestTrace) -> None:
        if self._log is None:
            return
        self._log.event(
            "request_trace", trace=rt.trace_id, hop=rt.hop,
            role=rt.role, wall_t=round(rt.wall_t, 6),
            total_ms=round((rt.total_s or 0.0) * 1e3, 3),
            stages_ms={k: round(v * 1e3, 3)
                       for k, v in rt.stages.items()},
            sampled=rt.sampled,
            **({"batch": rt.batch} if rt.batch is not None else {}),
            **({"status": rt.status} if rt.status is not None else {}),
            **({"rows": rt.rows} if rt.rows else {}),
            **({"attempts": rt.attempts} if rt.attempts else {}),
            **({"shed": rt.shed} if rt.shed else {}),
            **({"degraded": True} if rt.degraded else {}))

    def _log_batch(self, bt: BatchTrace) -> None:
        if self._log is None:
            return
        self._log.event(
            "batch_trace", batch=bt.batch_id,
            wall_t=round(bt.wall_t, 6),
            total_ms=round((bt.total_s or 0.0) * 1e3, 3),
            bucket=bt.bucket, rows=bt.rows, requests=bt.requests,
            stages_ms={k: round(v * 1e3, 3)
                       for k, v in bt.stages.items()})

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "role": self.role,
                "requests": self.requests,
                "sampled_tail": self.sampled_tail,
                "sampled_floor": self.sampled_floor,
                "batches": self.batches,
                "buffered": len(self._ring),
                "threshold_ms": round(self.threshold_s * 1e3, 3),
                "sample_every": self.sample_every,
            }

    def retained(self) -> list[RequestTrace]:
        """The ring's current contents (tests / status introspection)."""
        with self._lock:
            return list(self._ring)

    def close(self) -> None:
        """Emit the summary event and deactivate.  Idempotent."""
        global _ACTIVE
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self._log is not None:
            self._log.event("serve_trace_summary", **self.snapshot())
        if self._owns_logger:
            self._log.close()
        with _ACTIVE_LOCK:
            if _ACTIVE is self:
                _ACTIVE = None


_ACTIVE: TraceRecorder | None = None
_ACTIVE_LOCK = threading.Lock()


def active() -> TraceRecorder | None:
    return _ACTIVE


def start(role: str = "replica", threshold_s: float = 0.05,
          sample_every: int = 100, cap: int = 512,
          run_logger=None) -> TraceRecorder:
    """Activate the (one per process) trace recorder."""
    global _ACTIVE
    owns = False
    if run_logger is None:
        from photon_ml_tpu.utils.run_log import RunLogger

        run_logger = RunLogger(None)
        owns = True
    rec = TraceRecorder(role, threshold_s=threshold_s,
                        sample_every=sample_every, cap=cap,
                        run_logger=run_logger, owns_logger=owns)
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            if owns:
                run_logger.close()
            raise RuntimeError("a trace recorder is already active")
        _ACTIVE = rec
    return rec


def begin() -> RequestTrace | None:
    """Module-level request begin: None when tracing is off (the
    hot-path contract — one global read)."""
    rec = _ACTIVE
    if rec is None:
        return None
    return rec.begin()


def finish(rt: RequestTrace | None, status: int | None = None) -> None:
    rec = _ACTIVE
    if rec is not None and rt is not None:
        rec.finish(rt, status=status)


def stage_summary(session=None) -> dict | None:
    """Per-stage latency table {stage: {count, p50_ms, p99_ms}} from
    the telemetry registry's ``serve.stage.<stage>_s`` histograms —
    the ``/status`` stages block, the monitor's dominant-stage input,
    and the bench's stage-median source.  Uses the registry's
    prefix-targeted accessor, NOT the full ``summary()`` snapshot —
    a /status poll must not sort every histogram in the process while
    request threads block on the registry lock."""
    tel = _telemetry()
    t = session if session is not None else tel.active()
    if t is None:
        return None
    out = {}
    for name, h in t.histogram_quantiles(
            "serve.stage.", (0.50, 0.99)).items():
        if not name.endswith("_s"):
            continue
        stage = name[len("serve.stage."):-2]
        q50, q99 = h["quantiles"]
        out[stage] = {
            "count": h["count"],
            "p50_ms": None if q50 is None else round(q50 * 1e3, 3),
            "p99_ms": None if q99 is None else round(q99 * 1e3, 3),
        }
    return out or None


def dominant_stage(summary: dict | None) -> tuple[str, float] | None:
    """(stage, p99_ms) with the largest p99 — the tail's dominant
    stage.  None when no stage histograms exist (tracing off)."""
    if not summary:
        return None
    best = None
    for stage, ent in summary.items():
        p99 = ent.get("p99_ms")
        if p99 is not None and (best is None or p99 > best[1]):
            best = (stage, p99)
    return best
