"""Online serving tier (ISSUE 12): the first request path.

Everything before this package is batch — Avro in, Avro/npz out, one
process per pass.  The serving tier is a persistent model-server
process that keeps the fused scoring program warm and answers
``POST /v1/score`` requests at micro-batch latency:

- ``serving.http``: the shared threaded HTTP core (stdlib
  ``ThreadingHTTPServer`` + a route table + warming/ready readiness
  semantics) — also the base of the monitor's status endpoint.
- ``serving.entity_store``: random-effect coefficients served from an
  mmap'd chunked disk store with a persistent entity-id index.
- ``serving.engine``: the model-only scoring plan — the streaming
  scorer's fused per-chunk device program (``_run_chunk``) dispatched
  on padded request batches from a CLOSED bucket shape set.
- ``serving.batcher``: the deadline-based micro-batcher coalescing
  concurrent requests into those buckets.
- ``serving.server``: ``ModelServer`` — checkpoint-manifest load,
  bucket warm-up, hot model swap, the HTTP surface; run it with
  ``python -m photon_ml_tpu.serving --config serve.json``.
- ``serving.fleet`` / ``serving.frontend`` (ISSUE 13): the resilient
  tier — a supervisor spawning N replica ``ModelServer`` subprocesses
  (healthz-probed, restarted with backoff + circuit breaker, rolled
  one at a time on a new manifest) behind one health-routed frontend
  (least-outstanding routing, bounded retry-once, overload shedding,
  aggregated fleet ``/status``); ``replicas > 1`` in the config runs
  it from the same CLI.
- ``serving.tracing`` (ISSUE 14): end-to-end request tracing — trace
  ids propagated frontend → replica and echoed on every response
  (``X-Photon-Request-Id``), per-request stage durations + the shared
  micro-batch span, tail-sampled into a bounded ring and
  ``request_trace`` JSONL events; ``python -m photon_ml_tpu.telemetry
  serve-report`` joins the processes' logs into the cross-process
  latency decomposition.
"""

# NOTE: no eager submodule imports — ``telemetry.monitor`` imports the
# shared HTTP core from ``serving.http``, and an eager ``server`` import
# here would close an import cycle through the telemetry package.


def __getattr__(name: str):
    if name == "ModelServer":
        from photon_ml_tpu.serving.server import ModelServer

        return ModelServer
    if name == "FleetServer":
        from photon_ml_tpu.serving.fleet import FleetServer

        return FleetServer
    raise AttributeError(name)
