"""Shared threaded HTTP core: route table + readiness semantics.

One stdlib ``ThreadingHTTPServer`` wrapper serves BOTH HTTP surfaces in
the package — the monitor's observer endpoint (``/status`` +
``/metrics``, ISSUE 10) and the model server's request path
(``/v1/score``, ISSUE 12).  Promoting the monitor's private
``_StatusServer`` into this module is the tentpole's first move: the
request path must not fork a second, slightly different server loop.

Readiness (ISSUE 12 satellite): every endpoint built on this core
answers ``GET /healthz`` with the SAME state machine —

- ``warming`` → **503**: the process is up but not serviceable yet
  (model loading, plan build, XLA compile in progress).  A load
  balancer or orchestrator probe must NOT route traffic here.
- ``ready`` → **200**: warm — the first request pays zero compiles.
- ``stopping`` → **503**: graceful drain in progress.

The previous monitor endpoint answered an unconditional 200 the moment
the socket bound, i.e. during exactly the plan/compile window where a
probe answer matters; both surfaces now report honestly.

Import discipline: stdlib only — ``telemetry.monitor`` imports this
module, so anything heavier would cycle through the package.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading

logger = logging.getLogger(__name__)

WARMING = "warming"
READY = "ready"
STOPPING = "stopping"

_STATES = (WARMING, READY, STOPPING)


class Readiness:
    """Thread-safe readiness state + human reason.

    Writers (the owning server's lifecycle) call ``set(state, reason)``;
    the HTTP thread reads ``snapshot()``.  ``healthz_body()`` is the
    shared wire format: ``{"ok": bool, "state": str, "reason": str?}``.
    """

    def __init__(self, state: str = WARMING, reason: str | None = None):
        self._lock = threading.Lock()
        self._state = state
        self._reason = reason
        self._check(state)

    @staticmethod
    def _check(state: str) -> None:
        if state not in _STATES:
            raise ValueError(
                f"readiness state {state!r} not in {_STATES}")

    def set(self, state: str, reason: str | None = None) -> None:
        self._check(state)
        with self._lock:
            self._state = state
            self._reason = reason

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> tuple[str, str | None]:
        with self._lock:
            return self._state, self._reason

    def healthz(self) -> tuple[int, dict]:
        """(HTTP code, JSON body) for ``GET /healthz``."""
        state, reason = self.snapshot()
        body = {"ok": state == READY, "state": state}
        if reason:
            body["reason"] = reason
        return (200 if state == READY else 503), body


class _Handler(http.server.BaseHTTPRequestHandler):
    """Route-table dispatch; the endpoint rides as a class attribute
    (one handler class per ``HttpEndpoint`` instance)."""

    endpoint: "HttpEndpoint | None" = None

    # Request paths are small JSON (scoring rows); cap the body read so
    # a hostile Content-Length cannot balloon the handler thread.
    MAX_BODY = 32 << 20

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj), "application/json")

    def _dispatch(self, method: str) -> None:
        ep = self.endpoint
        path = self.path.split("?", 1)[0]
        if path in ("/", "/healthz"):
            # "/" doubles as the health probe (the round-15 monitor
            # endpoint answered it; existing probes keep working) —
            # with the honest state machine, not an unconditional 200.
            code, body = ep.readiness.healthz()
            self._send_json(code, body)
            return
        route = ep.routes.get((method, path))
        if route is None:
            self._send_json(404, {
                "error": "unknown route",
                "routes": sorted({p for _, p in ep.routes} | {"/healthz"}),
            })
            return
        body = b""
        if method == "POST":
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                length = 0
            if length > self.MAX_BODY:
                self._send_json(413, {"error": "request body too large",
                                      "max_bytes": self.MAX_BODY})
                return
            body = self.rfile.read(length) if length else b""
        try:
            code, payload, ctype = route(body)
        except HttpError as e:
            code, payload, ctype = e.code, json.dumps(e.body), \
                "application/json"
        except Exception as e:   # a handler bug must answer, not hang
            logger.exception("http route %s %s failed", method, path)
            code, payload, ctype = 500, json.dumps(
                {"error": f"{type(e).__name__}: {e}"}), "application/json"
        self._send(code, payload, ctype)

    def do_GET(self) -> None:    # noqa: N802 (http.server API)
        self._dispatch("GET")

    def do_POST(self) -> None:   # noqa: N802 (http.server API)
        self._dispatch("POST")

    def log_message(self, format, *args):   # noqa: A002 (stdlib API)
        logger.debug("http: " + format, *args)


class HttpError(Exception):
    """Raise from a route handler to answer a structured error."""

    def __init__(self, code: int, **body):
        self.code = int(code)
        self.body = body
        super().__init__(f"{code}: {body}")


class HttpEndpoint:
    """The threaded server: binds at construction (port 0 = ephemeral;
    the bound port is in ``.port``), serves after ``start()``.

    ``routes``: ``{(method, path): fn(body: bytes) -> (code, payload,
    content_type)}``.  ``/healthz`` is built in, answered from
    ``readiness`` (see module docstring) — routes cannot shadow it.
    Handlers run on per-connection daemon threads (stdlib
    ``ThreadingHTTPServer``); blocking inside a handler (the scoring
    path waits on its micro-batch) stalls only that connection.

    Binds 127.0.0.1 by default: both surfaces are operator tools, not
    public internet listeners; fronting proxies own external exposure.
    """

    def __init__(self, routes: dict, readiness: Readiness | None = None,
                 port: int = 0, host: str = "127.0.0.1"):
        self.routes = dict(routes)
        self.readiness = readiness if readiness is not None \
            else Readiness(READY)
        handler = type("_BoundHandler", (_Handler,), {"endpoint": self})
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._started = False
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="photon-http-endpoint")

    def start(self) -> None:
        self._thread.start()
        self._started = True

    def close(self) -> None:
        # shutdown() waits on an event only serve_forever() sets: a
        # never-started server (error-path close) must skip it or the
        # close deadlocks forever.
        if self._started:
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
        self._httpd.server_close()
