"""Shared threaded HTTP core: route table + readiness semantics.

One stdlib ``ThreadingHTTPServer`` wrapper serves BOTH HTTP surfaces in
the package — the monitor's observer endpoint (``/status`` +
``/metrics``, ISSUE 10) and the model server's request path
(``/v1/score``, ISSUE 12).  Promoting the monitor's private
``_StatusServer`` into this module is the tentpole's first move: the
request path must not fork a second, slightly different server loop.

Readiness (ISSUE 12 satellite): every endpoint built on this core
answers ``GET /healthz`` with the SAME state machine —

- ``warming`` → **503**: the process is up but not serviceable yet
  (model loading, plan build, XLA compile in progress).  A load
  balancer or orchestrator probe must NOT route traffic here.
- ``ready`` → **200**: warm — the first request pays zero compiles.
- ``stopping`` → **503**: graceful drain in progress.

The previous monitor endpoint answered an unconditional 200 the moment
the socket bound, i.e. during exactly the plan/compile window where a
probe answer matters; both surfaces now report honestly.

Connection hardening (ISSUE 13 satellite): every accepted connection
gets a socket timeout (``request_timeout_s``) so a stalled client —
half-sent request line, declared-but-never-sent body — is disconnected
instead of pinning a handler thread forever, and the request body read
is bounded by ``max_body`` so a hostile ``Content-Length`` cannot OOM
the process.  Route errors can carry extra response headers
(``HttpError(..., headers={"Retry-After": "1"})`` — the overload-shed
contract).

Request identity (ISSUE 14): every dispatch adopts (or mints) a trace
context from ``X-Photon-Trace`` / ``X-Photon-Request-Id`` and echoes
``X-Photon-Request-Id`` on EVERY response — 404s, 413s, 500s, 503
sheds, and retry-exhausted 502s included — so a client can correlate
ANY outcome with fleet ``/status`` and the run logs.  Routes read the
context via ``tracing.context()``; a route that began a
``RequestTrace`` leaves it attached and the core stamps the
response-write stage and finishes it after the bytes go out.

Import discipline: stdlib only — ``telemetry.monitor`` imports this
module, so anything heavier would cycle through the package
(``serving.tracing`` is stdlib-only at import time for the same
reason).
"""

from __future__ import annotations

import http.server
import json
import logging
import socket
import threading
import time

from photon_ml_tpu.serving import tracing

logger = logging.getLogger(__name__)

WARMING = "warming"
READY = "ready"
STOPPING = "stopping"

_STATES = (WARMING, READY, STOPPING)

# Default per-connection socket timeout and request-body bound; both
# overridable per endpoint (the serving config's http_timeout_s).
DEFAULT_TIMEOUT_S = 30.0
DEFAULT_MAX_BODY = 32 << 20


class Readiness:
    """Thread-safe readiness state + human reason.

    Writers (the owning server's lifecycle) call ``set(state, reason)``;
    the HTTP thread reads ``snapshot()``.  ``healthz_body()`` is the
    shared wire format: ``{"ok": bool, "state": str, "reason": str?}``.
    """

    def __init__(self, state: str = WARMING, reason: str | None = None):
        self._lock = threading.Lock()
        self._state = state
        self._reason = reason
        self._check(state)

    @staticmethod
    def _check(state: str) -> None:
        if state not in _STATES:
            raise ValueError(
                f"readiness state {state!r} not in {_STATES}")

    def set(self, state: str, reason: str | None = None) -> None:
        self._check(state)
        with self._lock:
            self._state = state
            self._reason = reason

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> tuple[str, str | None]:
        with self._lock:
            return self._state, self._reason

    def healthz(self) -> tuple[int, dict]:
        """(HTTP code, JSON body) for ``GET /healthz``."""
        state, reason = self.snapshot()
        body = {"ok": state == READY, "state": state}
        if reason:
            body["reason"] = reason
        return (200 if state == READY else 503), body


class _Handler(http.server.BaseHTTPRequestHandler):
    """Route-table dispatch; the endpoint rides as a class attribute
    (one handler class per ``HttpEndpoint`` instance)."""

    endpoint: "HttpEndpoint | None" = None

    # Per-connection socket timeout (socketserver applies it in
    # setup()): a client that stalls mid-request is disconnected
    # instead of holding its handler thread forever.  The endpoint
    # overrides this on the bound subclass.
    timeout = DEFAULT_TIMEOUT_S

    # Request paths are small JSON (scoring rows); cap the body read so
    # a hostile Content-Length cannot balloon the handler thread.
    MAX_BODY = DEFAULT_MAX_BODY

    def _send(self, code: int, body: str, ctype: str,
              headers: dict | None = None) -> None:
        data = body.encode()
        t0 = time.perf_counter()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        # The request-id echo contract (ISSUE 14): EVERY response —
        # sheds and errors included — carries the trace identity.
        for k, v in (getattr(self, "_trace_hdrs", None) or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(data)
        self._sent = (code, time.perf_counter() - t0)

    def _send_json(self, code: int, obj,
                   headers: dict | None = None) -> None:
        self._send(code, json.dumps(obj), "application/json",
                   headers=headers)

    def _dispatch(self, method: str) -> None:
        ctx = tracing.from_headers(self.headers)
        tracing.set_context(ctx)
        self._trace_hdrs = {
            tracing.REQUEST_ID_HEADER: ctx.trace_id,
            tracing.TRACE_HEADER: ctx.header_value(),
        }
        self._sent = None
        try:
            self._dispatch_routed(method)
        finally:
            # A route that began a RequestTrace left it attached: the
            # write stage is the send the core just performed, and the
            # finish here covers EVERY outcome (200s, sheds, 500s).
            rt = tracing.take_attached()
            if rt is not None:
                sent = self._sent
                if sent is not None:
                    rt.stamp("write", sent[1])
                tracing.finish(rt, status=sent[0] if sent else None)
            tracing.clear()

    def _dispatch_routed(self, method: str) -> None:
        ep = self.endpoint
        path = self.path.split("?", 1)[0]
        if path in ("/", "/healthz"):
            # "/" doubles as the health probe (the round-15 monitor
            # endpoint answered it; existing probes keep working) —
            # with the honest state machine, not an unconditional 200.
            code, body = ep.readiness.healthz()
            self._send_json(code, body)
            return
        route = ep.routes.get((method, path))
        if route is None:
            self._send_json(404, {
                "error": "unknown route",
                "routes": sorted({p for _, p in ep.routes} | {"/healthz"}),
            })
            return
        body = b""
        if method == "POST":
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                length = 0
            if length > self.MAX_BODY:
                self._send_json(413, {"error": "request body too large",
                                      "max_bytes": self.MAX_BODY})
                return
            try:
                body = self.rfile.read(length) if length else b""
            except (TimeoutError, socket.timeout, OSError) as e:
                # The declared body never arrived inside the socket
                # timeout: drop the connection — the thread must not
                # stay pinned to a stalled client.
                logger.warning("http: request body read failed (%r); "
                               "closing connection", e)
                self.close_connection = True
                return
        try:
            # Routes return (code, payload, ctype) or, when they need
            # extra response headers, (code, payload, ctype, headers).
            result = route(body)
            if len(result) == 4:
                code, payload, ctype, headers = result
            else:
                code, payload, ctype = result
                headers = None
        except HttpError as e:
            code, payload, ctype = e.code, json.dumps(e.body), \
                "application/json"
            headers = e.headers
        except Exception as e:   # a handler bug must answer, not hang
            logger.exception("http route %s %s failed", method, path)
            code, payload, ctype = 500, json.dumps(
                {"error": f"{type(e).__name__}: {e}"}), "application/json"
            headers = None
        self._send(code, payload, ctype, headers=headers)

    def do_GET(self) -> None:    # noqa: N802 (http.server API)
        self._dispatch("GET")

    def do_POST(self) -> None:   # noqa: N802 (http.server API)
        self._dispatch("POST")

    def log_message(self, format, *args):   # noqa: A002 (stdlib API)
        logger.debug("http: " + format, *args)


class HttpError(Exception):
    """Raise from a route handler to answer a structured error.
    ``headers`` ride on the response (e.g. ``Retry-After`` on a shed)."""

    def __init__(self, code: int, headers: dict | None = None, **body):
        self.code = int(code)
        self.body = body
        self.headers = dict(headers) if headers else None
        super().__init__(f"{code}: {body}")


class HttpEndpoint:
    """The threaded server: binds at construction (port 0 = ephemeral;
    the bound port is in ``.port``), serves after ``start()``.

    ``routes``: ``{(method, path): fn(body: bytes) -> (code, payload,
    content_type)}``.  ``/healthz`` is built in, answered from
    ``readiness`` (see module docstring) — routes cannot shadow it.
    Handlers run on per-connection daemon threads (stdlib
    ``ThreadingHTTPServer``); blocking inside a handler (the scoring
    path waits on its micro-batch) stalls only that connection, and the
    per-connection socket timeout bounds how long a stalled CLIENT can
    hold the thread.

    Binds 127.0.0.1 by default: both surfaces are operator tools, not
    public internet listeners; fronting proxies own external exposure.
    """

    def __init__(self, routes: dict, readiness: Readiness | None = None,
                 port: int = 0, host: str = "127.0.0.1",
                 request_timeout_s: float = DEFAULT_TIMEOUT_S,
                 max_body: int = DEFAULT_MAX_BODY):
        self.routes = dict(routes)
        self.readiness = readiness if readiness is not None \
            else Readiness(READY)
        handler = type("_BoundHandler", (_Handler,), {
            "endpoint": self,
            "timeout": float(request_timeout_s),
            "MAX_BODY": int(max_body),
        })
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._started = False
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="photon-http-endpoint")

    def start(self) -> None:
        self._thread.start()
        self._started = True

    def close(self) -> None:
        # shutdown() waits on an event only serve_forever() sets: a
        # never-started server (error-path close) must skip it or the
        # close deadlocks forever.
        if self._started:
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
        self._httpd.server_close()
