"""mmap'd random-effect coefficient store for the serving tier.

A GAME model's random effects are per-ENTITY coefficient rows — at the
"millions of users" scale the serving tier exists for, the [E, p]
table is the one model component that must not live in anonymous host
RSS (everything else is O(features)).  This module serves it from the
round-8 disk tier instead (ISSUE 12 tentpole):

- **Chunked coefficient files**: the model's rows, in global entity
  order (``grouping.entity_ids`` — ``np.unique`` ascending), split
  into ``entity_chunk``-row chunks and spilled through
  ``data.chunk_store.ChunkStore`` with the flat array codec — atomic
  content-keyed ``.npz`` files, memory-mapped loads, an LRU
  ``host_max_resident`` window.  A restart with the same model finds
  the same content key and reuses every file (warm artifact, the
  plan-cache discipline).
- **Persistent entity-id → (chunk, row) index**: one sidecar ``.npz``
  holding the sorted id array, memory-mapped back for lookups — the
  id → global-position join is a ``searchsorted`` against FILE-BACKED
  pages, and position ``g`` maps to ``(g // entity_chunk,
  g % entity_chunk)`` by construction (chunking is contiguous in
  global entity order).
- **Unseen entities**: join misses return ``hit=False`` and ZERO rows
  — the caller's mini-table keeps the zero fallback row, so an unseen
  entity scores exactly the fixed effect (the batch path's tested
  semantics).

Without a (writable) spill dir the store degrades to a host-resident
table with one warning — the disk tier is an optimization for the big-E
regime, never a correctness dependency (the ``probe_spill_dir`` rule).

Request-path resilience (ISSUE 13): a chunk read failure on the
serving hot path retries through ``reliability.retry`` (bounded
exponential backoff, transient errnos only) and then DEGRADES — the
affected rows are served as zeros, i.e. fixed-effect-only scoring,
exactly the unseen-entity semantics — with ``serve.store_degraded``
counted and the response marked ``degraded`` instead of failing the
request with a 500.  The ``serve.store_load`` fault seam makes this
path deterministically testable.
"""

from __future__ import annotations

import logging
import os

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.data.chunk_store import (
    ChunkStore,
    _open_npz_mmap,
    array_content_key,
    decode_array_chunk,
    encode_array_chunk,
    probe_spill_dir,
)
from photon_ml_tpu.game.dataset import sorted_id_join
from photon_ml_tpu.models.game import RandomEffectModel

logger = logging.getLogger(__name__)

# On-disk serve-store format version (rides in the content key).
ENTITY_STORE_VERSION = 1


def _extract_rows(model: RandomEffectModel, lo: int, hi: int,
                  blocks_np: list[np.ndarray]) -> np.ndarray:
    """Coefficient rows [hi-lo, p] for global entity positions
    [lo, hi) — vectorized gather from the size-bucketed blocks."""
    g = model.grouping
    bucket = np.asarray(g.entity_bucket[lo:hi])
    slot = np.asarray(g.entity_slot[lo:hi])
    out = np.zeros((hi - lo, blocks_np[0].shape[-1]), np.float32)
    for b in np.unique(bucket):
        sel = bucket == b
        out[sel] = blocks_np[b][slot[sel]]
    return out


class EntityServeStore:
    """Per-entity coefficient rows behind an id join.

    Construct via ``build`` (from a ``RandomEffectModel``).  ``lookup``
    is the serving hot path: query ids → coefficient rows + hit mask,
    touching only the chunks the batch's entities live in.
    """

    def __init__(self, name: str, ids: np.ndarray, dim: int,
                 entity_chunk: int, store: ChunkStore | None,
                 table: np.ndarray | None):
        self.name = name
        self._ids = ids                  # sorted unique (possibly mmap)
        self.dim = int(dim)
        self.entity_chunk = int(entity_chunk)
        self._store = store              # chunked disk tier, or
        self._table = table              # ...resident fallback
        self.n_entities = int(len(ids))
        self.lookups = 0
        self.misses = 0                  # unseen-entity rows served
        self.degraded_lookups = 0        # rows served fixed-effect-only

    @property
    def spilled(self) -> bool:
        return self._store is not None

    @classmethod
    def build(cls, name: str, model: RandomEffectModel,
              spill_dir: str | None, entity_chunk: int = 4096,
              host_max_resident: int = 4) -> "EntityServeStore":
        if model.projection is not None:
            raise ValueError(
                f"random effect '{name}' is projected; the entity "
                "serve store holds width-uniform rows (projected "
                "effects score host-side)")
        g = model.grouping
        ids = np.asarray(g.entity_ids)
        blocks_np = [np.asarray(b, np.float32)
                     for b in model.coefficient_blocks]
        dim = blocks_np[0].shape[-1]
        E = len(ids)
        C = int(entity_chunk)
        n_chunks = max(1, -(-E // C))

        if probe_spill_dir(spill_dir) is None:
            # Resident fallback: one [E, p] table (the pre-serving
            # shape) — correct, just not RSS-bounded in E.
            table = _extract_rows(model, 0, E, blocks_np)
            logger.info("entity serve store '%s': resident (%d entities"
                        " x %d, no spill dir)", name, E, dim)
            return cls(name, ids, dim, C, None, table)

        key = "resrv-" + array_content_key(
            [ids] + blocks_np,
            {"entity_chunk": C, "dim": int(dim),
             "version": ENTITY_STORE_VERSION})

        def build_chunk(i: int) -> dict:
            lo = i * C
            hi = min(lo + C, E)
            return {"w": _extract_rows(model, lo, hi, blocks_np)}

        store = ChunkStore(spill_dir, key, n_chunks,
                           host_max_resident=host_max_resident,
                           rebuild=build_chunk,
                           codec=(encode_array_chunk,
                                  decode_array_chunk))
        missing = [i for i in range(n_chunks) if not store.has(i)]
        for i in missing:        # one chunk in flight: bounded ETL RSS
            store.put(i, build_chunk(i), keep_resident=False)

        # Persistent id index: written once per content key, mmap'd
        # back so the E-sized join array is file-backed page cache, not
        # anonymous RSS.
        index_path = os.path.join(store.dir, f"{key}-index.npz")
        ids_view = ids
        try:
            if not os.path.exists(index_path):
                from photon_ml_tpu.cache.plan_cache import atomic_savez

                atomic_savez(index_path,
                             {"kind": "entity_serve_index",
                              "version": ENTITY_STORE_VERSION,
                              "entity_chunk": C, "dim": int(dim)},
                             {"ids": ids})
            ids_view = _open_npz_mmap(index_path)["ids"]
        except Exception as e:  # photon-lint: disable=swallowed-exception (index persistence is an optimization; the in-memory ids are authoritative)
            logger.warning("entity serve store '%s': id index at %s "
                           "unavailable (%r); using resident ids",
                           name, index_path, e)
        logger.info(
            "entity serve store '%s': %d entities x %d in %d chunk(s) "
            "at %s (%d built, %d reused; host window %d)", name, E,
            dim, n_chunks, spill_dir, len(missing),
            n_chunks - len(missing), store.host_max_resident)
        return cls(name, ids_view, dim, C, store, None)

    def _chunk_rows(self, c: int):
        """One chunk's decoded coefficient table, through the serving
        fault seam and the bounded-retry policy (transient OSErrors
        back off and retry; everything else propagates to the caller's
        degradation fallback)."""
        from photon_ml_tpu.reliability import faults
        from photon_ml_tpu.reliability.retry import run_with_retries

        def attempt():
            faults.fire("serve.store_load", chunk=c, store=self.name)
            return self._store.get(c)["w"]

        return run_with_retries(
            attempt, label=f"serve store '{self.name}' chunk {c}",
            retry_counter="serve.store_retries",
            gave_up_counter="serve.store_gave_up")

    def lookup(self, query_ids: np.ndarray
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(rows [m, p] float32, hit [m] bool, degraded [m] bool) for
        ``query_ids``.  Misses (unseen entities) come back as zero
        rows.  ``degraded[i]`` is True when row i's coefficient chunk
        could not be read (after bounded retries): that row is served
        as zeros — graceful degradation to fixed-effect-only scoring
        (the unseen-entity semantics) instead of a failed request —
        and the PER-ROW mask lets the batcher mark only the requests
        actually affected, not every request co-batched with them.
        The store stays up; a later lookup retries the chunk."""
        query_ids = np.asarray(query_ids)
        m = len(query_ids)
        g = sorted_id_join(np.asarray(self._ids), query_ids)
        hit = g >= 0
        out = np.zeros((m, self.dim), np.float32)
        degraded = np.zeros(m, bool)
        self.lookups += m
        n_miss = int(m - hit.sum())
        if n_miss:
            self.misses += n_miss
            telemetry.count("serve.entity_misses", n_miss)
        if self._table is not None:
            out[hit] = self._table[g[hit]]
            return out, hit, degraded
        gh = g[hit]
        rows_out = np.nonzero(hit)[0]
        for c in np.unique(gh // self.entity_chunk):
            sel = (gh // self.entity_chunk) == c
            try:
                w = self._chunk_rows(int(c))
            except Exception as e:
                # Fixed-effect-only fallback: the rows this chunk
                # would have served stay zero — exactly how an unseen
                # entity scores — and those rows are marked degraded
                # instead of failing the request with a 500.
                degraded[rows_out[sel]] = True
                self.degraded_lookups += int(sel.sum())
                telemetry.count("serve.store_degraded")
                logger.warning(
                    "entity serve store '%s': chunk %d unreadable "
                    "(%r); serving fixed-effect-only for %d row(s)",
                    self.name, int(c), e, int(sel.sum()))
                continue
            # Fancy-indexing a memmap copies just the touched rows —
            # the batch's working set, not the chunk.
            out[rows_out[sel]] = w[gh[sel] - int(c) * self.entity_chunk]
        return out, hit, degraded

    def stats(self) -> dict:
        st = {"name": self.name, "entities": self.n_entities,
              "dim": self.dim, "spilled": self.spilled,
              "lookups": self.lookups, "misses": self.misses,
              "degraded_lookups": self.degraded_lookups}
        if self._store is not None:
            st.update({"chunk_loads": self._store.loads,
                       "window_hits": self._store.hits,
                       "peak_resident": self._store.peak_resident})
        return st

    def close(self) -> None:
        """Drop the decoded-chunk window (retiring a swapped-out
        model's store).  Files stay on disk — they are content-keyed
        warm artifacts, exactly like every other chunk store."""
        if self._store is not None:
            self._store.drop_resident()
